"""Query-time IVF engine: routing, nprobe selection, fallback, timings.

The serving-facing half of `tpu_ivf`. `IVFRouter.search` runs the two
device stages of `ops/knn_ivf.py` — centroid routing and pruned scoring —
as separate dispatches so the per-phase wall times the profiler and
`_nodes/stats` report (route / score / merge) are measured, not modeled.

nprobe selection:
  * an integer setting is clamped to nlist and snapped up to the
    dispatch grid's pow-2 ladder (nprobe is a compiled-shape parameter;
    see ops/dispatch.py — snapping up never probes fewer partitions
    than configured);
  * `"auto"` tunes once per layout generation: a held-out sample of the
    indexed vectors becomes the query set, the engine's own full-probe
    (nprobe = nlist) result the ground truth, and nprobe doubles until
    recall@k meets `recall_target` — the recall-gate escape hatch.
    Full-probe truth isolates routing loss (what nprobe controls) from
    storage-quantization loss (what dtype controls); at the limit the
    tuner returns nlist and the engine is exactly as good as
    exhaustive-over-buckets.

Fallback (exhaustive `ops/knn.py`) triggers whenever pruning can't hold
its contract: filtered searches (the mask may eliminate every probed
partition), layouts flagged `needs_retrain`, k beyond the probed-row
budget, or f32-precision requests (IVF is a throughput path; exactness
asks go to the exact kernel).
"""

from __future__ import annotations

import time
from typing import Optional

import numpy as np

from elasticsearch_tpu.ann.ivf_index import IVFIndex


def _pad_back_k(scores, rows, k: int, k_dev: int):
    """Widen device results [Q, k_dev] back to the requested [Q, k]
    with the empty-slot sentinels (-inf, -1) — the probed-row budget
    caps what the kernels can return. Shared by the single-device and
    mesh paths so the result contract can never diverge."""
    scores_np = np.asarray(scores)
    rows_np = np.asarray(rows)
    if k_dev < k:
        pad = k - k_dev
        scores_np = np.pad(scores_np, ((0, 0), (0, pad)),
                           constant_values=-np.inf)
        rows_np = np.pad(rows_np, ((0, 0), (0, pad)),
                         constant_values=-1)
    return scores_np, rows_np


class IVFRouter:
    """One field's IVF engine instance (wraps the layout + tuning state)."""

    def __init__(self, index: IVFIndex, nprobe="auto",
                 recall_target: float = 0.95, tune_sample: int = 128,
                 tune_seed: int = 0, tune_margin: float = 0.01):
        self.index = index
        self.nprobe_setting = nprobe
        self.recall_target = float(recall_target)
        self.tune_sample = int(tune_sample)
        self.tune_seed = int(tune_seed)
        # tune slightly past the target: the gate is measured on a finite
        # held-out sample, and serving queries are noisier than corpus rows
        self.tune_margin = float(tune_margin)
        self._tuned_nprobe: Optional[int] = None
        self.last_phases: dict = {}

    def with_index(self, index: IVFIndex) -> "IVFRouter":
        """A new router serving `index` with this router's settings AND
        its tuned nprobe carried over — the segments merge scheduler
        swaps extended layouts in without re-running the recall-gate
        tuner (the layout geometry is unchanged by an append)."""
        new = IVFRouter(index, nprobe=self.nprobe_setting,
                        recall_target=self.recall_target,
                        tune_sample=self.tune_sample,
                        tune_seed=self.tune_seed,
                        tune_margin=self.tune_margin)
        new._tuned_nprobe = self._tuned_nprobe
        return new

    # ---------------------------------------------------------- nprobe

    def effective_nprobe(self, k: int) -> int:
        if self.nprobe_setting != "auto":
            n = max(1, min(int(self.nprobe_setting), self.index.nlist))
            if n != self.index.nlist and n & (n - 1):
                # nprobe is a static arg of the dispatched kernels and
                # the closed grid only admits pow-2 rungs (or full
                # nlist): snap an off-ladder setting UP — never fewer
                # probes than configured, recall only improves
                n = min(1 << (n - 1).bit_length(), self.index.nlist)
            return n
        if self._tuned_nprobe is None:
            self._tuned_nprobe = self.tune_nprobe(k=max(k, 10))
        return self._tuned_nprobe

    def tune_nprobe(self, k: int = 10) -> int:
        """Recall-gate auto-tune: double nprobe until recall@k on a
        held-out sample of the indexed vectors meets the target.

        Ground truth is the engine's own full-probe (nprobe = nlist)
        result over the same partitions and storage dtype — that isolates
        the loss nprobe actually controls (routing) from quantization
        loss, which no amount of extra probing can recover and would
        otherwise drive the tuner all the way to exhaustive."""
        idx = self.index
        valid_mask = idx.part_rows >= 0
        flat_vecs = idx.part_vecs[valid_mask]
        n = int(valid_mask.sum())
        if n == 0:
            return 1
        rng = np.random.default_rng(self.tune_seed)
        sample = min(self.tune_sample, n)
        pick = rng.choice(n, size=sample, replace=False)
        queries = flat_vecs[pick]
        k_eff = min(k, n)

        _, truth, _ = self._device_search(queries, k_eff, idx.nlist)
        truth_rows = [set(t[t >= 0]) for t in truth]

        gate = min(1.0, self.recall_target + self.tune_margin)
        nprobe = 1
        while True:
            _, got_rows, _ = self._device_search(queries, k_eff, nprobe)
            hits = sum(len(truth_rows[i] & set(got_rows[i]))
                       for i in range(sample))
            recall = hits / max(sum(len(t) for t in truth_rows), 1)
            if recall >= gate or nprobe >= idx.nlist:
                return nprobe
            nprobe = min(idx.nlist, nprobe * 2)

    # ---------------------------------------------------------- search

    def should_fallback(self, k: int, has_filter: bool,
                        precision: str) -> Optional[str]:
        """Reason string when this search must take the exhaustive path."""
        idx = self.index
        if has_filter:
            return "filtered"
        if precision == "f32":
            return "f32_precision"
        if idx.needs_retrain:
            return "needs_retrain"
        if idx.total == 0:
            return "empty"
        if k > idx.cap:  # one probe can't even fill the result list
            return "k_exceeds_partition"
        return None

    def _device_search(self, queries: np.ndarray, k: int, nprobe: int):
        """(scores [Q,k], rows [Q,k], phases dict) — rows are
        device-corpus row ids, -1 for empty slots."""
        import jax.numpy as jnp

        from elasticsearch_tpu.ops import knn_ivf

        from elasticsearch_tpu.ops import pallas_ivf_fused as fused

        idx = self.index
        nprobe = max(1, min(nprobe, idx.nlist))
        t0 = time.perf_counter_ns()
        parts = idx.device_partitions()
        q = knn_ivf._prep_queries(jnp.asarray(queries, dtype=jnp.float32),
                                  idx.metric)
        probe_ids, cent_scores = knn_ivf.route(q, parts, nprobe,
                                               metric=idx.metric)
        probe_ids.block_until_ready()
        t1 = time.perf_counter_ns()
        k_dev = min(k, nprobe * idx.cap)
        # fused Pallas gather+score when the layout/metric allow and the
        # backend prefers it (accelerators; ES_TPU_IVF_FUSED forces in
        # interpret mode) — no [Q, nprobe, cap, D] staged tile gather
        use_fused = (fused.fused_eligible(parts.parts.dtype, idx.metric)
                     and fused.fused_preferred())
        if use_fused:
            scores, rows = fused.fused_probe_scores(
                q, parts, probe_ids, k_dev, metric=idx.metric)
        else:
            scores, rows = knn_ivf.score_probes(q, parts, probe_ids, k_dev,
                                                metric=idx.metric)
        rows.block_until_ready()
        t2 = time.perf_counter_ns()
        scores_np, rows_np = _pad_back_k(scores, rows, k, k_dev)
        t3 = time.perf_counter_ns()
        phases = {"engine": "tpu_ivf", "nprobe": nprobe,
                  "nlist": idx.nlist,
                  "scored_rows": nprobe * idx.cap,
                  "fused_probe": use_fused,
                  "route_nanos": t1 - t0, "score_nanos": t2 - t1,
                  "merge_nanos": t3 - t2}
        return scores_np, rows_np, phases

    def _mesh_search(self, queries: np.ndarray, k: int, nprobe: int,
                     mesh):
        """SPMD execution: one compiled program routes on replicated
        centroids, scores each shard's owned partitions, and merges the
        [S, Q, k] candidates over ICI (`parallel/sharded_ivf.py`). Same
        result contract as `_device_search` — row ids are flat
        device-corpus rows either way."""
        import jax
        import jax.numpy as jnp

        from elasticsearch_tpu.ops import knn_ivf
        from elasticsearch_tpu.parallel import mesh as mesh_lib
        from elasticsearch_tpu.parallel import policy
        from elasticsearch_tpu.parallel.sharded_ivf import (
            sharded_ivf_search)

        idx = self.index
        nprobe = max(1, min(nprobe, idx.nlist))
        t0 = time.perf_counter_ns()
        sivf = idx.device_partitions_sharded(mesh)
        # prep on device with the single-device recipe (bitwise-identical
        # routing scores), then re-lay out across the mesh WITHOUT a
        # host round-trip — np.asarray here would sync and re-upload the
        # whole query batch per dispatch
        q = knn_ivf._prep_queries(
            jnp.asarray(np.asarray(queries, dtype=np.float32)),
            idx.metric)
        q = jax.device_put(q, mesh_lib.query_sharding(mesh))
        k_dev = min(k, nprobe * idx.cap)
        scores, rows = sharded_ivf_search(q, sivf, k_dev, nprobe, mesh,
                                          metric=idx.metric)
        rows.block_until_ready()
        t1 = time.perf_counter_ns()
        scores_np, rows_np = _pad_back_k(scores, rows, k, k_dev)
        t2 = time.perf_counter_ns()
        n_shards = int(mesh.shape[mesh_lib.SHARD_AXIS])
        gather = policy.gather_bytes(n_shards, len(queries), k_dev)
        policy.record_leg("ivf", t1 - t0, t2 - t1, gather)
        phases = {"engine": "tpu_ivf_mesh", "nprobe": nprobe,
                  "nlist": idx.nlist, "mesh_shards": n_shards,
                  "scored_rows": nprobe * idx.cap,
                  "collective_bytes": gather,
                  # route + score + merge run inside ONE SPMD program;
                  # the in-program split is not observable from the host
                  "route_nanos": 0, "score_nanos": t1 - t0,
                  "merge_nanos": t2 - t1}
        return scores_np, rows_np, phases

    def search(self, queries: np.ndarray, k: int,
               nprobe: Optional[int] = None,
               num_candidates: Optional[int] = None,
               mesh=None):
        """Pruned top-k over the partition layout.

        num_candidates (the `_search` knn API knob) widens probing the way
        ef does for HNSW: enough partitions are probed that at least that
        many rows get scored.

        mesh: a (dp, shard) serving mesh to execute on as one SPMD
        program (the store's mesh router passes it); None = the
        single-device two-dispatch path.

        Returns (scores [Q, k], rows [Q, k], phases). Callers decide
        fallback beforehand via `should_fallback` — this always prunes.
        """
        if nprobe is None:
            nprobe = self.effective_nprobe(k)
        if num_candidates is not None and num_candidates > 0:
            want = -(-int(num_candidates) // max(self.index.cap, 1))
            if want > nprobe:
                # num_candidates is a PER-REQUEST knob and nprobe is a
                # static arg of the routed kernels (a distinct value is a
                # fresh compiled shape): snap the widening to the next
                # pow-2 rung, clamped to nlist, so a client sweeping
                # num_candidates stays inside the closed dispatch grid.
                # Probing more partitions than asked only helps recall —
                # "at least num_candidates rows" still holds.
                nprobe = min(1 << (want - 1).bit_length(),
                             self.index.nlist)
        if mesh is not None:
            scores, rows, phases = self._mesh_search(
                np.asarray(queries, dtype=np.float32), k, nprobe, mesh)
        else:
            scores, rows, phases = self._device_search(
                np.asarray(queries, dtype=np.float32), k, nprobe)
        self.last_phases = phases
        return scores, rows, phases
