"""Unified per-dispatch cost router.

ONE cost model now answers every routing question the serving path used
to answer three different ways:

* **copy selection** — which STARTED copy of a shard a coordinator fans
  a query leg to (previously a private ARS EWMA ranking in
  `ClusterNode._select_copy`),
* **dp-vs-shard split** — whether a mesh-accepted dispatch takes one dp
  group or the full-mesh program (previously ad-hoc thresholds in
  `parallel/policy._choose_split`), and
* **remote placement** — which node receives a new shard copy when
  balancer weights tie (previously node-name order in
  `allocation._pick_node`).

The per-route cost is the sum the reference's adaptive replica selection
approximates (`SearchExecutionStatsCollector`), made explicit:

    cost(route) = estimated queue wait   (outstanding dispatches we routed
                                          there x the node's service EWMA)
                + transport RTT EWMA     (TcpTransportService.rtt_ms over
                                          real sockets; 0 in-process/sim)
                + device-leg estimate    (service EWMA net of transport —
                                          the remote engine + device time)

Every decision is counted with its reason; the counts surface under
`_nodes/stats indices.mesh.router.dispatch` (assembled by
`parallel/policy.stats()`), so a tail regression is attributable to the
routing tier that caused it.

`DispatchRouter` is per-coordinator (one per `ClusterNode`) because the
queue-wait term is "dispatches *I* have in flight there". The counters
and the observation table are process-global, mirroring the policy
module: placement runs in pure allocation functions with no node handle,
and `_nodes/stats` reports one router section per process.
"""

from __future__ import annotations

import threading
from typing import Callable, Dict, List, Optional, Sequence, Tuple

# same smoothing as the reference's ARS response-time EWMA (and the
# pre-unification ClusterNode._ars_observe): new = 0.7*prev + 0.3*obs
EWMA_ALPHA = 0.3

_lock = threading.Lock()

_counters = {
    "copy": {"decisions": 0, "reasons": {}},
    "split": {"decisions": 0, "reasons": {}},
    "placement": {"decisions": 0, "reasons": {}},
}

# process-global per-node observation table: the static placement path
# (pure functions in cluster/allocation.py) reads route costs from here;
# DispatchRouter instances publish into it on every select/observe.
# node_id -> {"service_ewma_ms", "rtt_ewma_ms", "inflight"}
_observations: Dict[str, dict] = {}


def _count(kind: str, reason: str) -> None:
    with _lock:
        c = _counters[kind]
        c["decisions"] += 1
        c["reasons"][reason] = c["reasons"].get(reason, 0) + 1


class DispatchRouter:
    """Per-coordinator routing state: service-time EWMA, in-flight
    dispatch counts, and the transport RTT feed."""

    def __init__(self, node_id: str = "",
                 rtt_provider: Optional[Callable[[str], Optional[float]]]
                 = None):
        self.node_id = node_id
        # rtt_provider(node_id) -> ms or None. Over TCP this is
        # TcpTransportService.rtt_ms; the sim transport has none, so the
        # RTT term is 0 and the cost collapses to the classic ARS rank.
        self.rtt_provider = rtt_provider
        # node_id -> coordinator-observed took EWMA (ms). ClusterNode
        # aliases this dict as `_ars_ewma` — tests and the bench harness
        # read and pop it directly, so it must stay a plain mutable dict.
        self.service_ewma: Dict[str, float] = {}
        # node_id -> dispatches selected but not yet observed back
        self.inflight: Dict[str, int] = {}

    # ------------------------------------------------------------- cost
    def rtt_ms(self, node_id: str) -> float:
        if self.rtt_provider is None:
            return 0.0
        try:
            return float(self.rtt_provider(node_id) or 0.0)
        except Exception:
            return 0.0

    def route_cost(self, node_id: str) -> Optional[float]:
        """Estimated ms until a dispatch routed to `node_id` completes;
        None for an unmeasured node (which must be probed, not costed)."""
        service = self.service_ewma.get(node_id)
        if service is None:
            return None
        rtt = self.rtt_ms(node_id)
        # the coordinator-observed took already contains the transport
        # round trip; subtracting it out keeps the three terms honest
        # instead of double-counting the wire
        device_leg = max(service - rtt, 0.0)
        queue_wait = self.inflight.get(node_id, 0) * service
        return queue_wait + rtt + device_leg

    # --------------------------------------------------- copy selection
    def select_copy(self, copies: Sequence, sid: int):
        """Pick the copy with the lowest route cost. Unmeasured nodes
        rank first so every copy gets probed (the ARS bootstrap rule);
        ties rotate by shard id so probe load spreads."""
        if len(copies) == 1:
            chosen, reason = copies[0], "single_copy"
        else:
            def rank(i_copy):
                i, copy = i_copy
                cost = self.route_cost(copy.node_id)
                return (0 if cost is None else 1, cost or 0.0,
                        (i + sid) % len(copies))
            best_i, chosen = min(enumerate(copies), key=rank)
            reason = ("unmeasured_probe"
                      if self.route_cost(chosen.node_id) is None
                      else "lowest_cost")
        node = chosen.node_id
        self.inflight[node] = self.inflight.get(node, 0) + 1
        self._publish(node)
        _count("copy", reason)
        return chosen

    def observe(self, node_id: str, took_ms: float) -> None:
        """Feed one completed (or timed-out-at-budget) dispatch back into
        the cost model. Late/duplicate observations only clamp inflight
        at zero — the estimate self-corrects."""
        n = self.inflight.get(node_id, 0)
        if n > 0:
            self.inflight[node_id] = n - 1
        prev = self.service_ewma.get(node_id)
        self.service_ewma[node_id] = (
            float(took_ms) if prev is None
            else (1.0 - EWMA_ALPHA) * prev + EWMA_ALPHA * float(took_ms))
        self._publish(node_id)

    def _publish(self, node_id: str) -> None:
        with _lock:
            _observations[node_id] = {
                "service_ewma_ms": self.service_ewma.get(node_id),
                "rtt_ewma_ms": self.rtt_ms(node_id),
                "inflight": self.inflight.get(node_id, 0),
            }


# ------------------------------------------------------- dp-vs-shard split
def choose_split(batch, n_rows: int, queue_depth: int, dp: int,
                 n_shards: int, min_rows: int) -> Tuple[str, str]:
    """dp-vs-shard split for one mesh-accepted dispatch, as a cost
    comparison in corpus-row units.

    The "dp" route runs on ONE dp group (S shards): its device leg scans
    n_rows/S per device and, because the other dp-1 groups stay free,
    queued batches land on disjoint devices — its queue-wait term is 0.
    The "shard" route runs the full-mesh program (S*dp devices): the
    device leg scans n_rows/(S*dp) but pays the wider program's fixed
    dispatch+gather costs, and every queued batch must wait a full
    service time (all devices are busy). The fixed-cost delta is
    calibrated so the break-even corpus is exactly `min_rows * dp` —
    the measured threshold the policy module has always enforced — which
    keeps the five pinned decision reasons byte-stable."""
    if batch is None:
        # no batch signal (legacy leg — device aggs): its kernels carry
        # shard-only specs and cache device mirrors against the full
        # serving mesh, so the full-mesh program is the only safe route
        split, reason = "shard", "no_batch_signal"
    elif batch < dp or batch % dp:
        # the full-mesh program splits the query batch along dp; a batch
        # its bucket can't split must take a group
        split, reason = "dp", "batch_below_dp"
    else:
        s = max(int(n_shards), 1)
        d = max(int(dp), 1)
        dp_cost = n_rows / s
        # full-mesh fixed-cost delta: min_rows*(dp-1)/S row-units makes
        # shard_cost == dp_cost exactly at n_rows == min_rows*dp
        shard_cost = (n_rows / (s * d)
                      + min_rows * (d - 1) / s
                      + int(queue_depth) * (n_rows / s + min_rows * d))
        if shard_cost > dp_cost:
            split = "dp"
            reason = ("queue_pressure" if queue_depth > 0
                      else "small_corpus_group")
        else:
            split, reason = "shard", "idle_large_corpus"
    _count("split", reason)
    return split, reason


# ------------------------------------------------------------- placement
def placement_cost(node_id: str) -> float:
    """Route cost of a node from the process-global observation table;
    0.0 when unobserved, so allocation with no serving traffic stays
    deterministic by (weight, node-name) — the historical order every
    pure-allocation test pins."""
    with _lock:
        obs = _observations.get(node_id)
    if not obs or obs.get("service_ewma_ms") is None:
        return 0.0
    service = float(obs["service_ewma_ms"])
    rtt = float(obs.get("rtt_ewma_ms") or 0.0)
    return (int(obs.get("inflight") or 0) * service + rtt
            + max(service - rtt, 0.0))


def placement_order(candidates) -> List[Tuple[float, str]]:
    """Order balancer candidates [(weight, node), ...] by (weight, route
    cost, node name): the balancer weight still dominates — the cost
    model only breaks weight ties, steering new copies away from hot
    nodes. Counts whether the cost term actually changed the order."""
    cands = list(candidates)
    if not cands:
        return []
    ranked = sorted((w, placement_cost(n), n) for w, n in cands)
    by_name = sorted(cands)
    ordered = [(w, n) for w, _, n in ranked]
    _count("placement",
           "cost_tiebreak" if ordered != by_name else "weight_order")
    return ordered


# ------------------------------------------------------------------ stats
def stats() -> dict:
    """`_nodes/stats indices.mesh.router.dispatch` section."""
    with _lock:
        return {
            "copy": {"decisions": _counters["copy"]["decisions"],
                     "reasons": dict(_counters["copy"]["reasons"])},
            "split": {"decisions": _counters["split"]["decisions"],
                      "reasons": dict(_counters["split"]["reasons"])},
            "placement": {
                "decisions": _counters["placement"]["decisions"],
                "reasons": dict(_counters["placement"]["reasons"])},
            "nodes": {n: dict(o)
                      for n, o in sorted(_observations.items())},
        }


def reset() -> None:
    """Zero the process-global counters and observations (tests)."""
    with _lock:
        for c in _counters.values():
            c["decisions"] = 0
            c["reasons"].clear()
        _observations.clear()
