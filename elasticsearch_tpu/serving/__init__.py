"""Query serving layer: micro-batching dispatch + host/device cost routing."""

from elasticsearch_tpu.serving.batcher import CombiningBatcher, CostModel

__all__ = ["CombiningBatcher", "CostModel"]
