"""Query serving layer: micro-batching dispatch + host/device cost routing."""

from elasticsearch_tpu.serving.batcher import (
    BoundedBatcher, CombiningBatcher, CostModel,
)

__all__ = ["BoundedBatcher", "CombiningBatcher", "CostModel"]
