"""Continuous-batching dispatch for vector search.

The round-3 serving path dispatched ONE query per device round-trip, so
end-to-end latency was ~100x the device time and tiny-corpus hybrid queries
lost to the reference's host-side BulkScorer (`QueryPhase.java:171`). The
r06 closed-loop rows then showed the NEXT bottleneck: both 8-client rows
blew the p99 <= 3x p50 gate (6.18x / 5.95x) because the batcher was a
single admit-or-429 drain loop — a request arriving just after a drain
waited a full service cycle plus queue, and host post-processing of batch
N serialized with the device dispatch of batch N+1. This module is the
continuous-batching scheduler (the Orca/vLLM iteration-level shape,
adapted to the shape-bucketed dispatcher):

* `CombiningBatcher` — a combining-lock queue: the first thread in becomes
  the runner and executes whatever requests accumulated while the previous
  dispatch was in flight. Under load, batch size grows adaptively with no
  added idle latency (an idle submit executes immediately, no timer). On
  top of that base it now schedules:

  - deadline-aware admission: queued requests order earliest-deadline-
    first, and shedding happens at SCHEDULE time — a request is timed out
    exactly when it can no longer meet its deadline, not only at
    enqueue-time queue-depth admission;
  - in-flight bucket top-up: a drained batch that lands below its
    dispatch bucket boundary (`ops/dispatch.bucket_queries`) has free
    padded rows anyway — late arrivals claim them (optionally waiting a
    bounded `target_batch_latency_ms` window) so they ride THIS dispatch
    instead of the next service cycle. Snapping to bucket boundaries
    means a top-up costs zero recompiles;
  - async dispatch pipelining: with a (dispatch_fn, finalize_fn) executor
    pair, the runner holds the lock only for the device dispatch (which
    returns un-synced arrays) and finalizes — device sync, host
    rescore/hydrate — OUTSIDE the lock, so the next runner's dispatch
    overlaps with this batch's host work. `async_depth` bounds how many
    batches may be in flight un-finalized.

* `CostModel` — per-dispatch host-vs-device routing. A device dispatch pays
  a fixed round-trip (measured once, lazily, against the live backend); a
  host VNNI pass pays corpus-scan time. Small corpus + small batch → host
  kernel (native/es_native.cc es_knn_i8p_topk); large corpus or deep batch →
  device matmul+top-k. Both return identical raw-score conventions, so the
  router is invisible to callers.
"""

from __future__ import annotations

import threading
import time
from concurrent.futures import Future
from typing import Any, Callable, List, Optional, Sequence, Tuple

from elasticsearch_tpu.common.errors import TaskCancelledError
from elasticsearch_tpu.common.threadpool import EsRejectedExecutionError
from elasticsearch_tpu.telemetry import metrics as _metrics
from elasticsearch_tpu.telemetry import thread_section as _thread_section
from elasticsearch_tpu.telemetry import trace as _tt

_overhead_lock = threading.Lock()
_overhead_ms: Optional[float] = None


def _probe_kernel(x):
    """Tiny round-trip kernel for `device_overhead_ms` (registered
    lazily — jax import cost stays off module import)."""
    return x + 1.0


def _host_gops() -> float:
    """Measured ~200 GOPS peak with AVX512-VNNI; priced at 150 GOPS — a
    25% derate for sustained serving (frequency throttle + co-running
    work), so the router only sends the host scans it can actually absorb.
    The scalar fallback the kernel dispatches to on older hosts is ~100x
    slower — price it honestly so the router doesn't send scans to a path
    that can't serve them."""
    try:
        from elasticsearch_tpu import native
        if native.knn_has_vnni():
            return 150.0e9
    except Exception:
        pass
    return 2.0e9


HOST_GOPS = None  # resolved lazily via _host_gops (native lib load order)
HOST_MEM_BPS = 10.0e9
# device matmul throughput (bf16 MXU, conservative)
DEVICE_OPS = 100.0e12


def device_overhead_ms() -> float:
    """One-time measurement of a tiny jit round-trip against the live
    backend — the fixed cost a device dispatch must amortize. ~0.1 ms on a
    direct-attached TPU host, tens of ms through a tunneled chip."""
    global _overhead_ms
    if _overhead_ms is not None:
        return _overhead_ms
    with _overhead_lock:
        if _overhead_ms is not None:
            return _overhead_ms
        try:
            import time

            import jax.numpy as jnp

            import numpy as _np

            from elasticsearch_tpu.ops import dispatch

            # the probe rides the same dispatcher every serving kernel
            # uses (a raw jax.jit here was a second compile path outside
            # the AOT cache — tpulint TPU001), so the measured round trip
            # includes the dispatch layer a real serving call pays
            dispatch.DISPATCH.register("serving.overhead_probe",
                                       _probe_kernel)
            x = _np.zeros((8,), _np.float32)
            # tpulint: disable=TPU009(one-time-per-process probe under the measurement latch, not a serving queue lock — nothing queues on it)
            _np.asarray(dispatch.call("serving.overhead_probe",
                                      jnp.asarray(x)))
            samples = []
            for _ in range(3):
                # a serving dispatch pays h2d (queries/mask), execute, AND
                # d2h (results) — measure the full round trip
                t0 = time.perf_counter()
                # tpulint: disable=TPU002(the probe MEASURES the per-dispatch d2h round trip on purpose; 3 iterations, once per process, not a serving loop),TPU009(same: the measurement latch is not a serving queue lock)
                _np.asarray(dispatch.call("serving.overhead_probe",
                                          jnp.asarray(x)))
                samples.append((time.perf_counter() - t0) * 1000.0)
            _overhead_ms = max(0.05, min(samples))
        except Exception:
            _overhead_ms = 1.0
    return _overhead_ms


class CostModel:
    """Estimate dispatch latency for a (batch, corpus) shape on each path."""

    @staticmethod
    def host_ms(batch: int, n_rows: int, dims: int) -> float:
        global HOST_GOPS
        if HOST_GOPS is None:
            HOST_GOPS = _host_gops()
        groups = (batch + 15) // 16  # kernel computes 16 query lanes a pass
        compute = 2.0 * groups * 16 * n_rows * dims / HOST_GOPS * 1000.0
        mem = groups * n_rows * dims / HOST_MEM_BPS * 1000.0
        return max(compute, mem) + 0.05

    @staticmethod
    def device_ms(batch: int, n_rows: int, dims: int) -> float:
        compute = 2.0 * batch * n_rows * dims / DEVICE_OPS * 1000.0
        return device_overhead_ms() + compute

    @classmethod
    def prefer_host(cls, batch: int, n_rows: int, dims: int) -> bool:
        return (cls.host_ms(batch, n_rows, dims)
                < cls.device_ms(batch, n_rows, dims))


class _QueueEntry:
    """One queued request: payload, future, and its schedule metadata."""

    __slots__ = ("request", "fut", "enqueued", "deadline", "seq", "claimed",
                 "trace", "span_parent", "token")

    def __init__(self, request, fut: Future, enqueued: float,
                 deadline: Optional[float], seq: int):
        self.request = request
        self.fut = fut
        self.enqueued = enqueued
        self.deadline = deadline   # monotonic instant; None = never expires
        self.seq = seq             # arrival order (EDF tie-break)
        self.claimed = False       # a runner owns it (set under _q_lock)
        # telemetry context, captured from the SUBMITTING thread at
        # enqueue time: the pipelined batcher claims, dispatches, and
        # finalizes this entry on other threads, so thread-locals alone
        # cannot follow the request — the entry carries its own trace
        # (None = unsampled), parent span id, and cancellation token
        # (the live task; a truthy `.cancelled` sheds at EDF admission)
        self.trace, self.span_parent, self.token = _tt.capture()

    def sort_key(self) -> Tuple[float, int]:
        return (self.deadline if self.deadline is not None else float("inf"),
                self.seq)


def _fresh_sched_stats() -> dict:
    return {"batches": 0, "pipelined_batches": 0, "requests": 0,
            "topups": 0, "deadline_sheds": 0, "cancelled_sheds": 0,
            "overlap_hits": 0,
            "queue_wait_nanos": 0, "dispatch_nanos": 0,
            "finalize_nanos": 0}


class CombiningBatcher:
    """Combining-lock request coalescer with continuous-batching
    scheduling.

    submit() enqueues and then either (a) finds its result already set by a
    concurrent runner, or (b) becomes the runner: drains the queue
    earliest-deadline-first, tops the batch up to its dispatch bucket
    boundary, and executes it. While a runner is dispatching, later
    submitters queue up — their requests form the next batch (or top up
    this one). No background thread, no batching timer, zero idle latency.

    Two executor shapes:

    * `execute(requests) -> results` — the classic synchronous path: runs
      under the run lock, exactly one batch in flight at a time.
    * `dispatch_fn(requests) -> handle` + `finalize_fn(handle) -> results`
      — the pipelined path: `dispatch_fn` launches device work and returns
      WITHOUT syncing (un-synced arrays in the handle); the runner then
      releases the run lock and finalizes (device sync + host
      post-processing) outside it, so the next batch's device dispatch
      overlaps this batch's host work. `async_depth` bounds in-flight
      un-finalized batches. `execute` stays the poisoned-batch serial-
      retry path (synthesized from the pair when not given).

    `sched` counts the scheduler's work: batches, top-ups, schedule-time
    deadline sheds, dispatch/finalize overlap hits, and cumulative
    queue-wait/dispatch/finalize time.
    """

    def __init__(self, execute: Optional[Callable[[Sequence], List]],
                 max_batch: int = 256, *,
                 dispatch_fn: Optional[Callable[[Sequence], Any]] = None,
                 finalize_fn: Optional[Callable[[Any], List]] = None,
                 topup: bool = True,
                 target_batch_latency_ms: float = 0.0,
                 async_depth: int = 2):
        from elasticsearch_tpu.ops import dispatch
        if (dispatch_fn is None) != (finalize_fn is None):
            raise ValueError("dispatch_fn and finalize_fn come as a pair")
        self._dispatch_fn = dispatch_fn
        self._finalize_fn = finalize_fn
        if execute is None:
            if dispatch_fn is None:
                raise ValueError("need execute or dispatch_fn/finalize_fn")
            execute = lambda reqs: finalize_fn(dispatch_fn(reqs))  # noqa: E731
        self._execute = execute
        # the batch ceiling snaps to a dispatch query bucket: a saturated
        # drain then hands the executor an exactly-bucket-sized batch (no
        # padding waste at peak), and light-load drains pad up to the
        # nearest bucket inside the executor — either way the compiled
        # shape set stays closed
        self._max_batch = dispatch.bucket_queries(max_batch)
        self._topup_enabled = bool(topup)
        self._target_ms = float(target_batch_latency_ms)
        self._run_lock = threading.Lock()
        self._q_lock = threading.Lock()
        self._q_cond = threading.Condition(self._q_lock)
        self._queue: List[_QueueEntry] = []
        self._seq = 0
        self._inflight = 0           # dispatched, not yet finalized
        self._depth_sem = threading.BoundedSemaphore(max(1, int(async_depth)))
        self._tls = threading.local()
        self.sched = _fresh_sched_stats()

    # ------------------------------------------------------------ queue
    def pending(self) -> int:
        """Requests queued but not yet claimed by a runner — the
        coalescing signal cost routers use to estimate the NEXT batch's
        size."""
        with self._q_lock:
            return len(self._queue)

    def load(self) -> dict:
        """Live scheduler snapshot for load-aware routing — what the
        mesh policy's dp-vs-shard router reads (via the store's
        `_queued_requests`): queued entries, in-flight batches, and the
        cumulative pressure counters (`topups`, `overlap_hits`,
        `queue_wait_nanos`) that say whether this batcher has been
        running hot. Note the router's queue-depth signal uses
        `pending` only — in-flight batches are already counted by the
        store's dispatch gauge."""
        with self._q_lock:
            return {"pending": len(self._queue),
                    "inflight": self._inflight,
                    "topups": self.sched["topups"],
                    "overlap_hits": self.sched["overlap_hits"],
                    "queue_wait_nanos": self.sched["queue_wait_nanos"]}

    def _deadline_for(self, now: float) -> Optional[float]:
        """Absolute deadline for a request enqueued at `now`; None means
        it never expires (base batcher has no admission deadline)."""
        return None

    def _admit(self, depth: int, now: float) -> None:
        """Admission hook, called under the queue lock with the current
        queue depth: subclasses refuse (raise) instead of queueing
        without bound."""

    def _enqueue(self, request, fut: Future,
                 deadline_at: Optional[float] = None) -> _QueueEntry:
        """Queue one request (admission may refuse — `_admit`). Returns
        the queue entry. `deadline_at` is a per-request ABSOLUTE deadline
        (time.monotonic seconds) — a cross-node search propagates the
        request's end-to-end deadline here so the EDF queue sheds the
        sub-request at THIS node's admission layer; it tightens (never
        loosens) the batcher's own admission deadline."""
        now = time.monotonic()
        with self._q_cond:
            self._admit(len(self._queue), now)
            deadline = self._deadline_for(now)
            if deadline_at is not None:
                deadline = deadline_at if deadline is None \
                    else min(deadline, deadline_at)
            entry = _QueueEntry(request, fut, now, deadline, self._seq)
            self._seq += 1
            self._queue.append(entry)
            self._q_cond.notify_all()
        return entry

    def _shed(self, entry: _QueueEntry, now: float) -> None:
        """Schedule-time deadline shed: the request can no longer meet
        its deadline, so it is timed out NOW instead of spending device
        time on an answer nobody reads."""
        self.sched["deadline_sheds"] += 1
        if not entry.fut.done():
            waited = (now - entry.enqueued) * 1000.0
            entry.fut.set_exception(EsRejectedExecutionError(
                f"rejected execution: request spent "
                f"{waited:.0f}ms queued, over the admission deadline"))

    def _shed_cancelled(self, entry: _QueueEntry, now: float) -> None:
        """Cancellation shed: the request's task was cancelled
        (`POST _tasks/_cancel`) while it sat queued — it leaves the EDF
        queue exactly like an expired deadline, before any device time
        is spent on an answer nobody will read."""
        self.sched["cancelled_sheds"] += 1
        if entry.trace is not None:
            entry.trace.record_span(
                "queue.wait", int((now - entry.enqueued) * 1e9),
                parent_id=entry.span_parent, status="cancelled")
        if not entry.fut.done():
            entry.fut.set_exception(TaskCancelledError(
                "task cancelled while queued (shed at EDF admission)"))

    def _claim_locked(self, want: int, now: float) -> List[_QueueEntry]:
        """Take up to `want` entries off the queue, earliest deadline
        first, shedding any whose deadline has already passed (or whose
        task was cancelled). Caller holds `_q_lock`."""
        if not self._queue:
            return []
        # deadline-less queues (the base batcher) are already in seq
        # order; skip the sort on the hot path. With a uniform
        # deadline_ms, arrival order IS deadline order, so this sort is
        # a near-no-op there too — it only reorders genuinely mixed
        # deadlines.
        if any(e.deadline is not None for e in self._queue):
            self._queue.sort(key=_QueueEntry.sort_key)
        claimed: List[_QueueEntry] = []
        keep: List[_QueueEntry] = []
        for entry in self._queue:
            if entry.token is not None \
                    and getattr(entry.token, "cancelled", False):
                self._shed_cancelled(entry, now)
                continue
            if entry.deadline is not None and now > entry.deadline:
                self._shed(entry, now)
                continue
            if len(claimed) < want:
                entry.claimed = True
                wait_ns = int((now - entry.enqueued) * 1e9)
                self.sched["queue_wait_nanos"] += wait_ns
                # live-tail surface + per-request attribution: both are
                # plain host writes (no syncs, no allocation beyond the
                # span) — safe under _q_lock
                _metrics.record("serving.queue_wait", wait_ns)
                if entry.trace is not None:
                    entry.trace.record_span("queue.wait", wait_ns,
                                            parent_id=entry.span_parent)
                claimed.append(entry)
            else:
                keep.append(entry)
        self._queue[:] = keep
        return claimed

    def _drain(self) -> List[_QueueEntry]:
        """Take the next batch off the queue (under the run lock):
        earliest-deadline-first, schedule-time shedding of expired
        entries."""
        with self._q_lock:
            return self._claim_locked(self._max_batch, time.monotonic())

    def _topup(self, batch: List[_QueueEntry]) -> List[_QueueEntry]:
        """In-flight bucket top-up: the drained batch dispatches padded to
        `bucket_queries(len(batch))` rows anyway, so any headroom up to
        that boundary is free — late arrivals claim it (zero recompiles:
        the compiled shape is the bucket, not the batch). With a
        `target_batch_latency_ms` budget the runner briefly waits for
        arrivals, but never past the oldest member's batching budget —
        an idle single query (bucket 1) never waits at all."""
        from elasticsearch_tpu.ops import dispatch
        if not batch:
            return batch
        target = len(batch) + dispatch.bucket_headroom(len(batch),
                                                       self._max_batch)
        if not self._topup_enabled or len(batch) >= target:
            return batch
        oldest = min(e.enqueued for e in batch)
        budget_until = oldest + self._target_ms / 1000.0
        joined = 0
        with self._q_cond:
            while len(batch) < target:
                now = time.monotonic()
                got = self._claim_locked(target - len(batch), now)
                if got:
                    batch.extend(got)
                    joined += len(got)
                    continue
                remaining = budget_until - now
                if remaining <= 0:
                    break
                self._q_cond.wait(min(remaining, 0.0005))
        if joined:
            self.sched["topups"] += joined
        return batch

    # ------------------------------------------------------------ serving
    @staticmethod
    def _check_results(batch: List[_QueueEntry], results: List) -> None:
        if len(results) != len(batch):
            raise RuntimeError(
                f"batch executor returned {len(results)} results "
                f"for {len(batch)} requests")

    def _set_results(self, batch: List[_QueueEntry], results: List) -> None:
        self._check_results(batch, results)
        for entry, res in zip(batch, results):
            entry.fut.set_result(res)

    def _retry_serially(self, batch: List[_QueueEntry], exc: Exception):
        """One poisoned request (bad filter, malformed vector) must not
        fail unrelated searches that happened to coalesce with it: retry
        each request alone so only the offender surfaces its error."""
        if len(batch) == 1:
            if not batch[0].fut.done():
                batch[0].fut.set_exception(exc)
            return
        for entry in batch:
            if entry.fut.done():
                continue
            try:
                entry.fut.set_result(self._execute([entry.request])[0])
            except Exception as one_exc:
                entry.fut.set_exception(one_exc)

    @staticmethod
    def _trace_leader(batch: List[_QueueEntry]) -> Optional[_QueueEntry]:
        """The batch's trace LEADER: the first member with a sampled
        trace. The leader's trace carries the batch-level device spans;
        other traced members (followers) link to them instead of
        double-counting device time that was shared by the whole
        coalesced batch."""
        for entry in batch:
            if entry.trace is not None:
                return entry
        return None

    def _trace_batch(self, batch: List[_QueueEntry], name: str,
                     dur_ns: int, status: str = "ok") -> None:
        """Record one batch-stage span on the leader's trace and link
        every traced follower to it. Retroactive spans only — the
        duration was already measured at an existing sync point, so this
        adds zero host syncs."""
        leader = self._trace_leader(batch)
        if leader is None:
            return
        span_id = leader.trace.record_span(
            name, dur_ns, parent_id=leader.span_parent, status=status,
            coalesced=len(batch))
        for entry in batch:
            if entry.trace is not None and entry is not leader:
                entry.trace.add_link(leader.trace.trace_id, span_id,
                                     "coalesced_follower")

    def _trace_since(self, batch: List[_QueueEntry]) -> Optional[int]:
        # dispatch-trace attribution (profile.dispatch): the runner
        # thread executes device work for EVERY request in the batch. If
        # this thread is recording a profile trace, label the batch's
        # events with the coalesced size so the leader's trace doesn't
        # silently claim follower dispatches as its own; followers still
        # report an empty trace (documented — `_nodes/stats
        # indices.dispatch` is the authoritative counter).
        from elasticsearch_tpu.ops import dispatch as _dispatch
        return (_dispatch.DISPATCH.event_count()
                if len(batch) > 1 and _dispatch.DISPATCH.events_enabled()
                else None)

    def _annotate(self, trace_since: Optional[int], n: int) -> None:
        # annotate on EVERY exit: the serial per-request retries of a
        # poisoned batch run on this same runner thread, and their
        # dispatches are just as much coalesced-batch work as the happy
        # path's
        if trace_since is None:
            return
        from elasticsearch_tpu.ops import dispatch as _dispatch
        _dispatch.DISPATCH.annotate_events(trace_since,
                                           coalesced_batch=n)

    def _run_sync(self, batch: List[_QueueEntry]) -> None:
        """Classic synchronous serving of one batch (under the run
        lock)."""
        trace_since = self._trace_since(batch)
        t0 = time.perf_counter_ns()
        err: Optional[BaseException] = None
        results = None

        def land_stage() -> None:
            # stats + stage span land BEFORE any future resolves: a
            # submitter thread woken by set_result may immediately
            # finish its request and ship the trace — the span must
            # already be in it. Sync path: dispatch + device sync ran
            # back to back, so the whole stage is one figure.
            dt = time.perf_counter_ns() - t0
            self.sched["dispatch_nanos"] += dt
            _metrics.record("serving.device_dispatch", dt)
            self._trace_batch(batch, "batch.execute", dt,
                              status="ok" if err is None else "error")

        try:
            try:
                results = self._execute([e.request for e in batch])
            except Exception as exc:
                err = exc
            except BaseException as exc:  # KeyboardInterrupt/SystemExit:
                err = exc
                land_stage()
                for entry in batch:      # fail fast, no serial retries
                    if not entry.fut.done():
                        entry.fut.set_exception(exc)
                raise
            if err is None:
                try:
                    self._check_results(batch, results)
                except Exception as exc:
                    err = exc
            land_stage()
            if err is None:
                self._set_results(batch, results)
            else:
                self._retry_serially(batch, err)
        finally:
            self._annotate(trace_since, len(batch))

    def _begin_pipelined(self, batch: List[_QueueEntry]):
        """Dispatch stage (under the run lock): launch the batch's device
        work WITHOUT syncing. Returns the finalize context."""
        trace_since = self._trace_since(batch)
        self._depth_sem.acquire()   # bounds in-flight un-finalized batches
        with self._q_lock:
            if self._inflight > 0:
                # a previous batch is still finalizing on another thread
                # while this dispatch starts: the overlap the pipeline
                # exists to create
                self.sched["overlap_hits"] += 1
            self._inflight += 1
        t0 = time.perf_counter_ns()
        handle: Any = None
        err: Optional[BaseException] = None
        try:
            handle = self._dispatch_fn([e.request for e in batch])
        except Exception as exc:
            err = exc
        except BaseException as exc:
            err = exc
            for entry in batch:
                if not entry.fut.done():
                    entry.fut.set_exception(exc)
            self._end_pipelined()
            self._annotate(trace_since, len(batch))
            raise
        finally:
            dt = time.perf_counter_ns() - t0
            self.sched["dispatch_nanos"] += dt
            # pipelined launch: un-synced device work under the lock
            _metrics.record("serving.device_dispatch", dt)
            self._trace_batch(batch, "batch.dispatch", dt,
                              status="ok" if err is None else "error")
        return batch, handle, err, trace_since

    def _end_pipelined(self) -> None:
        with self._q_lock:
            self._inflight -= 1
        self._depth_sem.release()

    def _finish_pipelined(self, batch: List[_QueueEntry], handle,
                          err: Optional[Exception],
                          trace_since: Optional[int]) -> None:
        """Finalize stage (OUTSIDE the run lock): device sync + host
        post-processing. Runs concurrently with the next batch's
        dispatch stage."""
        released = False
        t0 = time.perf_counter_ns()
        results = None

        def land_stage() -> None:
            # the deferred device-sync + host post-processing stage:
            # histogram for the live tail, leader span + follower links
            # for per-request attribution. Lands BEFORE any future
            # resolves — a submitter thread woken by set_result may
            # immediately finish its request and ship the trace, and the
            # span must already be in it.
            dt = time.perf_counter_ns() - t0
            with self._q_lock:   # concurrent finalizes both land here
                self.sched["finalize_nanos"] += dt
            _metrics.record("serving.device_sync", dt)
            self._trace_batch(batch, "batch.finalize", dt,
                              status="ok" if err is None else "error")

        try:
            if err is None:
                try:
                    results = self._finalize_fn(handle)
                    self._check_results(batch, results)
                except Exception as exc:
                    err = exc
                except BaseException as exc:
                    err = exc
                    land_stage()
                    for entry in batch:
                        if not entry.fut.done():
                            entry.fut.set_exception(exc)
                    raise
            land_stage()
            if err is None:
                self._set_results(batch, results)
            else:
                # serial retries re-enter the FULL sync executor
                # (dispatch + finalize) — take the scheduler lock so
                # they serialize with other dispatch stages exactly like
                # a sync batch (executor plan caches/stats assume
                # dispatch stages never run concurrently). Release this
                # batch's depth slot FIRST: a runner can block on the
                # slot while holding the run lock, so retrying while
                # still holding it would deadlock at async_depth=1.
                self._end_pipelined()
                released = True
                with self._run_lock:
                    self._retry_serially(batch, err)
        finally:
            if not released:
                self._end_pipelined()
            self._annotate(trace_since, len(batch))

    def batch_meta(self) -> dict:
        """Schedule metadata of the batch THIS thread is currently
        executing (set just before the executor runs): coalesced size and
        the longest queue wait among its members. Executors fold it into
        per-request observability (profile.hybrid queue_wait). CONSUMED
        on read — a poisoned batch's serial retries re-enter the
        executor on this same thread and must not re-count the dead
        batch's schedule metadata. Empty off a runner thread."""
        meta = getattr(self._tls, "meta", None)
        self._tls.meta = None
        return dict(meta or {})

    def _run_once(self, entry: Optional[_QueueEntry] = None) -> None:
        """One scheduler turn: drain + top up + serve a batch (if any).
        With `entry`, returns immediately once that entry is claimed or
        done instead of competing to run someone else's batch."""
        pending = None
        with self._run_lock:
            if entry is not None and (entry.fut.done() or entry.claimed):
                return
            batch = self._drain()
            if batch:
                batch = self._topup(batch)
            if not batch:
                return
            self.sched["batches"] += 1
            self.sched["requests"] += len(batch)
            now = time.monotonic()
            leader = self._trace_leader(batch)
            self._tls.meta = {
                "coalesced": len(batch),
                "queue_wait_max_nanos": int(max(
                    (now - e.enqueued) for e in batch) * 1e9),
                # leader trace handoff: the executor's finalize stage
                # (possibly another thread) attaches its fine-grained
                # spans (plan/fuse/hydrate) to the batch leader's trace
                "trace": leader.trace if leader is not None else None,
                "trace_parent": leader.span_parent
                if leader is not None else None}
            # name the drain/finalize sections on the borrowed runner
            # thread so `_nodes/hot_threads` attributes a busy stack to
            # the batcher instead of to whichever client thread happened
            # to become the runner
            with _thread_section("batcher-drain"):
                if self._dispatch_fn is not None:
                    self.sched["pipelined_batches"] += 1
                    pending = self._begin_pipelined(batch)
                else:
                    self._run_sync(batch)
        if pending is not None:
            with _thread_section("batcher-finalize"):
                self._finish_pipelined(*pending)

    def submit(self, request, deadline_at: Optional[float] = None):
        fut: Future = Future()
        entry = self._enqueue(request, fut, deadline_at=deadline_at)
        while not fut.done():
            if entry.claimed:
                # a runner owns this request; its finalize (possibly on
                # another thread) will set the future
                break
            # block until the current runner releases the dispatch lock,
            # then take over if our request still isn't scheduled
            self._run_once(entry)
        return fut.result()


class BoundedBatcher(CombiningBatcher):
    """CombiningBatcher + admission control: the p99-tail fix.

    The r03 record's 1.1–2.5 s p99 tails (15–30× p50) came from exactly
    this queue growing without bound under closed-loop overload — every
    request eventually served, each behind an ever-longer convoy. A
    production serving path sheds instead (the reference's
    EsRejectedExecutionHandler / `ThreadPool.java:129` bounded queues →
    HTTP 429):

    * depth limit — a submit that finds `max_queue_depth` requests already
      waiting is rejected immediately with `EsRejectedExecutionError`
      (HTTP 429 through the existing error mapping); the client retries
      against a queue that can still absorb it.
    * deadline — every request carries `enqueue + deadline_ms` as its
      schedule deadline: the queue orders earliest-deadline-first and the
      scheduler sheds a request the moment it can no longer be served in
      time (at drain AND during top-up claims), rather than spending
      device time on an answer nobody reads.

    `stats` counts shed requests and tracks the high-water queue depth so
    saturation tests can assert the bound actually held.
    """

    def __init__(self, execute: Optional[Callable[[Sequence], List]],
                 max_batch: int = 256, max_queue_depth: int = 256,
                 deadline_ms: Optional[float] = None,
                 warmup: Optional[Callable[[], None]] = None, **kwargs):
        super().__init__(execute, max_batch=max_batch, **kwargs)
        self.max_queue_depth = max_queue_depth
        self.deadline_ms = deadline_ms
        self.stats = {"accepted": 0, "rejected_depth": 0,
                      "shed_deadline": 0, "shed_cancelled": 0,
                      "max_depth_seen": 0}
        if warmup is not None:
            # warmup-at-start: pre-compile the dispatch bucket grid off
            # the critical path, so the queue's first drained batch finds
            # its program compiled instead of stalling behind XLA
            threading.Thread(target=self._run_warmup, args=(warmup,),
                             daemon=True, name="batcher-warmup").start()

    @staticmethod
    def _run_warmup(warmup: Callable[[], None]) -> None:
        try:
            warmup()
        except Exception as exc:
            # a warmup failure must never take down admission — but a
            # silent one is indistinguishable from warmup-disabled while
            # first batches stall behind the compiles warmup exists to
            # absorb, so leave a trace
            import logging
            logging.getLogger("elasticsearch_tpu.serving").warning(
                "hybrid batcher warmup failed (first batches will pay "
                "compiles): %s", exc)

    def _deadline_for(self, now: float) -> Optional[float]:
        if self.deadline_ms is None:
            return None
        return now + self.deadline_ms / 1000.0

    def _shed(self, entry: _QueueEntry, now: float) -> None:
        self.stats["shed_deadline"] += 1
        self.sched["deadline_sheds"] += 1
        if not entry.fut.done():
            waited = (now - entry.enqueued) * 1000.0
            entry.fut.set_exception(EsRejectedExecutionError(
                f"rejected execution: request spent "
                f"{waited:.0f}ms queued, over the "
                f"{self.deadline_ms:.0f}ms admission deadline"))

    def _shed_cancelled(self, entry: _QueueEntry, now: float) -> None:
        self.stats["shed_cancelled"] += 1
        super()._shed_cancelled(entry, now)

    def _admit(self, depth: int, now: float) -> None:
        if depth >= self.max_queue_depth:
            self.stats["rejected_depth"] += 1
            raise EsRejectedExecutionError(
                f"rejected execution: hybrid search queue is full "
                f"[{depth} >= {self.max_queue_depth}] (queue capacity "
                f"{self.max_queue_depth})")
        self.stats["accepted"] += 1
        if depth + 1 > self.stats["max_depth_seen"]:
            self.stats["max_depth_seen"] = depth + 1
