"""Micro-batching dispatch for vector search.

The round-3 serving path dispatched ONE query per device round-trip, so
end-to-end latency was ~100x the device time and tiny-corpus hybrid queries
lost to the reference's host-side BulkScorer (`QueryPhase.java:171`). Two
fixes live here:

* `CombiningBatcher` — a combining-lock queue: the first thread in becomes
  the runner and executes whatever requests accumulated while the previous
  dispatch was in flight. Under load, batch size grows adaptively with no
  added idle latency (an idle submit executes immediately, no timer). This
  is the cross-request coalescing layer the reference never needed (Lucene
  searches are per-thread CPU); a TPU serving path lives or dies by it.

* `CostModel` — per-dispatch host-vs-device routing. A device dispatch pays
  a fixed round-trip (measured once, lazily, against the live backend); a
  host VNNI pass pays corpus-scan time. Small corpus + small batch → host
  kernel (native/es_native.cc es_knn_i8p_topk); large corpus or deep batch →
  device matmul+top-k. Both return identical raw-score conventions, so the
  router is invisible to callers.
"""

from __future__ import annotations

import threading
import time
from concurrent.futures import Future
from typing import Callable, List, Optional, Sequence

from elasticsearch_tpu.common.threadpool import EsRejectedExecutionError

_overhead_lock = threading.Lock()
_overhead_ms: Optional[float] = None


def _probe_kernel(x):
    """Tiny round-trip kernel for `device_overhead_ms` (registered
    lazily — jax import cost stays off module import)."""
    return x + 1.0


def _host_gops() -> float:
    """Measured ~200 GOPS peak with AVX512-VNNI; priced at 150 GOPS — a
    25% derate for sustained serving (frequency throttle + co-running
    work), so the router only sends the host scans it can actually absorb.
    The scalar fallback the kernel dispatches to on older hosts is ~100x
    slower — price it honestly so the router doesn't send scans to a path
    that can't serve them."""
    try:
        from elasticsearch_tpu import native
        if native.knn_has_vnni():
            return 150.0e9
    except Exception:
        pass
    return 2.0e9


HOST_GOPS = None  # resolved lazily via _host_gops (native lib load order)
HOST_MEM_BPS = 10.0e9
# device matmul throughput (bf16 MXU, conservative)
DEVICE_OPS = 100.0e12


def device_overhead_ms() -> float:
    """One-time measurement of a tiny jit round-trip against the live
    backend — the fixed cost a device dispatch must amortize. ~0.1 ms on a
    direct-attached TPU host, tens of ms through a tunneled chip."""
    global _overhead_ms
    if _overhead_ms is not None:
        return _overhead_ms
    with _overhead_lock:
        if _overhead_ms is not None:
            return _overhead_ms
        try:
            import time

            import jax.numpy as jnp

            import numpy as _np

            from elasticsearch_tpu.ops import dispatch

            # the probe rides the same dispatcher every serving kernel
            # uses (a raw jax.jit here was a second compile path outside
            # the AOT cache — tpulint TPU001), so the measured round trip
            # includes the dispatch layer a real serving call pays
            dispatch.DISPATCH.register("serving.overhead_probe",
                                       _probe_kernel)
            x = _np.zeros((8,), _np.float32)
            _np.asarray(dispatch.call("serving.overhead_probe",
                                      jnp.asarray(x)))
            samples = []
            for _ in range(3):
                # a serving dispatch pays h2d (queries/mask), execute, AND
                # d2h (results) — measure the full round trip
                t0 = time.perf_counter()
                # tpulint: disable=TPU002(the probe MEASURES the per-dispatch d2h round trip on purpose; 3 iterations, once per process, not a serving loop)
                _np.asarray(dispatch.call("serving.overhead_probe",
                                          jnp.asarray(x)))
                samples.append((time.perf_counter() - t0) * 1000.0)
            _overhead_ms = max(0.05, min(samples))
        except Exception:
            _overhead_ms = 1.0
    return _overhead_ms


class CostModel:
    """Estimate dispatch latency for a (batch, corpus) shape on each path."""

    @staticmethod
    def host_ms(batch: int, n_rows: int, dims: int) -> float:
        global HOST_GOPS
        if HOST_GOPS is None:
            HOST_GOPS = _host_gops()
        groups = (batch + 15) // 16  # kernel computes 16 query lanes a pass
        compute = 2.0 * groups * 16 * n_rows * dims / HOST_GOPS * 1000.0
        mem = groups * n_rows * dims / HOST_MEM_BPS * 1000.0
        return max(compute, mem) + 0.05

    @staticmethod
    def device_ms(batch: int, n_rows: int, dims: int) -> float:
        compute = 2.0 * batch * n_rows * dims / DEVICE_OPS * 1000.0
        return device_overhead_ms() + compute

    @classmethod
    def prefer_host(cls, batch: int, n_rows: int, dims: int) -> bool:
        return (cls.host_ms(batch, n_rows, dims)
                < cls.device_ms(batch, n_rows, dims))


class CombiningBatcher:
    """Combining-lock request coalescer.

    submit() enqueues and then either (a) finds its result already set by a
    concurrent runner, or (b) becomes the runner: drains the queue and
    executes one batch. While a runner is executing, later submitters just
    queue up — their requests form the next batch. No background thread, no
    batching timer, zero idle latency.
    """

    def __init__(self, execute: Callable[[Sequence], List],
                 max_batch: int = 256):
        from elasticsearch_tpu.ops import dispatch
        self._execute = execute
        # the batch ceiling snaps to a dispatch query bucket: a saturated
        # drain then hands the executor an exactly-bucket-sized batch (no
        # padding waste at peak), and light-load drains pad up to the
        # nearest bucket inside the executor — either way the compiled
        # shape set stays closed
        self._max_batch = dispatch.bucket_queries(max_batch)
        self._run_lock = threading.Lock()
        self._q_lock = threading.Lock()
        self._queue: List = []

    def pending(self) -> int:
        """Requests queued but not yet executed — the coalescing signal
        cost routers use to estimate the NEXT batch's size."""
        with self._q_lock:
            return len(self._queue)

    def _enqueue(self, request, fut: Future) -> None:
        """Admission hook: subclasses may refuse (raise) instead of
        queueing without bound."""
        with self._q_lock:
            self._queue.append((request, fut))

    def _drain(self) -> List:
        """Take the next batch off the queue (under the run lock).
        Subclasses may shed entries here (deadline-expired requests get
        their exception set and are excluded from the batch)."""
        with self._q_lock:
            batch = self._queue[: self._max_batch]
            del self._queue[: self._max_batch]
        return batch

    def submit(self, request):
        fut: Future = Future()
        self._enqueue(request, fut)
        while not fut.done():
            # block until the current runner finishes, then take over if our
            # request still isn't served
            with self._run_lock:
                if fut.done():
                    break
                batch = self._drain()
                if not batch:
                    continue
                # dispatch-trace attribution (profile.dispatch): the
                # runner thread executes device work for EVERY request in
                # the batch. If this thread is recording a profile trace,
                # label the batch's events with the coalesced size so the
                # leader's trace doesn't silently claim follower
                # dispatches as its own; followers still report an empty
                # trace (documented — `_nodes/stats indices.dispatch` is
                # the authoritative counter).
                from elasticsearch_tpu.ops import dispatch as _dispatch
                trace_since = (_dispatch.DISPATCH.event_count()
                               if len(batch) > 1
                               and _dispatch.DISPATCH.events_enabled()
                               else None)
                try:
                    results = self._execute([r for r, _ in batch])
                    if len(results) != len(batch):
                        raise RuntimeError(
                            f"batch executor returned {len(results)} results "
                            f"for {len(batch)} requests")
                    for (_, f), res in zip(batch, results):
                        f.set_result(res)
                except Exception as exc:
                    if len(batch) == 1:
                        if not batch[0][1].done():
                            batch[0][1].set_exception(exc)
                    else:
                        # one poisoned request (bad filter, malformed
                        # vector) must not fail unrelated searches that
                        # happened to coalesce with it: retry each request
                        # alone so only the offender surfaces its error
                        for r, f in batch:
                            if f.done():
                                continue
                            try:
                                f.set_result(self._execute([r])[0])
                            except Exception as one_exc:
                                f.set_exception(one_exc)
                except BaseException as exc:  # KeyboardInterrupt/SystemExit:
                    for _, f in batch:       # fail fast, no serial retries
                        if not f.done():
                            f.set_exception(exc)
                    raise
                finally:
                    # annotate on EVERY exit: the serial per-request
                    # retries of a poisoned batch run on this same
                    # runner thread, and their dispatches are just as
                    # much coalesced-batch work as the happy path's
                    if trace_since is not None:
                        _dispatch.DISPATCH.annotate_events(
                            trace_since, coalesced_batch=len(batch))
        return fut.result()


class BoundedBatcher(CombiningBatcher):
    """CombiningBatcher + admission control: the p99-tail fix.

    The r03 record's 1.1–2.5 s p99 tails (15–30× p50) came from exactly
    this queue growing without bound under closed-loop overload — every
    request eventually served, each behind an ever-longer convoy. A
    production serving path sheds instead (the reference's
    EsRejectedExecutionHandler / `ThreadPool.java:129` bounded queues →
    HTTP 429):

    * depth limit — a submit that finds `max_queue_depth` requests already
      waiting is rejected immediately with `EsRejectedExecutionError`
      (HTTP 429 through the existing error mapping); the client retries
      against a queue that can still absorb it.
    * deadline — a request that waited longer than `deadline_ms` before
      its batch started is dead on arrival (the caller has usually timed
      out); the runner sheds it at drain time rather than spending device
      time on an answer nobody reads.

    `stats` counts shed requests and tracks the high-water queue depth so
    saturation tests can assert the bound actually held.
    """

    def __init__(self, execute: Callable[[Sequence], List],
                 max_batch: int = 256, max_queue_depth: int = 256,
                 deadline_ms: Optional[float] = None,
                 warmup: Optional[Callable[[], None]] = None):
        super().__init__(execute, max_batch=max_batch)
        self.max_queue_depth = max_queue_depth
        self.deadline_ms = deadline_ms
        self.stats = {"accepted": 0, "rejected_depth": 0,
                      "shed_deadline": 0, "max_depth_seen": 0}
        if warmup is not None:
            # warmup-at-start: pre-compile the dispatch bucket grid off
            # the critical path, so the queue's first drained batch finds
            # its program compiled instead of stalling behind XLA
            threading.Thread(target=self._run_warmup, args=(warmup,),
                             daemon=True, name="batcher-warmup").start()

    @staticmethod
    def _run_warmup(warmup: Callable[[], None]) -> None:
        try:
            warmup()
        except Exception as exc:
            # a warmup failure must never take down admission — but a
            # silent one is indistinguishable from warmup-disabled while
            # first batches stall behind the compiles warmup exists to
            # absorb, so leave a trace
            import logging
            logging.getLogger("elasticsearch_tpu.serving").warning(
                "hybrid batcher warmup failed (first batches will pay "
                "compiles): %s", exc)

    def _enqueue(self, request, fut: Future) -> None:
        with self._q_lock:
            depth = len(self._queue)
            if depth >= self.max_queue_depth:
                self.stats["rejected_depth"] += 1
                raise EsRejectedExecutionError(
                    f"rejected execution: hybrid search queue is full "
                    f"[{depth} >= {self.max_queue_depth}] (queue capacity "
                    f"{self.max_queue_depth})")
            self.stats["accepted"] += 1
            if depth + 1 > self.stats["max_depth_seen"]:
                self.stats["max_depth_seen"] = depth + 1
            self._queue.append(((request, time.monotonic()), fut))

    def _drain(self) -> List:
        batch = super()._drain()
        if self.deadline_ms is None:
            return [((req), fut) for (req, _t0), fut in batch]
        now = time.monotonic()
        kept = []
        for (req, t0), fut in batch:
            if (now - t0) * 1000.0 > self.deadline_ms:
                self.stats["shed_deadline"] += 1
                if not fut.done():
                    fut.set_exception(EsRejectedExecutionError(
                        f"rejected execution: request spent "
                        f"{(now - t0) * 1000.0:.0f}ms queued, over the "
                        f"{self.deadline_ms:.0f}ms admission deadline"))
                continue
            kept.append((req, fut))
        return kept
