"""Deadline-budgeted scatter-gather: the cross-node production query path.

Everything below the process boundary already sheds on deadlines — the
continuous batcher (serving/batcher.py) orders its queue earliest-deadline-
first and times a request out the moment it can no longer be served. But the
CLUSTER coordinator's fan-outs (`cluster_node._query_phase` and friends)
waited for `pending == 0` with no timer: one slow or dead data node hung the
whole accumulator, and the request's deadline died at the coordinator
instead of traveling into the per-shard sub-requests.

This module is the reference's layers 5–7 shape (action/transport/
coordination — AbstractSearchAsyncAction + SearchTimeProvider + the
per-shard timeout accounting of `SearchResponse._shards`), rebuilt on the
injected transport/scheduler pair so one implementation serves the
deterministic simulator and the asyncio TCP deployment:

* `ScatterGather` — one fan-out phase under a time budget. Every launched
  sub-request gets its OWN timeout accounting (a dead node can never hang
  the phase); responses/failures/timeouts resolve each item exactly once;
  when the last item resolves (or times out) the phase summary fires.
  All items share the phase's absolute expiry instant, so ONE sweep
  timer per phase enforces every per-shard timeout — the asyncio
  deployment would otherwise accumulate an uncancellable TimerHandle per
  replica per write for the full budget. Late responses — a slow node
  answering after its timeout — are counted and fed to the caller's
  latency observer (ARS) but can no longer change the response.

* deadline envelopes — `attach_deadline` stamps a sub-request with the
  request's ABSOLUTE deadline in coordinator-clock ms (`scheduler.now_ms`
  domain: virtual time under the simulator, CLOCK_MONOTONIC-based loop
  time over TCP — comparable across processes on one host, the gRPC
  absolute-deadline convention). The remote handler reads
  `remaining_ms` on arrival and routes it into its own admission layer:
  the continuous batcher's EDF queue sheds the sub-request *remotely*, so
  the coordinator's per-shard timer is a backstop for dead nodes, not the
  primary shedding mechanism. The coordinator therefore waits
  `deadline_grace_ms` PAST the propagated deadline — a remote shed beats
  the local timer and carries honest attribution.

* `FanoutStats` — per-phase fan-out counters, per-node slow/fail tallies
  (the same signal the ARS observer ranks copies by), remote-shed
  attribution, and partial-response counts; surfaced under
  `_nodes/stats fanout` and, per-request, `profile.fanout`.

Settings (cluster-level, dynamic via `PUT /_cluster/settings`):

    search.fanout.query_budget_ms     per-shard QUERY-phase budget (15000)
    search.fanout.fetch_budget_ms     per-shard FETCH-phase budget (10000)
    search.fanout.deadline_grace_ms   how long the coordinator waits past a
                                      propagated deadline for the remote's
                                      own shed to arrive (1000)
    search.fanout.partial_results     true: budget expiry returns partial
                                      results with `timed_out: true` and
                                      `_shards.failed` accounting; false:
                                      a timed-out phase is a 503 error
                                      (allow_partial_search_results=false)
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Optional

from elasticsearch_tpu.telemetry import metrics as _metrics

# key under which a sub-request carries its deadline envelope; "_"-prefixed
# so it can never collide with a user-visible request field
ENVELOPE_KEY = "_fanout"

DEFAULT_QUERY_BUDGET_MS = 15_000
DEFAULT_FETCH_BUDGET_MS = 10_000
DEFAULT_DEADLINE_GRACE_MS = 1_000

# outcome vocabulary — exactly one per launched item
OK = "ok"
FAILED = "failed"
TIMED_OUT = "timed_out"
SHED = "shed"          # the remote's own admission layer rejected it

_PHASE_KEYS = ("launched", OK, FAILED, TIMED_OUT, SHED,
               "late_responses", "phase_timeouts")


def budgets_from_settings(settings: Optional[dict]) -> dict:
    """Resolve the `search.fanout.*` knobs from a (cluster) settings dict.
    Values may arrive as strings through the REST settings API."""
    from elasticsearch_tpu.common.settings import setting_bool
    s = settings or {}

    def _ms(key: str, default: int) -> int:
        try:
            return max(int(float(s.get(key, default))), 0)
        except (TypeError, ValueError):
            return default

    return {
        "query_budget_ms": _ms("search.fanout.query_budget_ms",
                               DEFAULT_QUERY_BUDGET_MS),
        "fetch_budget_ms": _ms("search.fanout.fetch_budget_ms",
                               DEFAULT_FETCH_BUDGET_MS),
        "deadline_grace_ms": _ms("search.fanout.deadline_grace_ms",
                                 DEFAULT_DEADLINE_GRACE_MS),
        "partial_results": setting_bool(
            s.get("search.fanout.partial_results", True)),
    }


def attach_deadline(request: dict, deadline_at_ms: Optional[int],
                    now_ms: int) -> dict:
    """Stamp a sub-request with the absolute deadline (coordinator-clock
    ms). No-op when the request carries no deadline."""
    if deadline_at_ms is not None:
        request[ENVELOPE_KEY] = {"deadline_at_ms": int(deadline_at_ms),
                                 "sent_at_ms": int(now_ms)}
    return request


def attach_trace(request: dict, trace, parent_span_id: str) -> dict:
    """Ride the trace context on the deadline envelope: the remote node
    opens a trace SEGMENT with the same trace id whose spans parent under
    `parent_span_id` (the coordinator's per-leg span), so the merged
    trace reads as one tree across the transport. No-op when the request
    isn't traced."""
    if trace is not None:
        request.setdefault(ENVELOPE_KEY, {})["trace"] = {
            "trace_id": trace.trace_id,
            "parent_span_id": parent_span_id,
            "opaque_id": trace.opaque_id,
        }
    return request


def trace_ctx_of(request: Optional[dict]) -> Optional[dict]:
    """The trace context an arriving sub-request carries, or None."""
    return ((request or {}).get(ENVELOPE_KEY) or {}).get("trace")


def remaining_ms(request: Optional[dict], now_ms: int) -> Optional[float]:
    """Budget left on an arriving sub-request, or None when it carries no
    deadline. Negative = already expired — shed at admission."""
    env = (request or {}).get(ENVELOPE_KEY) or {}
    at = env.get("deadline_at_ms")
    if at is None:
        return None
    return float(at) - float(now_ms)


def shed_response(shard: Any, shed_by: str) -> dict:
    """The structured rejection a remote node returns when a propagated
    deadline expired before (or while) the sub-request was admitted.
    Travels as a RESPONSE, not a transport failure, so the coordinator
    can attribute it (deadline shed, not node death)."""
    return {"shard": shard, "rejected": "deadline_exceeded",
            "shed_by": shed_by}


def is_shed(resp: Any) -> bool:
    return isinstance(resp, dict) and \
        resp.get("rejected") == "deadline_exceeded"


class FanoutStats:
    """Counters for the cross-node serving path. Mutated only from the
    owning node's scheduler thread (simulator task / asyncio loop), so no
    locking — same single-threaded-actor discipline as the transport."""

    def __init__(self) -> None:
        self.phases: Dict[str, Dict[str, int]] = {}
        self.per_node: Dict[str, Dict[str, int]] = {}
        self.partial_responses = 0
        # data-plane side: sub-requests THIS node shed on arrival because
        # the propagated deadline had expired — `batcher` means the
        # continuous batcher's EDF queue did the shedding
        self.remote = {"sheds_admission": 0, "sheds_batcher": 0}

    def phase(self, name: str) -> Dict[str, int]:
        pc = self.phases.get(name)
        if pc is None:
            pc = self.phases[name] = {k: 0 for k in _PHASE_KEYS}
        return pc

    def node(self, node_id: str) -> Dict[str, int]:
        nc = self.per_node.get(node_id)
        if nc is None:
            nc = self.per_node[node_id] = {"slow": 0, "failed": 0}
        return nc

    def snapshot(self) -> dict:
        return {
            "phases": {p: dict(c) for p, c in sorted(self.phases.items())},
            "per_node": {n: dict(c)
                         for n, c in sorted(self.per_node.items())},
            "partial_responses": self.partial_responses,
            "remote": dict(self.remote),
        }


class ScatterGather:
    """One fan-out phase: launch sub-requests, resolve each exactly once
    (response / failure / per-shard timer), fire `on_done(summary)` when
    the last one resolves.

    Usage::

        sg = ScatterGather(scheduler, phase="query", budget_ms=15_000,
                           stats=node.fanout_stats, on_done=finish)
        for target in targets:
            sg.launch(key, target.node_id, send, on_item=fold)
        sg.seal()

    `send(on_response, on_failure)` performs the actual RPC (or local
    direct call); `on_item(outcome, payload, err)` folds one result into
    the caller's accumulator. `seal()` marks the launch set complete —
    a phase with zero launches completes at seal time.

    The per-shard timeouts make the no-hang guarantee structural: every
    launched item is resolved by the phase's sweep timer at the latest,
    so `on_done` ALWAYS fires within the budget (+ one scheduler hop),
    regardless of what the network drops. One timer serves the whole
    phase because every item expires at the same absolute instant
    (phase start + budget); the sweep resolves each still-pending item
    individually, so per-shard timeout accounting is unchanged.
    """

    def __init__(self, scheduler, *, phase: str, budget_ms: int,
                 stats: Optional[FanoutStats] = None,
                 on_done: Optional[Callable[[dict], None]] = None,
                 observe: Optional[Callable[[str, float], None]] = None,
                 trace=None, trace_parent: Optional[str] = None):
        self._scheduler = scheduler
        self.phase = phase
        self.budget_ms = max(int(budget_ms), 0)
        self.stats = stats if stats is not None else FanoutStats()
        self._on_done = on_done
        # request trace (telemetry.trace.Trace) of the search this phase
        # serves: each launch opens a per-leg span ended at resolution —
        # resolution is structural (response/failure/sweep timer), so a
        # dead node produces an ERROR span, never a leaked one
        self._trace = trace
        self._trace_parent = trace_parent
        # latency observer (ARS EWMA feed): called with (node_id, took_ms)
        # for on-time responses AND late arrivals; timeouts feed a penalty
        self._observe = observe
        self._started_ms = scheduler.now_ms
        self._pending: Dict[Any, str] = {}
        # key -> timeout resolver, installed per launch, popped on
        # resolution (so resolved items' closures free immediately);
        # the single sweep timer drains whatever is left at budget end
        self._timeout_resolvers: Dict[Any, Callable[[], None]] = {}
        self._timer_armed = False
        self._launched = 0
        self._sealed = False
        self._finished = False
        self._counts = {OK: 0, FAILED: 0, TIMED_OUT: 0, SHED: 0}

    # ------------------------------------------------------------ launching
    def launch(self, key: Any, node_id: str,
               send: Callable[[Callable, Callable], None],
               on_item: Optional[Callable[[str, Any, Any], None]] = None,
               request: Optional[dict] = None) -> None:
        pc = self.stats.phase(self.phase)
        pc["launched"] += 1
        self._launched += 1
        self._pending[key] = node_id
        sent_ms = self._scheduler.now_ms
        leg_span = None
        if self._trace is not None:
            leg_span = self._trace.begin_span(
                f"{self.phase}[{node_id}]", parent_id=self._trace_parent,
                node=node_id, shard=str(key))
            if request is not None:
                # the remote's segment parents under THIS leg span, so
                # the merged tree shows coordinator leg → remote work
                attach_trace(request, self._trace, leg_span.span_id)

        def resolve(outcome: str, payload=None, err=None) -> None:
            if self._pending.pop(key, None) is None:
                return  # already resolved (timer raced a late response)
            self._timeout_resolvers.pop(key, None)
            self._counts[outcome] += 1
            pc[outcome] += 1
            if leg_span is not None:
                # one end per leg, on every outcome: a dead node's leg is
                # an ERROR span in the trace, not a leak
                self._trace.end_span(
                    leg_span, status="ok" if outcome == OK else outcome)
            try:
                if on_item is not None:
                    on_item(outcome, payload, err)
            finally:
                # the phase must complete even if the caller's fold raised
                self._maybe_finish()

        def on_response(resp) -> None:
            took = max(self._scheduler.now_ms - sent_ms, 0)
            # live fan-out leg tail (`_nodes/stats telemetry`): scheduler-
            # clock ms (virtual under the simulator) as nanos
            _metrics.record("fanout.leg", int(took * 1e6))
            if key not in self._pending:
                # late: the timer already resolved this shard. Observe the
                # true latency (the ARS signal that makes the next request
                # prefer another copy) but never mutate the response.
                pc["late_responses"] += 1
                if self._observe is not None:
                    self._observe(node_id, float(took))
                return
            if self._observe is not None:
                self._observe(node_id, float(took))
            if is_shed(resp):
                resolve(SHED, resp)
            else:
                resolve(OK, resp)

        def on_failure(err) -> None:
            if key in self._pending:
                self.stats.node(node_id)["failed"] += 1
            resolve(FAILED, None, err)

        def on_timeout() -> None:
            if key not in self._pending:
                return
            self.stats.node(node_id)["slow"] += 1
            if self._observe is not None:
                # a timed-out shard observed at the full budget: the ARS
                # EWMA ranks this node behind every copy that answered
                self._observe(node_id, float(self.budget_ms))
            resolve(TIMED_OUT)

        self._timeout_resolvers[key] = on_timeout
        # one sweep timer per PHASE, armed at the first launch: every
        # item shares the same absolute expiry (phase start + budget),
        # and per-launch timers would pile up uncancellable handles on
        # the asyncio deployment (one per replica per write, alive for
        # the full budget)
        if not self._timer_armed:
            self._timer_armed = True
            delay = max(self._started_ms + self.budget_ms
                        - self._scheduler.now_ms, 0)
            self._scheduler.schedule_in(
                delay, self._sweep_expired, f"fanout:{self.phase}")
        send(on_response, on_failure)

    def _sweep_expired(self) -> None:
        """Budget expiry: resolve every still-pending item as timed out
        (each individually, so per-shard accounting is identical to a
        per-item timer)."""
        for resolver in [self._timeout_resolvers[k]
                         for k in list(self._timeout_resolvers)
                         if k in self._pending]:
            resolver()

    def seal(self) -> None:
        """No more launches; a zero-target phase completes here."""
        self._sealed = True
        self._maybe_finish()

    # ------------------------------------------------------------ completion
    @property
    def timed_out(self) -> bool:
        """Reference `timed_out` semantics: a shard timer expired, or a
        remote shed its sub-request on the propagated deadline."""
        return self._counts[TIMED_OUT] > 0 or self._counts[SHED] > 0

    def _maybe_finish(self) -> None:
        if self._finished or not self._sealed or self._pending:
            return
        self._finished = True
        pc = self.stats.phase(self.phase)
        if self._counts[TIMED_OUT] > 0:
            pc["phase_timeouts"] += 1
        summary = {
            "phase": self.phase,
            "launched": self._launched,
            "budget_ms": self.budget_ms,
            "elapsed_ms": max(self._scheduler.now_ms - self._started_ms, 0),
            # counts per outcome: ok / failed / timed_out / shed
            **dict(self._counts),
            # reference `timed_out` semantics (bool): a shard timer
            # expired, or a remote shed on the propagated deadline
            "any_timed_out": self.timed_out,
        }
        if self._on_done is not None:
            self._on_done(summary)
