"""Index and shard lifecycle on one node.

Re-design of `indices/IndicesService` + `index/IndexService` + `IndexShard`
(SURVEY.md §2.4, layer 9): an index is settings + mappings + N shard engines;
each shard pairs a host engine (postings/doc-values/translog) with a device
vector store. Single-node scope here; the cluster layer routes shard copies
across nodes on top of this.
"""

from __future__ import annotations

import copy
import os
import re
import shutil
import time
from typing import Dict, List, Optional

from elasticsearch_tpu.common.errors import (
    IllegalArgumentError, IndexNotFoundError, ResourceAlreadyExistsError,
    ValidationError,
)
from elasticsearch_tpu.common.settings import Settings
from elasticsearch_tpu.cluster.routing import shard_id_for
from elasticsearch_tpu.index.engine import Engine, EngineResult
from elasticsearch_tpu.index.mapping import MapperService
from elasticsearch_tpu.index.segment import ShardReader, SegmentView
from elasticsearch_tpu.vectors.store import VectorStoreShard

_INDEX_NAME_RE = re.compile(r"^[^A-Z\\/*?\"<>| ,#:][^A-Z\\/*?\"<>| ,#]*$")


def resolve_date_math_name(part: str) -> str:
    """`<static{date_math{format|tz}}>` index-name resolution (reference:
    IndexNameExpressionResolver.DateMathExpressionResolver). Non-date-math
    expressions pass through unchanged."""
    if not (part.startswith("<") and part.endswith(">")):
        return part
    inner, out, i = part[1:-1], [], 0
    while i < len(inner):
        if inner[i] == "{":
            depth, j = 1, i + 1
            while j < len(inner) and depth:
                depth += {"{": 1, "}": -1}.get(inner[j], 0)
                j += 1
            out.append(_eval_date_math(inner[i + 1:j - 1]))
            i = j
        else:
            out.append(inner[i])
            i += 1
    return "".join(out)


def _eval_date_math(expr: str) -> str:
    import datetime as _dt
    fmt = "yyyy.MM.dd"
    if "{" in expr:
        expr, _, rest = expr.partition("{")
        fmt = rest.rstrip("}").split("|", 1)[0]
    t = _dt.datetime.now(_dt.timezone.utc)
    if not expr.startswith("now"):
        raise IllegalArgumentError(
            f"invalid date math expression [{expr}]")
    for op, num, unit in re.findall(r"([+\-/])(\d*)([yMwdhHms])", expr[3:]):
        if op == "/":
            if unit == "y":
                t = t.replace(month=1, day=1, hour=0, minute=0, second=0,
                              microsecond=0)
            elif unit == "M":
                t = t.replace(day=1, hour=0, minute=0, second=0,
                              microsecond=0)
            elif unit == "w":
                t = (t - _dt.timedelta(days=t.weekday())).replace(
                    hour=0, minute=0, second=0, microsecond=0)
            elif unit == "d":
                t = t.replace(hour=0, minute=0, second=0, microsecond=0)
            elif unit in ("h", "H"):
                t = t.replace(minute=0, second=0, microsecond=0)
            elif unit == "m":
                t = t.replace(second=0, microsecond=0)
            else:
                t = t.replace(microsecond=0)
        else:
            n = int(num or 1) * (1 if op == "+" else -1)
            if unit == "y":
                t = t.replace(year=t.year + n)
            elif unit == "M":
                mo = t.month - 1 + n
                t = t.replace(year=t.year + mo // 12, month=mo % 12 + 1)
            else:
                t += _dt.timedelta(**{
                    {"w": "weeks", "d": "days", "h": "hours", "H": "hours",
                     "m": "minutes", "s": "seconds"}[unit]: n})
    return (fmt.replace("yyyy", f"{t.year:04d}")
               .replace("uuuu", f"{t.year:04d}")
               .replace("MM", f"{t.month:02d}")
               .replace("dd", f"{t.day:02d}")
               .replace("HH", f"{t.hour:02d}")
               .replace("mm", f"{t.minute:02d}")
               .replace("ss", f"{t.second:02d}"))

# Rebased multi-shard row space: shard s contributes rows in
# [s * SHARD_ROW_SPACE, (s+1) * SHARD_ROW_SPACE).
SHARD_ROW_SPACE = 1 << 40


class IndexShardHandle:
    """One local shard: engine + device vector store + refresh plumbing."""

    def __init__(self, index_name: str, shard_id: int, path: str,
                 mapper_service: MapperService, translog_sync: str = "request",
                 vector_dtype: str = "bf16", index_sort=None,
                 knn_engine: str = "tpu", knn_nlist=None,
                 knn_nprobe="auto", knn_topup: bool = True,
                 knn_target_batch_latency_ms: float = 2.0,
                 knn_async_depth: int = 2,
                 segments_settings: Optional[dict] = None,
                 semantic_cache_settings: Optional[dict] = None):
        self.index_name = index_name
        self.shard_id = shard_id
        self.engine = Engine(path, mapper_service,
                             translog_sync=translog_sync,
                             index_sort=index_sort)
        self.vector_store = VectorStoreShard(
            dtype=vector_dtype, knn_engine=knn_engine,
            knn_nlist=knn_nlist, knn_nprobe=knn_nprobe,
            topup=knn_topup,
            target_batch_latency_ms=knn_target_batch_latency_ms,
            async_depth=knn_async_depth,
            **(segments_settings or {}),
            **(semantic_cache_settings or {}))
        self.mapper_service = mapper_service
        # seed restored derived state (columnar blocks, IVF layout)
        # BEFORE the first vector sync, so a snapshot-restored shard
        # serves without re-encoding or re-training (recovery/seed.py)
        from elasticsearch_tpu.recovery import seed as recovery_seed
        recovery_seed.maybe_apply(self.engine, self.vector_store)
        self._sync_vectors(self.engine.acquire_searcher())
        self.engine.add_refresh_listener(self._sync_vectors)

    def _sync_vectors(self, reader: ShardReader) -> None:
        vf = self.mapper_service.vector_fields()
        if vf:
            self.vector_store.sync(reader, vf)

    def close(self):
        self.engine.close()


def validate_knn_settings(settings: dict):
    """Validate + normalize the `index.knn.*` engine settings; returns
    (engine, nlist, nprobe). ONE owner for both the single-node create
    path and the cluster master's create-index handler — a bad value must
    400 at creation, never crash a state applier later."""
    engine = str(settings.get("index.knn.engine", "tpu"))
    if engine not in ("tpu", "tpu_ivf"):
        raise IllegalArgumentError(
            f"unknown [index.knn.engine] value [{engine}]; "
            f"expected one of [tpu, tpu_ivf]")
    nlist = settings.get("index.knn.nlist")
    if nlist is not None:
        try:
            nlist = int(nlist)
        except (TypeError, ValueError):
            nlist = 0
        if nlist < 1:
            raise IllegalArgumentError(
                f"[index.knn.nlist] must be an integer >= 1, got "
                f"[{settings.get('index.knn.nlist')}]")
    nprobe = settings.get("index.knn.nprobe", "auto")
    if nprobe != "auto":
        try:
            nprobe = int(nprobe)
        except (TypeError, ValueError):
            nprobe = 0
        if nprobe < 1:
            raise IllegalArgumentError(
                f"[index.knn.nprobe] must be an integer >= 1 or "
                f"\"auto\", got [{settings.get('index.knn.nprobe')}]")
    return engine, nlist, nprobe


def validate_segments_settings(settings: dict) -> dict:
    """Validate + normalize the `index.segments.*` generational-corpus
    settings into `VectorStoreShard` constructor kwargs. ONE owner for
    the single-node create path and the cluster master's create-index
    handler (like `validate_knn_settings`)."""
    from elasticsearch_tpu.common.settings import setting_bool
    out = {"segments_enabled": setting_bool(
        settings.get("index.segments.enabled", True), default=True)}
    for key, attr, floor in (("index.segments.tier_size",
                              "segments_tier_size", 2),
                             ("index.segments.max_l0",
                              "segments_max_l0", 1)):
        raw = settings.get(key)
        if raw is None:
            continue
        try:
            val = int(raw)
        except (TypeError, ValueError):
            val = floor - 1
        if val < floor:
            raise IllegalArgumentError(
                f"[{key}] must be an integer >= {floor}, got [{raw}]")
        out[attr] = val
    raw = settings.get("index.segments.merge_budget_ms")
    if raw is not None:
        try:
            val = float(raw)
        except (TypeError, ValueError):
            val = -1.0
        if val <= 0:
            raise IllegalArgumentError(
                f"[index.segments.merge_budget_ms] must be a number "
                f"> 0, got [{raw}]")
        out["segments_merge_budget_ms"] = val
    return out


def validate_semantic_cache_settings(settings: dict) -> dict:
    """Validate + normalize the `index.knn.semantic_cache.*` settings
    (vectors/semantic_cache.py: opt-in device-resident ring of recent
    query embeddings) into `VectorStoreShard` constructor kwargs. ONE
    owner for the single-node create path and the cluster master's
    create-index handler (like `validate_knn_settings`)."""
    from elasticsearch_tpu.common.settings import setting_bool
    out = {"semantic_cache_enabled": setting_bool(
        settings.get("index.knn.semantic_cache.enabled", False),
        default=False)}
    raw = settings.get("index.knn.semantic_cache.size")
    if raw is not None:
        try:
            val = int(raw)
        except (TypeError, ValueError):
            val = 0
        if val < 1 or val > 65536:
            raise IllegalArgumentError(
                f"[index.knn.semantic_cache.size] must be an integer in "
                f"[1, 65536], got [{raw}]")
        out["semantic_cache_size"] = val
    raw = settings.get("index.knn.semantic_cache.threshold")
    if raw is not None:
        try:
            val = float(raw)
        except (TypeError, ValueError):
            val = -1.0
        if not (0.5 <= val <= 1.0):
            raise IllegalArgumentError(
                f"[index.knn.semantic_cache.threshold] must be a number "
                f"in [0.5, 1.0], got [{raw}]")
        out["semantic_cache_threshold"] = val
    return out


def _reject_translog_retention(settings: dict) -> None:
    """index.translog.retention.* was removed in 8.0 (soft deletes own
    history retention — IndexSettings.TRANSLOG_RETENTION checks)."""
    def _walk(d, prefix=""):
        for k, v in (d or {}).items():
            path = f"{prefix}{k}"
            if isinstance(v, dict):
                _walk(v, path + ".")
            elif path.replace("index.", "", 1).startswith(
                    "translog.retention."):
                raise IllegalArgumentError(
                    f"Translog retention setting [{path}] is no longer "
                    f"supported; history is retained by soft deletes")
    _walk(settings)


class IndexService:
    def __init__(self, name: str, path: str, settings: Settings, mapping: dict,
                 uuid: str):
        self.name = name
        self.path = path
        self.uuid = uuid
        self.settings = settings
        self.creation_date = int(time.time() * 1000)
        # closed indices reject reads/writes but keep metadata visible
        # (MetaDataIndexStateService open/close)
        self.closed = False
        # recovery provenance for the _recovery API (RecoverySource):
        # EMPTY_STORE fresh, EXISTING_STORE reopened from disk, SNAPSHOT
        # restored (set by snapshots/service.py with the source coords)
        self.recovery_source = {"type": "EMPTY_STORE"}
        from elasticsearch_tpu.index.analysis import AnalysisRegistry
        registry = AnalysisRegistry.from_index_settings(
            settings.as_flat_dict())
        self.analysis_registry = registry
        self.mapper_service = MapperService(mapping or {"properties": {}},
                                            registry=registry)
        nested_limit = settings.get("index.mapping.nested_objects.limit",
                                    settings.get(
                                        "mapping.nested_objects.limit"))
        if nested_limit is not None:
            self.mapper_service.nested_objects_limit = int(nested_limit)
        soft = settings.get("index.soft_deletes.enabled",
                            settings.get("soft_deletes.enabled", True))
        if str(soft).lower() == "false":
            raise IllegalArgumentError(
                "Creating indices with soft-deletes disabled is no longer "
                "supported. The setting [index.soft_deletes.enabled] can "
                "only be set to true.")
        self.num_shards = int(settings.get("index.number_of_shards", 1))
        self.num_replicas = int(settings.get("index.number_of_replicas", 1))
        if self.num_shards < 1 or self.num_shards > 1024:
            raise IllegalArgumentError(
                f"index [{name}]: number_of_shards must be in [1, 1024], "
                f"got {self.num_shards}")
        sync = settings.get("index.translog.durability", "request")
        sync = "request" if sync == "request" else "async"
        vec_dtype = settings.get("index.knn.vector_dtype", "bf16")
        knn_engine, knn_nlist, knn_nprobe = validate_knn_settings(
            settings.as_flat_dict())
        sort_field = settings.get("index.sort.field")
        index_sort = None
        if sort_field:
            if isinstance(sort_field, list):
                # list syntax accepted; physical sorting uses the primary
                # (first) sort field
                sort_field = sort_field[0] if sort_field else None
            order_s = settings.get("index.sort.order", "asc")
            if isinstance(order_s, list):
                order_s = order_s[0] if order_s else "asc"
            if sort_field:
                index_sort = (str(sort_field), str(order_s))
        # continuous-batching knobs of the per-shard kNN batchers
        # (`vectors/store.py`): bucket top-up + pipelined dispatch depth
        from elasticsearch_tpu.common.settings import setting_bool
        knn_topup = setting_bool(settings.get("index.knn.topup", True))
        knn_target_ms = float(settings.get(
            "index.knn.target_batch_latency_ms", 2.0))
        knn_async_depth = int(settings.get("index.knn.async_depth", 2))
        # generational device segments (`elasticsearch_tpu/segments/`):
        # seal/tombstone/merge lifecycle knobs of the vector store
        segments_settings = validate_segments_settings(
            settings.as_flat_dict())
        # device-resident semantic cache (`index.knn.semantic_cache.*`):
        # opt-in near-duplicate query reuse on the kNN path
        semantic_cache_settings = validate_semantic_cache_settings(
            settings.as_flat_dict())
        self.shards: List[IndexShardHandle] = []
        for s in range(self.num_shards):
            self.shards.append(IndexShardHandle(
                name, s, os.path.join(path, str(s)), self.mapper_service,
                translog_sync=sync, vector_dtype=vec_dtype,
                index_sort=index_sort, knn_engine=knn_engine,
                knn_nlist=knn_nlist, knn_nprobe=knn_nprobe,
                knn_topup=knn_topup,
                knn_target_batch_latency_ms=knn_target_ms,
                knn_async_depth=knn_async_depth,
                segments_settings=segments_settings,
                semantic_cache_settings=semantic_cache_settings))
        self.aliases: Dict[str, dict] = {}

    @property
    def hidden(self) -> bool:
        """index.hidden: excluded from wildcard expansion by default
        (reference: IndexMetaData.INDEX_HIDDEN_SETTING, 7.7+)."""
        return str(self.settings.get("index.hidden", "false")) in ("true", "True")

    def settings_update(self, updates: Dict[str, Any]) -> None:
        """Apply dynamic index-setting updates (reference:
        MetaDataUpdateSettingsService — dynamic settings only; static ones
        like number_of_shards are rejected)."""
        _reject_translog_retention(updates)
        for key in updates:
            if key in ("index.number_of_shards", "index.uuid"):
                raise IllegalArgumentError(
                    f"setting [{key}] is not dynamically updateable")
        merged = dict(self.settings.as_flat_dict())
        for k, v in updates.items():
            if v is None:
                merged.pop(k, None)  # null resets to the default
            else:
                merged[k] = v
        self.settings = Settings.of(merged)
        if "index.number_of_replicas" in updates:
            v = updates["index.number_of_replicas"]
            self.num_replicas = 1 if v is None else int(v)  # null = default

    def route(self, doc_id: str, routing: Optional[str] = None) -> IndexShardHandle:
        sid = shard_id_for(routing if routing is not None else doc_id, self.num_shards)
        return self.shards[sid]

    def refresh(self):
        for s in self.shards:
            s.engine.refresh()

    def flush(self):
        for s in self.shards:
            s.engine.flush()
        self.flush_count = getattr(self, "flush_count", 0) + 1

    def force_merge(self):
        for s in self.shards:
            s.engine.merge()

    def doc_count(self) -> int:
        return sum(s.engine.doc_count() for s in self.shards)

    def combined_reader(self, exclude_shards=frozenset()) -> ShardReader:
        """A reader spanning all local shards with rebased global rows.

        Single-node aggregation scope: cross-shard aggs run over this merged
        view (the distributed layer replaces this with per-shard partials +
        coordinator reduce, `SearchPhaseController.reduceAggs`).

        Memoized on the underlying per-shard reader generations: repeated
        searches between refreshes see the SAME reader object (and gen),
        which is what keys the request/query caches and the per-reader
        field-stats cache.

        exclude_shards: internal shard ids to omit entirely — the
        shard-failure retry path (a failed shard contributes nothing, as
        if it didn't exist). Not memoized; error paths only.
        """
        gens = tuple(s.engine.acquire_searcher().gen for s in self.shards)
        if not exclude_shards \
                and getattr(self, "_combined_gens", None) == gens:
            return self._combined_reader
        views = []
        for s in self.shards:
            if s.shard_id in exclude_shards:
                continue
            offset = s.shard_id * SHARD_ROW_SPACE
            for view in s.engine.acquire_searcher().views:
                seg = copy.copy(view.segment)
                seg.base = view.segment.base + offset
                v2 = SegmentView.__new__(SegmentView)
                v2.segment = seg
                v2.live = view.live
                views.append(v2)
        reader = ShardReader(views)
        if not exclude_shards:
            self._combined_reader = reader
            self._combined_gens = gens
        return reader

    def shard_of_row(self, row: int) -> IndexShardHandle:
        return self.shards[row // SHARD_ROW_SPACE]

    def close(self):
        for s in self.shards:
            s.close()


class IndicesService:
    def __init__(self, data_path: str):
        self.data_path = data_path
        os.makedirs(data_path, exist_ok=True)
        self.indices: Dict[str, IndexService] = {}
        self._uuid_counter = 0
        self._load_existing()

    # -- persistence of index metadata ---------------------------------------
    def _meta_path(self, name: str) -> str:
        return os.path.join(self.data_path, name, "index_meta.json")

    def _load_existing(self) -> None:
        if not os.path.isdir(self.data_path):
            return
        for name in sorted(os.listdir(self.data_path)):
            if os.path.exists(self._meta_path(name)):
                self.open_index(name)

    def update_settings(self, svc: IndexService, updates: Dict[str, Any]) -> None:
        """Dynamic settings update + durable metadata write — in-memory-only
        updates would silently lose state (e.g. index.frozen) on restart."""
        svc.settings_update(updates)
        self._persist_meta(svc)

    def _persist_meta(self, svc: IndexService) -> None:
        import json
        os.makedirs(os.path.dirname(self._meta_path(svc.name)), exist_ok=True)
        with open(self._meta_path(svc.name), "w") as f:
            json.dump({"settings": svc.settings.as_flat_dict(),
                       "mappings": svc.mapper_service.to_dict(),
                       "aliases": svc.aliases,
                       "uuid": svc.uuid,
                       "state": "close" if svc.closed else "open"}, f)

    # -- CRUD -----------------------------------------------------------------
    def open_index(self, name: str) -> IndexService:
        """Open an index from an existing on-disk data directory (restore path)."""
        import json
        meta_file = self._meta_path(name)
        if not os.path.exists(meta_file):
            raise IndexNotFoundError(name)
        if name in self.indices:
            raise ResourceAlreadyExistsError(f"index [{name}] already open")
        with open(meta_file) as f:
            meta = json.load(f)
        svc = IndexService(name, os.path.join(self.data_path, name),
                           Settings(meta.get("settings", {})),
                           meta.get("mappings", {}), meta.get("uuid", name))
        svc.aliases = meta.get("aliases", {})
        svc.closed = meta.get("state") == "close"
        svc.recovery_source = {"type": "EXISTING_STORE"}
        self.indices[name] = svc
        return svc

    # -- open / close state ---------------------------------------------------
    def close_index_state(self, name: str) -> None:
        """POST /{index}/_close: reads/writes rejected until reopened
        (MetaDataIndexStateService.closeIndices)."""
        svc = self.get(name)
        svc.flush()  # closing commits everything (the reopened index
        # then recovers from its own files: existing_store)
        svc.closed = True
        self._persist_meta(svc)

    def open_index_state(self, name: str) -> None:
        svc = self.get(name)
        svc.closed = False
        self._persist_meta(svc)

    def check_open(self, svc: IndexService) -> IndexService:
        from elasticsearch_tpu.common.errors import IndexClosedError
        if svc.closed:
            raise IndexClosedError(f"closed index [{svc.name}]")
        return svc

    def create_index(self, name: str, settings: Optional[dict] = None,
                     mappings: Optional[dict] = None,
                     aliases: Optional[dict] = None) -> IndexService:
        self.validate_index_name(name)
        if name in self.indices:
            raise ResourceAlreadyExistsError(f"index [{name}] already exists", index=name)
        flat = Settings.builder()
        flat.put("index.number_of_shards", 1)
        flat.put("index.number_of_replicas", 1)
        if settings:
            _reject_translog_retention(settings)
            # normalize every key under the index. namespace — bodies mix
            # bare keys with a nested "index" object freely
            norm = {}
            for k, v in settings.items():
                if k == "index" and isinstance(v, dict):
                    norm.setdefault("index", {}).update(v)
                elif k.startswith("index."):
                    norm[k] = v
                else:
                    norm.setdefault("index", {})[k] = v
            flat.put_dict(norm)
        s = flat.build()
        self._uuid_counter += 1
        # 22-char base64 uuid (reference: UUIDs.base64UUID via
        # TimeBasedUUIDGenerator; the _cat suites pin the 22-char shape)
        import base64
        uuid = base64.b64encode(
            os.urandom(4) + self._uuid_counter.to_bytes(4, "big")
            + os.urandom(8)).decode()[:22]
        svc = IndexService(name, os.path.join(self.data_path, name), s,
                           mappings, uuid)
        if aliases:
            svc.aliases = {a: (spec or {}) for a, spec in aliases.items()}
        self.indices[name] = svc
        self._persist_meta(svc)
        return svc

    def delete_index(self, name: str) -> None:
        svc = self.indices.pop(name, None)
        if svc is None:
            raise IndexNotFoundError(name)
        svc.close()
        shutil.rmtree(svc.path, ignore_errors=True)

    def get(self, name: str) -> IndexService:
        """Resolve a concrete index or single-index alias for a
        single-document op; a multi-index alias is an error (reference:
        IndexNameExpressionResolver.concreteSingleIndex)."""
        name = resolve_date_math_name(name)
        svc = self.indices.get(name)
        if svc is None:
            matches = [s for s in self.indices.values() if name in s.aliases]
            if len(matches) > 1:
                names = ", ".join(sorted(s.name for s in matches))
                raise IllegalArgumentError(
                    f"Alias [{name}] has more than one indices associated "
                    f"with it [[{names}]], can't execute a single index op")
            if matches:
                return matches[0]
            raise IndexNotFoundError(name)
        return svc

    def exists(self, name: str) -> bool:
        if name in self.indices:
            return True
        return any(name in s.aliases for s in self.indices.values())

    def resolve(self, expression: Optional[str],
                expand_hidden: bool = False,
                expand_closed: bool = False) -> List[IndexService]:
        """Resolve a comma/wildcard index expression (reference:
        IndexNameExpressionResolver). Hidden indices are excluded from
        wildcard expansion unless `expand_hidden` (expand_wildcards=all/
        hidden) or both the pattern and the index name are dot-prefixed."""
        if expression in (None, "", "_all", "*"):
            # wildcard/_all expansion targets OPEN indices
            # (IndicesOptions.expandWildcardsOpen default)
            return [s for s in self.indices.values()
                    if (expand_closed or not s.closed)
                    and (expand_hidden or not s.hidden)]
        out = []
        seen = set()
        for part in expression.split(","):
            part = resolve_date_math_name(part.strip())
            if "*" in part:
                pat = re.compile("^" + part.replace(".", r"\.").replace("*", ".*") + "$")
                dotted = part.startswith(".")

                def visible(s, n):
                    return (expand_hidden or not s.hidden
                            or (dotted and n.startswith(".")))
                matched = [s for n, s in self.indices.items()
                           if pat.match(n)
                           and (expand_closed or not s.closed)
                           and visible(s, n)]
                for s in self.indices.values():
                    if s.closed and not expand_closed:
                        continue
                    for a, opts in s.aliases.items():
                        # an alias is hidden only when itself declared
                        # is_hidden (not because its index is hidden)
                        a_visible = (expand_hidden
                                     or not (opts or {}).get("is_hidden")
                                     or (dotted and a.startswith(".")))
                        if pat.match(a) and a_visible:
                            matched.append(s)
                            break
                for m in matched:
                    if m.name not in seen:
                        seen.add(m.name)
                        out.append(m)
            else:
                svc = self.indices.get(part)
                if svc is None:
                    # a multi-target expression expands an alias to ALL its
                    # indices (the single-index-op restriction in get()
                    # applies only to doc-level ops)
                    matches = [s for s in self.indices.values()
                               if part in s.aliases]
                    if not matches:
                        raise IndexNotFoundError(part)
                    for m in matches:
                        if m.name not in seen:
                            seen.add(m.name)
                            out.append(m)
                    continue
                if svc.name not in seen:
                    seen.add(svc.name)
                    out.append(svc)
        return out

    def resolve_open(self, expression: Optional[str]) -> List[IndexService]:
        """Resolve for DATA operations: a concretely-named closed index is
        an error (IndexClosedException); wildcards already skipped them."""
        out = self.resolve(expression)
        for svc in out:
            self.check_open(svc)
        return out

    @staticmethod
    def validate_index_name(name: str) -> None:
        if not name or name in (".", "..") or name.startswith(("-", "_", "+")) \
                or not _INDEX_NAME_RE.match(name) or len(name.encode()) > 255:
            from elasticsearch_tpu.common.errors import InvalidIndexNameError
            raise InvalidIndexNameError(
                f"Invalid index name [{name}]", index=name)

    def update_mapping(self, name: str, mapping: dict) -> None:
        svc = self.get(name)
        svc.mapper_service.merge(mapping)
        self._persist_meta(svc)

    def update_aliases(self, actions: List[dict]) -> None:
        def _targets(spec, key, plural):
            # `index`/`indices` (and `alias`/`aliases`) are interchangeable
            # singular/plural forms (IndicesAliasesRequest.AliasActions)
            vals = spec.get(plural)
            if vals is None:
                one = spec.get(key)
                if one is None:
                    raise IllegalArgumentError(f"[{key}] is required")
                vals = [one]
            elif isinstance(vals, str):
                vals = [vals]
            return [str(v) for v in vals]

        for action in actions:
            if "add" in action:
                spec = action["add"]
                opts = {k: v for k, v in spec.items()
                        if k not in ("index", "indices", "alias", "aliases")}
                # plain `routing` expands to both sides (AliasMetaData);
                # routing values are strings
                if "routing" in opts:
                    routing = opts.pop("routing")
                    opts.setdefault("index_routing", str(routing))
                    opts.setdefault("search_routing", str(routing))
                for rk in ("index_routing", "search_routing"):
                    if rk in opts:
                        opts[rk] = str(opts[rk])
                for iname in _targets(spec, "index", "indices"):
                    for svc in (self.resolve(iname) if "*" in iname
                                else [self.get(iname)]):
                        for alias in _targets(spec, "alias", "aliases"):
                            svc.aliases[alias] = dict(opts)
                        self._persist_meta(svc)
            elif "remove" in action:
                spec = action["remove"]
                for iname in _targets(spec, "index", "indices"):
                    for svc in (self.resolve(iname) if "*" in iname
                                else [self.get(iname)]):
                        import fnmatch as _fn
                        for alias in _targets(spec, "alias", "aliases"):
                            if "*" in alias:
                                for a in [a for a in svc.aliases
                                          if _fn.fnmatch(a, alias)]:
                                    svc.aliases.pop(a, None)
                            else:
                                svc.aliases.pop(alias, None)
                        self._persist_meta(svc)
            elif "remove_index" in action:
                # atomic swap support (IndicesAliasesRequest removeIndex)
                spec = action["remove_index"]
                for iname in _targets(spec, "index", "indices"):
                    self.delete_index(iname)
            else:
                raise IllegalArgumentError(
                    "alias action must be add, remove, or remove_index")

    def close(self):
        for svc in self.indices.values():
            svc.close()
