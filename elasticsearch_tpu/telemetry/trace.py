"""Distributed request tracing: spans, per-node trace ring, context.

One trace follows ONE request across every layer it crosses — REST parse,
coordinator fan-out, each scatter-gather leg, the remote node's queue
wait, the shape-bucketed device dispatch, the deferred device sync at
finalize, hydrate and merge — and lands, completed, in a bounded per-node
ring served by `GET _nodes/traces`. Design constraints, in order:

* zero host syncs — spans NEVER force a device read. Live spans read
  `time.monotonic_ns()` around host work; device-time attribution reuses
  durations the serving code already measures at its existing sync
  points (`record_span(name, dur_ns)` is retroactive). tpulint
  TPU002/TPU009 stay clean by construction because tracing adds no
  blocking calls.
* survives the async pipelined batcher — a request's dispatch and
  finalize run on different threads, so context travels on the queue
  entry (captured at enqueue from the submitting thread's context), not
  on thread-locals alone. A request coalesced into another request's
  batch does NOT claim the batch's device time: the batch LEADER's trace
  carries the dispatch/sync spans, and followers carry a link
  `{trace_id, span_id, reason: coalesced_follower}` to them.
* crosses the transport — `serving/fanout.attach_trace` rides the trace
  context (trace id + parent span id) on the PR-12 deadline envelope;
  the remote node opens a trace SEGMENT with the same trace id whose
  spans parent under the coordinator's leg, returns the span list in its
  response for the coordinator to absorb, and ALSO keeps the segment in
  its own ring (so `_nodes/traces` attributes per node).

Sampling: `telemetry.tracing.sample_rate` picks every round(1/rate)-th
request deterministically (a counter, not an RNG — reproducible in
tests); `?trace=true` or a `profile` body forces a trace regardless.

Spans opened live (`begin_span`) MUST be closed on every path — use the
`span()` context manager or `end_span` in a `finally:`; tpulint TPU012
flags the leaked-span shape statically.
"""

from __future__ import annotations

import threading
import time
import uuid
from collections import deque
from contextlib import contextmanager
from typing import Any, Dict, List, Optional

DEFAULT_SAMPLE_RATE = 0.01
DEFAULT_RING_SIZE = 256


def _new_id() -> str:
    return uuid.uuid4().hex[:16]


class Span:
    __slots__ = ("span_id", "parent_id", "name", "start_ns", "dur_ns",
                 "status", "attrs")

    def __init__(self, name: str, parent_id: Optional[str],
                 start_ns: int, attrs: Optional[dict] = None):
        self.span_id = _new_id()
        self.parent_id = parent_id
        self.name = name
        self.start_ns = start_ns
        self.dur_ns: Optional[int] = None   # None = still open
        self.status = "ok"
        self.attrs = attrs or {}

    def to_dict(self) -> dict:
        out = {"span_id": self.span_id, "parent_id": self.parent_id,
               "name": self.name, "start_ns": self.start_ns,
               "dur_ns": self.dur_ns, "status": self.status}
        if self.attrs:
            out["attrs"] = dict(self.attrs)
        return out


class Trace:
    """One request's trace (or, on a data node, one remote segment of a
    coordinator's trace — same trace_id, different node_id). Spans append
    under a lock: the pipelined batcher legitimately writes from several
    threads (submit thread, runner thread, finalize thread)."""

    __slots__ = ("trace_id", "node_id", "action", "opaque_id", "forced",
                 "root", "spans", "links", "started_ns", "took_ns",
                 "_open", "_lock")

    def __init__(self, action: str, node_id: str,
                 opaque_id: Optional[str] = None, forced: bool = False,
                 trace_id: Optional[str] = None,
                 parent_span_id: Optional[str] = None):
        self.trace_id = trace_id or _new_id()
        self.node_id = node_id
        self.action = action
        self.opaque_id = opaque_id
        self.forced = forced
        self.spans: List[Span] = []
        self.links: List[dict] = []
        self.started_ns = time.monotonic_ns()
        self.took_ns: Optional[int] = None
        self._open: Dict[str, str] = {}   # span_id -> name (insertion order)
        self._lock = threading.Lock()
        self.root = self.begin_span(action, parent_id=parent_span_id)

    # ----------------------------------------------------------- live spans
    def begin_span(self, name: str, parent_id: Optional[str] = None,
                   **attrs) -> Span:
        """Open a live span NOW. Every begin_span must reach `end_span`
        on all paths (context manager or try/finally — tpulint TPU012)."""
        sp = Span(name, parent_id, time.monotonic_ns(), attrs or None)
        with self._lock:
            self.spans.append(sp)
            self._open[sp.span_id] = name
        return sp

    def end_span(self, sp: Span, status: Optional[str] = None) -> None:
        if sp.dur_ns is None:
            sp.dur_ns = time.monotonic_ns() - sp.start_ns
        if status is not None:
            sp.status = status
        with self._lock:
            self._open.pop(sp.span_id, None)

    # ---------------------------------------------------- retroactive spans
    def record_span(self, name: str, dur_ns: int,
                    parent_id: Optional[str] = None,
                    status: str = "ok", **attrs) -> str:
        """Attach an already-measured duration as a closed span — the
        zero-host-sync path for device-adjacent attribution: the serving
        code measured `dur_ns` at a sync point that already exists, and
        the span is born finished (it can never leak)."""
        sp = Span(name, parent_id, time.monotonic_ns() - max(int(dur_ns), 0),
                  attrs or None)
        sp.dur_ns = max(int(dur_ns), 0)
        sp.status = status
        with self._lock:
            self.spans.append(sp)
        return sp.span_id

    def add_link(self, trace_id: str, span_id: str, reason: str) -> None:
        """Reference a span in ANOTHER trace without claiming its time —
        the coalesced-follower shape: the leader's trace carries the
        batch's device spans, followers carry this link."""
        with self._lock:
            self.links.append({"trace_id": trace_id, "span_id": span_id,
                               "reason": reason})

    def absorb(self, span_dicts: List[dict]) -> None:
        """Fold a remote segment's serialized spans into this trace (the
        coordinator side of cross-node tracing). Parent ids were set by
        the remote against the envelope's parent span, so the merged tree
        hangs together without rewriting."""
        with self._lock:
            for d in span_dicts:
                sp = Span(d.get("name", "?"), d.get("parent_id"),
                          int(d.get("start_ns", 0)), d.get("attrs"))
                sp.span_id = d.get("span_id", sp.span_id)
                sp.dur_ns = d.get("dur_ns")
                sp.status = d.get("status", "ok")
                self.spans.append(sp)

    # ------------------------------------------------------------ rendering
    def current_span_name(self) -> Optional[str]:
        """Name of the most recently opened, still-open span — what the
        tasks API shows as `current_span` for an in-flight request."""
        with self._lock:
            name = None
            for name in self._open.values():
                pass
            return name

    def span_dicts(self) -> List[dict]:
        with self._lock:
            return [sp.to_dict() for sp in self.spans]

    def top_spans(self, n: int = 3) -> List[dict]:
        """The n longest CLOSED spans (root excluded) — the attachment a
        slow-log breach carries so an operator can answer 'where did THIS
        slow request spend its time' from the log line alone."""
        with self._lock:
            closed = [sp for sp in self.spans
                      if sp.dur_ns is not None and sp is not self.root]
        closed.sort(key=lambda sp: -(sp.dur_ns or 0))
        return [{"name": sp.name, "dur_ns": sp.dur_ns,
                 **({"node": sp.attrs["node"]} if "node" in sp.attrs
                    else {})}
                for sp in closed[:n]]

    def to_dict(self) -> dict:
        return {"trace_id": self.trace_id, "node": self.node_id,
                "action": self.action, "opaque_id": self.opaque_id,
                "forced": self.forced, "took_ns": self.took_ns,
                "spans": self.span_dicts(),
                "links": list(self.links)}


class Tracer:
    """Sampling decisions + the bounded completed-trace ring.

    Process-wide (`TRACER`), like the dispatcher: in a multi-node-per-
    process simulation each trace carries the node_id it completed on,
    and the ring filters per node at read time."""

    def __init__(self, sample_rate: float = DEFAULT_SAMPLE_RATE,
                 ring_size: int = DEFAULT_RING_SIZE):
        self._lock = threading.Lock()
        self._sample_every = self._every(sample_rate)
        self.sample_rate = sample_rate
        self._req = 0
        self._ring: deque = deque(maxlen=ring_size)
        self.stats = {"started": 0, "sampled": 0, "forced": 0,
                      "completed": 0}

    @staticmethod
    def _every(rate: float) -> int:
        if rate is None or rate <= 0.0:
            return 0
        return max(int(round(1.0 / min(float(rate), 1.0))), 1)

    def configure(self, sample_rate: Optional[float] = None,
                  ring_size: Optional[int] = None) -> None:
        with self._lock:
            if sample_rate is not None:
                self.sample_rate = float(sample_rate)
                self._sample_every = self._every(float(sample_rate))
            if ring_size is not None and ring_size != self._ring.maxlen:
                self._ring = deque(self._ring, maxlen=max(int(ring_size),
                                                          1))

    def should_sample(self) -> bool:
        """Deterministic head sampling: every round(1/rate)-th request."""
        with self._lock:
            if self._sample_every <= 0:
                return False
            self._req += 1
            return self._req % self._sample_every == 0

    # ------------------------------------------------------------ lifecycle
    def start(self, action: str, node_id: str, forced: bool = False,
              opaque_id: Optional[str] = None) -> Optional[Trace]:
        """Root-trace entry (the REST layer). None = not sampled."""
        if not forced and not self.should_sample():
            return None
        with self._lock:
            self.stats["started"] += 1
            self.stats["forced" if forced else "sampled"] += 1
        return Trace(action, node_id, opaque_id=opaque_id, forced=forced)

    def start_remote(self, action: str, node_id: str, trace_id: str,
                     parent_span_id: Optional[str],
                     opaque_id: Optional[str] = None) -> Trace:
        """Remote-segment entry (a data node serving a sub-request whose
        envelope carried trace context): always traced — the coordinator
        already paid the sampling decision."""
        with self._lock:
            self.stats["started"] += 1
        return Trace(action, node_id, opaque_id=opaque_id, forced=True,
                     trace_id=trace_id, parent_span_id=parent_span_id)

    def finish(self, trace: Trace, status: Optional[str] = None) -> None:
        trace.end_span(trace.root, status=status)
        trace.took_ns = trace.root.dur_ns
        with self._lock:
            self.stats["completed"] += 1
            self._ring.append(trace)

    # ------------------------------------------------------------- reading
    def traces(self, node_id: Optional[str] = None,
               limit: int = 50) -> List[dict]:
        """Most-recent-first completed traces, optionally filtered to one
        node's segments (the per-node `_nodes/traces` view)."""
        with self._lock:
            items = list(self._ring)
        out = []
        for tr in reversed(items):
            if node_id is not None and tr.node_id != node_id:
                continue
            out.append(tr.to_dict())
            if len(out) >= max(int(limit), 1):
                break
        return out

    def snapshot(self) -> dict:
        with self._lock:
            return {**self.stats, "ring": len(self._ring),
                    "ring_size": self._ring.maxlen,
                    "sample_rate": self.sample_rate}

    def clear(self) -> None:
        """Tests/bench only."""
        with self._lock:
            self._ring.clear()
            for k in self.stats:
                self.stats[k] = 0
            self._req = 0


TRACER = Tracer()


# ---------------------------------------------------------------------------
# Thread-local request context
# ---------------------------------------------------------------------------

class _Ctx(threading.local):
    trace: Optional[Trace] = None
    span_id: Optional[str] = None
    task: Optional[Any] = None


_CTX = _Ctx()


def current_trace() -> Optional[Trace]:
    return _CTX.trace


def current_span_id() -> Optional[str]:
    return _CTX.span_id


def current_task() -> Optional[Any]:
    """The live task registered for this thread's in-flight request —
    doubles as the cancellation token the batcher queue observes (any
    object with a truthy `.cancelled` sheds at EDF admission)."""
    return _CTX.task


def capture() -> tuple:
    """Snapshot this thread's context for a cross-thread handoff (the
    queue entry / scheduler hop): (trace, parent_span_id, task)."""
    return (_CTX.trace, _CTX.span_id, _CTX.task)


@contextmanager
def use(trace: Optional[Trace] = None, span_id: Optional[str] = None,
        task: Optional[Any] = None):
    """Install a request context on this thread for the duration of the
    block (REST handler body, remote sub-request execution)."""
    prev = (_CTX.trace, _CTX.span_id, _CTX.task)
    _CTX.trace = trace
    _CTX.span_id = span_id if span_id is not None else (
        trace.root.span_id if trace is not None else None)
    _CTX.task = task if task is not None else prev[2]
    try:
        yield
    finally:
        _CTX.trace, _CTX.span_id, _CTX.task = prev


@contextmanager
def span(name: str, **attrs):
    """Live child span under the current context; no-op (yields None)
    when this request isn't traced. The with-shape is the API on purpose
    — it cannot leak (tpulint TPU012)."""
    tr = _CTX.trace
    if tr is None:
        yield None
        return
    sp = tr.begin_span(name, parent_id=_CTX.span_id, **attrs)
    prev = _CTX.span_id
    _CTX.span_id = sp.span_id
    try:
        yield sp
    except BaseException:
        tr.end_span(sp, status="error")
        raise
    finally:
        tr.end_span(sp)
        _CTX.span_id = prev


def record_span(name: str, dur_ns: int, status: str = "ok",
                **attrs) -> Optional[str]:
    """Retroactive span on the current trace (None when untraced)."""
    tr = _CTX.trace
    if tr is None:
        return None
    return tr.record_span(name, dur_ns, parent_id=_CTX.span_id,
                          status=status, **attrs)
