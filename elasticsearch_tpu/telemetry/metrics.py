"""Process-wide metrics registry: counters, gauges, log2-bucket histograms.

Before this module the repo's latency numbers lived in two places that
could not answer "what is p99 RIGHT NOW": cumulative nanos totals in
per-subsystem stats dicts (`_nodes/stats` could report a mean but never a
tail) and closed-loop percentiles computed inside `bench_matrix.py` (a
harness, not a serving surface). This registry is the one in-tree home
for live distributions: subsystems record durations as they already
measure them (no new clock reads, no device syncs), and
`_nodes/stats telemetry` renders p50/p90/p99/p999 from the histograms on
demand.

Histograms use FIXED log2 buckets over nanoseconds (bucket i covers
(2^(i-1), 2^i]); 64 buckets span sub-nanosecond to ~584 years, so there
is no configuration, no rescaling, and recording is one bit_length + one
add under a per-histogram lock (~100 ns). Percentiles interpolate
linearly inside the winning bucket, which bounds the error to one bucket
width — the bench cross-check (`gate` in bench_matrix) asserts the
histogram-derived p99 agrees with a closed-loop measured p99 within one
bucket.

Process-wide like the kernel dispatcher (`ops/dispatch.DISPATCH`): one
registry serves every node in the process, and the stats section is
node-level by construction.
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional, Sequence

N_BUCKETS = 64


def bucket_index(value_ns: int) -> int:
    """Bucket for a nanosecond duration: bucket i (i >= 1) covers
    (2^(i-1), 2^i] — exact powers of two land in their own bucket's
    upper edge, not one higher; bucket 0 holds <= 1 ns (zero/negative
    clock noise must not throw)."""
    v = int(value_ns)
    if v <= 1:
        return 0
    return min((v - 1).bit_length(), N_BUCKETS - 1)


def bucket_upper_ns(i: int) -> int:
    """Inclusive upper bound of bucket i."""
    return 1 if i <= 0 else 1 << i


def percentile_from_counts(counts: Sequence[int], q: float) -> float:
    """Percentile (ns) from a bucket-count vector: find the bucket where
    the cumulative count crosses q, interpolate linearly inside it. The
    answer is within one log2 bucket of the true value by construction."""
    total = sum(counts)
    if total <= 0:
        return 0.0
    target = q * total
    cum = 0.0
    for i, c in enumerate(counts):
        if c <= 0:
            continue
        if cum + c >= target:
            lo = float(0 if i == 0 else 1 << max(i - 1, 0))
            hi = float(bucket_upper_ns(i))
            frac = (target - cum) / c
            return lo + frac * (hi - lo)
        cum += c
    return float(bucket_upper_ns(N_BUCKETS - 1))


class Counter:
    __slots__ = ("name", "value", "_lock")

    def __init__(self, name: str):
        self.name = name
        self.value = 0
        self._lock = threading.Lock()

    def inc(self, n: int = 1) -> None:
        with self._lock:
            self.value += n


class Gauge:
    __slots__ = ("name", "value", "_lock")

    def __init__(self, name: str):
        self.name = name
        self.value = 0.0
        self._lock = threading.Lock()

    def set(self, v: float) -> None:
        with self._lock:
            self.value = v

    def inc(self, n: float = 1.0) -> None:
        with self._lock:
            self.value += n

    def dec(self, n: float = 1.0) -> None:
        with self._lock:
            self.value -= n


class Histogram:
    """Fixed log2-bucket latency histogram over nanoseconds."""

    __slots__ = ("name", "counts", "count", "sum_ns", "max_ns", "_lock")

    def __init__(self, name: str):
        self.name = name
        self.counts: List[int] = [0] * N_BUCKETS
        self.count = 0
        self.sum_ns = 0
        self.max_ns = 0
        self._lock = threading.Lock()

    def record(self, value_ns: int) -> None:
        v = int(value_ns)
        i = bucket_index(v)
        with self._lock:
            self.counts[i] += 1
            self.count += 1
            self.sum_ns += max(v, 0)
            if v > self.max_ns:
                self.max_ns = v

    def percentile(self, q: float) -> float:
        with self._lock:
            counts = list(self.counts)
        return percentile_from_counts(counts, q)

    def snapshot(self, raw: bool = False) -> dict:
        with self._lock:
            counts = list(self.counts)
            count, sum_ns, max_ns = self.count, self.sum_ns, self.max_ns
        out = {
            "count": count,
            "sum_nanos": sum_ns,
            "mean_nanos": (sum_ns / count) if count else 0.0,
            "max_nanos": max_ns,
            "p50_nanos": percentile_from_counts(counts, 0.50),
            "p90_nanos": percentile_from_counts(counts, 0.90),
            "p99_nanos": percentile_from_counts(counts, 0.99),
            "p999_nanos": percentile_from_counts(counts, 0.999),
        }
        if raw:
            out["counts"] = counts
        return out


class MetricsRegistry:
    """Named metric registry: get-or-create, thread-safe, snapshot-able.

    Metric creation takes the registry lock; recording takes only the
    metric's own lock, so the steady-state cost is one uncontended lock
    acquire per record."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._counters: Dict[str, Counter] = {}
        self._gauges: Dict[str, Gauge] = {}
        self._histograms: Dict[str, Histogram] = {}

    def counter(self, name: str) -> Counter:
        c = self._counters.get(name)
        if c is None:
            with self._lock:
                c = self._counters.setdefault(name, Counter(name))
        return c

    def gauge(self, name: str) -> Gauge:
        g = self._gauges.get(name)
        if g is None:
            with self._lock:
                g = self._gauges.setdefault(name, Gauge(name))
        return g

    def histogram(self, name: str) -> Histogram:
        h = self._histograms.get(name)
        if h is None:
            with self._lock:
                h = self._histograms.setdefault(name, Histogram(name))
        return h

    def snapshot(self, raw: bool = False) -> dict:
        with self._lock:
            counters = dict(self._counters)
            gauges = dict(self._gauges)
            hists = dict(self._histograms)
        return {
            "counters": {n: c.value for n, c in sorted(counters.items())},
            "gauges": {n: g.value for n, g in sorted(gauges.items())},
            "histograms": {n: h.snapshot(raw=raw)
                           for n, h in sorted(hists.items())},
        }

    def reset(self) -> None:
        """Drop every metric (tests/bench only)."""
        with self._lock:
            self._counters.clear()
            self._gauges.clear()
            self._histograms.clear()


REGISTRY = MetricsRegistry()


def counter(name: str) -> Counter:
    return REGISTRY.counter(name)


def gauge(name: str) -> Gauge:
    return REGISTRY.gauge(name)


def histogram(name: str) -> Histogram:
    return REGISTRY.histogram(name)


def record(name: str, value_ns: int) -> None:
    """One-call histogram record — the subsystem-facing entry."""
    REGISTRY.histogram(name).record(value_ns)


def snapshot(raw: bool = False) -> dict:
    return REGISTRY.snapshot(raw=raw)
