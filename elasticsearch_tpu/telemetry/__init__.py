"""End-to-end request telemetry: traces, metrics, live tasks.

Three coupled pieces (ISSUE 14), one always-on low-overhead layer:

* `telemetry.trace` — distributed tracing. Every search/write request
  gets a trace (sampled by `telemetry.tracing.sample_rate`, forced by
  `?trace=true` or a `profile` body) whose spans cover REST parse,
  coordinator fan-out, each scatter-gather leg (context rides the PR-12
  deadline envelope), remote queue wait, device dispatch, the deferred
  device sync at finalize, hydrate and merge. Completed traces land in a
  bounded per-node ring (`GET _nodes/traces`) and attach (trace id +
  top-3 spans) to slow-log breaches.
* `telemetry.metrics` — process-wide counters/gauges/log2-bucket latency
  histograms; `_nodes/stats telemetry` reports live p50/p90/p99/p999 for
  end-to-end search latency, queue wait, device dispatch/sync and
  fan-out leg latency without a bench harness.
* the tasks binding below — `rest_request` registers every instrumented
  REST request with the node's TaskManager (action, opaque id, trace id,
  current span); `GET _tasks` lists them live, and `POST
  _tasks/_cancel` flips the task's `cancelled` flag, which the
  continuous batcher's EDF queue observes at admission (cancelled
  entries shed exactly like expired deadlines).

`X-Opaque-ID` threads through all three: the REST layer captures the
header once and it travels on the task, the trace, and any slow-log
entry the request breaches.

Settings (node-level; process-wide like the dispatcher — only an
explicit setting reconfigures, so a second in-process node without one
never clobbers an earlier node's choice):

    telemetry.tracing.sample_rate   head-sampling rate (default 0.01)
    telemetry.traces.ring_size      completed-trace ring bound (256)
"""

from __future__ import annotations

from contextlib import contextmanager
from typing import Optional

from elasticsearch_tpu.telemetry import metrics
from elasticsearch_tpu.telemetry import trace as trace_mod
from elasticsearch_tpu.telemetry.metrics import REGISTRY
from elasticsearch_tpu.telemetry.trace import (
    TRACER,
    Trace,
    capture,
    current_span_id,
    current_task,
    current_trace,
    record_span,
    span,
    use,
)

__all__ = [
    "metrics", "trace_mod", "REGISTRY", "TRACER", "Trace",
    "capture", "current_span_id", "current_task", "current_trace",
    "record_span", "span", "use", "rest_request",
    "configure_from_settings", "thread_section",
]


def configure_from_settings(settings: Optional[dict]) -> None:
    """Wire `telemetry.*` node settings into the process-wide tracer.
    Explicit settings only — absent keys leave the current (possibly
    earlier-node-configured) policy untouched."""
    s = settings or {}
    rate = s.get("telemetry.tracing.sample_rate")
    ring = s.get("telemetry.traces.ring_size")
    if rate is not None:
        TRACER.configure(sample_rate=float(rate))
    if ring is not None:
        TRACER.configure(ring_size=int(ring))


@contextmanager
def rest_request(node, action: str, *, opaque_id: Optional[str] = None,
                 force_trace: bool = False, description: str = "",
                 parse_nanos: int = 0):
    """Instrument one REST request end to end: register a live task
    (visible in `GET _tasks`, cancellable into the batcher queue), open
    a trace when sampled/forced, and install both on the thread so every
    layer below (batcher entries, fan-out envelopes, slow logs) can see
    them. Yields the Trace (or None when unsampled)."""
    tracer = TRACER
    tr = tracer.start(action, node_id=getattr(node, "node_id", "?"),
                      forced=force_trace, opaque_id=opaque_id)
    if tr is not None and parse_nanos:
        tr.record_span("rest.parse", parse_nanos,
                       parent_id=tr.root.span_id)
    tasks = getattr(node, "tasks", None)
    task = None
    if tasks is not None:
        task = tasks.register(action, description=description,
                              opaque_id=opaque_id, trace=tr)
    try:
        with use(trace=tr, task=task):
            yield tr
    except BaseException:
        if tr is not None:
            tracer.finish(tr, status="error")
            tr = None
        raise
    finally:
        if task is not None:
            tasks.unregister(task)
        if tr is not None:
            tracer.finish(tr)


@contextmanager
def thread_section(section: str):
    """Temporarily tag the current thread's name with the serving section
    it is executing (`»batcher-drain`, `»batcher-finalize`, ...), so a
    hot-threads report attributes a busy stack to its subsystem even
    when the work runs on a borrowed submitter thread (the combining
    batcher has no threads of its own — the first submitter in becomes
    the runner). One string assignment each way; nanoseconds."""
    import threading
    t = threading.current_thread()
    prev = t.name
    t.name = f"{prev}»{section}"
    try:
        yield
    finally:
        t.name = prev
