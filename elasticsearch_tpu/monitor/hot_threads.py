"""Hot threads: stack dumps of the busiest threads.

Reference: `monitor/jvm/HotThreads.java:41` — samples thread CPU over an
interval and prints the top-N stacks. Python analog: sample
`sys._current_frames` twice and report threads whose top frame advanced
(busy) with their current stacks.
"""

from __future__ import annotations

import sys
import threading
import time
import traceback
from typing import Dict


def hot_threads_report(interval_s: float = 0.05, top_n: int = 3,
                       node_name: str = "node") -> str:
    first: Dict[int, str] = {
        tid: _top_frame_key(frame)
        for tid, frame in sys._current_frames().items()
    }
    time.sleep(max(0.0, interval_s))
    frames = sys._current_frames()
    names = {t.ident: t.name for t in threading.enumerate()}
    lines = [f"::: {{{node_name}}}",
             f"   Hot threads at {time.strftime('%Y-%m-%dT%H:%M:%S')}, "
             f"interval={interval_s}s, busiestThreads={top_n}:"]
    busy_first = sorted(
        frames.items(),
        key=lambda kv: (first.get(kv[0]) == _top_frame_key(kv[1])),  # moved first
    )
    for tid, frame in busy_first[:top_n]:
        name = names.get(tid, str(tid))
        state = "runnable" if first.get(tid) != _top_frame_key(frame) else "waiting"
        lines.append(f"   0.0% cpu usage by thread '{name}' ({state})")
        for entry in traceback.format_stack(frame)[-10:]:
            for ln in entry.rstrip().splitlines():
                lines.append("     " + ln.strip())
    return "\n".join(lines) + "\n"


def _top_frame_key(frame) -> str:
    return f"{frame.f_code.co_filename}:{frame.f_lineno}"
