"""Hot threads: stack dumps of the busiest threads, by subsystem.

Reference: `monitor/jvm/HotThreads.java:41` — samples thread CPU over an
interval and prints the top-N stacks. Python analog: sample
`sys._current_frames` twice and report threads whose top frame advanced
(busy) with their current stacks.

Serving threads carry subsystem-identifying names so a busy stack is
attributable at a glance: the node thread pools prefix `es[<pool>]`
(common/threadpool.py), background workers name themselves at spawn
(`segments-merge`, `dispatch-warmup`, `batcher-warmup`,
`agg-column-resync`), and the combining batcher — which runs on BORROWED
submitter threads — tags the current thread for the duration of a drain
or finalize section (`telemetry.thread_section`: `»batcher-drain`,
`»batcher-finalize`). The report maps each thread to its subsystem from
that name.
"""

from __future__ import annotations

import sys
import threading
import time
import traceback
from typing import Dict

# thread-name fragment -> subsystem label, most specific first
_SUBSYSTEMS = (
    ("»batcher-drain", "serving/batcher dispatch"),
    ("»batcher-finalize", "serving/batcher finalize"),
    ("batcher-warmup", "serving/batcher warmup"),
    ("segments-merge", "segments background merge"),
    ("dispatch-warmup", "ops/dispatch warmup"),
    ("agg-column-resync", "aggs column resync"),
    ("es[search_throttled]", "search_throttled pool"),
    ("es[search]", "search pool"),
    ("es[write]", "write pool"),
    ("es[get]", "get pool"),
    ("es[generic]", "generic pool"),
    ("es[snapshot]", "snapshot pool"),
    ("es[force_merge]", "force_merge pool"),
)


def subsystem_of(thread_name: str) -> str:
    for fragment, label in _SUBSYSTEMS:
        if fragment in thread_name:
            return label
    if thread_name.startswith("es["):
        return thread_name.split("]")[0] + "] pool"
    return "other"


def hot_threads_report(interval_s: float = 0.05, top_n: int = 3,
                       node_name: str = "node") -> str:
    first: Dict[int, str] = {
        tid: _top_frame_key(frame)
        for tid, frame in sys._current_frames().items()
    }
    time.sleep(max(0.0, interval_s))
    frames = sys._current_frames()
    names = {t.ident: t.name for t in threading.enumerate()}
    lines = [f"::: {{{node_name}}}",
             f"   Hot threads at {time.strftime('%Y-%m-%dT%H:%M:%S')}, "
             f"interval={interval_s}s, busiestThreads={top_n}:"]
    busy_first = sorted(
        frames.items(),
        key=lambda kv: (first.get(kv[0]) == _top_frame_key(kv[1])),  # moved first
    )
    for tid, frame in busy_first[:top_n]:
        name = names.get(tid, str(tid))
        state = "runnable" if first.get(tid) != _top_frame_key(frame) else "waiting"
        lines.append(f"   0.0% cpu usage by thread '{name}' ({state}) "
                     f"[{subsystem_of(name)}]")
        for entry in traceback.format_stack(frame)[-10:]:
            for ln in entry.rstrip().splitlines():
                lines.append("     " + ln.strip())
    return "\n".join(lines) + "\n"


def _top_frame_key(frame) -> str:
    return f"{frame.f_code.co_filename}:{frame.f_lineno}"
