"""Monitoring: hot threads, process/OS probes, slow logs, deprecations.

Reference: `monitor/` (JvmGcMonitorService, HotThreads, probes), per-index
slow logs (`index/SearchSlowLog.java`), `DeprecationLogger`.
"""

from elasticsearch_tpu.monitor.hot_threads import hot_threads_report
from elasticsearch_tpu.monitor.slow_log import SlowLog

__all__ = ["hot_threads_report", "SlowLog"]
