"""OS / process / filesystem / runtime probes for the stats APIs.

Re-design of `monitor/os/OsProbe.java`, `monitor/process/ProcessProbe.java`,
`monitor/fs/FsProbe.java`, and the JVM probes (SURVEY.md §2.1/§5.5): the
reference reads MXBeans and /proc; here the probes read /proc and the
stdlib directly (no psutil dependency). Each probe returns the exact stats
sections `_nodes/stats` publishes.
"""

from __future__ import annotations

import gc
import os
import resource
import shutil
import threading
import time

_START_TIME = time.time()


def _meminfo() -> dict:
    out = {}
    try:
        with open("/proc/meminfo") as f:
            for line in f:
                parts = line.split()
                if len(parts) >= 2:
                    out[parts[0].rstrip(":")] = int(parts[1]) * 1024
    except OSError:
        pass
    return out


def os_probe() -> dict:
    """OsProbe.osStats(): load averages + memory + swap."""
    try:
        load1, load5, load15 = os.getloadavg()
    except OSError:
        load1 = load5 = load15 = -1.0
    mem = _meminfo()
    total = mem.get("MemTotal", 0)
    available = mem.get("MemAvailable", mem.get("MemFree", 0))
    used = max(total - available, 0)
    return {
        "timestamp": int(time.time() * 1000),
        "cpu": {"load_average": {"1m": round(load1, 2), "5m": round(load5, 2),
                                 "15m": round(load15, 2)},
                "percent": _cpu_percent()},
        "mem": {"total_in_bytes": total, "free_in_bytes": available,
                "used_in_bytes": used,
                "used_percent": round(100.0 * used / total, 1) if total else 0,
                "free_percent": round(100.0 * available / total, 1) if total else 0},
        "swap": {"total_in_bytes": mem.get("SwapTotal", 0),
                 "free_in_bytes": mem.get("SwapFree", 0),
                 "used_in_bytes": max(mem.get("SwapTotal", 0)
                                      - mem.get("SwapFree", 0), 0)},
        "allocated_processors": os.cpu_count() or 1,
    }


_last_cpu: dict = {}
_last_cpu_lock = threading.Lock()


def _cpu_percent() -> int:
    """Whole-system CPU busy %% since the previous probe (OsProbe reads
    /proc/stat the same way; first call returns -1: no interval yet)."""
    try:
        with open("/proc/stat") as f:
            fields = [int(x) for x in f.readline().split()[1:]]
    except (OSError, ValueError):
        return -1
    idle = fields[3] + (fields[4] if len(fields) > 4 else 0)
    total = sum(fields)
    # read-modify-write under the lock: concurrent _nodes/stats requests
    # interleaving here would compute percentages over torn intervals
    # (tpulint TPU008)
    with _last_cpu_lock:
        prev = _last_cpu.get("v")
        _last_cpu["v"] = (idle, total)
    if prev is None or total == prev[1]:
        return -1
    didle, dtotal = idle - prev[0], total - prev[1]
    return int(round(100.0 * (dtotal - didle) / dtotal))


def process_probe() -> dict:
    """ProcessProbe.processStats(): fds, cpu, virtual/resident memory."""
    usage = resource.getrusage(resource.RUSAGE_SELF)
    try:
        open_fds = len(os.listdir("/proc/self/fd"))
    except OSError:
        open_fds = -1
    try:
        soft, _hard = resource.getrlimit(resource.RLIMIT_NOFILE)
    except (ValueError, OSError):
        soft = -1
    vm_bytes = 0
    try:
        with open("/proc/self/statm") as f:
            vm_bytes = int(f.read().split()[0]) * resource.getpagesize()
    except (OSError, ValueError, IndexError):
        pass
    return {
        "timestamp": int(time.time() * 1000),
        "open_file_descriptors": open_fds,
        "max_file_descriptors": soft,
        "cpu": {"total_in_millis": int((usage.ru_utime + usage.ru_stime) * 1000),
                "percent": -1},
        "mem": {"resident_in_bytes": usage.ru_maxrss * 1024,
                "total_virtual_in_bytes": vm_bytes},
    }


def _fs_type(path: str) -> str:
    """Filesystem type of the mount holding `path` (FsInfo.Path#type),
    best-effort from /proc/mounts; "local" when undeterminable."""
    try:
        import os
        best, fstype = "", "local"
        real = os.path.realpath(path or ".")
        with open("/proc/mounts") as f:
            for line in f:
                parts = line.split()
                mp = parts[1].rstrip("/") if len(parts) >= 3 else ""
                if len(parts) >= 3 \
                        and (real == parts[1] or real == mp
                             or real.startswith(mp + "/")) \
                        and len(parts[1]) > len(best):
                    best, fstype = parts[1], parts[2]
        return fstype
    except OSError:
        return "local"


def fs_probe(data_path: str) -> dict:
    """FsProbe.stats(): per-data-path totals."""
    try:
        du = shutil.disk_usage(data_path or ".")
        total, free, available = du.total, du.free, du.free
    except OSError:
        total = free = available = 0
    return {
        "timestamp": int(time.time() * 1000),
        "total": {"total_in_bytes": total, "free_in_bytes": free,
                  "available_in_bytes": available},
        "data": [{"path": data_path, "type": _fs_type(data_path),
                  "total_in_bytes": total,
                  "free_in_bytes": free, "available_in_bytes": available}],
    }


def runtime_probe() -> dict:
    """The JVM-probe analog for the Python runtime: heap-ish RSS, GC
    collection counts per generation, thread count, uptime."""
    usage = resource.getrusage(resource.RUSAGE_SELF)
    gc_stats = gc.get_stats()
    collectors = {}
    for gen, s in enumerate(gc_stats):
        collectors[f"gen{gen}"] = {
            "collection_count": s.get("collections", 0),
            "collected": s.get("collected", 0)}
    return {
        "timestamp": int(time.time() * 1000),
        "uptime_in_millis": int((time.time() - _START_TIME) * 1000),
        "mem": {"heap_used_in_bytes": usage.ru_maxrss * 1024,
                "heap_max_in_bytes": _meminfo().get("MemTotal", 0)},
        "gc": {"collectors": collectors},
        "threads": {"count": threading.active_count(),
                    "peak_count": threading.active_count()},
        # JVM buffer-pool analog: numpy/mmap buffers play "direct",
        # mapped segment files play "mapped" (JvmStats.BufferPool)
        "buffer_pools": {
            "direct": {"count": 0, "used_in_bytes": 0,
                       "total_capacity_in_bytes": 0},
            "mapped": {"count": 0, "used_in_bytes": 0,
                       "total_capacity_in_bytes": 0}},
    }
