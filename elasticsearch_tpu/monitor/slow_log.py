"""Per-index search/indexing slow logs.

Reference: `index/SearchSlowLog.java` / `IndexingSlowLog.java` — threshold
settings per level (warn/info/debug/trace); breaches emit a structured log
line. Here breaches append to an in-memory ring consumable from stats/tests
(`_nodes/stats indices.slowlog`, `GET /_slowlog`).

Telemetry coupling (ISSUE 14): a breach is exactly the moment an operator
asks "where did THIS slow request spend its time", so entries carry the
caller's `X-Opaque-ID`, the request's trace id plus its top-3 spans (when
the request was sampled/forced), and the phase breakdown the serving path
already measured — the answer travels WITH the breach instead of requiring
a second lookup. Every serving path feeds the same ring: the host query
path, the fused hybrid/kNN device path, and the cross-node fan-out
coordinator.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

from elasticsearch_tpu.common.settings import parse_time_value

LEVELS = ("warn", "info", "debug", "trace")


class SlowLog:
    def __init__(self, kind: str = "search"):
        self.kind = kind
        self.entries: List[dict] = []
        self.total = 0   # breaches ever (the ring truncates entries)

    def thresholds(self, settings) -> Dict[str, float]:
        out = {}
        for level in LEVELS:
            key = (f"index.{self.kind}.slowlog.threshold."
                   f"{'query' if self.kind == 'search' else 'index'}.{level}")
            v = settings.get(key)
            if v is not None:
                out[level] = parse_time_value(v, key)
        return out

    def maybe_log(self, settings, index: str, took_s: float,
                  source: Optional[Any] = None, *,
                  opaque_id: Optional[str] = None,
                  trace: Optional[Any] = None,
                  phases: Optional[dict] = None) -> Optional[str]:
        level_hit = None
        ths = self.thresholds(settings)
        for level in LEVELS:   # warn is the highest threshold; first hit wins
            th = ths.get(level)
            if th is not None and th >= 0 and took_s >= th:
                level_hit = level
                break
        if level_hit is None:
            return None
        entry = {"index": index, "level": level_hit,
                 "took_ms": took_s * 1000.0,
                 "source": source}
        if opaque_id is not None:
            entry["opaque_id"] = opaque_id
        if phases:
            entry["phases"] = dict(phases)
        if trace is not None:
            # attach the trace id + the three longest spans so the log
            # line alone answers where the time went; the full trace
            # stays in the `_nodes/traces` ring under this id
            entry["trace_id"] = trace.trace_id
            entry["top_spans"] = trace.top_spans(3)
        self.entries.append(entry)
        self.total += 1
        if len(self.entries) > 1000:
            del self.entries[:500]
        return level_hit

    def stats(self, recent: int = 5) -> dict:
        """The `_nodes/stats indices.slowlog` section: breach count +
        the most recent entries (full ring via `GET /_slowlog`)."""
        recent = max(int(recent), 0)
        return {"count": self.total,
                "recent": list(self.entries[-recent:]) if recent else []}
