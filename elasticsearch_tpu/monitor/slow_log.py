"""Per-index search/indexing slow logs.

Reference: `index/SearchSlowLog.java` / `IndexingSlowLog.java` — threshold
settings per level (warn/info/debug/trace); breaches emit a structured log
line. Here breaches append to an in-memory ring consumable from stats/tests.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

from elasticsearch_tpu.common.settings import parse_time_value

LEVELS = ("warn", "info", "debug", "trace")


class SlowLog:
    def __init__(self, kind: str = "search"):
        self.kind = kind
        self.entries: List[dict] = []

    def thresholds(self, settings) -> Dict[str, float]:
        out = {}
        for level in LEVELS:
            key = (f"index.{self.kind}.slowlog.threshold."
                   f"{'query' if self.kind == 'search' else 'index'}.{level}")
            v = settings.get(key)
            if v is not None:
                out[level] = parse_time_value(v, key)
        return out

    def maybe_log(self, settings, index: str, took_s: float,
                  source: Optional[Any] = None) -> Optional[str]:
        level_hit = None
        ths = self.thresholds(settings)
        for level in LEVELS:   # warn is the highest threshold; first hit wins
            th = ths.get(level)
            if th is not None and th >= 0 and took_s >= th:
                level_hit = level
                break
        if level_hit is None:
            return None
        self.entries.append({"index": index, "level": level_hit,
                             "took_ms": took_s * 1000.0,
                             "source": source})
        if len(self.entries) > 1000:
            del self.entries[:500]
        return level_hit
