"""Benchmark matrix: the five BASELINE.md configs on one chip.

`bench.py` remains the driver contract (ONE JSON line, config 1). This
script reports every config as its own JSON line so the full matrix is
recorded (BENCH_MATRIX_r{N}.json):

  1 cosine kNN, SIFT-like 1M x 128        (binned Pallas kernel, bf16)
  2 l2_norm kNN, GIST-like 256k x 960     (exact XLA path — no HNSW in
                                           the reference either; recall 1.0)
  3 hybrid BM25 + kNN with RRF fusion     (end-to-end through Node.search)
  4 int8 10M x 768 NORTH STAR             (in-kernel s8xs8 MXU matmul,
                                           ~7.9 GB corpus resident in HBM,
                                           ground truth = exact f32 over
                                           the full pre-quantization data)
  5 filtered kNN, 1M x 128, 10% filter    (host bitmap -> masked top-k)
  7 IVF partition-pruned kNN, 1M x 128    (ann/: k-means routed, nprobe
                                           auto-tuned to recall@10 >= 0.95,
                                           ~nprobe/nlist of corpus scored)

Latency caveat: this environment adds a ~70 ms tunnel round-trip to EVERY
dispatch (a TPU-attached host pays ~100 µs). Each config therefore reports
  qps              amortized throughput (batches scanned in one dispatch)
  batch_ms         marginal per-batch device time (tunnel excluded, from
                   the slope between two scan lengths)
  p50_ms / p99_ms  single-dispatch wall times as observed THROUGH the
                   tunnel (upper bounds; dominated by the fixed overhead)
"""

from __future__ import annotations

import functools
import json
import time

import numpy as np

K = 10
BATCH = 256


def _scan_searcher(fn):
    import jax

    @functools.partial(jax.jit, static_argnames=("kk",))
    def search_all(qs, c, kk):
        def body(carry, qb):
            return carry, fn(qb, c, kk)
        _, out = jax.lax.scan(body, None, qs)
        return out

    return search_all


def _measure(search_all, corpus, queries_np, d, n_small=8, n_large=64):
    """(qps_amortized, marginal_batch_s, p50_ms, p99_ms, first_ids)."""
    import jax.numpy as jnp

    def run(nb):
        qs = jnp.asarray(queries_np[: nb * BATCH].reshape(nb, BATCH, d))
        out = search_all(qs, corpus, K)
        ids = np.asarray(out[1])
        ts = []
        for _ in range(3):
            t0 = time.perf_counter()
            out = search_all(qs, corpus, K)
            ids = np.asarray(out[1])
            ts.append(time.perf_counter() - t0)
        return min(ts), ids

    t_small, ids = run(n_small)
    t_large, _ = run(n_large)
    marginal = (t_large - t_small) / (n_large - n_small)
    qps = n_large * BATCH / t_large
    # single-dispatch latency distribution (tunnel-dominated upper bound)
    q1 = jnp.asarray(queries_np[:BATCH].reshape(1, BATCH, d))
    lats = []
    for _ in range(15):
        t0 = time.perf_counter()
        out = search_all(q1, corpus, K)
        np.asarray(out[1])
        lats.append((time.perf_counter() - t0) * 1000)
    return qps, marginal, float(np.percentile(lats, 50)), \
        float(np.percentile(lats, 99)), ids


def _small_batch_rows(name, fn, corpus, queries_np, d, n_iter=64):
    """True device p50 at interactive batch sizes (1/4/16): n_iter
    dispatches scanned inside ONE compiled program amortize the tunnel
    round-trip out of the measurement (BASELINE.md asks for p50; the
    256-batch rows only bound the amortized slope)."""
    import jax.numpy as jnp
    for b in (1, 4, 16):
        qs = jnp.asarray(queries_np[: n_iter * b].reshape(n_iter, b, d))
        f = _scan_searcher(fn)
        np.asarray(f(qs, corpus, K)[1])
        ts = []
        for _ in range(5):
            t0 = time.perf_counter()
            np.asarray(f(qs, corpus, K)[1])
            ts.append(time.perf_counter() - t0)
        med = sorted(ts)[2]
        print(json.dumps({
            "config": f"{name}_small_batch", "batch": b,
            "device_p50_ms": round(med / n_iter * 1000, 3),
            "qps_at_batch": round(b * n_iter / med, 1)}), flush=True)


def _recall(ids, ids_ref, k=K):
    n = ids_ref.shape[0]
    hits = sum(len(set(ids[r][:k]) & set(ids_ref[r][:k])) for r in range(n))
    return hits / (n * k)


def _dispatch_mark():
    """Snapshot of the shape-bucketed dispatch counters; pair with
    `_dispatch_delta` so each row records ITS OWN executable-cache
    traffic (hits/misses/compiles/compile time). Raw-kernel rows driven
    inside the scan harness inline into one outer jit and legitimately
    show zeros — the serving rows (hybrid/closed-loop/small-batch) are
    where steady state must read misses=0."""
    from elasticsearch_tpu.ops import dispatch
    return dispatch.stats(per_bucket=False)


def _dispatch_delta(mark):
    from elasticsearch_tpu.ops import dispatch
    now = dispatch.stats(per_bucket=False)
    return {"hits": now["hits"] - mark["hits"],
            "misses": now["misses"] - mark["misses"],
            "compiles": now["compiles"] - mark["compiles"],
            "compile_ms": round(
                (now["compile_nanos"] - mark["compile_nanos"]) / 1e6, 1),
            "out_of_grid": now["out_of_grid_compiles"]
            - mark["out_of_grid_compiles"]}


def _telemetry_mark():
    """Raw snapshot of the process-wide telemetry histograms (bucket
    counts included); pair with `_telemetry_delta` so each row records
    the live-percentile surface for ITS OWN requests — the in-tree
    `_nodes/stats telemetry` numbers, cross-checkable against the row's
    closed-loop measured percentiles."""
    from elasticsearch_tpu.telemetry import metrics
    return metrics.snapshot(raw=True)


def _telemetry_delta(mark, names=("search.took", "serving.queue_wait",
                                  "serving.device_dispatch",
                                  "serving.device_sync")):
    """Per-histogram delta percentiles between two marks (ms)."""
    from elasticsearch_tpu.telemetry import metrics
    now = metrics.snapshot(raw=True)
    out = {}
    for name in names:
        after = now["histograms"].get(name)
        if after is None:
            continue
        before = (mark["histograms"].get(name) or {})
        b_counts = before.get("counts") or [0] * metrics.N_BUCKETS
        counts = [a - b for a, b in zip(after["counts"], b_counts)]
        count = sum(counts)
        if count <= 0:
            continue
        out[name] = {
            "count": count,
            "p50_ms": round(
                metrics.percentile_from_counts(counts, 0.50) / 1e6, 2),
            "p99_ms": round(
                metrics.percentile_from_counts(counts, 0.99) / 1e6, 2)}
    return out


def _compile_noise_label(disp: dict) -> dict:
    """Label timed-loop compile noise in a closed-loop row (the PR 10
    leftover: on the CPU floor a handful of steady-state shapes can
    still compile inside the timed window — e.g. a generational seal's
    first bucket — and one XLA compile reads as a multi-hundred-ms p99
    outlier that has nothing to do with serving). Rows carry the label
    so tail comparisons (the dp sweep especially) aren't silently
    polluted: a row with compiles > 0 has a compile-inflated p99, not a
    scheduling regression."""
    if disp.get("compiles", 0) <= 0:
        return {}
    return {"p99_compile_noise": {
        "timed_loop_compiles": disp["compiles"],
        "compile_ms": disp["compile_ms"],
        "note": "p99 includes CPU-floor XLA compile stalls inside the "
                "timed loop (PR 10 leftover) — compare tails against "
                "rows with timed_loop_compiles=0"}}


def hybrid_serving_stats(node) -> dict:
    """Serving-stats fields of the hybrid bench row, read from the SAME
    live node instance that served the timed loop (`node.
    _hybrid_stats_section()` sums the per-index executors the queries
    actually went through). The r06 record carried `plan_cache_hits: 0`
    here — root-caused to the rows having been captured by a pre-PR4
    bench/engine snapshot (the daemon ran the code on disk at capture
    time, before the plan-cache key fix landed), NOT to stats being read
    from a wrong process or engine instance; tests/test_bench_harness.py
    pins this wiring so a regression in either the key scrubbing or the
    stats plumbing re-fires visibly in the row."""
    hs = node._hybrid_stats_section()
    return {
        "plan_cache_hits": hs["plan_cache_hits"],
        "plan_cache_misses": hs["plan_cache_misses"],
        "hybrid_batches": hs["batches"],
        "rejected_429": hs["rejected_depth"] + hs["shed_deadline"],
        "sched": dict(hs["scheduler"]),
        # closed-loop tail attribution (cumulative ms over the run):
        # queueing vs device dispatch+sync vs host hydrate — a red
        # p99/p50 gate is diagnosable from the row alone
        "tail_ms": {
            "queue_wait": round(hs["queue_wait_nanos"] / 1e6, 1),
            "device": round(
                (hs["dispatch_nanos"] + hs["sync_nanos"]) / 1e6, 1),
            "hydrate": round(hs["hydrate_nanos"] / 1e6, 1)}}


def knn_scheduler_stats(node) -> dict:
    """Continuous-batching scheduler fields of the closed-loop (1cl/4cl)
    rows: the per-(field, k) kNN batchers' counters summed over shards
    (`_nodes/stats indices.knn.scheduler`)."""
    sched = node._knn_stats_section().get("scheduler", {})
    return {
        "sched": {key: sched.get(key, 0)
                  for key in ("batches", "pipelined_batches", "topups",
                              "deadline_sheds", "overlap_hits")},
        "tail_ms": {
            "queue_wait": round(sched.get("queue_wait_nanos", 0) / 1e6, 1),
            "dispatch": round(sched.get("dispatch_nanos", 0) / 1e6, 1),
            "finalize": round(sched.get("finalize_nanos", 0) / 1e6, 1)}}


def _emit(name, qps, marginal, p50, p99, recall, n, d, dtype, extra=None,
          dispatch=None):
    row = {
        "config": name, "qps": round(qps, 1),
        "batch_ms": round(marginal * 1000, 3),
        "p50_ms": round(p50, 1), "p99_ms": round(p99, 1),
        "recall_at_10": round(recall, 4), "n_docs": n, "dims": d,
        "dtype": dtype, "batch": BATCH, **(extra or {})}
    if dispatch is not None:
        row["dispatch"] = dispatch
    print(json.dumps(row), flush=True)


def run_config(name, n, d, metric, dtype, filter_frac=None):
    import os

    import jax
    import jax.numpy as jnp

    from elasticsearch_tpu.ops import knn as knn_ops

    if os.environ.get("BENCH_SMALL") == "1":
        n = min(n, 131_072)

    rng = np.random.default_rng(7)
    centers = rng.standard_normal((128, d)).astype(np.float32) * 2.0
    vectors = (centers[rng.integers(0, 128, size=n)]
               + rng.standard_normal((n, d)).astype(np.float32))
    nq = BATCH * 64
    queries = vectors[rng.integers(0, n, size=nq)] \
        + 0.3 * rng.standard_normal((nq, d)).astype(np.float32)
    corpus = knn_ops.build_corpus(vectors, metric=metric, dtype=dtype)
    _ = np.asarray(corpus.num_valid)
    mark = _dispatch_mark()

    mask = None
    if filter_frac is not None:
        keep = rng.random(corpus.matrix.shape[0]) < filter_frac
        keep[n:] = False
        mask = jnp.asarray(keep)

    if mask is not None:
        def fn(qb, c, kk, m=mask):
            return knn_ops.knn_search(qb, c, kk, metric=metric, filter_mask=m)
    else:
        def fn(qb, c, kk):
            return knn_ops.knn_search_auto(qb, c, kk, metric=metric)

    qps, marginal, p50, p99, ids = _measure(
        _scan_searcher(fn), corpus, queries, d)
    # delta closes BEFORE the recall oracle below: its outermost f32
    # knn_search dispatches (and compiles) through the cache too, and
    # that's measurement machinery, not the benchmarked kernel path
    row_dispatch = _dispatch_delta(mark)

    # recall vs exact f32 on the first batch
    f32_corpus = knn_ops.build_corpus(vectors, metric=metric, dtype="f32") \
        if dtype != "f32" else corpus
    _, ids_ref = knn_ops.knn_search(
        jnp.asarray(queries[:BATCH]), f32_corpus, k=K, metric=metric,
        precision="f32", filter_mask=mask)
    recall = _recall(ids[0], np.asarray(ids_ref))
    _emit(name, qps, marginal, p50, p99, recall, n, d, dtype,
          {"filter_frac": filter_frac} if filter_frac is not None else None,
          dispatch=row_dispatch)
    if name.startswith("1_"):
        _small_batch_rows(name, fn, corpus, queries, d)


def run_ivf_config(name: str = "7_ivf_sift1m", n: int = 1_000_000,
                   d: int = 128, nlist: int = 1024,
                   recall_target: float = 0.95):
    """IVF partition-pruned kNN (`elasticsearch_tpu/ann/`): k-means routed,
    nprobe auto-tuned to the recall gate, scoring ~nprobe/nlist of the
    corpus. The recall column is measured against exact f32 ground truth
    over the FULL corpus — the row only counts if it holds the >= 0.95
    gate while the scored fraction stays <= 25%."""
    import os

    import jax
    import jax.numpy as jnp

    from elasticsearch_tpu.ann import IVFRouter, build_ivf_index
    from elasticsearch_tpu.ops import knn as knn_ops
    from elasticsearch_tpu.ops import knn_ivf

    if os.environ.get("BENCH_SMALL") == "1":
        n, nlist = 131_072, 512

    rng = np.random.default_rng(7)
    centers = rng.standard_normal((128, d)).astype(np.float32) * 2.0
    vectors = (centers[rng.integers(0, 128, size=n)]
               + rng.standard_normal((n, d)).astype(np.float32))
    nq = BATCH * 64
    queries = vectors[rng.integers(0, n, size=nq)] \
        + 0.3 * rng.standard_normal((nq, d)).astype(np.float32)

    t0 = time.perf_counter()
    index = build_ivf_index(vectors, metric="cosine", nlist=nlist, seed=0)
    router = IVFRouter(index, nprobe="auto", recall_target=recall_target)
    nprobe = router.effective_nprobe(K)
    parts = index.device_partitions()
    jax.block_until_ready(parts.parts)
    build_s = time.perf_counter() - t0

    def fn(qb, c, kk, nprobe=nprobe):
        return knn_ivf.ivf_search(qb, c, kk, nprobe, metric="cosine")

    qps, marginal, p50, p99, ids = _measure(
        _scan_searcher(fn), parts, queries, d)

    # exact f32 ground truth over the full (flat) corpus, first batch
    f32_corpus = knn_ops.build_corpus(vectors, metric="cosine", dtype="f32")
    _, ids_ref = knn_ops.knn_search(
        jnp.asarray(queries[:BATCH]), f32_corpus, k=K, metric="cosine",
        precision="f32")
    recall = _recall(ids[0], np.asarray(ids_ref))
    _emit(name, qps, marginal, p50, p99, recall, n, d, "bf16",
          {"engine": "tpu_ivf", "nlist": index.nlist, "nprobe": nprobe,
           "scored_fraction": round(index.scored_fraction(nprobe), 4),
           "recall_gate": recall_target, "build_s": round(build_s, 1),
           "ground_truth": "exact_f32_full_corpus"})


def run_north_star_10m_int8(n: int = 10_000_000, emit: bool = True,
                            extra: bool = True, residual: bool = False):
    """Config 4 at true scale: 10M x 768 int8, one chip.

    Data is generated ON DEVICE in 1M-row chunks (the full f32 corpus is
    30 GB — it never exists anywhere). Each chunk, while still f32, feeds
    an exact-ground-truth running top-k for the query set; it is then
    row-normalized, int8-quantized, and written into the resident corpus.
    Returns the headline row dict (bench.py embeds it in the official
    record; `emit`/`extra` control the matrix's own JSON lines).

    residual: also build the second int8 level (row ~ q8*s + r8*rs) and
    measure the packed rescore against it — the recall-headroom recipe
    (ops/pallas_knn_binned._rescore_scores). Doubles corpus HBM, so run
    it at n <= 5M on a 16 GB chip."""
    import os

    import jax
    import jax.numpy as jnp

    if os.environ.get("BENCH_SMALL") == "1":
        n = min(n, 1_000_000)

    from elasticsearch_tpu.ops import knn as knn_ops
    from elasticsearch_tpu.ops.knn import Corpus
    from elasticsearch_tpu.ops import pallas_knn_binned as binned

    from elasticsearch_tpu.ops import dispatch
    backend = jax.devices()[0].platform
    if not dispatch.is_accelerator_backend():
        # the binned Pallas kernel only COMPILES on TPU-class backends
        # ("Only interpret mode is supported on CPU backend", the r06
        # capture failure); interpret mode at 10M x 768 is not a
        # measurement, so a CPU-floor capture records a LABELED skip.
        # Kernel correctness off-TPU is covered by the interpret-mode
        # runs in tests/test_ops_knn.py.
        row = {"config": "4_north_star_int8_10Mx768",
               "skipped": "binned Pallas kernel needs a TPU-class "
                          f"backend (have {backend}); interpret-mode "
                          "correctness covered by tests",
               "backend": backend}
        if emit:
            print(json.dumps(row), flush=True)
        return row

    d = 768
    chunk = min(1_000_000, n)
    n_pad = ((n + binned.BLOCK_N - 1) // binned.BLOCK_N) * binned.BLOCK_N
    nchunks = n // chunk
    key = jax.random.PRNGKey(42)
    kc, kq, *chunk_keys = jax.random.split(key, nchunks + 2)

    centers = jax.random.normal(kc, (16384, d), dtype=jnp.float32) * 2.0

    @jax.jit
    def gen_chunk(k):
        ka, kb = jax.random.split(k)
        idx = jax.random.randint(ka, (chunk,), 0, 16384)
        x = centers[idx] + 0.7 * jax.random.normal(kb, (chunk, d))
        x = x / jnp.linalg.norm(x, axis=-1, keepdims=True)  # cosine prep
        return x

    @jax.jit
    def gen_queries(k):
        # held-out-query style (SIFT/Cohere query splits): perturbations of
        # actual corpus documents, not of cluster centers
        ka, kb = jax.random.split(k)
        x0 = gen_chunk(chunk_keys[0])
        qi = jax.random.randint(ka, (BATCH * 16,), 0, chunk)
        q = x0[qi] + 0.3 * jax.random.normal(kb, (BATCH * 16, d))
        return q / jnp.linalg.norm(q, axis=-1, keepdims=True)

    queries = gen_queries(kq)

    truth_queries = queries[:BATCH]

    @jax.jit
    def exact_update(x, base, best_s, best_i):
        # ground truth: f32-precision scores of the FIRST batch of queries
        # vs this f32 chunk ([256, 1M] f32 scores = 1 GB transient; the
        # full query set would blow HBM)
        s = jax.lax.dot_general(
            truth_queries, x, (((1,), (1,)), ((), ())),
            precision=jax.lax.Precision.HIGHEST)
        ids = base + jnp.arange(chunk, dtype=jnp.int32)[None, :]
        cat_s = jnp.concatenate([best_s, s], axis=1)
        cat_i = jnp.concatenate([best_i, jnp.broadcast_to(ids, s.shape)], axis=1)
        vals, pos = jax.lax.top_k(cat_s, K)
        return vals, jnp.take_along_axis(cat_i, pos, axis=1)

    # the codec registry's int8 recipe (quant/codec.py) — the bench must
    # quantize EXACTLY like the serving path or its numbers drift from
    # what the engine ships (the TPU013 story, applied to the harness)
    from elasticsearch_tpu.quant import codec as quant_codec
    _int8 = quant_codec.get("int8")

    @jax.jit
    def quantize(x):
        return _int8.encode_jnp(x)

    @jax.jit
    def quantize_residual(x, q8, scale):
        r = x - q8.astype(jnp.float32) * scale[:, None]
        return _int8.encode_jnp(r)

    @functools.partial(jax.jit, donate_argnums=(0,))
    def write_chunk(buf, q8, base):
        return jax.lax.dynamic_update_slice(buf, q8, (base, 0))

    @functools.partial(jax.jit, donate_argnums=(0,))
    def write_scales(buf, s, base):
        return jax.lax.dynamic_update_slice(buf, s, (base,))

    t_build0 = time.perf_counter()
    matrix = jnp.zeros((n_pad, d), dtype=jnp.int8)
    scales = jnp.ones((n_pad,), dtype=jnp.float32)
    res_mat = jnp.zeros((n_pad, d), dtype=jnp.int8) if residual else None
    res_scales = jnp.ones((n_pad,), dtype=jnp.float32) if residual else None
    best_s = jnp.full((BATCH, K), -1e30, dtype=jnp.float32)
    best_i = jnp.zeros((BATCH, K), dtype=jnp.int32)
    for i, ck in enumerate(chunk_keys):
        x = gen_chunk(ck)
        best_s, best_i = exact_update(x, i * chunk, best_s, best_i)
        q8, sc = quantize(x)
        if residual:
            r8, rs = quantize_residual(x, q8, sc)
            res_mat = write_chunk(res_mat, r8, i * chunk)
            res_scales = write_scales(res_scales, rs, i * chunk)
            del r8, rs
        matrix = write_chunk(matrix, q8, i * chunk)
        scales = write_scales(scales, sc, i * chunk)
        del x, q8, sc
    ids_ref = np.asarray(best_i)
    build_s = time.perf_counter() - t_build0

    corpus = Corpus(matrix=matrix,
                    sq_norms=jnp.ones((n_pad,), dtype=jnp.float32),
                    scales=scales, num_valid=jnp.int32(n),
                    residual=res_mat, residual_scales=res_scales)

    def fn(qb, c, kk):
        return binned.binned_knn_search(qb, c, kk, metric="cosine")

    queries_np = np.asarray(queries)
    qps, marginal, p50, p99, ids = _measure(
        _scan_searcher(fn), corpus, queries_np, d, n_small=4, n_large=16)
    recall = _recall(ids[0], ids_ref)
    eff_tops = 2 * BATCH * n * d / marginal / 1e12
    headline = {
        "config": "4_north_star_int8_10Mx768", "qps": round(qps, 1),
        "batch_ms": round(marginal * 1000, 3),
        "recall_at_10": round(recall, 4), "n_docs": n, "dims": d,
        "dtype": "int8", "batch": BATCH,
        "hbm_corpus_gb": round(n_pad * d / 1e9, 2),
        "effective_int8_tops": round(eff_tops, 1),
        "ground_truth": "exact_f32_full_corpus",
        "build_s": round(build_s, 1)}
    if residual:
        # the recall-headroom target row (VERDICT r4 item 2): packed
        # rescore with bf16x2 query + residual reconstruction — near-exact
        # re-ranking of the kernel's own candidates at a few % QPS cost
        def fn_pr(qb, c, kk):
            return binned.binned_knn_search_rescored_packed(
                qb, c, kk, metric="cosine", rescore_candidates=128)

        qps_pr, marg_pr, p50_pr, p99_pr, ids_pr = _measure(
            _scan_searcher(fn_pr), corpus, queries_np, d,
            n_small=4, n_large=16)
        headline["packed_residual_rescore"] = {
            "qps": round(qps_pr, 1),
            "recall_at_10": round(_recall(ids_pr[0], ids_ref), 4),
            "qps_cost_pct": round(100 * (1 - qps_pr / qps), 1),
            "hbm_corpus_gb": round(2 * n_pad * d / 1e9, 2)}
        if emit:
            _emit("4pr_north_star_int8_residual_rescore", qps_pr, marg_pr,
                  p50_pr, p99_pr, _recall(ids_pr[0], ids_ref), n, d,
                  "int8+int8res",
                  {"rescore": "top128packed_bf16x2_query_residual",
                   "ground_truth": "exact_f32_full_corpus"})
    if emit:
        _emit("4_north_star_int8_10Mx768", qps, marginal, p50, p99, recall,
              n, d, "int8",
              {"hbm_corpus_gb": round(n_pad * d / 1e9, 2),
               "effective_int8_tops": round(eff_tops, 1),
               "ground_truth": "exact_f32_full_corpus",
               "build_s": round(build_s, 1)})
    if not extra:
        return headline

    # recall-headroom variant: the binned pass + an unquantized-query
    # re-score of the top bins' member rows (removes query quantization +
    # bin-collision loss). The bin gather costs a corpus-size-independent
    # ~6 ms/batch, so it's reported as its own row rather than silently
    # taxing the headline config.
    def fn_r(qb, c, kk):
        return binned.binned_knn_search_rescored(qb, c, kk, metric="cosine",
                                                 rescore_bins=16)

    qps_r, marg_r, p50_r, p99_r, ids_r = _measure(
        _scan_searcher(fn_r), corpus, queries_np, d, n_small=4, n_large=16)
    _emit("4r_north_star_int8_rescored", qps_r, marg_r, p50_r, p99_r,
          _recall(ids_r[0], ids_ref), n, d, "int8",
          {"rescore": "top16bins_bf16_query",
           "ground_truth": "exact_f32_full_corpus"})

    # cheaper headroom variants (VERDICT r3 item 5): packed-winner rescore
    # reuses the rows the kernel already identified (~25 MB/batch of
    # gathers vs ~200), and the hybrid adds a few whole bins for
    # same-bin-collision recovery
    def fn_p(qb, c, kk):
        return binned.binned_knn_search_rescored_packed(
            qb, c, kk, metric="cosine", rescore_candidates=128)

    qps_p, marg_p, p50_p, p99_p, ids_p = _measure(
        _scan_searcher(fn_p), corpus, queries_np, d, n_small=4, n_large=16)
    _emit("4p_north_star_int8_packed_rescore", qps_p, marg_p, p50_p, p99_p,
          _recall(ids_p[0], ids_ref), n, d, "int8",
          {"rescore": "top128packed_bf16_query",
           "ground_truth": "exact_f32_full_corpus"})

    def fn_h(qb, c, kk):
        return binned.binned_knn_search_rescored_hybrid(
            qb, c, kk, metric="cosine", rescore_bins=8,
            rescore_candidates=128)

    qps_h, marg_h, p50_h, p99_h, ids_h = _measure(
        _scan_searcher(fn_h), corpus, queries_np, d, n_small=4, n_large=16)
    _emit("4h_north_star_int8_hybrid_rescore", qps_h, marg_h, p50_h, p99_h,
          _recall(ids_h[0], ids_ref), n, d, "int8",
          {"rescore": "top8bins+top128packed_bf16_query",
           "ground_truth": "exact_f32_full_corpus"})
    _small_batch_rows("4_north_star", fn, corpus, queries_np, d, n_iter=16)
    return headline


def run_density_ladder(n: int = 262_144, d: int = 768):
    """Config 12: the quantization ladder density sweep (ISSUE 15).

    One clustered 768-d corpus served down every codec rung
    (`elasticsearch_tpu/quant/`): per-encoding qps, recall@10 vs exact
    f32, device HBM bytes-per-doc (packed row + per-row aux + norms),
    and the single-chip density column `max_docs_per_chip` (16 GB HBM /
    bytes_per_doc). Packed rungs (int4/binary) measure the TWO-PHASE
    shape the store serves: coarse packed top-(K·oversample) on device
    plus the exact f32 host rescore of the window, with the rescore's
    host cost folded into the effective qps. CPU-floor captures label
    themselves as ever (`cpu_fallback`), and rows carry the PR 11
    `_compile_noise_label` so compile stalls can't masquerade as
    serving tails."""
    import os

    import jax
    import jax.numpy as jnp

    from elasticsearch_tpu.ops import dispatch
    from elasticsearch_tpu.ops import knn as knn_ops
    from elasticsearch_tpu.ops import similarity as sim
    from elasticsearch_tpu.quant import codec as quant_codec
    from elasticsearch_tpu.quant import rescore as quant_rescore

    if os.environ.get("BENCH_SMALL") == "1":
        n = min(n, 65_536)
    backend = jax.devices()[0].platform
    cpu_fallback = not dispatch.is_accelerator_backend()
    hbm_bytes = 16 * 1024**3

    # clustered corpus at a FIXED ~64 docs/cluster (cluster count scales
    # with n): binary sign-sketch recall depends on neighbor geometry,
    # not just corpus size — a query's true top-10 must be semantically
    # close (same-cluster) rows for a 1-bit sketch to rank, the regime
    # real embedding corpora live in. Isotropic few-cluster blobs (the
    # sketch's worst case) and 4-doc micro-clusters (top-10 mostly
    # near-orthogonal cross-cluster ties) both sink ANY coarse 1-bit
    # pass; this shape keeps the recall column about the CODEC, with
    # held-out queries as 0.3-perturbations of corpus docs as ever.
    rng = np.random.default_rng(7)
    n_centers = max(n // 64, 1)
    centers = rng.standard_normal((n_centers, d)).astype(np.float32) * 2.0
    vectors = (centers[rng.integers(0, n_centers, size=n)]
               + rng.standard_normal((n, d)).astype(np.float32))
    nq = BATCH * 64
    queries = (vectors[rng.integers(0, n, size=nq)]
               + 0.3 * rng.standard_normal((nq, d)).astype(np.float32))

    f32_corpus = knn_ops.build_corpus(vectors, metric=sim.COSINE,
                                      dtype="f32")
    _, ids_ref = knn_ops.knn_search(
        jnp.asarray(queries[:BATCH]), f32_corpus, k=K, metric=sim.COSINE,
        precision="f32")
    ids_ref = np.asarray(ids_ref)

    for encoding in ("f32", "bf16", "int8", "int4", "binary"):
        corpus = (f32_corpus if encoding == "f32"
                  else knn_ops.build_corpus(vectors, metric=sim.COSINE,
                                            dtype=encoding,
                                            residual=False))
        packed = encoding in quant_codec.PACKED_ENCODINGS
        oversample = quant_rescore.DEFAULT_OVERSAMPLE.get(encoding, 0)
        n_pad = corpus.matrix.shape[0]
        mark = _dispatch_mark()
        if packed:
            w = quant_rescore.coarse_window(K, oversample, limit=n_pad)
            k_coarse = dispatch.bucket_k(w, limit=n_pad)

            def fn(qb, c, kk, _kc=k_coarse):
                return knn_ops.knn_search(qb, c, _kc, metric=sim.COSINE)
        else:
            def fn(qb, c, kk):
                return knn_ops.knn_search_auto(qb, c, kk,
                                               metric=sim.COSINE)

        qps, marginal, p50, p99, ids = _measure(
            _scan_searcher(fn), corpus, queries, d, n_small=4, n_large=16)
        row_dispatch = _dispatch_delta(mark)

        rescore_ms = 0.0
        if packed:
            # phase two on the first batch: exact f32 re-rank of the
            # coarse window (the store's response-assembly shape); its
            # host cost folds into the SAME amortized-qps basis the
            # dense rows report (per-batch rescore added to the
            # amortized per-batch time), so the ladder's rung-vs-rung
            # qps column compares like for like
            w = quant_rescore.coarse_window(K, oversample, limit=n_pad)
            s, i = knn_ops.knn_search(
                jnp.asarray(queries[:BATCH]), corpus,
                dispatch.bucket_k(w, limit=n_pad), metric=sim.COSINE)
            s = np.asarray(s)[:, :w]
            i = np.asarray(i)[:, :w]
            t0 = time.perf_counter()
            _, out_i, _stats = quant_rescore.rescore_boards(
                queries[:BATCH], s, i, K, lambda u: vectors[u],
                sim.COSINE)
            rescore_ms = (time.perf_counter() - t0) * 1000
            recall = _recall(out_i, ids_ref)
            qps = BATCH / (BATCH / qps + rescore_ms / 1000)
        else:
            recall = _recall(ids[0], ids_ref)

        bpd = quant_codec.bytes_per_doc(encoding, d)
        max_docs = hbm_bytes // bpd
        row = {
            "config": "12_density_ladder", "encoding": encoding,
            "qps": round(qps, 1), "batch_ms": round(marginal * 1000, 3),
            "p50_ms": round(p50, 1), "p99_ms": round(p99, 1),
            "recall_at_10": round(recall, 4), "n_docs": n, "dims": d,
            "batch": BATCH,
            "bytes_per_doc": bpd,
            "hbm_gb": 16,
            "max_docs_per_chip": int(max_docs),
            "single_chip_100m": bool(max_docs >= 100_000_000),
            "backend": backend,
            "dispatch": row_dispatch,
            **({"cpu_fallback": True} if cpu_fallback else {}),
            **({"rescore": {"oversample": oversample,
                            "window": quant_rescore.coarse_window(
                                K, oversample, limit=n_pad),
                            "host_rescore_ms_per_batch":
                                round(rescore_ms, 2)}}
               if packed else {}),
            **_compile_noise_label(row_dispatch),
        }
        print(json.dumps(row), flush=True)
        if encoding != "f32":
            del corpus


def run_hybrid_rrf(mesh=None):
    """Config 3: BM25 + kNN fused with RRF on an MS-MARCO-shaped corpus
    (100k docs, 768-d vectors, zipfian text), end-to-end through
    Node.search. Round 3 served one device round-trip per query (7.2 QPS on
    2k docs); the serving layer now coalesces concurrent requests and
    cost-routes small-corpus kNN to the host VNNI kernel, so this measures
    both a single-client p50 and a concurrent-client throughput row.
    `mesh`: optional `search.mesh.*` node settings — the dp-mesh rerun
    (run_rest_closed_loop_dp) points the same corpus at a replicated
    mesh instead of dp=1 shapes."""
    import tempfile
    import threading

    from elasticsearch_tpu.node import Node

    import os

    rng = np.random.default_rng(3)
    # BENCH_HYBRID_FULL=1 forces the full 100k corpus even in small mode:
    # the acceptance gate for config 3 is stated against 100k docs, and a
    # CPU-floor capture should still measure that corpus when given time
    n_docs = 10_000 if (os.environ.get("BENCH_SMALL") == "1"
                        and os.environ.get("BENCH_HYBRID_FULL") != "1") \
        else 100_000
    dims = 768
    vocab = np.array([f"tok{i}" for i in range(20_000)])
    zipf = (rng.zipf(1.25, size=n_docs * 12) - 1) % 20_000

    node = Node(tempfile.mkdtemp(), settings=mesh)
    node.create_index_with_templates("hybrid", mappings={"properties": {
        "body": {"type": "text"},
        "v": {"type": "dense_vector", "dims": dims}}})
    t_build0 = time.perf_counter()
    pos = 0
    for c0 in range(0, n_docs, 2000):
        ops = []
        for i in range(c0, min(c0 + 2000, n_docs)):
            ops.append({"index": {"_index": "hybrid", "_id": str(i)}})
            ops.append({
                "body": " ".join(vocab[zipf[pos:pos + 12]]),
                "v": rng.standard_normal(dims).astype(np.float32).tolist()})
            pos += 12
        node.bulk(ops)
    # one segment, like every reference benchmark setup (merge() ends
    # with its own refresh + vector re-sync)
    node.indices.get("hybrid").force_merge()
    build_s = time.perf_counter() - t_build0

    def body_for(qv, terms):
        return {"rank": {"rrf": {"rank_constant": 60,
                                 "rank_window_size": 100}},
                "query": {"match": {"body": " ".join(terms)}},
                "knn": {"field": "v", "query_vector": qv, "k": 100,
                        "num_candidates": 100},
                "size": 10, "_source": False}

    def rand_query():
        qv = rng.standard_normal(dims).astype(np.float32).tolist()
        terms = vocab[(rng.zipf(1.25, size=2) - 1) % 20_000]
        return body_for(qv, list(terms))

    warm = rand_query()
    resp = node.search("hybrid", warm)
    assert resp["hits"]["hits"], "rrf returned no hits"

    # single-client p50: one query at a time, host-routed kNN
    bodies = [rand_query() for _ in range(50)]
    lats = []
    for b in bodies:
        t0 = time.perf_counter()
        node.search("hybrid", b)
        lats.append((time.perf_counter() - t0) * 1000)
    print(json.dumps({"config": "3_hybrid_bm25_knn_rrf_single",
                      "p50_ms": round(float(np.percentile(lats, 50)), 2),
                      "p99_ms": round(float(np.percentile(lats, 99)), 2),
                      "n_docs": n_docs, "dims": dims,
                      **({"mesh": mesh} if mesh else {}),
                      "build_s": round(build_s, 1)}), flush=True)

    # concurrent clients: whole hybrid queries coalesce through the
    # fused-plan batcher into shared lexical + kNN dispatches
    n_clients, per_client = 8, 40
    client_bodies = [[rand_query() for _ in range(per_client)]
                     for _ in range(n_clients)]
    # concurrent warmup: the batched lexical/kNN jits specialize on
    # power-of-2 batch buckets — compile them OUTSIDE the timed loop
    warm = [threading.Thread(
        target=lambda: [node.search("hybrid", rand_query())
                        for _ in range(6)]) for _ in range(n_clients)]
    for t in warm:
        t.start()
    for t in warm:
        t.join()
    # deterministic grid warmup on top of the stochastic warm queries:
    # the lexical kernel's term-tile dimension (m) pads to the batch max
    # and a zipf-popular term alone spans dozens of impact tiles, so a
    # timed-loop batch can hit an m rung the warm queries never produced
    # (measured: one such miss cost a 750 ms XLA compile mid-loop and
    # alone blew the p99 gate). Run the executor's warmup grid
    # synchronously — the same grid a TPU-class deployment precompiles
    # at batcher start via warmup-at-open.
    node._hybrid_executor(node.indices.get("hybrid"))._warmup()
    mark = _dispatch_mark()  # steady state: the timed loop must read 0 misses
    tmark = _telemetry_mark()
    all_lats = [[] for _ in range(n_clients)]

    def client(ci):
        for b in client_bodies[ci]:
            t0 = time.perf_counter()
            node.search("hybrid", b)
            all_lats[ci].append((time.perf_counter() - t0) * 1000)

    threads = [threading.Thread(target=client, args=(ci,))
               for ci in range(n_clients)]
    t0 = time.perf_counter()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    wall = time.perf_counter() - t0
    lats = np.concatenate(all_lats)
    p50 = float(np.percentile(lats, 50))
    p99 = float(np.percentile(lats, 99))
    qps = n_clients * per_client / wall
    disp = _dispatch_delta(mark)
    print(json.dumps({"config": "3_hybrid_bm25_knn_rrf",
                      "qps": round(qps, 1),
                      "p50_ms": round(p50, 2),
                      "p99_ms": round(p99, 2),
                      "p99_over_p50": round(p99 / max(p50, 1e-9), 2),
                      "gate_p99_le_3x_p50": bool(p99 <= 3 * p50),
                      "gate_500qps": bool(qps >= 500),
                      "n_docs": n_docs, "dims": dims,
                      "concurrent_clients": n_clients,
                      "fused_lists": 2,
                      "execution": "fused_hybrid_plan",
                      **({"mesh": mesh} if mesh else {}),
                      **hybrid_serving_stats(node),
                      **_compile_noise_label(disp),
                      "telemetry": _telemetry_delta(tmark),
                      "dispatch": disp}), flush=True)
    node.close()


def run_telemetry_overhead(n_docs: int = 5_000, dims: int = 64,
                           n_clients: int = 4, per_client: int = 60):
    """Config 11: the telemetry layer's overhead + percentile fidelity.

    Two closed loops over the SAME hybrid corpus, driven through the
    REST controller (where tracing engages): sampled tracing OFF
    (sample_rate=0) vs ON (sample_rate=1 — every request traced, the
    worst case; production defaults to 0.01). Gates:

      gate_telemetry_overhead   p50(on) <= 1.05 x p50(off) — the layer
                                must stay invisible at the median
      gate_histogram_p99        the `search.took` histogram-derived p99
                                (the `_nodes/stats telemetry` surface)
                                agrees with the closed-loop measured p99
                                within one log2 bucket — the in-tree
                                percentile surface is trustworthy
    """
    import tempfile
    import threading

    from elasticsearch_tpu.node import Node
    from elasticsearch_tpu.rest.actions import register_all
    from elasticsearch_tpu.rest.controller import RestController
    from elasticsearch_tpu.telemetry import TRACER, metrics

    rng = np.random.default_rng(23)
    vocab = np.array([f"tok{i}" for i in range(2_000)])
    zipf = (rng.zipf(1.25, size=n_docs * 8) - 1) % 2_000
    node = Node(tempfile.mkdtemp())
    node.create_index_with_templates("tel", mappings={"properties": {
        "body": {"type": "text"},
        "v": {"type": "dense_vector", "dims": dims}}})
    pos = 0
    for c0 in range(0, n_docs, 1000):
        ops = []
        for i in range(c0, min(c0 + 1000, n_docs)):
            ops.append({"index": {"_index": "tel", "_id": str(i)}})
            ops.append({
                "body": " ".join(vocab[zipf[pos:pos + 8]]),
                "v": rng.standard_normal(dims).astype(
                    np.float32).tolist()})
            pos += 8
        node.bulk(ops)
    node.indices.get("tel").force_merge()
    rc = RestController()
    register_all(rc, node)

    def rand_body():
        return json.dumps({
            "rank": {"rrf": {"rank_constant": 60,
                             "rank_window_size": 50}},
            "query": {"match": {"body": " ".join(
                vocab[(rng.zipf(1.25, size=2) - 1) % 2_000])}},
            "knn": {"field": "v",
                    "query_vector": rng.standard_normal(dims).astype(
                        np.float32).tolist(),
                    "k": 50, "num_candidates": 50},
            "size": 10, "_source": False}).encode()

    client_bodies = [[rand_body() for _ in range(per_client)]
                     for _ in range(n_clients)]

    def closed_loop():
        all_lats = [[] for _ in range(n_clients)]

        def client(ci):
            for raw in client_bodies[ci]:
                t0 = time.perf_counter()
                st, _resp = rc.dispatch("POST", "/tel/_search", {}, raw,
                                        "application/json")
                assert st == 200
                all_lats[ci].append((time.perf_counter() - t0) * 1000)

        threads = [threading.Thread(target=client, args=(ci,))
                   for ci in range(n_clients)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        return np.concatenate(all_lats)

    # warmup: compile the hybrid grid + touch every bucket the loop uses
    node._hybrid_executor(node.indices.get("tel"))._warmup()
    for _ in range(8):
        rc.dispatch("POST", "/tel/_search", {}, rand_body(),
                    "application/json")

    prior_rate = TRACER.sample_rate
    try:
        TRACER.configure(sample_rate=0.0)
        lats_off = closed_loop()
        TRACER.configure(sample_rate=1.0)
        tmark = _telemetry_mark()
        lats_on = closed_loop()
    finally:
        TRACER.configure(sample_rate=prior_rate)

    p50_off = float(np.percentile(lats_off, 50))
    p50_on = float(np.percentile(lats_on, 50))
    p99_on = float(np.percentile(lats_on, 99))
    tel = _telemetry_delta(tmark, names=("search.took",))
    hist_p99_ms = tel.get("search.took", {}).get("p99_ms", 0.0)
    bucket_gap = abs(metrics.bucket_index(int(hist_p99_ms * 1e6))
                     - metrics.bucket_index(int(p99_on * 1e6)))
    overhead = p50_on / max(p50_off, 1e-9)
    print(json.dumps({
        "config": "11_telemetry_overhead",
        "p50_off_ms": round(p50_off, 2),
        "p50_on_ms": round(p50_on, 2),
        "p50_overhead": round(overhead, 3),
        "gate_telemetry_overhead": bool(overhead <= 1.05),
        "p99_measured_ms": round(p99_on, 2),
        "p99_histogram_ms": round(hist_p99_ms, 2),
        "p99_bucket_gap": int(bucket_gap),
        "gate_histogram_p99": bool(bucket_gap <= 1),
        "traced_requests": tel.get("search.took", {}).get("count", 0),
        "n_docs": n_docs, "dims": dims,
        "concurrent_clients": n_clients,
        "telemetry": tel}), flush=True)
    node.close()


def _inject_vector_segment(shard, field, mat):
    """Seal a synthetic segment holding `mat` directly into the shard's
    engine — the corpus-build path for e2e serving rows where bulk-indexing
    millions of JSON vectors would dominate the benchmark run."""
    from elasticsearch_tpu.index.segment import Segment

    engine = shard.engine
    n = mat.shape[0]
    base = engine._next_row
    seg = Segment(
        seg_id=engine._next_seg_id, base=base, num_docs=n,
        postings={}, field_lengths={}, total_terms={}, doc_values={},
        vectors={field: (mat, np.ones(n, dtype=bool))},
        ids=[f"d{base + i}" for i in range(n)],
        sources=[None] * n,
        seq_nos=np.arange(base, base + n, dtype=np.int64))
    engine.segments.append(seg)
    engine._next_seg_id += 1
    engine._next_row += n


def run_closed_loop(name: str, n: int, d: int, dtype: str = "bf16",
                    n_clients: int = 8, per_client: int = 40, mesh=None):
    """8-client closed-loop latency through the full serving path
    (Node.search → CombiningBatcher → device/host kernel) for the
    config-1 and config-4 corpus shapes.

    The row exists to prove the p99 tail fix: the r03 record showed
    1,086 ms (config 1) and 2,508 ms (config 4) p99 against ~70 ms p50 —
    unbounded queueing at batch 256. With the combining batcher + bounded
    admission, the recorded gate is p99 <= 3x p50 (VERDICT r5 Next #2);
    the row prints the measured ratio and the boolean so the record
    itself says whether the gate held."""
    import tempfile
    import threading

    from elasticsearch_tpu.node import Node

    rng = np.random.default_rng(17)
    node = Node(tempfile.mkdtemp(), settings=mesh)
    mapping = {"properties": {"v": {"type": "dense_vector", "dims": d}}}
    if dtype == "int8":
        mapping["properties"]["v"]["index_options"] = {"type": "int8_flat"}
    node.create_index_with_templates(name, mappings=mapping)
    t0 = time.perf_counter()
    mat = rng.standard_normal((n, d)).astype(np.float32)
    _inject_vector_segment(node.indices.get(name).shards[0], "v", mat)
    del mat
    node.indices.get(name).refresh()
    build_s = time.perf_counter() - t0

    def body():
        return {"knn": {"field": "v",
                        "query_vector":
                            rng.standard_normal(d).astype(
                                np.float32).tolist(),
                        "k": 10, "num_candidates": 10},
                "size": 10, "_source": False}

    # warmup must cover the CONCURRENT path: the combining batcher pads
    # coalesced batches to power-of-2 buckets and the device jit
    # specializes per bucket — an unwarmed bucket compiling inside the
    # timed loop reads as a multi-second p99 outlier that has nothing to
    # do with steady-state serving
    def warm_client():
        for _ in range(6):
            node.search(name, body())

    warm = [threading.Thread(target=warm_client)
            for _ in range(n_clients)]
    for t in warm:
        t.start()
    for t in warm:
        t.join()
    mark = _dispatch_mark()  # steady state: the timed loop must read 0 misses
    tmark = _telemetry_mark()
    client_bodies = [[body() for _ in range(per_client)]
                     for _ in range(n_clients)]
    all_lats = [[] for _ in range(n_clients)]

    def client(ci):
        for b in client_bodies[ci]:
            t0 = time.perf_counter()
            node.search(name, b)
            all_lats[ci].append((time.perf_counter() - t0) * 1000)

    threads = [threading.Thread(target=client, args=(ci,))
               for ci in range(n_clients)]
    t0 = time.perf_counter()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    wall = time.perf_counter() - t0
    lats = np.concatenate(all_lats)
    p50 = float(np.percentile(lats, 50))
    p99 = float(np.percentile(lats, 99))
    disp = _dispatch_delta(mark)
    qps = n_clients * per_client / wall
    extra = {}
    if mesh:
        from elasticsearch_tpu.parallel import policy
        extra["mesh"] = mesh
        extra["router"] = policy.stats().get("router", {})
    print(json.dumps({
        "config": f"{name}_closed_loop_8c",
        "qps": round(qps, 1),
        "p50_ms": round(p50, 2), "p99_ms": round(p99, 2),
        "p99_over_p50": round(p99 / max(p50, 1e-9), 2),
        "gate_p99_le_3x_p50": bool(p99 <= 3 * p50),
        "gate_500qps": bool(qps >= 500),
        "n_docs": n, "dims": d, "dtype": dtype,
        "concurrent_clients": n_clients,
        "build_s": round(build_s, 1),
        **extra,
        **knn_scheduler_stats(node),
        **_compile_noise_label(disp),
        "telemetry": _telemetry_delta(tmark),
        "dispatch": disp}), flush=True)
    node.close()


def run_zipf_cached_closed_loop(n: int = 1_000_000, d: int = 128,
                                n_clients: int = 8, per_client: int = 40,
                                pool_size: int = 48):
    """Config 13: zipf-skewed repeated queries through the layered
    read-path caches (PR 16) under closed-loop clients with sustained
    ingest churn.

    Two identical corpora serve the SAME zipf query stream: `zoff`
    (every body carries `request_cache: false`, semantic cache off —
    every query recomputes) and `zon` (device request cache on by
    default for kNN bodies, `index.knn.semantic_cache.enabled: true`).
    The stream draws from a fixed pool with zipf(1.2) rank weights;
    30% of draws re-send the SAME embedding with 1e-6 float jitter —
    a different canonical body (request-cache miss) but a
    near-identical embedding, the re-embedded-query shape the semantic
    ring exists for. A churn thread injects a small delta segment +
    refresh every second DURING both timed loops, so the recorded hit
    rates are the steady state under fingerprint invalidation, not a
    frozen-reader best case.

    Gates:
      gate_cache_p50        served rate (request-cache + semantic hits
                            over queries) >= 0.25 AND p50_on <= p50_off
                            — the cache tier must actually serve and
                            actually help
      gate_p99_le_3x_p50    the EXISTING closed-loop tail gate, applied
                            to the uncached run: the cache layer's probe
                            /key work must not regress the miss path
      gate_cached_tail      p99_on <= 1.5 x p99_off: a cached run's tail
                            (its misses + invalidation recompute) must
                            not be worse than the uncached tail"""
    import os
    import tempfile
    import threading

    from elasticsearch_tpu.node import Node

    if os.environ.get("BENCH_SMALL") == "1":
        n = 100_000
    rng = np.random.default_rng(23)
    node = Node(tempfile.mkdtemp())
    t0 = time.perf_counter()
    mat = rng.standard_normal((n, d)).astype(np.float32)
    for name, settings in (
            ("zoff", None),
            ("zon", {"index.knn.semantic_cache.enabled": True,
                     "index.knn.semantic_cache.size": 256,
                     "index.knn.semantic_cache.threshold": 0.995})):
        node.create_index_with_templates(
            name, settings=settings,
            mappings={"properties": {
                "v": {"type": "dense_vector", "dims": d}}})
        _inject_vector_segment(node.indices.get(name).shards[0], "v", mat)
        node.indices.get(name).refresh()
    del mat
    build_s = time.perf_counter() - t0

    # zipf-ranked query pool: rank r drawn with p ~ 1/r^1.2, so the head
    # repeats heavily (request-cache hits) and the tail stays cold
    pool = rng.standard_normal((pool_size, d)).astype(np.float32)
    total = n_clients * per_client
    ranks = (rng.zipf(1.2, size=total) - 1) % pool_size
    jitter = rng.random(total) < 0.30

    def make_body(i, cached):
        q = pool[ranks[i]]
        if jitter[i]:
            # same embedding re-sent with float noise far below the
            # semantic guard's identity epsilon: the canonical body
            # differs (request-cache miss) but the ring probe reads
            # sim ~= 1.0 and the exact-rescore guard passes
            q = q + rng.standard_normal(d).astype(np.float32) * 1e-6
        b = {"knn": {"field": "v", "query_vector": q.tolist(),
                     "k": 10, "num_candidates": 10},
             "size": 10, "_source": False}
        if not cached:
            b["request_cache"] = False
        return b

    bodies = {
        False: [make_body(i, False) for i in range(total)],
        True: [make_body(i, True) for i in range(total)]}

    wdelta = rng.standard_normal((256, d)).astype(np.float32)

    def warm(index, cached):
        def round_():
            def one():
                for i in range(6):
                    node.search(index, make_body(i % pool_size, cached))
            ts = [threading.Thread(target=one) for _ in range(n_clients)]
            for t in ts:
                t.start()
            for t in ts:
                t.join()

        round_()
        # churn-path warm: the timed loops seal a 256-row delta per
        # second, and a fresh seal's generational dispatch buckets
        # compile on first use — on the CPU floor that is a ~1.7 s stall
        # that lands in the uncached run's p99 (the PR 10 compile-noise
        # class). Seal one identical delta per index here and re-drive
        # the clients so those buckets compile outside the timed window.
        _inject_vector_segment(node.indices.get(index).shards[0],
                               "v", wdelta)
        node.indices.get(index).refresh()
        round_()

    def drive(index, cached):
        shard = node.indices.get(index).shards[0]
        stop = threading.Event()
        refreshes = [0]
        crng = np.random.default_rng(99)  # identical churn both runs

        def churn():
            while not stop.wait(1.0):
                dm = crng.standard_normal((256, d)).astype(np.float32)
                _inject_vector_segment(shard, "v", dm)
                node.indices.get(index).refresh()  # fingerprint moves
                refreshes[0] += 1

        stream = bodies[cached]
        per = [stream[ci * per_client:(ci + 1) * per_client]
               for ci in range(n_clients)]
        all_lats = [[] for _ in range(n_clients)]

        def client(ci):
            for b in per[ci]:
                t1 = time.perf_counter()
                node.search(index, b)
                all_lats[ci].append((time.perf_counter() - t1) * 1000)

        ct = threading.Thread(target=churn)
        ts = [threading.Thread(target=client, args=(ci,))
              for ci in range(n_clients)]
        t1 = time.perf_counter()
        ct.start()
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        wall = time.perf_counter() - t1
        stop.set()
        ct.join()
        lats = np.concatenate(all_lats)
        return (float(np.percentile(lats, 50)),
                float(np.percentile(lats, 99)), wall, refreshes[0])

    warm("zoff", False)
    warm("zon", True)
    dev0 = dict(node.caches.device_request.stats())
    host0 = dict(node.caches.request.stats())
    knn0 = node._knn_stats_section()
    mark = _dispatch_mark()

    p50_off, p99_off, wall_off, ref_off = drive("zoff", False)
    p50_on, p99_on, wall_on, ref_on = drive("zon", True)

    dev1 = node.caches.device_request.stats()
    host1 = dict(node.caches.request.stats())
    knn1 = node._knn_stats_section()
    disp = _dispatch_delta(mark)

    dev_hits = dev1["hits"] - dev0["hits"]
    dev_misses = dev1["misses"] - dev0["misses"]
    sem_probes = knn1["semantic_probes"] - knn0["semantic_probes"]
    sem_hits = knn1["semantic_hits"] - knn0["semantic_hits"]
    served_rate = (dev_hits + sem_hits) / max(total, 1)
    print(json.dumps({
        "config": "13_zipf_cached_closed_loop",
        "p50_off_ms": round(p50_off, 2), "p99_off_ms": round(p99_off, 2),
        "p50_on_ms": round(p50_on, 2), "p99_on_ms": round(p99_on, 2),
        "qps_off": round(total / wall_off, 1),
        "qps_on": round(total / wall_on, 1),
        "rungs": {
            "device_request_cache": {
                "hits": dev_hits, "misses": dev_misses,
                "hit_rate": round(dev_hits
                                  / max(dev_hits + dev_misses, 1), 3)},
            "request_cache": {
                "hits": host1["hits"] - host0["hits"],
                "misses": host1["misses"] - host0["misses"]},
            "semantic": {
                "probes": sem_probes, "hits": sem_hits,
                "rejects": knn1["semantic_rejects"]
                - knn0["semantic_rejects"],
                "inserts": knn1["semantic_inserts"]
                - knn0["semantic_inserts"],
                "invalidations": knn1["semantic_invalidations"]
                - knn0["semantic_invalidations"],
                "hit_rate": round(sem_hits / max(sem_probes, 1), 3)}},
        "served_rate": round(served_rate, 3),
        "churn_refreshes": {"off": ref_off, "on": ref_on},
        "gate_cache_p50": bool(served_rate >= 0.25
                               and p50_on <= p50_off),
        "gate_p99_le_3x_p50": bool(p99_off <= 3 * p50_off),
        "gate_cached_tail": bool(p99_on <= 1.5 * p99_off),
        "n_docs": n, "dims": d, "zipf_pool": pool_size,
        "concurrent_clients": n_clients,
        "build_s": round(build_s, 1),
        **_compile_noise_label(disp),
        "dispatch": disp}), flush=True)
    node.close()


def run_e2e_single():
    """True end-to-end single-query latency: HTTP request -> REST parse ->
    Node.search -> serving layer -> device/host kernel -> JSON response,
    through a real socket (BASELINE asks for p50; the matrix's other rows
    measure device time only). Config-1 shape at full 1M x 128; the north
    star's 10M x 768 f32 host copy (30 GB) cannot be staged on this host,
    so its e2e row runs at 1M x 768 and says so."""
    import asyncio
    import http.client
    import tempfile
    import threading

    from elasticsearch_tpu.node import Node
    from elasticsearch_tpu.rest.actions import register_all
    from elasticsearch_tpu.rest.controller import RestController
    from elasticsearch_tpu.rest.http_server import HttpServer

    node = Node(tempfile.mkdtemp())
    controller = RestController()
    register_all(controller, node)
    server = HttpServer(controller, port=0, thread_pool=node.thread_pool)
    loop = asyncio.new_event_loop()

    async def _serve():
        await server.start()

    def _run_loop():
        asyncio.set_event_loop(loop)
        loop.run_forever()

    t = threading.Thread(target=_run_loop, daemon=True)
    t.start()
    asyncio.run_coroutine_threadsafe(_serve(), loop).result(30)
    port = server.port

    import os

    rng = np.random.default_rng(7)
    shapes = (("e2e1", 1_000_000, 128), ("e2e4", 1_000_000, 768))
    if os.environ.get("BENCH_SMALL") == "1":
        shapes = (("e2e1", 100_000, 128), ("e2e4", 100_000, 768))
    for name, n, d in shapes:
        node.create_index_with_templates(name, mappings={"properties": {
            "v": {"type": "dense_vector", "dims": d}}})
        t0 = time.perf_counter()
        mat = rng.standard_normal((n, d)).astype(np.float32)
        shard = node.indices.get(name).shards[0]
        _inject_vector_segment(shard, "v", mat)
        del mat
        node.indices.get(name).refresh()  # device upload + host mirror
        build_s = time.perf_counter() - t0

        conn = http.client.HTTPConnection("127.0.0.1", port)
        lats = []
        for it in range(23):
            qv = rng.standard_normal(d).astype(np.float32).tolist()
            body = json.dumps({"knn": {"field": "v", "query_vector": qv,
                                       "k": 10, "num_candidates": 10},
                               "size": 10, "_source": False})
            t0 = time.perf_counter()
            conn.request("POST", f"/{name}/_search", body,
                         {"Content-Type": "application/json"})
            resp = conn.getresponse().read()
            if it >= 3:  # first hits compile/build paths
                lats.append((time.perf_counter() - t0) * 1000)
            assert b'"hits"' in resp
        conn.close()
        print(json.dumps({"config": f"{name}_rest_single_query",
                          "p50_ms": round(float(np.percentile(lats, 50)), 2),
                          "p99_ms": round(float(np.percentile(lats, 99)), 2),
                          "n_docs": n, "dims": d,
                          "build_s": round(build_s, 1)}), flush=True)

    loop.call_soon_threadsafe(loop.stop)
    node.close()


def run_small_batch_serving(n: int = 1_000_000, d: int = 128):
    """Batch-size latency sweep THROUGH the serving store (pad-to-bucket
    + dispatch executable cache), the row that kills the r06 anomaly
    (batch=4 @ 149 ms p50 vs batch=16 @ 31.6 ms — a smaller batch must
    never be slower than a larger one once every size executes a
    pre-compiled bucket program).

    Emits per-batch p50s plus `gate_monotone_sane`: p50(b) <= 1.25 x
    p50(b') for every b < b' (tolerance covers timer noise; a recompile
    stall is a 5-50x violation, not 1.25x)."""
    import os

    from elasticsearch_tpu.ops import knn as knn_ops
    from elasticsearch_tpu.ops import similarity as sim
    from elasticsearch_tpu.vectors.store import FieldCorpus, VectorStoreShard

    if os.environ.get("BENCH_SMALL") == "1":
        n = min(n, 131_072)
    rng = np.random.default_rng(19)
    vectors = rng.standard_normal((n, d)).astype(np.float32)
    store = VectorStoreShard(warmup=False)
    corpus = knn_ops.build_corpus(vectors, metric=sim.COSINE, dtype="bf16")
    store._fields["v"] = FieldCorpus(
        corpus, np.arange(n, dtype=np.int64), sim.COSINE, d,
        version=("bench",))
    del vectors

    batches = (1, 4, 16)
    # warmup pass compiles each bucket once — steady state measured after
    for b in batches:
        qs = rng.standard_normal((b, d)).astype(np.float32)
        store.search_many("v", [(q, None) for q in qs], k=K)
    mark = _dispatch_mark()
    p50s = {}
    for b in batches:
        lats = []
        for _ in range(15):
            qs = rng.standard_normal((b, d)).astype(np.float32)
            reqs = [(q, None) for q in qs]
            t0 = time.perf_counter()
            store.search_many("v", reqs, k=K)
            lats.append((time.perf_counter() - t0) * 1000)
        p50s[b] = float(np.percentile(lats, 50))
    gate = all(p50s[a] <= 1.25 * p50s[b]
               for i, a in enumerate(batches)
               for b in batches[i + 1:])
    print(json.dumps({
        "config": "1sb_small_batch_serving",
        **{f"p50_ms_b{b}": round(p50s[b], 2) for b in batches},
        "gate_monotone_sane": bool(gate),
        "n_docs": n, "dims": d, "dtype": "bf16",
        "dispatch": _dispatch_delta(mark)}), flush=True)


def run_device_aggs(n_docs: int = 100_000):
    """Config 8: device-resident aggregations (ops/aggs.py +
    search/agg_plan.py) — dashboard-shaped bodies (terms+stats,
    CALENDAR date_histogram, 2-level sub-agg trees, cardinality over a
    range-filtered match set) served by the fused filter→aggregate
    device plan vs the host numpy walkers, with byte-parity asserted
    between the two. `dispatch` records the aggs.* executable-cache
    behavior of the measured (post-warm) window — a steady-state
    dashboard must show zero compiles — and `gate_device_ratio` holds
    the device-routed fraction of agg nodes at ≥ 0.9 (the cost router
    is pinned off for the device rows so the gate measures ELIGIBILITY,
    not the router's tiny-corpus escape hatch)."""
    import os
    import tempfile

    from elasticsearch_tpu.node import Node

    if os.environ.get("BENCH_SMALL") == "1":
        n_docs = min(n_docs, 4_000)
    rng = np.random.default_rng(23)
    node = Node(tempfile.mkdtemp())
    node.settings["search.aggs.cost_router"] = "false"
    try:
        node.create_index_with_templates("dash", mappings={"properties": {
            "cat": {"type": "keyword"}, "status": {"type": "keyword"},
            "bytes": {"type": "long"}, "ts": {"type": "date"}}})
        cats = [f"service-{i}" for i in range(24)]
        t0 = time.perf_counter()
        base_ts = 1_600_000_000_000
        for c0 in range(0, n_docs, 5000):
            ops = []
            for i in range(c0, min(c0 + 5000, n_docs)):
                ops.append({"index": {"_index": "dash", "_id": str(i)}})
                ops.append({"cat": cats[int(rng.integers(24))],
                            "status": ["ok", "warn", "err"][i % 3],
                            "bytes": int(rng.integers(0, 1 << 20)),
                            "ts": base_ts + (i % 720) * 60_000})
            node.bulk(ops)
        node.indices.get("dash").force_merge()
        node.indices.get("dash").refresh()
        build_s = time.perf_counter() - t0

        def body(lo):
            # size 1 (not 0): size-0 agg responses are shard-request-cache
            # eligible, and the host-comparison pass re-issues these exact
            # bodies — a cached device response would make host_p50 and
            # parity_vs_host measure the LRU, not the host walkers
            return {"query": {"range": {"bytes": {"gte": int(lo)}}},
                    "size": 1,
                    "aggs": {
                        "by_cat": {"terms": {"field": "cat", "size": 10},
                                   "aggs": {"b": {"stats":
                                                  {"field": "bytes"}}}},
                        "over_time": {"date_histogram": {
                            "field": "ts", "fixed_interval": "1h"},
                            "aggs": {"b": {"sum": {"field": "bytes"}}}},
                        # rung 2: calendar interval (boundary table),
                        # 2-level sub-agg tree (composite-id boards),
                        # cardinality (HLL register boards)
                        "per_hour": {"date_histogram": {
                            "field": "ts", "calendar_interval": "hour"},
                            "aggs": {"uc": {"cardinality":
                                            {"field": "cat"}}}},
                        "cat_status": {"terms": {"field": "cat",
                                                 "size": 5},
                                       "aggs": {"st": {"terms": {
                                           "field": "status"},
                                           "aggs": {"b": {"sum": {
                                               "field": "bytes"}}}}}},
                        "services": {"cardinality": {"field": "cat"}},
                        "tiers": {"range": {"field": "bytes", "ranges": [
                            {"to": 1 << 14}, {"from": 1 << 14,
                                              "to": 1 << 18},
                            {"from": 1 << 18}]}}}}

        # distinct range bounds per query defeat the shard request cache
        # while the agg-plan cache (scrubbed bounds) still hits
        los = rng.integers(0, 1 << 10, size=40)
        for lo in los[:5]:
            node.search("dash", body(lo))  # warm: columns + aggs.* grid
        mark = _dispatch_mark()
        dev_lats = []
        dev_resps = []
        for lo in los:
            t0 = time.perf_counter()
            dev_resps.append(node.search("dash", body(lo)))
            dev_lats.append((time.perf_counter() - t0) * 1000)
        disp = _dispatch_delta(mark)
        eng = node._aggs["dash"][1]
        agg_stats = {k: eng.stats[k] for k in
                     ("device_nodes", "host_nodes", "plan_cache_hits",
                      "plan_cache_misses", "mesh_dispatches")}
        agg_stats["fallback_reasons"] = {
            r: dict(ent) for r, ent in
            eng.stats["fallback_reasons"].items()}
        routed = agg_stats["device_nodes"] + agg_stats["host_nodes"]
        device_ratio = agg_stats["device_nodes"] / max(routed, 1)

        node.settings["search.aggs.device_enabled"] = "false"
        host_lats = []
        parity = True
        for lo, dresp in zip(los, dev_resps):
            t0 = time.perf_counter()
            hresp = node.search("dash", body(lo))
            host_lats.append((time.perf_counter() - t0) * 1000)
            d, h = dict(dresp), dict(hresp)
            d.pop("took", None), h.pop("took", None)
            if json.dumps(d, sort_keys=True) != json.dumps(h,
                                                           sort_keys=True):
                parity = False
        dev_p50 = float(np.percentile(dev_lats, 50))
        host_p50 = float(np.percentile(host_lats, 50))
        print(json.dumps({
            "config": "8_device_aggs_dashboard",
            "p50_ms": round(dev_p50, 2),
            "p99_ms": round(float(np.percentile(dev_lats, 99)), 2),
            "host_p50_ms": round(host_p50, 2),
            "speedup_vs_host": round(host_p50 / max(dev_p50, 1e-9), 2),
            "parity_vs_host": parity,
            "device_ratio": round(device_ratio, 3),
            "gate_device_ratio": device_ratio >= 0.9,
            "n_docs": n_docs,
            "aggs": agg_stats,
            "build_s": round(build_s, 1),
            "dispatch": disp}), flush=True)
    finally:
        node.close()


def run_retrieval_workloads(n_docs: int = 20_000, dims: int = 64):
    """Config 16: learned-sparse + late-interaction retrieval on the
    device kernel substrates (ops/sparse.py + ops/pallas_maxsim.py +
    vectors/late_interaction.py), on a token-bearing corpus shape the
    matrix didn't previously cover: every doc carries a `rank_features`
    weight map AND a ragged [2-8, dims] token matrix (int8 columnar
    blocks) AND a text body.

    Three rows: sparse-only (device `sparse.topk` vs the pure-host
    `weighted_tokens` walker, byte parity asserted), late-interaction-
    only (fused coarse+MaxSim vs the exact host MaxSim walker, recall@10
    gated), and the 3-leg rank.rrf hybrid (match + sparse + late legs
    through the fused plan executor, `gate_p99_le_3x_p50`). Each row
    carries its own dispatch delta — steady state must read compiles=0 —
    and rows on the CPU floor label interpret-mode/compile noise."""
    import os
    import tempfile

    import jax

    from elasticsearch_tpu.node import Node
    from elasticsearch_tpu.ops import dispatch

    if os.environ.get("BENCH_SMALL") == "1":
        n_docs = min(n_docs, 2_000)
    rng = np.random.default_rng(29)
    backend = jax.devices()[0].platform
    cpu_fallback = not dispatch.is_accelerator_backend()
    node = Node(tempfile.mkdtemp())
    try:
        node.create_index_with_templates("ret", mappings={"properties": {
            "body": {"type": "text"},
            "feats": {"type": "rank_features"},
            "colv": {"type": "rank_vectors", "dims": dims,
                     "encoding": "int8", "oversample": 8}}})
        vocab = [f"feat{i}" for i in range(2_000)]
        words = [f"w{i}" for i in range(500)]
        topics = rng.standard_normal((64, dims)).astype(np.float32)
        t0 = time.perf_counter()
        for c0 in range(0, n_docs, 2_000):
            ops = []
            for i in range(c0, min(c0 + 2_000, n_docs)):
                nt = int(rng.integers(2, 9))
                toks = (topics[i % 64]
                        + 0.6 * rng.standard_normal((nt, dims))) \
                    .astype(np.float32)
                ops.append({"index": {"_index": "ret", "_id": str(i)}})
                ops.append({
                    "body": " ".join(rng.choice(words, 6)),
                    "feats": {v: float(rng.uniform(0.05, 8.0))
                              for v in rng.choice(vocab, 5,
                                                  replace=False)},
                    "colv": toks.tolist()})
            node.bulk(ops)
        node.indices.get("ret").force_merge()
        node.indices.get("ret").refresh()
        build_s = time.perf_counter() - t0

        svc = node.indices.get("ret")
        reader = svc.combined_reader()
        ex = node._hybrid_executor(svc)
        n_q = 40

        def sparse_q(i):
            return {vocab[int(v)]: float(rng.uniform(0.5, 3.0))
                    for v in rng.integers(0, 2_000, 4)}

        # ---- row 1: learned sparse, device kernel vs host walker ----
        sqs = [sparse_q(i) for i in range(n_q)]
        for q in sqs[:5]:
            ex.sparse.search_batch(reader, "feats", [(q, 1.0)], 100,
                                   route="device")
        mark = _dispatch_mark()
        dev_lats, dev_out = [], []
        for q in sqs:
            t1 = time.perf_counter()
            out = ex.sparse.search_batch(reader, "feats", [(q, 1.0)],
                                         100, route="device")
            dev_lats.append((time.perf_counter() - t1) * 1000)
            dev_out.append(out[0])
        disp = _dispatch_delta(mark)
        host_lats = []
        parity = True
        for q, (drows, dscores) in zip(sqs, dev_out):
            t1 = time.perf_counter()
            resp = node.search("ret", {
                "query": {"sparse_vector": {"field": "feats",
                                            "query_vector": q}},
                "size": 100})
            host_lats.append((time.perf_counter() - t1) * 1000)
            hids = [h["_id"] for h in resp["hits"]["hits"]]
            dids = [reader.get_id(int(r)) for r in drows[:len(hids)]]
            if dids != hids:
                parity = False
        dev_p50 = float(np.percentile(dev_lats, 50))
        host_p50 = float(np.percentile(host_lats, 50))
        print(json.dumps({
            "config": "16_retrieval_workloads", "row": "sparse_only",
            "p50_ms": round(dev_p50, 2),
            "p99_ms": round(float(np.percentile(dev_lats, 99)), 2),
            "host_walker_p50_ms": round(host_p50, 2),
            "speedup_vs_host": round(host_p50 / max(dev_p50, 1e-9), 2),
            "parity_vs_host": parity,
            "gate_zero_steady_compiles": disp["compiles"] == 0,
            "n_docs": n_docs, "backend": backend,
            **({"cpu_fallback": True} if cpu_fallback else {}),
            "dispatch": disp, "build_s": round(build_s, 1),
            **_compile_noise_label(disp)}), flush=True)

        # ---- row 2: late interaction, fused rescore vs exact oracle --
        mapper = svc.mapper_service.get("colv")
        lqs = []
        for i in range(n_q):
            t = topics[int(rng.integers(64))]
            lqs.append((t + 0.3 * rng.standard_normal((4, dims)))
                       .astype(np.float32))
        for qt in lqs[:5]:
            ex.late.search_batch(reader, mapper, [(qt, 1.0)], 10)
        mark = _dispatch_mark()
        dev_lats, dev_rows = [], []
        for qt in lqs:
            t1 = time.perf_counter()
            (rows, _), = ex.late.search_batch(reader, mapper,
                                              [(qt, 1.0)], 10)
            dev_lats.append((time.perf_counter() - t1) * 1000)
            dev_rows.append(rows)
        disp = _dispatch_delta(mark)
        host_lats, hits = [], 0
        for qt, drows in zip(lqs, dev_rows):
            t1 = time.perf_counter()
            resp = node.search("ret", {
                "query": {"late_interaction": {
                    "field": "colv", "query_tokens": qt.tolist()}},
                "size": 10})
            host_lats.append((time.perf_counter() - t1) * 1000)
            oids = {h["_id"] for h in resp["hits"]["hits"]}
            hits += len({reader.get_id(int(r))
                         for r in drows.tolist()} & oids)
        recall = hits / (n_q * 10)
        dev_p50 = float(np.percentile(dev_lats, 50))
        host_p50 = float(np.percentile(host_lats, 50))
        lf = ex.late.field(reader, mapper)
        print(json.dumps({
            "config": "16_retrieval_workloads",
            "row": "late_interaction_only",
            "p50_ms": round(dev_p50, 2),
            "p99_ms": round(float(np.percentile(dev_lats, 99)), 2),
            "host_walker_p50_ms": round(host_p50, 2),
            "speedup_vs_host": round(host_p50 / max(dev_p50, 1e-9), 2),
            "recall_at_10_vs_exact": round(recall, 3),
            "gate_recall": recall >= 0.95,
            "gate_zero_steady_compiles": disp["compiles"] == 0,
            "encoding": lf.encoding, "cap": lf.cap,
            "coarse_window": lf.coarse_window(10),
            "tile_mb": round(lf.nbytes() / 1e6, 1),
            "n_docs": n_docs, "backend": backend,
            **({"cpu_fallback": True} if cpu_fallback else {}),
            "dispatch": disp,
            **_compile_noise_label(disp)}), flush=True)

        # ---- row 3: 3-leg rank.rrf hybrid through the fused plan ----
        def rrf_body(i):
            return {"rank": {"rrf": {}}, "sub_searches": [
                {"query": {"match": {"body": " ".join(
                    rng.choice(words, 2))}}},
                {"query": {"sparse_vector": {"field": "feats",
                                             "query_vector": sqs[i]}}},
                {"query": {"late_interaction": {
                    "field": "colv", "query_tokens": lqs[i].tolist(),
                    "k": 10}}}], "size": 10}

        for i in range(5):
            node.search("ret", rrf_body(i))
        mark = _dispatch_mark()
        lats = []
        for i in range(n_q):
            t1 = time.perf_counter()
            node.search("ret", rrf_body(i))
            lats.append((time.perf_counter() - t1) * 1000)
        disp = _dispatch_delta(mark)
        p50 = float(np.percentile(lats, 50))
        p99 = float(np.percentile(lats, 99))
        print(json.dumps({
            "config": "16_retrieval_workloads", "row": "hybrid_rrf_3leg",
            "p50_ms": round(p50, 2), "p99_ms": round(p99, 2),
            "gate_p99_le_3x_p50": bool(p99 <= 3 * p50),
            "gate_zero_steady_compiles": disp["compiles"] == 0,
            "plan_cache_hits": ex.stats["plan_cache_hits"],
            "plan_cache_misses": ex.stats["plan_cache_misses"],
            "sparse_grid_fallbacks": ex.stats["sparse_grid_fallbacks"],
            "maxsim_grid_fallbacks": ex.stats["maxsim_grid_fallbacks"],
            "n_docs": n_docs, "backend": backend,
            **({"cpu_fallback": True} if cpu_fallback else {}),
            "dispatch": disp,
            **_compile_noise_label(disp)}), flush=True)
    finally:
        node.close()


def run_ingest_while_search(n_seed: int = 200_000, d: int = 64,
                            docs_per_sec: int = 4000,
                            duration_s: float = 8.0,
                            refresh_interval_s: float = 0.25,
                            n_clients: int = 2):
    """Config 9: sustained ingest concurrent with closed-loop search —
    the writes-while-searching workload the generational segments
    subsystem exists for (`elasticsearch_tpu/segments/`).

    An ingest thread seals a new engine segment + refreshes every
    `refresh_interval_s` at a sustained doc rate while closed-loop
    clients search through the full serving path. The row records search
    p50/p99 DURING ingest, the worst single refresh stall (the
    pre-subsystem number here was a full corpus re-upload), seal/merge
    counters, and two gates:

      gate_no_rebuild_stall  zero full-corpus rebuilds in steady state
      parity_ok              at sampled points (ingest paused, snapshot
                             settled) the generational store's response
                             is byte-identical to a monolithic store
                             synced on the same reader — both pinned to
                             the DEVICE route, which is what the
                             generational fan-out replaces

    Runs (labeled) on CPU-fallback hosts like the other serving rows."""
    import os
    import tempfile

    from elasticsearch_tpu.node import Node
    from elasticsearch_tpu.serving.batcher import CostModel

    if os.environ.get("BENCH_SMALL") == "1":
        n_seed, docs_per_sec, duration_s = 30_000, 2000, 5.0

    rng = np.random.default_rng(29)
    node = Node(tempfile.mkdtemp())
    node.create_index_with_templates(
        "ing", mappings={"properties": {
            "v": {"type": "dense_vector", "dims": d}}})
    shard = node.indices.get("ing").shards[0]
    t0 = time.perf_counter()
    _inject_vector_segment(shard, "v",
                           rng.standard_normal((n_seed, d))
                           .astype(np.float32))
    node.indices.get("ing").refresh()
    build_s = time.perf_counter() - t0

    # the parity oracle and the serving store must take the same route:
    # pin the cost model off the host VNNI mirror for the bench's
    # duration (the generational fan-out replaces the DEVICE path)
    prefer_host = CostModel.prefer_host
    CostModel.prefer_host = staticmethod(lambda *a, **kw: False)
    try:
        _run_ingest_while_search_body(
            node, shard, rng, d, docs_per_sec, duration_s,
            refresh_interval_s, n_clients, n_seed, build_s)
    finally:
        # the patch must never leak into later configs — their routing
        # (and therefore their numbers) would silently change
        CostModel.prefer_host = prefer_host
        node.close()


def _run_ingest_while_search_body(node, shard, rng, d, docs_per_sec,
                                  duration_s, refresh_interval_s,
                                  n_clients, n_seed, build_s):
    import threading

    import jax

    from elasticsearch_tpu.vectors.store import VectorStoreShard

    mono = VectorStoreShard(segments_enabled=False,
                            host_mirror_max_bytes=0)
    vf = node.indices.get("ing").mapper_service.vector_fields()

    def body():
        return {"knn": {"field": "v",
                        "query_vector": rng.standard_normal(d)
                        .astype(np.float32).tolist(),
                        "k": 10, "num_candidates": 10},
                "size": 10, "_source": False}

    for _ in range(8):  # warm the serving grid before the timed window
        node.search("ing", body())

    from elasticsearch_tpu import columnar

    def _rss_bytes():
        import os as _os
        with open("/proc/self/statm") as f:
            return int(f.read().split()[1]) * _os.sysconf("SC_PAGESIZE")

    seg0 = shard.vector_store.segment_stats()
    col0 = columnar.STORE.stats()
    rss0 = _rss_bytes()
    rss_peak = [rss0]
    mark = _dispatch_mark()
    pause = threading.Event()      # sampler asks ingest to hold
    idle = threading.Event()       # ingest acknowledges (snapshot settled)
    stop = threading.Event()
    stalls, ingested, refreshes = [], [0], [0]
    batch = max(64, int(docs_per_sec * refresh_interval_s))

    def ingest():
        deadline = time.perf_counter() + duration_s
        while time.perf_counter() < deadline and not stop.is_set():
            if pause.is_set():
                idle.set()
                time.sleep(0.002)
                continue
            idle.clear()
            mat = rng.standard_normal((batch, d)).astype(np.float32)
            t1 = time.perf_counter()
            _inject_vector_segment(shard, "v", mat)
            node.indices.get("ing").refresh()   # seals the L0 delta
            stalls.append(time.perf_counter() - t1)
            ingested[0] += batch
            refreshes[0] += 1
            rss_peak[0] = max(rss_peak[0], _rss_bytes())
            budget = refresh_interval_s - (time.perf_counter() - t1)
            if budget > 0:
                time.sleep(budget)
        idle.set()

    lats: list = []
    lat_lock = threading.Lock()

    def client():
        while not stop.is_set():
            b = body()
            t1 = time.perf_counter()
            node.search("ing", b)
            dt = (time.perf_counter() - t1) * 1000
            with lat_lock:
                lats.append(dt)

    def sample_parity() -> bool:
        """Pause ingest on a settled snapshot and compare the live
        generational store against a monolithic sync of the SAME
        reader, byte for byte."""
        pause.set()
        idle.wait(timeout=5.0)
        try:
            reader = shard.engine.acquire_searcher()
            shard.vector_store.sync(reader, vf)   # settle (normally a noop)
            mono.sync(reader, vf)
            ok = True
            for _ in range(3):
                q = rng.standard_normal(d).astype(np.float32)
                a = shard.vector_store.search("v", q, 10)
                b2 = mono.search("v", q, 10)
                ok = ok and np.array_equal(a[0], b2[0]) \
                    and np.array_equal(a[1], b2[1])
            return ok
        finally:
            pause.clear()

    threads = [threading.Thread(target=ingest)]
    threads += [threading.Thread(target=client, daemon=True)
                for _ in range(n_clients)]
    for t in threads:
        t.start()
    parity_samples, parity_ok = 0, True
    sample_at = (0.35, 0.7)  # fractions of the run
    t_start = time.perf_counter()
    for frac in sample_at:
        wait = t_start + frac * duration_s - time.perf_counter()
        if wait > 0:
            time.sleep(wait)
        parity_ok = sample_parity() and parity_ok
        parity_samples += 1
    threads[0].join()
    stop.set()
    # one final settled sample after ingest completes
    parity_ok = sample_parity() and parity_ok
    parity_samples += 1
    for t in threads[1:]:
        t.join(timeout=2.0)

    gc = shard.vector_store._gens.get("v")
    if gc is not None:
        gc.drain(timeout_s=10.0)
    seg1 = shard.vector_store.segment_stats()
    col1 = columnar.STORE.stats()
    rebuilds = seg1["full_rebuilds"] - seg0["full_rebuilds"]
    # columnar segment-block-store ledger over the ingest window: the
    # O(delta) refresh claim as counters — extraction time actually
    # paid, and ZERO full-corpus compositions during append-only ingest
    # (`gate_delta_refresh`); peak host-RSS delta bounds the host-RAM
    # story (shared blocks, no per-generation host_vectors pins)
    full_extract_compositions = (col1["compositions"]["full"]
                                 - col0["compositions"]["full"])
    with lat_lock:
        arr = np.asarray(lats) if lats else np.zeros(1)
    wall = time.perf_counter() - t_start
    print(json.dumps({
        "config": "9_ingest_while_search",
        "backend": jax.devices()[0].platform,
        "n_seed": n_seed, "dims": d,
        "ingested_docs": ingested[0],
        "achieved_docs_per_sec": round(ingested[0] / max(wall, 1e-9), 1),
        "target_docs_per_sec": docs_per_sec,
        "refreshes": refreshes[0],
        "searches_during_ingest": len(arr),
        "search_p50_ms": round(float(np.percentile(arr, 50)), 2),
        "search_p99_ms": round(float(np.percentile(arr, 99)), 2),
        "max_refresh_stall_ms": round(max(stalls) * 1000, 2)
        if stalls else 0.0,
        "mean_refresh_stall_ms": round(
            float(np.mean(stalls)) * 1000, 2) if stalls else 0.0,
        "seed_build_s": round(build_s, 2),
        "seals": seg1["seals"] - seg0.get("seals", 0),
        "merges": seg1.get("merges", 0) - seg0.get("merges", 0),
        "merge_ms": round((seg1.get("merge_nanos", 0)
                           - seg0.get("merge_nanos", 0)) / 1e6, 1),
        "generations_final": seg1.get("generations", 0),
        "tombstoned_rows": seg1.get("tombstoned_rows", 0),
        "full_rebuilds": rebuilds,
        "rebuilds_avoided": seg1["rebuilds_avoided"]
        - seg0["rebuilds_avoided"],
        "parity_samples": parity_samples,
        "parity_vs_monolithic": bool(parity_ok),
        "gate_no_rebuild_stall": bool(rebuilds == 0 and parity_ok),
        "refresh_extract_ms": round(
            (col1["extract_nanos"] - col0["extract_nanos"]) / 1e6, 2),
        "block_extracts": col1["extracts"] - col0["extracts"],
        "block_cache_hits": col1["hits"] - col0["hits"],
        "full_corpus_extracts": full_extract_compositions,
        "columnar_blocks_final": col1["blocks"],
        "columnar_block_bytes_final": col1["bytes"],
        "peak_rss_delta_mb": round(
            max(rss_peak[0] - rss0, 0) / 1e6, 1),
        "gate_delta_refresh": bool(full_extract_compositions == 0),
        "dispatch": _dispatch_delta(mark)}), flush=True)


def _run_on_simulated_mesh(config_name: str, child_flag: str, body,
                           min_devices: int):
    """Shared re-exec scaffold for mesh bench configs: run `body(
    simulated)` when this process already sees `min_devices` devices,
    otherwise re-exec this script with 8 virtual XLA host devices under
    `child_flag` and relabel every emitted JSON row `simulated_mesh:
    true` — those rows validate program structure (partitioning, merge,
    compile-cache, scheduling), NOT ICI bandwidth, so their qps/p50
    columns are not comparable to real-mesh captures."""
    import os
    import subprocess
    import sys

    import jax

    n_dev = len(jax.devices())
    if n_dev >= min_devices:
        body(simulated=os.environ.get("BENCH_MESH_CHILD") == "1")
        return
    if os.environ.get("BENCH_MESH_CHILD") == "1":
        # the re-exec failed to take (XLA flag landed after backend init)
        print(json.dumps({"config": config_name,
                          "error": "simulated mesh re-exec still sees "
                                   f"{n_dev} device(s)"}), flush=True)
        return
    env = dict(os.environ)
    env["XLA_FLAGS"] = (env.get("XLA_FLAGS", "")
                        + " --xla_force_host_platform_device_count=8")
    env["JAX_PLATFORMS"] = "cpu"
    env["BENCH_MESH_CHILD"] = "1"
    proc = subprocess.run(
        [sys.executable, os.path.abspath(__file__), child_flag],
        env=env, capture_output=True, text=True, timeout=3600)
    emitted = 0
    for line in proc.stdout.splitlines():
        try:
            row = json.loads(line)
        except (json.JSONDecodeError, ValueError):
            print(line, file=sys.stderr, flush=True)
            continue
        row["simulated_mesh"] = True
        print(json.dumps(row), flush=True)
        emitted += 1
    if proc.returncode != 0 or emitted == 0:
        tail = (proc.stderr or "").strip().splitlines()[-1:] or [""]
        print(json.dumps({"config": config_name,
                          "error": "simulated mesh subprocess failed "
                                   f"(rc={proc.returncode})",
                          "stderr_tail": tail[0][:200]}), flush=True)


def run_sharded_fused():
    """Config 6: the mesh-sharded serving path (PR 5) — exact kNN, IVF,
    and the fused hybrid plan each executing as ONE shard_map program
    with an ICI all-gather merge, plus parity-vs-single-device on every
    variant (re-exec'd onto 8 virtual devices when needed)."""
    _run_on_simulated_mesh("6_sharded_fused_spmd", "--sharded-only",
                           _sharded_rows, min_devices=2)


def _sharded_rows(simulated: bool):
    """The config-6 measurement body; runs under a jax that sees >=2
    devices (a real mesh, or the forced-host-device-count child)."""
    import os

    import jax
    import jax.numpy as jnp

    from elasticsearch_tpu.ops import knn as knn_ops
    from elasticsearch_tpu.parallel import mesh as mesh_lib
    from elasticsearch_tpu.parallel.sharded_knn import (
        ShardedFieldState, distributed_knn_search)

    small = simulated or os.environ.get("BENCH_SMALL") == "1"
    shards = min(len(jax.devices()), 8)
    mesh = mesh_lib.make_mesh(num_shards=shards, dp=1)
    base = {"shards": shards, "merge": "ici_all_gather_one_program"}
    if simulated:
        # program-structure capture on virtual host devices: says so on
        # the row (BENCH methodology: no ICI, don't compare throughput)
        base["measures"] = "program_structure_not_ici"

    # -- exact kNN -------------------------------------------------------
    n, d = (131_072 if small else 1_000_000), 128
    rng = np.random.default_rng(11)
    centers = rng.standard_normal((128, d)).astype(np.float32) * 2.0
    vectors = (centers[rng.integers(0, 128, size=n)]
               + rng.standard_normal((n, d)).astype(np.float32))
    state = ShardedFieldState(vectors, mesh, "cosine", "bf16")
    nq = BATCH * 16
    queries = (vectors[rng.integers(0, n, size=nq)]
               + 0.3 * rng.standard_normal((nq, d)).astype(np.float32))

    def fn(qb, c, kk):
        return distributed_knn_search(qb, c, kk, mesh, metric="cosine")

    qps, marginal, p50, p99, _ = _measure(
        _scan_searcher(fn), state.corpus, queries, d, n_small=4,
        n_large=16)
    # parity leg runs through the DISPATCHED path (the one serving uses)
    q0 = jax.device_put(jnp.asarray(queries[:BATCH]),
                        state.query_sharding())
    s_mesh, gids = distributed_knn_search(q0, state.corpus, K, mesh,
                                          metric="cosine")
    rows_mesh = state.map_ids(np.asarray(gids))
    one_corpus = knn_ops.build_corpus(vectors, metric="cosine",
                                      dtype="bf16")
    s_one, rows_one = knn_ops.knn_search(
        jnp.asarray(queries[:BATCH]), one_corpus, k=K, metric="cosine")
    parity = bool(np.array_equal(rows_mesh, np.asarray(rows_one)))
    print(json.dumps({"config": "6_sharded_fused_spmd",
                      "qps": round(qps, 1),
                      "batch_ms": round(marginal * 1000, 3),
                      "p50_ms": round(p50, 1), "p99_ms": round(p99, 1),
                      "n_docs": n, "dims": d, "dtype": "bf16",
                      "parity_vs_single_device": parity,
                      "recall_vs_single_device": round(
                          _recall(rows_mesh, np.asarray(rows_one)), 4),
                      **base}), flush=True)
    del state, one_corpus, vectors

    # -- IVF -------------------------------------------------------------
    from elasticsearch_tpu.ann import IVFRouter, build_ivf_index

    n_ivf, nlist = (32_768, 128) if small else (1_000_000, 1024)
    vectors = (centers[rng.integers(0, 128, size=n_ivf)]
               + rng.standard_normal((n_ivf, d)).astype(np.float32))
    index = build_ivf_index(vectors, metric="cosine", nlist=nlist, seed=0)
    router = IVFRouter(index, nprobe="auto")
    nprobe = router.effective_nprobe(K)
    qs = (vectors[rng.integers(0, n_ivf, size=BATCH)]
          + 0.3 * rng.standard_normal((BATCH, d)).astype(np.float32))
    s_mesh, rows_mesh, phases = router.search(qs, K, nprobe=nprobe,
                                              mesh=mesh)
    mark = _dispatch_mark()
    lats = []
    for _ in range(10):
        t0 = time.perf_counter()
        s_mesh, rows_mesh, phases = router.search(qs, K, nprobe=nprobe,
                                                  mesh=mesh)
        lats.append((time.perf_counter() - t0) * 1000)
    p50 = float(np.percentile(lats, 50))
    disp = _dispatch_delta(mark)  # before the single-device parity leg
    s_one, rows_one, _ = router.search(qs, K, nprobe=nprobe)
    print(json.dumps({"config": "6_sharded_ivf",
                      "qps": round(BATCH / (p50 / 1000), 1),
                      "p50_ms": round(p50, 1),
                      "p99_ms": round(float(np.percentile(lats, 99)), 1),
                      "n_docs": n_ivf, "dims": d, "nlist": nlist,
                      "nprobe": nprobe, "engine": phases.get("engine"),
                      "parity_vs_single_device": bool(
                          np.array_equal(rows_mesh, rows_one)
                          and s_mesh.tobytes() == s_one.tobytes()),
                      "dispatch": disp, **base}),
          flush=True)
    del index, router, vectors

    # -- hybrid (BM25 + kNN + RRF through Node.search) -------------------
    import tempfile

    from elasticsearch_tpu.node import Node
    from elasticsearch_tpu.parallel import policy

    n_docs, dims = (4_000, 64) if small else (100_000, 768)
    policy.reset(full=True)
    policy.configure(enabled=True, num_shards=shards, min_rows=1)
    node = Node(tempfile.mkdtemp())
    try:
        node.create_index_with_templates(
            "hybrid", mappings={"properties": {
                "body": {"type": "text"},
                "v": {"type": "dense_vector", "dims": dims}}})
        vocab = np.array([f"tok{i}" for i in range(5_000)])
        zipf = (rng.zipf(1.25, size=n_docs * 8) - 1) % 5_000
        pos = 0
        for c0 in range(0, n_docs, 2000):
            ops = []
            for i in range(c0, min(c0 + 2000, n_docs)):
                ops.append({"index": {"_index": "hybrid",
                                      "_id": str(i)}})
                ops.append({"body": " ".join(vocab[zipf[pos:pos + 8]]),
                            "v": rng.standard_normal(dims)
                            .astype(np.float32).tolist()})
                pos += 8
            node.bulk(ops)
        node.indices.get("hybrid").force_merge()

        def rand_body():
            terms = vocab[(rng.zipf(1.25, size=2) - 1) % 5_000]
            return {"rank": {"rrf": {"rank_constant": 60,
                                     "rank_window_size": 50}},
                    "query": {"match": {"body": " ".join(terms)}},
                    "knn": {"field": "v",
                            "query_vector": rng.standard_normal(dims)
                            .astype(np.float32).tolist(),
                            "k": 50, "num_candidates": 50},
                    "size": 10, "_source": False}

        bodies = [rand_body() for _ in range(30)]
        for b in bodies[:5]:
            node.search("hybrid", json.loads(json.dumps(b)))
        mark = _dispatch_mark()
        mesh_before = policy.stats()
        lats, mesh_resps = [], []
        for b in bodies:
            t0 = time.perf_counter()
            mesh_resps.append(node.search("hybrid",
                                          json.loads(json.dumps(b))))
            lats.append((time.perf_counter() - t0) * 1000)
        mesh_routes = (policy.stats()["router"]["mesh"]
                       - mesh_before["router"]["mesh"])
        disp = _dispatch_delta(mark)  # before the single-device replay
        # parity: identical bodies with the mesh router off must produce
        # byte-identical responses (modulo took)
        policy.configure(enabled=False)
        parity = True
        for b, mresp in zip(bodies, mesh_resps):
            oresp = node.search("hybrid", json.loads(json.dumps(b)))
            mresp, oresp = dict(mresp), dict(oresp)
            mresp.pop("took", None), oresp.pop("took", None)
            if json.dumps(mresp, sort_keys=True) != \
                    json.dumps(oresp, sort_keys=True):
                parity = False
                break
        print(json.dumps({
            "config": "6_sharded_hybrid_rrf",
            "qps": round(len(bodies) / (sum(lats) / 1000), 1),
            "p50_ms": round(float(np.percentile(lats, 50)), 2),
            "p99_ms": round(float(np.percentile(lats, 99)), 2),
            "n_docs": n_docs, "dims": dims,
            "mesh_routed_legs": mesh_routes,
            "parity_vs_single_device": parity,
            "execution": "fused_hybrid_plan_spmd",
            "dispatch": disp, **base}), flush=True)
    finally:
        node.close()
        policy.reset(full=True)


def run_dp_replicated():
    """Config 6 dp row: replicated mesh serving (PR 11) — closed-loop
    qps sweep over dp ∈ {1, 2, 4} on the 8-device mesh at EQUAL corpus,
    `parity_vs_single_device` per row, per-row dispatch deltas (the
    timed loop must compile nothing), and the `gate_500qps` wiring
    (re-exec'd onto 8 virtual devices when needed — those rows measure
    scheduling concurrency and program shape, not ICI bandwidth)."""
    _run_on_simulated_mesh("6_dp_replicated", "--dp-only",
                           _dp_replicated_rows, min_devices=8)


def _dp_replicated_rows(simulated: bool, n: int = 4096, d: int = 64,
                        batch: int = 64, k: int = 256,
                        n_clients: int = 4, per_client: int = 30):
    """The dp sweep body (needs >= 8 devices). Interactive merge-heavy
    shape on purpose: the [S, Q, k] all-gather merge replicates on
    every participating device, so the dp win on a shared-core
    simulated mesh comes from smaller per-group boards + overlapped
    launches — the scheduling-concurrency story the row documents.
    `simulated` is the re-exec scaffold's body contract; the dp sweep
    runs the same (small) shape on real and simulated meshes, and the
    parent labels simulated rows."""
    del simulated
    import threading

    import jax
    import jax.numpy as jnp

    from elasticsearch_tpu.ops import knn as knn_ops
    from elasticsearch_tpu.parallel import mesh as mesh_lib
    from elasticsearch_tpu.parallel import policy
    from elasticsearch_tpu.parallel.sharded_knn import (
        ShardedFieldState, distributed_knn_search)

    rng = np.random.default_rng(31)
    vectors = rng.standard_normal((n, d)).astype(np.float32)
    queries = rng.standard_normal((256, d)).astype(np.float32)
    parity_queries = queries[:batch]
    # single-device oracle at the serving dtype (byte-comparable)
    one_corpus = knn_ops.build_corpus(vectors, metric="cosine",
                                      dtype="bf16")
    s_ref, i_ref = knn_ops.knn_search(
        jnp.asarray(parity_queries), one_corpus, k=k, metric="cosine")
    s_ref, i_ref = np.asarray(s_ref), np.asarray(i_ref)

    base = {"shards_times_dp": 8, "n_docs": n, "dims": d, "batch": batch,
            "k": k, "concurrent_clients": n_clients,
            "measures": "scheduling_concurrency_not_ici"}
    results = {}
    try:
        for dp in (1, 2, 4):
            policy.reset(full=True)
            policy.configure(enabled=True, dp=dp, num_shards=8 // dp,
                             min_rows=1)
            mesh = policy.serving_mesh()
            state = ShardedFieldState(vectors, mesh, "cosine", "bf16")
            inflight = [0]
            lock = threading.Lock()

            def one(qs, state=state, dp=dp):
                # the live load signal a serving store would feed the
                # router (queued + in-flight dispatches)
                with lock:
                    depth = inflight[0]
                    inflight[0] += 1
                try:
                    route = policy.decide("knn", n, batch=batch,
                                          queue_depth=depth)
                    q = jax.device_put(jnp.asarray(qs),
                                       mesh_lib.query_sharding(route))
                    s, g = distributed_knn_search(
                        q, state.corpus_for(route), k, route,
                        metric="cosine")
                    g.block_until_ready()
                    return s, g, state
                finally:
                    with lock:
                        inflight[0] -= 1
            # deterministic route warmup: the router picks the full
            # mesh when idle and a dp group under pressure, so warm
            # BOTH route families explicitly (each group's view + its
            # executable) — the timed loop must compile nothing
            for route in [mesh] + list(policy.dp_groups()):
                qw = jax.device_put(jnp.asarray(parity_queries),
                                    mesh_lib.query_sharding(route))
                _, gw = distributed_knn_search(
                    qw, state.corpus_for(route), k, route,
                    metric="cosine")
                gw.block_until_ready()
            mark = _dispatch_mark()
            policy.reset()                # clean route counters per row

            def client():
                for i in range(per_client):
                    lo = (i * batch) % (256 - batch)
                    one(queries[lo: lo + batch])

            threads = [threading.Thread(target=client)
                       for _ in range(n_clients)]
            t0 = time.perf_counter()
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            wall = time.perf_counter() - t0
            disp = _dispatch_delta(mark)
            s, g, st = one(parity_queries)
            rows = st.map_ids(np.asarray(g))
            parity = bool(np.array_equal(rows, i_ref)
                          and np.asarray(s).tobytes() == s_ref.tobytes())
            qps = n_clients * per_client * batch / wall
            results[dp] = (qps, parity, disp)
            row = {"config": "6_dp_replicated", "dp": dp,
                   "num_shards": 8 // dp, "qps": round(qps, 1),
                   "parity_vs_single_device": parity,
                   "router_dp": policy.stats()["router"]["dp"],
                   **_compile_noise_label(disp),
                   "dispatch": disp, **base}
            print(json.dumps(row), flush=True)
    finally:
        policy.reset(full=True)
    q1, q4 = results[1][0], results[4][0]
    print(json.dumps({
        "config": "6_dp_replicated_summary",
        "qps_dp1": round(q1, 1), "qps_dp2": round(results[2][0], 1),
        "qps_dp4": round(q4, 1),
        "speedup_dp4_vs_dp1": round(q4 / max(q1, 1e-9), 2),
        "gate_dp4_ge_2x_dp1": bool(q4 >= 2.0 * q1),
        "gate_500qps": bool(q4 >= 500),
        "parity_all_rows": bool(all(p for _, p, _ in results.values())),
        "zero_timed_loop_compiles": bool(all(
            disp["compiles"] == 0 for _, _, disp in results.values())),
        **base}), flush=True)


def run_fanout_node_kill(pre_ms: int = 4_000, post_ms: int = 12_000,
                         n_docs: int = 240, shards: int = 4,
                         n_clients: int = 4):
    """Config 10: kill a node mid-closed-loop during sustained ingest and
    require p99 and result-completeness to DEGRADE GRACEFULLY rather than
    cliff (the scenario gate from the ROADMAP's cross-node item).

    Runs a 3-node cluster on the deterministic simulator with the fault-
    injection transport (testing/faults.py): closed-loop search clients +
    a steady write ticker, then `kill_node` on a data holder. Latencies
    are VIRTUAL transport milliseconds (seeded 1-50ms per hop) — the row
    measures the coordination/fan-out behavior (timers, partial results,
    ARS rerouting, master eviction), not kernel throughput, and labels
    itself `virtual_time: true` accordingly.

    Gates:
      gate_no_hang            every in-flight search completes; the
                              client loops never stall
      gate_no_error_cliff     zero error responses — degradation shows
                              as `timed_out` partials, never exceptions
      gate_p99_bounded        post-kill p99 <= pre-kill p99 + query
                              budget + grace + slack (the labeled bound:
                              a dead node costs at most one budget)
      gate_completeness_recovers  the final post-kill window serves full
                              `_shards` coverage again (ARS reroute +
                              master eviction + replica promotion)
    """
    import os as _os
    import shutil
    import tempfile

    from elasticsearch_tpu.cluster.cluster_node import ClusterNode
    from elasticsearch_tpu.cluster.coordination import bootstrap_state
    from elasticsearch_tpu.cluster.state import ShardRoutingEntry
    from elasticsearch_tpu.testing.deterministic import (
        DeterministicTaskQueue, DisruptableTransport)
    from elasticsearch_tpu.testing.faults import FaultInjectingTransport

    query_budget_ms, grace_ms = 400, 100
    queue = DeterministicTaskQueue(seed=23)
    faults = FaultInjectingTransport(DisruptableTransport(queue),
                                     scheduler=queue)
    tmp = tempfile.mkdtemp()
    ids = ["n0", "n1", "n2"]
    initial = bootstrap_state(ids)
    # replication budget down from 30s: the bench window is 16s virtual,
    # and a write stalled on a dead replica must resolve inside it
    saved_repl = ClusterNode._REPLICATION_BUDGET_MS
    ClusterNode._REPLICATION_BUDGET_MS = 3_000
    nodes = {nid: ClusterNode(nid, _os.path.join(tmp, nid), faults, queue,
                              [p for p in ids if p != nid], initial)
             for nid in ids}
    try:
        for n in nodes.values():
            n.start()
        for _ in range(600):
            queue.run_for(200)
            masters = [n for n in nodes.values() if n.is_master]
            if masters and len(masters[0].cluster_state.nodes) == 3:
                break
        coord = nodes["n0"]

        def call(fn, *args, **kw):
            box = {}
            fn(*args, **kw, on_done=lambda r: box.update(r=r))
            for _ in range(600):
                queue.run_for(200)
                if "r" in box:
                    return box["r"]
            raise RuntimeError(f"no response from {fn.__name__}")

        call(coord.client_create_index, "kill",
             settings={"index.number_of_shards": shards,
                       "index.number_of_replicas": 1},
             mappings={"properties": {"title": {"type": "text"},
                                      "n": {"type": "long"}}})

        def all_started():
            rs = coord.cluster_state.shards_of("kill")
            return bool(rs) and all(
                r.state == ShardRoutingEntry.STARTED for r in rs)

        for _ in range(600):
            queue.run_for(200)
            if all_started():
                break
        call(coord.client_update_settings,
             {"search.fanout.query_budget_ms": query_budget_ms,
              "search.fanout.fetch_budget_ms": query_budget_ms,
              "search.fanout.deadline_grace_ms": grace_ms})
        for i in range(n_docs):
            call(coord.client_write, "kill",
                 {"type": "index", "id": f"d{i}",
                  "source": {"title": f"doc {i}", "n": i}})
        call(coord.client_refresh, "kill")

        # victim: a non-master data holder that is not the coordinator
        master_id = next(n.node_id for n in nodes.values() if n.is_master)
        held = {}
        for r in coord.cluster_state.shards_of("kill"):
            if r.state == ShardRoutingEntry.STARTED and r.node_id:
                held.setdefault(r.node_id, 0)
                held[r.node_id] += 1
        victim = next(nid for nid in sorted(held)
                      if nid not in (coord.node_id, master_id))

        # sustained ingest: one write every 40 virtual ms, fire-and-forget
        ingest = {"sent": 0, "acked": 0}

        def write_tick():
            i = ingest["sent"]
            ingest["sent"] += 1
            coord.client_write(
                "kill", {"type": "index", "id": f"w{i}",
                         "source": {"title": f"live {i}", "n": i}},
                on_done=lambda r: ingest.__setitem__(
                    "acked", ingest["acked"] + 1),
                on_failure=lambda e: None)
            queue.schedule_in(40, write_tick, "bench_ingest")

        # closed-loop search clients: issue, record, immediately re-issue
        # (t_done_ms, took_ms, ok_shards, total, timed_out, err, client)
        records = []
        inflight = {"n": 0}

        def issue(client_id):
            t0 = queue.now_ms
            inflight["n"] += 1

            def done(resp):
                inflight["n"] -= 1
                err = "error" in resp
                sh = resp.get("_shards") or {}
                records.append((queue.now_ms, queue.now_ms - t0,
                                sh.get("successful", 0),
                                sh.get("total", shards),
                                bool(resp.get("timed_out")), err,
                                client_id))
                queue.schedule_in(5, lambda: issue(client_id),
                                  f"bench_client:{client_id}")

            coord.client_search("kill", {"query": {"match_all": {}},
                                         "size": 10}, done)

        write_tick()
        for ci in range(n_clients):
            issue(ci)
        queue.run_for(pre_ms)
        kill_at = queue.now_ms
        pre = [r for r in records]
        # the kill must hit a node that is actually SERVING: drop the
        # victim from the coordinator's ARS table so adaptive replica
        # selection probes it first (unmeasured copies rank ahead) —
        # otherwise a victim that happened to rank behind its peers at
        # kill time never sees a query and the degradation gates are
        # vacuous
        getattr(coord, "_ars_ewma", {}).pop(victim, None)
        faults.kill_node(victim)
        queue.run_for(post_ms)
        post = [r for r in records if r[0] > kill_at]

        def pct(rows, q):
            if not rows:
                return 0.0
            return float(np.percentile(np.asarray(
                [r[1] for r in rows], dtype=np.float64), q))

        pre_p50, pre_p99 = pct(pre, 50), pct(pre, 99)
        post_p50, post_p99 = pct(post, 50), pct(post, 99)
        completeness = [r[2] / max(r[3], 1) for r in post]
        final_window = [r[2] / max(r[3], 1) for r in post
                        if r[0] > kill_at + post_ms - 2_000]
        errors = sum(1 for r in records if r[5])
        partials = sum(1 for r in post if r[4])
        bound_ms = pre_p99 + query_budget_ms + grace_ms + 200
        row = {
            "config": "10_fanout_node_kill",
            "virtual_time": True,
            "n_docs": n_docs, "shards": shards, "replicas": 1,
            "n_clients": n_clients, "victim": victim,
            "searches_pre": len(pre), "searches_post": len(post),
            "pre_p50_ms": round(pre_p50, 1),
            "pre_p99_ms": round(pre_p99, 1),
            "post_p50_ms": round(post_p50, 1),
            "post_p99_ms": round(post_p99, 1),
            "p99_bound_ms": round(bound_ms, 1),
            "timed_out_partials": partials,
            "error_responses": errors,
            "completeness_min": round(min(completeness), 3)
            if completeness else 0.0,
            "completeness_final_window": round(
                sum(final_window) / len(final_window), 3)
            if final_window else 0.0,
            "ingest_sent": ingest["sent"], "ingest_acked": ingest["acked"],
            "remote_sheds": {nid: dict(n.fanout_stats.remote)
                             for nid, n in nodes.items()},
            # no-hang means EVERY client's loop is still advancing in the
            # FINAL post-kill window — a single stuck client must fail
            # the gate even while the other loops keep populating `post`
            "gate_no_hang": bool(post and all(
                any(r[6] == ci and r[0] > kill_at + post_ms - 2_000
                    for r in post)
                for ci in range(n_clients))),
            "gate_no_error_cliff": bool(errors == 0),
            "gate_p99_bounded": bool(post_p99 <= bound_ms),
            "gate_completeness_recovers": bool(
                final_window and
                sum(final_window) / len(final_window) >= 0.999),
        }
        row["gate_graceful_degradation"] = bool(
            row["gate_no_hang"] and row["gate_no_error_cliff"]
            and row["gate_p99_bounded"]
            and row["gate_completeness_recovers"] and partials > 0)
        print(json.dumps(row), flush=True)
    finally:
        ClusterNode._REPLICATION_BUDGET_MS = saved_repl
        for n in nodes.values():
            try:
                if not n.coordinator.stopped:
                    n.stop()
            except Exception:
                pass
        shutil.rmtree(tmp, ignore_errors=True)


def run_kill_and_replace(pre_ms: int = 4_000, green_max_ms: int = 120_000,
                         settle_ms: int = 4_000, n_docs: int = 96,
                         n_clients: int = 3):
    """Config 14: kill a copy-holding node mid-closed-loop, join a FRESH
    node, and measure the durable-elasticity contract (ISSUE 17): how
    long until the cluster is green again, how deep the completeness dip
    goes and that it recovers to 1.0, that the replacement copy is built
    from shipped blocks rather than re-ingest (`segment_counters`
    full-rebuilds stay flat everywhere, `gate_no_reingest`), and that a
    pinned knn query serves byte-identical results after recovery.

    Same virtual-time regime as config 10 (seeded 1-50ms transport hops,
    `virtual_time: true`): the row measures recovery orchestration —
    block manifest diff, chunked block transfer, translog tail replay,
    warm finalize — not kernel throughput.

    Gates:
      gate_time_to_green      kill -> every copy STARTED on live nodes
                              within `green_max_ms` virtual ms
      gate_completeness_dips  the kill was actually felt: at least one
                              post-kill window saw partial coverage
      gate_completeness_recovers  the final window serves full coverage
      gate_no_reingest        full_rebuilds delta == 0 on survivors AND
                              the replacement (blocks, not re-encode)
      gate_blocks_shipped     the replacement's recovery shipped > 0
                              blocks (the block path ran, ops-only
                              replay of a flushed shard is impossible)
      gate_byte_identical     the pinned knn query returns identical
                              (id, score) lists before and after
    """
    import os as _os
    import shutil
    import tempfile

    import jax

    from elasticsearch_tpu.cluster.cluster_node import ClusterNode
    from elasticsearch_tpu.cluster.coordination import bootstrap_state
    from elasticsearch_tpu.cluster.state import ShardRoutingEntry
    from elasticsearch_tpu.testing.deterministic import (
        DeterministicTaskQueue, DisruptableTransport)
    from elasticsearch_tpu.testing.faults import FaultInjectingTransport

    dims = 16
    queue = DeterministicTaskQueue(seed=37)
    faults = FaultInjectingTransport(DisruptableTransport(queue),
                                     scheduler=queue)
    tmp = tempfile.mkdtemp()
    ids = ["n0", "n1", "n2"]
    initial = bootstrap_state(ids)
    saved_repl = ClusterNode._REPLICATION_BUDGET_MS
    ClusterNode._REPLICATION_BUDGET_MS = 3_000
    nodes = {nid: ClusterNode(nid, _os.path.join(tmp, nid), faults, queue,
                              [p for p in ids if p != nid], initial)
             for nid in ids}

    def vec(i):
        rng = np.random.default_rng(5000 + i)
        x = rng.standard_normal(dims)
        return [float(f) for f in x / np.linalg.norm(x)]

    try:
        for n in nodes.values():
            n.start()
        for _ in range(600):
            queue.run_for(200)
            masters = [n for n in nodes.values() if n.is_master]
            if masters and len(masters[0].cluster_state.nodes) == 3:
                break
        coord = nodes["n0"]

        def call(fn, *args, **kw):
            box = {}
            fn(*args, **kw, on_done=lambda r: box.update(r=r))
            for _ in range(600):
                queue.run_for(200)
                if "r" in box:
                    return box["r"]
            raise RuntimeError(f"no response from {fn.__name__}")

        # 1 shard x 2 replicas on 3 nodes: every node holds a copy, so
        # once one dies the joining FRESH node is the only legal home
        # for the replacement — the bench measures ITS block recovery,
        # not a spare survivor's
        call(coord.client_create_index, "elastic",
             settings={"index.number_of_shards": 1,
                       "index.number_of_replicas": 2},
             mappings={"properties": {
                 "n": {"type": "long"},
                 "v": {"type": "dense_vector", "dims": dims,
                       "index": True, "similarity": "dot_product",
                       "index_options": {"type": "int4_flat"}}}})

        def live_nodes():
            return {nid: n for nid, n in nodes.items()
                    if not n.coordinator.stopped}

        def all_green(exclude=()):
            rs = coord.cluster_state.shards_of("elastic")
            return bool(rs) and all(
                r.state == ShardRoutingEntry.STARTED
                and r.node_id not in exclude for r in rs)

        for _ in range(600):
            queue.run_for(200)
            if all_green():
                break
        # tight fanout budgets (config-10 regime): a dead copy shows as
        # a bounded timed-out partial, so the completeness dip is
        # visible instead of queries stalling on the victim
        call(coord.client_update_settings,
             {"search.fanout.query_budget_ms": 400,
              "search.fanout.fetch_budget_ms": 400,
              "search.fanout.deadline_grace_ms": 100})
        for i in range(n_docs):
            call(coord.client_write, "elastic",
                 {"type": "index", "id": f"d{i}",
                  "source": {"n": i, "v": vec(i)}})
        call(coord.client_refresh, "elastic")

        # flush every copy: the translog trims, so the replacement can
        # ONLY bootstrap through the block manifest path
        for n in live_nodes().values():
            sh = n.local_shards.get(("elastic", 0))
            if sh is not None:
                sh.engine.flush()

        # pinned identity query, captured before the kill
        knn_body = {"knn": {"field": "v", "query_vector": vec(9999),
                            "k": 5, "num_candidates": n_docs}, "size": 5}
        pre_hits = [(h["_id"], h["_score"]) for h in
                    call(coord.client_search, "elastic", dict(knn_body))
                    ["hits"]["hits"]]

        rebuilds_pre = {
            nid: n.local_shards[("elastic", 0)].vector_store
            .segment_counters["full_rebuilds"]
            for nid, n in live_nodes().items()
            if ("elastic", 0) in n.local_shards}

        # closed-loop clients: coverage tracking through the disruption
        records = []  # (t_done_ms, ok_shards, total_shards, err)

        def issue(client_id):
            def done(resp):
                sh = resp.get("_shards") or {}
                records.append((queue.now_ms, sh.get("successful", 0),
                                sh.get("total", 1), "error" in resp))
                queue.schedule_in(10, lambda: issue(client_id),
                                  f"bench_client:{client_id}")

            coord.client_search("elastic",
                                {"query": {"match_all": {}}, "size": 5},
                                done)

        for ci in range(n_clients):
            issue(ci)
        queue.run_for(pre_ms)

        # victim: a copy holder that is neither master nor coordinator
        master_id = next(n.node_id for n in nodes.values() if n.is_master)
        holders = {r.node_id for r in
                   coord.cluster_state.shards_of("elastic") if r.node_id}
        victim = next(nid for nid in sorted(holders)
                      if nid not in (coord.node_id, master_id))
        kill_at = queue.now_ms
        # rank the victim first in adaptive replica selection so the
        # kill hits copies that are actually serving (config-10 idiom)
        getattr(coord, "_ars_ewma", {}).pop(victim, None)
        faults.kill_node(victim)
        nodes[victim].stop()

        # the REPLACEMENT: a brand-new empty node joins the cluster
        fresh = ClusterNode("n9", _os.path.join(tmp, "n9"), faults, queue,
                            [nid for nid in live_nodes()],
                            coord.cluster_state)
        nodes["n9"] = fresh
        fresh.start()

        green_at = None
        while queue.now_ms - kill_at < green_max_ms:
            queue.run_for(200)
            if all_green(exclude={victim}):
                green_at = queue.now_ms
                break
        time_to_green = (green_at - kill_at) if green_at else None
        queue.run_for(settle_ms)  # post-green settle window

        post = [r for r in records if r[0] > kill_at]
        completeness = [r[1] / max(r[2], 1) for r in post]
        final_window = [r[1] / max(r[2], 1) for r in post
                        if r[0] > queue.now_ms - 2_000]
        errors = sum(1 for r in records if r[3])

        rebuilds_post = {
            nid: n.local_shards[("elastic", 0)].vector_store
            .segment_counters["full_rebuilds"]
            for nid, n in live_nodes().items()
            if ("elastic", 0) in n.local_shards}
        survivors_flat = all(
            rebuilds_post.get(nid, v) == v
            for nid, v in rebuilds_pre.items() if nid != victim)
        replacement_flat = all(
            v == 0 for nid, v in rebuilds_post.items()
            if nid not in rebuilds_pre)
        rec = fresh.recovery_summary()

        for n in live_nodes().values():
            n.refresh_all()
        post_hits = [(h["_id"], h["_score"]) for h in
                     call(coord.client_search, "elastic", dict(knn_body))
                     ["hits"]["hits"]]

        row = {
            "config": "14_kill_and_replace",
            "virtual_time": True,
            "backend": jax.devices()[0].platform,
            "n_docs": n_docs, "dims": dims, "shards": 1, "replicas": 2,
            "n_clients": n_clients, "victim": victim,
            "time_to_green_ms": time_to_green,
            "completeness_min": round(min(completeness), 3)
            if completeness else 0.0,
            "completeness_final_window": round(
                sum(final_window) / len(final_window), 3)
            if final_window else 0.0,
            "searches_post": len(post),
            "error_responses": errors,
            "recovery_blocks_shipped": rec["blocks_shipped"],
            "recovery_blocks_reused": rec["blocks_reused"],
            "recovery_bytes_shipped": rec["bytes_shipped"],
            "recovery_attempts": rec["attempts"],
            "recovery_throttle_ms": rec["throttle_time_in_millis"],
            "full_rebuilds_pre": sum(rebuilds_pre.values()),
            "full_rebuilds_post": sum(rebuilds_post.values()),
            "gate_time_to_green": bool(time_to_green is not None),
            "gate_completeness_dips": bool(
                completeness and min(completeness) < 1.0),
            "gate_completeness_recovers": bool(
                final_window and
                sum(final_window) / len(final_window) >= 0.999),
            "gate_no_reingest": bool(survivors_flat and replacement_flat),
            "gate_blocks_shipped": bool(rec["blocks_shipped"] > 0),
            "gate_byte_identical": bool(post_hits == pre_hits),
        }
        row["gate_durable_elasticity"] = bool(
            row["gate_time_to_green"] and row["gate_completeness_recovers"]
            and row["gate_no_reingest"] and row["gate_blocks_shipped"]
            and row["gate_byte_identical"])
        print(json.dumps(row), flush=True)
    finally:
        ClusterNode._REPLICATION_BUDGET_MS = saved_repl
        for n in nodes.values():
            try:
                if not n.coordinator.stopped:
                    n.stop()
            except Exception:
                pass
        shutil.rmtree(tmp, ignore_errors=True)


# ------------------------------------------------------- 15_real_cluster

def _rc_pump(loop, seconds: float) -> None:
    """Run the coordinator's event loop for a wall-clock window. One
    continuous `run_until_complete` per window (not a pump-in-slices
    loop): callbacks fire on their real deadlines throughout."""
    import asyncio
    loop.run_until_complete(asyncio.sleep(seconds))


def _rc_wait(loop, pred, timeout_s: float, what: str) -> None:
    import asyncio

    async def wait():
        deadline = loop.time() + timeout_s
        while not pred():
            if loop.time() > deadline:
                raise RuntimeError(f"timed out waiting for {what}")
            await asyncio.sleep(0.02)

    loop.run_until_complete(wait())


def _rc_call(loop, fn, *args, timeout_s: float = 120.0, **kw):
    """Callback API -> blocking call, driving the loop while waiting."""
    box = {}
    fn(*args, **kw, on_done=lambda r: box.update(r=r))
    _rc_wait(loop, lambda: "r" in box, timeout_s,
             getattr(fn, "__name__", "call"))
    return box["r"]


def _rc_boot(child_ids, tmp, *, cluster_settings=None, policy_config=None,
             env=None, coord_id="coord"):
    """Launch one OS process per child id and join an in-parent
    coordinating-only node (roles={"master"}: it votes and coordinates
    but never holds copies, so every data leg crosses a real socket)."""
    import asyncio
    import os as _os

    from elasticsearch_tpu.cluster.launcher import (
        find_free_ports, join_cluster, launch_nodes)

    all_ids = list(child_ids) + [coord_id]
    ports = find_free_ports(len(all_ids))
    peers = {nid: ("127.0.0.1", p) for nid, p in zip(all_ids, ports)}
    procs = launch_nodes(list(child_ids), tmp, peers, masters=all_ids,
                         policy_config=policy_config,
                         cluster_settings=cluster_settings, env=env)
    loop = asyncio.new_event_loop()
    asyncio.set_event_loop(loop)
    try:
        coord, transport = join_cluster(
            coord_id, _os.path.join(tmp, coord_id), peers, all_ids, loop,
            cluster_settings=cluster_settings, roles={"master"})
        _rc_wait(loop,
                 lambda: (len(coord.cluster_state.nodes) == len(all_ids)
                          and coord.cluster_state.master_node_id),
                 90.0, "cluster formation")
    except Exception:
        for p in procs:
            p.terminate()
        raise
    return procs, coord, transport, loop


def _rc_teardown(procs, coord, transport, loop) -> None:
    try:
        coord.stop()
    except Exception:
        pass
    try:
        loop.run_until_complete(transport.close())
    except Exception:
        pass
    for p in procs:
        try:
            p.terminate()
        except Exception:
            pass
    try:
        loop.close()
    except Exception:
        pass


def _rc_write_docs(loop, coord, index, docs, chunk: int = 32) -> None:
    """Index (doc_id, source) pairs with `chunk` writes in flight."""
    i = 0
    while i < len(docs):
        part = docs[i:i + chunk]
        box = {"n": 0}
        bump = lambda *_a, b=box: b.__setitem__("n", b["n"] + 1)  # noqa: E731
        for doc_id, src in part:
            coord.client_write(index, {"type": "index", "id": doc_id,
                                       "source": src},
                               on_done=bump, on_failure=bump)
        _rc_wait(loop, lambda: box["n"] == len(part), 120.0,
                 f"write chunk at {i}")
        i += chunk


def _rc_pct(lats, q):
    if not lats:
        return 0.0
    return float(np.percentile(np.asarray(lats, dtype=np.float64), q))


def _rc_sim_closed_loop(n_docs: int, shards: int, n_clients: int,
                        per_client: int):
    """The virtual-time baseline: the IDENTICAL workload (coordinating-
    only coordinator + 3 data nodes, same index shape, same doc count,
    same closed-loop client count) on the deterministic simulator with
    its seeded 1-50ms hops. Returns (p50_ms, p99_ms) in VIRTUAL ms —
    the wall-clock row reports itself against these so the record shows
    what the sim regime claimed for the same topology."""
    import os as _os
    import shutil
    import tempfile

    from elasticsearch_tpu.cluster.cluster_node import ClusterNode
    from elasticsearch_tpu.cluster.coordination import bootstrap_state
    from elasticsearch_tpu.cluster.state import ShardRoutingEntry
    from elasticsearch_tpu.testing.deterministic import (
        DeterministicTaskQueue, DisruptableTransport)

    queue = DeterministicTaskQueue(seed=29)
    transport = DisruptableTransport(queue)
    tmp = tempfile.mkdtemp()
    data_ids = ["d0", "d1", "d2"]
    all_ids = data_ids + ["coord"]
    initial = bootstrap_state(sorted(all_ids))
    nodes = {nid: ClusterNode(
        nid, _os.path.join(tmp, nid), transport, queue,
        [p for p in all_ids if p != nid], initial,
        roles={"master"} if nid == "coord" else None)
        for nid in all_ids}
    try:
        for n in nodes.values():
            n.start()
        for _ in range(600):
            queue.run_for(200)
            ms = [n for n in nodes.values() if n.is_master]
            if ms and len(ms[0].cluster_state.nodes) == len(all_ids):
                break
        coord = nodes["coord"]

        def call(fn, *args, **kw):
            box = {}
            fn(*args, **kw, on_done=lambda r: box.update(r=r))
            for _ in range(600):
                queue.run_for(200)
                if "r" in box:
                    return box["r"]
            raise RuntimeError(f"no response from {fn.__name__}")

        call(coord.client_create_index, "docs",
             settings={"index.number_of_shards": shards,
                       "index.number_of_replicas": 1},
             mappings={"properties": {"title": {"type": "text"},
                                      "n": {"type": "long"}}})

        def all_started():
            rs = coord.cluster_state.shards_of("docs")
            return bool(rs) and all(
                r.state == ShardRoutingEntry.STARTED for r in rs)

        for _ in range(600):
            queue.run_for(200)
            if all_started():
                break
        for i in range(n_docs):
            call(coord.client_write, "docs",
                 {"type": "index", "id": f"d{i}",
                  "source": {"title": f"doc {i}", "n": i}})
        call(coord.client_refresh, "docs")

        lats = []
        left = {"n": n_clients * per_client}

        def issue(ci, remaining):
            t0 = queue.now_ms

            def done(resp):
                lats.append(queue.now_ms - t0)
                left["n"] -= 1
                if remaining > 1:
                    queue.schedule_in(5, lambda: issue(ci, remaining - 1),
                                      f"sim_client:{ci}")

            coord.client_search("docs", {"query": {"match_all": {}},
                                         "size": 10}, done)

        for ci in range(n_clients):
            issue(ci, per_client)
        for _ in range(2000):
            queue.run_for(200)
            if left["n"] == 0:
                break
        return _rc_pct(lats, 50), _rc_pct(lats, 99)
    finally:
        for n in nodes.values():
            try:
                if not n.coordinator.stopped:
                    n.stop()
            except Exception:
                pass
        shutil.rmtree(tmp, ignore_errors=True)


def run_real_cluster(pre_s: float = 4.0, post_s: float = 12.0,
                     n_docs: int = 120, shards: int = 4,
                     n_clients: int = 4, per_client: int = 60):
    """Config 15: the first WALL-CLOCK cross-node rows — every number in
    configs 10/14 and the fan-out suite before this PR was virtual-time
    simulation. Three data nodes run as separate OS processes booted by
    `cluster/launcher.py`, each serving `transport/tcp.py`'s framed
    binary protocol on a real socket; the coordinator joins in-process
    as a coordinating-only node (no data role), so every query leg,
    write replication hop, and cluster-state publication crosses a
    kernel socket boundary between processes. Rows carry
    `simulated: false, virtual_time: false`.

    Scenario `closed_loop`: fixed-count closed-loop match_all clients;
    reports wall p50/p99/qps next to the sim-regime baseline (the same
    topology and workload on the deterministic simulator, virtual ms).

    Scenario `node_kill`: config 10 re-measured over sockets — closed-
    loop clients + a 25/s write ticker, then SIGKILL a copy-holding
    child (no FIN help from a closing runtime; peers learn from dead
    sockets and fault timeouts). Same gates as config 10 with one
    honest difference: over real sockets node death is DETECTABLE (a
    reset/EOF fails the leg fast), so degradation shows as failed-shard
    partials as often as budget timeouts — `degraded_partials` counts
    both and feeds the `partials > 0` term of
    `gate_graceful_degradation`.
    """
    import shutil
    import tempfile
    import time as _time

    from elasticsearch_tpu.cluster.state import ShardRoutingEntry
    from elasticsearch_tpu.serving import router as router_lib

    query_budget_ms, grace_ms = 400, 100
    tmp = tempfile.mkdtemp()
    child_ids = ["d0", "d1", "d2"]
    settings = {"search.fanout.query_budget_ms": query_budget_ms,
                "search.fanout.fetch_budget_ms": query_budget_ms,
                "search.fanout.deadline_grace_ms": grace_ms}
    router_lib.reset()
    procs, coord, transport, loop = _rc_boot(
        child_ids, tmp, cluster_settings=settings)
    try:
        _rc_call(loop, coord.client_create_index, "kill",
                 settings={"index.number_of_shards": shards,
                           "index.number_of_replicas": 1},
                 mappings={"properties": {"title": {"type": "text"},
                                          "n": {"type": "long"}}})

        def all_started():
            rs = coord.cluster_state.shards_of("kill")
            return bool(rs) and all(
                r.state == ShardRoutingEntry.STARTED for r in rs)

        _rc_wait(loop, all_started, 120.0, "shards STARTED")
        _rc_write_docs(loop, coord, "kill",
                       [(f"d{i}", {"title": f"doc {i}", "n": i})
                        for i in range(n_docs)])
        refreshed = _rc_call(loop, coord.client_refresh, "kill")
        body = {"query": {"match_all": {}}, "size": 10}
        for _ in range(6):  # warm per-shard query paths in every child
            _rc_call(loop, coord.client_search, "kill", dict(body))

        # ---------------------------------------- scenario: closed_loop
        lats = []
        left = {"n": n_clients * per_client}

        def issue_fixed(ci, remaining):
            t0 = loop.time()

            def done(resp):
                lats.append((loop.time() - t0) * 1000.0)
                left["n"] -= 1
                if remaining > 1:
                    issue_fixed(ci, remaining - 1)

            coord.client_search("kill", dict(body), done)

        t_wall = _time.perf_counter()
        for ci in range(n_clients):
            issue_fixed(ci, per_client)
        _rc_wait(loop, lambda: left["n"] == 0, 180.0, "closed-loop drain")
        wall = _time.perf_counter() - t_wall
        p50, p99 = _rc_pct(lats, 50), _rc_pct(lats, 99)
        sim_p50, sim_p99 = _rc_sim_closed_loop(n_docs, shards, n_clients,
                                               per_client)
        print(json.dumps({
            "config": "15_real_cluster", "scenario": "closed_loop",
            "simulated": False, "virtual_time": False,
            "transport": "tcp_sockets",
            "processes": len(child_ids) + 1,
            "n_docs": n_docs, "shards": shards, "replicas": 1,
            "n_clients": n_clients, "searches": len(lats),
            "refresh_failed_shards": (refreshed.get("_shards") or {})
            .get("failed"),
            "qps": round(n_clients * per_client / wall, 1),
            "p50_ms": round(p50, 2), "p99_ms": round(p99, 2),
            "p99_over_p50": round(p99 / max(p50, 1e-9), 2),
            "gate_p99_le_3x_p50": bool(p99 <= 3 * p50),
            "sim_baseline": {"virtual_time": True,
                             "p50_ms": round(sim_p50, 1),
                             "p99_ms": round(sim_p99, 1)},
        }), flush=True)

        # ------------------------------------------ scenario: node_kill
        ingest = {"sent": 0, "acked": 0}
        stop = {"done": False}

        def write_tick():
            if stop["done"]:
                return
            i = ingest["sent"]
            ingest["sent"] += 1
            coord.client_write(
                "kill", {"type": "index", "id": f"w{i}",
                         "source": {"title": f"live {i}", "n": i}},
                on_done=lambda r: ingest.__setitem__(
                    "acked", ingest["acked"] + 1),
                on_failure=lambda e: None)
            loop.call_later(0.04, write_tick)

        # (t_done_s, took_ms, ok_shards, total, timed_out, err, client)
        records = []

        def issue(ci):
            t0 = loop.time()

            def done(resp):
                sh = resp.get("_shards") or {}
                records.append((loop.time(), (loop.time() - t0) * 1000.0,
                                sh.get("successful", 0),
                                sh.get("total", shards),
                                bool(resp.get("timed_out")),
                                "error" in resp, ci))
                if not stop["done"]:
                    loop.call_later(0.005, issue, ci)

            coord.client_search("kill", dict(body), done)

        write_tick()
        for ci in range(n_clients):
            issue(ci)
        _rc_pump(loop, pre_s)
        kill_at = loop.time()
        pre = list(records)

        master_id = coord.cluster_state.master_node_id
        held = {}
        for r in coord.cluster_state.shards_of("kill"):
            if r.state == ShardRoutingEntry.STARTED and r.node_id:
                held[r.node_id] = held.get(r.node_id, 0) + 1
        victim = next(nid for nid in sorted(held)
                      if nid not in (coord.node_id, master_id))
        # config-10 idiom: drop the victim from the cost table so copy
        # selection probes it (unmeasured ranks first) — the kill must
        # hit a node that is actually serving
        coord._ars_ewma.pop(victim, None)
        next(p for p in procs if p.node_id == victim).kill()
        _rc_pump(loop, post_s)
        stop["done"] = True
        _rc_pump(loop, 1.0)  # drain in-flight responses

        post = [r for r in records if r[0] > kill_at]
        pre_p99 = _rc_pct([r[1] for r in pre], 99)
        post_p99 = _rc_pct([r[1] for r in post], 99)
        completeness = [r[2] / max(r[3], 1) for r in post]
        final_window = [r[2] / max(r[3], 1) for r in post
                        if r[0] > kill_at + post_s - 2.0]
        errors = sum(1 for r in records if r[5])
        timeouts = sum(1 for r in post if r[4])
        degraded = sum(1 for r in post if r[4] or r[2] < r[3])
        bound_ms = pre_p99 + query_budget_ms + grace_ms + 200
        row = {
            "config": "15_real_cluster", "scenario": "node_kill",
            "simulated": False, "virtual_time": False,
            "transport": "tcp_sockets",
            "processes": len(child_ids) + 1,
            "n_docs": n_docs, "shards": shards, "replicas": 1,
            "n_clients": n_clients, "victim": victim,
            "searches_pre": len(pre), "searches_post": len(post),
            "pre_p50_ms": round(_rc_pct([r[1] for r in pre], 50), 1),
            "pre_p99_ms": round(pre_p99, 1),
            "post_p50_ms": round(_rc_pct([r[1] for r in post], 50), 1),
            "post_p99_ms": round(post_p99, 1),
            "p99_bound_ms": round(bound_ms, 1),
            "timed_out_partials": timeouts,
            "degraded_partials": degraded,
            "error_responses": errors,
            "completeness_min": round(min(completeness), 3)
            if completeness else 0.0,
            "completeness_final_window": round(
                sum(final_window) / len(final_window), 3)
            if final_window else 0.0,
            "ingest_sent": ingest["sent"], "ingest_acked": ingest["acked"],
            "router": router_lib.stats(),
            "gate_no_hang": bool(post and all(
                any(r[6] == ci and r[0] > kill_at + post_s - 2.0
                    for r in post)
                for ci in range(n_clients))),
            "gate_no_error_cliff": bool(errors == 0),
            "gate_p99_bounded": bool(post_p99 <= bound_ms),
            "gate_completeness_recovers": bool(
                final_window and
                sum(final_window) / len(final_window) >= 0.999),
        }
        row["gate_graceful_degradation"] = bool(
            row["gate_no_hang"] and row["gate_no_error_cliff"]
            and row["gate_p99_bounded"]
            and row["gate_completeness_recovers"] and degraded > 0)
        print(json.dumps(row), flush=True)
    finally:
        _rc_teardown(procs, coord, transport, loop)
        shutil.rmtree(tmp, ignore_errors=True)
    _rc_dp_sweep()


def _rc_dp_sweep(dims: int = 64, n_docs: int = 2048, n_clients: int = 4,
                 per_client: int = 25):
    """Config 15 dp rows: the config-6 dp qps sweep re-measured with the
    query arriving over a REAL socket. One data child is launched with 8
    forced host devices and the mesh policy configured at boot
    (`--policy`); the coordinator fans kNN bodies to it over TCP, so
    each row's qps includes framing, the socket round trip, and the
    child's dp-vs-shard split decision under live queue depth. dp=1 is
    the full-mesh-only baseline; the sweep reports the dp=4 ratio and a
    cross-run parity check on a pinned query (the dp split must never
    change bytes)."""
    import shutil
    import tempfile
    import time as _time

    rng = np.random.default_rng(71)
    vecs = rng.standard_normal((n_docs, dims)).astype(np.float32)
    vecs /= np.linalg.norm(vecs, axis=1, keepdims=True)
    pin = rng.standard_normal(dims).astype(np.float32)
    pin /= np.linalg.norm(pin)
    results = {}
    for dp in (1, 4):
        tmp = tempfile.mkdtemp()
        procs, coord, transport, loop = _rc_boot(
            ["v0"], tmp,
            policy_config={"enabled": True, "dp": dp,
                           "num_shards": 8 // dp, "min_rows": 1},
            env={"XLA_FLAGS": "--xla_force_host_platform_device_count=8"})
        try:
            from elasticsearch_tpu.cluster.state import ShardRoutingEntry
            _rc_call(loop, coord.client_create_index, "vec",
                     settings={"index.number_of_shards": 1,
                               "index.number_of_replicas": 0},
                     mappings={"properties": {
                         "n": {"type": "long"},
                         "v": {"type": "dense_vector", "dims": dims,
                               "index": True,
                               "similarity": "dot_product"}}})
            _rc_wait(loop, lambda: all(
                r.state == ShardRoutingEntry.STARTED
                for r in (coord.cluster_state.shards_of("vec") or [None])
                if r is not None) and bool(
                    coord.cluster_state.shards_of("vec")),
                120.0, "vec shard STARTED")
            _rc_write_docs(loop, coord, "vec",
                           [(f"d{i}", {"n": i,
                                       "v": [float(x) for x in vecs[i]]})
                            for i in range(n_docs)], chunk=64)
            _rc_call(loop, coord.client_refresh, "vec")

            def knn_body(q):
                return {"knn": {"field": "v",
                                "query_vector": [float(x) for x in q],
                                "k": 10, "num_candidates": 64},
                        "size": 10, "_source": False}

            # warmup: both route families (full mesh + dp group) compile
            # in the child before the timed loop
            for i in range(8):
                _rc_call(loop, coord.client_search, "vec",
                         knn_body(vecs[i]), timeout_s=300.0)
            pinned = _rc_call(loop, coord.client_search, "vec",
                              knn_body(pin))
            pinned_hits = [(h["_id"], h["_score"])
                           for h in pinned["hits"]["hits"]]

            lats = []
            left = {"n": n_clients * per_client}

            def issue(ci, remaining):
                t0 = loop.time()

                def done(resp):
                    lats.append((loop.time() - t0) * 1000.0)
                    left["n"] -= 1
                    if remaining > 1:
                        issue(ci, remaining - 1)

                q = vecs[(ci * per_client + remaining) % n_docs]
                coord.client_search("vec", knn_body(q), done)

            t_wall = _time.perf_counter()
            for ci in range(n_clients):
                issue(ci, per_client)
            _rc_wait(loop, lambda: left["n"] == 0, 300.0, "dp sweep drain")
            wall = _time.perf_counter() - t_wall
            qps = n_clients * per_client / wall
            results[dp] = (qps, pinned_hits)
            print(json.dumps({
                "config": "15_real_cluster", "scenario": "dp_sweep",
                "simulated": False, "virtual_time": False,
                "transport": "tcp_sockets", "dp": dp,
                "num_shards": 8 // dp, "devices_in_child": 8,
                "n_docs": n_docs, "dims": dims,
                "n_clients": n_clients, "searches": len(lats),
                "qps": round(qps, 1),
                "p50_ms": round(_rc_pct(lats, 50), 2),
                "p99_ms": round(_rc_pct(lats, 99), 2),
                "measures": "socket_rtt_plus_scheduling_not_ici",
            }), flush=True)
        finally:
            _rc_teardown(procs, coord, transport, loop)
            shutil.rmtree(tmp, ignore_errors=True)
    q1, q4 = results[1][0], results[4][0]
    print(json.dumps({
        "config": "15_real_cluster", "scenario": "dp_sweep_summary",
        "simulated": False, "virtual_time": False,
        "qps_dp1": round(q1, 1), "qps_dp4": round(q4, 1),
        "speedup_dp4_vs_dp1": round(q4 / max(q1, 1e-9), 2),
        "parity_dp4_vs_dp1": bool(results[1][1] == results[4][1]),
    }), flush=True)


def run_rest_closed_loop_dp():
    """PR 11 leftover (b): the REST closed-loop rows (`1cl`/`4cl`,
    hybrid) served dp=1 shapes — point their corpora at a dp mesh
    (`search.mesh.dp=4` over 8 devices) and re-record `gate_500qps`
    end-to-end. Re-exec'd onto 8 virtual devices when needed; those rows
    measure scheduling concurrency + program shape, not ICI."""
    _run_on_simulated_mesh("rest_closed_loop_dp", "--rest-dp-only",
                           _rest_dp_rows, min_devices=8)


def _rest_dp_rows(simulated: bool):
    del simulated
    import os

    from elasticsearch_tpu.parallel import policy

    small = os.environ.get("BENCH_SMALL") == "1"
    mesh = {"search.mesh.enabled": True, "search.mesh.dp": 4,
            "search.mesh.min_rows": 1}
    try:
        run_hybrid_rrf(mesh=mesh)
        run_closed_loop("1cl", 100_000 if small else 1_000_000, 128,
                        dtype="bf16", mesh=mesh)
        run_closed_loop("4cl", 100_000 if small else 1_000_000, 768,
                        dtype="int8", mesh=mesh)
    finally:
        # the mesh policy is process-wide: a dp row must never leak its
        # routing into later configs
        policy.reset(full=True)


def main():
    import os
    import sys
    import traceback

    if "--rest-dp-only" in sys.argv:
        # the simulated-mesh child re-exec (run_rest_closed_loop_dp)
        _rest_dp_rows(simulated=True)
        return

    if "--dp-only" in sys.argv:
        # the simulated-mesh child re-exec (run_dp_replicated)
        run_dp_replicated()
        return

    if "--real-cluster-only" in sys.argv:
        # the wall-clock multi-process rows alone (config 15): boots
        # child node processes, so it gets its own entry point for
        # re-measurement without re-running the kernel matrix
        run_real_cluster()
        return

    if "--sharded-only" in sys.argv:
        # the simulated-mesh child re-exec (run_sharded_fused): emit the
        # config-6 rows only, on whatever device mesh this process sees
        run_sharded_fused()
        return

    small = os.environ.get("BENCH_SMALL") == "1"

    def guarded(fn, *args, **kwargs):
        """One config must never lose the rest of the matrix: rows flush
        as they complete, and a config that can't run on this backend
        (e.g. the Pallas binned kernel on the CPU floor) reports itself
        as a labeled failure line instead of killing the process."""
        try:
            fn(*args, **kwargs)
        except Exception as e:  # noqa: BLE001 — diagnostic row, not fatal
            print(json.dumps({
                "config": f"{getattr(fn, '__name__', str(fn))}",
                "error": f"{type(e).__name__}: {e}"[:300],
                "trace_tail": traceback.format_exc().strip()
                .splitlines()[-1][:200]}), flush=True)

    # serving-path rows first: the hybrid fused plan and the 8-client
    # closed-loop tail rows are the record's open questions (VERDICT r5
    # Next #1/#2); raw-kernel configs follow. Since PR 12 these rows
    # serve a dp-mesh corpus (search.mesh.dp=4) instead of dp=1 shapes —
    # the PR 11 leftover (b) re-measurement (re-exec'd onto 8 virtual
    # devices when this process sees fewer). The 10Mx768 corpus can't
    # stage an f32 host copy here (30 GB); the config-4 SHAPE runs at 1M
    # rows like the e2e row, and says so.
    guarded(run_rest_closed_loop_dp)
    guarded(run_telemetry_overhead)
    guarded(run_fanout_node_kill)
    guarded(run_kill_and_replace)
    guarded(run_real_cluster)
    guarded(run_config, "1_cosine_sift1m", 1_000_000, 128, "cosine",
            "bf16")
    guarded(run_config, "2_l2_gist_960d", 262_144, 960, "l2_norm", "bf16")
    guarded(run_zipf_cached_closed_loop)
    guarded(run_e2e_single)
    guarded(run_north_star_10m_int8)
    guarded(run_config, "5_filtered_10pct", 1_000_000, 128, "cosine",
            "bf16", filter_frac=0.10)
    guarded(run_small_batch_serving)
    guarded(run_ivf_config)
    guarded(run_density_ladder)
    guarded(run_device_aggs)
    guarded(run_retrieval_workloads)
    guarded(run_ingest_while_search)
    guarded(run_sharded_fused)
    guarded(run_dp_replicated)


if __name__ == "__main__":
    main()
