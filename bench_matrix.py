"""Benchmark matrix: the five BASELINE.md configs at single-chip scale.

`bench.py` remains the driver contract (ONE JSON line, config 1). This
script reports every config as its own JSON line so the full matrix is
measurable on one chip:

  1 cosine kNN, SIFT-like 1M x 128        (binned Pallas kernel)
  2 l2_norm kNN, GIST-like 256k x 960     (exact XLA path — no HNSW in
                                           the reference either; recall 1.0)
  3 hybrid BM25 + kNN with RRF fusion     (end-to-end through Node.search)
  4 int8 scalar-quantized, 1M x 768       (int8 corpus, recall vs f32)
  5 filtered kNN, 1M x 128, 10% filter    (host bitmap -> masked top-k)

Batches are scanned on-device inside one dispatch (see bench.py for why:
this environment adds a tunnel round-trip per dispatch).
"""

from __future__ import annotations

import functools
import json
import time

import numpy as np


def _device_qps(search_all, qstack, corpus, k, n_queries, runs=3):
    import jax
    out = search_all(qstack, corpus, k)
    ids = np.asarray(out[1])
    times = []
    for _ in range(runs):
        t0 = time.perf_counter()
        out = search_all(qstack, corpus, k)
        ids = np.asarray(out[1])
        times.append(time.perf_counter() - t0)
    return n_queries / float(np.median(times)), ids


def _recall(ids, ids_ref, k):
    n = ids_ref.shape[0]
    hits = sum(len(set(ids[r][:k]) & set(ids_ref[r][:k])) for r in range(n))
    return hits / (n * k)


def _scan_searcher(fn):
    import jax

    @functools.partial(jax.jit, static_argnames=("kk",))
    def search_all(qs, c, kk):
        def body(carry, qb):
            return carry, fn(qb, c, kk)
        _, out = jax.lax.scan(body, None, qs)
        return out

    return search_all


def run_config(name, n, d, metric, dtype, k, batches, batch, filter_frac=None):
    import jax
    import jax.numpy as jnp

    from elasticsearch_tpu.ops import knn as knn_ops
    from elasticsearch_tpu.ops import similarity as sim

    rng = np.random.default_rng(7)
    centers = rng.standard_normal((128, d)).astype(np.float32) * 2.0
    vectors = (centers[rng.integers(0, 128, size=n)]
               + rng.standard_normal((n, d)).astype(np.float32))
    nq = batch * batches
    queries = vectors[rng.integers(0, n, size=nq)] \
        + 0.3 * rng.standard_normal((nq, d)).astype(np.float32)
    corpus = knn_ops.build_corpus(vectors, metric=metric, dtype=dtype)
    qstack = jnp.asarray(queries.reshape(batches, batch, d))
    jax.block_until_ready(corpus)

    mask = None
    if filter_frac is not None:
        keep = rng.random(corpus.matrix.shape[0]) < filter_frac
        keep[n:] = False
        mask = jnp.asarray(keep)

    if mask is not None:
        def fn(qb, c, kk, m=mask):
            return knn_ops.knn_search(qb, c, kk, metric=metric, filter_mask=m)
    else:
        def fn(qb, c, kk):
            return knn_ops.knn_search_auto(qb, c, kk, metric=metric)

    qps, ids = _device_qps(_scan_searcher(fn), qstack, corpus, k, nq)

    # recall vs exact f32 on the first batch
    f32_corpus = knn_ops.build_corpus(vectors, metric=metric, dtype="f32") \
        if dtype != "f32" else corpus
    _, ids_ref = knn_ops.knn_search(qstack[0], f32_corpus, k=k, metric=metric,
                                    precision="f32",
                                    filter_mask=mask)
    recall = _recall(ids[0], np.asarray(ids_ref), k)
    print(json.dumps({"config": name, "qps": round(qps, 1),
                      "recall_at_10": round(recall, 4), "n_docs": n,
                      "dims": d, "metric": metric, "dtype": dtype,
                      **({"filter_frac": filter_frac}
                         if filter_frac is not None else {})}), flush=True)


def run_hybrid_rrf():
    """Config 3: BM25 + kNN fused with RRF, end-to-end through Node."""
    import tempfile

    from elasticsearch_tpu.node import Node

    rng = np.random.default_rng(3)
    words = ["alpha", "beta", "gamma", "delta", "tpu", "search", "vector",
             "index", "shard", "query"]
    node = Node(tempfile.mkdtemp())
    node.create_index_with_templates("hybrid", mappings={"properties": {
        "body": {"type": "text"},
        "v": {"type": "dense_vector", "dims": 64}}})
    n_docs = 2000
    ops = []
    for i in range(n_docs):
        text = " ".join(rng.choice(words, size=8))
        ops.append({"index": {"_index": "hybrid", "_id": str(i)}})
        ops.append({"body": text,
                    "v": rng.standard_normal(64).astype(np.float32).tolist()})
    node.bulk(ops)
    node.indices.get("hybrid").refresh()

    qv = rng.standard_normal(64).astype(np.float32).tolist()
    body = {"rank": {"rrf": {"rank_constant": 60, "rank_window_size": 100}},
            "query": {"match": {"body": "tpu vector"}},
            "knn": {"field": "v", "query_vector": qv, "k": 100},
            "size": 10}
    node.search("hybrid", body)  # warm
    t0 = time.perf_counter()
    n_runs = 30
    for _ in range(n_runs):
        resp = node.search("hybrid", body)
    dt = time.perf_counter() - t0
    assert resp["hits"]["hits"], "rrf returned no hits"
    print(json.dumps({"config": "3_hybrid_bm25_knn_rrf",
                      "qps": round(n_runs / dt, 1),
                      "p50_ms": round(dt / n_runs * 1000, 2),
                      "n_docs": n_docs, "fused_lists": 2}), flush=True)
    node.close()


def main():
    run_config("1_cosine_sift1m", 1_000_000, 128, "cosine", "bf16",
               k=10, batches=50, batch=128)
    run_config("2_l2_gist_960d", 262_144, 960, "l2_norm", "bf16",
               k=10, batches=10, batch=128)
    run_hybrid_rrf()
    run_config("4_int8_768d", 1_000_000, 768, "cosine", "int8",
               k=10, batches=10, batch=128)
    run_config("5_filtered_10pct", 1_000_000, 128, "cosine", "bf16",
               k=10, batches=10, batch=128, filter_frac=0.10)


if __name__ == "__main__":
    main()
