"""Vector codec subsystem (`elasticsearch_tpu/quant/`).

Pins the quantization-ladder contracts:
* encode host-vs-device parity — every codec's np and jnp twins produce
  BYTE-identical packed data (scales allclose: float reduction order),
  and the host decode twin round-trips within the rung's error bound;
* recall gates per rung on the 768-d clustered bench shape — int4 and
  binary(Hamming) + exact rescore both hold recall@10 >= 0.95 vs exact
  f32 at their default oversamples;
* the store-level two-phase path (`index_options` int4_flat /
  binary_flat / int4_ivf): recall, rescore counters, profile phases,
  and the `rescore_oversample` small fix;
* dtype changes run on the MERGE thread: an int8→int4 mapping update
  never full-rebuilds on the serving path (`dtype_change` rebuilds stay
  0), searches stay byte-stable during the re-encode, and the budgeted
  merger installs the re-encoded generations;
* per-segment ENCODED blocks cache in the columnar store like f32 rows
  (delta composition on append);
* mesh byte parity for packed corpora (multidevice).
"""

import numpy as np
import pytest

from elasticsearch_tpu.common.errors import MapperParsingError
from elasticsearch_tpu.index.mapping import DenseVectorFieldMapper
from elasticsearch_tpu.index.segment import Segment, SegmentView, ShardReader
from elasticsearch_tpu.ops import dispatch
from elasticsearch_tpu.ops import knn as knn_ops
from elasticsearch_tpu.ops import similarity as sim
from elasticsearch_tpu.quant import codec as quant_codec
from elasticsearch_tpu.quant import rescore as quant_rescore
from elasticsearch_tpu.vectors.store import VectorStoreShard

SEED = 11


# ---------------------------------------------------------------------------
# codec registry: host/device twins, round-trips, accounting
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("name", ["int8", "int4", "binary"])
def test_encode_np_and_jnp_twins_byte_parity(name):
    """The np and jnp encoders implement ONE recipe: packed bytes are
    identical; scales agree to reduction-order float noise."""
    import jax.numpy as jnp
    rng = np.random.default_rng(SEED)
    mat = rng.standard_normal((128, 64)).astype(np.float32) * 3.0
    codec = quant_codec.get(name)
    enc = codec.encode_np(mat)
    data_j, scales_j = codec.encode_jnp(jnp.asarray(mat))
    np.testing.assert_array_equal(enc.data, np.asarray(data_j))
    np.testing.assert_allclose(enc.scales, np.asarray(scales_j), rtol=1e-6)


@pytest.mark.parametrize("name,rel", [("int8", 1 / 254), ("int4", 1 / 14)])
def test_scalar_decode_roundtrip_bound(name, rel):
    """Symmetric max-abs scaling bounds per-element error by half a
    quantization step of the row's max magnitude."""
    rng = np.random.default_rng(SEED + 1)
    mat = rng.standard_normal((64, 32)).astype(np.float32)
    codec = quant_codec.get(name)
    enc = codec.encode_np(mat)
    recon = codec.decode_np(enc.data, enc.scales)
    bound = np.abs(mat).max(axis=1)[:, None] * rel + 1e-6
    assert (np.abs(recon - mat) <= bound).all()


def test_binary_decode_is_sign_times_mean_abs():
    rng = np.random.default_rng(SEED + 2)
    mat = rng.standard_normal((16, 64)).astype(np.float32)
    codec = quant_codec.get("binary")
    enc = codec.encode_np(mat)
    recon = codec.decode_np(enc.data, enc.scales)
    np.testing.assert_array_equal(np.sign(recon), np.where(mat >= 0, 1, -1))
    np.testing.assert_allclose(
        np.abs(recon),
        np.broadcast_to(np.abs(mat).mean(axis=1)[:, None], mat.shape),
        rtol=1e-5)


def test_bytes_per_doc_ladder_and_single_chip_density():
    """The ladder's density story at the bench shape (768 d): binary
    clears 100M docs in a 16 GB HBM chip; int8 does not."""
    assert quant_codec.bytes_per_doc("f32", 768) == 768 * 4 + 4
    assert quant_codec.bytes_per_doc("bf16", 768) == 768 * 2 + 4
    assert quant_codec.bytes_per_doc("int8", 768) == 768 + 8
    assert quant_codec.bytes_per_doc("int4", 768) == 384 + 8
    assert quant_codec.bytes_per_doc("binary", 768) == 96 + 8
    hbm = 16 * 1024**3
    assert hbm // quant_codec.bytes_per_doc("binary", 768) >= 100_000_000
    assert hbm // quant_codec.bytes_per_doc("int8", 768) < 100_000_000


def test_packed_dims_constraints():
    with pytest.raises(ValueError):
        quant_codec.get("int4").encode_np(np.zeros((2, 7), np.float32))
    with pytest.raises(ValueError):
        quant_codec.get("binary").encode_np(np.zeros((2, 48), np.float32))


def test_unknown_codec_raises():
    with pytest.raises(KeyError):
        quant_codec.get("int2")


# ---------------------------------------------------------------------------
# recall gates per rung (the 768-d clustered bench shape, ops-level)
# ---------------------------------------------------------------------------

def _bench_shape(n=8192, d=768, nq=16):
    rng = np.random.default_rng(7)
    centers = rng.standard_normal((64, d)).astype(np.float32) * 2.0
    vecs = (centers[rng.integers(0, 64, size=n)]
            + rng.standard_normal((n, d)).astype(np.float32))
    qs = (vecs[rng.integers(0, n, size=nq)]
          + 0.3 * rng.standard_normal((nq, d)).astype(np.float32))
    return vecs, qs


@pytest.fixture(scope="module")
def bench_shape():
    import jax.numpy as jnp
    vecs, qs = _bench_shape()
    c32 = knn_ops.build_corpus(vecs, dtype="f32")
    _, i_ref = knn_ops.knn_search(jnp.asarray(qs), c32, 10, precision="f32")
    return vecs, qs, np.asarray(i_ref)


@pytest.mark.parametrize("encoding", ["int4", "binary"])
def test_two_phase_recall_gate(bench_shape, encoding):
    """Coarse packed top-(k·oversample) + exact f32 rescore holds
    recall@10 >= 0.95 vs exact f32 at the DEFAULT oversample."""
    import jax.numpy as jnp
    vecs, qs, i_ref = bench_shape
    corpus = knn_ops.build_corpus(vecs, dtype=encoding)
    over = quant_rescore.DEFAULT_OVERSAMPLE[encoding]
    w = quant_rescore.coarse_window(10, over, limit=corpus.matrix.shape[0])
    k_b = dispatch.bucket_k(w, limit=corpus.matrix.shape[0])
    s, i = knn_ops.knn_search(jnp.asarray(qs), corpus, k_b)
    s, i = np.asarray(s)[:, :w], np.asarray(i)[:, :w]
    out_s, out_i, stats = quant_rescore.rescore_boards(
        qs, s, i, 10, lambda u: vecs[u], sim.COSINE)
    nq = len(qs)
    recall = np.mean([len(set(out_i[r]) & set(i_ref[r])) / 10
                      for r in range(nq)])
    assert recall >= 0.95, (encoding, recall)
    assert stats["window"] == w
    # rescored scores are EXACT f32 raw similarities
    qn = qs / np.linalg.norm(qs, axis=-1, keepdims=True)
    vn = vecs / np.linalg.norm(vecs, axis=-1, keepdims=True)
    for r in range(3):
        expect = np.einsum("d,cd->c", qn[r], vn[out_i[r]])
        np.testing.assert_allclose(out_s[r], expect, rtol=1e-5, atol=1e-6)


def test_corpus_from_encoded_blocks_is_byte_identical(bench_shape):
    """The columnar encoded-block assembly equals the monolithic encode
    byte for byte (rows encode independently)."""
    vecs, _, _ = bench_shape
    vecs = vecs[:1000]
    for encoding in ("int4", "binary"):
        mono = knn_ops.build_corpus(vecs, dtype=encoding)
        codec = quant_codec.get(encoding)
        normed = vecs / np.maximum(
            np.linalg.norm(vecs, axis=-1, keepdims=True), 1e-30)
        enc = codec.encode_np(normed)
        split = knn_ops.corpus_from_encoded(
            enc.data, enc.scales, vecs, dtype=encoding,
            pad_to=mono.matrix.shape[0])
        np.testing.assert_array_equal(np.asarray(mono.matrix),
                                      np.asarray(split.matrix))
        np.testing.assert_array_equal(np.asarray(mono.scales),
                                      np.asarray(split.scales))


# ---------------------------------------------------------------------------
# store-level integration (index_options → two-phase serving)
# ---------------------------------------------------------------------------

DIMS = 256


def _seg(seg_id, base, mat):
    n = mat.shape[0]
    return Segment(
        seg_id=seg_id, base=base, num_docs=n, postings={},
        field_lengths={}, total_terms={}, doc_values={},
        vectors={"v": (mat, np.ones(n, dtype=bool))},
        ids=[f"d{base + i}" for i in range(n)], sources=[None] * n,
        seq_nos=np.arange(base, base + n, dtype=np.int64))


def _mapper(otype=None, extra=None):
    params = {"type": "dense_vector", "dims": DIMS, "similarity": "cosine"}
    if otype is not None:
        opts = {"type": otype}
        opts.update(extra or {})
        params["index_options"] = opts
    return DenseVectorFieldMapper("v", params)


def _store(**kw):
    kw.setdefault("host_mirror_max_bytes", 0)
    kw.setdefault("segments_background_merge", False)
    return VectorStoreShard(**kw)


@pytest.fixture(scope="module")
def clustered():
    rng = np.random.default_rng(3)
    centers = rng.standard_normal((16, DIMS)).astype(np.float32) * 2.0
    mat = (centers[rng.integers(0, 16, size=900)]
           + 0.5 * rng.standard_normal((900, DIMS)).astype(np.float32))
    # held-out-query style (the bench convention): perturbations of
    # corpus documents, not unrelated noise — a pure-noise query has no
    # meaningful neighbors for a recall gate to measure
    qs = (mat[rng.integers(0, 900, size=4)]
          + 0.3 * rng.standard_normal((4, DIMS)).astype(np.float32))
    return mat, qs


def _reader(*mats):
    segs, base = [], 0
    for i, m in enumerate(mats):
        segs.append(_seg(i, base, m))
        base += m.shape[0]
    return ShardReader([SegmentView(s) for s in segs])


class TestStoreTwoPhase:
    def test_packed_flat_recall_and_counters(self, clustered):
        mat, qs = clustered
        ref = _store()
        ref.sync(_reader(mat), {"v": _mapper()})
        for otype in ("int4_flat", "binary_flat"):
            st = _store()
            st.sync(_reader(mat), {"v": _mapper(otype)})
            hits = 0
            for q in qs:
                r_rows, _ = ref.search("v", q, 10, precision="f32")
                rows, scores = st.search("v", q, 10)
                assert len(rows) == 10
                hits += len(set(rows) & set(r_rows))
            assert hits / (10 * len(qs)) >= 0.9, otype
            assert st.knn_stats["rescore_searches"] == len(qs)
            assert st.last_knn_phases["rescore"]["window"] > 10
            fs = st.field_stats()["v"]
            assert fs["encoding"] == otype.split("_")[0]
            assert fs["bytes_per_doc"] == quant_codec.bytes_per_doc(
                fs["encoding"], DIMS)
            assert fs["rescore"] is True

    def test_rescore_oversample_is_honored(self, clustered):
        mat, qs = clustered
        st = _store()
        st.sync(_reader(mat), {"v": _mapper(
            "int4_flat", {"rescore_oversample": 7})})
        st.search("v", qs[0], 10)
        assert st.last_knn_phases["rescore"]["window"] == 70
        st2 = _store()
        st2.sync(_reader(mat), {"v": _mapper(
            "int4_flat", {"rescore": False})})
        st2.search("v", qs[0], 10)
        assert st2.knn_stats["rescore_searches"] == 0

    def test_unknown_index_options_type_raises_clearly(self, clustered):
        """The store-level small fix: a hand-built mapper with an
        unknown type must error, not silently serve f32 flat."""
        mat, _ = clustered
        mapper = _mapper()
        mapper.params["index_options"] = {"type": "int2_flat"}
        st = _store()
        with pytest.raises(MapperParsingError, match="int2_flat"):
            st.sync(_reader(mat), {"v": mapper})

    def test_mapper_validates_new_types_and_constraints(self):
        with pytest.raises(MapperParsingError):
            DenseVectorFieldMapper("v", {
                "type": "dense_vector", "dims": 31, "similarity": "cosine",
                "index_options": {"type": "binary_flat"}})
        with pytest.raises(MapperParsingError):
            DenseVectorFieldMapper("v", {
                "type": "dense_vector", "dims": 33, "similarity": "cosine",
                "index_options": {"type": "int4_flat"}})
        with pytest.raises(MapperParsingError):
            DenseVectorFieldMapper("v", {
                "type": "dense_vector", "dims": 64,
                "similarity": "l2_norm",
                "index_options": {"type": "binary_flat"}})
        # MIP rankings depend on magnitudes the sign sketch discards
        with pytest.raises(MapperParsingError):
            DenseVectorFieldMapper("v", {
                "type": "dense_vector", "dims": 64,
                "similarity": "max_inner_product",
                "index_options": {"type": "binary_flat"}})
        with pytest.raises(MapperParsingError):
            DenseVectorFieldMapper("v", {
                "type": "dense_vector", "dims": 64, "similarity": "cosine",
                "index_options": {"type": "int4_flat",
                                  "rescore_oversample": 0}})

    def test_int4_ivf_two_phase(self, clustered):
        mat, qs = clustered
        ref = _store()
        ref.sync(_reader(mat), {"v": _mapper()})
        st = _store()
        st.sync(_reader(mat), {"v": _mapper("int4_ivf", {"nprobe": 8})})
        hits = 0
        for q in qs:
            r_rows, _ = ref.search("v", q, 10, precision="f32")
            rows, _ = st.search("v", q, 10)
            hits += len(set(rows) & set(r_rows))
        assert st.knn_stats["ivf_searches"] == len(qs)
        assert st.knn_stats["rescore_searches"] == len(qs)
        # IVF prunes AND quantizes; the rescore window still recovers
        # most of exact top-10 on this clustered shape
        assert hits / (10 * len(qs)) >= 0.8


class TestDtypeChangeOnMergeThread:
    def test_reencode_never_full_rebuilds_and_stays_byte_stable(
            self, clustered):
        mat, qs = clustered
        st = _store()
        st.sync(_reader(mat), {"v": _mapper("int8_flat")})
        before = [st.search("v", q, 10) for q in qs]
        # mapping update int8 → int4: absorbed as a retarget, NOT a
        # serving-path rebuild
        st.sync(_reader(mat), {"v": _mapper("int4_flat")})
        assert st.segment_counters["full_rebuilds"] == 0
        assert st.segment_counters["rebuild_reasons"].get(
            "dtype_change", 0) == 0
        assert st.segment_counters["rebuilds_avoided"] == 1
        gc = st._gens["v"]
        assert gc.stats["dtype_retargets"] == 1
        # searches during the re-encode window serve the OLD encoding
        # byte-stably (the int8 base is still installed)
        for (b_rows, b_sc), q in zip(before, qs):
            rows, sc = st.search("v", q, 10)
            np.testing.assert_array_equal(rows, b_rows)
            np.testing.assert_array_equal(sc, b_sc)
        # the budgeted merger re-encodes on ITS thread
        assert gc.merge_pending()
        assert gc.run_merges() >= 1
        assert gc.stats["dtype_reencodes"] >= 1
        assert str(gc.snapshot().generations[0].corpus.matrix.dtype) \
            == "uint8"
        assert st.segment_counters["full_rebuilds"] == 0
        # post-re-encode serving is two-phase and keeps quality
        ref = _store()
        ref.sync(_reader(mat), {"v": _mapper()})
        hits = 0
        for q in qs:
            rows, _ = st.search("v", q, 10)
            r_rows, _ = ref.search("v", q, 10, precision="f32")
            hits += len(set(rows) & set(r_rows))
        assert hits / (10 * len(qs)) >= 0.9
        assert st.knn_stats["rescore_searches"] >= len(qs)

    def test_new_seals_encode_at_target_while_base_lags(self, clustered):
        mat, qs = clustered
        st = _store()
        st.sync(_reader(mat[:700]), {"v": _mapper("int8_flat")})
        st.sync(_reader(mat[:700], mat[700:]),
                {"v": _mapper("int4_flat")})
        gc = st._gens["v"]
        snap = gc.snapshot()
        dtypes = {str(g.corpus.matrix.dtype) for g in snap.generations}
        # mixed mid-transition: the int8 base serves beside the freshly
        # int4-sealed delta; search still answers
        assert dtypes == {"int8", "uint8"}
        rows, _ = st.search("v", qs[0], 10)
        assert len(rows) == 10
        gc.run_merges()
        snap = gc.snapshot()
        assert {str(g.corpus.matrix.dtype)
                for g in snap.generations} == {"uint8"}


class TestEncodedColumnarBlocks:
    def test_encoded_blocks_cache_delta_on_append(self, clustered):
        from elasticsearch_tpu import columnar
        mat, _ = clustered
        columnar.STORE.reset()
        # segment OBJECTS persist across refreshes (the engine's NRT
        # contract the weakref block cache keys on)
        seg0, seg1 = _seg(0, 0, mat[:600]), _seg(1, 600, mat[600:])
        st = _store(segments_enabled=False)
        st.sync(ShardReader([SegmentView(seg0)]),
                {"v": _mapper("int4_flat")})
        stats = columnar.STORE.stats()
        enc = stats["fields"].get("v:vector_enc")
        assert enc is not None and enc["extracts"] == 1
        # append-only refresh: the old segment's ENCODED block is a
        # cache hit; only the delta segment encodes
        st.sync(ShardReader([SegmentView(seg0), SegmentView(seg1)]),
                {"v": _mapper("int4_flat")})
        stats = columnar.STORE.stats()
        enc = stats["fields"]["v:vector_enc"]
        assert enc["extracts"] == 2 and enc["hits"] >= 1
        assert enc["compositions"]["delta"] == 1


@pytest.mark.multidevice
class TestMeshPackedParity:
    @pytest.mark.parametrize("encoding", ["int4", "binary"])
    def test_sharded_packed_matches_single_device(self, encoding):
        """A packed corpus served as ONE SPMD program returns the same
        rows/scores as the single-device packed kernel (byte parity —
        the shard-local math is identical and the merge is exact)."""
        import jax
        import jax.numpy as jnp

        from elasticsearch_tpu.parallel import mesh as mesh_lib
        from elasticsearch_tpu.parallel.sharded_knn import (
            build_sharded_corpus, distributed_knn_search)
        rng = np.random.default_rng(5)
        vecs = rng.standard_normal((1024, 64)).astype(np.float32)
        qs = rng.standard_normal((8, 64)).astype(np.float32)
        assert jax.device_count() >= 4
        mesh = mesh_lib.make_mesh(num_shards=4, dp=1)
        corpus, layout = build_sharded_corpus(
            vecs, mesh, metric=sim.COSINE, dtype=encoding)
        s_mesh, gids = distributed_knn_search(
            jnp.asarray(qs), corpus, k=10, mesh=mesh, metric=sim.COSINE)
        orig = layout.to_original_ids(np.asarray(gids))
        single = knn_ops.build_corpus(vecs, dtype=encoding)
        s_one, i_one = knn_ops.knn_search(jnp.asarray(qs), single, 10)
        s_one, i_one = np.asarray(s_one), np.asarray(i_one)
        for r in range(len(qs)):
            assert set(orig[r].tolist()) == set(i_one[r].tolist())
        np.testing.assert_allclose(np.sort(np.asarray(s_mesh), axis=1),
                                   np.sort(s_one, axis=1),
                                   rtol=1e-5, atol=1e-5)
