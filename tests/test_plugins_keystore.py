"""Plugin system (SPI extension points, isolated loading) and the secure
settings keystore + CLI. Reference: server plugins/ + PluginsService.java,
common/settings/KeyStoreWrapper.java, distribution/tools/keystore-cli."""

import json
import subprocess
import sys

import pytest

from elasticsearch_tpu.common.errors import IllegalArgumentError
from elasticsearch_tpu.common.keystore import KeyStore
from elasticsearch_tpu.node import Node
from elasticsearch_tpu.plugins import EXTRA_QUERY_PARSERS, Plugin, PluginsService

PLUGIN_SRC = '''
from elasticsearch_tpu.plugins import Plugin
from elasticsearch_tpu.index.analysis import Analyzer, keyword_tokenizer
from elasticsearch_tpu.index.mapping import KeywordFieldMapper
from elasticsearch_tpu.ingest.service import Processor, _set_path
from elasticsearch_tpu.search.queries import TermQuery


class ShoutMapper(KeywordFieldMapper):
    type_name = "shout"

    def index_terms(self, value):
        return [str(value).upper()]

    def doc_value(self, value):
        return str(value).upper()


class StampProcessor(Processor):
    kind = "stamp"

    def run(self, ctx):
        _set_path(ctx, self.spec.get("target_field", "stamped"), True)


class MyPlugin(Plugin):
    name = "my-plugin"
    version = "1.2.3"

    def get_analyzers(self):
        return [Analyzer("verbatim", keyword_tokenizer)]

    def get_field_mappers(self):
        return [ShoutMapper]

    def get_processors(self):
        return [StampProcessor]

    def get_queries(self):
        # exact_upper: term match on the uppercased value
        return {"exact_upper": lambda spec: TermQuery(
            spec["field"], str(spec["value"]).upper())}

    def get_rest_handlers(self, rc, node):
        rc.register("GET", "/_my_plugin/ping",
                    lambda req: (200, {"pong": True}))
'''


@pytest.fixture(autouse=True)
def _isolate_global_registries():
    """Plugin extensions install into process-global registries; snapshot
    and restore them so contributions don't leak across tests."""
    from elasticsearch_tpu.index import analysis as _an
    from elasticsearch_tpu.index.mapping import FIELD_TYPES
    from elasticsearch_tpu.ingest.service import PROCESSORS
    field_types = dict(FIELD_TYPES)
    processors = dict(PROCESSORS)
    analyzers = dict(_an.DEFAULT_REGISTRY._analyzers)
    parsers = dict(EXTRA_QUERY_PARSERS)
    yield
    FIELD_TYPES.clear(); FIELD_TYPES.update(field_types)
    PROCESSORS.clear(); PROCESSORS.update(processors)
    _an.DEFAULT_REGISTRY._analyzers = analyzers
    EXTRA_QUERY_PARSERS.clear(); EXTRA_QUERY_PARSERS.update(parsers)


@pytest.fixture
def plugin_dir(tmp_path):
    pdir = tmp_path / "plugins" / "my-plugin"
    pdir.mkdir(parents=True)
    (pdir / "plugin.py").write_text(PLUGIN_SRC)
    (pdir / "plugin.json").write_text(json.dumps(
        {"name": "my-plugin", "description": "test plugin",
         "version": "1.2.3"}))
    return tmp_path / "plugins"


def test_plugin_loading_and_extensions(tmp_path, plugin_dir):
    node = Node(str(tmp_path / "data"),
                settings={"path.plugins": str(plugin_dir)})
    try:
        assert [p["name"] for p in node.plugins.info()] == ["my-plugin"]

        # field mapper extension
        node.create_index_with_templates("t", mappings={"properties": {
            "code": {"type": "shout"}}})
        node.index_doc("t", "1", {"code": "abc"}, refresh="true")
        resp = node.search("t", {"query": {"term": {"code": "abc"}}})
        assert resp["hits"]["total"]["value"] == 1  # coerced to ABC both ways

        # plugin query parser
        resp = node.search("t", {"query": {"exact_upper": {
            "field": "code", "value": "abc"}}})
        assert resp["hits"]["total"]["value"] == 1

        # ingest processor extension
        node.ingest.put_pipeline("pl", {"processors": [{"stamp": {}}]})
        node.index_doc("t", "2", {"code": "x"}, pipeline="pl",
                       refresh="true")
        assert node.get_doc("t", "2")["_source"]["stamped"] is True

        # analyzer extension
        from elasticsearch_tpu.index.analysis import DEFAULT_REGISTRY
        assert DEFAULT_REGISTRY.get("verbatim").terms("One Two") == \
            ["One Two"]

        # REST handler + _cat/plugins
        from elasticsearch_tpu.rest.actions import register_all
        from elasticsearch_tpu.rest.controller import RestController
        rc = RestController()
        register_all(rc, node)
        status, body = rc.dispatch("GET", "/_my_plugin/ping", {}, b"",
                                   "application/json")
        assert status == 200 and body == {"pong": True}
        status, body = rc.dispatch("GET", "/_cat/plugins",
                                   {"format": "json"}, b"",
                                   "application/json")
        assert any(row.get("component") == "my-plugin" for row in body)
    finally:
        node.close()


def test_plugin_module_isolation(tmp_path):
    """Two plugins both shipping a `helper` import don't clash."""
    for i, marker in enumerate(("alpha", "beta")):
        pdir = tmp_path / "plugins" / f"p{i}"
        pdir.mkdir(parents=True)
        (pdir / "plugin.py").write_text(f'''
from elasticsearch_tpu.plugins import Plugin

MARKER = "{marker}"

class P{i}(Plugin):
    name = "p{i}"
    def get_queries(self):
        return {{"q_{marker}": lambda spec: None}}
''')
    svc = PluginsService(str(tmp_path / "plugins"))
    svc.load_all()
    assert len(svc.plugins) == 2
    mods = [type(p).__module__ for p in svc.plugins]
    assert mods[0] != mods[1]  # isolated module names


def test_broken_plugin_rejected(tmp_path):
    pdir = tmp_path / "plugins" / "bad"
    pdir.mkdir(parents=True)
    (pdir / "plugin.py").write_text("this is not python ][")
    svc = PluginsService(str(tmp_path / "plugins"))
    with pytest.raises(IllegalArgumentError):
        svc.load_plugin(str(pdir))


def test_plugin_picks_defined_class_not_imported_base(tmp_path):
    """An imported Plugin subclass (shared base) must not shadow the
    plugin's own class."""
    shared = tmp_path / "shared_base"
    shared.mkdir()
    (shared / "base_mod.py").write_text('''
from elasticsearch_tpu.plugins import Plugin

class SharedBase(Plugin):
    name = "WRONG-base"
''')
    pdir = tmp_path / "plugins" / "derived"
    pdir.mkdir(parents=True)
    (pdir / "plugin.py").write_text(f'''
import sys
sys.path.insert(0, {str(shared)!r})
from base_mod import SharedBase

class Derived(SharedBase):
    name = "derived-plugin"
''')
    svc = PluginsService(str(tmp_path / "plugins"))
    svc.load_all()
    assert svc.plugins[0].name == "derived-plugin"


def test_plugin_extensions_removed_on_close(tmp_path, plugin_dir):
    node = Node(str(tmp_path / "data"),
                settings={"path.plugins": str(plugin_dir)})
    from elasticsearch_tpu.plugins import EXTRA_QUERY_PARSERS as EQ
    assert "exact_upper" in EQ
    node.close()
    assert "exact_upper" not in EQ
    from elasticsearch_tpu.index.mapping import FIELD_TYPES
    assert "shout" not in FIELD_TYPES


def test_on_node_start_fires_once_without_rest(tmp_path):
    calls = []

    class P(Plugin):
        name = "p"

        def on_node_start(self, node):
            calls.append(node.node_id)

    node = Node(str(tmp_path / "data"))
    try:
        node.plugins.register(P())
        node.plugins._node_started = False
        node.plugins.start_node(node)
        node.plugins.start_node(node)  # idempotent
        from elasticsearch_tpu.rest.actions import register_all
        from elasticsearch_tpu.rest.controller import RestController
        register_all(RestController(), node)  # must not re-fire
        register_all(RestController(), node)
        assert calls == [node.node_id]
    finally:
        node.close()


def test_keystore_merge_does_not_mutate_caller_settings(tmp_path):
    ks_path = str(tmp_path / "d" / "config" / "tpu_search.keystore")
    ks = KeyStore.create(ks_path)
    ks.set("secret.token", "sssh")
    ks.save()
    caller_settings = {"some.flag": True}
    node = Node(str(tmp_path / "d"), settings=caller_settings)
    try:
        assert node.settings["secret.token"] == "sssh"
        assert "secret.token" not in caller_settings  # caller dict untouched
    finally:
        node.close()


# ------------------------------------------------------------------ keystore

def test_keystore_roundtrip_and_tamper_detection(tmp_path):
    path = str(tmp_path / "ks")
    ks = KeyStore.create(path, password="s3cret")
    ks.set("s3.client.default.secret_key", "AKIA...")
    ks.set("bootstrap.password", "hunter2")
    ks.save()

    ks2 = KeyStore.load(path, password="s3cret")
    assert ks2.list() == ["bootstrap.password",
                          "s3.client.default.secret_key"]
    assert ks2.get("bootstrap.password") == "hunter2"

    with pytest.raises(IllegalArgumentError):
        KeyStore.load(path, password="wrong")

    # bit-flip in ciphertext → integrity failure, not silent corruption
    blob = bytearray(open(path, "rb").read())
    blob[-1] ^= 0xFF
    open(path, "wb").write(bytes(blob))
    with pytest.raises(IllegalArgumentError):
        KeyStore.load(path, password="s3cret")

    # secrets are not plaintext on disk even with empty password
    ks3 = KeyStore.create(str(tmp_path / "ks2"))
    ks3.set("token", "super-secret-value")
    ks3.save()
    raw = open(str(tmp_path / "ks2"), "rb").read()
    assert b"super-secret-value" not in raw


def test_keystore_feeds_node_settings(tmp_path):
    ks_path = str(tmp_path / "data" / "config" / "tpu_search.keystore")
    ks = KeyStore.create(ks_path)
    ks.set("bootstrap.password", "from-keystore")
    ks.save()
    node = Node(str(tmp_path / "data"))
    try:
        assert node.settings["bootstrap.password"] == "from-keystore"
        assert node.keystore is not None
        # explicit settings win over keystore values
    finally:
        node.close()
    node = Node(str(tmp_path / "data"),
                settings={"bootstrap.password": "explicit"})
    try:
        assert node.settings["bootstrap.password"] == "explicit"
    finally:
        node.close()


def test_keystore_cli(tmp_path):
    path = str(tmp_path / "cli.keystore")
    env = {"PYTHONPATH": ".", "PATH": "/usr/bin:/bin"}

    def cli(*args, stdin=None):
        return subprocess.run(
            [sys.executable, "-m", "elasticsearch_tpu.keystore_cli", *args,
             "--path", path],
            input=stdin, capture_output=True, text=True, cwd=".", env=env)

    assert cli("create").returncode == 0
    assert cli("add", "xpack.secret", "--stdin",
               stdin="value1\n").returncode == 0
    out = cli("list")
    assert out.returncode == 0 and out.stdout.strip() == "xpack.secret"
    assert cli("remove", "xpack.secret").returncode == 0
    assert cli("list").stdout.strip() == ""
    # invalid setting name rejected
    bad = cli("add", "bad name!", "--stdin", stdin="v\n")
    assert bad.returncode != 0


def test_invalid_setting_name():
    ks = KeyStore("unused")
    with pytest.raises(IllegalArgumentError):
        ks.set("spaces not allowed", "v")


def test_keystore_v1_migration(tmp_path):
    """v1 files (single shared key) stay readable; saving rewrites as v2
    with separated enc/mac subkeys."""
    import hashlib
    import hmac as hmac_mod
    import json as json_mod
    import secrets as secrets_mod

    from elasticsearch_tpu.common import keystore as ks_mod

    path = str(tmp_path / "old.keystore")
    # hand-craft a v1 file with the legacy single-key scheme
    salt = secrets_mod.token_bytes(16)
    nonce = secrets_mod.token_bytes(16)
    key = hashlib.pbkdf2_hmac("sha256", b"pw", salt, ks_mod._ITERATIONS,
                              dklen=32)
    payload = json_mod.dumps({"s3.client.default.secret_key": "old"}).encode()
    ciphertext = ks_mod._keystream_xor(key, nonce, payload)
    header = ks_mod._MAGIC + bytes([1]) + salt + nonce
    mac = hmac_mod.new(key, header + ciphertext, hashlib.sha256).digest()
    with open(path, "wb") as f:
        f.write(header + mac + ciphertext)

    ks = ks_mod.KeyStore.load(path, "pw")
    assert ks.get("s3.client.default.secret_key") == "old"
    ks.save()
    with open(path, "rb") as f:
        assert f.read()[4] == ks_mod._VERSION  # upgraded on save
    ks2 = ks_mod.KeyStore.load(path, "pw")
    assert ks2.get("s3.client.default.secret_key") == "old"
