"""Analytics aggregations (boxplot/string_stats/top_metrics/matrix_stats),
extended pipeline aggs, enrich policies + processor, graph explore.
Reference: x-pack/plugin/analytics, modules/aggs-matrix-stats,
x-pack/plugin/enrich, x-pack/plugin/graph."""

import numpy as np
import pytest

from elasticsearch_tpu.node import Node


@pytest.fixture
def node(tmp_path):
    n = Node(str(tmp_path / "data"))
    yield n
    n.close()


@pytest.fixture
def sales(node):
    data = [
        {"price": 10.0, "qty": 1, "name": "alpha", "cat": "a"},
        {"price": 20.0, "qty": 2, "name": "beta", "cat": "a"},
        {"price": 30.0, "qty": 3, "name": "gamma", "cat": "b"},
        {"price": 40.0, "qty": 4, "name": "delta", "cat": "b"},
        {"price": 1000.0, "qty": 5, "name": "epsilon", "cat": "b"},
    ]
    for i, d in enumerate(data):
        node.index_doc("sales", str(i), d)
    node.indices.get("sales").refresh()
    return node


def agg(node, body):
    return node.search("sales", {"size": 0, "aggs": body})["aggregations"]


def test_boxplot(sales):
    out = agg(sales, {"b": {"boxplot": {"field": "price"}}})["b"]
    assert out["min"] == 10.0 and out["max"] == 1000.0
    assert out["q1"] == 20.0 and out["q2"] == 30.0 and out["q3"] == 40.0
    assert out["upper"] == 40.0  # 1000 is an outlier beyond 1.5*IQR


def test_string_stats(sales):
    out = agg(sales, {"s": {"string_stats": {"field": "cat.keyword"}}})["s"]
    assert out["count"] == 5
    assert out["min_length"] == 1 and out["max_length"] == 1
    assert out["entropy"] > 0.9  # 2/5 vs 3/5 split


def test_top_metrics(sales):
    out = agg(sales, {"t": {"top_metrics": {
        "metrics": {"field": "qty"},
        "sort": {"price": "desc"}, "size": 2}}})["t"]
    assert [t["metrics"]["qty"] for t in out["top"]] == [5.0, 4.0]
    assert out["top"][0]["sort"] == [1000.0]


def test_matrix_stats(sales):
    out = agg(sales, {"m": {"matrix_stats": {
        "fields": ["price", "qty"]}}})["m"]
    assert out["doc_count"] == 5
    by_name = {f["name"]: f for f in out["fields"]}
    assert by_name["qty"]["mean"] == 3.0
    # price and qty are positively correlated
    assert by_name["price"]["correlation"]["qty"] > 0.5
    assert by_name["price"]["correlation"]["price"] == pytest.approx(1.0)


def test_extended_stats_and_percentiles_bucket(sales):
    out = agg(sales, {
        "cats": {"terms": {"field": "cat.keyword"},
                 "aggs": {"avg_p": {"avg": {"field": "price"}}}},
        "es": {"extended_stats_bucket": {"buckets_path": "cats>avg_p"}},
        "pb": {"percentiles_bucket": {"buckets_path": "cats>avg_p",
                                      "percents": [50.0]}},
    })
    assert out["es"]["count"] == 2
    assert out["es"]["avg"] == pytest.approx((15.0 + 1070.0 / 3) / 2)
    assert out["pb"]["values"]["50.0"] is not None


# ------------------------------------------------------------------- enrich

def test_enrich_policy_and_processor(node):
    for i, d in enumerate([
            {"email": "amy@x.io", "name": "Amy", "title": "CTO"},
            {"email": "bob@x.io", "name": "Bob", "title": "Dev"}]):
        node.index_doc("users", str(i), d)
    node.indices.get("users").refresh()

    node.enrich.put_policy("users-policy", {"match": {
        "indices": ["users"], "match_field": "email",
        "enrich_fields": ["name", "title"]}})
    result = node.enrich.execute_policy("users-policy")
    assert result["documents"] == 2
    assert node.indices.exists(".enrich-users-policy")

    node.ingest.put_pipeline("add-user", {"processors": [
        {"enrich": {"policy_name": "users-policy", "field": "author",
                    "target_field": "user"}}]})
    resp = node.index_doc("posts", "1", {"author": "amy@x.io", "t": "hi"},
                          pipeline="add-user", refresh="true")
    doc = node.get_doc("posts", "1")
    assert doc["_source"]["user"] == {"email": "amy@x.io", "name": "Amy",
                                      "title": "CTO"}
    # no match → field untouched
    node.index_doc("posts", "2", {"author": "zed@x.io"},
                   pipeline="add-user", refresh="true")
    assert "user" not in node.get_doc("posts", "2")["_source"]

    pol = node.enrich.get_policy("users-policy")
    assert pol["policies"][0]["config"]["match"]["match_field"] == "email"
    node.enrich.delete_policy("users-policy")
    from elasticsearch_tpu.common.errors import ResourceNotFoundError
    with pytest.raises(ResourceNotFoundError):
        node.enrich.get_policy("users-policy")


def test_enrich_target_mutation_does_not_corrupt_lookup(node):
    """Mutating the enriched target of one doc must not leak into the shared
    lookup table or other docs."""
    node.index_doc("users", "1", {"email": "a@x.io", "name": "Amy"},
                   refresh="true")
    node.enrich.put_policy("p", {"match": {
        "indices": ["users"], "match_field": "email",
        "enrich_fields": ["name"]}})
    node.enrich.execute_policy("p")
    node.ingest.put_pipeline("pl", {"processors": [
        {"enrich": {"policy_name": "p", "field": "who",
                    "target_field": "u"}},
        {"set": {"field": "u.injected", "value": "x"}}]})
    node.index_doc("d", "1", {"who": "a@x.io"}, pipeline="pl",
                   refresh="true")
    node.index_doc("d", "2", {"who": "a@x.io"}, pipeline="pl",
                   refresh="true")
    # second doc got a clean copy, and the lookup entry is untouched beyond
    # its own injected set
    assert node.get_doc("d", "2")["_source"]["u"] == {
        "email": "a@x.io", "name": "Amy", "injected": "x"}
    assert "injected" not in node.enrich.lookups["p"]["a@x.io"]


def test_enrich_policy_pages_beyond_search_window(node):
    """Policy execution must cover the whole source index, not one page."""
    for i in range(1500):
        node.index_doc("big", str(i), {"k": f"key{i}", "v": i})
    node.indices.get("big").refresh()
    node.enrich.put_policy("bigp", {"match": {
        "indices": ["big"], "match_field": "k", "enrich_fields": ["v"]}})
    out = node.enrich.execute_policy("bigp")
    assert out["documents"] == 1500
    assert node.enrich.lookup("bigp", "key1400")[0]["v"] == 1400


def test_enrich_geo_match(node):
    node.index_doc("zones", "1", {
        "area": {"type": "envelope", "coordinates": [[0.0, 10.0], [10.0, 0.0]]},
        "zone_name": "alpha-zone"})
    node.indices.get("zones").refresh()
    node.enrich.put_policy("geo-policy", {"geo_match": {
        "indices": ["zones"], "match_field": "area",
        "enrich_fields": ["zone_name"]}})
    node.enrich.execute_policy("geo-policy")
    hits = node.enrich.lookup("geo-policy", {"lat": 5.0, "lon": 5.0})
    assert len(hits) == 1 and hits[0]["zone_name"] == "alpha-zone"
    assert node.enrich.lookup("geo-policy", {"lat": 50.0, "lon": 50.0}) == []


# -------------------------------------------------------------------- graph

def test_graph_explore(node):
    # people buy items; explore item→person→item co-purchase structure
    purchases = [
        ("p1", "guitar"), ("p1", "amp"), ("p2", "guitar"), ("p2", "amp"),
        ("p3", "guitar"), ("p3", "drums"), ("p4", "piano"),
    ]
    for i, (person, item) in enumerate(purchases):
        node.index_doc("orders", str(i), {"person": person, "item": item})
    node.indices.get("orders").refresh()

    resp = node.graph.explore("orders", {
        "query": {"term": {"item.keyword": "guitar"}},
        "vertices": [{"field": "person.keyword", "size": 5}],
        "connections": {"vertices": [{"field": "item.keyword", "size": 5}]},
        "use_significance": False,
    })
    assert not resp["timed_out"]
    by_term = {(v["field"], v["term"]): v for v in resp["vertices"]}
    # depth 0: guitar buyers
    assert by_term[("person.keyword", "p1")]["depth"] == 0
    assert by_term[("person.keyword", "p3")]["depth"] == 0
    # depth 1: their other purchases
    assert by_term[("item.keyword", "amp")]["depth"] == 1
    assert by_term[("item.keyword", "drums")]["depth"] == 1
    assert ("item.keyword", "piano") not in by_term  # unconnected
    # connections reference vertex array indices
    for c in resp["connections"]:
        assert 0 <= c["source"] < len(resp["vertices"])
        assert 0 <= c["target"] < len(resp["vertices"])
    srcs = {resp["vertices"][c["source"]]["term"] for c in resp["connections"]}
    assert {"p1", "p2", "p3"} <= srcs


def test_graph_rest(node):
    from elasticsearch_tpu.rest.actions import register_all
    from elasticsearch_tpu.rest.controller import RestController
    import json as _json
    rc = RestController()
    register_all(rc, node)
    node.index_doc("g", "1", {"a": "x", "b": "y"}, refresh="true")
    status, body = rc.dispatch(
        "POST", "/g/_graph/explore", {},
        _json.dumps({"query": {"match_all": {}},
                     "vertices": [{"field": "a.keyword"}],
                     "use_significance": False}).encode(),
        "application/json")
    assert status == 200 and body["vertices"][0]["term"] == "x"


def test_graph_multi_hop_with_controls(node):
    """Three-hop crawl with per-vertex include/exclude, sample controls,
    and normalized wave weights (TransportGraphExploreAction contract):
    guitar → buyers → their items → other buyers of those items."""
    purchases = [
        ("p1", "guitar"), ("p1", "amp"), ("p2", "guitar"), ("p2", "amp"),
        ("p3", "guitar"), ("p3", "drums"), ("p4", "amp"), ("p4", "mic"),
        ("p5", "piano"),
    ]
    for i, (person, item) in enumerate(purchases):
        node.index_doc("orders3", str(i), {"person": person, "item": item})
    node.indices.get("orders3").refresh()

    resp = node.graph.explore("orders3", {
        "query": {"term": {"item.keyword": "guitar"}},
        "controls": {"use_significance": False, "sample_size": 50},
        "vertices": [{"field": "person.keyword", "size": 10}],
        "connections": {
            "vertices": [{"field": "item.keyword", "size": 10,
                          "exclude": ["guitar"]}],
            "connections": {
                "vertices": [{"field": "person.keyword", "size": 10}]}},
    })
    assert not resp["timed_out"]
    by_term = {(v["field"], v["term"]): v for v in resp["vertices"]}
    # wave structure: buyers(0) -> items(1) -> people(2)
    assert by_term[("person.keyword", "p1")]["depth"] == 0
    assert by_term[("item.keyword", "amp")]["depth"] == 1
    assert ("item.keyword", "guitar") not in by_term  # excluded
    # p4 never bought a guitar but shares the amp: reachable only at hop 2
    assert by_term[("person.keyword", "p4")]["depth"] == 2
    assert ("person.keyword", "p5") not in by_term     # disconnected
    # weights normalize per wave: every weight in (0, 1]
    assert all(0 < v["weight"] <= 1.0 for v in resp["vertices"])
    # every connection joins adjacent depths, keyed by array index
    for c in resp["connections"]:
        s, t = resp["vertices"][c["source"]], resp["vertices"][c["target"]]
        assert t["depth"] <= s["depth"] + 1


def test_graph_timeout_reports_timed_out(node):
    node.index_doc("gt", "1", {"a": "x", "b": "y"}, refresh="true")
    resp = node.graph.explore("gt", {
        "query": {"match_all": {}},
        "controls": {"use_significance": False, "timeout": 0},
        "vertices": [{"field": "a.keyword"}],
        "connections": {"vertices": [{"field": "b.keyword"}]},
    })
    # deadline already passed before the first hop: partial result,
    # honestly flagged (the reference's timedOut contract)
    assert resp["timed_out"] is True
    assert all(v["depth"] == 0 for v in resp["vertices"])


def test_graph_include_restricts_crawl(node):
    node.index_doc("gi", "1", {"person": "p1", "item": "amp"})
    node.index_doc("gi", "2", {"person": "p1", "item": "drums"})
    node.indices.get("gi").refresh()
    resp = node.graph.explore("gi", {
        "query": {"term": {"person.keyword": "p1"}},
        "controls": {"use_significance": False},
        "vertices": [{"field": "person.keyword"}],
        "connections": {"vertices": [{"field": "item.keyword",
                                      "include": ["amp"]}]},
    })
    items = {v["term"] for v in resp["vertices"]
             if v["field"] == "item.keyword"}
    assert items == {"amp"}
