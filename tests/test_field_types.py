"""Extended field types (reference SURVEY.md §2.4 mapper inventory):
binary, range family, completion, search_as_you_type, token_count, wildcard,
flattened, constant_keyword, murmur3, histogram, annotated_text, geo_shape,
sparse_vector, alias."""

import pytest

from elasticsearch_tpu.common.errors import MapperParsingError
from elasticsearch_tpu.index.engine import Engine
from elasticsearch_tpu.index.mapping import MapperService, parse_wkt
from elasticsearch_tpu.search.queries import SearchContext, parse_query

MAPPING = {
    "properties": {
        "blob": {"type": "binary"},
        "age_range": {"type": "integer_range"},
        "temp_range": {"type": "float_range"},
        "when": {"type": "date_range"},
        "net": {"type": "ip_range"},
        "suggest": {"type": "completion"},
        "title": {"type": "search_as_you_type"},
        "body_words": {"type": "token_count", "analyzer": "standard"},
        "path": {"type": "wildcard"},
        "attrs": {"type": "flattened"},
        "env": {"type": "constant_keyword"},
        "h": {"type": "murmur3"},
        "latency": {"type": "histogram"},
        "note": {"type": "annotated_text"},
        "area": {"type": "geo_shape"},
        "sparse": {"type": "sparse_vector"},
        "byline": {"type": "alias", "path": "author"},
        "author": {"type": "keyword"},
        "views": {"type": "long"},
    }
}

DOCS = {
    "1": {"blob": "aGVsbG8=", "age_range": {"gte": 10, "lte": 20},
          "temp_range": {"gt": 0.5, "lt": 1.5},
          "when": {"gte": "2020-01-01", "lt": "2020-02-01"},
          "net": "10.0.0.0/8",
          "suggest": {"input": ["nevermind", "never say never"], "weight": 5},
          "title": "quick brown fox", "body_words": "one two three",
          "path": "/var/log/syslog", "attrs": {"color": "red",
                                               "spec": {"ram": "16gb"}},
          "env": "prod", "h": "abc",
          "latency": {"values": [1.0, 5.0, 10.0], "counts": [3, 2, 1]},
          "note": "visited [Berlin](Capital&City) today",
          "area": {"type": "polygon", "coordinates":
                   [[[0.0, 0.0], [10.0, 0.0], [10.0, 10.0], [0.0, 10.0],
                     [0.0, 0.0]]]},
          "sparse": {"f1": 0.5, "f2": 2.0},
          "author": "amy", "views": 10},
    "2": {"age_range": {"gte": 15, "lte": 30}, "suggest": "nevada",
          "title": "quiet black cat", "body_words": "one two",
          "path": "/usr/bin/python", "attrs": {"color": "blue"},
          "env": "prod", "net": {"gte": "192.168.0.1", "lte": "192.168.0.10"},
          "area": {"type": "point", "coordinates": [50.0, 50.0]},
          "author": "bob", "views": 20},
}


@pytest.fixture(scope="module")
def engine(tmp_path_factory):
    e = Engine(str(tmp_path_factory.mktemp("ft") / "shard"),
               MapperService(MAPPING))
    for doc_id, d in DOCS.items():
        e.index(doc_id, d)
    e.refresh()
    yield e
    e.close()


@pytest.fixture(scope="module")
def ctx(engine):
    return SearchContext(engine.acquire_searcher(), engine.mapper_service)


def run(ctx, q):
    ds = parse_query(q).execute(ctx)
    return sorted(ctx.reader.get_id(int(r)) for r in ds.rows)


# ------------------------------------------------------------------- binary

def test_binary_stored_and_invalid_rejected(ctx):
    assert ctx.reader.get_doc_value("blob", 0) == "aGVsbG8="
    ms = MapperService({"properties": {"b": {"type": "binary"}}})
    with pytest.raises(MapperParsingError):
        ms.parse_document("x", {"b": "not base64!!!"})


# -------------------------------------------------------------------- ranges

def test_integer_range_term_membership(ctx):
    assert run(ctx, {"term": {"age_range": 12}}) == ["1"]
    assert run(ctx, {"term": {"age_range": 18}}) == ["1", "2"]
    assert run(ctx, {"term": {"age_range": 25}}) == ["2"]
    assert run(ctx, {"term": {"age_range": 99}}) == []


def test_range_query_relations(ctx):
    assert run(ctx, {"range": {"age_range": {"gte": 18, "lte": 40}}}) \
        == ["1", "2"]  # intersects by default
    assert run(ctx, {"range": {"age_range": {"gte": 5, "lte": 40,
                                             "relation": "within"}}}) \
        == ["1", "2"]
    assert run(ctx, {"range": {"age_range": {"gte": 12, "lte": 18,
                                             "relation": "contains"}}}) \
        == ["1"]


def test_float_range_exclusive_bounds(ctx):
    assert run(ctx, {"term": {"temp_range": 0.5}}) == []  # gt excluded
    assert run(ctx, {"term": {"temp_range": 1.0}}) == ["1"]


def test_date_range(ctx):
    assert run(ctx, {"term": {"when": "2020-01-15"}}) == ["1"]
    assert run(ctx, {"term": {"when": "2020-02-01"}}) == []  # lt bound


def test_ip_range_cidr(ctx):
    assert run(ctx, {"term": {"net": "10.1.2.3"}}) == ["1"]
    assert run(ctx, {"term": {"net": "192.168.0.5"}}) == ["2"]
    assert run(ctx, {"term": {"net": "172.16.0.1"}}) == []


# --------------------------------------------------------------- completion

def test_completion_suggester(ctx):
    from elasticsearch_tpu.search.extras import execute_suggest
    out = execute_suggest(ctx, {"s": {"prefix": "nev",
                                      "completion": {"field": "suggest"}}})
    texts = [o["text"] for o in out["s"][0]["options"]]
    assert "nevermind" in texts and "nevada" in texts
    out = execute_suggest(ctx, {"s": {"prefix": "never s",
                                      "completion": {"field": "suggest"}}})
    assert [o["text"] for o in out["s"][0]["options"]] == ["never say never"]


# ------------------------------------------------------- search_as_you_type

def test_search_as_you_type_subfields_and_bool_prefix(ctx):
    # shingle subfields exist and index shingles
    assert run(ctx, {"match": {"title._2gram": "quick brown"}}) == ["1"]
    assert run(ctx, {"match": {"title._3gram": "quick brown fox"}}) == ["1"]
    # as-you-type: last token is a prefix
    assert run(ctx, {"multi_match": {
        "query": "quick bro", "type": "bool_prefix",
        "fields": ["title", "title._2gram", "title._3gram"]}}) == ["1"]
    assert run(ctx, {"match_bool_prefix": {"title": "qui"}}) == ["1", "2"]


# ------------------------------------------------------------- token_count

def test_token_count(ctx):
    assert run(ctx, {"range": {"body_words": {"gte": 3}}}) == ["1"]
    assert run(ctx, {"term": {"body_words": 2}}) == ["2"]


# ----------------------------------------------------------------- wildcard

def test_wildcard_field(ctx):
    assert run(ctx, {"wildcard": {"path": "*syslog"}}) == ["1"]
    assert run(ctx, {"wildcard": {"path": "/usr/*"}}) == ["2"]


# ---------------------------------------------------------------- flattened

def test_flattened_root_and_keyed(ctx):
    assert run(ctx, {"term": {"attrs": "red"}}) == ["1"]       # any leaf
    assert run(ctx, {"term": {"attrs.color": "blue"}}) == ["2"]
    assert run(ctx, {"term": {"attrs.spec.ram": "16gb"}}) == ["1"]
    assert run(ctx, {"term": {"attrs.color": "green"}}) == []


def test_flattened_depth_limit():
    ms = MapperService({"properties": {
        "f": {"type": "flattened", "depth_limit": 1}}})
    with pytest.raises(MapperParsingError):
        ms.parse_document("x", {"f": {"a": {"b": {"c": "deep"}}}})


# --------------------------------------------------------- constant_keyword

def test_constant_keyword(ctx):
    assert run(ctx, {"term": {"env": "prod"}}) == ["1", "2"]
    ms = MapperService({"properties": {
        "e": {"type": "constant_keyword", "value": "prod"}}})
    with pytest.raises(MapperParsingError):
        ms.parse_document("x", {"e": "staging"})


# ------------------------------------------------------------------ murmur3

def test_murmur3_hash_stored(ctx):
    v = ctx.reader.get_doc_value("h", 0)
    assert isinstance(v, int) and -(1 << 31) <= v < (1 << 31)


# ---------------------------------------------------------------- histogram

def test_histogram_validation(ctx):
    assert ctx.reader.get_doc_value("latency", 0) == {
        "values": [1.0, 5.0, 10.0], "counts": [3, 2, 1]}
    ms = MapperService({"properties": {"l": {"type": "histogram"}}})
    with pytest.raises(MapperParsingError):
        ms.parse_document("x", {"l": {"values": [2.0, 1.0], "counts": [1, 1]}})
    with pytest.raises(MapperParsingError):
        ms.parse_document("x", {"l": {"values": [1.0], "counts": [1, 2]}})


# ----------------------------------------------------------- annotated_text

def test_annotated_text_indexes_annotations(ctx):
    assert run(ctx, {"match": {"note": "berlin"}}) == ["1"]   # visible text
    assert run(ctx, {"match": {"note": "capital"}}) == ["1"]  # annotation


# ---------------------------------------------------------------- geo_shape

def test_geo_shape_relations(ctx):
    q = {"geo_shape": {"area": {"shape": {
        "type": "envelope", "coordinates": [[5.0, 8.0], [8.0, 5.0]]},
        "relation": "intersects"}}}
    assert run(ctx, q) == ["1"]
    q = {"geo_shape": {"area": {"shape": {
        "type": "envelope", "coordinates": [[40.0, 60.0], [60.0, 40.0]]}}}}
    assert run(ctx, q) == ["2"]  # point inside envelope
    q = {"geo_shape": {"area": {"shape": {
        "type": "envelope", "coordinates": [[-20.0, 30.0], [30.0, -20.0]]},
        "relation": "within"}}}
    assert run(ctx, q) == ["1"]
    q = {"geo_shape": {"area": {"shape": {
        "type": "envelope", "coordinates": [[80.0, 90.0], [90.0, 80.0]]},
        "relation": "disjoint"}}}
    assert run(ctx, q) == ["1", "2"]


def test_wkt_parsing():
    assert parse_wkt("POINT (30 10)") == {"type": "point",
                                          "coordinates": [30.0, 10.0]}
    env = parse_wkt("ENVELOPE(-10, 10, 20, -20)")
    assert env == {"type": "envelope",
                   "coordinates": [[-10.0, 20.0], [10.0, -20.0]]}
    poly = parse_wkt("POLYGON ((0 0, 4 0, 4 4, 0 0))")
    assert poly["type"] == "polygon" and len(poly["coordinates"][0]) == 4


# ------------------------------------------------------------ sparse_vector

def test_sparse_vector_stored(ctx):
    assert ctx.reader.get_doc_value("sparse", 0) == {"f1": 0.5, "f2": 2.0}


# -------------------------------------------------------------------- alias

def test_alias_resolves_in_queries_and_aggs(ctx):
    assert run(ctx, {"term": {"byline": "amy"}}) == ["1"]
    assert run(ctx, {"exists": {"field": "byline"}}) == ["1", "2"]
    from elasticsearch_tpu.search.aggregations import numeric_values
    import numpy as np
    # alias to a numeric field flows through aggregations
    ms = ctx.mapper_service
    assert ms.get("byline").type_name == "keyword"
    assert ms.resolve_field("byline") == "author"


def test_multivalued_range_array(tmp_path):
    """Arrays of dict field values must index as multiple values, not be
    misrouted to object parsing."""
    e = Engine(str(tmp_path / "s"), MapperService({"properties": {
        "r": {"type": "integer_range"}}}))
    e.index("1", {"r": [{"gte": 1, "lte": 2}, {"gte": 5, "lte": 6}]})
    e.refresh()
    c = SearchContext(e.acquire_searcher(), e.mapper_service)
    assert [c.reader.get_id(int(x)) for x in
            parse_query({"term": {"r": 5}}).execute(c).rows] == ["1"]
    assert parse_query({"term": {"r": 3}}).execute(c).rows.size == 0
    # no bogus dynamic fields from the dict bounds
    assert e.mapper_service.get("r.gte") is None
    e.close()


def test_constant_keyword_query_does_not_fix_value():
    ms = MapperService({"properties": {"e": {"type": "constant_keyword"}}})
    mapper = ms.get("e")
    assert mapper.index_terms("staging") == ["staging"]  # query coercion
    assert mapper.params.get("value") is None            # mapping unchanged
    ms.parse_document("1", {"e": "prod"})                # first doc fixes it
    assert mapper.params["value"] == "prod"


def test_prefix_and_match_through_alias(tmp_path):
    e = Engine(str(tmp_path / "s"), MapperService({"properties": {
        "name": {"type": "keyword"},
        "desc": {"type": "text"},
        "name_alias": {"type": "alias", "path": "name"},
        "desc_alias": {"type": "alias", "path": "desc"}}}))
    e.index("1", {"name": "falcon", "desc": "a fast bird"})
    e.refresh()
    c = SearchContext(e.acquire_searcher(), e.mapper_service)

    def ids(q):
        return [c.reader.get_id(int(x))
                for x in parse_query(q).execute(c).rows]

    assert ids({"prefix": {"name_alias": "fal"}}) == ["1"]
    assert ids({"wildcard": {"name_alias": "*con"}}) == ["1"]
    ds = parse_query({"match": {"desc_alias": "fast"}}).execute(c)
    ds2 = parse_query({"match": {"desc": "fast"}}).execute(c)
    assert ds.rows.tolist() == ds2.rows.tolist()
    assert ds.scores.tolist() == ds2.scores.tolist()  # same BM25 stats
    e.close()


def test_alias_write_rejected():
    ms = MapperService({"properties": {
        "a": {"type": "keyword"},
        "al": {"type": "alias", "path": "a"}}})
    with pytest.raises(MapperParsingError):
        ms.parse_document("x", {"al": "boom"})
