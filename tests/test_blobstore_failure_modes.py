"""Blobstore failure modes and backend contract parity.

The durable-elasticity gates lean on three promises this file pins:

* backend parity — `MemoryBlobStore` and `FsBlobStore` expose the SAME
  observable contract (roundtrip, overwrite, missing-read error,
  idempotent delete, prefix listing), so every snapshot/recovery test
  that runs against memory holds for fs and vice versa;
* corrupt/partial blobs are REJECTED AND RETRYABLE — a content-
  addressed blob whose bytes stop hashing to their name raises on read,
  is evicted so the dedup fast-path cannot pin the corruption, and the
  next put+get round-trips cleanly;
* concurrent snapshot + delete — snapshots share blobs by content;
  deleting one snapshot while others are being created must never
  corrupt a survivor's restore.
"""

import threading

import pytest

from elasticsearch_tpu.node import Node
from elasticsearch_tpu.snapshots.blobstore import (
    BlobStoreError, FsBlobStore, MemoryBlobStore,
)
from elasticsearch_tpu.snapshots.service import Repository, RepositoryError

BACKENDS = ("fs", "memory")


def _store(kind, tmp_path, tag):
    if kind == "fs":
        return FsBlobStore(str(tmp_path / f"fs_{tag}"))
    # memory stores are shared by name: key them on the test's tmp dir
    # so parallel tests never collide
    return MemoryBlobStore(f"{tmp_path.name}_{tag}")


def _repository(kind, tmp_path, tag):
    if kind == "fs":
        return Repository(f"r_{tag}", "fs",
                          {"location": str(tmp_path / f"repo_{tag}")})
    return Repository(f"r_{tag}", "memory",
                      {"location": f"{tmp_path.name}_repo_{tag}"})


# ---------------------------------------------------------------------------
# shared contract suite: every assertion runs identically per backend
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("kind", BACKENDS)
def test_contract_roundtrip_overwrite_list_delete(kind, tmp_path):
    store = _store(kind, tmp_path, "contract")
    store.write_blob("blobs/aa", b"alpha")
    store.write_blob("blobs/bb", b"beta")
    store.write_blob("snapshots/s1.json", b"{}")

    assert store.read_blob("blobs/aa") == b"alpha"
    assert store.exists("blobs/aa")
    assert not store.exists("blobs/zz")

    # overwrite is last-write-wins, not append
    store.write_blob("blobs/aa", b"alpha2")
    assert store.read_blob("blobs/aa") == b"alpha2"

    # listing is prefix-scoped and sorted
    assert store.list_blobs("blobs/") == ["blobs/aa", "blobs/bb"]
    assert store.list_blobs("snapshots/") == ["snapshots/s1.json"]

    # delete is effective and idempotent (a retried cleanup must not
    # blow up because the first attempt already won)
    store.delete_blob("blobs/aa")
    store.delete_blob("blobs/aa")
    assert not store.exists("blobs/aa")
    assert store.list_blobs("blobs/") == ["blobs/bb"]


@pytest.mark.parametrize("kind", BACKENDS)
def test_contract_missing_read_raises(kind, tmp_path):
    store = _store(kind, tmp_path, "missing")
    with pytest.raises(BlobStoreError):
        store.read_blob("blobs/never_written")


@pytest.mark.parametrize("kind", BACKENDS)
def test_corrupt_blob_rejected_evicted_and_retryable(kind, tmp_path):
    """Bit rot / partial upload: the verified read fails, the corrupt
    blob stops existing (so put_bytes' dedup can't keep skipping the
    repair), and a retried put+get round-trips."""
    repo = _repository(kind, tmp_path, "corrupt")
    payload = b"block-bytes" * 512
    digest = repo.put_bytes(payload)
    assert repo.get_bytes(digest) == payload

    # corrupt the stored copy underneath the repository (truncation —
    # the partial-upload shape — plus flipped tail bytes)
    repo.store.write_blob(f"blobs/{digest}", payload[:-7] + b"XXXXXXX")
    with pytest.raises(RepositoryError, match="digest verification"):
        repo.get_bytes(digest)
    assert not repo.has_blob(digest), \
        "corrupt blob survived the failed read — dedup would pin it"

    # the retry: re-upload actually writes (no stale dedup), read heals
    assert repo.put_bytes(payload) == digest
    assert repo.get_bytes(digest) == payload


@pytest.mark.parametrize("kind", BACKENDS)
def test_missing_blob_is_repository_error(kind, tmp_path):
    repo = _repository(kind, tmp_path, "gone")
    digest = repo.put_bytes(b"here today")
    repo.store.delete_blob(f"blobs/{digest}")
    with pytest.raises(RepositoryError, match="missing blob"):
        repo.get_bytes(digest)


def test_fs_partial_upload_never_visible(tmp_path):
    """FsBlobStore writes through a `.tmp` + atomic rename: a crash
    mid-upload leaves only the temp file, which must read as ABSENT —
    not as a truncated blob."""
    store = FsBlobStore(str(tmp_path / "fs_partial"))
    store.write_blob("blobs/good", b"complete")
    # a torn upload: the temp file exists, the final name never did
    with open(store._path("blobs/torn") + ".tmp", "wb") as f:
        f.write(b"half a blo")
    assert not store.exists("blobs/torn")
    assert store.list_blobs("blobs/") == ["blobs/good"]
    with pytest.raises(BlobStoreError):
        store.read_blob("blobs/torn")


# ---------------------------------------------------------------------------
# concurrent snapshot + delete
# ---------------------------------------------------------------------------

def test_concurrent_snapshot_create_and_delete(tmp_path):
    """Creates race deletes against one repository: content-addressed
    blobs are shared across snapshots, so deleting older snapshots while
    new ones are being cut must leave every surviving manifest fully
    restorable (the delete removes the manifest, never a blob a
    survivor still references)."""
    node = Node(str(tmp_path))
    try:
        node.create_index_with_templates(
            "race", mappings={"properties": {"n": {"type": "long"}}})
        ops = []
        for i in range(40):
            ops.append({"index": {"_index": "race", "_id": str(i)}})
            ops.append({"n": i})
        node.bulk(ops)
        node.indices.get("race").refresh()
        node.snapshots.put_repository("mem", {
            "type": "memory",
            "settings": {"location": f"{tmp_path.name}_race"}})

        errors = []
        created = []

        def creator():
            for i in range(6):
                try:
                    node.snapshots.create_snapshot(
                        "mem", f"c{i}", {"indices": "race"})
                    created.append(f"c{i}")
                except Exception as exc:  # noqa: BLE001
                    errors.append(("create", f"c{i}", exc))

        def deleter():
            # chase the creator: delete everything but the newest
            for _ in range(60):
                names = sorted(created)
                for name in names[:-1]:
                    try:
                        node.snapshots.delete_snapshot("mem", name)
                    except Exception:
                        pass  # already deleted by a previous lap
                if len(created) >= 6:
                    break

        t1 = threading.Thread(target=creator)
        t2 = threading.Thread(target=deleter)
        t1.start(); t2.start()
        t1.join(); t2.join()
        assert not errors, errors

        repo = node.snapshots.get_repository("mem")
        survivors = repo.list_snapshots()
        assert "c5" in survivors, survivors

        # the newest survivor restores completely despite the churn
        node.indices.delete_index("race")
        node.snapshots.restore_snapshot("mem", "c5", {"indices": "race"})
        node.indices.get("race").refresh()
        resp = node.search("race", {"query": {"match_all": {}}, "size": 0})
        assert resp["hits"]["total"]["value"] == 40
    finally:
        node.close()
