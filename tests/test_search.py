"""Search layer tests: query DSL, BM25, knn, script_score, sort, fetch, aggs."""

import numpy as np
import pytest

from elasticsearch_tpu.index.engine import Engine
from elasticsearch_tpu.index.mapping import MapperService
from elasticsearch_tpu.search.queries import SearchContext, parse_query
from elasticsearch_tpu.search.service import execute_fetch_phase, execute_query_phase

MAPPING = {
    "properties": {
        "title": {"type": "text"},
        "body": {"type": "text", "analyzer": "english"},
        "tag": {"type": "keyword"},
        "tags": {"type": "keyword"},
        "views": {"type": "long"},
        "price": {"type": "float"},
        "published": {"type": "date"},
        "active": {"type": "boolean"},
        "vec": {"type": "dense_vector", "dims": 3, "similarity": "cosine"},
    }
}

DOCS = [
    {"title": "the quick brown fox", "body": "foxes are quick animals", "tag": "animal",
     "tags": ["wild", "fast"], "views": 100, "price": 9.99,
     "published": "2020-01-15", "active": True, "vec": [1.0, 0.0, 0.0]},
    {"title": "lazy dogs sleep", "body": "dogs sleeping lazily all day", "tag": "animal",
     "tags": ["domestic"], "views": 50, "price": 19.99,
     "published": "2020-02-20", "active": False, "vec": [0.9, 0.1, 0.0]},
    {"title": "quick sort algorithm", "body": "sorting quickly with quicksort", "tag": "cs",
     "tags": ["code"], "views": 500, "price": 0.0,
     "published": "2020-03-10", "active": True, "vec": [0.0, 1.0, 0.0]},
    {"title": "brown bread recipe", "body": "baking brown bread", "tag": "food",
     "tags": ["baking", "fast"], "views": 75, "price": 4.5,
     "published": "2021-01-05", "active": True, "vec": [0.0, 0.0, 1.0]},
    {"title": "fox hunting banned", "body": "the fox is safe now", "tag": "news",
     "tags": ["wild"], "views": 200, "price": 2.0,
     "published": "2021-06-30", "active": False, "vec": [0.7, 0.7, 0.0]},
]


@pytest.fixture(scope="module")
def engine(tmp_path_factory):
    e = Engine(str(tmp_path_factory.mktemp("search") / "shard"), MapperService(MAPPING))
    for i, d in enumerate(DOCS):
        e.index(str(i), d)
    e.refresh()
    yield e
    e.close()


@pytest.fixture(scope="module")
def ctx(engine):
    return SearchContext(engine.acquire_searcher(), engine.mapper_service)


def run_query(ctx, q):
    ds = parse_query(q).execute(ctx)
    ids = [ctx.reader.get_id(int(r)) for r in ds.rows]
    return ids, ds


def test_match_all(ctx):
    ids, _ = run_query(ctx, {"match_all": {}})
    assert sorted(ids) == ["0", "1", "2", "3", "4"]


def test_term_keyword(ctx):
    ids, _ = run_query(ctx, {"term": {"tag": "animal"}})
    assert sorted(ids) == ["0", "1"]


def test_terms_multivalued(ctx):
    ids, _ = run_query(ctx, {"terms": {"tags": ["wild", "code"]}})
    assert sorted(ids) == ["0", "2", "4"]


def test_match_bm25_ranking(ctx):
    ids, ds = run_query(ctx, {"match": {"title": "quick fox"}})
    assert set(ids) >= {"0", "2", "4"}
    # doc 0 matches both terms -> highest score
    best = ids[int(np.argmax(ds.scores))]
    assert best == "0"


def test_match_operator_and(ctx):
    ids, _ = run_query(ctx, {"match": {"title": {"query": "quick fox", "operator": "and"}}})
    assert ids == ["0"]


def test_match_with_stemming(ctx):
    # english analyzer: "sleeping" stems to match "sleep"... body has "sleeping"
    ids, _ = run_query(ctx, {"match": {"body": "sleep"}})
    assert "1" in ids


def test_match_phrase(ctx):
    ids, _ = run_query(ctx, {"match_phrase": {"title": "quick brown fox"}})
    assert ids == ["0"]
    ids, _ = run_query(ctx, {"match_phrase": {"title": "brown quick"}})
    assert ids == []


def test_range_numeric(ctx):
    ids, _ = run_query(ctx, {"range": {"views": {"gte": 100, "lt": 500}}})
    assert sorted(ids) == ["0", "4"]


def test_range_date(ctx):
    ids, _ = run_query(ctx, {"range": {"published": {"gte": "2021-01-01"}}})
    assert sorted(ids) == ["3", "4"]


def test_bool_query(ctx):
    q = {"bool": {
        "must": [{"match": {"title": "quick"}}],
        "filter": [{"term": {"active": True}}],
        "must_not": [{"term": {"tag": "cs"}}],
    }}
    ids, _ = run_query(ctx, q)
    assert ids == ["0"]


def test_bool_should_scoring(ctx):
    q = {"bool": {"should": [{"match": {"title": "fox"}}, {"term": {"tag": "food"}}]}}
    ids, _ = run_query(ctx, q)
    assert sorted(ids) == ["0", "3", "4"]


def test_exists(ctx):
    ids, _ = run_query(ctx, {"exists": {"field": "vec"}})
    assert len(ids) == 5


def test_ids_query(ctx):
    ids, _ = run_query(ctx, {"ids": {"values": ["1", "3"]}})
    assert sorted(ids) == ["1", "3"]


def test_prefix_wildcard_regexp_fuzzy(ctx):
    ids, _ = run_query(ctx, {"prefix": {"tag": "ani"}})
    assert sorted(ids) == ["0", "1"]
    ids, _ = run_query(ctx, {"wildcard": {"tag": "f*d"}})
    assert ids == ["3"]
    ids, _ = run_query(ctx, {"regexp": {"tag": "c[st]"}})
    assert ids == ["2"]
    ids, _ = run_query(ctx, {"fuzzy": {"tag": {"value": "animol"}}})
    assert sorted(ids) == ["0", "1"]


def test_constant_score_and_boost(ctx):
    _, ds = run_query(ctx, {"constant_score": {"filter": {"term": {"tag": "cs"}}, "boost": 3.0}})
    assert np.allclose(ds.scores, 3.0)


def test_knn_query(ctx):
    ids, ds = run_query(ctx, {"knn": {"field": "vec", "query_vector": [1.0, 0.05, 0.0], "k": 2}})
    assert set(ids) == {"0", "1"}
    # scores follow (1+cos)/2 convention
    assert (ds.scores <= 1.0).all() and (ds.scores >= 0.0).all()


def test_knn_with_filter(ctx):
    q = {"knn": {"field": "vec", "query_vector": [1.0, 0.0, 0.0], "k": 3,
                 "filter": {"term": {"active": True}}}}
    ids, _ = run_query(ctx, q)
    assert "1" not in ids and "4" not in ids


def test_script_score_vector(ctx):
    q = {"script_score": {
        "query": {"match_all": {}},
        "script": {"source": "cosineSimilarity(params.qv, 'vec') + 1.0",
                   "params": {"qv": [1.0, 0.0, 0.0]}}}}
    ids, ds = run_query(ctx, q)
    assert len(ids) == 5
    best = ids[int(np.argmax(ds.scores))]
    assert best == "0"
    assert ds.scores.max() == pytest.approx(2.0, abs=1e-5)


def test_script_score_doc_values(ctx):
    q = {"script_score": {
        "query": {"match_all": {}},
        "script": {"source": "doc['views'].value * 2 + params.base",
                   "params": {"base": 1}}}}
    ids, ds = run_query(ctx, q)
    by_id = dict(zip(ids, ds.scores))
    assert by_id["2"] == pytest.approx(1001.0)


def test_function_score(ctx):
    q = {"function_score": {
        "query": {"match_all": {}},
        "functions": [{"field_value_factor": {"field": "views", "factor": 0.01}}],
        "boost_mode": "replace"}}
    ids, ds = run_query(ctx, q)
    by_id = dict(zip(ids, ds.scores))
    assert by_id["2"] == pytest.approx(5.0)


# ---------------------------------------------------------------------------
# full query phase + fetch
# ---------------------------------------------------------------------------

def search(engine, body):
    reader = engine.acquire_searcher()
    result = execute_query_phase(reader, engine.mapper_service, body)
    hits = execute_fetch_phase(reader, engine.mapper_service, body, result,
                               from_offset=int(body.get("from", 0) or 0))
    return result, hits


def test_query_phase_sort_by_field(engine):
    result, hits = search(engine, {"query": {"match_all": {}},
                                   "sort": [{"views": "desc"}], "size": 3})
    assert [h["_id"] for h in hits] == ["2", "4", "0"]
    assert hits[0]["sort"] == [500.0]


def test_query_phase_from_size(engine):
    _, hits = search(engine, {"query": {"match_all": {}},
                              "sort": [{"views": "asc"}], "from": 2, "size": 2})
    assert [h["_id"] for h in hits] == ["0", "4"]


def test_search_after(engine):
    _, hits = search(engine, {"query": {"match_all": {}}, "sort": [{"views": "asc"}],
                              "search_after": [75], "size": 10})
    assert [h["_id"] for h in hits] == ["0", "4", "2"]


def test_source_filtering(engine):
    _, hits = search(engine, {"query": {"ids": {"values": ["0"]}},
                              "_source": ["title", "views"]})
    assert set(hits[0]["_source"].keys()) == {"title", "views"}


def test_docvalue_and_script_fields(engine):
    _, hits = search(engine, {"query": {"ids": {"values": ["2"]}},
                              "docvalue_fields": ["views"],
                              "script_fields": {"double_views": {
                                  "script": {"source": "doc['views'].value * 2"}}}})
    assert hits[0]["fields"]["views"] == [500]
    assert hits[0]["fields"]["double_views"] == [1000.0]


def test_highlight(engine):
    _, hits = search(engine, {"query": {"match": {"title": "fox"}},
                              "highlight": {"fields": {"title": {}}}})
    hl = {h["_id"]: h.get("highlight", {}) for h in hits}
    assert "<em>fox</em>" in hl["0"]["title"][0]


def test_min_score_and_total(engine):
    result, _ = search(engine, {"query": {"match": {"title": "quick"}}, "min_score": 1e9})
    assert result.total_hits == 0


def test_post_filter_does_not_affect_aggs(engine):
    result, hits = search(engine, {
        "query": {"match_all": {}},
        "post_filter": {"term": {"tag": "cs"}},
        "aggs": {"by_tag": {"terms": {"field": "tag"}}}})
    assert len(hits) == 1 and hits[0]["_id"] == "2"
    buckets = {b["key"]: b["doc_count"] for b in result.aggregations["by_tag"]["buckets"]}
    assert buckets["animal"] == 2  # aggs scope ignores post_filter


def test_rescore_window(engine):
    result, hits = search(engine, {
        "query": {"match": {"title": "quick"}},
        "rescore": {"window_size": 10, "query": {
            "rescore_query": {"term": {"tag": "cs"}},
            "query_weight": 1.0, "rescore_query_weight": 100.0}}})
    assert hits[0]["_id"] == "2"  # boosted by rescore


# ---------------------------------------------------------------------------
# aggregations
# ---------------------------------------------------------------------------

def agg(engine, aggs, query=None):
    body = {"query": query or {"match_all": {}}, "aggs": aggs, "size": 0}
    result = execute_query_phase(engine.acquire_searcher(), engine.mapper_service, body)
    return result.aggregations


def test_terms_agg(engine):
    out = agg(engine, {"t": {"terms": {"field": "tag"}}})
    buckets = out["t"]["buckets"]
    assert buckets[0]["key"] == "animal" and buckets[0]["doc_count"] == 2


def test_terms_agg_multivalued(engine):
    out = agg(engine, {"t": {"terms": {"field": "tags"}}})
    counts = {b["key"]: b["doc_count"] for b in out["t"]["buckets"]}
    assert counts["wild"] == 2 and counts["fast"] == 2


def test_metric_aggs(engine):
    out = agg(engine, {
        "avg_views": {"avg": {"field": "views"}},
        "stats_price": {"stats": {"field": "price"}},
        "extended": {"extended_stats": {"field": "views"}},
        "card": {"cardinality": {"field": "tag"}},
        "pct": {"percentiles": {"field": "views", "percents": [50]}},
    })
    assert out["avg_views"]["value"] == pytest.approx(185.0)
    assert out["stats_price"]["max"] == pytest.approx(19.99)
    assert out["card"]["value"] == 4
    assert out["pct"]["values"]["50.0"] == pytest.approx(100.0)
    assert out["extended"]["std_deviation"] > 0


def test_histogram_agg(engine):
    out = agg(engine, {"h": {"histogram": {"field": "views", "interval": 100}}})
    counts = {b["key"]: b["doc_count"] for b in out["h"]["buckets"]}
    assert counts[0.0] == 2 and counts[100.0] == 1 and counts[500.0] == 1


def test_date_histogram_agg(engine):
    out = agg(engine, {"d": {"date_histogram": {"field": "published",
                                                "calendar_interval": "year"}}})
    buckets = out["d"]["buckets"]
    assert [b["doc_count"] for b in buckets] == [3, 2]
    assert buckets[0]["key_as_string"].startswith("2020-01-01")


def test_range_agg_with_subagg(engine):
    out = agg(engine, {"r": {"range": {"field": "views",
                                       "ranges": [{"to": 100}, {"from": 100}]},
                             "aggs": {"avg_price": {"avg": {"field": "price"}}}}})
    b = out["r"]["buckets"]
    assert b[0]["doc_count"] == 2 and b[1]["doc_count"] == 3
    assert b[0]["avg_price"]["value"] == pytest.approx((19.99 + 4.5) / 2)


def test_filters_agg(engine):
    out = agg(engine, {"f": {"filters": {"filters": {
        "animals": {"term": {"tag": "animal"}},
        "active": {"term": {"active": True}}}}}})
    assert out["f"]["buckets"]["animals"]["doc_count"] == 2
    assert out["f"]["buckets"]["active"]["doc_count"] == 3


def test_pipeline_aggs(engine):
    out = agg(engine, {
        "years": {"date_histogram": {"field": "published", "calendar_interval": "year"},
                  "aggs": {"total_views": {"sum": {"field": "views"}}}},
        "avg_per_year": {"avg_bucket": {"buckets_path": "years>total_views"}},
        "max_year": {"max_bucket": {"buckets_path": "years>total_views"}},
    })
    assert out["avg_per_year"]["value"] == pytest.approx((650 + 275) / 2)
    assert out["max_year"]["value"] == pytest.approx(650.0)


def test_cumulative_and_derivative(engine):
    out = agg(engine, {
        "years": {"date_histogram": {"field": "published", "calendar_interval": "year"},
                  "aggs": {"v": {"sum": {"field": "views"}},
                           "cum": {"cumulative_sum": {"buckets_path": "v"}},
                           "deriv": {"derivative": {"buckets_path": "v"}}}}})
    buckets = out["years"]["buckets"]
    assert buckets[0]["cum"]["value"] == pytest.approx(650.0)
    assert buckets[1]["cum"]["value"] == pytest.approx(925.0)
    assert buckets[1]["deriv"]["value"] == pytest.approx(275.0 - 650.0)


def test_composite_agg(engine):
    out = agg(engine, {"c": {"composite": {
        "sources": [{"tag": {"terms": {"field": "tag"}}}], "size": 2}}})
    assert len(out["c"]["buckets"]) == 2
    after = out["c"]["after_key"]
    out2 = agg(engine, {"c": {"composite": {
        "sources": [{"tag": {"terms": {"field": "tag"}}}], "size": 10, "after": after}}})
    keys = [b["key"]["tag"] for b in out2["c"]["buckets"]]
    assert keys == sorted(keys)
    total = len(out["c"]["buckets"]) + len(out2["c"]["buckets"])
    assert total == 4


def test_query_string(ctx):
    # AND binds both neighbors
    ids, _ = run_query(ctx, {"query_string": {"query": "quick AND fox", "fields": ["title"]}})
    assert ids == ["0"]
    # OR overrides default_operator=and
    ids, _ = run_query(ctx, {"query_string": {"query": "bread OR algorithm",
                                              "fields": ["title"], "default_operator": "and"}})
    assert sorted(ids) == ["2", "3"]
    # field:value + negation + phrase
    ids, _ = run_query(ctx, {"query_string": {"query": 'title:fox -title:banned'}})
    assert ids == ["0"]
    ids, _ = run_query(ctx, {"query_string": {"query": '"brown bread"', "fields": ["title"]}})
    assert ids == ["3"]
    # free text over all text fields
    ids, _ = run_query(ctx, {"query_string": {"query": "quicksort"}})
    assert ids == ["2"]
    # invalid operator rejected
    import pytest as _pt
    from elasticsearch_tpu.common.errors import ParsingError
    with _pt.raises(ParsingError):
        run_query(ctx, {"query_string": {"query": "a", "default_operator": "snd"}})
