"""Cross-cluster search + cross-cluster replication.

Reference behaviors: RemoteClusterService + SearchResponseMerger (CCS),
x-pack/plugin/ccr follower change-tailing, auto-follow patterns.
"""

import json

import pytest

from elasticsearch_tpu.node import Node
from elasticsearch_tpu.rest.actions import register_all
from elasticsearch_tpu.rest.controller import RestController


class Client:
    def __init__(self, node):
        self.rc = RestController()
        register_all(self.rc, node)

    def req(self, method, path, body=None, **query):
        raw = json.dumps(body).encode() if body is not None else b""
        return self.rc.dispatch(method, path, {k: str(v) for k, v in query.items()},
                                raw, "application/json")


@pytest.fixture
def clusters(tmp_path):
    local = Node(str(tmp_path / "local"), cluster_name="local")
    remote = Node(str(tmp_path / "remote"), cluster_name="east")
    local.remotes.register("east", remote)
    yield local, remote
    local.close()
    remote.close()


# --------------------------------------------------------------------- CCS

def test_ccs_pure_remote_search(clusters):
    local, remote = clusters
    remote.index_doc("logs", "1", {"msg": "remote hello"})
    remote.indices.get("logs").refresh()
    result = local.search("east:logs", {"query": {"match": {"msg": "hello"}}})
    assert result["hits"]["total"]["value"] == 1
    assert result["hits"]["hits"][0]["_index"] == "east:logs"


def test_ccs_mixed_local_remote_merge(clusters):
    local, remote = clusters
    local.index_doc("logs", "L", {"msg": "hello local"})
    local.indices.get("logs").refresh()
    remote.index_doc("logs", "R", {"msg": "hello remote"})
    remote.indices.get("logs").refresh()
    result = local.search("logs,east:logs",
                          {"query": {"match": {"msg": "hello"}}})
    assert result["hits"]["total"]["value"] == 2
    indices = {h["_index"] for h in result["hits"]["hits"]}
    assert indices == {"logs", "east:logs"}
    assert result["_clusters"]["total"] == 2


def test_ccs_remote_info_endpoint(clusters):
    local, _ = clusters
    c = Client(local)
    st, body = c.req("GET", "/_remote/info")
    assert body["east"]["connected"] is True


def test_ccs_unknown_remote_404(clusters):
    local, _ = clusters
    with pytest.raises(Exception):
        local.search("west:idx", {"query": {"match_all": {}}})


# --------------------------------------------------------------------- CCR

def test_ccr_follow_replicates_and_tails(clusters):
    local, remote = clusters
    remote.index_doc("leader", "1", {"v": "one"})
    remote.index_doc("leader", "2", {"v": "two"})
    remote.indices.get("leader").refresh()
    c = Client(local)
    st, body = c.req("PUT", "/follower/_ccr/follow",
                     {"remote_cluster": "east", "leader_index": "leader"})
    assert st == 200 and body["index_following_started"]
    # initial copy
    local.indices.get("follower").refresh()
    assert local.indices.get("follower").doc_count() == 2
    # new leader writes arrive on next poll
    remote.index_doc("leader", "3", {"v": "three"})
    remote.indices.get("leader").refresh()
    local.ccr.run_once()
    assert local.indices.get("follower").doc_count() == 3
    # deletes propagate
    remote.delete_doc("leader", "1")
    remote.indices.get("leader").refresh()
    local.ccr.run_once()
    assert local.indices.get("follower").doc_count() == 2
    st, body = c.req("GET", "/_ccr/stats")
    shard = body["follow_stats"]["indices"][0]["shards"][0]
    assert shard["leader_index"] == "leader"
    assert shard["operations_written"] >= 3


def test_ccr_pause_resume_unfollow(clusters):
    local, remote = clusters
    remote.index_doc("leader", "1", {"v": 1})
    remote.indices.get("leader").refresh()
    c = Client(local)
    c.req("PUT", "/f2/_ccr/follow",
          {"remote_cluster": "east", "leader_index": "leader"})
    c.req("POST", "/f2/_ccr/pause_follow")
    remote.index_doc("leader", "2", {"v": 2})
    remote.indices.get("leader").refresh()
    local.ccr.run_once()
    local.indices.get("f2").refresh()
    assert local.indices.get("f2").doc_count() == 1   # paused: no tailing
    c.req("POST", "/f2/_ccr/resume_follow")
    assert local.indices.get("f2").doc_count() == 2
    # unfollow requires pause first
    st, _ = c.req("POST", "/f2/_ccr/unfollow")
    assert st == 400
    c.req("POST", "/f2/_ccr/pause_follow")
    st, _ = c.req("POST", "/f2/_ccr/unfollow")
    assert st == 200


def test_ccr_auto_follow(clusters):
    local, remote = clusters
    c = Client(local)
    c.req("PUT", "/_ccr/auto_follow/metrics", {
        "remote_cluster": "east",
        "leader_index_patterns": ["metrics-*"],
        "follow_index_pattern": "{{leader_index}}-copy"})
    remote.index_doc("metrics-2024", "1", {"m": 1})
    remote.indices.get("metrics-2024").refresh()
    local.ccr.run_once()
    assert local.indices.exists("metrics-2024-copy")
    local.indices.get("metrics-2024-copy").refresh()
    assert local.indices.get("metrics-2024-copy").doc_count() == 1
    st, body = c.req("GET", "/_ccr/auto_follow/metrics")
    assert body["patterns"][0]["name"] == "metrics"
