"""Generational device segments (`elasticsearch_tpu/segments/`).

Pins the write-while-search lifecycle:
* byte-parity of generational vs monolithic search (appends, tombstoned
  rows, k deeper than one generation, per-query filters);
* merge-policy tier math (tier-full runs, L0 overflow, tombstone GC);
* copy-on-write safety — a search dispatched against a pre-merge
  snapshot lands correct results after the merge installs;
* the `segments.*` kernel grid stays closed under strict dispatch with a
  zero-recompile second pass;
* the pre-subsystem rebuild stall is counted (monolithic path) and the
  generational path reports zero rebuilds;
* mesh graduation (multidevice): a merge moves the base generation into
  the sharded corpus, result-identical.
"""

import tempfile
from collections import namedtuple

import numpy as np
import pytest

from elasticsearch_tpu.index.mapping import DenseVectorFieldMapper
from elasticsearch_tpu.index.segment import Segment, SegmentView, ShardReader
from elasticsearch_tpu.ops import dispatch
from elasticsearch_tpu.segments import TieredMergePolicy
from elasticsearch_tpu.segments.generation import generation_tier
from elasticsearch_tpu.vectors.store import VectorStoreShard

SEED = 42
DIMS = 16


def _seg(seg_id, base, mat, deleted=None):
    n = mat.shape[0]
    return Segment(
        seg_id=seg_id, base=base, num_docs=n, postings={},
        field_lengths={}, total_terms={}, doc_values={},
        vectors={"v": (mat, np.ones(n, dtype=bool))},
        ids=[f"d{base + i}" for i in range(n)], sources=[None] * n,
        seq_nos=np.arange(base, base + n, dtype=np.int64))


def _mapper(similarity="cosine"):
    return DenseVectorFieldMapper(
        "v", {"type": "dense_vector", "dims": DIMS,
              "similarity": similarity})


def _stores(**gen_kwargs):
    """(generational, monolithic) store pair; host mirrors off so both
    run the DEVICE path — that is the byte-parity oracle (host-vs-device
    routing parity has its own suite in test_serving.py)."""
    gen = VectorStoreShard(segments_enabled=True, host_mirror_max_bytes=0,
                           segments_background_merge=False, **gen_kwargs)
    mono = VectorStoreShard(segments_enabled=False,
                            host_mirror_max_bytes=0)
    return gen, mono


def _corpus_segments(rng, sizes):
    segs, base = [], 0
    for i, n in enumerate(sizes):
        mat = rng.standard_normal((n, DIMS)).astype(np.float32)
        segs.append(_seg(i, base, mat))
        base += n
    return segs


def _sync_both(gen, mono, mapper, views):
    reader_a = ShardReader(views)
    gen.sync(reader_a, {"v": mapper})
    # a reader is a point-in-time object; give the second store its own
    mono.sync(ShardReader([SegmentView(v.segment) for v in views]),
              {"v": mapper})


def _assert_parity(gen, mono, rng, ks=(3, 10, 64), n_queries=4,
                   filter_rows=None):
    for _ in range(n_queries):
        q = rng.standard_normal(DIMS).astype(np.float32)
        for k in ks:
            a = gen.search("v", q, k, filter_rows=filter_rows)
            b = mono.search("v", q, k, filter_rows=filter_rows)
            assert np.array_equal(a[0], b[0]), (k, a[0], b[0])
            assert np.array_equal(a[1], b[1]), (k, a[1], b[1])


@pytest.fixture
def strict_dispatch():
    old = dispatch.DISPATCH.strict
    dispatch.DISPATCH.strict = True
    yield dispatch.DISPATCH
    dispatch.DISPATCH.strict = old


# ---------------------------------------------------------------------------
# Merge-policy tier math
# ---------------------------------------------------------------------------

FakeGen = namedtuple("FakeGen", "tier n_rows dead_rows")


def _fg(tier, rows=None, dead=0):
    return FakeGen(tier, rows if rows is not None else 128 << tier, dead)


class TestTieredMergePolicy:
    def test_tier_from_rows_follows_row_bucket_ladder(self):
        assert generation_tier(1) == 0
        assert generation_tier(128) == 0
        assert generation_tier(129) == 1
        assert generation_tier(256) == 1
        assert generation_tier(512) == 2
        assert generation_tier(100_000) == \
            (dispatch.bucket_gen_rows(100_000) // 128).bit_length() - 1

    def test_row_bucket_ladder_is_pow2_then_capped_multiples(self):
        assert dispatch.bucket_gen_rows(1) == 128
        assert dispatch.bucket_gen_rows(129) == 256
        assert dispatch.bucket_gen_rows(1 << 20) == 1 << 20
        assert dispatch.bucket_gen_rows((1 << 20) + 1) == 2 << 20
        assert dispatch.in_gen_row_grid(256)
        assert not dispatch.in_gen_row_grid(384)
        assert dispatch.in_gen_row_grid(3 << 20)

    def test_tier_full_run_merges_first_tier_size(self):
        pol = TieredMergePolicy(tier_size=3, max_l0=8)
        gens = [_fg(4), _fg(0), _fg(0), _fg(0), _fg(0)]
        spec = pol.select(gens)
        assert (spec.start, spec.stop, spec.reason) == (1, 4, "tier_full")

    def test_run_must_be_contiguous_same_tier(self):
        pol = TieredMergePolicy(tier_size=3, max_l0=8)
        gens = [_fg(4), _fg(0), _fg(1), _fg(0), _fg(1), _fg(0)]
        # no contiguous same-tier run of 3 and only 3 L0s (<= max_l0)
        assert pol.select(gens) is None

    def test_l0_overflow_merges_trailing_run(self):
        pol = TieredMergePolicy(tier_size=10, max_l0=3)
        gens = [_fg(4), _fg(0), _fg(0), _fg(0), _fg(0)]
        spec = pol.select(gens)
        assert (spec.start, spec.stop, spec.reason) == (1, 5,
                                                        "l0_overflow")

    def test_tombstone_gc_selects_mostly_dead_generation(self):
        pol = TieredMergePolicy(tier_size=10, max_l0=10,
                                gc_deleted_fraction=0.5)
        gens = [_fg(4, rows=2048, dead=100), _fg(1, rows=200, dead=150)]
        spec = pol.select(gens)
        assert (spec.start, spec.stop, spec.reason) == (1, 2,
                                                        "tombstone_gc")

    def test_steady_state_selects_nothing(self):
        pol = TieredMergePolicy(tier_size=4, max_l0=8)
        assert pol.select([_fg(5), _fg(3), _fg(1), _fg(0)]) is None
        assert pol.select([]) is None

    def test_force_merge_spec(self):
        assert TieredMergePolicy.force([_fg(2), _fg(0)]).reason == "force"
        assert TieredMergePolicy.force([_fg(2)]) is None
        assert TieredMergePolicy.force(
            [_fg(2, rows=512, dead=3)]) is not None


# ---------------------------------------------------------------------------
# Byte parity vs the monolithic path
# ---------------------------------------------------------------------------

class TestGenerationalParity:
    def test_append_refreshes_seal_and_stay_byte_identical(self):
        rng = np.random.default_rng(SEED)
        gen, mono = _stores()
        mapper = _mapper()
        segs = _corpus_segments(rng, [400, 60, 33, 200])
        for i in range(1, len(segs) + 1):
            _sync_both(gen, mono, mapper,
                       [SegmentView(s) for s in segs[:i]])
            _assert_parity(gen, mono, rng)
        st = gen.segment_stats()
        assert st["full_rebuilds"] == 0
        assert st["seals"] == 3
        assert st["rebuilds_avoided"] == 3
        assert st["generations"] == 4

    def test_k_deeper_than_one_generation(self):
        """k larger than every L0 (and the base) still merges exactly:
        a small generation contributes ALL its rows as candidates."""
        rng = np.random.default_rng(SEED + 1)
        gen, mono = _stores()
        mapper = _mapper("l2_norm")
        segs = _corpus_segments(rng, [150, 20, 40])
        for i in range(1, len(segs) + 1):
            _sync_both(gen, mono, mapper,
                       [SegmentView(s) for s in segs[:i]])
        _assert_parity(gen, mono, rng, ks=(25, 100, 210, 500))

    def test_deletes_become_tombstones_not_rebuilds(self):
        rng = np.random.default_rng(SEED + 2)
        gen, mono = _stores()
        mapper = _mapper()
        segs = _corpus_segments(rng, [300, 80])
        _sync_both(gen, mono, mapper, [SegmentView(s) for s in segs])
        # deletes across both generations
        views = [SegmentView(segs[0], deleted_locals={0, 17, 250}),
                 SegmentView(segs[1], deleted_locals={5})]
        gen.sync(ShardReader(views), {"v": mapper})
        mono.sync(ShardReader(
            [SegmentView(segs[0], deleted_locals={0, 17, 250}),
             SegmentView(segs[1], deleted_locals={5})]), {"v": mapper})
        _assert_parity(gen, mono, rng, ks=(5, 50, 380))
        st = gen.segment_stats()
        assert st["full_rebuilds"] == 0
        assert st["tombstoned_rows"] == 4
        assert st["tombstone_deletes"] == 4
        # deleted engine rows can never surface
        q = rng.standard_normal(DIMS).astype(np.float32)
        rows, _ = gen.search("v", q, 380)
        assert not np.isin([0, 17, 250, 305], rows).any()

    def test_filtered_search_parity_across_generations(self):
        rng = np.random.default_rng(SEED + 3)
        gen, mono = _stores()
        mapper = _mapper()
        segs = _corpus_segments(rng, [256, 64])
        _sync_both(gen, mono, mapper, [SegmentView(s) for s in segs])
        fr = np.sort(rng.choice(320, 90, replace=False)).astype(np.int64)
        _assert_parity(gen, mono, rng, ks=(10, 64), filter_rows=fr)

    def test_merges_consolidate_and_preserve_results(self):
        rng = np.random.default_rng(SEED + 4)
        gen, mono = _stores(segments_tier_size=3)
        mapper = _mapper()
        segs = _corpus_segments(rng, [300] + [50] * 5)
        for i in range(1, len(segs) + 1):
            _sync_both(gen, mono, mapper,
                       [SegmentView(s) for s in segs[:i]])
        gc = gen._gens["v"]
        before = gen.segment_stats()["generations"]
        assert gc.run_merges() >= 1
        after = gen.segment_stats()
        assert after["generations"] < before
        assert after["merges"] >= 1
        assert after["merge_nanos"] > 0
        _assert_parity(gen, mono, rng)
        # force-merge back to one clean generation
        assert gc.force_merge()
        assert gen.segment_stats()["generations"] == 1
        _assert_parity(gen, mono, rng)

    def test_background_merge_thread_drains(self):
        rng = np.random.default_rng(SEED + 5)
        gen = VectorStoreShard(segments_enabled=True,
                               host_mirror_max_bytes=0,
                               segments_tier_size=3,
                               segments_merge_budget_ms=5.0)
        mapper = _mapper()
        segs = _corpus_segments(rng, [300] + [40] * 5)
        for i in range(1, len(segs) + 1):
            gen.sync(ShardReader([SegmentView(s) for s in segs[:i]]),
                     {"v": mapper})
        gc = gen._gens["v"]
        gc.drain()
        st = gen.segment_stats()
        assert st["merges"] >= 1
        assert gc.merge_pending() is False

    def test_segment_rewrite_falls_back_to_one_rebuild(self):
        """An engine-level segment rewrite (rows re-based) cannot be
        expressed as a delta — it rebuilds, once, with its reason."""
        rng = np.random.default_rng(SEED + 6)
        gen, _ = _stores()
        mapper = _mapper()
        mat = rng.standard_normal((200, DIMS)).astype(np.float32)
        gen.sync(ShardReader([SegmentView(_seg(0, 0, mat))]),
                 {"v": mapper})
        # same vectors, rewritten into one segment at a different base
        gen.sync(ShardReader([SegmentView(_seg(7, 64, mat))]),
                 {"v": mapper})
        st = gen.segment_stats()
        assert st["full_rebuilds"] == 1
        assert st["rebuild_reasons"] == {"segment_rewrite": 1}

    def test_monolithic_path_counts_the_rebuild_stall(self):
        """satellite: with segments disabled, every delta refresh is a
        full-corpus rebuild — now counted + reasoned so the bench can
        hold the pre-subsystem cost against the generational row."""
        rng = np.random.default_rng(SEED + 7)
        mono = VectorStoreShard(segments_enabled=False,
                                host_mirror_max_bytes=0)
        mapper = _mapper()
        segs = _corpus_segments(rng, [200, 40])
        mono.sync(ShardReader([SegmentView(segs[0])]), {"v": mapper})
        mono.sync(ShardReader([SegmentView(s) for s in segs]),
                  {"v": mapper})
        mono.sync(ShardReader(
            [SegmentView(segs[0], deleted_locals={3}),
             SegmentView(segs[1])]), {"v": mapper})
        st = mono.segment_stats()
        assert st["full_rebuilds"] == 2
        assert st["rebuild_reasons"] == {"append_headroom": 1,
                                         "deletes": 1}
        assert st["rebuilds_avoided"] == 0


# ---------------------------------------------------------------------------
# Copy-on-write + strict grid
# ---------------------------------------------------------------------------

class TestCopyOnWriteAndGrid:
    def test_search_dispatched_mid_merge_reads_old_generation_set(self):
        """A snapshot taken before a merge stays fully servable after
        the merge installs: the install is copy-on-write, nothing the
        old set references is mutated or donated."""
        rng = np.random.default_rng(SEED + 8)
        gen, mono = _stores(segments_tier_size=3)
        mapper = _mapper()
        segs = _corpus_segments(rng, [300] + [50] * 4)
        for i in range(1, len(segs) + 1):
            _sync_both(gen, mono, mapper,
                       [SegmentView(s) for s in segs[:i]])
        gc = gen._gens["v"]
        snap = gc.snapshot()
        q = rng.standard_normal(DIMS).astype(np.float32)
        expected = mono.search("v", q, 10)
        # "dispatch" against the pre-merge snapshot, then merge, then
        # land — exactly the pipelined path's ordering
        handle = gen._dispatch_generational(
            snap, gen.field("v"), 10, "bf16", [(q, None)], None)
        assert gc.run_merges() >= 1
        assert gc.snapshot().generations != snap.generations
        (rows, scores), = gen.finalize_many(handle)
        assert np.array_equal(rows, expected[0])
        assert np.array_equal(scores, expected[1])
        # and the old snapshot still dispatches fresh searches correctly
        handle2 = gen._dispatch_generational(
            snap, gen.field("v"), 10, "bf16", [(q, None)], None)
        (rows2, scores2), = gen.finalize_many(handle2)
        assert np.array_equal(rows2, expected[0])

    def test_tombstone_install_is_copy_on_write(self):
        rng = np.random.default_rng(SEED + 9)
        gen, _ = _stores()
        mapper = _mapper()
        segs = _corpus_segments(rng, [200, 40])
        _sync_both(gen, VectorStoreShard(segments_enabled=False,
                                         host_mirror_max_bytes=0),
                   mapper, [SegmentView(s) for s in segs])
        gc = gen._gens["v"]
        snap = gc.snapshot()
        old_tombstones = [g.tombstones for g in snap.generations]
        gen.sync(ShardReader([SegmentView(segs[0], deleted_locals={1}),
                              SegmentView(segs[1])]), {"v": mapper})
        # the old snapshot's generations were replaced, never mutated
        for t in old_tombstones:
            assert not t.any()
        assert gc.snapshot().dead_rows == 1

    def test_segments_grid_strict_zero_recompile_second_pass(
            self, strict_dispatch):
        """The `segments.*` kernel grid is CLOSED: first pass compiles
        in-grid under strict mode, an identical second pass runs
        entirely from the executable cache."""
        rng = np.random.default_rng(SEED + 10)
        gen, mono = _stores()
        mapper = _mapper()
        segs = _corpus_segments(rng, [500, 37, 150])
        for i in range(1, len(segs) + 1):
            _sync_both(gen, mono, mapper,
                       [SegmentView(s) for s in segs[:i]])
        q = rng.standard_normal(DIMS).astype(np.float32)
        fr = np.arange(0, 600, 3, dtype=np.int64)
        first = gen.search("v", q, 10)
        first_f = gen.search("v", q, 10, filter_rows=fr)
        c0 = dispatch.DISPATCH.compile_count()
        again = gen.search("v", q, 10)
        again_f = gen.search("v", q, 10, filter_rows=fr)
        assert dispatch.DISPATCH.compile_count() == c0, \
            "segments second pass recompiled"
        assert np.array_equal(first[0], again[0])
        assert np.array_equal(first_f[0], again_f[0])
        buckets = dispatch.DISPATCH.stats()["buckets"]
        assert any(k.startswith("segments.knn") for k in buckets)

    def test_sealed_generation_warmup_entries_precompile(self):
        rng = np.random.default_rng(SEED + 11)
        gen, _ = _stores()
        mapper = _mapper()
        segs = _corpus_segments(rng, [200, 40])
        gen.sync(ShardReader([SegmentView(s) for s in segs[:1]]),
                 {"v": mapper})
        gen.sync(ShardReader([SegmentView(s) for s in segs]),
                 {"v": mapper})
        l0 = gen._gens["v"].snapshot().generations[1]
        entries = l0.warmup_entries(DIMS, "cosine")
        assert entries and all(e[0] == "segments.knn" for e in entries)
        dispatch.DISPATCH.warmup(entries, background=False)
        c0 = dispatch.DISPATCH.compile_count()
        dispatch.DISPATCH.warmup(entries, background=False)
        assert dispatch.DISPATCH.compile_count() == c0


# ---------------------------------------------------------------------------
# Node-level wiring: profile + stats + settings
# ---------------------------------------------------------------------------

class TestNodeWiring:
    def test_profile_and_stats_sections(self):
        from elasticsearch_tpu.node import Node
        node = Node(tempfile.mkdtemp())
        try:
            node.create_index_with_templates(
                "t", mappings={"properties": {
                    "v": {"type": "dense_vector", "dims": 8}}})
            rng = np.random.default_rng(5)
            for batch in range(3):
                for i in range(30):
                    node.index_doc("t", f"{batch}_{i}",
                                   {"v": rng.standard_normal(8).tolist()})
                node.indices.get("t").refresh()
            node.delete_doc("t", "0_0")
            node.indices.get("t").refresh()
            body = {"knn": {"field": "v",
                            "query_vector":
                                rng.standard_normal(8).tolist(),
                            "k": 5, "num_candidates": 5},
                    "size": 5, "profile": True}
            resp = node.search("t", body)
            knn_prof = resp["profile"]["shards"][0]["knn"]
            assert knn_prof["engine"] == "tpu_generational"
            assert knn_prof["generations"] >= 2
            assert knn_prof["tombstoned_rows"] == 1
            seg = node.local_node_stats()["indices"]["segments"]["device"]
            assert seg["full_rebuilds"] == 0
            assert seg["rebuilds_avoided"] >= 2
            assert seg["seals"] >= 2
            assert seg["generations"] >= 2
            assert seg["tiers"]
        finally:
            node.close()

    def test_segments_settings_validation(self):
        from elasticsearch_tpu.common.errors import IllegalArgumentError
        from elasticsearch_tpu.indices.service import (
            validate_segments_settings)
        out = validate_segments_settings({
            "index.segments.enabled": "false",
            "index.segments.tier_size": "6",
            "index.segments.max_l0": 4,
            "index.segments.merge_budget_ms": "25"})
        assert out == {"segments_enabled": False,
                       "segments_tier_size": 6,
                       "segments_max_l0": 4,
                       "segments_merge_budget_ms": 25.0}
        with pytest.raises(IllegalArgumentError):
            validate_segments_settings({"index.segments.tier_size": 1})
        with pytest.raises(IllegalArgumentError):
            validate_segments_settings(
                {"index.segments.merge_budget_ms": "0"})

    def test_segments_disabled_setting_serves_monolithic(self):
        from elasticsearch_tpu.node import Node
        node = Node(tempfile.mkdtemp())
        try:
            node.create_index_with_templates(
                "t", settings={"index.segments.enabled": False},
                mappings={"properties": {
                    "v": {"type": "dense_vector", "dims": 8}}})
            shard = node.indices.get("t").shards[0]
            assert shard.vector_store.segments_enabled is False
        finally:
            node.close()


# ---------------------------------------------------------------------------
# Mesh graduation (SPMD) — rides the standalone strict recompile gate
# ---------------------------------------------------------------------------

@pytest.mark.multidevice
class TestMeshGraduation:
    def test_merge_graduates_base_into_sharded_corpus(
            self, mesh_serving):
        """L0 generations stay single-device; a merge graduates the new
        base into the sharded serving corpus, result-identical, and the
        post-graduation grid holds a strict zero-recompile second
        pass."""
        rng = np.random.default_rng(SEED + 12)
        gen, mono = _stores()
        mapper = _mapper()
        segs = _corpus_segments(rng, [1600, 100])
        for i in range(1, len(segs) + 1):
            _sync_both(gen, mono, mapper,
                       [SegmentView(s) for s in segs[:i]])
        # fan-out: base rides the mesh leg, the L0 stays single-device
        _assert_parity(gen, mono, rng, ks=(10,))
        assert gen.knn_stats["mesh_searches"] >= 1
        gc = gen._gens["v"]
        assert gc.force_merge()
        base = gc.snapshot().generations[0]
        assert base.mesh_state is not None, \
            "merge did not graduate into the sharded corpus"
        assert base.mesh_state.n_rows == 1700
        _assert_parity(gen, mono, rng, ks=(10, 64))
        # strict zero-recompile second pass over the graduated grid
        q = rng.standard_normal(DIMS).astype(np.float32)
        gen.search("v", q, 10)
        old_strict = dispatch.DISPATCH.strict
        dispatch.DISPATCH.strict = True
        try:
            c0 = dispatch.DISPATCH.compile_count()
            gen.search("v", q, 10)
            assert dispatch.DISPATCH.compile_count() == c0
        finally:
            dispatch.DISPATCH.strict = old_strict

    def test_tombstoned_mesh_base_masks_in_spmd(self, mesh_serving):
        rng = np.random.default_rng(SEED + 13)
        gen, mono = _stores()
        mapper = _mapper()
        segs = _corpus_segments(rng, [1600])
        _sync_both(gen, mono, mapper, [SegmentView(segs[0])])
        dead = set(range(12))
        gen.sync(ShardReader([SegmentView(segs[0],
                                          deleted_locals=dead)]),
                 {"v": mapper})
        mono.sync(ShardReader([SegmentView(segs[0],
                                           deleted_locals=dead)]),
                  {"v": mapper})
        _assert_parity(gen, mono, rng, ks=(10, 100))
        q = rng.standard_normal(DIMS).astype(np.float32)
        rows, _ = gen.search("v", q, 100)
        assert not np.isin(sorted(dead), rows).any()
        assert gen.segment_stats()["full_rebuilds"] == 0
