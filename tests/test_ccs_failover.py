"""CCS failure-mode depth (VERDICT r5 Next #9), alongside
tests/test_remote_cluster_wire.py:

- gateway-node failover WITHIN an alias: the remote cluster has two
  nodes, the local WireRemote sniffs both as gateways; killing one node
  mid-alias must not break the alias — the next RPC fails over to the
  surviving gateway (SniffConnectionStrategy round-robin + one re-sniff,
  `xpack/remote_cluster.py:_call_async`).
- mid-stream remote disconnect during a long CCS search: the remote dies
  while a search is in flight; with skip_unavailable=true the caller gets
  a degraded (skipped) response or a typed error within the RPC timeout —
  never a hang, never an unhandled socket error.
"""

import json
import os
import signal
import socket
import subprocess
import sys
import threading
import time
import urllib.error
import urllib.request

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _free_ports(n):
    socks, ports = [], []
    for _ in range(n):
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        socks.append(s)
        ports.append(s.getsockname()[1])
    for s in socks:
        s.close()
    return ports


def _req(method, url, body=None, timeout=30):
    data = json.dumps(body).encode() if body is not None else None
    r = urllib.request.Request(url, data=data, method=method,
                               headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(r, timeout=timeout) as resp:
        return json.loads(resp.read())


def _wait_up(port, deadline_s=90):
    deadline = time.monotonic() + deadline_s
    while time.monotonic() < deadline:
        try:
            _req("GET", f"http://127.0.0.1:{port}/")
            return
        except Exception:
            time.sleep(0.5)
    raise AssertionError(f"server on {port} never came up")


N_EAST = 3  # quorum survives one node death (a 2-node remote would not)


@pytest.fixture(scope="module")
def clusters(tmp_path_factory):
    """local (1 node) + east (3 nodes, all transport-bound gateways)."""
    tmp = tmp_path_factory.mktemp("ccs_failover")
    http_ports = _free_ports(1 + N_EAST)
    tp_ports = _free_ports(1 + N_EAST)
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    east_seeds = ",".join(f"127.0.0.1:{p}" for p in tp_ports[1:])
    procs = []
    # local single node
    procs.append(subprocess.Popen(
        [sys.executable, "-m", "elasticsearch_tpu.server",
         "--port", str(http_ports[0]), "--name", "local-0",
         "--cluster-name", "local", "--data", str(tmp / "local"),
         "-E", f"transport.port={tp_ports[0]}"],
        cwd=REPO, env=env,
        stdout=open(tmp / "local.log", "w"), stderr=subprocess.STDOUT))
    # 3-node east cluster
    masters = ",".join(f"east-{i}" for i in range(N_EAST))
    for i in range(N_EAST):
        procs.append(subprocess.Popen(
            [sys.executable, "-m", "elasticsearch_tpu.server",
             "--port", str(http_ports[1 + i]), "--name", f"east-{i}",
             "--cluster-name", "east", "--data", str(tmp / f"east{i}"),
             "-E", f"transport.port={tp_ports[1 + i]}",
             "-E", f"discovery.seed_hosts={east_seeds}",
             "-E", f"cluster.initial_master_nodes={masters}"],
            cwd=REPO, env=env,
            stdout=open(tmp / f"east{i}.log", "w"),
            stderr=subprocess.STDOUT))
    for p in http_ports:
        _wait_up(p)
    local = f"http://127.0.0.1:{http_ports[0]}"
    east = f"http://127.0.0.1:{http_ports[1]}"
    # wait for east to form its full cluster so every node is sniffable
    deadline = time.monotonic() + 120
    while time.monotonic() < deadline:
        try:
            h = _req("GET", f"{east}/_cluster/health")
            if h.get("number_of_nodes") == N_EAST:
                break
        except Exception:
            pass
        time.sleep(0.5)
    else:
        raise AssertionError("east cluster never formed")
    yield local, east, http_ports, tp_ports, procs, tmp
    for p in procs:
        if p.poll() is None:
            p.send_signal(signal.SIGTERM)
    for p in procs:
        try:
            p.wait(timeout=10)
        except subprocess.TimeoutExpired:
            p.kill()


def test_gateway_failover_within_alias(clusters):
    local, east, http_ports, tp_ports, procs, tmp = clusters

    # seed docs on east (through node 0; replication makes them visible
    # cluster-wide)
    for i in range(20):
        _req("PUT", f"{east}/logs/_doc/e{i}",
             {"msg": f"east doc {i}", "n": i})
    _req("POST", f"{east}/logs/_refresh")

    # register the alias with every east transport as a seed
    _req("PUT", f"{local}/_cluster/settings", {"persistent": {
        "cluster.remote.east.seeds":
            [f"127.0.0.1:{p}" for p in tp_ports[1:]],
        "cluster.remote.east.skip_unavailable": "false"}})

    r = _req("POST", f"{local}/east:logs/_search",
             {"query": {"match": {"msg": "east"}}, "size": 5})
    assert r["hits"]["total"]["value"] == 20

    info = _req("GET", f"{local}/_remote/info")
    assert info["east"]["connected"] is True
    # the sniff pooled the CLUSTER's gateways, not just the seed it
    # happened to dial (MAX_GATEWAY_NODES caps at 3)
    assert info["east"]["num_nodes_connected"] >= 2

    # kill the gateway holding NO copy of `logs` (1 shard + 1 replica on
    # 3 nodes leaves exactly one data-free node): the alias must keep
    # serving through the survivors while its round-robin keeps landing
    # on the dead gateway. (Killing a copy-holding node entangles this
    # test with replica promotion — a separate subsystem with a known
    # empty-store promotion bug, tracked in ROADMAP.md open items.)
    state = _req("GET", f"{east}/_cluster/state")
    holders = {r["node"] for r in state["routing"]
               if r["index"] == "logs"}
    victim = next(i for i in range(N_EAST)
                  if f"east-{i}" not in holders)
    procs[1 + victim].send_signal(signal.SIGKILL)
    procs[1 + victim].wait(timeout=10)
    # converged = a full rotation of the surviving gateways answers with
    # the complete result set (mid-recovery a survivor can briefly serve
    # partial results while the replica promotes)
    deadline = time.monotonic() + 120
    streak = 0
    while time.monotonic() < deadline and streak < 4:
        try:
            r = _req("POST", f"{local}/east:logs/_search",
                     {"query": {"match": {"msg": "east"}}, "size": 5},
                     timeout=60)
        except urllib.error.HTTPError:
            streak = 0     # dead-gateway RPC surfaced typed; round-robin
            time.sleep(1)  # + re-sniff finds the survivors next call
            continue
        if r["hits"]["total"]["value"] == 20:
            streak += 1
        else:
            streak = 0
            time.sleep(1)
    assert streak >= 4, "alias never failed over to surviving gateways"

    info = _req("GET", f"{local}/_remote/info")
    assert info["east"]["connected"] is True


def test_midstream_disconnect_degrades_not_hangs(clusters):
    """Kill the whole remote while a long CCS search is in flight: with
    skip_unavailable=true every in-flight and subsequent search must
    complete (degraded) or fail typed — bounded by the RPC timeout, no
    hang, and the local side stays healthy."""
    local, east, http_ports, tp_ports, procs, tmp = clusters

    # local data so the degraded responses still carry hits
    for i in range(5):
        _req("PUT", f"{local}/logs/_doc/l{i}", {"msg": f"local doc {i}"})
    _req("POST", f"{local}/logs/_refresh")

    # make the remote leg slow enough to reliably catch mid-stream: a
    # painless script_score over east's docs
    slow_body = {
        "query": {"script_score": {
            "query": {"match_all": {}},
            "script": {"source":
                       "double s = 0; for (int i = 0; i < 2000; ++i) "
                       "{ s += i * 0.5; } s"}}},
        "size": 5}
    _req("PUT", f"{local}/_cluster/settings", {"persistent": {
        "cluster.remote.east.skip_unavailable": "true"}})

    results = []
    errors = []

    def searcher():
        t0 = time.monotonic()
        try:
            r = _req("POST", f"{local}/logs,east:logs/_search",
                     dict(slow_body), timeout=90)
            results.append((time.monotonic() - t0, r))
        except urllib.error.HTTPError as e:
            errors.append((time.monotonic() - t0, e.code))
        except Exception as e:  # noqa: BLE001 — the test asserts on type
            errors.append((time.monotonic() - t0, type(e).__name__))

    threads = [threading.Thread(target=searcher) for _ in range(4)]
    for t in threads:
        t.start()
    time.sleep(0.15)  # let the searches reach the remote leg
    # kill the surviving east node mid-flight (east-0 died in the
    # failover test when run as a module; kill whichever still runs)
    for p in procs[1:]:
        if p.poll() is None:
            p.send_signal(signal.SIGKILL)
    for p in procs[1:]:
        try:
            p.wait(timeout=10)
        except subprocess.TimeoutExpired:
            pass
    for t in threads:
        t.join(120)
        assert not t.is_alive(), "CCS search hung after remote death"

    # every search resolved; none waited unboundedly (RPC timeout is 30s)
    assert len(results) + len(errors) == 4
    for elapsed, _ in results + errors:
        assert elapsed < 90
    # degraded responses (if the kill landed before/during the remote
    # call) carry the local hits and mark the remote skipped/failed
    for _, r in results:
        assert r["hits"] is not None
        if r.get("_clusters"):
            assert r["_clusters"]["successful"] >= 1

    # the alias reports disconnected afterwards, local cluster healthy
    deadline = time.monotonic() + 30
    while time.monotonic() < deadline:
        info = _req("GET", f"{local}/_remote/info")
        if info["east"]["connected"] is False:
            break
        time.sleep(1)
    r = _req("POST", f"{local}/logs,east:logs/_search",
             {"query": {"match": {"msg": "local"}}}, timeout=60)
    assert r["hits"]["total"]["value"] == 5
    assert r["_clusters"]["skipped"] == 1
