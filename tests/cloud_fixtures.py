"""In-process GCS- and Azure-compatible fixtures (analogs of the
reference's fake-gcs-server / Azurite test fixtures), for
`GcsBlobStore` / `AzureBlobStore`:

- GcsFixture: JSON/media API — media upload, `alt=media` download,
  object stat, delete, and paged listing with `nextPageToken`.
- AzureFixture: Block Blob PUT/GET/HEAD/DELETE +
  `?restype=container&comp=list` XML with `NextMarker` pagination.
"""

from __future__ import annotations

import json
import threading
import urllib.parse
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, Tuple

GCS_PAGE = 2    # tiny pages force the pagination path in tests
AZURE_PAGE = 2


class _GcsHandler(BaseHTTPRequestHandler):
    store: Dict[Tuple[str, str], bytes] = {}

    def log_message(self, *args):
        pass

    def _reply(self, code: int, body: bytes = b"",
               ctype: str = "application/json"):
        self.send_response(code)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def do_POST(self):
        parsed = urllib.parse.urlsplit(self.path)
        query = {k: v[-1] for k, v in
                 urllib.parse.parse_qs(parsed.query).items()}
        # /upload/storage/v1/b/{bucket}/o?uploadType=media&name=...
        parts = parsed.path.strip("/").split("/")
        if len(parts) >= 6 and parts[0] == "upload" and parts[5] == "o":
            bucket = parts[4]
            name = query.get("name", "")
            length = int(self.headers.get("Content-Length", 0))
            self.store[(bucket, name)] = self.rfile.read(length)
            self._reply(200, json.dumps({"name": name}).encode())
            return
        self._reply(400)

    def do_GET(self):
        parsed = urllib.parse.urlsplit(self.path)
        query = {k: v[-1] for k, v in
                 urllib.parse.parse_qs(parsed.query).items()}
        parts = parsed.path.strip("/").split("/")
        # /storage/v1/b/{bucket}/o[/{object}]
        if len(parts) >= 5 and parts[0] == "storage" and parts[4] == "o":
            bucket = parts[3]
            if len(parts) == 5:  # listing
                prefix = query.get("prefix", "")
                names = sorted(k for (b, k) in self.store
                               if b == bucket and k.startswith(prefix))
                start = int(query.get("pageToken", 0) or 0)
                page = names[start:start + GCS_PAGE]
                out = {"items": [{"name": n} for n in page]}
                if start + GCS_PAGE < len(names):
                    out["nextPageToken"] = str(start + GCS_PAGE)
                self._reply(200, json.dumps(out).encode())
                return
            name = urllib.parse.unquote(parts[5])
            blob = self.store.get((bucket, name))
            if blob is None:
                self._reply(404)
                return
            if query.get("alt") == "media":
                self._reply(200, blob, "application/octet-stream")
            else:  # stat
                self._reply(200, json.dumps(
                    {"name": name, "size": str(len(blob))}).encode())
            return
        self._reply(400)

    def do_DELETE(self):
        parsed = urllib.parse.urlsplit(self.path)
        parts = parsed.path.strip("/").split("/")
        if len(parts) >= 6 and parts[0] == "storage" and parts[4] == "o":
            key = (parts[3], urllib.parse.unquote(parts[5]))
            if key in self.store:
                del self.store[key]
                self._reply(204)
            else:
                self._reply(404)
            return
        self._reply(400)


class _AzureHandler(BaseHTTPRequestHandler):
    store: Dict[Tuple[str, str], bytes] = {}
    # when set to (account, base64_key), every request must carry a valid
    # SharedKey Authorization header — the Azurite-grade check that keeps
    # the client's signing code honest
    require_auth: Tuple[str, str] = ()

    def _check_auth(self, payload_len: int) -> bool:
        if not self.require_auth:
            return True
        import base64
        import hashlib
        import hmac
        account, key_b64 = self.require_auth
        auth = self.headers.get("Authorization", "")
        if not auth.startswith(f"SharedKey {account}:"):
            return False
        presented = auth.rsplit(":", 1)[1]
        parsed = urllib.parse.urlsplit(self.path)
        canon_headers = "".join(
            f"{k.lower()}:{v}\n" for k, v in sorted(
                (h, self.headers[h]) for h in self.headers
                if h.lower().startswith("x-ms-")))
        canon_resource = f"/{account}{parsed.path}"
        for qk, qv in sorted(urllib.parse.parse_qsl(
                parsed.query, keep_blank_values=True)):
            canon_resource += f"\n{qk}:{qv}"
        length = str(payload_len) if payload_len else ""
        ctype = self.headers.get("Content-Type", "") if payload_len else ""
        string_to_sign = "\n".join([
            self.command, "", "", length, "", ctype, "", "", "", "", "",
            "",
        ]) + canon_headers + canon_resource
        expect = base64.b64encode(hmac.new(
            base64.b64decode(key_b64), string_to_sign.encode(),
            hashlib.sha256).digest()).decode()
        return hmac.compare_digest(presented, expect)

    def log_message(self, *args):
        pass

    def _parse(self):
        parsed = urllib.parse.urlsplit(self.path)
        parts = parsed.path.lstrip("/").split("/", 1)
        container = parts[0]
        blob = urllib.parse.unquote(parts[1]) if len(parts) > 1 else ""
        query = {k: v[-1] for k, v in
                 urllib.parse.parse_qs(parsed.query).items()}
        return container, blob, query

    def _reply(self, code: int, body: bytes = b"",
               ctype: str = "application/octet-stream"):
        self.send_response(code)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def do_PUT(self):
        container, blob, _q = self._parse()
        length = int(self.headers.get("Content-Length", 0))
        data = self.rfile.read(length)
        if not self._check_auth(length):
            self._reply(403)
            return
        self.store[(container, blob)] = data
        self._reply(201)

    def do_GET(self):
        container, blob, query = self._parse()
        if not self._check_auth(0):
            self._reply(403)
            return
        if query.get("comp") == "list":
            prefix = query.get("prefix", "")
            names = sorted(k for (c, k) in self.store
                           if c == container and k.startswith(prefix))
            start = int(query.get("marker", 0) or 0)
            page = names[start:start + AZURE_PAGE]
            marker = (f"<NextMarker>{start + AZURE_PAGE}</NextMarker>"
                      if start + AZURE_PAGE < len(names) else "")
            xml = ("<?xml version=\"1.0\"?><EnumerationResults><Blobs>"
                   + "".join(f"<Blob><Name>{n}</Name></Blob>" for n in page)
                   + f"</Blobs>{marker}</EnumerationResults>").encode()
            self._reply(200, xml, "application/xml")
            return
        data = self.store.get((container, blob))
        if data is None:
            self._reply(404)
        else:
            self._reply(200, data)

    def do_HEAD(self):
        container, blob, _q = self._parse()
        if (container, blob) in self.store:
            self.send_response(200)
            self.send_header("Content-Length", "0")
            self.end_headers()
        else:
            self.send_response(404)
            self.end_headers()

    def do_DELETE(self):
        container, blob, _q = self._parse()
        if (container, blob) in self.store:
            del self.store[(container, blob)]
            self._reply(202)
        else:
            self._reply(404)


class _Fixture:
    handler = None

    def __init__(self):
        self.server = ThreadingHTTPServer(("127.0.0.1", 0), self.handler)
        self.port = self.server.server_address[1]
        self.thread = threading.Thread(target=self.server.serve_forever,
                                       daemon=True)

    @property
    def endpoint(self) -> str:
        return f"http://127.0.0.1:{self.port}"

    def __enter__(self):
        self.thread.start()
        return self

    def __exit__(self, *exc):
        self.server.shutdown()
        self.server.server_close()


class GcsFixture(_Fixture):
    handler = _GcsHandler


class AzureFixture(_Fixture):
    handler = _AzureHandler
