"""Spec-driven REST conformance: executes the reference's YAML behavior
suites (rest-api-spec test DSL) against this framework's controller
(§4.5 ESClientYamlSuiteTestCase analog; runner in yaml_runner.py).

The suites in MUST_PASS are fully green and pinned — a regression in any of
them fails CI. The wider sweep (and its triaged failures) is recorded by
`python conformance.py` into CONFORMANCE.md.
"""

import json
import shutil
import tempfile

import pytest

from yaml_runner import REF_SPEC, YamlTestRunner, specs_available

pytestmark = pytest.mark.skipif(
    not specs_available(), reason="reference rest-api-spec not present")


class ConformanceClient:
    def __init__(self, root):
        from elasticsearch_tpu.node import Node
        from elasticsearch_tpu.rest.actions import register_all
        from elasticsearch_tpu.rest.controller import RestController
        self.dir = tempfile.mkdtemp(dir=root)
        self.node = Node(self.dir)
        # the reference's YAML test cluster boots with `node.attr.testattr`
        self.node.node_attrs = {"testattr": "test"}
        self.rc = RestController()
        register_all(self.rc, self.node)

    def req(self, method, path, body=None, headers=None, **query):
        from elasticsearch_tpu.common import xcontent
        headers = {str(k).lower(): str(v)
                   for k, v in (headers or {}).items()}
        ctype = headers.get("content-type", "application/json")
        raw = b""
        if body is not None:
            if isinstance(body, (list, tuple)):   # ndjson: dict or raw lines
                raw = b"\n".join(
                    (line.strip().encode() if isinstance(line, str)
                     else json.dumps(line).encode())
                    for line in body) + b"\n"
            elif isinstance(body, str):
                raw = body.encode()
            else:
                # encode per the declared Content-Type (the `headers`
                # feature sends yaml/cbor/smile bodies); the controller
                # decodes by the same negotiation the HTTP layer uses
                raw = xcontent.dumps(
                    body, xcontent.XContentType.from_media_type(ctype))
        q = {k: str(v) for k, v in query.items()}
        # Accept only affects response ENCODING, which this in-process
        # client never performs (handlers return parsed objects; the wire
        # codecs are covered by the HTTP-layer and xcontent tests)
        return self.rc.dispatch(method, path, q, raw, ctype, headers)

    def close(self):
        self.node.close()
        shutil.rmtree(self.dir, ignore_errors=True)


# EVERY reference suite is green as of round 4 (921 pass / 0 fail /
# 135 skip-on-unsupported-features) — all pinned against regression
MUST_PASS = [
    "bulk/10_basic.yml",
    "bulk/20_list_of_strings.yml",
    "bulk/30_big_string.yml",
    "bulk/40_source.yml",
    "bulk/50_refresh.yml",
    "bulk/60_deprecated.yml",
    "bulk/80_cas.yml",
    "cat.aliases/10_basic.yml",
    "cat.aliases/20_headers.yml",
    "cat.aliases/30_json.yml",
    "cat.aliases/40_hidden.yml",
    "cat.allocation/10_basic.yml",
    "cat.count/10_basic.yml",
    "cat.fielddata/10_basic.yml",
    "cat.health/10_basic.yml",
    "cat.indices/10_basic.yml",
    "cat.indices/20_hidden.yml",
    "cat.nodeattrs/10_basic.yml",
    "cat.nodes/10_basic.yml",
    "cat.plugins/10_basic.yml",
    "cat.recovery/10_basic.yml",
    "cat.repositories/10_basic.yml",
    "cat.segments/10_basic.yml",
    "cat.shards/10_basic.yml",
    "cat.snapshots/10_basic.yml",
    "cat.tasks/10_basic.yml",
    "cat.templates/10_basic.yml",
    "cat.thread_pool/10_basic.yml",
    "cluster.allocation_explain/10_basic.yml",
    "cluster.component_template/10_basic.yml",
    "cluster.health/10_basic.yml",
    "cluster.health/20_request_timeout.yml",
    "cluster.health/30_indices_options.yml",
    "cluster.pending_tasks/10_basic.yml",
    "cluster.put_settings/10_basic.yml",
    "cluster.remote_info/10_info.yml",
    "cluster.reroute/10_basic.yml",
    "cluster.reroute/11_explain.yml",
    "cluster.reroute/20_response_filtering.yml",
    "cluster.state/10_basic.yml",
    "cluster.state/20_filtering.yml",
    "cluster.state/30_expand_wildcards.yml",
    "cluster.stats/10_basic.yml",
    "count/10_basic.yml",
    "count/20_query_string.yml",
    "create/10_with_id.yml",
    "create/15_without_id.yml",
    "create/35_external_version.yml",
    "create/40_routing.yml",
    "create/60_refresh.yml",
    "create/70_nested.yml",
    "delete/10_basic.yml",
    "delete/11_shard_header.yml",
    "delete/12_result.yml",
    "delete/20_cas.yml",
    "delete/25_external_version.yml",
    "delete/26_external_gte_version.yml",
    "delete/30_routing.yml",
    "delete/50_refresh.yml",
    "delete/60_missing.yml",
    "exists/10_basic.yml",
    "exists/40_routing.yml",
    "exists/60_realtime_refresh.yml",
    "exists/70_defaults.yml",
    "explain/10_basic.yml",
    "explain/20_source_filtering.yml",
    "explain/30_query_string.yml",
    "field_caps/10_basic.yml",
    "field_caps/20_meta.yml",
    "get/10_basic.yml",
    "get/15_default_values.yml",
    "get/20_stored_fields.yml",
    "get/40_routing.yml",
    "get/50_with_headers.yml",
    "get/60_realtime_refresh.yml",
    "get/70_source_filtering.yml",
    "get/80_missing.yml",
    "get/90_versions.yml",
    "get_source/10_basic.yml",
    "get_source/15_default_values.yml",
    "get_source/40_routing.yml",
    "get_source/60_realtime_refresh.yml",
    "get_source/70_source_filtering.yml",
    "get_source/80_missing.yml",
    "get_source/85_source_missing.yml",
    "index/10_with_id.yml",
    "index/12_result.yml",
    "index/15_without_id.yml",
    "index/20_optype.yml",
    "index/30_cas.yml",
    "index/35_external_version.yml",
    "index/36_external_gte_version.yml",
    "index/40_routing.yml",
    "index/60_refresh.yml",
    "indices.analyze/10_analyze.yml",
    "indices.analyze/20_analyze_limit.yml",
    "indices.clear_cache/10_basic.yml",
    "indices.clone/10_basic.yml",
    "indices.clone/20_source_mapping.yml",
    "indices.clone/30_copy_settings.yml",
    "indices.create/10_basic.yml",
    "indices.data_stream/10_basic.yml",
    "indices.delete/10_basic.yml",
    "indices.delete_alias/10_basic.yml",
    "indices.delete_alias/all_path_options.yml",
    "indices.exists/10_basic.yml",
    "indices.exists/20_read_only_index.yml",
    "indices.exists_alias/10_basic.yml",
    "indices.exists_template/10_basic.yml",
    "indices.flush/10_basic.yml",
    "indices.forcemerge/10_basic.yml",
    "indices.get/10_basic.yml",
    "indices.get_alias/10_basic.yml",
    "indices.get_alias/20_empty.yml",
    "indices.get_alias/30_wildcards.yml",
    "indices.get_field_mapping/10_basic.yml",
    "indices.get_field_mapping/20_missing_field.yml",
    "indices.get_field_mapping/40_missing_index.yml",
    "indices.get_field_mapping/50_field_wildcards.yml",
    "indices.get_index_template/10_basic.yml",
    "indices.get_index_template/20_get_missing.yml",
    "indices.get_mapping/10_basic.yml",
    "indices.get_mapping/30_missing_index.yml",
    "indices.get_mapping/40_aliases.yml",
    "indices.get_mapping/50_wildcard_expansion.yml",
    "indices.get_mapping/60_empty.yml",
    "indices.get_settings/10_basic.yml",
    "indices.get_settings/20_aliases.yml",
    "indices.get_settings/30_defaults.yml",
    "indices.get_template/10_basic.yml",
    "indices.get_template/20_get_missing.yml",
    "indices.open/10_basic.yml",
    "indices.open/20_multiple_indices.yml",
    "indices.put_alias/10_basic.yml",
    "indices.put_alias/all_path_options.yml",
    "indices.put_index_template/10_basic.yml",
    "indices.put_mapping/10_basic.yml",
    "indices.put_mapping/all_path_options.yml",
    "indices.put_settings/10_basic.yml",
    "indices.put_settings/11_reset.yml",
    "indices.put_settings/all_path_options.yml",
    "indices.put_template/10_basic.yml",
    "indices.recovery/10_basic.yml",
    "indices.refresh/10_basic.yml",
    "indices.rollover/10_basic.yml",
    "indices.rollover/20_max_doc_condition.yml",
    "indices.rollover/30_max_size_condition.yml",
    "indices.rollover/40_mapping.yml",
    "indices.segments/10_basic.yml",
    "indices.shard_stores/10_basic.yml",
    "indices.shrink/10_basic.yml",
    "indices.shrink/20_source_mapping.yml",
    "indices.shrink/30_copy_settings.yml",
    "indices.sort/10_basic.yml",
    "indices.split/10_basic.yml",
    "indices.split/20_source_mapping.yml",
    "indices.split/30_copy_settings.yml",
    "indices.stats/10_index.yml",
    "indices.stats/11_metric.yml",
    "indices.stats/12_level.yml",
    "indices.stats/13_fields.yml",
    "indices.stats/14_groups.yml",
    "indices.stats/20_translog.yml",
    "indices.stats/30_segments.yml",
    "indices.stats/40_updates_on_refresh.yml",
    "indices.update_aliases/10_basic.yml",
    "indices.update_aliases/20_routing.yml",
    "indices.update_aliases/30_remove_index_and_replace_with_alias.yml",
    "indices.upgrade/10_basic.yml",
    "indices.upgrade/20_deprecated.yml",
    "indices.validate_query/10_basic.yml",
    "indices.validate_query/20_query_string.yml",
    "info/10_info.yml",
    "info/20_lucene_version.yml",
    "ingest/10_basic.yml",
    "mget/10_basic.yml",
    "mget/12_non_existent_index.yml",
    "mget/13_missing_metadata.yml",
    "mget/14_alias_to_multiple_indices.yml",
    "mget/15_ids.yml",
    "mget/17_default_index.yml",
    "mget/20_stored_fields.yml",
    "mget/40_routing.yml",
    "mget/60_realtime_refresh.yml",
    "mget/70_source_filtering.yml",
    "mget/80_deprecated.yml",
    "mlt/10_basic.yml",
    "mlt/20_docs.yml",
    "mlt/30_unlike.yml",
    "msearch/10_basic.yml",
    "msearch/11_status.yml",
    "msearch/20_typed_keys.yml",
    "mtermvectors/10_basic.yml",
    "mtermvectors/20_deprecated.yml",
    "nodes.info/10_basic.yml",
    "nodes.info/20_transport.yml",
    "nodes.info/30_settings.yml",
    "nodes.reload_secure_settings/10_basic.yml",
    "nodes.stats/10_basic.yml",
    "nodes.stats/11_indices_metrics.yml",
    "nodes.stats/20_response_filtering.yml",
    "nodes.stats/30_discovery.yml",
    "ping/10_ping.yml",
    "range/10_basic.yml",
    "scripts/20_get_script_context.yml",
    "scripts/25_get_script_languages.yml",
    "scroll/10_basic.yml",
    "scroll/11_clear.yml",
    "scroll/12_slices.yml",
    "scroll/20_keep_alive.yml",
    "search.aggregation/100_avg_metric.yml",
    "search.aggregation/10_histogram.yml",
    "search.aggregation/110_max_metric.yml",
    "search.aggregation/120_min_metric.yml",
    "search.aggregation/130_sum_metric.yml",
    "search.aggregation/140_value_count_metric.yml",
    "search.aggregation/150_stats_metric.yml",
    "search.aggregation/160_extended_stats_metric.yml",
    "search.aggregation/170_cardinality_metric.yml",
    "search.aggregation/180_percentiles_tdigest_metric.yml",
    "search.aggregation/190_percentiles_hdr_metric.yml",
    "search.aggregation/200_top_hits_metric.yml",
    "search.aggregation/20_terms.yml",
    "search.aggregation/220_filters_bucket.yml",
    "search.aggregation/230_composite.yml",
    "search.aggregation/240_max_buckets.yml",
    "search.aggregation/250_moving_fn.yml",
    "search.aggregation/260_weighted_avg.yml",
    "search.aggregation/270_median_absolute_deviation_metric.yml",
    "search.aggregation/280_geohash_grid.yml",
    "search.aggregation/280_rare_terms.yml",
    "search.aggregation/290_geotile_grid.yml",
    "search.aggregation/300_pipeline.yml",
    "search.aggregation/30_sig_terms.yml",
    "search.aggregation/310_date_agg_per_day_of_week.yml",
    "search.aggregation/320_missing.yml",
    "search.aggregation/40_range.yml",
    "search.aggregation/50_filter.yml",
    "search.aggregation/70_adjacency_matrix.yml",
    "search.aggregation/80_typed_keys.yml",
    "search.aggregation/90_sig_text.yml",
    "search.highlight/10_unified.yml",
    "search.highlight/20_fvh.yml",
    "search.highlight/30_max_analyzed_offset.yml",
    "search.highlight/40_keyword_ignore.yml",
    "search.inner_hits/10_basic.yml",
    "search/100_stored_fields.yml",
    "search/10_source_filtering.yml",
    "search/110_field_collapsing.yml",
    "search/115_multiple_field_collapsing.yml",
    "search/120_batch_reduce_size.yml",
    "search/140_pre_filter_search_shards.yml",
    "search/150_rewrite_on_coordinator.yml",
    "search/160_exists_query.yml",
    "search/170_terms_query.yml",
    "search/180_locale_dependent_mapping.yml",
    "search/190_index_prefix_search.yml",
    "search/200_ignore_malformed.yml",
    "search/200_index_phrase_search.yml",
    "search/20_default_values.yml",
    "search/210_rescore_explain.yml",
    "search/220_total_hits_object.yml",
    "search/230_interval_query.yml",
    "search/240_date_nanos.yml",
    "search/250_distance_feature.yml",
    "search/300_sequence_numbers.yml",
    "search/30_limits.yml",
    "search/310_match_bool_prefix.yml",
    "search/320_disallow_queries.yml",
    "search/40_indices_boost.yml",
    "search/60_query_string.yml",
    "search/70_response_filtering.yml",
    "search/80_indices_options.yml",
    "search/90_search_after.yml",
    "search/issue4895.yml",
    "search/issue9606.yml",
    "search_shards/10_basic.yml",
    "snapshot.create/10_basic.yml",
    "snapshot.get/10_basic.yml",
    "snapshot.get_repository/10_basic.yml",
    "snapshot.restore/10_basic.yml",
    "snapshot.status/10_basic.yml",
    "suggest/10_basic.yml",
    "suggest/20_completion.yml",
    "suggest/30_context.yml",
    "suggest/40_typed_keys.yml",
    "suggest/50_completion_with_multi_fields.yml",
    "tasks.cancel/10_basic.yml",
    "tasks.get/10_basic.yml",
    "tasks.list/10_basic.yml",
    "termvectors/10_basic.yml",
    "termvectors/20_issue7121.yml",
    "termvectors/30_realtime.yml",
    "update/10_doc.yml",
    "update/11_shard_header.yml",
    "update/12_result.yml",
    "update/13_legacy_doc.yml",
    "update/16_noop.yml",
    "update/20_doc_upsert.yml",
    "update/22_doc_as_upsert.yml",
    "update/35_if_seq_no.yml",
    "update/40_routing.yml",
    "update/60_refresh.yml",
    "update/80_source_filtering.yml",
    "update/85_fields_meta.yml",
    "update/90_error.yml",
]


@pytest.fixture(scope="module")
def runner(tmp_path_factory):
    root = str(tmp_path_factory.mktemp("yaml_conf"))
    yield YamlTestRunner(lambda: ConformanceClient(root))


@pytest.mark.parametrize("suite", MUST_PASS)
def test_reference_yaml_suite(runner, suite):
    import os
    results = runner.run_suite(os.path.join(REF_SPEC, "test", suite))
    failures = [r for r in results if r["status"] == "FAIL"]
    assert not failures, "\n".join(
        f"{r['test']}: {r['reason']}" for r in failures)
    assert any(r["status"] == "PASS" for r in results) or all(
        r["status"] == "SKIP" for r in results)
