"""TPU009 fires: blocking syncs while holding the batcher/serving lock."""
# tpulint: hot-path
import threading

import numpy as np

from elasticsearch_tpu.ops import dispatch

_run_lock = threading.Lock()
_q_cond = threading.Condition()


def sync_inside_drain_critical_section(queries):
    with _run_lock:
        scores = dispatch.call("knn.exact", queries)
        out = np.asarray(scores)  # [expect] d2h transfer under the lock
    return out


def block_until_ready_under_lock(queries):
    scores = dispatch.call("knn.exact", queries)
    with _run_lock:
        scores.block_until_ready()  # [expect] device wait under the lock
    return scores


def future_result_under_lock(fut):
    with _run_lock:
        return fut.result()  # [expect] scheduler blocks on a future


def scalar_pull_under_condition(queries):
    with _q_cond:
        scores = dispatch.call("knn.exact", queries)
        return scores.sum().item()  # [expect] scalar pull under the lock
