"""TPU003 clean: identity caches hold the object (pinning its address);
id() in non-key contexts is fine."""
_CSR_CACHE = {}


def cached_csr(mesh, build):
    # keying on the OBJECT keeps it alive: the address cannot recycle
    # while the entry exists
    entry = _CSR_CACHE.get(mesh)
    if entry is None:
        entry = build(mesh)
        _CSR_CACHE[mesh] = entry
    return entry


def debug_label(node):
    return f"in-process:{id(node):x}"  # a label, not a cache key
