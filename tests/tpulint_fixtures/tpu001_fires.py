"""TPU001 fires: raw compilation paths outside the dispatcher."""
import functools

import jax
import jax as j
from jax import jit as _jit  # [expect] raw jit import, aliased
from jax.experimental.shard_map import shard_map  # [expect] raw import


@functools.partial(jax.jit, static_argnames=("k",))  # [expect] raw jit
def my_kernel(x, k):
    return x[:k]


def other(x):
    f = jax.jit(lambda v: v + 1.0)  # [expect] raw jit
    return f(x)


def aliased(x):
    f = j.jit(lambda v: v + 1.0)  # [expect] raw jit via module alias
    return f(x)
