"""TPU006 fires: enable_x64 outside the dispatcher's scoped path."""
import jax
from jax.experimental import enable_x64  # [expect] x64 import


def sum64(values):
    with enable_x64():
        return values.sum()


def flip_global():
    jax.config.update("jax_enable_x64", True)  # [expect] global flip
