"""TPU008 clean: every mutation of module-level caches holds the lock;
import-time population and locals are exempt."""
import threading

_lock = threading.Lock()
_plan_cache = {}
_REGISTRY = {}

_REGISTRY["builtin"] = object()  # import-time: single-threaded by design


def put_plan(key, plan):
    with _lock:
        _plan_cache[key] = plan


def local_scratch(rows):
    buckets = {}
    for r in rows:
        buckets[r % 8] = r  # a local, not the module cache
    return buckets


def shadowing_local_with_nested_global(rows):
    _plan_cache = {}  # LOCAL shadow of the module cache

    def reset_module_cache():
        # a nested helper's `global` must not un-shadow the OUTER
        # function's local (rebinding a global is not a container
        # mutation either way)
        global _plan_cache
        _plan_cache = {}

    for r in rows:
        _plan_cache[r] = r  # mutating the local shadow: no lock needed
    return _plan_cache, reset_module_cache
