"""TPU005 fires: cache keys built from raw request payloads."""
import json

_plan_cache = {}


def plan_for(body, compile_plan):
    key = None
    plan = _plan_cache.get(json.dumps(body, sort_keys=True))  # [expect]
    if plan is None:
        plan = compile_plan(body)
        _plan_cache[json.dumps(body, sort_keys=True)] = plan  # [expect]
    return plan, key


_request_cache = {}


def shard_search(plan_key, scrubbed, run_query):
    # device-path request cache keyed on the (scrubbed) body alone: no
    # reader fingerprint, so a refresh never invalidates
    cached = _request_cache.get((plan_key, scrubbed))  # [expect]
    if cached is None:
        cached = run_query()
        _request_cache[(plan_key, scrubbed)] = cached  # [expect]
    return cached
