"""TPU005 fires: cache keys built from raw request payloads."""
import json

_plan_cache = {}


def plan_for(body, compile_plan):
    key = None
    plan = _plan_cache.get(json.dumps(body, sort_keys=True))  # [expect]
    if plan is None:
        plan = compile_plan(body)
        _plan_cache[json.dumps(body, sort_keys=True)] = plan  # [expect]
    return plan, key
