"""TPU011 clean: block-store reads and transient per-pass locals."""
# tpulint: hot-path


def extract(view, field):
    return object()


def column(view, field):
    # the sanctioned shape: per-(segment, field) extraction through the
    # shared segment block store
    from elasticsearch_tpu import columnar
    blk, _cached = columnar.STORE.values_block(view, field, False)
    return blk


def merge_pass(views, field):
    # a TRANSIENT local keyed by seg_id inside one pass caches nothing
    # across refreshes — not a private extraction cache
    local = {}
    for v in views:
        local[v.segment.seg_id] = extract(v, field)
    return local


class PlanEngine:
    def __init__(self):
        self._plans = {}

    def plan(self, body_key):
        # a persistent dict keyed by something OTHER than segment
        # identity is not this rule's business
        cached = self._plans.get(body_key)
        if cached is None:
            cached = object()
            self._plans[body_key] = cached
        return cached
