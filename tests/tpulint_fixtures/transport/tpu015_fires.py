"""TPU015 fires: blocking calls lexically on the asyncio event loop.

Lives under a `transport/` path segment so the rule's
`async_actor_globs` scope applies, mirroring the real transport tier.
"""
import asyncio
import socket
import subprocess
import time


class Transport:
    def __init__(self, loop):
        self.loop = loop

    async def handle_request(self, request):
        time.sleep(0.05)                                      # [expect]
        with open("/tmp/spool", "wb") as f:                   # [expect]
            f.write(request)
        return subprocess.run(["true"])                       # [expect]

    async def open_channel(self, host, port):
        return socket.create_connection((host, port))         # [expect]

    def arm_retry(self):
        self.loop.call_later(
            1.0, lambda: time.sleep(0.2))                     # [expect]

    def arm_flush(self):
        def flush_cb():
            open("/tmp/wal", "ab").close()                    # [expect]
        self.loop.call_soon(flush_cb)
