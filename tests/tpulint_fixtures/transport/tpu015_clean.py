"""TPU015 clean: event-loop code that never blocks the loop, plus sync
helpers in the same file that legitimately block but run on threads."""
import asyncio
import time


class Transport:
    def __init__(self, loop, scheduler):
        self.loop = loop
        self.scheduler = scheduler
        self.running = True

    async def handle_request(self, request):
        await asyncio.sleep(0.05)                  # async sleep: fine
        data = await self.loop.run_in_executor(    # file IO on a thread
            None, self._read_spool)
        return data

    def _read_spool(self):
        # sync helper: runs in the executor, never on the loop
        with open("/tmp/spool", "rb") as f:
            return f.read()

    def keepalive_thread_loop(self):
        # thread-loop body (threading.Thread target): blocking by design,
        # never scheduled on the event loop
        while self.running:
            time.sleep(1.0)

    def arm_flush(self):
        # the abstract scheduler (sim queue / AsyncioScheduler) runs
        # engine callbacks by design — out of TPU015's lexical scope
        self.scheduler.schedule_in(100, self._read_spool, "flush")

    async def spawn_worker(self):
        def worker():
            # nested sync def: judged separately (may run on a thread)
            time.sleep(0.5)
        await self.loop.run_in_executor(None, worker)
