"""TPU006 clean: 64-bit kernels register with x64=True — the dispatcher
scopes the flag around both lower() and execution."""
from elasticsearch_tpu.ops import dispatch


def _sum64_impl(values):
    return values.sum()


dispatch.DISPATCH.register("fx.sum64", _sum64_impl, x64=True)


def sum64(values):
    return dispatch.call("fx.sum64", values)
