"""TPU012 clean: monotonic duration clocks; spans closed structurally."""
# tpulint: hot-path
import time


def monotonic_duration(fn):
    t0 = time.perf_counter_ns()
    fn()
    return time.perf_counter_ns() - t0


def deadline_math(budget_s):
    return time.monotonic() + budget_s


def context_manager_span(telemetry, work):
    with telemetry.span("score"):
        return work()


def try_finally_span(trace, work):
    sp = trace.begin_span("drain")
    try:
        return work()
    finally:
        trace.end_span(sp)


def cross_closure_close(trace, launch):
    leg = trace.begin_span("leg")

    def resolve(outcome):
        trace.end_span(leg, status=outcome)

    launch(resolve)


def retroactive_span(trace, dur_ns):
    # born closed — record_span cannot leak
    trace.record_span("device.sync", dur_ns)
