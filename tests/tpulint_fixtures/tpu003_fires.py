"""TPU003 fires: caches keyed on id() of long-lived objects."""
_CSR_CACHE = {}
_MISC = {}


def cached_csr(mesh, build):
    entry = _CSR_CACHE.get(id(mesh))  # [expect] id() in cache .get()
    if entry is None:
        entry = build(mesh)
        _CSR_CACHE[id(mesh)] = entry  # [expect] id() as subscript key
    return entry


def make_key(reader, field):
    key = (id(reader), field)  # [expect] id() assigned into a key tuple
    return key


def leaf_sig(x):
    return ("py", type(x).__name__, id(x))  # [expect] returned from *sig*
