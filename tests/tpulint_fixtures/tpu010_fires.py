"""TPU010 fires: transport fan-outs that can hang on a silent drop."""


class BrokenCoordinator:
    def __init__(self, transport, scheduler, node_id):
        self.transport = transport
        self.scheduler = scheduler
        self.node_id = node_id

    def fire_and_forget_without_failure_path(self, target, request):
        self.transport.send(self.node_id, target,  # [expect] no on_failure
                            "indices:data/read/query", request,
                            on_response=lambda r: None)

    def unbounded_pending_counter_join(self, targets, request, on_done):
        results = {}
        pending = {"count": len(targets)}  # [expect] no timer on the join

        def one(resp, target):
            results[target] = resp
            pending["count"] -= 1
            if pending["count"] == 0:
                on_done(results)

        for target in targets:
            self.transport.send(
                self.node_id, target, "indices:data/read/query", request,
                on_response=lambda r, t=target: one(r, t),
                on_failure=lambda _e, t=target: one(None, t))
