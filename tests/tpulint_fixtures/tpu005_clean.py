"""TPU005 clean: the key scrubs per-query values through a normalizer."""
_plan_cache = {}


def plan_cache_key(body):
    # scrubs query vectors to dims, match text to placeholders
    return repr(sorted(body))


def plan_for(body, compile_plan):
    key = plan_cache_key(body)
    plan = _plan_cache.get(key)
    if plan is None:
        plan = compile_plan(body)
        _plan_cache[key] = plan
    return plan


_request_cache = {}


def request_cache_key(plan_key, scrubbed, fingerprint):
    return (plan_key, scrubbed, fingerprint)


def shard_search(plan_key, scrubbed, reader, run_query):
    # reader fingerprint in the key: refresh/delete/merge invalidate
    cached = _request_cache.get(
        request_cache_key(plan_key, scrubbed, fingerprint=reader.gen))
    if cached is None:
        cached = run_query()
        _request_cache[(plan_key, scrubbed, reader.gen)] = cached
    return cached
