"""TPU005 clean: the key scrubs per-query values through a normalizer."""
_plan_cache = {}


def plan_cache_key(body):
    # scrubs query vectors to dims, match text to placeholders
    return repr(sorted(body))


def plan_for(body, compile_plan):
    key = plan_cache_key(body)
    plan = _plan_cache.get(key)
    if plan is None:
        plan = compile_plan(body)
        _plan_cache[key] = plan
    return plan
