"""TPU001 clean: kernels register with the dispatcher; sharded programs
build through the version-portable wrapper."""
from elasticsearch_tpu.ops import dispatch
from elasticsearch_tpu.parallel.sharded_knn import shard_map


def _my_kernel_impl(x, k):
    return x[:k]


dispatch.DISPATCH.register("fx.my_kernel", _my_kernel_impl,
                           static_argnames=("k",))


def my_kernel(x, k):
    return dispatch.call("fx.my_kernel", x, k=k)


def build_sharded(body, mesh, in_specs, out_specs):
    return shard_map(body, mesh=mesh, in_specs=in_specs,
                     out_specs=out_specs)
