"""TPU014 fires: unverified content-blob reads and sealed-generation
state mutated outside index/engine.py, segments/, recovery/."""


def read_blob_no_verification(store):
    """The 'just a peek' class: bytes flow out unverified."""
    return store.read_blob("blobs/abc123")  # [expect]


def size_probe(store, digests):
    """Sizing blobs still reads them — a truncated blob reports a
    plausible size and nobody ever notices."""
    total = 0
    for digest in digests:
        total += len(store.read_blob(f"blobs/{digest}"))  # [expect]
    return total


def hijack_deleted_rows(engine, seg_id):
    engine.deleted_rows[seg_id] = set()  # [expect]


def hijack_version_map(engine, doc_id, vv):
    engine.version_map.update({doc_id: vv})  # [expect]
    del engine.version_map[doc_id]  # [expect]


def hijack_segments(engine, seg):
    engine.segments.append(seg)  # [expect]
    engine.segments = []  # [expect]
