"""TPU007 fires: PartitionSpec rank vs array rank mismatches."""
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from elasticsearch_tpu.parallel.sharded_knn import shard_map


def _kernel(board, scales):
    return board * scales


def mesh_scores(mesh):
    board = jnp.zeros((8, 128))
    scales = jnp.zeros((128,))
    fn = shard_map(_kernel, mesh=mesh,
                   in_specs=(P("shard", None), P(None, None)),
                   out_specs=P("shard", None))
    return fn(board, scales)  # [expect] scales is rank 1, spec is rank 2


def arity_mismatch(mesh):
    board = jnp.zeros((8, 128))
    fn = shard_map(_kernel, mesh=mesh,
                   in_specs=(P("shard", None), P(None)),
                   out_specs=P("shard", None))
    return fn(board)  # [expect] 2 in_specs, 1 argument
