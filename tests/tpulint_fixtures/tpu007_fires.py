"""TPU007 fires: PartitionSpec rank vs array rank mismatches."""
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from elasticsearch_tpu.parallel.sharded_knn import shard_map


def _kernel(board, scales):
    return board * scales


def mesh_scores(mesh):
    board = jnp.zeros((8, 128))
    scales = jnp.zeros((128,))
    fn = shard_map(_kernel, mesh=mesh,
                   in_specs=(P("shard", None), P(None, None)),
                   out_specs=P("shard", None))
    return fn(board, scales)  # [expect] scales is rank 1, spec is rank 2


def arity_mismatch(mesh):
    board = jnp.zeros((8, 128))
    fn = shard_map(_kernel, mesh=mesh,
                   in_specs=(P("shard", None), P(None)),
                   out_specs=P("shard", None))
    return fn(board)  # [expect] 2 in_specs, 1 argument


def dp_axis_typo(devices):
    import numpy as np
    from jax.sharding import Mesh

    board = jnp.zeros((8, 128))
    mesh = Mesh(np.array(devices).reshape(2, 4), ("dp", "shard"))
    fn = shard_map(_kernel, mesh=mesh,
                   in_specs=(P("pd", None), P("shard", None)),  # [expect]
                   out_specs=P("dp", None))
    return fn(board, board)


def stale_axis_from_renamed_mesh(devices):
    import numpy as np
    from jax.sharding import Mesh

    board = jnp.zeros((8, 128))
    mesh = Mesh(np.array(devices).reshape(1, 8), ("replica", "rows"))
    fn = shard_map(_kernel, mesh=mesh,
                   in_specs=(P("replica", None), P("rows", None)),
                   out_specs=P("shard", None))  # [expect]
    return fn(board, board)
