"""TPU002 fires: host syncs on device arrays in a hot-path module."""
# tpulint: hot-path
import numpy as np
import numpy as _np

from elasticsearch_tpu.ops import dispatch


def per_row_pull(queries):
    scores = dispatch.call("knn.exact", queries)
    out = []
    for i in range(8):
        out.append(float(scores[i]))  # [expect] scalar pull in a loop
    return out


def scalar_pull_anywhere(queries):
    scores = dispatch.call("knn.exact", queries)
    return scores.sum().item()  # [expect] .item() on a device array


def transfer_in_loop(batches):
    results = []
    for q in batches:
        s = dispatch.call("knn.exact", q)
        results.append(np.asarray(s))  # [expect] d2h inside the loop
    return results


def transfer_in_loop_aliased_numpy(batches):
    results = []
    for q in batches:
        s = dispatch.call("knn.exact", q)
        results.append(_np.asarray(s))  # [expect] alias, same d2h
    return results
