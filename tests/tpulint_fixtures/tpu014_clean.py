"""TPU014 clean: digest-verified blob reads, non-content-addressed
keys, and read-only (or non-sealed) uses of engine state."""

import hashlib


def read_blob_verified(store, digest):
    """The sanctioned shape: verify before the bytes escape."""
    data = store.read_blob(f"blobs/{digest}")
    if hashlib.sha256(data).hexdigest() != digest:
        raise ValueError(f"blob [{digest}] failed digest verification")
    return data


def read_manifest(store, name):
    # manifests are named, not content-addressed — out of scope
    return store.read_blob(f"manifests/{name}.json")


def inspect_engine(engine, doc_id):
    # reading sealed state is fine; only mutation desyncs the commit
    vv = engine.version_map.get(doc_id)
    live = sum(len(rows) for rows in engine.deleted_rows.values())
    return vv, live, len(engine.segments)


def local_segments_are_not_engine_state(items):
    segments = []
    for item in items:
        segments.append(item)
    return segments


def non_sealed_attrs_mutate_freely(node, alloc):
    node.recoveries.pop(alloc, None)
    node.recovery_stats.update({"attempts": 0})
