"""TPU012 fires: wall-clock durations in a hot module + leaked spans."""
# tpulint: hot-path
import time


def wall_clock_duration(fn):
    t0 = time.time()  # [expect] wall clock read in a hot module
    fn()
    return time.time() - t0  # [expect] and the matching re-read


def leaky_live_span(trace):
    sp = trace.begin_span("score")  # [expect] opened, never closed
    return sp


def leaky_on_error_path(tracer, work):
    span = tracer.start_span("drain")  # [expect] no close anywhere
    work()
    return span
