"""TPU007 clean: spec ranks match array ranks (the PR 5 fix shape —
a rank-1 replicated spec for the rank-1 scales array)."""
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from elasticsearch_tpu.parallel.sharded_knn import shard_map


def _kernel(board, scales):
    return board * scales


def mesh_scores(mesh):
    board = jnp.zeros((8, 128))
    scales = jnp.zeros((128,))
    in_specs = (P("shard", None), P(None))
    fn = shard_map(_kernel, mesh=mesh, in_specs=in_specs,
                   out_specs=P("shard", None))
    return fn(board, scales)


def dp_axes_match(devices):
    import numpy as np
    from jax.sharding import Mesh

    board = jnp.zeros((8, 128))
    mesh = Mesh(np.array(devices).reshape(2, 4), ("dp", "shard"))
    fn = shard_map(_kernel, mesh=mesh,
                   in_specs=(P("dp", None), P("shard", None)),
                   out_specs=P("dp", None))
    return fn(board, board)


def unknown_mesh_is_not_judged(mesh):
    """Axis names can't be checked when the mesh is opaque (a param) —
    the rule must stay silent rather than guess."""
    board = jnp.zeros((8, 128))
    scales = jnp.zeros((128,))
    fn = shard_map(_kernel, mesh=mesh,
                   in_specs=(P("anyaxis", None), P(None)),
                   out_specs=P("anyaxis", None))
    return fn(board, scales)
