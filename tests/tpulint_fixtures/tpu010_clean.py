"""TPU010 clean: failure handlers everywhere, joins bounded by timers.

The sanctioned shapes: every `transport.send` carries `on_failure`, and a
pending-counter join arms a scheduler backstop (`schedule_in`) so a
silently dropped response can never hang the accumulator — the structure
`serving.fanout.ScatterGather` provides for free.
"""


class GuardedCoordinator:
    def __init__(self, transport, scheduler, node_id):
        self.transport = transport
        self.scheduler = scheduler
        self.node_id = node_id

    def send_with_failure_path(self, target, request, on_done):
        self.transport.send(self.node_id, target,
                            "indices:data/read/query", request,
                            on_response=on_done,
                            on_failure=lambda e: on_done(None))

    def bounded_pending_counter_join(self, targets, request, on_done,
                                     budget_ms=15_000):
        results = {}
        pending = {"count": len(targets)}

        def one(resp, target):
            if target not in results:
                results[target] = resp
                pending["count"] -= 1
            if pending["count"] == 0:
                on_done(results)

        def expire():
            # backstop: resolve every target that never answered
            for target in targets:
                if target not in results:
                    one(None, target)

        self.scheduler.schedule_in(budget_ms, expire, "fanout_backstop")
        for target in targets:
            self.transport.send(
                self.node_id, target, "indices:data/read/query", request,
                on_response=lambda r, t=target: one(r, t),
                on_failure=lambda _e, t=target: one(None, t))

    def no_transport_involved(self, items, on_done):
        # a pending-counter over local work is not a fan-out join
        pending = {"count": len(items)}
        for item in items:
            pending["count"] -= 1
        if pending["count"] == 0:
            on_done(items)
