"""TPU002 clean: one bulk d2h at response-assembly time, host math on
host arrays."""
# tpulint: hot-path
import numpy as np

from elasticsearch_tpu.ops import dispatch


def response_assembly(queries):
    scores, ids = dispatch.call("knn.exact", queries)
    ids.block_until_ready()
    scores = np.asarray(scores)  # bulk transfer, outside any loop
    ids = np.asarray(ids)
    out = []
    for qi in range(len(scores)):  # host-side loop over HOST arrays
        out.append((float(scores[qi][0]), ids[qi].tolist()))
    return out
