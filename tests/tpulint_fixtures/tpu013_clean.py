"""TPU013 clean: quantization routed through the codec registry; other
clip/round/shift arithmetic stays out of scope."""

import numpy as np

from elasticsearch_tpu.quant import codec as quant_codec


def encode_rows(matrix, encoding):
    """The sanctioned shape: the registry owns the recipe."""
    enc = quant_codec.get(encoding).encode_np(matrix)
    return enc.data, enc.scales


def quantize_queries(q):
    return quant_codec.quantize_queries_int8_jnp(q)


def unrelated_clip(scores):
    # clip without a round-of-division inside is score clamping, not
    # quantization (the binned kernel's CLAMP window)
    return np.clip(scores, -3.0, 3.0)


def rounded_ratio(a, b):
    # round of a division OUTSIDE a clip is ordinary arithmetic
    return np.round(a / b)


def shifted_masks(ids, bits):
    # shifts of non-sign data are bit bookkeeping, not sign packing
    return (ids & ~((1 << bits) - 1)) | (ids << 2)


def encode_uid_nibbles(doc_id):
    # scalar nibble pairs from plain ints (the Uid _id encoding) carry
    # no array evidence — not token-block packing
    out = bytearray([0xFE])
    for i in range(0, len(doc_id), 2):
        b1 = ord(doc_id[i]) - ord("0")
        b2 = ord(doc_id[i + 1]) - ord("0") if i + 1 < len(doc_id) else 0x0F
        out.append((b1 << 4) | b2)
    return bytes(out)
