"""TPU004 fires: reading a buffer after donating it to a kernel."""
import jax.numpy as jnp

from elasticsearch_tpu.ops import dispatch


def _score_impl(board, counts, queries):
    return board + queries, counts


dispatch.DISPATCH.register("fx.score_board", _score_impl,
                           donate_argnums=(0, 1))


def score(queries):
    board = jnp.zeros((8, 128))
    counts = jnp.zeros((8,))
    out, _ = dispatch.call("fx.score_board", board, counts, queries)
    checksum = board.sum()  # [expect] board's HBM was donated to XLA
    return out, checksum
