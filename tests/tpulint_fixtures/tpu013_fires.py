"""TPU013 fires: hand-rolled quantization arithmetic outside quant/."""

import numpy as np


def quantize_rows_int8(matrix):
    """A fifth private copy of the int8 recipe (the drift class)."""
    scales = np.abs(matrix).max(axis=-1) / 127.0
    q8 = np.clip(np.round(matrix / scales[:, None]), -127, 127)  # [expect]
    return q8.astype(np.int8), scales


def quantize_rows_int4(matrix, scales):
    return np.clip(np.rint(matrix / scales[:, None]), -7, 7)  # [expect]


def pack_signs_shift(rows):
    bits = (rows >= 0).astype(np.uint32)
    words = 0
    for j in range(32):
        words = words | (bits[:, j] << j)
    return words | ((rows[:, 0] >= 0) << 31)  # [expect]


def pack_signs_packbits(rows):
    return np.packbits(rows >= 0, axis=-1)  # [expect]


def pack_token_block_int4(tokens, scales):
    """A private int4 token-block packer (the token-packing drift
    class quant/tokens.py exists to prevent)."""
    q = (np.clip(np.round(tokens / scales[:, None]), -8, 7)  # [expect]
         .astype(np.int32) + 8)
    return (q[:, 0::2].astype(np.uint8)  # [expect]
            | (q[:, 1::2].astype(np.uint8) << 4))


def pack_planes_sliced(q):
    # plane-slice evidence alone (no astype) also marks nibble packing
    return q[:, 0::2] | (q[:, 1::2] << 4)  # [expect]
