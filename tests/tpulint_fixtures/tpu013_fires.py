"""TPU013 fires: hand-rolled quantization arithmetic outside quant/."""

import numpy as np


def quantize_rows_int8(matrix):
    """A fifth private copy of the int8 recipe (the drift class)."""
    scales = np.abs(matrix).max(axis=-1) / 127.0
    q8 = np.clip(np.round(matrix / scales[:, None]), -127, 127)  # [expect]
    return q8.astype(np.int8), scales


def quantize_rows_int4(matrix, scales):
    return np.clip(np.rint(matrix / scales[:, None]), -7, 7)  # [expect]


def pack_signs_shift(rows):
    bits = (rows >= 0).astype(np.uint32)
    words = 0
    for j in range(32):
        words = words | (bits[:, j] << j)
    return words | ((rows[:, 0] >= 0) << 31)  # [expect]


def pack_signs_packbits(rows):
    return np.packbits(rows >= 0, axis=-1)  # [expect]
