"""TPU004 clean: donated buffers are treated as consumed; fresh ones are
allocated per call."""
import jax.numpy as jnp

from elasticsearch_tpu.ops import dispatch


def _score_impl(board, counts, queries):
    return board + queries, counts


dispatch.DISPATCH.register("fx.score_board2", _score_impl,
                           donate_argnums=(0, 1))


def score(queries):
    board = jnp.zeros((8, 128))
    counts = jnp.zeros((8,))
    out, out_counts = dispatch.call("fx.score_board2", board, counts,
                                    queries)
    return out, out_counts  # only the results are read


def score_twice(queries):
    board = jnp.zeros((8, 128))
    counts = jnp.zeros((8,))
    out, _ = dispatch.call("fx.score_board2", board, counts, queries)
    board = jnp.zeros((8, 128))  # reallocated: the old buffer is gone
    counts = jnp.zeros((8,))
    out2, _ = dispatch.call("fx.score_board2", board, counts, queries)
    return out, out2
