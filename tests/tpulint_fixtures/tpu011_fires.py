"""TPU011 fires: private per-segment extraction caches outside columnar/."""
# tpulint: hot-path

_EXTRACTIONS = {}


def extract(view, field):
    return object()


class ColumnStore:
    def __init__(self):
        self._seg_cache = {}

    def column(self, view, field):
        fp = (view.segment.seg_id, view.segment.num_docs)
        col = self._seg_cache.get((field, view.segment.seg_id))  # [expect] name-matched private segment cache
        if col is None or col.fingerprint != fp:
            col = extract(view, field)
            self._seg_cache[(field, view.segment.seg_id)] = col  # [expect] store into the private cache
        return col


class PostingsStore:
    def __init__(self):
        self._by_segment = {}

    def postings(self, view, field):
        fp = (view.segment.seg_id, view.segment.num_docs)
        cached = self._by_segment.get(fp)  # [expect] fingerprint-keyed persistent dict
        if cached is None:
            cached = extract(view, field)
            self._by_segment[fp] = cached  # [expect] fingerprint-keyed store
        return cached


def cached_block(view, field):
    entry = _EXTRACTIONS.get(view.segment.seg_id)  # [expect] seg_id-keyed module-level cache
    if entry is None:
        entry = extract(view, field)
        _EXTRACTIONS[view.segment.seg_id] = entry  # [expect] seg_id-keyed module-level store
    return entry
