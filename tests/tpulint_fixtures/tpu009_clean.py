"""TPU009 clean: dispatch under the lock, sync at response assembly.

The sanctioned continuous-batching shape: the lock is held only for the
un-synced device dispatch (and plain queue bookkeeping); device→host
landing, future waits, and scalar reads happen outside the critical
section, so batch N's host work overlaps batch N+1's dispatch.
"""
# tpulint: hot-path
import threading

import numpy as np

from elasticsearch_tpu.ops import dispatch

_run_lock = threading.Lock()
_q_lock = threading.Lock()
_queue = []


def dispatch_under_lock_sync_outside(queries):
    with _run_lock:
        # launch only: the returned arrays stay un-synced futures
        scores = dispatch.call_async("knn.exact", queries)
    return np.asarray(scores)  # response-assembly landing, lock released


def queue_bookkeeping_under_lock(request):
    with _q_lock:
        _queue.append(request)
        depth = len(_queue)
    return depth


def wait_on_future_outside_lock(fut):
    with _run_lock:
        claimed = True
    if claimed:
        return fut.result()  # the submit tail: no lock held
    return None


def host_array_under_lock(rows):
    # np.asarray of a HOST value under a lock is not a device sync
    with _q_lock:
        return np.asarray(rows)
