"""TPU008 fires: module-level caches mutated outside the module lock."""
import threading

_lock = threading.Lock()
_plan_cache = {}
_counters = {"hits": 0}


def put_plan(key, plan):
    _plan_cache[key] = plan  # [expect] mutation without _lock


def count_hit(name):
    with _lock:
        _counters["hits"] += 1
    _counters.setdefault(name, 0)  # [expect] mutation outside the with
