"""Allocation decider chain + weighted balancer unit tests
(`routing/allocation/decider/*Tests`, `BalancedShardsAllocatorTests` analog)."""

from elasticsearch_tpu.cluster import allocation
from elasticsearch_tpu.cluster.allocation import (
    NO, THROTTLE, YES, AllocationContext, AwarenessDecider,
    DiskThresholdDecider, EnableDecider, FilterDecider, SameShardDecider,
    ShardsLimitDecider, ThrottlingDecider, decide_allocate, decide_remain,
)
from elasticsearch_tpu.cluster.state import (
    ClusterState, DiscoveryNode, ShardRoutingEntry,
)

INIT = ShardRoutingEntry.INITIALIZING
STARTED = ShardRoutingEntry.STARTED
UNASSIGNED = ShardRoutingEntry.UNASSIGNED
RELOCATING = ShardRoutingEntry.RELOCATING


def mk_state(n_nodes=3, routing=(), settings=None, metadata=None, attrs=None,
             isa=None):
    nodes = {}
    for i in range(n_nodes):
        nid = f"n{i}"
        nodes[nid] = DiscoveryNode(nid, attributes=(attrs or {}).get(nid))
    return ClusterState(nodes=nodes, routing=list(routing),
                        settings=settings or {},
                        metadata=metadata or {"idx": {"settings": {}}},
                        in_sync_allocations=isa or {})


def entry(shard=0, primary=False, node=None, state=UNASSIGNED, aid="a1",
          index="idx", reloc=None):
    return ShardRoutingEntry(index, shard, primary, node, state, aid, reloc)


# ---------------------------------------------------------------- deciders

def test_same_shard_decider():
    e = entry(aid="new")
    st = mk_state(routing=[entry(node="n0", state=STARTED, aid="old")])
    ctx = AllocationContext(st)
    d = SameShardDecider()
    assert d.can_allocate(e, "n0", ctx) == NO
    assert d.can_allocate(e, "n1", ctx) == YES


def test_enable_decider():
    d = EnableDecider()
    p, r = entry(primary=True), entry(primary=False)
    ctx = AllocationContext(mk_state(settings={
        "cluster.routing.allocation.enable": "primaries"}))
    assert d.can_allocate(p, "n0", ctx) == YES
    assert d.can_allocate(r, "n0", ctx) == NO
    ctx = AllocationContext(mk_state(settings={
        "cluster.routing.allocation.enable": "none"}))
    assert d.can_allocate(p, "n0", ctx) == NO
    ctx = AllocationContext(mk_state(settings={
        "cluster.routing.rebalance.enable": "none"}))
    assert d.can_rebalance(ctx) == NO


def test_filter_decider_cluster_exclude_and_require():
    d = FilterDecider()
    attrs = {"n0": {"zone": "a"}, "n1": {"zone": "b"}, "n2": {"zone": "a"}}
    ctx = AllocationContext(mk_state(
        attrs=attrs,
        settings={"cluster.routing.allocation.exclude.zone": "b"}))
    assert d.can_allocate(entry(), "n1", ctx) == NO
    assert d.can_allocate(entry(), "n0", ctx) == YES
    # exclusions drain running shards too
    assert d.can_remain(entry(node="n1", state=STARTED), "n1", ctx) == NO

    ctx = AllocationContext(mk_state(
        attrs=attrs,
        settings={"cluster.routing.allocation.require.zone": "b"}))
    assert d.can_allocate(entry(), "n1", ctx) == YES
    assert d.can_allocate(entry(), "n2", ctx) == NO


def test_filter_decider_index_level_and_name_wildcard():
    d = FilterDecider()
    meta = {"idx": {"settings":
                    {"index.routing.allocation.exclude._name": "n1*"}}}
    ctx = AllocationContext(mk_state(metadata=meta))
    assert d.can_allocate(entry(), "n1", ctx) == NO
    assert d.can_allocate(entry(), "n0", ctx) == YES


def test_disk_threshold_decider():
    d = DiskThresholdDecider()
    info = {"n0": {"total_bytes": 100, "free_bytes": 10},   # 90% used
            "n1": {"total_bytes": 100, "free_bytes": 50}}   # 50% used
    ctx = AllocationContext(mk_state(), cluster_info=info)
    assert d.can_allocate(entry(), "n0", ctx) == NO     # above low (85%)
    assert d.can_allocate(entry(), "n1", ctx) == YES
    assert d.can_remain(entry(node="n0"), "n0", ctx) == NO   # above high (90%)
    assert d.can_remain(entry(node="n1"), "n1", ctx) == YES
    # nodes without disk info are not penalized
    assert d.can_allocate(entry(), "n2", ctx) == YES


def test_throttling_decider():
    d = ThrottlingDecider()
    routing = [entry(shard=i, node="n0", state=INIT, aid=f"a{i}")
               for i in range(2)]
    ctx = AllocationContext(mk_state(routing=routing))
    assert d.can_allocate(entry(shard=7, aid="new"), "n0", ctx) == THROTTLE
    assert d.can_allocate(entry(shard=7, aid="new"), "n1", ctx) == YES
    # raising the limit unthrottles
    ctx = AllocationContext(mk_state(routing=routing, settings={
        "cluster.routing.allocation.node_concurrent_recoveries": 4}))
    assert d.can_allocate(entry(shard=7, aid="new"), "n0", ctx) == YES


def test_awareness_decider_spreads_across_zones():
    d = AwarenessDecider()
    attrs = {"n0": {"zone": "a"}, "n1": {"zone": "a"}, "n2": {"zone": "b"}}
    # primary already in zone a; 2 copies over 2 zones -> cap 1 per zone
    routing = [entry(primary=True, node="n0", state=STARTED, aid="p")]
    st = mk_state(attrs=attrs, routing=routing + [entry(aid="rep")],
                  settings={
                      "cluster.routing.allocation.awareness.attributes": "zone"})
    ctx = AllocationContext(st)
    assert d.can_allocate(entry(aid="rep"), "n1", ctx) == NO   # zone a again
    assert d.can_allocate(entry(aid="rep"), "n2", ctx) == YES  # zone b


def test_shards_limit_decider():
    d = ShardsLimitDecider()
    meta = {"idx": {"settings":
                    {"index.routing.allocation.total_shards_per_node": 1}}}
    routing = [entry(shard=0, node="n0", state=STARTED, aid="a0")]
    ctx = AllocationContext(mk_state(routing=routing, metadata=meta))
    assert d.can_allocate(entry(shard=1, aid="new"), "n0", ctx) == NO
    assert d.can_allocate(entry(shard=1, aid="new"), "n1", ctx) == YES


def test_chain_no_beats_throttle():
    routing = [entry(shard=i, node="n0", state=INIT, aid=f"a{i}")
               for i in range(2)] + [entry(shard=7, node="n0", state=STARTED,
                                           aid="held")]
    ctx = AllocationContext(mk_state(routing=routing))
    # same-shard NO wins over throttling THROTTLE on n0
    assert decide_allocate(entry(shard=7, aid="new"), "n0", ctx) == NO


# ---------------------------------------------------------------- reroute

def test_reroute_assigns_new_index_and_balances():
    st = mk_state(n_nodes=3)
    st = st.with_(metadata={"idx": {"settings": {
        "index.number_of_shards": 3, "index.number_of_replicas": 1}}})
    st = allocation.allocate_new_index(st, "idx", 3, 1)
    assigned = [r for r in st.routing if r.node_id]
    per_node = {}
    for r in assigned:
        per_node[r.node_id] = per_node.get(r.node_id, 0) + 1
    assert len(assigned) == 6
    assert max(per_node.values()) == 2  # perfectly balanced 6 over 3


def test_reroute_never_fabricates_lost_primary():
    # primary was started (in-sync id recorded), then its node died
    st = mk_state(n_nodes=2, routing=[
        entry(primary=True, node="n0", state=STARTED, aid="p0")],
        isa={("idx", 0): {"p0"}})
    st = allocation.node_left(st, "n0")
    prim = [r for r in st.routing if r.primary]
    assert len(prim) == 1 and prim[0].state == UNASSIGNED
    # repeated reroutes keep it red: the in-sync holder is gone
    st = allocation.reroute(st)
    assert [r for r in st.routing if r.primary][0].node_id is None
    assert st.in_sync_allocations[("idx", 0)] == {"p0"}


def test_throttled_allocation_drains_on_shard_started():
    # 1 node, 4 replicas of distinct shards to allocate, limit 2 at a time
    st = mk_state(n_nodes=1, metadata={"idx": {"settings": {
        "index.number_of_shards": 4, "index.number_of_replicas": 0}}})
    st = allocation.allocate_new_index(st, "idx", 4, 0)
    init = [r for r in st.routing if r.state == INIT]
    unassigned = [r for r in st.routing if r.state == UNASSIGNED]
    assert len(init) == 2 and len(unassigned) == 2  # throttled at 2
    # completing one recovery frees a slot and reroute picks up the next
    st = allocation.shard_started(st, init[0].allocation_id)
    assert sum(1 for r in st.routing if r.state == INIT) == 2
    assert sum(1 for r in st.routing if r.state == UNASSIGNED) == 1


# ---------------------------------------------------------------- rebalance

def test_rebalance_moves_shards_to_new_node():
    routing = [entry(shard=i, primary=True, node=f"n{i % 2}", state=STARTED,
                     aid=f"p{i}") for i in range(6)]
    st = mk_state(n_nodes=3, routing=routing,
                  metadata={"idx": {"settings":
                                    {"index.number_of_replicas": 0}}},
                  isa={("idx", i): {f"p{i}"} for i in range(6)})
    st = allocation.rebalance(st)
    moves = [r for r in st.routing if r.relocation_source]
    assert moves, "no relocation started toward the empty node"
    assert all(m.node_id == "n2" for m in moves)
    sources = [r for r in st.routing if r.state == RELOCATING]
    assert len(sources) == len(moves)

    # completing the move drops the source and hands over the primary flag
    st2 = allocation.shard_started(st, moves[0].allocation_id)
    done = next(r for r in st2.routing
                if r.allocation_id == moves[0].allocation_id)
    assert done.state == STARTED and done.primary
    assert all(r.allocation_id != moves[0].relocation_source
               for r in st2.routing)


def test_rebalance_respects_enable_none():
    routing = [entry(shard=i, primary=True, node="n0", state=STARTED,
                     aid=f"p{i}") for i in range(4)]
    st = mk_state(n_nodes=2, routing=routing,
                  settings={"cluster.routing.rebalance.enable": "none"},
                  metadata={"idx": {"settings":
                                    {"index.number_of_replicas": 0}}})
    st = allocation.rebalance(st)
    assert not [r for r in st.routing if r.relocation_source]


def test_rebalance_canceled_when_target_node_dies():
    routing = [entry(shard=i, primary=True, node="n0", state=STARTED,
                     aid=f"p{i}") for i in range(4)]
    st = mk_state(n_nodes=2, routing=routing,
                  metadata={"idx": {"settings":
                                    {"index.number_of_replicas": 0}}},
                  isa={("idx", i): {f"p{i}"} for i in range(4)})
    st = allocation.rebalance(st)
    moves = [r for r in st.routing if r.relocation_source]
    assert moves and moves[0].node_id == "n1"
    st = allocation.node_left(st, "n1")
    # sources revert to STARTED; no RELOCATING orphans remain
    assert not [r for r in st.routing if r.state == RELOCATING]
    assert not [r for r in st.routing if r.relocation_source]
    assert all(r.state == STARTED for r in st.routing if r.primary)


def test_high_watermark_drains_node():
    routing = [entry(shard=0, primary=True, node="n0", state=STARTED, aid="p0")]
    st = mk_state(n_nodes=2, routing=routing,
                  metadata={"idx": {"settings":
                                    {"index.number_of_replicas": 0}}},
                  isa={("idx", 0): {"p0"}})
    info = {"n0": {"total_bytes": 100, "free_bytes": 5},
            "n1": {"total_bytes": 100, "free_bytes": 90}}
    st = allocation.rebalance(st, cluster_info=info)
    moves = [r for r in st.routing if r.relocation_source]
    assert moves and moves[0].node_id == "n1"
