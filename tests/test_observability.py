"""Observability: breakers, profiler, slow logs, hot threads, cluster
settings, allocation explain, termvectors, PIT, segments, resolve, cat.

Reference behaviors: HierarchyCircuitBreakerService, search/profile,
SearchSlowLog, HotThreads, admin cluster/indices REST handlers.
"""

import json

import pytest

from elasticsearch_tpu.common.breakers import (
    CircuitBreakingError,
    HierarchyCircuitBreakerService,
)
from elasticsearch_tpu.node import Node
from elasticsearch_tpu.rest.actions import register_all
from elasticsearch_tpu.rest.controller import RestController


class Client:
    def __init__(self, node):
        self.rc = RestController()
        register_all(self.rc, node)

    def req(self, method, path, body=None, **query):
        raw = json.dumps(body).encode() if body is not None else b""
        return self.rc.dispatch(method, path, {k: str(v) for k, v in query.items()},
                                raw, "application/json")


@pytest.fixture
def node(tmp_path):
    n = Node(str(tmp_path / "data"))
    yield n
    n.close()


@pytest.fixture
def client(node):
    return Client(node)


# ---------------------------------------------------------------- breakers

def test_breaker_trips_and_releases():
    svc = HierarchyCircuitBreakerService(total_limit=1000)
    svc.add_estimate("request", 500, "q1")
    with pytest.raises(CircuitBreakingError):
        svc.add_estimate("request", 200, "q2")   # 500+200 > 600 limit
    assert svc.breakers["request"].trip_count == 1
    svc.release("request", 500)
    svc.add_estimate("request", 200, "q3")       # fits now
    stats = svc.stats()
    assert stats["request"]["estimated_size_in_bytes"] == 200
    assert stats["parent"]["limit_size_in_bytes"] == 950


def test_breaker_stats_in_nodes_stats(client):
    st, body = client.req("GET", "/_nodes/stats")
    node_stats = next(iter(body["nodes"].values()))
    assert "request" in node_stats["breakers"]
    assert "parent" in node_stats["breakers"]


# ---------------------------------------------------------------- profiler

def test_search_profile(client):
    client.req("PUT", "/p/_doc/1", {"t": "hello world"})
    client.req("POST", "/p/_refresh")
    st, body = client.req("POST", "/p/_search", {
        "profile": True, "query": {"match": {"t": "hello"}},
        "aggs": {"n": {"value_count": {"field": "t"}}}})
    assert st == 200
    shards = body["profile"]["shards"]
    assert len(shards) == 1
    q = shards[0]["searches"][0]["query"][0]
    assert q["type"] == "match"
    assert q["time_in_nanos"] > 0
    assert "breakdown" in q
    assert shards[0]["aggregations"][0]["description"] == "n"


# ---------------------------------------------------------------- slow log

def test_search_slow_log(client, node):
    client.req("PUT", "/slow", {"settings": {
        "index.search.slowlog.threshold.query.warn": "0ms"}})
    client.req("PUT", "/slow/_doc/1", {"x": 1})
    client.req("POST", "/slow/_refresh")
    client.req("POST", "/slow/_search", {"query": {"match_all": {}}})
    st, body = client.req("GET", "/_slowlog")
    assert any(e["index"] == "slow" and e["level"] == "warn"
               for e in body["search"])


# ------------------------------------------------------------- hot threads

def test_hot_threads(client):
    st, body = client.req("GET", "/_nodes/hot_threads")
    assert st == 200
    assert "Hot threads at" in body


# --------------------------------------------------------- cluster settings

def test_cluster_settings_roundtrip(client):
    st, body = client.req("PUT", "/_cluster/settings", {
        "persistent": {"search": {"default_timeout": "10s"}},
        "transient": {"logger.level": "DEBUG"}})
    assert body["persistent"]["search.default_timeout"] == "10s"
    st, body = client.req("GET", "/_cluster/settings")
    assert body["persistent"]["search.default_timeout"] == "10s"
    assert body["transient"]["logger.level"] == "DEBUG"
    # null deletes
    client.req("PUT", "/_cluster/settings",
               {"transient": {"logger.level": None}})
    st, body = client.req("GET", "/_cluster/settings")
    assert "logger.level" not in body["transient"]


# --------------------------------------------- reroute/allocation explain

def test_allocation_explain_unassigned_replica(client):
    client.req("PUT", "/r1", {"settings": {"index.number_of_replicas": 1}})
    st, body = client.req("POST", "/_cluster/allocation/explain",
                          {"index": "r1", "shard": 0, "primary": False})
    assert body["current_state"] == "unassigned"
    assert body["can_allocate"] == "no"
    assert body["node_allocation_decisions"][0]["deciders"][0]["decider"] == \
        "same_shard"


def test_reroute_validates_commands(client):
    st, _ = client.req("POST", "/_cluster/reroute",
                       {"commands": [{"move": {"index": "x", "shard": 0}}]})
    assert st == 200
    st, _ = client.req("POST", "/_cluster/reroute",
                       {"commands": [{"bogus": {}}]})
    assert st == 400


# ------------------------------------------------------------- termvectors

def test_termvectors(client):
    client.req("PUT", "/tv/_doc/1", {"body": "the quick quick fox"})
    client.req("POST", "/tv/_refresh")
    st, body = client.req("GET", "/tv/_termvectors/1",
                          {"fields": ["body"], "term_statistics": True})
    terms = body["term_vectors"]["body"]["terms"]
    assert terms["quick"]["term_freq"] == 2
    assert terms["quick"]["doc_freq"] == 1
    assert [t["position"] for t in terms["fox"]["tokens"]] == [3]


# -------------------------------------------------------------------- PIT

def test_point_in_time(client):
    client.req("PUT", "/pit1/_doc/1", {"x": 1})
    client.req("POST", "/pit1/_refresh")
    st, body = client.req("POST", "/pit1/_pit", keep_alive="1m")
    assert st == 200 and body["id"]
    st, closed = client.req("DELETE", "/_pit", {"id": body["id"]})
    assert closed["succeeded"] is True
    st, closed = client.req("DELETE", "/_pit", {"id": body["id"]})
    assert closed["succeeded"] is False


# ----------------------------------------------------- segments + resolve

def test_segments_and_cat_segments(client):
    client.req("PUT", "/seg/_doc/1", {"x": 1})
    client.req("POST", "/seg/_refresh")
    st, body = client.req("GET", "/seg/_segments")
    shards = body["indices"]["seg"]["shards"]
    total_docs = sum(s["num_docs"]
                     for shard in shards.values()
                     for entry in shard
                     for s in entry["segments"].values())
    assert total_docs == 1
    st, text = client.req("GET", "/_cat/segments", v="true")
    assert "seg" in text


def test_resolve_index(client):
    client.req("PUT", "/logs-1", {"aliases": {"logs": {}}})
    client.req("PUT", "/logs-2")
    st, body = client.req("GET", "/_resolve/index/logs-*")
    names = [i["name"] for i in body["indices"]]
    assert names == ["logs-1", "logs-2"] or set(names) == {"logs-1", "logs-2"}
    st, body = client.req("GET", "/_resolve/index/logs")
    assert body["aliases"][0]["name"] == "logs"


# ------------------------------------------------------------------- _cat

def test_cat_extras(client, node):
    client.req("PUT", "/_snapshot/r1", {"type": "fs", "settings": {
        "location": str(node.indices.data_path) + "/snaps"
        if hasattr(node.indices, "data_path") else "/tmp/snaps"}})
    for path in ("/_cat/allocation", "/_cat/thread_pool", "/_cat/plugins",
                 "/_cat/master", "/_cat/pending_tasks", "/_cat/repositories",
                 "/_cat/templates", "/_cat/recovery"):
        st, body = client.req("GET", path, v="true")
        assert st == 200, path
    st, body = client.req("GET", "/_cat/plugins", format="json")
    assert any(row["component"] == "sql" for row in body)


def test_deprecations(client):
    client.req("PUT", "/frozen1", {"settings": {"index.frozen": True}})
    st, body = client.req("GET", "/_migration/deprecations")
    assert any("frozen" in d["message"] for d in body["deprecations"])


def test_monitor_probes_shapes():
    """OsProbe/ProcessProbe/FsProbe/runtime probe stats sections."""
    from elasticsearch_tpu.monitor.probes import (
        fs_probe, os_probe, process_probe, runtime_probe,
    )
    o = os_probe()
    assert o["mem"]["total_in_bytes"] > 0
    assert o["allocated_processors"] >= 1
    assert "load_average" in o["cpu"]
    p = process_probe()
    assert p["open_file_descriptors"] > 0
    assert p["mem"]["resident_in_bytes"] > 0
    f = fs_probe(".")
    assert f["total"]["total_in_bytes"] > 0
    assert f["data"][0]["free_in_bytes"] >= 0
    j = runtime_probe()
    assert j["threads"]["count"] >= 1
    assert "collectors" in j["gc"]


def test_scroll_slicing_partitions_disjointly(tmp_path):
    """slice {id,max} splits one logical scroll into disjoint, complete
    partitions (search/slice/SliceBuilder)."""
    from elasticsearch_tpu.node import Node
    node = Node(str(tmp_path / "sl"))
    for i in range(40):
        node.index_doc("logs", str(i), {"n": i})
    node.indices.get("logs").refresh()

    seen = []
    for sid in range(3):
        resp = node.search_scroll_start(
            "logs", {"query": {"match_all": {}}, "size": 100,
                     "slice": {"id": sid, "max": 3}})
        ids = [h["_id"] for h in resp["hits"]["hits"]]
        assert resp["hits"]["total"]["value"] == len(ids)
        seen.append(set(ids))
    # disjoint and complete
    assert seen[0] | seen[1] | seen[2] == {str(i) for i in range(40)}
    assert not (seen[0] & seen[1]) and not (seen[1] & seen[2]) \
        and not (seen[0] & seen[2])
    # every slice got SOMETHING (hash distributes)
    assert all(s for s in seen)
    with __import__("pytest").raises(Exception):
        node.search_scroll_start("logs", {"slice": {"id": 5, "max": 3}})
    node.close()
