"""Named thread pools: sizing, queue bounds, rejection, stats, routing
(ThreadPool.java / EsThreadPoolExecutor analogs)."""

import threading
import time

import pytest

from elasticsearch_tpu.common.threadpool import (
    EsRejectedExecutionError, ThreadPool, pool_for_route,
)


def test_default_pools_and_info():
    tp = ThreadPool()
    info = tp.info()
    assert info["search"]["type"] == "fixed"
    assert info["search"]["queue_size"] == 1000
    assert info["write"]["queue_size"] == 10000
    assert info["generic"]["type"] == "scaling"
    assert info["force_merge"]["size"] == 1
    # lazily allocated: no executors yet
    assert all(s["completed"] == 0 for s in tp.stats().values())
    tp.shutdown()


def test_settings_overrides():
    tp = ThreadPool({"thread_pool.search.size": 2,
                     "thread_pool.search.queue_size": 7})
    assert tp.info()["search"] == {"type": "fixed", "size": 2,
                                   "queue_size": 7}
    tp.shutdown()


def test_submit_runs_and_counts():
    tp = ThreadPool()
    futures = [tp.submit("search", lambda i=i: i * 2) for i in range(10)]
    assert sorted(f.result(timeout=5) for f in futures) == list(range(0, 20, 2))
    s = tp.stats()["search"]
    assert s["completed"] == 10 and s["rejected"] == 0
    tp.shutdown()


def test_queue_full_rejects_with_429_semantics():
    tp = ThreadPool({"thread_pool.search.size": 1,
                     "thread_pool.search.queue_size": 2})
    gate = threading.Event()
    blocker = tp.submit("search", gate.wait, 10)
    # the single worker is blocked; fill the 2-slot queue (accounting counts
    # queued+running, so the blocker occupies one slot until it RUNS)
    time.sleep(0.05)
    fillers = [tp.submit("search", lambda: None) for _ in range(2)]
    with pytest.raises(EsRejectedExecutionError) as e:
        tp.submit("search", lambda: None)
    assert e.value.status == 429
    assert tp.stats()["search"]["rejected"] == 1
    gate.set()
    for f in fillers:
        f.result(timeout=5)
    tp.shutdown()


def test_pools_are_isolated():
    """A saturated write pool must not impede search (per-workload pools)."""
    tp = ThreadPool({"thread_pool.write.size": 1,
                     "thread_pool.write.queue_size": 1})
    gate = threading.Event()
    tp.submit("write", gate.wait, 10)
    time.sleep(0.05)
    tp.submit("write", lambda: None)
    with pytest.raises(EsRejectedExecutionError):
        tp.submit("write", lambda: None)
    # search still runs immediately
    assert tp.submit("search", lambda: 42).result(timeout=5) == 42
    gate.set()
    tp.shutdown()


def test_route_classification():
    assert pool_for_route("POST", "/idx/_search") == "search"
    assert pool_for_route("GET", "/_msearch") == "search"
    assert pool_for_route("POST", "/_bulk") == "write"
    assert pool_for_route("PUT", "/idx/_doc/1") == "write"
    assert pool_for_route("GET", "/idx/_doc/1") == "get"
    assert pool_for_route("GET", "/_mget") == "get"
    assert pool_for_route("GET", "/_cat/indices") == "management"
    assert pool_for_route("GET", "/_cluster/health") == "management"
    assert pool_for_route("PUT", "/_snapshot/repo/snap") == "snapshot"
    assert pool_for_route("POST", "/idx/_refresh") == "refresh"
    assert pool_for_route("POST", "/idx/_forcemerge") == "force_merge"
    assert pool_for_route("PUT", "/idx") == "generic"


def test_node_stats_exposes_thread_pools(tmp_path):
    from elasticsearch_tpu.node import Node
    from elasticsearch_tpu.rest.actions import register_all
    from elasticsearch_tpu.rest.controller import RestController
    node = Node(str(tmp_path / "d"))
    rc = RestController()
    register_all(rc, node)
    node.thread_pool.submit("search", lambda: 1).result(timeout=5)
    status, body = rc.dispatch("GET", "/_nodes/stats", {}, b"", None)
    tp = body["nodes"][node.node_id]["thread_pool"]
    assert tp["search"]["completed"] == 1
    assert set(tp) >= {"search", "write", "get", "generic", "management"}
    node.close()


def test_frozen_index_searches_on_search_throttled_pool(tmp_path):
    from elasticsearch_tpu.node import Node
    from elasticsearch_tpu.rest.actions import register_all
    from elasticsearch_tpu.rest.controller import RestController
    node = Node(str(tmp_path / "fz"))
    rc = RestController()
    register_all(rc, node)
    node.index_doc("cold", "1", {"n": 1}, refresh="true")
    status, _ = rc.dispatch("POST", "/cold/_freeze", {}, b"", None)
    assert status == 200
    resp = node.search("cold", {"query": {"match_all": {}}},
                       ignore_throttled=False)
    assert resp["hits"]["total"]["value"] == 1
    assert node.thread_pool.stats()["search_throttled"]["completed"] == 1
    # default searches skip frozen indices entirely
    resp = node.search("cold", {"query": {"match_all": {}}})
    assert resp["hits"]["total"]["value"] == 0
    node.close()
