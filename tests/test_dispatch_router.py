"""Unified per-dispatch cost router (serving/router.py).

One cost model — queue wait + transport RTT + device leg — now drives
copy selection (ARS), the dp-vs-shard split, and placement tie-breaks.
These tests pin the cost arithmetic, the decision reasons, the EWMA
smoothing (0.7/0.3, byte-compatible with the pre-unification ARS
observer), and the `_nodes/stats indices.mesh.router.dispatch` surface.
"""

import json
from types import SimpleNamespace

import pytest

from elasticsearch_tpu.serving import router


@pytest.fixture(autouse=True)
def _clean_router():
    router.reset()
    yield
    router.reset()


def _copy(node_id):
    return SimpleNamespace(node_id=node_id)


# ---------------------------------------------------------------- cost model

def test_route_cost_is_none_until_observed():
    r = router.DispatchRouter("coord")
    assert r.route_cost("n1") is None
    r.observe("n1", 40.0)
    assert r.route_cost("n1") == pytest.approx(40.0)  # 0 queued + device leg


def test_route_cost_sums_queue_rtt_and_device_leg():
    rtts = {"n1": 6.0}
    r = router.DispatchRouter("coord", rtt_provider=rtts.get)
    r.observe("n1", 20.0)
    r.inflight["n1"] = 2
    # queue wait 2*20 + rtt 6 + device leg (20-6)
    assert r.route_cost("n1") == pytest.approx(2 * 20.0 + 6.0 + 14.0)


def test_device_leg_never_negative_when_rtt_exceeds_service():
    r = router.DispatchRouter("coord", rtt_provider=lambda n: 50.0)
    r.observe("n1", 10.0)
    assert r.route_cost("n1") == pytest.approx(50.0 + 0.0)


def test_rtt_provider_failures_degrade_to_zero():
    def boom(node_id):
        raise RuntimeError("transport closed")
    r = router.DispatchRouter("coord", rtt_provider=boom)
    assert r.rtt_ms("n1") == 0.0
    r.observe("n1", 12.0)
    assert r.route_cost("n1") == pytest.approx(12.0)


def test_ewma_matches_historical_ars_smoothing():
    r = router.DispatchRouter("coord")
    r.observe("n1", 100.0)
    r.observe("n1", 10.0)
    # new = 0.7*prev + 0.3*obs — the exact pre-unification constant
    assert r.service_ewma["n1"] == pytest.approx(0.7 * 100.0 + 0.3 * 10.0)


# ------------------------------------------------------------ copy selection

def test_single_copy_short_circuits_with_reason():
    r = router.DispatchRouter("coord")
    chosen = r.select_copy([_copy("n1")], sid=0)
    assert chosen.node_id == "n1"
    assert router.stats()["copy"]["reasons"] == {"single_copy": 1}


def test_unmeasured_copies_are_probed_with_sid_rotation():
    r = router.DispatchRouter("coord")
    picks = {r.select_copy([_copy("a"), _copy("b"), _copy("c")],
                           sid=sid).node_id for sid in range(3)}
    # the (i + sid) % n tie-break spreads probes over all three copies
    assert picks == {"a", "b", "c"}
    assert router.stats()["copy"]["reasons"] == {"unmeasured_probe": 3}


def test_measured_copies_route_to_lowest_cost():
    r = router.DispatchRouter("coord")
    r.observe("fast", 5.0)
    r.observe("slow", 50.0)
    chosen = r.select_copy([_copy("slow"), _copy("fast")], sid=0)
    assert chosen.node_id == "fast"
    assert router.stats()["copy"]["reasons"] == {"lowest_cost": 1}


def test_inflight_tracks_select_and_observe_with_clamping():
    r = router.DispatchRouter("coord")
    r.observe("n1", 5.0)
    r.observe("n2", 50.0)
    for _ in range(3):
        r.select_copy([_copy("n1"), _copy("n2")], sid=0)
    assert r.inflight["n1"] == 3
    r.observe("n1", 5.0)
    assert r.inflight["n1"] == 2
    # late/duplicate observations clamp at zero, never go negative
    for _ in range(5):
        r.observe("n1", 5.0)
    assert r.inflight["n1"] == 0


def test_queue_wait_steers_away_from_backed_up_copy():
    """The classic ARS behavior the unified model must preserve: a fast
    node with a deep outstanding queue loses to a slower idle node."""
    r = router.DispatchRouter("coord")
    r.observe("fast", 10.0)
    r.observe("slower", 25.0)
    for _ in range(4):   # 4 un-acked dispatches on the fast node
        r.inflight["fast"] = r.inflight.get("fast", 0) + 1
    # fast: 4*10 + 10 = 50 > slower: 25
    chosen = r.select_copy([_copy("fast"), _copy("slower")], sid=0)
    assert chosen.node_id == "slower"


# ---------------------------------------------------------- dp-vs-shard split

def test_split_reasons_are_byte_stable():
    min_rows, dp = 1000, 4
    cases = [
        # (batch, n_rows, queue_depth) -> (split, reason)
        ((None, 8000, 0), ("shard", "no_batch_signal")),
        ((2, 8000, 0), ("dp", "batch_below_dp")),      # batch < dp
        ((6, 8000, 0), ("dp", "batch_below_dp")),      # batch % dp != 0
        ((4, 8000, 2), ("dp", "queue_pressure")),
        ((4, 2000, 0), ("dp", "small_corpus_group")),
        ((4, 8000, 0), ("shard", "idle_large_corpus")),
    ]
    for (batch, n_rows, q), want in cases:
        got = router.choose_split(batch, n_rows, q, dp=dp, n_shards=2,
                                  min_rows=min_rows)
        assert got == want, f"batch={batch} n_rows={n_rows} q={q}: {got}"
    reasons = router.stats()["split"]["reasons"]
    assert reasons == {"no_batch_signal": 1, "batch_below_dp": 2,
                       "queue_pressure": 1, "small_corpus_group": 1,
                       "idle_large_corpus": 1}


def test_split_break_even_is_exactly_min_rows_times_dp():
    """The fixed-cost calibration: the cost comparison flips at the same
    `min_rows * dp` threshold the policy module has always enforced —
    equality takes the full-mesh program."""
    min_rows, dp = 500, 4
    at = router.choose_split(4, min_rows * dp, 0, dp=dp, n_shards=3,
                             min_rows=min_rows)
    below = router.choose_split(4, min_rows * dp - 1, 0, dp=dp, n_shards=3,
                                min_rows=min_rows)
    assert at == ("shard", "idle_large_corpus")
    assert below == ("dp", "small_corpus_group")


# ---------------------------------------------------------------- placement

def test_placement_weight_dominates_cost():
    r = router.DispatchRouter("coord")
    r.observe("heavy", 500.0)   # terrible route cost, but lowest weight
    ordered = router.placement_order([(2.0, "idle"), (1.0, "heavy")])
    assert ordered == [(1.0, "heavy"), (2.0, "idle")]
    assert router.stats()["placement"]["reasons"] == {"weight_order": 1}


def test_placement_cost_breaks_weight_ties():
    r = router.DispatchRouter("coord")
    # "a_hot" sorts FIRST by name but carries the worse route cost: only
    # the cost term can put "z_cool" ahead of it
    r.observe("a_hot", 80.0)
    r.observe("z_cool", 5.0)
    ordered = router.placement_order([(1.0, "a_hot"), (1.0, "z_cool")])
    assert ordered == [(1.0, "z_cool"), (1.0, "a_hot")]
    assert router.stats()["placement"]["reasons"] == {"cost_tiebreak": 1}


def test_placement_with_no_traffic_is_name_deterministic():
    ordered = router.placement_order([(1.0, "b"), (1.0, "a"), (0.5, "c")])
    assert ordered == [(0.5, "c"), (1.0, "a"), (1.0, "b")]
    assert router.stats()["placement"]["reasons"] == {"weight_order": 1}


# ------------------------------------------------------------ stats surface

def test_stats_shape_and_node_observations():
    r = router.DispatchRouter("coord", rtt_provider=lambda n: 3.0)
    r.observe("n1", 30.0)
    r.select_copy([_copy("n1")], sid=0)
    s = router.stats()
    assert set(s) == {"copy", "split", "placement", "nodes"}
    assert s["copy"]["decisions"] == 1
    assert s["nodes"]["n1"]["service_ewma_ms"] == pytest.approx(30.0)
    assert s["nodes"]["n1"]["rtt_ewma_ms"] == pytest.approx(3.0)
    assert s["nodes"]["n1"]["inflight"] == 1


def test_dispatch_section_rides_nodes_stats(tmp_path):
    """The router's per-reason counts surface verbatim under
    `_nodes/stats indices.mesh.router.dispatch` via the REST tier."""
    from elasticsearch_tpu.node import Node
    from elasticsearch_tpu.rest.actions import register_all
    from elasticsearch_tpu.rest.controller import RestController

    router.choose_split(None, 100, 0, dp=1, n_shards=1, min_rows=10)
    n = Node(str(tmp_path / "data"))
    try:
        rc = RestController()
        register_all(rc, n)
        st, body = rc.dispatch("GET", "/_nodes/stats", {}, b"",
                               "application/json")
        assert st == 200
        node_stats = next(iter(body["nodes"].values()))
        dispatch = node_stats["indices"]["mesh"]["router"]["dispatch"]
        assert dispatch["split"]["reasons"]["no_batch_signal"] >= 1
        assert set(dispatch) == {"copy", "split", "placement", "nodes"}
        json.dumps(dispatch)  # the section must be JSON-serializable
    finally:
        n.close()
