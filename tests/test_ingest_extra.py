"""Grok library + extended ingest processors.

Reference behaviors: libs/grok pattern bank, modules/ingest-common
processors (csv/kv/json/urldecode/html_strip/bytes/fingerprint/foreach),
ingest-user-agent, ingest-geoip (inline database variant).
"""

import json

import pytest

from elasticsearch_tpu.ingest.grok import Grok
from elasticsearch_tpu.node import Node
from elasticsearch_tpu.rest.actions import register_all
from elasticsearch_tpu.rest.controller import RestController


class Client:
    def __init__(self, node):
        self.rc = RestController()
        register_all(self.rc, node)

    def req(self, method, path, body=None, **query):
        raw = json.dumps(body).encode() if body is not None else b""
        return self.rc.dispatch(method, path, {k: str(v) for k, v in query.items()},
                                raw, "application/json")


@pytest.fixture
def node(tmp_path):
    n = Node(str(tmp_path / "data"))
    yield n
    n.close()


@pytest.fixture
def client(node):
    return Client(node)


def simulate(client, processors, doc):
    st, body = client.req("POST", "/_ingest/pipeline/_simulate", {
        "pipeline": {"processors": processors},
        "docs": [{"_source": doc}]})
    assert st == 200, body
    return body["docs"][0]["doc"]["_source"]


# -------------------------------------------------------------------- grok

def test_grok_basic_extraction():
    g = Grok("%{IPV4:client} %{WORD:method} %{NUMBER:bytes:int}")
    out = g.match("10.2.3.4 GET 1234")
    assert out == {"client": "10.2.3.4", "method": "GET", "bytes": 1234}


def test_grok_apache_log():
    g = Grok("%{COMMONAPACHELOG}")
    line = ('127.0.0.1 - frank [10/Oct/2000:13:55:36 -0700] '
            '"GET /apache_pb.gif HTTP/1.0" 200 2326')
    out = g.match(line)
    assert out["source.address"] == "127.0.0.1"
    assert out["http.request.method"] == "GET"
    assert out["http.response.status_code"] == 200
    assert out["http.response.body.bytes"] == 2326


def test_grok_custom_definition():
    g = Grok("%{ORDER:order_id}", {"ORDER": r"ORD-\d{6}"})
    assert g.match("ref ORD-123456 ok") == {"order_id": "ORD-123456"}


def test_grok_no_match_raises_in_pipeline(client):
    st, body = client.req("POST", "/_ingest/pipeline/_simulate", {
        "pipeline": {"processors": [
            {"grok": {"field": "msg", "patterns": ["%{IPV4:ip}"]}}]},
        "docs": [{"_source": {"msg": "no ip here"}}]})
    assert "error" in body["docs"][0]


def test_grok_processor_multiple_patterns(client):
    out = simulate(client, [
        {"grok": {"field": "msg",
                  "patterns": ["level=%{LOGLEVEL:level}",
                               "%{TIMESTAMP_ISO8601:ts}"]}}],
        {"msg": "2024-03-05T10:00:00Z startup"})
    assert out["ts"] == "2024-03-05T10:00:00Z"


# -------------------------------------------------------- misc processors

def test_csv_processor(client):
    out = simulate(client, [
        {"csv": {"field": "row", "target_fields": ["a", "b", "c"]}}],
        {"row": 'x,"y,z",3'})
    assert out["a"] == "x" and out["b"] == "y,z" and out["c"] == "3"


def test_kv_processor(client):
    out = simulate(client, [
        {"kv": {"field": "msg", "field_split": " ", "value_split": "="}}],
        {"msg": "ip=1.2.3.4 error=NONE"})
    assert out["ip"] == "1.2.3.4" and out["error"] == "NONE"


def test_json_processor(client):
    out = simulate(client, [
        {"json": {"field": "raw", "target_field": "parsed"}}],
        {"raw": '{"a": 1}'})
    assert out["parsed"] == {"a": 1}


def test_urldecode_htmlstrip_bytes(client):
    out = simulate(client, [
        {"urldecode": {"field": "u"}},
        {"html_strip": {"field": "h"}},
        {"bytes": {"field": "sz"}}],
        {"u": "a%20b%2Fc", "h": "<b>bold</b> text", "sz": "2kb"})
    assert out["u"] == "a b/c"
    assert out["h"] == "bold text"
    assert out["sz"] == 2048


def test_fingerprint_deterministic(client):
    doc = {"user": "alice", "n": 7}
    out1 = simulate(client, [{"fingerprint": {"fields": ["user", "n"]}}], dict(doc))
    out2 = simulate(client, [{"fingerprint": {"fields": ["n", "user"]}}], dict(doc))
    assert out1["fingerprint"] == out2["fingerprint"]   # field order canonical


def test_sort_and_foreach(client):
    out = simulate(client, [
        {"sort": {"field": "tags", "order": "desc"}},
        {"foreach": {"field": "vals",
                     "processor": {"uppercase": {"field": "_ingest._value"}}}}],
        {"tags": [3, 1, 2], "vals": ["a", "b"]})
    assert out["tags"] == [3, 2, 1]
    assert out["vals"] == ["A", "B"]


def test_uri_parts(client):
    out = simulate(client, [{"uri_parts": {"field": "link"}}],
                   {"link": "https://user:pw@example.com:8443/p/f.txt?q=1#top"})
    u = out["url"]
    assert u["domain"] == "example.com" and u["port"] == 8443
    assert u["extension"] == "txt" and u["query"] == "q=1"


def test_dot_expander(client):
    out = simulate(client, [{"dot_expander": {"field": "a.b"}}],
                   {"a.b": 5})
    assert out["a"] == {"b": 5}


def test_user_agent(client):
    ua = ("Mozilla/5.0 (Windows NT 10.0; Win64; x64) AppleWebKit/537.36 "
          "(KHTML, like Gecko) Chrome/120.0.0.0 Safari/537.36")
    out = simulate(client, [{"user_agent": {"field": "agent"}}],
                   {"agent": ua})
    assert out["user_agent"]["name"] == "Chrome"
    assert out["user_agent"]["version"] == "120"
    assert out["user_agent"]["os"]["name"] == "Windows"


def test_geoip_inline_database(client):
    db = [{"cidr": "10.0.0.0/8", "country_iso_code": "ZZ",
           "city_name": "Intranet"}]
    out = simulate(client, [{"geoip": {"field": "ip", "database": db}}],
                   {"ip": "10.1.2.3"})
    assert out["geoip"]["city_name"] == "Intranet"
