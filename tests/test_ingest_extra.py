"""Grok library + extended ingest processors.

Reference behaviors: libs/grok pattern bank, modules/ingest-common
processors (csv/kv/json/urldecode/html_strip/bytes/fingerprint/foreach),
ingest-user-agent, ingest-geoip (inline database variant).
"""

import json

import pytest

from elasticsearch_tpu.ingest.grok import Grok
from elasticsearch_tpu.node import Node
from elasticsearch_tpu.rest.actions import register_all
from elasticsearch_tpu.rest.controller import RestController


class Client:
    def __init__(self, node):
        self.rc = RestController()
        register_all(self.rc, node)

    def req(self, method, path, body=None, **query):
        raw = json.dumps(body).encode() if body is not None else b""
        return self.rc.dispatch(method, path, {k: str(v) for k, v in query.items()},
                                raw, "application/json")


@pytest.fixture
def node(tmp_path):
    n = Node(str(tmp_path / "data"))
    yield n
    n.close()


@pytest.fixture
def client(node):
    return Client(node)


def simulate(client, processors, doc):
    st, body = client.req("POST", "/_ingest/pipeline/_simulate", {
        "pipeline": {"processors": processors},
        "docs": [{"_source": doc}]})
    assert st == 200, body
    return body["docs"][0]["doc"]["_source"]


# -------------------------------------------------------------------- grok

def test_grok_basic_extraction():
    g = Grok("%{IPV4:client} %{WORD:method} %{NUMBER:bytes:int}")
    out = g.match("10.2.3.4 GET 1234")
    assert out == {"client": "10.2.3.4", "method": "GET", "bytes": 1234}


def test_grok_apache_log():
    g = Grok("%{COMMONAPACHELOG}")
    line = ('127.0.0.1 - frank [10/Oct/2000:13:55:36 -0700] '
            '"GET /apache_pb.gif HTTP/1.0" 200 2326')
    out = g.match(line)
    assert out["source.address"] == "127.0.0.1"
    assert out["http.request.method"] == "GET"
    assert out["http.response.status_code"] == 200
    assert out["http.response.body.bytes"] == 2326


def test_grok_custom_definition():
    g = Grok("%{ORDER:order_id}", {"ORDER": r"ORD-\d{6}"})
    assert g.match("ref ORD-123456 ok") == {"order_id": "ORD-123456"}


def test_grok_no_match_raises_in_pipeline(client):
    st, body = client.req("POST", "/_ingest/pipeline/_simulate", {
        "pipeline": {"processors": [
            {"grok": {"field": "msg", "patterns": ["%{IPV4:ip}"]}}]},
        "docs": [{"_source": {"msg": "no ip here"}}]})
    assert "error" in body["docs"][0]


def test_grok_processor_multiple_patterns(client):
    out = simulate(client, [
        {"grok": {"field": "msg",
                  "patterns": ["level=%{LOGLEVEL:level}",
                               "%{TIMESTAMP_ISO8601:ts}"]}}],
        {"msg": "2024-03-05T10:00:00Z startup"})
    assert out["ts"] == "2024-03-05T10:00:00Z"


# -------------------------------------------------------- misc processors

def test_csv_processor(client):
    out = simulate(client, [
        {"csv": {"field": "row", "target_fields": ["a", "b", "c"]}}],
        {"row": 'x,"y,z",3'})
    assert out["a"] == "x" and out["b"] == "y,z" and out["c"] == "3"


def test_kv_processor(client):
    out = simulate(client, [
        {"kv": {"field": "msg", "field_split": " ", "value_split": "="}}],
        {"msg": "ip=1.2.3.4 error=NONE"})
    assert out["ip"] == "1.2.3.4" and out["error"] == "NONE"


def test_json_processor(client):
    out = simulate(client, [
        {"json": {"field": "raw", "target_field": "parsed"}}],
        {"raw": '{"a": 1}'})
    assert out["parsed"] == {"a": 1}


def test_urldecode_htmlstrip_bytes(client):
    out = simulate(client, [
        {"urldecode": {"field": "u"}},
        {"html_strip": {"field": "h"}},
        {"bytes": {"field": "sz"}}],
        {"u": "a%20b%2Fc", "h": "<b>bold</b> text", "sz": "2kb"})
    assert out["u"] == "a b/c"
    assert out["h"] == "bold text"
    assert out["sz"] == 2048


def test_fingerprint_deterministic(client):
    doc = {"user": "alice", "n": 7}
    out1 = simulate(client, [{"fingerprint": {"fields": ["user", "n"]}}], dict(doc))
    out2 = simulate(client, [{"fingerprint": {"fields": ["n", "user"]}}], dict(doc))
    assert out1["fingerprint"] == out2["fingerprint"]   # field order canonical


def test_sort_and_foreach(client):
    out = simulate(client, [
        {"sort": {"field": "tags", "order": "desc"}},
        {"foreach": {"field": "vals",
                     "processor": {"uppercase": {"field": "_ingest._value"}}}}],
        {"tags": [3, 1, 2], "vals": ["a", "b"]})
    assert out["tags"] == [3, 2, 1]
    assert out["vals"] == ["A", "B"]


def test_uri_parts(client):
    out = simulate(client, [{"uri_parts": {"field": "link"}}],
                   {"link": "https://user:pw@example.com:8443/p/f.txt?q=1#top"})
    u = out["url"]
    assert u["domain"] == "example.com" and u["port"] == 8443
    assert u["extension"] == "txt" and u["query"] == "q=1"


def test_dot_expander(client):
    out = simulate(client, [{"dot_expander": {"field": "a.b"}}],
                   {"a.b": 5})
    assert out["a"] == {"b": 5}


def test_user_agent(client):
    ua = ("Mozilla/5.0 (Windows NT 10.0; Win64; x64) AppleWebKit/537.36 "
          "(KHTML, like Gecko) Chrome/120.0.0.0 Safari/537.36")
    out = simulate(client, [{"user_agent": {"field": "agent"}}],
                   {"agent": ua})
    assert out["user_agent"]["name"] == "Chrome"
    assert out["user_agent"]["version"] == "120"
    assert out["user_agent"]["os"]["name"] == "Windows"


def test_geoip_inline_database(client):
    db = [{"cidr": "10.0.0.0/8", "country_iso_code": "ZZ",
           "city_name": "Intranet"}]
    out = simulate(client, [{"geoip": {"field": "ip", "database": db}}],
                   {"ip": "10.1.2.3"})
    assert out["geoip"]["city_name"] == "Intranet"


# --------------------------------------------------------- attachment

class TestAttachmentProcessor:
    """Tika-lite `attachment` processor (plugins/ingest-attachment):
    sniff + extract per format, indexed_chars, properties subset,
    remove_binary."""

    def _run(self, spec, doc):
        from elasticsearch_tpu.ingest.attachment import AttachmentProcessor
        p = AttachmentProcessor(spec)
        p.run(doc)
        return doc

    @staticmethod
    def _b64(raw: bytes) -> str:
        import base64
        return base64.b64encode(raw).decode()

    def test_plain_text_and_language(self):
        raw = b"the quick brown fox is in the woods and it runs for fun"
        doc = self._run({"field": "data"}, {"data": self._b64(raw)})
        att = doc["attachment"]
        assert att["content_type"] == "text/plain"
        assert "quick brown fox" in att["content"]
        assert att["content_length"] == len(att["content"])
        assert att["language"] == "en"

    def test_html_extraction_with_title(self):
        raw = (b"<html><head><title>My Page</title>"
               b"<script>var x = 1;</script></head>"
               b"<body><h1>Hello</h1><p>World of text</p></body></html>")
        doc = self._run({"field": "data"}, {"data": self._b64(raw)})
        att = doc["attachment"]
        assert att["content_type"] == "text/html"
        assert "Hello" in att["content"] and "World of text" in att["content"]
        assert "var x" not in att["content"]       # scripts suppressed
        assert att["title"] == "My Page"

    def test_docx_extraction(self):
        import io
        import zipfile
        buf = io.BytesIO()
        with zipfile.ZipFile(buf, "w") as z:
            z.writestr("[Content_Types].xml", "<Types/>")
            z.writestr("word/document.xml",
                       '<w:document xmlns:w="x"><w:body>'
                       "<w:p><w:r><w:t>First paragraph.</w:t></w:r></w:p>"
                       "<w:p><w:r><w:t>Second </w:t></w:r>"
                       "<w:r><w:t>part&amp;more.</w:t></w:r></w:p>"
                       "</w:body></w:document>")
            z.writestr("docProps/core.xml",
                       '<cp:coreProperties xmlns:cp="c" xmlns:dc="d">'
                       "<dc:title>Quarterly Report</dc:title>"
                       "<dc:creator>Alex Writer</dc:creator>"
                       "</cp:coreProperties>")
        doc = self._run({"field": "data"},
                        {"data": self._b64(buf.getvalue())})
        att = doc["attachment"]
        assert att["content_type"].endswith("wordprocessingml.document")
        assert "First paragraph." in att["content"]
        assert "Second part&more." in att["content"]   # runs joined, unescaped
        assert att["title"] == "Quarterly Report"
        assert att["author"] == "Alex Writer"

    def test_pdf_extraction_best_effort(self):
        import zlib
        stream = zlib.compress(
            b"BT /F1 12 Tf (Hello from a PDF) Tj "
            b"[(glued) (words)] TJ ET")
        raw = (b"%PDF-1.4\n1 0 obj\n<< /Length " +
               str(len(stream)).encode() +
               b" /Filter /FlateDecode >>\nstream\n" + stream +
               b"endstream\nendobj\n%%EOF")
        doc = self._run({"field": "data"}, {"data": self._b64(raw)})
        att = doc["attachment"]
        assert att["content_type"] == "application/pdf"
        assert "Hello from a PDF" in att["content"]
        assert "gluedwords" in att["content"].replace(" ", "")

    def test_rtf_extraction(self):
        raw = rb"{\rtf1\ansi{\fonttbl\f0 Arial;}\f0 Salut mon ami, c'est le texte pour toi.}"
        doc = self._run({"field": "data"}, {"data": self._b64(raw)})
        att = doc["attachment"]
        assert att["content_type"] == "application/rtf"
        assert "Salut mon ami" in att["content"]

    def test_indexed_chars_and_properties_and_remove_binary(self):
        raw = b"the fox " * 100
        doc = self._run(
            {"field": "data", "target_field": "att", "indexed_chars": 10,
             "properties": ["content", "content_type"],
             "remove_binary": True},
            {"data": self._b64(raw)})
        assert doc["att"]["content"] == "the fox th"
        assert set(doc["att"]) == {"content", "content_type"}
        assert "data" not in doc     # binary removed

    def test_per_doc_indexed_chars_field(self):
        doc = self._run(
            {"field": "data", "indexed_chars_field": "max_chars"},
            {"data": self._b64(b"abcdefghij"), "max_chars": 4})
        assert doc["attachment"]["content"] == "abcd"

    def test_missing_and_invalid(self):
        import pytest as _pytest
        from elasticsearch_tpu.ingest.service import IngestProcessorError
        self._run({"field": "data", "ignore_missing": True}, {})
        with _pytest.raises(IngestProcessorError):
            self._run({"field": "data"}, {})
        with _pytest.raises(IngestProcessorError, match="base64"):
            self._run({"field": "data"}, {"data": "!!!not-base64!!!"})
        with _pytest.raises(IngestProcessorError, match="integer"):
            self._run({"field": "data", "indexed_chars_field": "mc"},
                      {"data": self._b64(b"abc"), "mc": "ten"})

    def test_utf16_text_decodes(self):
        raw = "unicode text body".encode("utf-16")  # BOM-prefixed
        doc = self._run({"field": "data"}, {"data": self._b64(raw)})
        assert doc["attachment"]["content"] == "unicode text body"
        assert "\x00" not in doc["attachment"]["content"]

    def test_pipeline_end_to_end(self, tmp_path):
        """attachment through a real pipeline + index + search."""
        import jax
        try:
            jax.config.update("jax_platforms", "cpu")
        except RuntimeError:
            pass
        from elasticsearch_tpu.node import Node
        node = Node(str(tmp_path))
        node.ingest.put_pipeline("att", {"processors": [
            {"attachment": {"field": "data", "remove_binary": True}}]})
        node.index_doc("docs", "1",
                       {"data": self._b64(b"findable attachment text")},
                       pipeline="att")
        node.indices.get("docs").refresh()
        r = node.search("docs", {"query": {
            "match": {"attachment.content": "findable"}}})
        assert r["hits"]["total"]["value"] == 1
        src = r["hits"]["hits"][0]["_source"]
        assert "data" not in src
        node.close()
