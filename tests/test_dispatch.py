"""Shape-bucketed dispatch layer (`ops/dispatch.py`) — the tier-1
recompile-regression suite.

What's pinned here:
* bucket selection is monotone and closed (the policy-level kill of the
  r06 "batch=4 slower than batch=16" inversion: a smaller batch can never
  map to a bigger — or freshly-compiled — program than a larger one);
* bucket-boundary parity: results are byte-identical across a pad
  boundary (a query riding in a batch of 8 == the same query in 9);
* steady-state zero-recompile: a fixed workload driven twice compiles
  only on the first pass — the dispatch compile counter stays flat on
  the second (the acceptance gate for the serving path);
* closed-grid enforcement: a compile for a shape outside the declared
  bucket grid raises under strict mode, and the PUBLIC serving paths
  never escape the grid even when fed ragged batch sizes — a future
  caller that forgets to pad fails here instead of silently
  reintroducing shape churn;
* donation safety: only the declared accumulator buffers are donated;
  corpus-resident arrays survive a dispatch and remain readable.
"""

import numpy as np
import pytest

from elasticsearch_tpu.ops import dispatch
from elasticsearch_tpu.ops import knn as knn_ops
from elasticsearch_tpu.ops import similarity as sim
from elasticsearch_tpu.vectors.store import VectorStoreShard


@pytest.fixture
def strict_dispatch():
    """Run a test with grid escapes raising; restore after."""
    old = dispatch.DISPATCH.strict
    dispatch.DISPATCH.strict = True
    yield dispatch.DISPATCH
    dispatch.DISPATCH.strict = old


def _corpus(n=256, d=16, seed=0, dtype="bf16"):
    rng = np.random.default_rng(seed)
    return knn_ops.build_corpus(
        rng.standard_normal((n, d), dtype=np.float32), dtype=dtype)


# ---------------------------------------------------------------------------
# bucket policy
# ---------------------------------------------------------------------------

class TestBucketSelection:
    def test_query_buckets_are_pow2_and_cover(self):
        for n in range(1, 300):
            b = dispatch.bucket_queries(n)
            assert b >= n
            assert b & (b - 1) == 0 or b % dispatch.MAX_QUERY_BUCKET == 0
            assert dispatch.is_query_bucket(b)

    def test_dead_rungs_2_and_4(self):
        """2..7 pad to 8: XLA-CPU's dot_general small-M path made a
        [4, N] score matmul ~3.5x SLOWER than [8, N] (the measured root
        cause of the r06 batch=4 @ 149 ms vs batch=16 @ 31.6 ms
        inversion, alongside the recompile churn); on TPU the MXU pads
        sublanes to 8 anyway, so the rung is free."""
        assert dispatch.bucket_queries(1) == 1
        for n in (2, 3, 4, 5, 6, 7, 8):
            assert dispatch.bucket_queries(n) == 8
        assert dispatch.bucket_queries(9) == 16
        assert not dispatch.is_query_bucket(2)
        assert not dispatch.is_query_bucket(4)

    def test_query_bucket_monotone(self):
        """No inversion is possible at the policy level: a smaller batch
        never selects a larger compiled program than a bigger batch (the
        r06 anomaly had batch=4 at 149 ms p50 vs batch=16 at 31.6 ms —
        4 was recompiling while 16 hit a cache)."""
        prev = 0
        for n in range(1, 2050):
            b = dispatch.bucket_queries(n)
            assert b >= prev
            prev = b

    def test_query_bucket_idempotent(self):
        for n in (1, 2, 8, 64, 2048, 4096):
            assert dispatch.bucket_queries(dispatch.bucket_queries(n)) \
                == dispatch.bucket_queries(n)

    def test_k_bucket_ladder(self):
        assert dispatch.bucket_k(10) == 10
        assert dispatch.bucket_k(11) == 16
        assert dispatch.bucket_k(65) == 100
        assert dispatch.bucket_k(101) == 128
        prev = 0
        for k in range(1, 1200):
            kb = dispatch.bucket_k(k)
            assert kb >= k and kb >= prev
            assert dispatch.in_k_grid(kb)
            prev = kb

    def test_k_bucket_clamps_to_corpus(self):
        assert dispatch.bucket_k(10, limit=7) == 7
        assert dispatch.bucket_k(3, limit=7) == 4
        assert dispatch.in_k_grid(7, limit=7)

    def test_beyond_ladder_multiples(self):
        kb = dispatch.bucket_k(1500)
        assert kb == 2048 and dispatch.in_k_grid(kb)

    def test_bucket_headroom_is_free_topup_budget(self):
        """The continuous batcher's top-up query: free rows left in a
        batch's dispatch bucket. A batch sitting ON a bucket boundary
        (incl. the lone-query bucket 1) has zero headroom — so a top-up
        can never change the compiled shape set."""
        assert dispatch.bucket_headroom(1) == 0
        assert dispatch.bucket_headroom(5) == 3
        assert dispatch.bucket_headroom(8) == 0
        assert dispatch.bucket_headroom(9) == 7
        assert dispatch.bucket_headroom(2048) == 0
        # a caller's max_batch ceiling clamps the budget
        assert dispatch.bucket_headroom(5, max_batch=6) == 1
        for n in range(1, 300):
            b = n + dispatch.bucket_headroom(n)
            assert dispatch.is_query_bucket(b) or b == n


# ---------------------------------------------------------------------------
# bucket-boundary parity
# ---------------------------------------------------------------------------

class TestPadBoundaryParity:
    def test_batch_8_vs_9_byte_identical(self):
        """The same query must return bit-identical results whether it
        coalesced into a batch of 8 (exact bucket) or 9 (padded to 16)."""
        store = VectorStoreShard(warmup=False)
        corpus = _corpus(512, 24)
        from elasticsearch_tpu.vectors.store import FieldCorpus
        fc = FieldCorpus(corpus, np.arange(512, dtype=np.int64),
                         sim.COSINE, 24, version=("t",))
        store._fields["v"] = fc
        rng = np.random.default_rng(7)
        queries = rng.standard_normal((9, 24), dtype=np.float32)
        reqs9 = [(q, None) for q in queries]
        out9 = store.search_many("v", reqs9, k=10)
        out8 = store.search_many("v", reqs9[:8], k=10)
        for i in range(8):
            np.testing.assert_array_equal(out8[i][0], out9[i][0])
            np.testing.assert_array_equal(out8[i][1], out9[i][1])

    def test_k_bucket_slice_parity(self):
        """k=11 buckets to 16 and slices: identical to a direct k=11
        top-k (top-k prefixes are exact)."""
        store = VectorStoreShard(warmup=False)
        corpus = _corpus(512, 24)
        from elasticsearch_tpu.vectors.store import FieldCorpus
        fc = FieldCorpus(corpus, np.arange(512, dtype=np.int64),
                         sim.COSINE, 24, version=("t",))
        store._fields["v"] = fc
        rng = np.random.default_rng(3)
        q = rng.standard_normal((4, 24), dtype=np.float32)
        out11 = store.search_many("v", [(x, None) for x in q], k=11)
        out16 = store.search_many("v", [(x, None) for x in q], k=16)
        for i in range(4):
            np.testing.assert_array_equal(out11[i][0], out16[i][0][:11])
            np.testing.assert_array_equal(out11[i][1], out16[i][1][:11])


# ---------------------------------------------------------------------------
# steady-state zero-recompile
# ---------------------------------------------------------------------------

class TestZeroRecompile:
    def test_fixed_workload_second_pass_compiles_nothing(self):
        """Acceptance gate: after the first pass of a fixed workload
        (which IS the warmup), a repeat records 0 new compiles."""
        store = VectorStoreShard(warmup=False)
        corpus = _corpus(384, 32, seed=1)
        from elasticsearch_tpu.vectors.store import FieldCorpus
        fc = FieldCorpus(corpus, np.arange(384, dtype=np.int64),
                         sim.COSINE, 32, version=("t",))
        store._fields["v"] = fc
        rng = np.random.default_rng(11)

        def drive():
            for batch, k in ((1, 10), (3, 10), (5, 13), (8, 10), (9, 40)):
                qs = rng.standard_normal((batch, 32), dtype=np.float32)
                store.search_many("v", [(q, None) for q in qs], k=k)

        drive()  # first pass: compiles the bucket grid
        before = dispatch.DISPATCH.compile_count()
        drive()  # steady state
        after = dispatch.DISPATCH.compile_count()
        assert after == before, (
            f"steady-state workload recompiled: {after - before} new "
            f"compiles; stats={dispatch.stats(per_bucket=True)}")

    def test_warmup_precompiles_grid(self):
        """An AOT-warmed bucket is a HIT on its first real query."""
        corpus = _corpus(256, 16, seed=5)
        spec = dispatch.specs_like(corpus)
        statics = {"k": 10, "metric": sim.COSINE, "precision": "bf16",
                   "block_size": None}
        entries = [("knn.exact",
                    (dispatch.query_spec(4, 16), spec, None), statics)]
        t = dispatch.DISPATCH.warmup(entries, background=True)
        t.join(timeout=120)
        before = dispatch.DISPATCH.compile_count()
        import jax.numpy as jnp
        q = np.zeros((4, 16), dtype=np.float32)
        knn_ops.knn_search(jnp.asarray(q), corpus, k=10)
        assert dispatch.DISPATCH.compile_count() == before

    def test_stats_shape(self):
        s = dispatch.stats(per_bucket=True)
        for key in ("hits", "misses", "compiles", "compile_nanos",
                    "out_of_grid_compiles", "buckets",
                    "cached_executables"):
            assert key in s
        for bucket_stats in s["buckets"].values():
            assert set(bucket_stats) == {"hits", "misses",
                                         "compile_nanos"}


# ---------------------------------------------------------------------------
# closed-grid enforcement (the CI regression gate)
# ---------------------------------------------------------------------------

class TestClosedGrid:
    def test_unbucketed_direct_call_is_flagged(self, strict_dispatch):
        """A raw kernel call with a non-bucket batch size is an escape:
        strict mode raises (this is what a future unpadded caller hits)."""
        import jax.numpy as jnp
        corpus = _corpus(256, 16, seed=2)
        q = jnp.zeros((3, 16), dtype=jnp.float32)  # 3 is not a bucket
        with pytest.raises(dispatch.DispatchGridEscape):
            knn_ops.knn_search(q, corpus, k=10)

    def test_public_serving_path_never_escapes(self, strict_dispatch):
        """The serving path pads every ragged batch to a bucket, so
        strict mode never fires — if this raises, somebody broke the
        pad-to-bucket coalescing."""
        store = VectorStoreShard(warmup=False)
        corpus = _corpus(320, 16, seed=3)
        from elasticsearch_tpu.vectors.store import FieldCorpus
        fc = FieldCorpus(corpus, np.arange(320, dtype=np.int64),
                         sim.COSINE, 16, version=("t",))
        store._fields["v"] = fc
        rng = np.random.default_rng(13)
        for batch in (1, 2, 3, 5, 7, 9, 11):
            qs = rng.standard_normal((batch, 16), dtype=np.float32)
            out = store.search_many("v", [(q, None) for q in qs], k=12)
            assert len(out) == batch

    def test_escape_counter_increments_when_lenient(self):
        import jax.numpy as jnp
        corpus = _corpus(256, 16, seed=4)
        before = dispatch.stats(per_bucket=False)["out_of_grid_compiles"]
        q = jnp.zeros((5, 16), dtype=jnp.float32)  # 5 is not a bucket
        knn_ops.knn_search(q, corpus, k=10)
        after = dispatch.stats(per_bucket=False)["out_of_grid_compiles"]
        assert after == before + 1


# ---------------------------------------------------------------------------
# donation safety
# ---------------------------------------------------------------------------

class TestDonationSafety:
    def _lexical_reader(self):
        from elasticsearch_tpu.index.mapping import MapperService
        from elasticsearch_tpu.index.engine import Engine
        import tempfile
        tmp = tempfile.mkdtemp(prefix="dispatch_bm25_")
        mapper = MapperService(
            {"properties": {"body": {"type": "text"}}})
        engine = Engine(tmp, mapper, translog_sync="async")
        words = ["alpha", "beta", "gamma", "delta", "epsilon"]
        rng = np.random.default_rng(23)
        for i in range(64):
            text = " ".join(rng.choice(words, size=6))
            engine.index(str(i), {"body": text})
        engine.refresh()
        return engine.acquire_searcher()

    def test_bm25_device_donation_correct_and_repeatable(self):
        """The donated score/count boards are freshly allocated per call,
        so back-to-back device dispatches stay correct — and the device
        route (donating) stays byte-identical to the host twin."""
        from elasticsearch_tpu.ops.bm25 import LexicalShard
        reader = self._lexical_reader()
        shard = LexicalShard()
        queries = [(["alpha", "beta"], 1.0), (["gamma"], 2.0)]
        host = shard.search_batch(reader, "body", queries, 10,
                                  route="host")
        for _ in range(3):  # repeatability: donation must not corrupt
            dev = shard.search_batch(reader, "body", queries, 10,
                                     route="device")
            for (hr, hs), (dr, ds) in zip(host, dev):
                np.testing.assert_array_equal(hr, dr)
                np.testing.assert_array_equal(hs, ds)

    def test_non_donated_args_survive(self):
        """Only the declared boards are donated: the tile arrays (the
        corpus-resident HBM state) survive a dispatch and remain
        readable."""
        import jax.numpy as jnp
        nq, width, m, n_tiles = 2, 129, 2, 2
        tile_slots = jnp.asarray(
            np.arange(n_tiles * 128, dtype=np.int32).reshape(n_tiles, 128)
            % (width - 1))
        tile_impacts = jnp.ones((n_tiles, 128), dtype=jnp.float32)
        args = (jnp.zeros((nq, width), jnp.float32),
                jnp.zeros((nq, width), jnp.int32),
                jnp.zeros((nq, m), jnp.int32),
                jnp.ones((nq, m), jnp.float32),
                jnp.ones((nq,), jnp.int32),
                tile_slots, tile_impacts, None)
        dispatch.call("bm25.topk", *args, k=4)
        # corpus arrays not donated: still alive and consistent
        assert not tile_slots.is_deleted()
        assert not tile_impacts.is_deleted()
        assert float(jnp.sum(tile_impacts)) == n_tiles * 128

    def test_registered_donation_argnums(self):
        """The registry pins donation to the board argnums only — a
        registration drift here silently donates the corpus."""
        import elasticsearch_tpu.ops.knn_ivf  # noqa: F401 (registers ivf.*)
        kernel = dispatch.DISPATCH._kernels["bm25.topk"]
        assert kernel.donate_argnums == (0, 1)
        for name in ("knn.exact", "ivf.route", "ivf.score_probes",
                     "topk.top_k", "topk.masked_top_k"):
            assert dispatch.DISPATCH._kernels[name].donate_argnums == ()


# ---------------------------------------------------------------------------
# dispatcher mechanics
# ---------------------------------------------------------------------------

class TestDispatcherMechanics:
    def test_tracer_calls_inline(self):
        """A dispatched kernel inside an enclosing jit inlines instead of
        touching the executable cache (bench_matrix's scan wrapper)."""
        import jax
        import jax.numpy as jnp
        from elasticsearch_tpu.ops import topk as topk_ops
        before = dispatch.DISPATCH.compile_count()

        @jax.jit
        def outer(x):
            return topk_ops.top_k(x, 4)[0]

        out = outer(jnp.arange(32.0).reshape(2, 16))
        assert out.shape == (2, 4)
        # outer's own jit compiles via jax, not via the dispatcher
        assert dispatch.DISPATCH.compile_count() == before
        assert dispatch.stats(per_bucket=False)["inline_calls"] >= 1

    def test_event_trace_thread_local(self):
        import jax.numpy as jnp
        from elasticsearch_tpu.ops import topk as topk_ops
        dispatch.DISPATCH.record_events(True)
        try:
            topk_ops.top_k(jnp.arange(64.0).reshape(4, 16), 10)
            events = dispatch.DISPATCH.drain_events()
        finally:
            dispatch.DISPATCH.record_events(False)
        assert events and events[0]["kernel"] == "topk.top_k"
        assert events[0]["cache"] in ("hit", "miss")
        # recording off: drain yields nothing
        assert dispatch.DISPATCH.drain_events() == []

    def test_persistent_cache_configure(self, tmp_path):
        import jax
        old = jax.config.jax_compilation_cache_dir
        try:
            assert dispatch.configure_persistent_cache(
                str(tmp_path / "xla_cache"))
            assert dispatch.persistent_cache_dir() == \
                str(tmp_path / "xla_cache")
            assert (tmp_path / "xla_cache").is_dir()
        finally:
            jax.config.update("jax_compilation_cache_dir", old)
