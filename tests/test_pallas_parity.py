"""Interpret-mode parity for the Pallas binned kNN kernels (ROADMAP
portability slice): the north-star int8 Pallas path only compiles on TPU
backends, so without these tests its program structure was never
regression-tested in tier-1 — r06's `run_north_star_10m_int8` errored on
the CPU floor and PR 4 merely downgraded that to a labeled skip. Pallas
interpret mode executes the same kernel body with jnp semantics on any
backend, so structural regressions (packing/decode math, bin geometry,
dequant scales, validity masking) fail HERE instead of on the next TPU
capture."""

import numpy as np
import pytest

from elasticsearch_tpu.ops import dispatch
from elasticsearch_tpu.ops import knn as knn_ops
from elasticsearch_tpu.ops import pallas_knn_binned as binned
from elasticsearch_tpu.ops import similarity as sim

N, D, K, NQ = 6000, 32, 4, 8  # one BLOCK_N tile, padded 6000 -> 8192


@pytest.fixture(scope="module")
def data():
    rng = np.random.default_rng(42)
    vecs = rng.standard_normal((N, D)).astype(np.float32)
    qs = rng.standard_normal((NQ, D)).astype(np.float32)
    vn = vecs / np.linalg.norm(vecs, axis=1, keepdims=True)
    qn = qs / np.linalg.norm(qs, axis=1, keepdims=True)
    exact = qn @ vn.T
    top_exact = np.argsort(-exact, axis=1)[:, :K]
    return vecs, qs, exact, top_exact


def _recall(ids, top_exact):
    return float(np.mean([len(set(ids[i]) & set(top_exact[i])) / K
                          for i in range(NQ)]))


def test_interpret_binned_matches_exact_structure(data):
    vecs, qs, exact, top_exact = data
    corpus = knn_ops.build_corpus(vecs, metric=sim.COSINE, dtype="f32",
                                  pad_to=binned.BLOCK_N)
    s, ids = binned.binned_knn_search(np.asarray(qs), corpus, k=K,
                                     metric=sim.COSINE, interpret=True)
    s, ids = np.asarray(s), np.asarray(ids)
    # every returned id is a real (non-padding) row and its packed score
    # decodes to the true cosine of that row (bf16 matmul + 6 masked
    # mantissa bits bound the error)
    assert (ids >= 0).all() and (ids < N).all()
    for i in range(NQ):
        assert len(set(ids[i].tolist())) == K  # no duplicate winners
        for j in range(K):
            assert abs(s[i, j] - exact[i, ids[i, j]]) < 0.05
    # binned reduction keeps one candidate per 64-row bin: recall@k is
    # bounded by bin collisions, not broken structure
    assert _recall(ids, top_exact) >= 0.85


def test_interpret_binned_int8_and_rescore_paths(data):
    vecs, qs, exact, top_exact = data
    corpus = knn_ops.build_corpus(vecs, metric=sim.COSINE, dtype="int8",
                                  pad_to=binned.BLOCK_N)
    _, ids = binned.binned_knn_search(np.asarray(qs), corpus, k=K,
                                      metric=sim.COSINE, interpret=True)
    base_recall = _recall(np.asarray(ids), top_exact)
    assert base_recall >= 0.7
    s8, ids8 = binned.binned_knn_search_rescored_packed(
        np.asarray(qs), corpus, k=K, metric=sim.COSINE,
        rescore_candidates=128, interpret=True)
    ids8 = np.asarray(ids8)
    assert (ids8 >= 0).all() and (ids8 < N).all()
    # rescoring re-ranks a superset of the base picks with the
    # unquantized query: it may only help
    assert _recall(ids8, top_exact) >= base_recall - 1e-9


def test_interpret_binned_validity_mask_excludes_padding(data):
    vecs, qs, _, _ = data
    # tiny corpus inside one tile: padding rows dominate and must never win
    small = vecs[:100]
    corpus = knn_ops.build_corpus(small, metric=sim.COSINE, dtype="f32",
                                  pad_to=binned.BLOCK_N)
    _, ids = binned.binned_knn_search(np.asarray(qs), corpus, k=K,
                                      metric=sim.COSINE, interpret=True)
    ids = np.asarray(ids)
    assert (ids < 100).all()


# ---------------------------------------------------------------------------
# fused IVF gather+score kernel (ops/pallas_ivf_fused.py): the scalar-
# prefetch gather must reproduce the scan-based probe scorer exactly
# ---------------------------------------------------------------------------

IVF_N, IVF_D, IVF_NLIST, IVF_NPROBE = 2048, 64, 32, 8


@pytest.fixture(scope="module")
def ivf_layouts():
    from elasticsearch_tpu.ann.ivf_index import build_ivf_index
    rng = np.random.default_rng(17)
    vecs = rng.standard_normal((IVF_N, IVF_D)).astype(np.float32)
    qs = rng.standard_normal((NQ, IVF_D)).astype(np.float32)
    out = {}
    for dt in ("f32", "bf16", "int8", "int4"):
        out[dt] = build_ivf_index(vecs, metric=sim.COSINE,
                                  nlist=IVF_NLIST, dtype=dt)
    return vecs, qs, out


@pytest.mark.parametrize("dt", ["f32", "bf16", "int8", "int4"])
def test_interpret_fused_probe_matches_scan_scorer(ivf_layouts, dt):
    """Byte parity of the fused gather+score board against the
    jnp.take-based scan scorer: identical winner rows, near-identical
    scores (both run the same bf16 matmul + dequant math)."""
    import jax.numpy as jnp

    from elasticsearch_tpu.ops import knn_ivf
    from elasticsearch_tpu.ops import pallas_ivf_fused as fused
    _, qs, layouts = ivf_layouts
    parts = layouts[dt].device_partitions()
    q = knn_ivf._prep_queries(jnp.asarray(qs), sim.COSINE)
    probe_ids, _ = knn_ivf.route(q, parts, IVF_NPROBE, metric=sim.COSINE)
    s_scan, r_scan = knn_ivf.score_probes(q, parts, probe_ids, 10,
                                          metric=sim.COSINE)
    s_f, r_f = fused.fused_probe_scores(q, parts, probe_ids, 10,
                                        metric=sim.COSINE, interpret=True)
    np.testing.assert_array_equal(np.asarray(r_scan), np.asarray(r_f))
    np.testing.assert_allclose(np.asarray(s_scan), np.asarray(s_f),
                               rtol=2e-3, atol=2e-3)


def test_interpret_fused_probe_validity_mask_excludes_padding(ivf_layouts):
    """Partition-capacity padding rows (part_rows == -1, zero scales)
    must never win a top-k slot, even when probed partitions are mostly
    padding."""
    import jax.numpy as jnp

    from elasticsearch_tpu.ann.ivf_index import build_ivf_index
    from elasticsearch_tpu.ops import knn_ivf
    from elasticsearch_tpu.ops import pallas_ivf_fused as fused
    rng = np.random.default_rng(23)
    tiny = rng.standard_normal((40, IVF_D)).astype(np.float32)
    idx = build_ivf_index(tiny, metric=sim.COSINE, nlist=4, dtype="f32")
    parts = idx.device_partitions()
    qs = rng.standard_normal((8, IVF_D)).astype(np.float32)
    q = knn_ivf._prep_queries(jnp.asarray(qs), sim.COSINE)
    probe_ids, _ = knn_ivf.route(q, parts, 4, metric=sim.COSINE)
    s, r = fused.fused_probe_scores(q, parts, probe_ids, 16,
                                    metric=sim.COSINE, interpret=True)
    s, r = np.asarray(s), np.asarray(r)
    real = r >= 0
    assert (r[real] < 40).all()
    assert (s[~real] < -1e37).all()  # padding slots carry the sentinel


def test_interpret_fused_probe_zero_recompile_second_pass(ivf_layouts):
    """The fused kernel's compile set is closed: a second pass over the
    warmed (Q bucket, nprobe, k) grid compiles nothing under strict
    dispatch."""
    import jax.numpy as jnp

    from elasticsearch_tpu.ops import knn_ivf
    from elasticsearch_tpu.ops import pallas_ivf_fused as fused
    _, qs, layouts = ivf_layouts
    parts = layouts["int8"].device_partitions()
    q = knn_ivf._prep_queries(jnp.asarray(qs), sim.COSINE)
    probe_ids, _ = knn_ivf.route(q, parts, IVF_NPROBE, metric=sim.COSINE)
    fused.fused_probe_scores(q, parts, probe_ids, 10, metric=sim.COSINE,
                             interpret=True)
    before = dispatch.DISPATCH.compile_count()
    strict_before = dispatch.DISPATCH.strict
    dispatch.DISPATCH.strict = True
    try:
        fused.fused_probe_scores(q, parts, probe_ids, 10,
                                 metric=sim.COSINE, interpret=True)
    finally:
        dispatch.DISPATCH.strict = strict_before
    assert dispatch.DISPATCH.compile_count() == before


def test_interpret_binned_steady_state_zero_recompile(data):
    vecs, qs, _, _ = data
    corpus = knn_ops.build_corpus(vecs, metric=sim.COSINE, dtype="f32",
                                  pad_to=binned.BLOCK_N)
    binned.binned_knn_search(np.asarray(qs), corpus, k=K,
                             metric=sim.COSINE, interpret=True)
    before = dispatch.DISPATCH.compile_count()
    strict_before = dispatch.DISPATCH.strict
    dispatch.DISPATCH.strict = True
    try:
        binned.binned_knn_search(np.asarray(qs), corpus, k=K,
                                 metric=sim.COSINE, interpret=True)
    finally:
        dispatch.DISPATCH.strict = strict_before
    assert dispatch.DISPATCH.compile_count() == before
