"""Extended query types: geo, rank features, MLT, terms_set, nested,
parent-join, percolate, span/intervals, wrapper, pinned + geo aggs.

Reference behaviors: index/query/* builders, modules/percolator,
modules/parent-join, modules/mapper-extras, x-pack search-business-rules.
"""

import base64
import json

import pytest

from elasticsearch_tpu.node import Node
from elasticsearch_tpu.rest.actions import register_all
from elasticsearch_tpu.rest.controller import RestController


class Client:
    def __init__(self, node):
        self.rc = RestController()
        register_all(self.rc, node)

    def req(self, method, path, body=None, **query):
        raw = json.dumps(body).encode() if body is not None else b""
        return self.rc.dispatch(method, path, {k: str(v) for k, v in query.items()},
                                raw, "application/json")


@pytest.fixture
def node(tmp_path):
    n = Node(str(tmp_path / "data"))
    yield n
    n.close()


@pytest.fixture
def client(node):
    return Client(node)


def ids(body):
    return {h["_id"] for h in body["hits"]["hits"]}


# --------------------------------------------------------------------- geo

def _seed_geo(client):
    client.req("PUT", "/places", {"mappings": {"properties": {
        "location": {"type": "geo_point"}, "name": {"type": "keyword"}}}})
    pts = {"brandenburg": (52.5163, 13.3777),
           "eiffel": (48.8584, 2.2945),
           "colosseum": (41.8902, 12.4922),
           "big_ben": (51.5007, -0.1246)}
    for name, (lat, lon) in pts.items():
        client.req("PUT", f"/places/_doc/{name}",
                   {"name": name, "location": {"lat": lat, "lon": lon}})
    client.req("POST", "/places/_refresh")


def test_geo_distance(client):
    _seed_geo(client)
    st, body = client.req("POST", "/places/_search", {"query": {
        "geo_distance": {"distance": "400km",
                         "location": {"lat": 48.85, "lon": 2.35}}}})
    assert ids(body) == {"eiffel", "big_ben"}


def test_geo_bounding_box(client):
    _seed_geo(client)
    st, body = client.req("POST", "/places/_search", {"query": {
        "geo_bounding_box": {"location": {
            "top_left": {"lat": 53.0, "lon": 0.0},
            "bottom_right": {"lat": 48.0, "lon": 14.0}}}}})
    assert ids(body) == {"brandenburg", "eiffel"}


def test_geo_polygon(client):
    _seed_geo(client)
    # triangle around Rome
    st, body = client.req("POST", "/places/_search", {"query": {
        "geo_polygon": {"location": {"points": [
            {"lat": 43.0, "lon": 11.0}, {"lat": 43.0, "lon": 14.0},
            {"lat": 40.0, "lon": 12.5}]}}}})
    assert ids(body) == {"colosseum"}


def test_geo_aggs(client):
    _seed_geo(client)
    st, body = client.req("POST", "/places/_search", {"size": 0, "aggs": {
        "grid": {"geohash_grid": {"field": "location", "precision": 2}},
        "box": {"geo_bounds": {"field": "location"}},
        "center": {"geo_centroid": {"field": "location"}}}})
    aggs = body["aggregations"]
    assert len(aggs["grid"]["buckets"]) >= 2
    assert aggs["box"]["bounds"]["top_left"]["lat"] == pytest.approx(52.5163)
    assert aggs["center"]["count"] == 4


def test_geotile_grid(client):
    _seed_geo(client)
    st, body = client.req("POST", "/places/_search", {"size": 0, "aggs": {
        "tiles": {"geotile_grid": {"field": "location", "precision": 4}}}})
    keys = [b["key"] for b in body["aggregations"]["tiles"]["buckets"]]
    assert all(k.startswith("4/") for k in keys)


def test_distance_feature_geo(client):
    _seed_geo(client)
    st, body = client.req("POST", "/places/_search", {"query": {
        "distance_feature": {"field": "location",
                             "origin": {"lat": 48.85, "lon": 2.35},
                             "pivot": "100km"}}})
    hits = body["hits"]["hits"]
    assert hits[0]["_id"] == "eiffel"      # closest scores highest


# ----------------------------------------------------------- rank features

def test_rank_feature_query(client):
    client.req("PUT", "/pages", {"mappings": {"properties": {
        "pagerank": {"type": "rank_feature"},
        "topics": {"type": "rank_features"}}}})
    client.req("PUT", "/pages/_doc/1", {"pagerank": 10.0,
                                        "topics": {"sports": 20.0}})
    client.req("PUT", "/pages/_doc/2", {"pagerank": 1.0,
                                        "topics": {"sports": 1.0}})
    client.req("POST", "/pages/_refresh")
    st, body = client.req("POST", "/pages/_search", {"query": {
        "rank_feature": {"field": "pagerank", "saturation": {"pivot": 5}}}})
    hits = body["hits"]["hits"]
    assert hits[0]["_id"] == "1" and hits[0]["_score"] > hits[1]["_score"]
    # rank_features sub-feature
    st, body = client.req("POST", "/pages/_search", {"query": {
        "rank_feature": {"field": "topics.sports", "log": {"scaling_factor": 1}}}})
    assert body["hits"]["hits"][0]["_id"] == "1"


# ----------------------------------------------------------- more_like_this

def test_more_like_this(client):
    docs = {
        "1": "machine learning on tensor processing units",
        "2": "deep machine learning with tensor hardware accelerators",
        "3": "cooking pasta with tomato sauce",
        "4": "machine learning tensor compilers",
    }
    for i, text in docs.items():
        client.req("PUT", f"/articles/_doc/{i}", {"body": text})
    client.req("POST", "/articles/_refresh")
    st, body = client.req("POST", "/articles/_search", {"query": {
        "more_like_this": {"fields": ["body"], "like": [{"_id": "1"}],
                           "min_term_freq": 1, "min_doc_freq": 2,
                           "minimum_should_match": 1}}})
    assert st == 200
    result = ids(body)
    assert "1" not in result          # liked doc excluded by default
    assert "2" in result and "4" in result
    assert "3" not in result


# --------------------------------------------------------------- terms_set

def test_terms_set(client):
    client.req("PUT", "/skills", {"mappings": {"properties": {
        "langs": {"type": "keyword"}, "required": {"type": "long"}}}})
    client.req("PUT", "/skills/_doc/1",
               {"langs": ["java", "python", "go"], "required": 2})
    client.req("PUT", "/skills/_doc/2", {"langs": ["java"], "required": 2})
    client.req("POST", "/skills/_refresh")
    st, body = client.req("POST", "/skills/_search", {"query": {
        "terms_set": {"langs": {"terms": ["java", "python"],
                                "minimum_should_match_field": "required"}}}})
    assert ids(body) == {"1"}


# ------------------------------------------------------------------ nested

def test_nested_query_object_pairing(client):
    client.req("PUT", "/drivers", {"mappings": {"properties": {
        "vehicles": {"type": "nested", "properties": {
            "make": {"type": "keyword"}, "year": {"type": "long"}}}}}})
    client.req("PUT", "/drivers/_doc/1", {"vehicles": [
        {"make": "honda", "year": 2000}, {"make": "ford", "year": 2020}]})
    client.req("PUT", "/drivers/_doc/2", {"vehicles": [
        {"make": "honda", "year": 2020}]})
    client.req("POST", "/drivers/_refresh")
    # only doc 2 has ONE object with both honda AND 2020 — the flat-field
    # cross-object match that nested exists to prevent would return both
    st, body = client.req("POST", "/drivers/_search", {"query": {
        "nested": {"path": "vehicles", "query": {"bool": {"must": [
            {"term": {"vehicles.make": "honda"}},
            {"range": {"vehicles.year": {"gte": 2015}}}]}}}}})
    assert ids(body) == {"2"}


# ------------------------------------------------------------- parent-join

def test_has_child_has_parent(client):
    client.req("PUT", "/qa", {"mappings": {"properties": {
        "relation": {"type": "join",
                     "relations": {"question": "answer"}},
        "body": {"type": "text"}}}})
    client.req("PUT", "/qa/_doc/q1", {"body": "how to jit", "relation": "question"})
    client.req("PUT", "/qa/_doc/q2", {"body": "how to grad", "relation": "question"})
    client.req("PUT", "/qa/_doc/a1", {"body": "use jax.jit decorator",
                                      "relation": {"name": "answer", "parent": "q1"}})
    client.req("PUT", "/qa/_doc/a2", {"body": "use jax.grad",
                                      "relation": {"name": "answer", "parent": "q2"}})
    client.req("POST", "/qa/_refresh")
    st, body = client.req("POST", "/qa/_search", {"query": {
        "has_child": {"type": "answer",
                      "query": {"match": {"body": "jit"}}}}})
    assert ids(body) == {"q1"}
    st, body = client.req("POST", "/qa/_search", {"query": {
        "has_parent": {"parent_type": "question",
                       "query": {"match": {"body": "grad"}}}}})
    assert ids(body) == {"a2"}
    st, body = client.req("POST", "/qa/_search", {"query": {
        "parent_id": {"type": "answer", "id": "q1"}}})
    assert ids(body) == {"a1"}


# --------------------------------------------------------------- percolate

def test_percolator(client):
    client.req("PUT", "/watches", {"mappings": {"properties": {
        "query": {"type": "percolator"}, "msg": {"type": "text"}}}})
    client.req("PUT", "/watches/_doc/w1",
               {"query": {"match": {"msg": "error"}}})
    client.req("PUT", "/watches/_doc/w2",
               {"query": {"bool": {"must": [
                   {"match": {"msg": "disk"}},
                   {"range": {"pct": {"gte": 90}}}]}}})
    client.req("POST", "/watches/_refresh")
    st, body = client.req("POST", "/watches/_search", {"query": {
        "percolate": {"field": "query",
                      "document": {"msg": "disk full error", "pct": 95}}}})
    assert ids(body) == {"w1", "w2"}
    st, body = client.req("POST", "/watches/_search", {"query": {
        "percolate": {"field": "query",
                      "document": {"msg": "disk warning", "pct": 50}}}})
    assert ids(body) == set()


# ---------------------------------------------------------- span/intervals

def _seed_text(client):
    client.req("PUT", "/texts/_doc/1",
               {"line": "the quick brown fox jumps over the lazy dog"})
    client.req("PUT", "/texts/_doc/2",
               {"line": "the dog was quick and brown was the fox"})
    client.req("POST", "/texts/_refresh")


def test_span_near_in_order(client):
    _seed_text(client)
    st, body = client.req("POST", "/texts/_search", {"query": {
        "span_near": {"clauses": [
            {"span_term": {"line": "quick"}},
            {"span_term": {"line": "fox"}}],
            "slop": 1, "in_order": True}}})
    assert ids(body) == {"1"}     # doc2 has them 7 apart / out of order


def test_span_first(client):
    _seed_text(client)
    st, body = client.req("POST", "/texts/_search", {"query": {
        "span_first": {"match": {"span_term": {"line": "dog"}}, "end": 3}}})
    assert ids(body) == {"2"}     # 'dog' at position 1 in doc2, 8 in doc1


def test_span_not(client):
    _seed_text(client)
    st, body = client.req("POST", "/texts/_search", {"query": {
        "span_not": {
            "include": {"span_term": {"line": "fox"}},
            "exclude": {"span_near": {"clauses": [
                {"span_term": {"line": "brown"}},
                {"span_term": {"line": "fox"}}],
                "slop": 0, "in_order": True}}}}})
    assert ids(body) == {"2"}     # doc1's fox immediately follows brown


def test_intervals_ordered(client):
    _seed_text(client)
    st, body = client.req("POST", "/texts/_search", {"query": {
        "intervals": {"line": {"match": {
            "query": "quick fox", "ordered": True, "max_gaps": 2}}}}})
    assert ids(body) == {"1"}


# ------------------------------------------------------- wrapper + pinned

def test_wrapper_query(client):
    client.req("PUT", "/w/_doc/1", {"k": "v"})
    client.req("POST", "/w/_refresh")
    inner = base64.b64encode(json.dumps({"term": {"k": "v"}}).encode()).decode()
    st, body = client.req("POST", "/w/_search",
                          {"query": {"wrapper": {"query": inner}}})
    assert ids(body) == {"1"}


def test_pinned_query(client):
    for i in range(5):
        client.req("PUT", f"/prods/_doc/{i}", {"t": "widget widget" if i < 3
                                               else "widget"})
    client.req("POST", "/prods/_refresh")
    st, body = client.req("POST", "/prods/_search", {"query": {
        "pinned": {"ids": ["4", "3"],
                   "organic": {"match": {"t": "widget"}}}}})
    top2 = [h["_id"] for h in body["hits"]["hits"][:2]]
    assert top2 == ["4", "3"]     # pinned order wins over organic score
