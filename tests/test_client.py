"""Client library against a real HTTP server subprocess (reference:
client/rest RestClient + client/rest-high-level typed surface)."""

import pytest

from tests.conftest import http_server_subprocess

from elasticsearch_tpu.client import (
    ConnectionError_,
    Transport,
    TpuSearchClient,
    TransportError,
)

PORT = 19351


@pytest.fixture(scope="module")
def server(tmp_path_factory):
    data = tmp_path_factory.mktemp("clientsrv")
    with http_server_subprocess(PORT, str(data)) as proc:
        yield proc


@pytest.fixture(scope="module")
def client(server):
    return TpuSearchClient([f"localhost:{PORT}"])


def test_info_and_ping(client):
    assert client.ping()
    info = client.info()
    assert info["tagline"] == "You Know, for (TPU) Search"


def test_document_lifecycle(client):
    r = client.index("books", {"title": "Dune", "pages": 412}, id="1",
                     refresh=True)
    assert r["result"] == "created"
    assert client.exists("books", "1")
    assert not client.exists("books", "zzz")
    doc = client.get("books", "1")
    assert doc["_source"]["title"] == "Dune"
    client.update("books", "1", {"doc": {"pages": 500}}, refresh=True)
    assert client.get("books", "1")["_source"]["pages"] == 500
    r = client.delete("books", "1", refresh=True)
    assert r["result"] == "deleted"


def test_bulk_and_search(client):
    ops = []
    for i in range(5):
        ops.append({"index": {"_index": "logs", "_id": str(i)}})
        ops.append({"level": "error" if i % 2 else "info", "n": i})
    r = client.bulk(ops, refresh=True)
    assert not r["errors"]
    resp = client.search("logs", {"query": {"term": {"level.keyword":
                                                     "error"}}})
    assert resp["hits"]["total"]["value"] == 2
    assert client.count("logs")["count"] == 5
    resp = client.search("logs", {"size": 2,
                                  "sort": [{"n": {"order": "asc"}}]},
                         scroll="1m")
    sid = resp["_scroll_id"]
    page2 = client.scroll(sid)
    assert [h["_source"]["n"] for h in page2["hits"]["hits"]] == [2, 3]


def test_indices_namespace(client):
    client.indices.create("typed", {"mappings": {"properties": {
        "v": {"type": "dense_vector", "dims": 4}}}})
    assert client.indices.exists("typed")
    mapping = client.indices.get_mapping("typed")
    assert mapping["typed"]["mappings"]["properties"]["v"]["dims"] == 4
    client.indices.put_settings({"index": {"refresh_interval": "5s"}},
                                index="typed")
    client.indices.delete("typed")
    assert not client.indices.exists("typed")


def test_cluster_and_cat(client):
    health = client.cluster.health()
    assert health["status"] in ("green", "yellow")
    cats = client.cat.indices()
    assert isinstance(cats, (list, str))


def test_knn_search_through_client(client):
    client.indices.create("vecs", {"mappings": {"properties": {
        "v": {"type": "dense_vector", "dims": 3,
              "similarity": "l2_norm"}}}})
    for i, vec in enumerate([[1, 0, 0], [0, 1, 0], [0, 0, 1]]):
        client.index("vecs", {"v": vec}, id=str(i))
    client.indices.refresh("vecs")
    resp = client.search("vecs", {"size": 1, "query": {
        "knn": {"field": "v", "query_vector": [0.9, 0.1, 0], "k": 1}}})
    assert resp["hits"]["hits"][0]["_id"] == "0"


def test_error_surfaces_as_transport_error(client):
    with pytest.raises(TransportError) as ei:
        client.get("missing-index", "1")
    assert ei.value.status == 404
    with pytest.raises(TransportError) as ei:
        client.search("logs", {"query": {"bogus_query": {}}})
    assert ei.value.status == 400


def test_sql_through_client(client):
    r = client.sql.query({"query": "SELECT n FROM logs ORDER BY n DESC "
                                   "LIMIT 2"})
    assert [row[0] for row in r["rows"]] == [4, 3]


def test_dead_host_failover():
    t = Transport([("localhost", 1), ("localhost", 2)], max_retries=1,
                  timeout=0.2)
    with pytest.raises(ConnectionError_):
        t.perform_request("GET", "/")
    assert len(t._dead) >= 1


def test_multi_host_round_robin(client):
    # one dead host + one live: requests succeed via failover
    t = Transport([("localhost", 9), f"localhost:{PORT}"], timeout=2.0)
    for _ in range(4):
        assert t.perform_request("GET", "/_cluster/health")["status"] \
            in ("green", "yellow")
