"""SQL + EQL engines.

Reference behaviors: x-pack/plugin/sql (query folding into _search bodies,
composite-agg GROUP BY, cursors, txt format), x-pack/plugin/eql (event
queries, sequences with by/maxspan).
"""

import json

import pytest

from elasticsearch_tpu.node import Node
from elasticsearch_tpu.rest.actions import register_all
from elasticsearch_tpu.rest.controller import RestController
from elasticsearch_tpu.xpack.sql import parse_sql, translate, where_to_dsl


class Client:
    def __init__(self, node):
        self.rc = RestController()
        register_all(self.rc, node)

    def req(self, method, path, body=None, **query):
        raw = json.dumps(body).encode() if body is not None else b""
        return self.rc.dispatch(method, path, {k: str(v) for k, v in query.items()},
                                raw, "application/json")


@pytest.fixture
def node(tmp_path):
    n = Node(str(tmp_path / "data"))
    yield n
    n.close()


@pytest.fixture
def client(node):
    return Client(node)


def _seed_emp(client):
    rows = [
        ("alice", "eng", 100, 30), ("bob", "eng", 120, 35),
        ("carol", "sales", 90, 28), ("dan", "sales", 95, 40),
        ("erin", "hr", 80, 50),
    ]
    for i, (name, dept, salary, age) in enumerate(rows):
        client.req("PUT", f"/emp/_doc/{i}",
                   {"name": name, "dept": dept, "salary": salary, "age": age})
    client.req("POST", "/emp/_refresh")


# ------------------------------------------------------------------ parsing

def test_parse_basic_select():
    q = parse_sql("SELECT name, salary FROM emp WHERE dept = 'eng' "
                  "ORDER BY salary DESC LIMIT 5")
    assert [it.name for it in q.select] == ["name", "salary"]
    assert q.table == "emp"
    assert q.limit == 5
    assert q.order_by[0][1] == "desc"


def test_where_translation():
    q = parse_sql("SELECT * FROM t WHERE a = 1 AND b > 2 OR NOT c = 'x'")
    dsl = where_to_dsl(q.where)
    assert "bool" in dsl


def test_translate_group_by_to_composite():
    q = parse_sql("SELECT dept, AVG(salary) FROM emp GROUP BY dept")
    body = translate(q)
    assert body["size"] == 0
    assert "composite" in body["aggs"]["groupby"]


# ---------------------------------------------------------------- execution

def test_sql_filter_query(client):
    _seed_emp(client)
    st, body = client.req("POST", "/_sql", {
        "query": "SELECT name, salary FROM emp WHERE dept = 'eng' "
                 "ORDER BY salary DESC"})
    assert st == 200
    assert [c["name"] for c in body["columns"]] == ["name", "salary"]
    assert body["rows"] == [["bob", 120], ["alice", 100]]


def test_sql_like_and_between(client):
    _seed_emp(client)
    st, body = client.req("POST", "/_sql", {
        "query": "SELECT name FROM emp WHERE name LIKE 'a%'"})
    assert body["rows"] == [["alice"]]
    st, body = client.req("POST", "/_sql", {
        "query": "SELECT name FROM emp WHERE salary BETWEEN 90 AND 100 "
                 "ORDER BY name ASC"})
    assert [r[0] for r in body["rows"]] == ["alice", "carol", "dan"]


def test_sql_select_star_columns_typed(client):
    _seed_emp(client)
    st, body = client.req("POST", "/_sql",
                          {"query": "SELECT * FROM emp LIMIT 1"})
    names = [c["name"] for c in body["columns"]]
    assert "salary" in names and "name" in names
    types = {c["name"]: c["type"] for c in body["columns"]}
    assert types["salary"] == "long"


def test_sql_group_by_having(client):
    _seed_emp(client)
    st, body = client.req("POST", "/_sql", {
        "query": "SELECT dept, AVG(salary) AS avg_sal, COUNT(*) AS n FROM emp "
                 "GROUP BY dept HAVING avg_sal > 85 ORDER BY avg_sal DESC"})
    assert st == 200
    assert [c["name"] for c in body["columns"]] == ["dept", "avg_sal", "n"]
    assert body["rows"][0][0] == "eng"
    assert body["rows"][0][1] == 110.0
    depts = [r[0] for r in body["rows"]]
    assert "hr" not in depts   # avg 80 filtered by HAVING


def test_sql_global_aggs(client):
    _seed_emp(client)
    st, body = client.req("POST", "/_sql", {
        "query": "SELECT COUNT(*), MAX(salary), MIN(age) FROM emp"})
    assert body["rows"] == [[5, 120.0, 28.0]]


def test_sql_cursor_pagination(client):
    _seed_emp(client)
    st, body = client.req("POST", "/_sql", {
        "query": "SELECT name FROM emp ORDER BY name ASC", "fetch_size": 2})
    assert len(body["rows"]) == 2 and "cursor" in body
    seen = [r[0] for r in body["rows"]]
    while "cursor" in body:
        st, body = client.req("POST", "/_sql", {"cursor": body["cursor"]})
        seen.extend(r[0] for r in body["rows"])
    assert seen == ["alice", "bob", "carol", "dan", "erin"]


def test_sql_translate_endpoint(client):
    _seed_emp(client)
    st, body = client.req("POST", "/_sql/translate", {
        "query": "SELECT name FROM emp WHERE salary >= 100"})
    assert body["query"] == {"range": {"salary": {"gte": 100}}}


def test_sql_txt_format(client):
    _seed_emp(client)
    st, body = client.req("POST", "/_sql",
                          {"query": "SELECT name FROM emp WHERE dept = 'hr'"},
                          format="txt")
    assert "name" in body and "erin" in body


def test_sql_distinct(client):
    _seed_emp(client)
    st, body = client.req("POST", "/_sql", {
        "query": "SELECT DISTINCT dept FROM emp ORDER BY dept ASC"})
    assert [r[0] for r in body["rows"]] == ["eng", "hr", "sales"]


# --------------------------------------------------------------------- EQL

def _seed_events(client):
    events = [
        (1, "process", "cmd.exe", "host1"),
        (2, "process", "powershell.exe", "host2"),
        (3, "network", "cmd.exe", "host1"),
        (4, "file", "cmd.exe", "host1"),
        (5, "network", "powershell.exe", "host2"),
        (6, "process", "bash", "host3"),
    ]
    for ts, cat, proc, host in events:
        client.req("POST", "/logs/_doc", {
            "@timestamp": ts * 1000,
            "event": {"category": cat},
            "process": {"name": proc},
            "host": {"name": host}})
    client.req("POST", "/logs/_refresh")


def test_eql_event_query(client):
    _seed_events(client)
    st, body = client.req("POST", "/logs/_eql/search", {
        "query": 'process where process.name == "cmd.exe"'})
    assert st == 200
    events = body["hits"]["events"]
    assert len(events) == 1
    assert events[0]["_source"]["process"]["name"] == "cmd.exe"


def test_eql_any_with_wildcard(client):
    _seed_events(client)
    st, body = client.req("POST", "/logs/_eql/search", {
        "query": 'any where wildcard(process.name, "*.exe")'})
    assert len(body["hits"]["events"]) == 5


def test_eql_sequence_by_host(client):
    _seed_events(client)
    st, body = client.req("POST", "/logs/_eql/search", {
        "query": 'sequence by host.name '
                 '[process where true] [network where true]'})
    assert st == 200
    seqs = body["hits"]["sequences"]
    assert len(seqs) == 2
    joins = sorted(s["join_keys"][0] for s in seqs)
    assert joins == ["host1", "host2"]
    for s in seqs:
        cats = [e["_source"]["event"]["category"] for e in s["events"]]
        assert cats == ["process", "network"]


def test_eql_sequence_maxspan_excludes(client):
    _seed_events(client)
    # host2: process at t=2, network at t=5 → span 3s, excluded by maxspan=2s
    st, body = client.req("POST", "/logs/_eql/search", {
        "query": 'sequence by host.name with maxspan=2s '
                 '[process where true] [network where true]'})
    seqs = body["hits"]["sequences"]
    assert len(seqs) == 1
    assert seqs[0]["join_keys"] == ["host1"]


def test_sql_jdbc_lite_wire(tmp_path):
    """The JDBC-lite wire: binary CBOR /_sql request AND response bodies
    over a real HTTP socket with cursor paging — the same wire shape the
    reference's JDBC driver speaks (JdbcHttpClient -> RestSqlQueryAction
    with binary content type), plus sql-cli's text rendering."""
    import asyncio
    import threading
    import urllib.request

    from elasticsearch_tpu.common import xcontent
    from elasticsearch_tpu.node import Node
    from elasticsearch_tpu.rest.actions import register_all
    from elasticsearch_tpu.rest.controller import RestController
    from elasticsearch_tpu.rest.http_server import HttpServer
    from elasticsearch_tpu.sql_cli import SqlWireClient, _text_table

    node = Node(str(tmp_path / "data"))
    for i in range(25):
        node.index_doc("emp", str(i), {"name": f"e{i:02d}", "salary": i})
    node.indices.get("emp").refresh()
    rc = RestController()
    register_all(rc, node)
    server = HttpServer(rc, host="127.0.0.1", port=0,
                        thread_pool=node.thread_pool)
    loop = asyncio.new_event_loop()
    started = threading.Event()

    def serve():
        asyncio.set_event_loop(loop)
        loop.run_until_complete(server.start())
        started.set()
        loop.run_forever()

    t = threading.Thread(target=serve, daemon=True)
    t.start()
    assert started.wait(15)
    try:
        base = f"http://127.0.0.1:{server.port}"
        client = SqlWireClient(base)
        rs = client.query(
            "SELECT name, salary FROM emp ORDER BY salary", fetch_size=10)
        assert [c["name"] for c in rs.columns] == ["name", "salary"]
        rows = list(rs)
        assert len(rows) == 25                      # 3 cursor pages
        assert rows[0][0] == "e00" and rows[-1][1] == 24

        # the raw wire really is binary CBOR both ways: no JSON braces
        raw_req = xcontent.dumps(
            {"query": "SELECT COUNT(*) FROM emp"}, xcontent.XContentType.CBOR)
        assert not raw_req.lstrip().startswith(b"{")
        http = urllib.request.Request(
            base + "/_sql", data=raw_req, method="POST",
            headers={"Content-Type": "application/cbor",
                     "Accept": "application/cbor"})
        with urllib.request.urlopen(http, timeout=10) as resp:
            assert resp.headers["Content-Type"].startswith(
                "application/cbor")
            payload = resp.read()
        assert not payload.lstrip().startswith(b"{")
        decoded = xcontent.loads(payload, xcontent.XContentType.CBOR)
        assert decoded["rows"][0][0] == 25

        # early close releases the server-side cursor
        rs2 = client.query("SELECT name FROM emp", fetch_size=5)
        assert rs2._cursor
        rs2.close()
        assert rs2.closed and rs2._cursor is None

        # sql-cli table rendering
        table = _text_table(
            [{"name": "a"}, {"name": "b"}], [[1, "xy"], [None, "z"]])
        assert table.splitlines()[0].startswith("a")
        assert "xy" in table
    finally:
        loop.call_soon_threadsafe(loop.stop)
        t.join(5)
        node.close()
