"""Security: authn (basic/API key), RBAC authz, DLS/FLS, audit.

Reference behaviors: x-pack/plugin/security — SecurityRestFilter (401 on
missing creds), RBACEngine (403 on missing privilege), NativeUsersStore,
ApiKeyService, role-based document/field-level security.
"""

import base64
import json

import pytest

from elasticsearch_tpu.node import Node
from elasticsearch_tpu.rest.actions import register_all
from elasticsearch_tpu.rest.controller import RestController


class Client:
    def __init__(self, node):
        self.rc = RestController()
        register_all(self.rc, node)

    def req(self, method, path, body=None, user=None, api_key=None, **query):
        raw = b""
        if body is not None:
            raw = json.dumps(body).encode()
        headers = {}
        if user is not None:
            name, pw = user
            headers["authorization"] = "Basic " + base64.b64encode(
                f"{name}:{pw}".encode()).decode()
        if api_key is not None:
            headers["authorization"] = "ApiKey " + api_key
        return self.rc.dispatch(method, path, {k: str(v) for k, v in query.items()},
                                raw, "application/json", headers)


ELASTIC = ("elastic", "changeme")


@pytest.fixture
def node(tmp_path):
    n = Node(str(tmp_path / "data"),
             settings={"xpack.security.enabled": True})
    yield n
    n.close()


@pytest.fixture
def client(node):
    return Client(node)


def _seed(client):
    for i, doc in enumerate([
            {"dept": "eng", "name": "alpha", "salary": 100},
            {"dept": "eng", "name": "beta", "salary": 120},
            {"dept": "hr", "name": "gamma", "salary": 90}]):
        st, _ = client.req("PUT", f"/staff/_doc/{i}", doc, user=ELASTIC)
        assert st in (200, 201)
    client.req("POST", "/staff/_refresh", user=ELASTIC)


# ------------------------------------------------------------ authentication

def test_missing_credentials_401(client):
    st, body = client.req("GET", "/_cluster/health")
    assert st == 401
    assert body["error"]["type"] == "security_exception"


def test_basic_auth_elastic_superuser(client):
    st, body = client.req("GET", "/_cluster/health", user=ELASTIC)
    assert st == 200
    st, body = client.req("GET", "/_security/_authenticate", user=ELASTIC)
    assert body["username"] == "elastic"
    assert "superuser" in body["roles"]


def test_wrong_password_401(client):
    st, _ = client.req("GET", "/_cluster/health", user=("elastic", "nope"))
    assert st == 401


# ------------------------------------------------------------------- users

def test_user_crud_and_login(client):
    st, body = client.req("PUT", "/_security/user/alice",
                          {"password": "s3cret1", "roles": ["viewer"]},
                          user=ELASTIC)
    assert st == 200 and body["created"]
    st, body = client.req("GET", "/_security/_authenticate",
                          user=("alice", "s3cret1"))
    assert st == 200 and body["username"] == "alice"
    # viewer can read but not write
    _seed(client)
    st, _ = client.req("POST", "/staff/_search", {"query": {"match_all": {}}},
                       user=("alice", "s3cret1"))
    assert st == 200
    st, body = client.req("PUT", "/staff/_doc/99", {"x": 1},
                          user=("alice", "s3cret1"))
    assert st == 403
    # disable then fail login
    client.req("PUT", "/_security/user/alice/_disable", user=ELASTIC)
    st, _ = client.req("GET", "/_security/_authenticate",
                       user=("alice", "s3cret1"))
    assert st == 401


def test_change_password(client):
    client.req("PUT", "/_security/user/bob",
               {"password": "first1", "roles": ["editor"]}, user=ELASTIC)
    client.req("POST", "/_security/user/bob/_password",
               {"password": "second2"}, user=ELASTIC)
    st, _ = client.req("GET", "/_security/_authenticate", user=("bob", "first1"))
    assert st == 401
    st, _ = client.req("GET", "/_security/_authenticate", user=("bob", "second2"))
    assert st == 200


# ------------------------------------------------------------------- roles

def test_custom_role_index_scoping(client):
    _seed(client)
    client.req("PUT", "/_security/role/staff-reader", {
        "cluster": [],
        "indices": [{"names": ["staff*"], "privileges": ["read"]}]},
        user=ELASTIC)
    client.req("PUT", "/_security/user/carol",
               {"password": "pw12345", "roles": ["staff-reader"]}, user=ELASTIC)
    carol = ("carol", "pw12345")
    st, _ = client.req("POST", "/staff/_search", {"query": {"match_all": {}}},
                       user=carol)
    assert st == 200
    # other index denied
    client.req("PUT", "/secret/_doc/1", {"x": 1}, user=ELASTIC)
    st, _ = client.req("POST", "/secret/_search", {"query": {"match_all": {}}},
                       user=carol)
    assert st == 403
    # cluster APIs denied
    st, _ = client.req("GET", "/_cluster/health", user=carol)
    assert st == 403


# ----------------------------------------------------------------- API keys

def test_api_key_roundtrip(client):
    st, created = client.req("POST", "/_security/api_key",
                             {"name": "ci-key"}, user=ELASTIC)
    assert st == 200 and created["api_key"]
    st, body = client.req("GET", "/_security/_authenticate",
                          api_key=created["encoded"])
    assert st == 200
    assert body["authentication_type"] == "api_key"
    # invalidate → 401
    client.req("DELETE", "/_security/api_key", {"ids": [created["id"]]},
               user=ELASTIC)
    st, _ = client.req("GET", "/_cluster/health", api_key=created["encoded"])
    assert st == 401


def test_api_key_restricted_role_descriptors(client):
    _seed(client)
    st, created = client.req("POST", "/_security/api_key", {
        "name": "limited",
        "role_descriptors": {
            "ro": {"cluster": [],
                   "indices": [{"names": ["staff"], "privileges": ["read"]}]}}},
        user=ELASTIC)
    key = created["encoded"]
    st, _ = client.req("POST", "/staff/_search", {"query": {"match_all": {}}},
                       api_key=key)
    assert st == 200
    st, _ = client.req("PUT", "/staff/_doc/50", {"x": 1}, api_key=key)
    assert st == 403


# ------------------------------------------------------------------ DLS/FLS

def test_document_level_security(client):
    _seed(client)
    client.req("PUT", "/_security/role/eng-only", {
        "indices": [{"names": ["staff"], "privileges": ["read"],
                     "query": {"term": {"dept": "eng"}}}]}, user=ELASTIC)
    client.req("PUT", "/_security/user/dave",
               {"password": "pw12345", "roles": ["eng-only"]}, user=ELASTIC)
    st, body = client.req("POST", "/staff/_search",
                          {"query": {"match_all": {}}},
                          user=("dave", "pw12345"))
    assert st == 200
    assert body["hits"]["total"]["value"] == 2
    depts = {h["_source"]["dept"] for h in body["hits"]["hits"]}
    assert depts == {"eng"}


def test_field_level_security(client):
    _seed(client)
    client.req("PUT", "/_security/role/no-salary", {
        "indices": [{"names": ["staff"], "privileges": ["read"],
                     "field_security": {"grant": ["dept", "name"]}}]},
        user=ELASTIC)
    client.req("PUT", "/_security/user/erin",
               {"password": "pw12345", "roles": ["no-salary"]}, user=ELASTIC)
    st, body = client.req("POST", "/staff/_search",
                          {"query": {"match_all": {}}},
                          user=("erin", "pw12345"))
    assert st == 200
    for h in body["hits"]["hits"]:
        assert "salary" not in h["_source"]
        assert "name" in h["_source"]


# -------------------------------------------------------------------- audit

def test_audit_trail_records_denials(client, node):
    client.req("GET", "/_cluster/health")  # anonymous → denied
    events = [e["event"] for e in node.security.audit]
    assert "anonymous_access_denied" in events


def test_security_disabled_passthrough(tmp_path):
    n = Node(str(tmp_path / "data2"))
    c = Client(n)
    st, _ = c.req("GET", "/_cluster/health")
    assert st == 200
    n.close()


class TestRealmChain:
    """File realm + ordered realm chain (InternalRealms analog)."""

    def _node_with_file_realm(self, tmp_path):
        import jax

        try:
            jax.config.update("jax_platforms", "cpu")
        except RuntimeError:
            pass
        import os

        from elasticsearch_tpu.node import Node

        cfg = tmp_path / "config"
        cfg.mkdir()
        (cfg / "users").write_text("filer:secret123\nshared:filepw\n")
        (cfg / "users_roles").write_text("superuser:filer\nwatcher:shared\n")
        node = Node(str(tmp_path), settings={"xpack.security.enabled": True})
        return node

    def test_file_realm_authenticates(self, tmp_path):
        import base64

        node = self._node_with_file_realm(tmp_path)
        hdr = {"authorization": "Basic "
               + base64.b64encode(b"filer:secret123").decode()}
        auth = node.security.authenticate(hdr)
        assert auth.username == "filer"
        assert "superuser" in auth.role_names
        node.close()

    def test_chain_falls_through_to_native(self, tmp_path):
        import base64

        node = self._node_with_file_realm(tmp_path)
        # the reserved native user still authenticates (file realm misses,
        # chain continues)
        hdr = {"authorization": "Basic "
               + base64.b64encode(b"elastic:changeme").decode()}
        auth = node.security.authenticate(hdr)
        assert auth.username == "elastic"
        node.close()

    def test_wrong_password_tries_next_realm(self, tmp_path):
        import base64

        import pytest as _pytest

        from elasticsearch_tpu.security.service import AuthenticationError

        node = self._node_with_file_realm(tmp_path)
        # file user with a wrong password: no realm authenticates
        hdr = {"authorization": "Basic "
               + base64.b64encode(b"filer:wrong").decode()}
        with _pytest.raises(AuthenticationError):
            node.security.authenticate(hdr)
        node.close()

    def test_anonymous_roles(self, tmp_path):
        import jax

        try:
            jax.config.update("jax_platforms", "cpu")
        except RuntimeError:
            pass
        from elasticsearch_tpu.node import Node

        node = Node(str(tmp_path), settings={
            "xpack.security.enabled": True,
            "xpack.security.authc.anonymous.roles": "viewer"})
        auth = node.security.authenticate({})
        assert auth.username == "_anonymous_"
        assert auth.auth_type == "anonymous"
        node.close()


class TestLicenseGating:
    """License tiers gate platinum features (XPackLicenseState analog)."""

    def _node(self, tmp_path, license_type="basic"):
        import jax

        try:
            jax.config.update("jax_platforms", "cpu")
        except RuntimeError:
            pass
        from elasticsearch_tpu.node import Node

        return Node(str(tmp_path), settings={
            "xpack.license.self_generated.type": license_type})

    def test_basic_license_refuses_ml(self, tmp_path):
        import pytest as _pytest

        from elasticsearch_tpu.common.errors import SearchEngineError

        node = self._node(tmp_path, "basic")
        assert node.license.license["type"] == "basic"
        with _pytest.raises(SearchEngineError, match="non-compliant"):
            node.license.gate("ml")
        node.close()

    def test_trial_allows_ml_and_expires_to_basic_gate(self, tmp_path):
        node = self._node(tmp_path, "trial")
        node.license.gate("ml")  # no raise
        assert node.license.allows("ccr")
        node.close()

    def test_start_trial_upgrades_basic(self, tmp_path):
        node = self._node(tmp_path, "basic")
        out = node.license.start_trial(acknowledge=True)
        assert out["trial_was_started"]
        node.license.gate("ml")  # now allowed
        # a second trial is refused
        again = node.license.start_trial(acknowledge=True)
        assert not again["trial_was_started"]
        node.close()

    def test_rest_license_roundtrip(self, tmp_path):
        node = self._node(tmp_path, "basic")
        from elasticsearch_tpu.rest.actions import register_all
        from elasticsearch_tpu.rest.controller import RestController

        rc = RestController()
        register_all(rc, node)
        status, body = rc.dispatch("GET", "/_license", {}, b"")[:2]
        assert body["license"]["type"] == "basic"
        node.close()


# --------------------------------------------------------- token service

class TestTokenService:
    def _client(self, tmp_path):
        n = Node(str(tmp_path / "data"),
                 settings={"xpack.security.enabled": True})
        return Client(n), n

    def req_bearer(self, client, method, path, token, body=None):
        raw = json.dumps(body).encode() if body is not None else b""
        return client.rc.dispatch(
            method, path, {}, raw, "application/json",
            {"authorization": f"Bearer {token}"})

    def test_grant_use_refresh_invalidate(self, tmp_path):
        """Full lifecycle (TokenService.java): password grant -> Bearer
        auth -> single-use refresh rotation -> invalidation."""
        client, node = self._client(tmp_path)
        st, tok = client.req("POST", "/_security/oauth2/token",
                             {"grant_type": "password",
                              "username": "elastic",
                              "password": "changeme"}, user=ELASTIC)
        assert st == 200
        assert tok["type"] == "Bearer" and tok["expires_in"] == 1200
        access, refresh = tok["access_token"], tok["refresh_token"]

        # the access token authenticates REST requests
        st, who = self.req_bearer(client, "GET",
                                  "/_security/_authenticate", access)
        assert st == 200 and who["username"] == "elastic"
        assert who["authentication_type"] == "token"

        # refresh rotates: new pair works, old refresh is single-use
        st, tok2 = client.req("POST", "/_security/oauth2/token",
                              {"grant_type": "refresh_token",
                               "refresh_token": refresh}, user=ELASTIC)
        assert st == 200 and tok2["access_token"] != access
        st, _ = self.req_bearer(client, "GET",
                                "/_security/_authenticate",
                                tok2["access_token"])
        assert st == 200
        # the rotated-out access token no longer authenticates
        st, _ = self.req_bearer(client, "GET",
                                "/_security/_authenticate", access)
        assert st == 401
        # reusing the OLD refresh token is an attack signal: 400 AND the
        # whole user chain dies
        st, _ = client.req("POST", "/_security/oauth2/token",
                           {"grant_type": "refresh_token",
                            "refresh_token": refresh}, user=ELASTIC)
        assert st == 400
        st, _ = self.req_bearer(client, "GET",
                                "/_security/_authenticate",
                                tok2["access_token"])
        assert st == 401
        node.close()

    def test_invalidate_by_token_and_user(self, tmp_path):
        client, node = self._client(tmp_path)
        st, tok = client.req("POST", "/_security/oauth2/token",
                             {"grant_type": "password",
                              "username": "elastic",
                              "password": "changeme"}, user=ELASTIC)
        st, out = client.req("DELETE", "/_security/oauth2/token",
                             {"token": tok["access_token"]}, user=ELASTIC)
        assert st == 200 and out["invalidated_tokens"] == 1
        st, _ = self.req_bearer(client, "GET",
                                "/_security/_authenticate",
                                tok["access_token"])
        assert st == 401
        # repeat invalidation counts as previously-invalidated
        st, out = client.req("DELETE", "/_security/oauth2/token",
                             {"token": tok["access_token"]}, user=ELASTIC)
        assert out["previously_invalidated_tokens"] == 1
        node.close()

    def test_expired_access_token_rejected(self, tmp_path):
        client, node = self._client(tmp_path)
        st, tok = client.req("POST", "/_security/oauth2/token",
                             {"grant_type": "password",
                              "username": "elastic",
                              "password": "changeme"}, user=ELASTIC)
        tid = tok["access_token"].partition(".")[0]
        node.security.store.tokens[tid]["access_expires"] -= 10_000
        st, _ = self.req_bearer(client, "GET",
                                "/_security/_authenticate",
                                tok["access_token"])
        assert st == 401
        node.close()

    def test_store_leak_is_not_credential_leak(self, tmp_path):
        """Presenting the STORED hash as a bearer secret must fail (the
        pass-the-hash property applied to tokens)."""
        client, node = self._client(tmp_path)
        st, tok = client.req("POST", "/_security/oauth2/token",
                             {"grant_type": "password",
                              "username": "elastic",
                              "password": "changeme"}, user=ELASTIC)
        tid = tok["access_token"].partition(".")[0]
        stored_hash = node.security.store.tokens[tid]["access_hash"]
        st, _ = self.req_bearer(client, "GET",
                                "/_security/_authenticate",
                                f"{tid}.{stored_hash}")
        assert st == 401
        node.close()

    def test_client_credentials_grant(self, tmp_path):
        client, node = self._client(tmp_path)
        st, tok = client.req("POST", "/_security/oauth2/token",
                             {"grant_type": "client_credentials"},
                             user=ELASTIC)
        assert st == 200
        assert "refresh_token" not in tok  # per the reference contract
        st, who = self.req_bearer(client, "GET",
                                  "/_security/_authenticate",
                                  tok["access_token"])
        assert st == 200 and who["username"] == "elastic"
        node.close()


# --------------------------------------------------------- kerberos realm

class TestKerberosRealm:
    def test_negotiate_chain_with_stub_validator(self, tmp_path):
        """Kerberos slot in the realm chain: a Negotiate header validates
        through the realm's (test-injected) ticket validator; the
        principal's roles resolve via delegated lookup in the other
        realms (authorization_realms analog)."""
        from elasticsearch_tpu.security.realms import KerberosRealm

        cfg = tmp_path / "data" / "config"
        cfg.mkdir(parents=True)
        (cfg / "users").write_text("alice:unused-pw\n")
        (cfg / "users_roles").write_text("superuser:alice\n")
        node = Node(str(tmp_path / "data"), settings={
            "xpack.security.enabled": True,
            "xpack.security.authc.realms.kerberos.krb1.order": 0})
        krb = [r for r in node.security.realms
               if r.type_name == "kerberos"]
        assert krb, "kerberos realm missing from the chain"
        assert krb[0].name == "krb1"
        # no validator configured: the realm never authenticates
        hdr = {"authorization": "Negotiate "
               + base64.b64encode(b"TICKET alice@EXAMPLE.COM").decode()}
        from elasticsearch_tpu.security.service import AuthenticationError
        with pytest.raises(AuthenticationError):
            node.security.authenticate(hdr)

        # inject the test validator (deployments plug real GSS here)
        def validator(ticket: bytes):
            if ticket.startswith(b"TICKET "):
                return ticket[len(b"TICKET "):].decode()
            return None

        krb[0].ticket_validator = validator
        auth = node.security.authenticate(hdr)
        assert auth.username == "alice"          # realm stripped
        assert auth.auth_type == "kerberos"
        assert "superuser" in auth.role_names    # via file-realm lookup
        # a garbage ticket still fails
        bad = {"authorization": "Negotiate "
               + base64.b64encode(b"NOT-A-TICKET").decode()}
        with pytest.raises(AuthenticationError):
            node.security.authenticate(bad)
        node.close()
