"""Security: authn (basic/API key), RBAC authz, DLS/FLS, audit.

Reference behaviors: x-pack/plugin/security — SecurityRestFilter (401 on
missing creds), RBACEngine (403 on missing privilege), NativeUsersStore,
ApiKeyService, role-based document/field-level security.
"""

import base64
import json

import pytest

from elasticsearch_tpu.node import Node
from elasticsearch_tpu.rest.actions import register_all
from elasticsearch_tpu.rest.controller import RestController


class Client:
    def __init__(self, node):
        self.rc = RestController()
        register_all(self.rc, node)

    def req(self, method, path, body=None, user=None, api_key=None, **query):
        raw = b""
        if body is not None:
            raw = json.dumps(body).encode()
        headers = {}
        if user is not None:
            name, pw = user
            headers["authorization"] = "Basic " + base64.b64encode(
                f"{name}:{pw}".encode()).decode()
        if api_key is not None:
            headers["authorization"] = "ApiKey " + api_key
        return self.rc.dispatch(method, path, {k: str(v) for k, v in query.items()},
                                raw, "application/json", headers)


ELASTIC = ("elastic", "changeme")


@pytest.fixture
def node(tmp_path):
    n = Node(str(tmp_path / "data"),
             settings={"xpack.security.enabled": True})
    yield n
    n.close()


@pytest.fixture
def client(node):
    return Client(node)


def _seed(client):
    for i, doc in enumerate([
            {"dept": "eng", "name": "alpha", "salary": 100},
            {"dept": "eng", "name": "beta", "salary": 120},
            {"dept": "hr", "name": "gamma", "salary": 90}]):
        st, _ = client.req("PUT", f"/staff/_doc/{i}", doc, user=ELASTIC)
        assert st in (200, 201)
    client.req("POST", "/staff/_refresh", user=ELASTIC)


# ------------------------------------------------------------ authentication

def test_missing_credentials_401(client):
    st, body = client.req("GET", "/_cluster/health")
    assert st == 401
    assert body["error"]["type"] == "security_exception"


def test_basic_auth_elastic_superuser(client):
    st, body = client.req("GET", "/_cluster/health", user=ELASTIC)
    assert st == 200
    st, body = client.req("GET", "/_security/_authenticate", user=ELASTIC)
    assert body["username"] == "elastic"
    assert "superuser" in body["roles"]


def test_wrong_password_401(client):
    st, _ = client.req("GET", "/_cluster/health", user=("elastic", "nope"))
    assert st == 401


# ------------------------------------------------------------------- users

def test_user_crud_and_login(client):
    st, body = client.req("PUT", "/_security/user/alice",
                          {"password": "s3cret1", "roles": ["viewer"]},
                          user=ELASTIC)
    assert st == 200 and body["created"]
    st, body = client.req("GET", "/_security/_authenticate",
                          user=("alice", "s3cret1"))
    assert st == 200 and body["username"] == "alice"
    # viewer can read but not write
    _seed(client)
    st, _ = client.req("POST", "/staff/_search", {"query": {"match_all": {}}},
                       user=("alice", "s3cret1"))
    assert st == 200
    st, body = client.req("PUT", "/staff/_doc/99", {"x": 1},
                          user=("alice", "s3cret1"))
    assert st == 403
    # disable then fail login
    client.req("PUT", "/_security/user/alice/_disable", user=ELASTIC)
    st, _ = client.req("GET", "/_security/_authenticate",
                       user=("alice", "s3cret1"))
    assert st == 401


def test_change_password(client):
    client.req("PUT", "/_security/user/bob",
               {"password": "first1", "roles": ["editor"]}, user=ELASTIC)
    client.req("POST", "/_security/user/bob/_password",
               {"password": "second2"}, user=ELASTIC)
    st, _ = client.req("GET", "/_security/_authenticate", user=("bob", "first1"))
    assert st == 401
    st, _ = client.req("GET", "/_security/_authenticate", user=("bob", "second2"))
    assert st == 200


# ------------------------------------------------------------------- roles

def test_custom_role_index_scoping(client):
    _seed(client)
    client.req("PUT", "/_security/role/staff-reader", {
        "cluster": [],
        "indices": [{"names": ["staff*"], "privileges": ["read"]}]},
        user=ELASTIC)
    client.req("PUT", "/_security/user/carol",
               {"password": "pw12345", "roles": ["staff-reader"]}, user=ELASTIC)
    carol = ("carol", "pw12345")
    st, _ = client.req("POST", "/staff/_search", {"query": {"match_all": {}}},
                       user=carol)
    assert st == 200
    # other index denied
    client.req("PUT", "/secret/_doc/1", {"x": 1}, user=ELASTIC)
    st, _ = client.req("POST", "/secret/_search", {"query": {"match_all": {}}},
                       user=carol)
    assert st == 403
    # cluster APIs denied
    st, _ = client.req("GET", "/_cluster/health", user=carol)
    assert st == 403


# ----------------------------------------------------------------- API keys

def test_api_key_roundtrip(client):
    st, created = client.req("POST", "/_security/api_key",
                             {"name": "ci-key"}, user=ELASTIC)
    assert st == 200 and created["api_key"]
    st, body = client.req("GET", "/_security/_authenticate",
                          api_key=created["encoded"])
    assert st == 200
    assert body["authentication_type"] == "api_key"
    # invalidate → 401
    client.req("DELETE", "/_security/api_key", {"ids": [created["id"]]},
               user=ELASTIC)
    st, _ = client.req("GET", "/_cluster/health", api_key=created["encoded"])
    assert st == 401


def test_api_key_restricted_role_descriptors(client):
    _seed(client)
    st, created = client.req("POST", "/_security/api_key", {
        "name": "limited",
        "role_descriptors": {
            "ro": {"cluster": [],
                   "indices": [{"names": ["staff"], "privileges": ["read"]}]}}},
        user=ELASTIC)
    key = created["encoded"]
    st, _ = client.req("POST", "/staff/_search", {"query": {"match_all": {}}},
                       api_key=key)
    assert st == 200
    st, _ = client.req("PUT", "/staff/_doc/50", {"x": 1}, api_key=key)
    assert st == 403


# ------------------------------------------------------------------ DLS/FLS

def test_document_level_security(client):
    _seed(client)
    client.req("PUT", "/_security/role/eng-only", {
        "indices": [{"names": ["staff"], "privileges": ["read"],
                     "query": {"term": {"dept": "eng"}}}]}, user=ELASTIC)
    client.req("PUT", "/_security/user/dave",
               {"password": "pw12345", "roles": ["eng-only"]}, user=ELASTIC)
    st, body = client.req("POST", "/staff/_search",
                          {"query": {"match_all": {}}},
                          user=("dave", "pw12345"))
    assert st == 200
    assert body["hits"]["total"]["value"] == 2
    depts = {h["_source"]["dept"] for h in body["hits"]["hits"]}
    assert depts == {"eng"}


def test_field_level_security(client):
    _seed(client)
    client.req("PUT", "/_security/role/no-salary", {
        "indices": [{"names": ["staff"], "privileges": ["read"],
                     "field_security": {"grant": ["dept", "name"]}}]},
        user=ELASTIC)
    client.req("PUT", "/_security/user/erin",
               {"password": "pw12345", "roles": ["no-salary"]}, user=ELASTIC)
    st, body = client.req("POST", "/staff/_search",
                          {"query": {"match_all": {}}},
                          user=("erin", "pw12345"))
    assert st == 200
    for h in body["hits"]["hits"]:
        assert "salary" not in h["_source"]
        assert "name" in h["_source"]


# -------------------------------------------------------------------- audit

def test_audit_trail_records_denials(client, node):
    client.req("GET", "/_cluster/health")  # anonymous → denied
    events = [e["event"] for e in node.security.audit]
    assert "anonymous_access_denied" in events


def test_security_disabled_passthrough(tmp_path):
    n = Node(str(tmp_path / "data2"))
    c = Client(n)
    st, _ = c.req("GET", "/_cluster/health")
    assert st == 200
    n.close()


class TestRealmChain:
    """File realm + ordered realm chain (InternalRealms analog)."""

    def _node_with_file_realm(self, tmp_path):
        import jax

        try:
            jax.config.update("jax_platforms", "cpu")
        except RuntimeError:
            pass
        import os

        from elasticsearch_tpu.node import Node

        cfg = tmp_path / "config"
        cfg.mkdir()
        (cfg / "users").write_text("filer:secret123\nshared:filepw\n")
        (cfg / "users_roles").write_text("superuser:filer\nwatcher:shared\n")
        node = Node(str(tmp_path), settings={"xpack.security.enabled": True})
        return node

    def test_file_realm_authenticates(self, tmp_path):
        import base64

        node = self._node_with_file_realm(tmp_path)
        hdr = {"authorization": "Basic "
               + base64.b64encode(b"filer:secret123").decode()}
        auth = node.security.authenticate(hdr)
        assert auth.username == "filer"
        assert "superuser" in auth.role_names
        node.close()

    def test_chain_falls_through_to_native(self, tmp_path):
        import base64

        node = self._node_with_file_realm(tmp_path)
        # the reserved native user still authenticates (file realm misses,
        # chain continues)
        hdr = {"authorization": "Basic "
               + base64.b64encode(b"elastic:changeme").decode()}
        auth = node.security.authenticate(hdr)
        assert auth.username == "elastic"
        node.close()

    def test_wrong_password_tries_next_realm(self, tmp_path):
        import base64

        import pytest as _pytest

        from elasticsearch_tpu.security.service import AuthenticationError

        node = self._node_with_file_realm(tmp_path)
        # file user with a wrong password: no realm authenticates
        hdr = {"authorization": "Basic "
               + base64.b64encode(b"filer:wrong").decode()}
        with _pytest.raises(AuthenticationError):
            node.security.authenticate(hdr)
        node.close()

    def test_anonymous_roles(self, tmp_path):
        import jax

        try:
            jax.config.update("jax_platforms", "cpu")
        except RuntimeError:
            pass
        from elasticsearch_tpu.node import Node

        node = Node(str(tmp_path), settings={
            "xpack.security.enabled": True,
            "xpack.security.authc.anonymous.roles": "viewer"})
        auth = node.security.authenticate({})
        assert auth.username == "_anonymous_"
        assert auth.auth_type == "anonymous"
        node.close()


class TestLicenseGating:
    """License tiers gate platinum features (XPackLicenseState analog)."""

    def _node(self, tmp_path, license_type="basic"):
        import jax

        try:
            jax.config.update("jax_platforms", "cpu")
        except RuntimeError:
            pass
        from elasticsearch_tpu.node import Node

        return Node(str(tmp_path), settings={
            "xpack.license.self_generated.type": license_type})

    def test_basic_license_refuses_ml(self, tmp_path):
        import pytest as _pytest

        from elasticsearch_tpu.common.errors import SearchEngineError

        node = self._node(tmp_path, "basic")
        assert node.license.license["type"] == "basic"
        with _pytest.raises(SearchEngineError, match="non-compliant"):
            node.license.gate("ml")
        node.close()

    def test_trial_allows_ml_and_expires_to_basic_gate(self, tmp_path):
        node = self._node(tmp_path, "trial")
        node.license.gate("ml")  # no raise
        assert node.license.allows("ccr")
        node.close()

    def test_start_trial_upgrades_basic(self, tmp_path):
        node = self._node(tmp_path, "basic")
        out = node.license.start_trial(acknowledge=True)
        assert out["trial_was_started"]
        node.license.gate("ml")  # now allowed
        # a second trial is refused
        again = node.license.start_trial(acknowledge=True)
        assert not again["trial_was_started"]
        node.close()

    def test_rest_license_roundtrip(self, tmp_path):
        node = self._node(tmp_path, "basic")
        from elasticsearch_tpu.rest.actions import register_all
        from elasticsearch_tpu.rest.controller import RestController

        rc = RestController()
        register_all(rc, node)
        status, body = rc.dispatch("GET", "/_license", {}, b"")[:2]
        assert body["license"]["type"] == "basic"
        node.close()
