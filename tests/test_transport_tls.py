"""Transport TLS + inter-node authentication
(libs/ssl-config + SecurityServerTransportInterceptor.java:50 analogs)."""

import asyncio

import pytest

# cert generation needs the optional `cryptography` package; without it
# these tests SKIP (the TLS code itself imports it lazily, so the rest
# of the transport suite is unaffected)
pytest.importorskip("cryptography")

from elasticsearch_tpu.transport import TcpTransportService
from elasticsearch_tpu.transport.tls import (
    TlsConfig, TlsConfigError, TransportAuth, TransportAuthError, current_auth,
    generate_ca, generate_node_cert,
)


@pytest.fixture(scope="module")
def certs(tmp_path_factory):
    out = str(tmp_path_factory.mktemp("certs"))
    ca = generate_ca(out)
    node = generate_node_cert(out, ca["cert"], ca["key"], name="node",
                              hosts=["127.0.0.1", "localhost"])
    rogue_ca = generate_ca(out + "/rogue")
    rogue = generate_node_cert(out + "/rogue", rogue_ca["cert"],
                               rogue_ca["key"], name="rogue")
    return {"ca": ca, "node": node, "rogue_ca": rogue_ca, "rogue": rogue}


def run(coro):
    loop = asyncio.new_event_loop()
    try:
        return loop.run_until_complete(coro)
    finally:
        loop.close()


def tls_for(certs, mode="certificate"):
    return TlsConfig(certs["node"]["cert"], certs["node"]["key"],
                     certificate_authorities=certs["ca"]["cert"],
                     verification_mode=mode)


async def make_pair(certs, tls_a=None, tls_b=None, auth_a=None, auth_b=None):
    a = TcpTransportService("a", tls=tls_a, auth=auth_a)
    b = TcpTransportService("b", tls=tls_b, auth=auth_b)
    await a.bind()
    await b.bind()
    a.add_peer_address("b", *b.bound_address)
    b.add_peer_address("a", *a.bound_address)
    return a, b


async def wait_for(box, key, timeout=5.0):
    deadline = asyncio.get_event_loop().time() + timeout
    while key not in box:
        if asyncio.get_event_loop().time() > deadline:
            raise AssertionError(f"no [{key}] within {timeout}s: {box}")
        await asyncio.sleep(0.005)
    return box[key]


def test_rpc_over_mutual_tls(certs):
    async def body():
        tls = tls_for(certs)
        a, b = await make_pair(certs, tls_a=tls, tls_b=tls)
        b.register("b", "echo",
                   lambda sender, req, respond: respond({"ok": req["n"]}))
        box = {}
        a.send("a", "b", "echo", {"n": 7},
               on_response=lambda r: box.update(r=r))
        assert (await wait_for(box, "r")) == {"ok": 7}
        await a.close(); await b.close()
    run(body())


def test_full_verification_checks_hostname(certs):
    async def body():
        tls = tls_for(certs, mode="full")  # cert has 127.0.0.1 + localhost SANs
        a, b = await make_pair(certs, tls_a=tls, tls_b=tls)
        b.register("b", "echo",
                   lambda sender, req, respond: respond({"ok": True}))
        box = {}
        a.send("a", "b", "echo", {},
               on_response=lambda r: box.update(r=r))
        assert (await wait_for(box, "r"))["ok"]
        await a.close(); await b.close()
    run(body())


def test_plaintext_client_rejected_by_tls_server(certs):
    async def body():
        tls = tls_for(certs)
        b = TcpTransportService("b", tls=tls)
        await b.bind()
        b.register("b", "echo",
                   lambda sender, req, respond: respond({"ok": True}))
        a = TcpTransportService("a")  # no TLS
        await a.bind()
        a.add_peer_address("b", *b.bound_address)
        box = {}
        a.send("a", "b", "echo", {}, on_failure=lambda e: box.update(e=e),
               timeout_ms=3000)
        e = await wait_for(box, "e")
        assert e is not None
        await a.close(); await b.close()
    run(body())


def test_untrusted_cert_rejected(certs):
    async def body():
        good = tls_for(certs)
        rogue = TlsConfig(certs["rogue"]["cert"], certs["rogue"]["key"],
                          certificate_authorities=certs["ca"]["cert"],
                          verification_mode="certificate")
        a, b = await make_pair(certs, tls_a=rogue, tls_b=good)
        b.register("b", "echo",
                   lambda sender, req, respond: respond({"ok": True}))
        box = {}
        a.send("a", "b", "echo", {}, on_failure=lambda e: box.update(e=e),
               timeout_ms=3000)
        e = await wait_for(box, "e")
        assert e is not None
        await a.close(); await b.close()
    run(body())


def test_verification_mode_validated(certs):
    with pytest.raises(TlsConfigError):
        TlsConfig(certs["node"]["cert"], certs["node"]["key"],
                  verification_mode="bogus")
    with pytest.raises(TlsConfigError):
        TlsConfig("/does/not/exist.crt", certs["node"]["key"])


def test_from_settings(certs):
    assert TlsConfig.from_settings({}) is None
    cfg = TlsConfig.from_settings({
        "transport.ssl.enabled": True,
        "transport.ssl.certificate": certs["node"]["cert"],
        "transport.ssl.key": certs["node"]["key"],
        "transport.ssl.certificate_authorities": certs["ca"]["cert"],
        "transport.ssl.verification_mode": "certificate"})
    assert cfg is not None and cfg.verification_mode == "certificate"
    with pytest.raises(TlsConfigError):
        TlsConfig.from_settings({"transport.ssl.enabled": "true"})


# ------------------------------------------------------------- transport auth

def test_auth_context_propagates_and_validates():
    async def body():
        auth = TransportAuth(b"cluster-shared-key")
        a, b = await make_pair(None, auth_a=auth, auth_b=auth)
        seen = {}

        def handler(sender, req, respond):
            seen["auth"] = current_auth.get()
            respond({"ok": True})

        b.register("b", "guarded", handler)
        box = {}
        a.send("a", "b", "guarded", {},
               on_response=lambda r: box.update(r=r))
        await wait_for(box, "r")
        assert seen["auth"]["user"] == "_system"
        assert seen["auth"]["roles"] == ["_internal"]
        await a.close(); await b.close()
    run(body())


def test_unauthenticated_peer_rejected_before_dispatch():
    async def body():
        auth = TransportAuth(b"cluster-shared-key")
        a, b = await make_pair(None, auth_a=None, auth_b=auth)  # sender unsigned
        called = {}
        b.register("b", "guarded",
                   lambda sender, req, respond: called.update(hit=True)
                   or respond({"ok": True}))
        box = {}
        a.send("a", "b", "guarded", {},
               on_failure=lambda e: box.update(e=e))
        e = await wait_for(box, "e")
        assert "security_exception" in str(e) or "authentication" in str(e)
        assert "hit" not in called, "handler ran despite failed authn"
        await a.close(); await b.close()
    run(body())


def test_wrong_key_rejected():
    async def body():
        a, b = await make_pair(None,
                               auth_a=TransportAuth(b"key-one"),
                               auth_b=TransportAuth(b"key-two"))
        b.register("b", "guarded",
                   lambda sender, req, respond: respond({"ok": True}))
        box = {}
        a.send("a", "b", "guarded", {},
               on_failure=lambda e: box.update(e=e))
        e = await wait_for(box, "e")
        assert "authentication" in str(e) or "security" in str(e)
        await a.close(); await b.close()
    run(body())


def test_rest_user_context_rides_rpc():
    """A REST-authenticated end user pushed into current_auth travels with
    the RPC and is what the remote handler sees (run-as propagation)."""
    async def body():
        auth = TransportAuth(b"cluster-shared-key")
        a, b = await make_pair(None, auth_a=auth, auth_b=auth)
        seen = {}
        b.register("b", "guarded", lambda sender, req, respond:
                   seen.update(auth=current_auth.get()) or respond({}))
        box = {}
        token = current_auth.set({"user": "alice", "roles": ["admin"]})
        try:
            a.send("a", "b", "guarded", {},
                   on_response=lambda r: box.update(r=r))
        finally:
            current_auth.reset(token)
        await wait_for(box, "r")
        assert seen["auth"] == {"user": "alice", "roles": ["admin"]}
        await a.close(); await b.close()
    run(body())


def test_mac_tamper_detected():
    auth = TransportAuth(b"k")
    ctx = auth.outbound_context("a", "act")
    assert auth.validate("a", "act", dict(ctx))["user"] == "_system"
    with pytest.raises(TransportAuthError):
        auth.validate("a", "other-action", dict(ctx))  # action substitution
    bad = dict(ctx)
    bad["roles"] = ["superuser"]
    with pytest.raises(TransportAuthError):
        auth.validate("a", "act", bad)
    with pytest.raises(TransportAuthError):
        auth.validate("a", "act", None)


def test_https_rest_server(certs, tmp_path):
    """The REST port terminates TLS in-process when http.ssl.* is set
    (SecurityRestFilter / xpack.security.http.ssl analog): https with the
    CA verifies and serves; plaintext HTTP on the same port fails the
    handshake and never reaches a handler."""
    import json
    import ssl as _ssl
    import threading
    import urllib.request
    import urllib.error

    from elasticsearch_tpu.node import Node
    from elasticsearch_tpu.rest.actions import register_all
    from elasticsearch_tpu.rest.controller import RestController
    from elasticsearch_tpu.rest.http_server import HttpServer
    from elasticsearch_tpu.server import _http_ssl_context

    settings = {"http.ssl.enabled": "true",
                "http.ssl.certificate": certs["node"]["cert"],
                "http.ssl.key": certs["node"]["key"]}
    node = Node(str(tmp_path))
    rc = RestController()
    register_all(rc, node)
    server = HttpServer(rc, host="127.0.0.1", port=0,
                        ssl_context=_http_ssl_context(settings))

    loop = asyncio.new_event_loop()
    started = threading.Event()

    def serve():
        asyncio.set_event_loop(loop)
        loop.run_until_complete(server.start())
        started.set()
        loop.run_forever()

    t = threading.Thread(target=serve, daemon=True)
    t.start()
    assert started.wait(15)
    port = server.port
    try:
        client_ctx = _ssl.create_default_context(
            cafile=certs["ca"]["cert"])
        client_ctx.check_hostname = False  # cert carries 127.0.0.1 SAN,
        # but default hostname checks vary by python build
        with urllib.request.urlopen(
                f"https://127.0.0.1:{port}/_cluster/health",
                context=client_ctx, timeout=10) as resp:
            body = json.loads(resp.read())
        assert body["status"] in ("green", "yellow")

        # plaintext on the TLS port fails before any handler runs
        try:
            urllib.request.urlopen(
                f"http://127.0.0.1:{port}/_cluster/health", timeout=5)
            raise AssertionError("plaintext request must not succeed")
        except (urllib.error.URLError, ConnectionError, OSError):
            pass

        # the shipped client speaks https with CA verification
        from elasticsearch_tpu.client import TpuSearchClient
        es = TpuSearchClient([f"https://127.0.0.1:{port}"],
                             ca_certs=certs["ca"]["cert"])
        assert es.cluster.health()["status"] in ("green", "yellow")
    finally:
        asyncio.run_coroutine_threadsafe(server.stop(), loop).result(10)
        loop.call_soon_threadsafe(loop.stop)
        t.join(10)
        node.close()


def test_token_lifecycle_over_https(certs, tmp_path):
    """Token grant-use-refresh over a real TLS REST port with security
    enabled: basic auth grants, Bearer authenticates, refresh rotates
    (TokenService.java e2e)."""
    import json
    import base64
    import ssl as _ssl
    import threading
    import urllib.request

    from elasticsearch_tpu.node import Node
    from elasticsearch_tpu.rest.actions import register_all
    from elasticsearch_tpu.rest.controller import RestController
    from elasticsearch_tpu.rest.http_server import HttpServer
    from elasticsearch_tpu.server import _http_ssl_context

    settings = {"http.ssl.enabled": "true",
                "http.ssl.certificate": certs["node"]["cert"],
                "http.ssl.key": certs["node"]["key"],
                "xpack.security.enabled": True}
    node = Node(str(tmp_path), settings=settings)
    rc = RestController()
    register_all(rc, node)
    server = HttpServer(rc, host="127.0.0.1", port=0,
                        ssl_context=_http_ssl_context(settings))
    loop = asyncio.new_event_loop()
    started = threading.Event()

    def serve():
        asyncio.set_event_loop(loop)
        loop.run_until_complete(server.start())
        started.set()
        loop.run_forever()

    t = threading.Thread(target=serve, daemon=True)
    t.start()
    assert started.wait(15)
    base = f"https://127.0.0.1:{server.port}"
    ctx = _ssl.create_default_context(cafile=certs["ca"]["cert"])
    ctx.check_hostname = False

    def req(method, path, body=None, headers=None):
        data = json.dumps(body).encode() if body is not None else None
        r = urllib.request.Request(
            base + path, data=data, method=method,
            headers={"Content-Type": "application/json", **(headers or {})})
        with urllib.request.urlopen(r, context=ctx, timeout=10) as resp:
            return json.loads(resp.read())

    try:
        basic = {"authorization": "Basic " + base64.b64encode(
            b"elastic:changeme").decode()}
        tok = req("POST", "/_security/oauth2/token",
                  {"grant_type": "password", "username": "elastic",
                   "password": "changeme"}, basic)
        bearer = {"authorization": f"Bearer {tok['access_token']}"}
        who = req("GET", "/_security/_authenticate", headers=bearer)
        assert who["username"] == "elastic"
        assert who["authentication_type"] == "token"

        tok2 = req("POST", "/_security/oauth2/token",
                   {"grant_type": "refresh_token",
                    "refresh_token": tok["refresh_token"]}, basic)
        who2 = req("GET", "/_security/_authenticate", headers={
            "authorization": f"Bearer {tok2['access_token']}"})
        assert who2["username"] == "elastic"
        # rotated-out access token now 401s
        import urllib.error
        try:
            req("GET", "/_security/_authenticate", headers=bearer)
            raise AssertionError("rotated token must not authenticate")
        except urllib.error.HTTPError as e:
            assert e.code == 401
    finally:
        loop.call_soon_threadsafe(loop.stop)
        t.join(5)
        node.close()
