"""C++ hot-loop kernels vs numpy reference implementations."""

import numpy as np
import pytest

from elasticsearch_tpu import native


@pytest.fixture(scope="module", autouse=True)
def built():
    assert native._load() is not None, "native library failed to build"
    assert native.AVAILABLE


def ref_bm25(freqs, lengths, idf, avg_len, k1, b, boost):
    f = freqs.astype(np.float64)
    tf = f / (f + k1 * (1.0 - b + b * lengths.astype(np.float64) / avg_len))
    return boost * idf * (k1 + 1.0) * tf


def test_bm25_matches_reference_formula():
    rng = np.random.default_rng(7)
    freqs = rng.integers(1, 50, 1000).astype(np.int32)
    lengths = rng.integers(1, 500, 1000).astype(np.float32)
    got = native.bm25_score(freqs, lengths, idf=2.37, avg_len=120.5,
                            k1=1.2, b=0.75, boost=1.3)
    want = ref_bm25(freqs, lengths, 2.37, 120.5, 1.2, 0.75, 1.3)
    np.testing.assert_allclose(got, want, rtol=1e-5)


def test_intersect_matches_numpy():
    rng = np.random.default_rng(11)
    for na, nb in [(0, 10), (10, 0), (1, 1), (100, 10000), (5000, 5000)]:
        a = np.unique(rng.integers(0, 20000, na)).astype(np.int64)
        b = np.unique(rng.integers(0, 20000, nb)).astype(np.int64)
        ia, ib = native.intersect_sorted(a, b)
        _, ria, rib = np.intersect1d(a, b, assume_unique=True,
                                     return_indices=True)
        np.testing.assert_array_equal(ia, ria)
        np.testing.assert_array_equal(ib, rib)
        if len(ia):
            np.testing.assert_array_equal(a[ia], b[ib])


def test_union_sum_matches_reference():
    rng = np.random.default_rng(13)
    a = np.unique(rng.integers(0, 500, 200)).astype(np.int64)
    b = np.unique(rng.integers(0, 500, 300)).astype(np.int64)
    sa = rng.random(len(a)).astype(np.float32)
    sb = rng.random(len(b)).astype(np.float32)
    rows, scores = native.union_sum(a, sa, b, sb)
    want_rows = np.union1d(a, b)
    want = np.zeros(len(want_rows), dtype=np.float64)
    want[np.searchsorted(want_rows, a)] += sa
    want[np.searchsorted(want_rows, b)] += sb
    np.testing.assert_array_equal(rows, want_rows)
    np.testing.assert_allclose(scores, want, rtol=1e-6)


def test_union_sum_null_scores():
    a = np.array([1, 3, 5], dtype=np.int64)
    b = np.array([3, 4], dtype=np.int64)
    rows, scores = native.union_sum(a, None, b,
                                    np.array([2.0, 7.0], dtype=np.float32))
    np.testing.assert_array_equal(rows, [1, 3, 4, 5])
    np.testing.assert_allclose(scores, [0.0, 2.0, 7.0, 0.0])


def test_topk_order_and_tiebreak():
    scores = np.array([1.0, 5.0, 5.0, 0.5, 9.0, 5.0], dtype=np.float32)
    idx = native.topk(scores, 4)
    # score desc, index asc on ties: 9.0@4, then the 5.0s at 1, 2, 5
    np.testing.assert_array_equal(idx, [4, 1, 2, 3 + 2])


def test_fallbacks_match_native(monkeypatch):
    """A host without g++ must produce byte-identical results."""
    rng = np.random.default_rng(23)
    scores = rng.integers(0, 50, 2000).astype(np.float32)  # many ties
    a = np.unique(rng.integers(0, 5000, 800)).astype(np.int64)
    b = np.unique(rng.integers(0, 5000, 1200)).astype(np.int64)
    sa = rng.random(len(a)).astype(np.float32)
    sb = rng.random(len(b)).astype(np.float32)

    n_topk = native.topk(scores, 25)
    n_int = native.intersect_sorted(a, b)
    n_union = native.union_sum(a, sa, b, sb)
    n_bm25 = native.bm25_score(np.arange(1, 100, dtype=np.int32),
                               np.full(99, 50.0, np.float32),
                               1.7, 80.0, 1.2, 0.75, 2.0)

    monkeypatch.setattr(native, "_lib", None)
    monkeypatch.setattr(native, "_load", lambda: None)

    np.testing.assert_array_equal(native.topk(scores, 25), n_topk)
    for got, want in zip(native.intersect_sorted(a, b), n_int):
        np.testing.assert_array_equal(got, want)
    rows, ssum = native.union_sum(a, sa, b, sb)
    np.testing.assert_array_equal(rows, n_union[0])
    np.testing.assert_allclose(ssum, n_union[1], rtol=1e-6)
    np.testing.assert_allclose(
        native.bm25_score(np.arange(1, 100, dtype=np.int32),
                          np.full(99, 50.0, np.float32),
                          1.7, 80.0, 1.2, 0.75, 2.0),
        n_bm25, rtol=1e-5)


def test_topk_k_exceeds_n_and_randomized():
    rng = np.random.default_rng(17)
    scores = rng.random(1000).astype(np.float32)
    for k in [0, 1, 10, 999, 1000, 5000]:
        idx = native.topk(scores, k)
        kk = min(k, len(scores))
        assert len(idx) == kk
        want = np.argsort(-scores, kind="stable")[:kk]
        np.testing.assert_array_equal(idx, want)


def test_knn_i8p_threaded_matches_single_thread(monkeypatch):
    """The row-range-parallel VNNI scan is bit-identical to the
    single-threaded scan: scores don't depend on the partition and TopK's
    (score desc, row asc) total order makes the merge deterministic."""
    import numpy as np

    from elasticsearch_tpu import native
    from elasticsearch_tpu.vectors.host_corpus import HostFieldCorpus

    if not native.AVAILABLE or not native.knn_has_vnni():
        import pytest
        pytest.skip("native VNNI kernel unavailable")

    rng = np.random.default_rng(17)
    n, d, b, k = 50_000, 96, 5, 12
    vecs = rng.standard_normal((n, d)).astype(np.float32)
    corpus = HostFieldCorpus(vecs, "cosine")
    queries = rng.standard_normal((b, d)).astype(np.float32)

    for nt in ("7", "4"):  # odd split exercises uneven tail ranges
        monkeypatch.setenv("ES_NATIVE_THREADS", "1")
        s1, r1 = corpus.search(queries, k)
        monkeypatch.setenv("ES_NATIVE_THREADS", nt)
        sn, rn = corpus.search(queries, k)
        np.testing.assert_array_equal(r1, rn)
        np.testing.assert_array_equal(s1, sn)
