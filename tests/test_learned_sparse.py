"""Learned-sparse retrieval on the BM25 impact substrate (ops/sparse.py).

`rank_features` postings land in the SAME tile-padded CSR layout the
lexical engine scores — stored weights ARE the impacts, the query's
term weights ride the per-tile boost lane — so the parity contract is
inherited verbatim: device `sparse.topk` output must be BYTE-IDENTICAL
(rows and f32 scores) to the pure-host `weighted_tokens` walker in
search/queries_ext.py, across append/delete lifecycles, and the fused
rrf leg must be json-identical to the two-phase oracle.  The grid is
closed: a query body over MAX_QUERY_TOKENS falls back to the walker as
a counted fallback LEG, never an unseen device shape.
"""

import json
import tempfile

import numpy as np
import pytest

from elasticsearch_tpu import native
from elasticsearch_tpu.index.engine import Engine
from elasticsearch_tpu.index.mapping import MapperService
from elasticsearch_tpu.ops import dispatch
from elasticsearch_tpu.ops.bm25 import TILE
from elasticsearch_tpu.ops.sparse import (MAX_QUERY_TOKENS, SparseField,
                                          SparseShard)
from elasticsearch_tpu.search.queries import SearchContext, parse_query


@pytest.fixture(scope="module")
def corpus():
    ms = MapperService({"properties": {
        "feats": {"type": "rank_features"},
        "body": {"type": "text"}}})
    eng = Engine(tempfile.mkdtemp(), ms)
    rng = np.random.default_rng(42)
    vocab = [f"tok{i}" for i in range(60)]
    for i in range(400):
        feats = {t: float(rng.uniform(0.05, 8.0))
                 for t in rng.choice(vocab, size=rng.integers(2, 9),
                                     replace=False)}
        eng.index(str(i), {"feats": feats, "body": f"doc {i}"})
    eng.refresh()
    return ms, eng, rng


def _reference(reader, ms, tokens, boost=1.0, window=100):
    """The host walker the device kernel must reproduce bit-for-bit."""
    ctx = SearchContext(reader, ms)
    q = parse_query({"sparse_vector": {"field": "feats",
                                       "query_vector": dict(tokens),
                                       "boost": boost}})
    ds = q.execute(ctx)
    idx = native.topk(ds.scores, min(window, len(ds.rows)))
    return ds.rows[idx], ds.scores[idx]


class TestParity:
    @pytest.mark.parametrize("route", ["host", "device"])
    def test_byte_identical_to_walker(self, corpus, route):
        ms, eng, _ = corpus
        reader = eng.acquire_searcher()
        sp = SparseShard()
        for toks in ({"tok1": 2.0, "tok2": 0.5},
                     {"tok5": 1.0},
                     {"tok10": 3.0, "tok11": 1.0, "tok12": 0.25,
                      "tok13": 4.0}):
            ref_rows, ref_scores = _reference(reader, ms, toks)
            (rows, scores), = sp.search_batch(
                reader, "feats", [(toks, 1.0)], 100, route=route)
            assert np.array_equal(rows, ref_rows)
            # byte-identical, not approx: same f32 weights, same tile
            # fold order as the walker's feature-major accumulation
            assert scores.tobytes() == ref_scores.tobytes()

    @pytest.mark.parametrize("route", ["host", "device"])
    def test_boost_folds_into_query_weights(self, corpus, route):
        ms, eng, _ = corpus
        reader = eng.acquire_searcher()
        sp = SparseShard()
        toks = {"tok3": 1.5, "tok7": 0.75}
        ref_rows, ref_scores = _reference(reader, ms, toks, boost=2.5)
        (rows, scores), = sp.search_batch(
            reader, "feats", [(toks, 2.5)], 100, route=route)
        assert np.array_equal(rows, ref_rows)
        assert scores.tobytes() == ref_scores.tobytes()

    def test_batch_matches_single_dispatch(self, corpus):
        ms, eng, _ = corpus
        reader = eng.acquire_searcher()
        sp = SparseShard()
        queries = [({"tok1": 1.0, "tok2": 2.0}, 1.0),
                   ({"tok9": 0.5}, 1.0),
                   ({"tok3": 1.0, "tok4": 1.0, "tok5": 1.0}, 2.0)]
        batched = sp.search_batch(reader, "feats", queries, 50,
                                  route="device")
        for q, (rows, scores) in zip(queries, batched):
            (r1, s1), = sp.search_batch(reader, "feats", [q], 50,
                                        route="device")
            assert np.array_equal(rows, r1)
            assert scores.tobytes() == s1.tobytes()

    def test_oov_feature_matches_nothing(self, corpus):
        ms, eng, _ = corpus
        reader = eng.acquire_searcher()
        sp = SparseShard()
        (rows, _), = sp.search_batch(
            reader, "feats", [({"zzz_never_indexed": 5.0}, 1.0)], 100,
            route="host")
        assert len(rows) == 0


class TestLifecycle:
    def test_append_delete_rebuild_parity(self):
        ms = MapperService({"properties": {
            "feats": {"type": "rank_features"}}})
        eng = Engine(tempfile.mkdtemp(), ms)
        for i in range(50):
            eng.index(str(i), {"feats": {"alpha": 1.0 + i % 7,
                                         f"tok{i % 5}": 2.0}})
        eng.refresh()
        sp = SparseShard()
        reader = eng.acquire_searcher()
        sp.search_batch(reader, "feats", [({"alpha": 1.0}, 1.0)], 100)
        assert sp.stats["rebuilds"] == 1
        sp.search_batch(reader, "feats", [({"alpha": 1.0}, 1.0)], 100)
        assert sp.stats["rebuilds"] == 1  # same reader: no rebuild

        for i in range(50, 80):
            eng.index(str(i), {"feats": {"alpha": 0.5, "beta": 3.0}})
        eng.refresh()
        reader2 = eng.acquire_searcher()
        ref_rows, ref_scores = _reference(reader2, ms, {"alpha": 1.0})
        (rows, scores), = sp.search_batch(reader2, "feats",
                                          [({"alpha": 1.0}, 1.0)], 100)
        assert sp.stats["rebuilds"] == 2
        assert np.array_equal(rows, ref_rows)
        assert scores.tobytes() == ref_scores.tobytes()

        eng.delete("3")
        eng.refresh()
        reader3 = eng.acquire_searcher()
        ref_rows, ref_scores = _reference(reader3, ms, {"alpha": 1.0})
        (rows, scores), = sp.search_batch(reader3, "feats",
                                          [({"alpha": 1.0}, 1.0)], 100)
        assert np.array_equal(rows, ref_rows)
        assert scores.tobytes() == ref_scores.tobytes()
        assert not any(reader3.get_id(int(r)) == "3" for r in rows)

    def test_docs_without_field_never_match(self, corpus):
        ms, eng, _ = corpus
        eng2 = Engine(tempfile.mkdtemp(), MapperService({"properties": {
            "feats": {"type": "rank_features"}}}))
        eng2.index("a", {"feats": {"x": 1.0}})
        eng2.index("b", {})                       # no field
        eng2.index("c", {"feats": {"y": 2.0}})
        eng2.refresh()
        reader = eng2.acquire_searcher()
        sp = SparseShard()
        (rows, _), = sp.search_batch(reader, "feats",
                                     [({"x": 1.0, "y": 1.0}, 1.0)], 10)
        assert {reader.get_id(int(r)) for r in rows} == {"a", "c"}


class TestLayout:
    def test_tiles_are_lane_padded_and_weights_are_impacts(self, corpus):
        ms, eng, _ = corpus
        reader = eng.acquire_searcher()
        sf = SparseField("feats")
        sf.sync(reader)
        assert sf.tile_slots.shape[1] == TILE
        pad = sf.tile_slots < 0
        assert np.all(sf.tile_impacts[pad] == 0.0)
        real = sf.tile_slots[~pad]
        assert real.min() >= 0 and real.max() < sf.n_slots
        assert np.all(np.diff(sf.row_map) > 0)
        # impacts are the STORED weights, not a BM25 recompute: spot a
        # doc's weight back through the tile layout
        first, nt = sf.term_tiles["tok1"]
        tile_w = sf.tile_impacts[first:first + nt]
        assert tile_w.max() <= 8.0 + 1e-6 and tile_w[~(
            sf.tile_slots[first:first + nt] < 0)].min() > 0.0


class TestNodePath:
    @pytest.fixture()
    def node(self):
        from elasticsearch_tpu.node import Node
        rng = np.random.default_rng(5)
        n = Node(tempfile.mkdtemp())
        n.create_index_with_templates("s", mappings={"properties": {
            "feats": {"type": "rank_features"},
            "body": {"type": "text"}}})
        vocab = [f"tok{j}" for j in range(30)]
        ops = []
        for i in range(150):
            ops.append({"index": {"_index": "s", "_id": str(i)}})
            ops.append({"feats": {t: float(rng.uniform(0.1, 4.0))
                                  for t in rng.choice(vocab, 5,
                                                      replace=False)},
                        "body": " ".join(rng.choice(list("abcd"), 4))})
        n.bulk(ops)
        n.indices.get("s").refresh()
        yield n
        n.close()

    def _compare(self, n, body):
        fused = n.search("s", dict(body))
        oracle = n.search("s", {**body, "__rrf_two_phase__": True})
        fused.pop("took")
        oracle.pop("took")
        assert json.dumps(fused, sort_keys=True) \
            == json.dumps(oracle, sort_keys=True)
        return fused

    def test_fused_rrf_leg_json_identical_to_oracle(self, node):
        toks = {"tok1": 2.0, "tok5": 1.0, "tok9": 0.5}
        self._compare(node, {"rank": {"rrf": {}}, "sub_searches": [
            {"query": {"sparse_vector": {"field": "feats",
                                         "query_vector": toks}}},
            {"query": {"match": {"body": "a b"}}}], "size": 10})
        # weighted_tokens body form binds to the same leg
        self._compare(node, {"rank": {"rrf": {}}, "sub_searches": [
            {"query": {"weighted_tokens": {"feats": {"tokens": toks}}}},
            {"query": {"match": {"body": "c"}}}], "size": 10})

    def test_over_grid_body_falls_back_and_is_counted(self, node):
        big = {f"t{j}": 1.0 for j in range(MAX_QUERY_TOKENS + 10)}
        body = {"rank": {"rrf": {}}, "sub_searches": [
            {"query": {"sparse_vector": {"field": "feats",
                                         "query_vector": big}}},
            {"query": {"match": {"body": "a"}}}], "size": 5}
        self._compare(node, body)
        ex = node._hybrid[node.indices.get("s").name]
        # fused + oracle runs bind the template twice -> 2 fallback legs
        assert ex.stats["sparse_grid_fallbacks"] >= 1

    def test_sparse_stats_surface_in_nodes_stats(self, node):
        toks = {"tok1": 1.0}
        node.search("s", {"rank": {"rrf": {}}, "sub_searches": [
            {"query": {"sparse_vector": {"field": "feats",
                                         "query_vector": toks}}},
            {"query": {"match": {"body": "a"}}}], "size": 5})
        hyb = node.local_node_stats()["indices"]["hybrid"]
        assert hyb["sparse"]["searches"] >= 1
        assert hyb["sparse"]["queries"] >= 1


def test_strict_zero_recompile_second_pass(corpus):
    ms, eng, _ = corpus
    reader = eng.acquire_searcher()
    sp = SparseShard()
    queries = [({"tok1": 1.0, "tok2": 2.0}, 1.0), ({"tok8": 1.0}, 1.0)]
    sp.search_batch(reader, "feats", queries, 100, route="device")  # warm
    before = dispatch.DISPATCH.compile_count()
    strict_before = dispatch.DISPATCH.strict
    dispatch.DISPATCH.strict = True
    try:
        got = sp.search_batch(reader, "feats", queries, 100,
                              route="device")
    finally:
        dispatch.DISPATCH.strict = strict_before
    assert got is not None
    assert dispatch.DISPATCH.compile_count() == before


@pytest.mark.multidevice
class TestMeshParity:
    def test_ragged_shard_mesh_parity(self, mesh_serving):
        """sparse.mesh_topk through the serving mesh: byte-identical to
        the single-device board on a corpus whose slot count does not
        divide the mesh (ragged last shard)."""
        ms = MapperService({"properties": {
            "feats": {"type": "rank_features"}}})
        eng = Engine(tempfile.mkdtemp(), ms)
        rng = np.random.default_rng(13)
        vocab = [f"tok{i}" for i in range(40)]
        for i in range(301):                       # odd: ragged shards
            feats = {t: float(rng.uniform(0.1, 5.0))
                     for t in rng.choice(vocab, size=rng.integers(2, 8),
                                         replace=False)}
            eng.index(str(i), {"feats": feats})
        eng.refresh()
        reader = eng.acquire_searcher()
        sp = SparseShard()
        queries = [({"tok1": 1.0, "tok2": 2.0}, 1.0),
                   ({"tok5": 0.5}, 2.0),
                   ({"tok7": 1.0, "tok8": 1.0, "tok9": 3.0}, 1.0)]
        mesh_res = sp.search_batch(reader, "feats", queries, 10,
                                   route="device")
        assert mesh_serving.stats()["router"]["mesh"] >= 1, \
            "sparse dispatch did not route to the mesh"
        mesh_serving.configure(enabled=False)
        one_res = sp.search_batch(reader, "feats", queries, 10,
                                  route="device")
        for (m_rows, m_scores), (o_rows, o_scores) in zip(mesh_res,
                                                          one_res):
            assert np.array_equal(m_rows, o_rows)
            assert m_scores.tobytes() == o_scores.tobytes()
