"""Sharded kNN on an 8-device virtual CPU mesh must equal the single-device result."""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from elasticsearch_tpu.ops import knn as knn_ops
from elasticsearch_tpu.ops import similarity as sim
from elasticsearch_tpu.parallel import mesh as mesh_lib
from elasticsearch_tpu.parallel.sharded_knn import build_sharded_corpus, distributed_knn_search

RNG = np.random.default_rng(7)


@pytest.fixture(scope="module")
def vectors():
    return RNG.standard_normal((3000, 32)).astype(np.float32)


@pytest.fixture(scope="module")
def queries():
    return RNG.standard_normal((8, 32)).astype(np.float32)


def exact_ids(queries, vectors, metric, k):
    q = queries / np.linalg.norm(queries, axis=-1, keepdims=True)
    c = vectors / np.linalg.norm(vectors, axis=-1, keepdims=True)
    scores = q @ c.T
    return np.argsort(-scores, axis=1)[:, :k], np.sort(scores, axis=1)[:, ::-1][:, :k]


@pytest.mark.parametrize("dp,shards", [(1, 8), (2, 4), (1, 4)])
def test_distributed_matches_exact(vectors, queries, dp, shards):
    assert jax.device_count() >= dp * shards, "conftest must force 8 cpu devices"
    mesh = mesh_lib.make_mesh(num_shards=shards, dp=dp)
    corpus, layout = build_sharded_corpus(vectors, mesh, metric=sim.COSINE, dtype="f32")
    scores, gids = distributed_knn_search(jnp.asarray(queries), corpus, k=10,
                                          mesh=mesh, metric=sim.COSINE, precision="f32")
    orig = layout.to_original_ids(np.asarray(gids))
    ref_ids, ref_scores = exact_ids(queries, vectors, sim.COSINE, 10)
    overlap = np.mean([
        len(set(orig[i].tolist()) & set(ref_ids[i].tolist())) / 10.0
        for i in range(queries.shape[0])
    ])
    assert overlap == 1.0
    np.testing.assert_allclose(np.asarray(scores), ref_scores, rtol=1e-4, atol=1e-4)


def test_distributed_filtered(vectors, queries):
    mesh = mesh_lib.make_mesh(num_shards=4, dp=2)
    corpus, layout = build_sharded_corpus(vectors, mesh, metric=sim.COSINE, dtype="f32")
    n_pad = corpus.matrix.shape[0]
    mask = np.zeros(n_pad, dtype=bool)
    keep = RNG.choice(vectors.shape[0], size=200, replace=False)
    mask[layout.to_global_ids(keep)] = True
    fm = jax.device_put(jnp.asarray(mask), mesh_lib.per_shard_sharding(mesh))
    scores, gids = distributed_knn_search(jnp.asarray(queries), corpus, k=10,
                                          mesh=mesh, metric=sim.COSINE,
                                          filter_mask=fm, precision="f32")
    orig = layout.to_original_ids(np.asarray(gids))
    assert set(orig.flatten().tolist()) <= set(keep.tolist())


def test_layout_headroom():
    mesh = mesh_lib.make_mesh(num_shards=4, dp=1)
    v = RNG.standard_normal((4 * 256, 8)).astype(np.float32)
    corpus, layout = build_sharded_corpus(v, mesh, min_headroom=8)
    assert layout.docs_per_shard == 256
    assert layout.rows_per_shard >= 256 + 8
    nv = np.asarray(corpus.num_valid)
    assert (nv == 256).all()


def test_serving_path_routes_through_mesh(tmp_path):
    """A multi-shard index on a multi-device host serves knn through ONE
    compiled SPMD program (distributed_knn_search), and the results match
    the host-merge fallback exactly (VERDICT r2 item 3: the mesh data
    plane in the serving path, not just tests)."""
    import numpy as np

    from elasticsearch_tpu.node import Node, _MultiShardVectorStore

    rng = np.random.default_rng(5)
    node = Node(str(tmp_path))
    node.create_index_with_templates("vec4", settings={"number_of_shards": 4},
                                     mappings={"properties": {
                                         "v": {"type": "dense_vector",
                                               "dims": 16,
                                               "similarity": "cosine"},
                                         "grp": {"type": "keyword"}}})
    n = 200
    vecs = rng.standard_normal((n, 16)).astype(np.float32)
    for i in range(n):
        node.index_doc("vec4", str(i), {"v": vecs[i].tolist(),
                                        "grp": "a" if i % 2 else "b"})
    node.indices.get("vec4").refresh()

    svc = node.indices.get("vec4")
    store = _MultiShardVectorStore(svc)
    q = rng.standard_normal(16).astype(np.float32)

    state = store._mesh_state("v")
    assert state is not None, "mesh path must engage (4 shards, 8 devices)"
    mesh_rows, mesh_scores = store._mesh_search(state, q, 10, None, "f32")

    # host-merge path recomputed for comparison
    all_rows, all_scores = [], []
    from elasticsearch_tpu.indices.service import SHARD_ROW_SPACE
    for shard in svc.shards:
        rows, scores = shard.vector_store.search("v", q, 10,
                                                 precision="f32")
        all_rows.append(rows + shard.shard_id * SHARD_ROW_SPACE)
        all_scores.append(scores)
    rows = np.concatenate(all_rows)
    scores = np.concatenate(all_scores)
    order = np.argsort(-scores, kind="stable")[:10]
    host_rows, host_scores = rows[order], scores[order]

    assert set(mesh_rows.tolist()) == set(host_rows.tolist())
    np.testing.assert_allclose(np.sort(mesh_scores)[::-1],
                               np.sort(host_scores)[::-1], rtol=2e-2)

    # the full node.search knn path returns the same docs
    resp = node.search("vec4", {"knn": {"field": "v",
                                        "query_vector": q.tolist(),
                                        "k": 10, "num_candidates": 50},
                                "size": 10})
    ids = {h["_id"] for h in resp["hits"]["hits"]}
    assert len(ids) == 10

    # filtered path agrees too
    filt = {"term": {"grp": "a"}}
    resp_f = node.search("vec4", {"knn": {"field": "v",
                                          "query_vector": q.tolist(),
                                          "k": 10, "num_candidates": 50,
                                          "filter": filt},
                                  "size": 10})
    for h in resp_f["hits"]["hits"]:
        assert int(h["_id"]) % 2 == 1  # grp == "a"
    node.close()
