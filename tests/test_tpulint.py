"""tpulint: the static JAX-discipline gate (tools/tpulint).

Three layers:

* `test_repo_is_lint_clean` — the tier-1 gate: the analyzer runs over
  `elasticsearch_tpu/` exactly as the CLI does and must report zero
  unsuppressed findings (pragmas need written reasons; baseline entries
  may not carry TODO reasons).
* golden fixtures — one fires/clean pair per rule under
  `tests/tpulint_fixtures/`, linted with only that rule selected; the
  `# [expect]` markers in the fires files pin WHERE each finding lands.
* machinery — pragma syntax (reason mandatory, standalone-comment
  placement), baseline round-trip (suppress, reason preservation,
  key stability against line shifts), CLI exit codes and JSON shape.
"""

import json
import os
import subprocess
import sys
import textwrap

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
FIXTURES = os.path.join(REPO, "tests", "tpulint_fixtures")
PACKAGE = os.path.join(REPO, "elasticsearch_tpu")

from tools.tpulint.engine import (  # noqa: E402
    Config,
    lint_paths,
    load_baseline,
    write_baseline,
)

RULE_IDS = tuple(f"TPU{i:03d}" for i in range(1, 16))


def _fixture_path(name: str) -> str:
    """Fixtures live flat under tpulint_fixtures/ — except path-scoped
    rules (e.g. TPU015's transport/ scope), whose fixtures sit in a
    subdirectory matching the rule's globs."""
    flat = os.path.join(FIXTURES, name)
    if os.path.exists(flat):
        return flat
    for root, _dirs, files in os.walk(FIXTURES):
        if name in files:
            return os.path.join(root, name)
    raise FileNotFoundError(f"no fixture named {name} under {FIXTURES}")


def lint_fixture(name: str, rule: str):
    return lint_paths([_fixture_path(name)],
                      config=Config(select=(rule,)), root=REPO)


def expected_lines(name: str):
    """Line numbers carrying an `# [expect]` marker in a fires fixture."""
    with open(_fixture_path(name)) as f:
        return {i for i, text in enumerate(f.read().splitlines(), 1)
                if "[expect]" in text}


# ---------------------------------------------------------------------------
# the tier-1 gate
# ---------------------------------------------------------------------------

def test_repo_is_lint_clean():
    """Zero unsuppressed findings over elasticsearch_tpu/ — the build-
    time analog of the ES_TPU_DISPATCH_STRICT=1 runtime gate. A new
    finding means: fix it, or suppress it with a WRITTEN reason
    (pragma or baseline entry) that review can judge."""
    baseline_file = os.path.join(REPO, "tools", "tpulint",
                                 "baseline.json")
    unsuppressed, by_pragma, by_baseline = lint_paths(
        [PACKAGE], baseline_path=baseline_file, root=REPO)
    assert not unsuppressed, \
        "tpulint findings (fix, or suppress with a written reason):\n" \
        + "\n".join(f.render() for f in unsuppressed)
    for f, reason in by_baseline:
        assert "TODO" not in reason, \
            f"baseline entry for {f.render()} still carries a TODO " \
            "reason — write the justification"


def test_baseline_file_entries_all_have_reasons():
    baseline = load_baseline(
        os.path.join(REPO, "tools", "tpulint", "baseline.json"))
    for key, (reason, _count) in baseline.items():
        assert reason and "TODO" not in reason, \
            f"baseline entry {key} has no written reason"


# ---------------------------------------------------------------------------
# golden fixtures: one fires/clean pair per rule
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("rule", RULE_IDS)
def test_rule_fires_on_fixture(rule):
    name = f"tpu{rule[3:]}_fires.py"
    findings, _, _ = lint_fixture(name, rule)
    assert findings, f"{name} produced no {rule} findings"
    assert all(f.rule == rule for f in findings)
    marked = expected_lines(name)
    assert marked, f"{name} has no [expect] markers"
    assert {f.line for f in findings} == marked, \
        f"{rule} fired at {sorted(f.line for f in findings)}, " \
        f"expected {sorted(marked)}:\n" \
        + "\n".join(f.render() for f in findings)


@pytest.mark.parametrize("rule", RULE_IDS)
def test_rule_quiet_on_clean_fixture(rule):
    name = f"tpu{rule[3:]}_clean.py"
    findings, _, _ = lint_fixture(name, rule)
    assert not findings, \
        f"{name} should be clean but fired:\n" \
        + "\n".join(f.render() for f in findings)


# ---------------------------------------------------------------------------
# pragma behavior
# ---------------------------------------------------------------------------

def _lint_source(tmp_path, source, rule, baseline_path=None):
    p = tmp_path / "mod.py"
    p.write_text(textwrap.dedent(source))
    return lint_paths([str(p)], config=Config(select=(rule,)),
                      baseline_path=baseline_path, root=str(tmp_path))


def test_pragma_with_reason_suppresses(tmp_path):
    un, by_pragma, _ = _lint_source(tmp_path, """
        import jax
        f = jax.jit(lambda x: x)  # tpulint: disable=TPU001(bench-only micro probe)
        """, "TPU001")
    assert not un
    assert len(by_pragma) == 1
    assert by_pragma[0][1] == "bench-only micro probe"


def test_pragma_on_preceding_comment_line_suppresses(tmp_path):
    un, by_pragma, _ = _lint_source(tmp_path, """
        import jax
        # tpulint: disable=TPU001(decorators need the line above)
        f = jax.jit(lambda x: x)
        """, "TPU001")
    assert not un
    assert len(by_pragma) == 1


def test_pragma_without_reason_does_not_suppress(tmp_path):
    un, by_pragma, _ = _lint_source(tmp_path, """
        import jax
        f = jax.jit(lambda x: x)  # tpulint: disable=TPU001
        """, "TPU001")
    assert len(un) == 1
    assert not by_pragma


def test_pragma_for_other_rule_does_not_suppress(tmp_path):
    un, _, _ = _lint_source(tmp_path, """
        import jax
        f = jax.jit(lambda x: x)  # tpulint: disable=TPU006(wrong rule)
        """, "TPU001")
    assert len(un) == 1


# ---------------------------------------------------------------------------
# baseline behavior
# ---------------------------------------------------------------------------

SOURCE_WITH_FINDING = """
    import jax
    f = jax.jit(lambda x: x)
    """


def test_baseline_suppresses_and_preserves_reason(tmp_path):
    bl = tmp_path / "baseline.json"
    findings, _, _ = _lint_source(tmp_path, SOURCE_WITH_FINDING, "TPU001")
    assert len(findings) == 1
    write_baseline(findings, str(bl))
    data = json.loads(bl.read_text())
    assert data["entries"][0]["reason"].startswith("TODO")
    # a human writes the reason; rewriting the baseline preserves it
    data["entries"][0]["reason"] = "grandfathered: legacy probe"
    bl.write_text(json.dumps(data))
    un, _, by_baseline = _lint_source(tmp_path, SOURCE_WITH_FINDING,
                                      "TPU001", baseline_path=str(bl))
    assert not un
    assert by_baseline[0][1] == "grandfathered: legacy probe"
    write_baseline([f for f, _ in by_baseline], str(bl))
    data = json.loads(bl.read_text())
    assert data["entries"][0]["reason"] == "grandfathered: legacy probe"


def test_baseline_key_survives_line_shifts(tmp_path):
    """Baseline keys carry no line numbers: adding code ABOVE a
    baselined site must not un-suppress it."""
    bl = tmp_path / "baseline.json"
    findings, _, _ = _lint_source(tmp_path, SOURCE_WITH_FINDING, "TPU001")
    write_baseline(findings, str(bl))
    shifted = """
        import jax

        UNRELATED = 1
        ALSO_UNRELATED = 2


        f = jax.jit(lambda x: x)
        """
    un, _, by_baseline = _lint_source(tmp_path, shifted, "TPU001",
                                      baseline_path=str(bl))
    assert not un
    assert len(by_baseline) == 1


def test_baseline_does_not_cover_new_findings(tmp_path):
    bl = tmp_path / "baseline.json"
    findings, _, _ = _lint_source(tmp_path, SOURCE_WITH_FINDING, "TPU001")
    write_baseline(findings, str(bl))
    # SOURCE_WITH_FINDING ends with the 4-space indent of its closing
    # quotes, so appending an unindented line keeps dedent() happy
    grown = SOURCE_WITH_FINDING + "g = jax.jit(lambda y: y)\n"
    un, _, by_baseline = _lint_source(tmp_path, grown, "TPU001",
                                      baseline_path=str(bl))
    assert len(by_baseline) == 1
    assert len(un) == 1
    assert "g = jax.jit" in un[0].snippet


def test_baseline_entry_does_not_absorb_copy_pasted_duplicates(tmp_path):
    """An entry covers `count` occurrences of its line — a NEW identical
    copy-paste in the same scope is a new finding, not a free ride."""
    bl = tmp_path / "baseline.json"
    findings, _, _ = _lint_source(tmp_path, SOURCE_WITH_FINDING, "TPU001")
    write_baseline(findings, str(bl))
    duplicated = SOURCE_WITH_FINDING + "f = jax.jit(lambda x: x)\n"
    un, _, by_baseline = _lint_source(tmp_path, duplicated, "TPU001",
                                      baseline_path=str(bl))
    assert len(by_baseline) == 1
    assert len(un) == 1  # the second identical line fires


def test_partial_baseline_write_preserves_out_of_scope_entries(tmp_path):
    """`--baseline write` over a path subset must not wipe entries (and
    written reasons) for files the run never linted."""
    a = tmp_path / "a.py"
    b = tmp_path / "b.py"
    a.write_text("import jax\nf = jax.jit(lambda x: x)\n")
    b.write_text("import jax\ng = jax.jit(lambda y: y)\n")
    bl = tmp_path / "baseline.json"
    from tools.tpulint.engine import linted_rel_paths
    findings, _, _ = lint_paths([str(a), str(b)],
                                config=Config(select=("TPU001",)),
                                root=str(tmp_path))
    write_baseline(findings, str(bl))
    data = json.loads(bl.read_text())
    for e in data["entries"]:
        e["reason"] = f"justified: {e['path']}"
    bl.write_text(json.dumps(data))
    # partial rewrite over a.py only: b.py's entry + reason must survive
    fa, _, ba = lint_paths([str(a)], config=Config(select=("TPU001",)),
                           baseline_path=str(bl), root=str(tmp_path))
    write_baseline(fa + [f for f, _ in ba], str(bl),
                   linted_paths=linted_rel_paths([str(a)],
                                                 str(tmp_path)),
                   selected_rules=("TPU001",))
    kept = {e["path"]: e["reason"]
            for e in json.loads(bl.read_text())["entries"]}
    assert kept == {"a.py": "justified: a.py", "b.py": "justified: b.py"}


def test_hot_path_marker_must_be_exact(tmp_path):
    """A disable-reason MENTIONING hot-path must not flip the module
    into TPU002's hot-path scope at a distance."""
    src = """
        import numpy as np
        # tpulint: disable=TPU003(keyed per hot-path mesh build)
        _CACHE = {}


        def pull(q):
            from elasticsearch_tpu.ops import dispatch
            s = dispatch.call("knn.exact", q)
            return s.item()
        """
    un, _, _ = _lint_source(tmp_path, src, "TPU002")
    assert not un  # not hot-path: the pragma body is not exactly hot-path
    marked = src.replace(
        "# tpulint: disable=TPU003(keyed per hot-path mesh build)",
        "# tpulint: hot-path")
    un, _, _ = _lint_source(tmp_path, marked, "TPU002")
    assert len(un) == 1


# ---------------------------------------------------------------------------
# CLI contract
# ---------------------------------------------------------------------------

def _run_cli(*argv):
    return subprocess.run(
        [sys.executable, "-m", "tools.tpulint", *argv],
        cwd=REPO, capture_output=True, text=True, timeout=120)


def test_cli_repo_is_clean_exit_0():
    proc = _run_cli()
    assert proc.returncode == 0, proc.stdout + proc.stderr


def test_cli_findings_exit_1_and_json_shape(tmp_path):
    bad = tmp_path / "bad.py"
    bad.write_text("import jax\nf = jax.jit(lambda x: x)\n")
    proc = _run_cli(str(bad), "--json", "--no-baseline")
    assert proc.returncode == 1, proc.stdout + proc.stderr
    report = json.loads(proc.stdout)
    assert report["counts"]["unsuppressed"] == 1
    (finding,) = report["findings"]
    assert finding["rule"] == "TPU001"
    assert finding["line"] == 2
    assert "snippet" in finding and "scope" in finding


def test_cli_bad_path_exit_2():
    proc = _run_cli(os.path.join(REPO, "no", "such", "path.py"))
    assert proc.returncode == 2


def test_cli_bad_baseline_mode_exit_2():
    proc = _run_cli("--baseline", "frobnicate")
    assert proc.returncode == 2


def test_cli_unknown_select_rule_exit_2():
    """A typoed --select must not silently select zero rules and report
    clean with exit 0."""
    proc = _run_cli("--select", "TPU01")
    assert proc.returncode == 2
    assert "unknown rule id" in proc.stderr


def test_cli_non_python_file_exit_2(tmp_path):
    """An existing non-.py argument walks to nothing — that must be a
    loud usage error, not a green '0 findings' no-op."""
    f = tmp_path / "notes.txt"
    f.write_text("import jax\n")
    proc = _run_cli(str(f))
    assert proc.returncode == 2
    assert "not a python file" in proc.stderr


def test_cli_baseline_write_roundtrip(tmp_path):
    bad = tmp_path / "bad.py"
    bad.write_text("import jax\nf = jax.jit(lambda x: x)\n")
    bl = tmp_path / "bl.json"
    proc = _run_cli(str(bad), "--baseline", "write",
                    "--baseline-file", str(bl))
    assert proc.returncode == 0, proc.stdout + proc.stderr
    entries = json.loads(bl.read_text())["entries"]
    assert len(entries) == 1 and entries[0]["rule"] == "TPU001"
    # with the fresh baseline the same lint is quiet
    proc = _run_cli(str(bad), "--baseline-file", str(bl))
    assert proc.returncode == 0, proc.stdout + proc.stderr


# ---------------------------------------------------------------------------
# registration-index integration (TPU004 reads real registrations)
# ---------------------------------------------------------------------------

def test_donated_kernel_index_sees_bm25_registration():
    """The project index must pick up `bm25.topk`'s donate_argnums from
    ops/bm25.py — TPU004 is only as good as this map."""
    from tools.tpulint.engine import Config as C, ModuleContext, \
        ProjectIndex
    path = os.path.join(PACKAGE, "ops", "bm25.py")
    with open(path) as f:
        ctx = ModuleContext(path, "elasticsearch_tpu/ops/bm25.py",
                            f.read(), C())
    idx = ProjectIndex()
    idx.scan(ctx)
    assert idx.donated_kernels.get("bm25.topk") == (0, 1)
