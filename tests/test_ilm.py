"""ILM, SLM, rollover, and resize (shrink/clone/split).

Reference behaviors: x-pack/plugin/ilm (phase/action step machine),
TransportRolloverAction (condition evaluation + alias swap),
TransportResizeAction.
"""

import json
import time

import pytest

from elasticsearch_tpu.node import Node
from elasticsearch_tpu.rest.actions import register_all
from elasticsearch_tpu.rest.controller import RestController


class Client:
    def __init__(self, node):
        self.rc = RestController()
        register_all(self.rc, node)

    def req(self, method, path, body=None, **query):
        raw = json.dumps(body).encode() if body is not None else b""
        return self.rc.dispatch(method, path, {k: str(v) for k, v in query.items()},
                                raw, "application/json")


@pytest.fixture
def node(tmp_path):
    n = Node(str(tmp_path / "data"))
    yield n
    n.close()


@pytest.fixture
def client(node):
    return Client(node)


# ----------------------------------------------------------------- rollover

def test_rollover_max_docs(client, node):
    client.req("PUT", "/logs-000001",
               {"aliases": {"logs": {"is_write_index": True}}})
    for i in range(5):
        client.req("POST", "/logs-000001/_doc", {"n": i})
    client.req("POST", "/logs-000001/_refresh")
    # condition not met
    st, body = client.req("POST", "/logs/_rollover",
                          {"conditions": {"max_docs": 10}})
    assert st == 200 and body["rolled_over"] is False
    # met
    st, body = client.req("POST", "/logs/_rollover",
                          {"conditions": {"max_docs": 5}})
    assert body["rolled_over"] is True
    assert body["new_index"] == "logs-000002"
    assert node.indices.exists("logs-000002")
    # write index moved: indexing through the alias lands in the new index
    client.req("POST", "/logs-000002/_doc", {"n": 99})
    client.req("POST", "/logs-000002/_refresh")
    assert node.indices.get("logs-000002").doc_count() == 1


def test_rollover_dry_run_and_unconditioned(client):
    client.req("PUT", "/audit-000001", {"aliases": {"audit": {}}})
    st, body = client.req("POST", "/audit/_rollover", {}, dry_run="true")
    assert body["dry_run"] is True and body["rolled_over"] is False
    st, body = client.req("POST", "/audit/_rollover", {})
    assert body["rolled_over"] is True   # no conditions == unconditional


# ------------------------------------------------------------------- resize

def test_shrink_copies_docs(client, node):
    client.req("PUT", "/big", {"settings": {"index.number_of_shards": 4}})
    for i in range(20):
        client.req("PUT", f"/big/_doc/{i}", {"v": i})
    client.req("POST", "/big/_refresh")
    st, body = client.req("POST", "/big/_shrink/small")
    assert st == 200 and body["copied_docs"] == 20
    assert node.indices.get("small").num_shards == 1
    st, body = client.req("GET", "/small/_count")
    assert body["count"] == 20


def test_clone_preserves_mapping(client, node):
    client.req("PUT", "/src", {"mappings": {"properties": {
        "v": {"type": "dense_vector", "dims": 4}}}})
    client.req("PUT", "/src/_doc/1", {"v": [1, 2, 3, 4]})
    client.req("POST", "/src/_refresh")
    st, body = client.req("POST", "/src/_clone/dst")
    assert st == 200
    props = node.indices.get("dst").mapper_service.to_dict()["properties"]
    assert props["v"]["type"] == "dense_vector"


def test_split_requires_shard_count(client):
    client.req("PUT", "/s1")
    st, body = client.req("POST", "/s1/_split/s2")
    assert st == 400


# --------------------------------------------------------------------- ILM

def test_ilm_policy_crud(client):
    st, _ = client.req("PUT", "/_ilm/policy/p1", {"policy": {"phases": {
        "hot": {"actions": {"rollover": {"max_docs": 3}}},
        "delete": {"min_age": "30d", "actions": {"delete": {}}}}}})
    assert st == 200
    st, body = client.req("GET", "/_ilm/policy/p1")
    assert "hot" in body["p1"]["policy"]["phases"]
    st, _ = client.req("DELETE", "/_ilm/policy/p1")
    assert st == 200
    st, _ = client.req("GET", "/_ilm/policy/p1")
    assert st == 404


def test_ilm_hot_rollover_then_delete(client, node):
    client.req("PUT", "/_ilm/policy/cycle", {"policy": {"phases": {
        "hot": {"actions": {"rollover": {"max_docs": 2}}},
        "delete": {"min_age": "1h", "actions": {"delete": {}}}}}})
    client.req("PUT", "/d-000001", {
        "settings": {"index.lifecycle.name": "cycle",
                     "index.lifecycle.rollover_alias": "d"},
        "aliases": {"d": {"is_write_index": True}}})
    for i in range(3):
        client.req("POST", "/d-000001/_doc", {"i": i})
    client.req("POST", "/d-000001/_refresh")
    now = int(time.time() * 1000)
    actions = node.ilm.run_once(now_ms=now)
    assert {"index": "d-000001", "action": "rollover",
            "new_index": "d-000002"} in actions
    assert node.indices.exists("d-000002")
    # new index inherits the policy
    assert node.indices.get("d-000002").settings.get(
        "index.lifecycle.name") == "cycle"
    # advance time past delete min_age → both indices deleted
    later = now + 2 * 3600 * 1000
    actions = node.ilm.run_once(now_ms=later)
    deleted = {a["index"] for a in actions if a["action"] == "delete"}
    assert "d-000001" in deleted
    assert not node.indices.exists("d-000001")


def test_ilm_warm_forcemerge_readonly(client, node):
    client.req("PUT", "/_ilm/policy/warmup", {"policy": {"phases": {
        "warm": {"min_age": "10m",
                 "actions": {"forcemerge": {"max_num_segments": 1},
                             "readonly": {}}}}}})
    client.req("PUT", "/w1", {
        "settings": {"index.lifecycle.name": "warmup"}})
    client.req("PUT", "/w1/_doc/1", {"x": 1})
    client.req("POST", "/w1/_refresh")
    now = int(time.time() * 1000)
    assert node.ilm.run_once(now_ms=now) == []    # min_age not reached
    actions = node.ilm.run_once(now_ms=now + 11 * 60 * 1000)
    kinds = {a["action"] for a in actions}
    assert kinds == {"forcemerge", "readonly"}
    assert node.indices.get("w1").settings.get("index.blocks.write") is True


def test_ilm_explain(client, node):
    client.req("PUT", "/_ilm/policy/px", {"policy": {"phases": {
        "hot": {"actions": {}}}}})
    client.req("PUT", "/managed", {"settings": {"index.lifecycle.name": "px"}})
    client.req("PUT", "/unmanaged")
    node.ilm.run_once()
    st, body = client.req("GET", "/managed/_ilm/explain")
    assert body["indices"]["managed"]["managed"] is True
    assert body["indices"]["managed"]["phase"] == "hot"
    st, body = client.req("GET", "/unmanaged/_ilm/explain")
    assert body["indices"]["unmanaged"]["managed"] is False


def test_ilm_start_stop(client, node):
    client.req("POST", "/_ilm/stop")
    st, body = client.req("GET", "/_ilm/status")
    assert body["operation_mode"] == "STOPPED"
    assert node.ilm.run_once() == []
    client.req("POST", "/_ilm/start")
    st, body = client.req("GET", "/_ilm/status")
    assert body["operation_mode"] == "RUNNING"


# --------------------------------------------------------------------- SLM

def test_slm_policy_and_execute(client, node, tmp_path):
    client.req("PUT", "/_snapshot/repo1",
               {"type": "fs", "settings": {"location": str(tmp_path / "snaps")}})
    client.req("PUT", "/data1/_doc/1", {"x": 1})
    client.req("POST", "/data1/_refresh")
    st, _ = client.req("PUT", "/_slm/policy/nightly", {
        "schedule": "0 30 1 * * ?", "name": "<nightly-{now/d}>",
        "repository": "repo1", "config": {"indices": "data1"}})
    assert st == 200
    st, body = client.req("POST", "/_slm/policy/nightly/_execute")
    assert st == 200 and body["snapshot_name"].startswith("nightly-")
    st, body = client.req("GET", "/_slm/policy/nightly")
    assert body["nightly"]["last_success"]["snapshot_name"] == body["nightly"]["last_success"]["snapshot_name"]
    # snapshot actually exists in the repo
    st, body = client.req("GET", "/_snapshot/repo1/_all")
    names = [s["snapshot"] for s in body["snapshots"]]
    assert any(n.startswith("nightly-") for n in names)


def test_dynamic_settings_update(client, node):
    client.req("PUT", "/cfg")
    st, _ = client.req("PUT", "/cfg/_settings",
                       {"index": {"number_of_replicas": 3}})
    assert st == 200
    assert node.indices.get("cfg").num_replicas == 3
    st, body = client.req("PUT", "/cfg/_settings",
                          {"index.number_of_shards": 9})
    assert st == 400
