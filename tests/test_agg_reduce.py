"""Distributed aggregation reduce: partial states merged across skewed
shards must match single-shard ground truth (InternalAggregation.reduce,
SearchPhaseController.java:734 analog)."""

import math
import random

import numpy as np
import pytest

from elasticsearch_tpu.index.engine import Engine
from elasticsearch_tpu.index.mapping import MapperService
from elasticsearch_tpu.search.agg_partials import (
    _hll_estimate, _hll_from_values, _hll_merge, _td_from_values, _td_merge,
    _td_quantile, compute_partial_aggs, finalize_aggs, merge_partial_aggs,
)
from elasticsearch_tpu.search.aggregations import compute_aggs
from elasticsearch_tpu.search.queries import SearchContext

MAPPING = {
    "properties": {
        "cat": {"type": "keyword"},
        "name": {"type": "keyword"},
        "v": {"type": "double"},
        "w": {"type": "double"},
        "ts": {"type": "date"},
        "pt": {"type": "geo_point"},
    }
}


def _mk_docs():
    rng = random.Random(7)
    docs = []
    for i in range(240):
        docs.append({
            "cat": ["red", "green", "blue", "teal"][i % 4],
            "name": f"u{i % 37}",
            "v": float(i),
            "w": float(1 + (i % 5)),
            "ts": 1_600_000_000_000 + (i % 6) * 3_600_000,
            "pt": {"lat": rng.uniform(-60, 60), "lon": rng.uniform(-170, 170)},
        })
    return docs


@pytest.fixture(scope="module")
def ctx(tmp_path_factory):
    e = Engine(str(tmp_path_factory.mktemp("aggred") / "shard"),
               MapperService(MAPPING))
    for i, d in enumerate(_mk_docs()):
        e.index(str(i), d)
    e.refresh()
    yield SearchContext(e.acquire_searcher(), e.mapper_service)
    e.close()


def _skewed_split(ctx, parts=3):
    """Deliberately skewed row partition: contiguous value ranges, so every
    per-shard metric differs wildly from the global one."""
    rows = ctx.all_rows()
    n = len(rows)
    cut1, cut2 = n // 6, n // 2  # uneven sizes
    return [rows[:cut1], rows[cut1:cut2], rows[cut2:]]


def _reduce(ctx, splits, spec):
    partials = [compute_partial_aggs(ctx, rows, spec) for rows in splits]
    merged = partials[0]
    for p in partials[1:]:
        merged = merge_partial_aggs(merged, p, spec)
    return finalize_aggs(merged, spec)


def _assert_close(a, b, path="$"):
    if isinstance(a, dict):
        assert isinstance(b, dict) and set(a) == set(b), \
            f"{path}: keys {sorted(a)} != {sorted(b)}"
        for k in a:
            _assert_close(a[k], b[k], f"{path}.{k}")
    elif isinstance(a, list):
        assert isinstance(b, list) and len(a) == len(b), f"{path}: len differs"
        for i, (x, y) in enumerate(zip(a, b)):
            _assert_close(x, y, f"{path}[{i}]")
    elif isinstance(a, float) and isinstance(b, (int, float)):
        assert math.isclose(a, float(b), rel_tol=1e-6, abs_tol=1e-9), \
            f"{path}: {a} != {b}"
    else:
        assert a == b, f"{path}: {a!r} != {b!r}"


METRIC_SPECS = {
    "the_avg": {"avg": {"field": "v"}},
    "the_sum": {"sum": {"field": "v"}},
    "the_min": {"min": {"field": "v"}},
    "the_max": {"max": {"field": "v"}},
    "the_stats": {"stats": {"field": "v"}},
    "the_count": {"value_count": {"field": "v"}},
    "the_wavg": {"weighted_avg": {"value": {"field": "v"},
                                  "weight": {"field": "w"}}},
}


def test_exact_metrics_match_ground_truth(ctx):
    spec = METRIC_SPECS
    truth = compute_aggs(ctx, ctx.all_rows(), spec)
    got = _reduce(ctx, _skewed_split(ctx), spec)
    _assert_close(got, truth)


def test_extended_stats_match(ctx):
    spec = {"es": {"extended_stats": {"field": "v", "sigma": 3.0}}}
    truth = compute_aggs(ctx, ctx.all_rows(), spec)
    got = _reduce(ctx, _skewed_split(ctx), spec)
    for k in ("count", "min", "max", "avg", "sum", "sum_of_squares"):
        assert math.isclose(got["es"][k], truth["es"][k], rel_tol=1e-9)
    assert math.isclose(got["es"]["variance"], truth["es"]["variance"],
                        rel_tol=1e-6)
    assert math.isclose(got["es"]["std_deviation_bounds"]["upper"],
                        truth["es"]["std_deviation_bounds"]["upper"],
                        rel_tol=1e-6)


def test_cardinality_across_shards(ctx):
    # 37 distinct names spread across all three skewed splits: per-shard
    # cardinalities sum to far more than 37, the merged HLL must not
    spec = {"names": {"cardinality": {"field": "name"}}}
    got = _reduce(ctx, _skewed_split(ctx), spec)
    assert got["names"]["value"] == 37


def test_percentiles_and_mad_across_shards(ctx):
    spec = {
        "pct": {"percentiles": {"field": "v", "percents": [25, 50, 75, 99]}},
        "mad": {"median_absolute_deviation": {"field": "v"}},
        "box": {"boxplot": {"field": "v"}},
        "ranks": {"percentile_ranks": {"field": "v", "values": [60.0, 200.0]}},
    }
    truth = compute_aggs(ctx, ctx.all_rows(), spec)
    got = _reduce(ctx, _skewed_split(ctx), spec)
    # t-digest is exact below compression (240 values ≈ near-exact)
    for p in ("25.0", "50.0", "75.0", "99.0"):
        assert math.isclose(got["pct"]["values"][p], truth["pct"]["values"][p],
                            rel_tol=0.02, abs_tol=1.5), (p, got["pct"], truth["pct"])
    assert math.isclose(got["mad"]["value"], truth["mad"]["value"],
                        rel_tol=0.05, abs_tol=2.0)
    assert got["box"]["min"] == truth["box"]["min"]
    assert got["box"]["max"] == truth["box"]["max"]
    assert math.isclose(got["box"]["q2"], truth["box"]["q2"],
                        rel_tol=0.02, abs_tol=1.5)
    for t in ("60.0", "200.0"):
        assert math.isclose(got["ranks"]["values"][t],
                            truth["ranks"]["values"][t],
                            rel_tol=0.03, abs_tol=1.0)


def test_terms_with_sub_aggs_across_shards(ctx):
    # the round-1 bug: merged terms buckets added doc_count but kept the
    # FIRST shard's sub-agg values; with contiguous-range splits every
    # shard's per-bucket avg differs from the global per-bucket avg
    spec = {"cats": {"terms": {"field": "cat"},
                     "aggs": {"m": {"avg": {"field": "v"}},
                              "u": {"cardinality": {"field": "name"}}}}}
    truth = compute_aggs(ctx, ctx.all_rows(), spec)
    got = _reduce(ctx, _skewed_split(ctx), spec)
    t_buckets = {b["key"]: b for b in truth["cats"]["buckets"]}
    g_buckets = {b["key"]: b for b in got["cats"]["buckets"]}
    assert set(t_buckets) == set(g_buckets)
    for key, tb in t_buckets.items():
        gb = g_buckets[key]
        assert gb["doc_count"] == tb["doc_count"]
        assert math.isclose(gb["m"]["value"], tb["m"]["value"], rel_tol=1e-9), \
            f"bucket {key}: merged avg {gb['m']['value']} != {tb['m']['value']}"
        assert gb["u"]["value"] == tb["u"]["value"]


def test_terms_order_and_truncation(ctx):
    spec = {"cats": {"terms": {"field": "cat", "size": 2,
                               "order": {"m": "desc"}},
                     "aggs": {"m": {"avg": {"field": "v"}}}}}
    truth = compute_aggs(ctx, ctx.all_rows(), spec)
    got = _reduce(ctx, _skewed_split(ctx), spec)
    assert [b["key"] for b in got["cats"]["buckets"]] == \
        [b["key"] for b in truth["cats"]["buckets"]]
    assert got["cats"]["sum_other_doc_count"] == \
        truth["cats"]["sum_other_doc_count"]


def test_histogram_and_date_histogram(ctx):
    spec = {
        "h": {"histogram": {"field": "v", "interval": 50.0},
              "aggs": {"s": {"sum": {"field": "w"}}}},
        "dh": {"date_histogram": {"field": "ts", "fixed_interval": "1h"}},
    }
    truth = compute_aggs(ctx, ctx.all_rows(), spec)
    got = _reduce(ctx, _skewed_split(ctx), spec)
    _assert_close(got, truth)


def test_range_filters_composite(ctx):
    spec = {
        "r": {"range": {"field": "v",
                        "ranges": [{"to": 60.0}, {"from": 60.0, "to": 180.0},
                                   {"from": 180.0}]},
              "aggs": {"m": {"max": {"field": "w"}}}},
        "f": {"filters": {"filters": {
            "reds": {"term": {"cat": "red"}},
            "high": {"range": {"v": {"gte": 120}}}}},
            "aggs": {"a": {"avg": {"field": "v"}}}},
        "c": {"composite": {"size": 6, "sources": [
            {"cc": {"terms": {"field": "cat"}}}]}},
    }
    truth = compute_aggs(ctx, ctx.all_rows(), spec)
    got = _reduce(ctx, _skewed_split(ctx), spec)
    _assert_close(got, truth)


def test_geo_and_string_and_matrix(ctx):
    spec = {
        "gb": {"geo_bounds": {"field": "pt"}},
        "gc": {"geo_centroid": {"field": "pt"}},
        "ss": {"string_stats": {"field": "name"}},
        "mx": {"matrix_stats": {"fields": ["v", "w"]}},
    }
    truth = compute_aggs(ctx, ctx.all_rows(), spec)
    got = _reduce(ctx, _skewed_split(ctx), spec)
    assert math.isclose(got["gb"]["bounds"]["top_left"]["lat"],
                        truth["gb"]["bounds"]["top_left"]["lat"])
    assert math.isclose(got["gc"]["location"]["lat"],
                        truth["gc"]["location"]["lat"], rel_tol=1e-9)
    assert got["ss"]["count"] == truth["ss"]["count"]
    assert math.isclose(got["ss"]["entropy"], truth["ss"]["entropy"],
                        rel_tol=1e-6)
    tm = {f["name"]: f for f in truth["mx"]["fields"]}
    gm = {f["name"]: f for f in got["mx"]["fields"]}
    for f in tm:
        assert math.isclose(gm[f]["mean"], tm[f]["mean"], rel_tol=1e-9)
        assert math.isclose(gm[f]["variance"], tm[f]["variance"], rel_tol=1e-6)
        assert math.isclose(gm[f]["correlation"]["v"], tm[f]["correlation"]["v"],
                            rel_tol=1e-6)
        assert math.isclose(gm[f]["skewness"], tm[f]["skewness"],
                            rel_tol=1e-5, abs_tol=1e-9)


def test_pipeline_aggs_run_after_reduce(ctx):
    spec = {
        "h": {"histogram": {"field": "v", "interval": 60.0},
              "aggs": {"s": {"sum": {"field": "w"}},
                       "cum": {"cumulative_sum": {"buckets_path": "s"}}}},
        "avg_of_sums": {"avg_bucket": {"buckets_path": "h>s"}},
    }
    truth = compute_aggs(ctx, ctx.all_rows(), spec)
    got = _reduce(ctx, _skewed_split(ctx), spec)
    _assert_close(got, truth)


def test_single_bucket_kinds(ctx):
    spec = {
        "miss": {"missing": {"field": "nope"},
                 "aggs": {"c": {"value_count": {"field": "v"}}}},
        "filt": {"filter": {"term": {"cat": "blue"}},
                 "aggs": {"a": {"avg": {"field": "v"}}}},
    }
    truth = compute_aggs(ctx, ctx.all_rows(), spec)
    got = _reduce(ctx, _skewed_split(ctx), spec)
    _assert_close(got, truth)


# ---------------------------------------------------------------------------
# sketch unit tests
# ---------------------------------------------------------------------------


def test_hll_accuracy_and_merge():
    a = _hll_from_values(range(0, 60_000))
    b = _hll_from_values(range(40_000, 100_000))
    est = _hll_estimate(_hll_merge(a, b))
    assert abs(est - 100_000) / 100_000 < 0.05
    # sparse path
    s = _hll_from_values(range(100))
    assert "sparse" in s
    assert abs(_hll_estimate(s) - 100) <= 2


def test_tdigest_exact_when_small_and_merge_quantiles():
    rng = np.random.default_rng(3)
    vals = rng.normal(0, 100, size=5000)
    a = _td_from_values(vals[:1000])
    b = _td_from_values(vals[1000:])
    m = _td_merge(a, b)
    for q in (0.01, 0.25, 0.5, 0.75, 0.99):
        approx = _td_quantile(m, q)
        exact = float(np.quantile(vals, q))
        assert abs(approx - exact) < 12.0, (q, approx, exact)
    # small inputs are exact at the median
    small = _td_from_values(np.asarray([1.0, 2.0, 3.0, 4.0, 5.0]))
    assert abs(_td_quantile(small, 0.5) - 3.0) < 1e-9


def test_auto_date_histogram_with_sub_aggs(ctx):
    # coarsening at finalize must merge the still-partial sub-agg states
    # (regression: rebucketing finalized sub values raised ParsingError)
    spec = {"adh": {"auto_date_histogram": {"field": "ts", "buckets": 3},
                    "aggs": {"m": {"avg": {"field": "v"}}}}}
    got = _reduce(ctx, _skewed_split(ctx), spec)
    assert len(got["adh"]["buckets"]) <= 3
    total = sum(b["doc_count"] for b in got["adh"]["buckets"])
    assert total == len(ctx.all_rows())
    for b in got["adh"]["buckets"]:
        assert isinstance(b["m"]["value"], float)


def test_histogram_min_doc_count_no_shard_zero_fill(ctx):
    # min_doc_count>0 must not trigger dense shard-side zero-filling; the
    # threshold applies to MERGED counts (each shard alone is below 30
    # for some buckets the union keeps)
    spec = {"h": {"histogram": {"field": "v", "interval": 40.0,
                                "min_doc_count": 30}}}
    truth = compute_aggs(ctx, ctx.all_rows(), spec)
    got = _reduce(ctx, _skewed_split(ctx), spec)
    _assert_close(got, truth)


def test_terms_shard_size_bounds_candidates(ctx):
    from elasticsearch_tpu.search.agg_partials import _partial_spec
    s = _partial_spec("terms", {"field": "name", "size": 10})
    assert s["size"] == 25  # size*1.5+10, reference default
    s = _partial_spec("terms", {"field": "name", "size": 10, "shard_size": 99})
    assert s["size"] == 99
    s = _partial_spec("rare_terms", {"field": "name"})
    assert s["size"] == 1000 and s["max_doc_count"] > 1 << 50


def test_histogram_too_many_buckets_guard(ctx):
    from elasticsearch_tpu.common.errors import IllegalArgumentError
    with pytest.raises(IllegalArgumentError):
        compute_aggs(ctx, ctx.all_rows(),
                     {"h": {"histogram": {"field": "v", "interval": 0.00001,
                                          "min_doc_count": 0}}})


def test_scripted_metric_cross_shard_reduce(ctx):
    """scripted_metric: init/map/combine per shard (Painless), reduce at
    the coordinator over all shard states — the distributed result equals
    the single-pass ground truth (ScriptedMetricAggregator.java:38)."""
    spec = {"profit": {"scripted_metric": {
        "init_script": "state.vals = []",
        "map_script": "state.vals.add(doc['v'].value)",
        "combine_script":
            "double s = 0; for (t in state.vals) { s += t } return s",
        "reduce_script":
            "double s = 0; for (a in states) { s += a } return s"}}}
    single = compute_aggs(ctx, ctx.all_rows(), spec)
    distributed = _reduce(ctx, _skewed_split(ctx), spec)
    assert single["profit"]["value"] == sum(float(i) for i in range(240))
    _assert_close(distributed, single)


def test_scripted_metric_states_without_reduce(ctx):
    """No reduce_script: the states list itself comes back (one combined
    state per shard), matching InternalScriptedMetric's default."""
    spec = {"m": {"scripted_metric": {
        "init_script": "state.n = 0",
        "map_script": "state.n += 1",
        "combine_script": "return state.n"}}}
    distributed = _reduce(ctx, _skewed_split(ctx), spec)
    assert sorted(distributed["m"]["value"]) == sorted([40, 80, 120])


def test_scripted_metric_params_and_missing_map_script(ctx):
    spec = {"m": {"scripted_metric": {
        "init_script": "state.n = 0",
        "map_script": "state.n += params.step",
        "combine_script": "return state.n",
        "reduce_script":
            "double s = 0; for (a in states) { s += a } return s",
        "params": {"step": 2}}}}
    out = compute_aggs(ctx, ctx.all_rows(), spec)
    assert out["m"]["value"] == 480
    import pytest as _pytest
    from elasticsearch_tpu.common.errors import IllegalArgumentError
    with _pytest.raises(IllegalArgumentError, match="map_script"):
        compute_aggs(ctx, ctx.all_rows(),
                     {"m": {"scripted_metric": {"combine_script": "return 1"}}})
