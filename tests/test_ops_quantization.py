"""`ops/quantization.py`: round-trip bounds, scale edge cases, scoring
parity — the int8 recipe every storage path (flat corpus, IVF partitions,
sharded layout) routes through."""

import numpy as np
import pytest

from elasticsearch_tpu.ops.quantization import (
    dequantize_int8, quantize_int8, quantize_int8_np,
)


def _roundtrip_err(mat, q8, scales):
    recon = q8.astype(np.float32) * scales[:, None]
    return np.abs(recon - mat)


def test_roundtrip_error_bound():
    """Symmetric max-abs/127 quantization bounds per-element error by half
    a quantization step: |x - q*s| <= s/2 = max|row|/254."""
    rng = np.random.default_rng(0)
    mat = rng.standard_normal((256, 64)).astype(np.float32) * 5.0
    q8, scales = quantize_int8_np(mat)
    assert q8.dtype == np.int8
    assert scales.dtype == np.float32
    err = _roundtrip_err(mat, q8, scales)
    bound = (np.abs(mat).max(axis=1) / 127.0 / 2.0)[:, None] + 1e-6
    assert (err <= bound).all()


def test_device_and_host_paths_agree():
    """quantize_int8 (device) and quantize_int8_np (host) implement ONE
    policy — both levels of build_corpus depend on that."""
    rng = np.random.default_rng(1)
    mat = rng.standard_normal((64, 32)).astype(np.float32)
    q_np, s_np = quantize_int8_np(mat)
    q_dev, s_dev = quantize_int8(mat)
    np.testing.assert_array_equal(q_np, np.asarray(q_dev))
    np.testing.assert_allclose(s_np, np.asarray(s_dev), rtol=1e-6)


def test_scale_edge_cases():
    # all-zero row: the 1e-30 scale floor prevents divide-by-zero and
    # round-trips to exact zeros
    mat = np.zeros((4, 8), dtype=np.float32)
    mat[1] = 1e-38  # denormal-ish magnitudes stay finite too
    mat[2] = -3.0   # pure negative row is symmetric around zero
    mat[3, 0] = 1e30  # huge magnitude: scale grows, no overflow/clip bias
    q8, scales = quantize_int8_np(mat)
    assert np.isfinite(scales).all()
    assert (scales > 0).all()
    assert (q8[0] == 0).all()
    assert q8[2].min() == -127  # symmetric: full range reachable, no -128
    assert q8.min() >= -127 and q8.max() <= 127
    recon = q8.astype(np.float32) * scales[:, None]
    assert recon[3, 0] == pytest.approx(1e30, rel=0.01)
    assert (recon[0] == 0).all()


def test_zero_point_symmetry():
    """Symmetric scheme: zero always maps to code 0 exactly (no zero-point
    offset), so padding rows stay exactly zero post-dequant."""
    rng = np.random.default_rng(2)
    mat = rng.standard_normal((16, 16)).astype(np.float32)
    mat[:, 3] = 0.0
    q8, scales = quantize_int8_np(mat)
    assert (q8[:, 3] == 0).all()
    deq = np.asarray(dequantize_int8(q8, scales))
    assert (deq[:, 3] == 0).all()


def test_int8_scoring_parity_vs_fp32():
    """End-to-end: int8-stored corpus scores match fp32 within tolerance
    and preserve the top-k set on separated data."""
    import jax.numpy as jnp

    from elasticsearch_tpu.ops import knn as knn_ops
    from elasticsearch_tpu.ops import similarity as sim

    rng = np.random.default_rng(3)
    centers = rng.standard_normal((8, 32)).astype(np.float32) * 3.0
    vecs = (centers[rng.integers(0, 8, 500)]
            + 0.3 * rng.standard_normal((500, 32)).astype(np.float32))
    queries = vecs[rng.integers(0, 500, 16)]

    c_f32 = knn_ops.build_corpus(vecs, metric=sim.COSINE, dtype="f32")
    c_int8 = knn_ops.build_corpus(vecs, metric=sim.COSINE, dtype="int8",
                                  residual=False)
    s_ref, i_ref = knn_ops.knn_search(jnp.asarray(queries), c_f32, 10,
                                      metric=sim.COSINE, precision="f32")
    s_q, i_q = knn_ops.knn_search(jnp.asarray(queries), c_int8, 10,
                                  metric=sim.COSINE, precision="f32")
    s_ref, i_ref = np.asarray(s_ref), np.asarray(i_ref)
    s_q, i_q = np.asarray(s_q), np.asarray(i_q)
    # dense clusters have near-ties below the quantization step, so the
    # top-10 *sets* may legitimately differ; parity means the int8 picks
    # are near-optimal under exact f32 scoring
    qn = queries / np.linalg.norm(queries, axis=1, keepdims=True)
    vn = vecs / np.linalg.norm(vecs, axis=1, keepdims=True)
    exact = qn @ vn.T
    for qi in range(16):
        kth_best = np.sort(exact[qi])[-10]
        picked = exact[qi][i_q[qi]]
        # every int8-selected neighbor scores within the int8 error
        # envelope of the true 10th-best
        assert (picked >= kth_best - 0.01).all(), \
            f"query {qi}: int8 picked a non-near-optimal neighbor"
        # and the reported int8 scores match exact f32 scores elementwise
        np.testing.assert_allclose(s_q[qi], picked, atol=0.01)
