"""Cross-cluster search + replication over the REAL binary transport.

Two separately-booted server processes (distinct clusters, each binding
HTTP + transport sockets); the local cluster connects sniff-mode via
`cluster.remote.<alias>.seeds` and everything crosses actual TCP:

- CCS merges local and remote hits (`RemoteClusterService.java`,
  `SniffConnectionStrategy.java`, one-request-per-cluster like
  `ccs_minimize_roundtrips`)
- CCR followers converge by polling ShardChanges RPCs
  (`ShardChangesAction.java:59`)
- killing the remote degrades per `skip_unavailable`
  (RemoteClusterService contract)
- `_remote/info` reports the truth (mode sniff, seeds, connectivity)
"""

import json
import os
import signal
import socket
import subprocess
import sys
import time
import urllib.error
import urllib.request

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _free_ports(n):
    socks, ports = [], []
    for _ in range(n):
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        socks.append(s)
        ports.append(s.getsockname()[1])
    for s in socks:
        s.close()
    return ports


def _req(method, url, body=None, timeout=15):
    data = json.dumps(body).encode() if body is not None else None
    r = urllib.request.Request(url, data=data, method=method,
                               headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(r, timeout=timeout) as resp:
        return json.loads(resp.read())


def _wait_up(port, deadline_s=90):
    deadline = time.monotonic() + deadline_s
    while time.monotonic() < deadline:
        try:
            _req("GET", f"http://127.0.0.1:{port}/")
            return
        except Exception:
            time.sleep(0.5)
    raise AssertionError(f"server on {port} never came up")


@pytest.fixture(scope="module")
def two_clusters(tmp_path_factory):
    """local + east: one server process each, transports bound."""
    tmp = tmp_path_factory.mktemp("wire_ccs")
    http_ports = _free_ports(2)
    tp_ports = _free_ports(2)
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    procs = []
    for i, name in enumerate(["local", "east"]):
        cmd = [sys.executable, "-m", "elasticsearch_tpu.server",
               "--port", str(http_ports[i]), "--name", f"{name}-0",
               "--cluster-name", name,
               "--data", str(tmp / name),
               "-E", f"transport.port={tp_ports[i]}"]
        procs.append(subprocess.Popen(
            cmd, cwd=REPO, env=env,
            stdout=open(tmp / f"{name}.log", "w"), stderr=subprocess.STDOUT))
    for p in http_ports:
        _wait_up(p)
    yield http_ports, tp_ports, procs, tmp
    for p in procs:
        if p.poll() is None:
            p.send_signal(signal.SIGTERM)
    for p in procs:
        try:
            p.wait(timeout=10)
        except subprocess.TimeoutExpired:
            p.kill()


def test_ccs_ccr_over_the_wire(two_clusters):
    http_ports, tp_ports, procs, tmp = two_clusters
    local, east = (f"http://127.0.0.1:{p}" for p in http_ports)

    # --- seed data on both clusters -------------------------------------
    _req("PUT", f"{east}/logs/_doc/r1",
         {"msg": "hello from east", "n": 1})
    _req("PUT", f"{east}/logs/_doc/r2", {"msg": "east only", "n": 2})
    _req("POST", f"{east}/logs/_refresh")
    _req("PUT", f"{local}/logs/_doc/l1",
         {"msg": "hello from local", "n": 3})
    _req("POST", f"{local}/logs/_refresh")

    # --- register the remote via cluster settings (sniff seeds) ---------
    _req("PUT", f"{local}/_cluster/settings", {"persistent": {
        "cluster.remote.east.seeds": [f"127.0.0.1:{tp_ports[1]}"],
        "cluster.remote.east.skip_unavailable": "true"}})

    # --- CCS: pure-remote then mixed merge ------------------------------
    r = _req("POST", f"{local}/east:logs/_search",
             {"query": {"match": {"msg": "east"}}})
    assert r["hits"]["total"]["value"] == 2
    assert all(h["_index"] == "east:logs" for h in r["hits"]["hits"])

    r = _req("POST", f"{local}/logs,east:logs/_search",
             {"query": {"match": {"msg": "hello"}}})
    assert r["hits"]["total"]["value"] == 2
    assert {h["_index"] for h in r["hits"]["hits"]} == {"logs", "east:logs"}
    assert r["_clusters"] == {"total": 2, "successful": 2, "skipped": 0}

    # --- _remote/info reports the truth ---------------------------------
    info = _req("GET", f"{local}/_remote/info")
    assert info["east"]["connected"] is True
    assert info["east"]["mode"] == "sniff"
    assert info["east"]["seeds"] == [f"127.0.0.1:{tp_ports[1]}"]
    assert info["east"]["num_nodes_connected"] == 1
    assert info["east"]["skip_unavailable"] is True

    # --- CCR: follow, converge, tail new ops, deletes -------------------
    r = _req("PUT", f"{local}/logs_copy/_ccr/follow",
             {"remote_cluster": "east", "leader_index": "logs"})
    assert r["follow_index_created"] is True
    _req("POST", f"{local}/logs_copy/_refresh")
    r = _req("POST", f"{local}/logs_copy/_search", {})
    assert r["hits"]["total"]["value"] == 2

    _req("PUT", f"{east}/logs/_doc/r3", {"msg": "late arrival", "n": 9})
    _req("DELETE", f"{east}/logs/_doc/r2")
    _req("POST", f"{east}/logs/_refresh")
    _req("POST", f"{local}/_ccr/_tick")  # scheduler tick
    _req("POST", f"{local}/logs_copy/_refresh")
    r = _req("POST", f"{local}/logs_copy/_search", {"size": 10})
    ids = {h["_id"] for h in r["hits"]["hits"]}
    assert ids == {"r1", "r3"}

    stats = _req("GET", f"{local}/_ccr/stats")
    shard = stats["follow_stats"]["indices"][0]["shards"][0]
    assert shard["remote_cluster"] == "east"
    assert shard["follower_global_checkpoint"] >= 2

    # --- kill the remote: skip_unavailable degrades gracefully ----------
    procs[1].send_signal(signal.SIGTERM)
    procs[1].wait(timeout=10)
    r = _req("POST", f"{local}/logs,east:logs/_search",
             {"query": {"match": {"msg": "hello"}}})
    assert r["hits"]["total"]["value"] == 1  # local hit only
    assert r["_clusters"]["skipped"] == 1
    assert r["_clusters"]["successful"] == 1

    info = _req("GET", f"{local}/_remote/info")
    assert info["east"]["connected"] is False

    # --- without skip_unavailable the search fails ----------------------
    _req("PUT", f"{local}/_cluster/settings", {"persistent": {
        "cluster.remote.east.skip_unavailable": "false"}})
    with pytest.raises(urllib.error.HTTPError):
        _req("POST", f"{local}/logs,east:logs/_search",
             {"query": {"match": {"msg": "hello"}}})


def test_ccs_from_clustered_deployment(tmp_path_factory=None, tmp_path=None):
    """CCS from a CLUSTERED local deployment (2 coordinated processes) to
    a remote single-node cluster: remote settings applied dynamically via
    the cluster-authoritative PUT /_cluster/settings override, searches
    merged over the wire."""
    import tempfile
    tmp = tempfile.mkdtemp(prefix="wire_ccs_clustered")
    http_ports = _free_ports(3)
    tp_ports = _free_ports(3)
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    seeds = ",".join(f"127.0.0.1:{p}" for p in tp_ports[:2])
    procs = []
    # 2-node clustered "local"
    for i in range(2):
        procs.append(subprocess.Popen(
            [sys.executable, "-m", "elasticsearch_tpu.server",
             "--port", str(http_ports[i]), "--name", f"n{i}",
             "--cluster-name", "local",
             "--data", os.path.join(tmp, f"n{i}"),
             "-E", f"transport.port={tp_ports[i]}",
             "-E", f"discovery.seed_hosts={seeds}",
             "-E", "cluster.initial_master_nodes=n0,n1"],
            cwd=REPO, env=env,
            stdout=open(os.path.join(tmp, f"n{i}.log"), "w"),
            stderr=subprocess.STDOUT))
    # single-node "east"
    procs.append(subprocess.Popen(
        [sys.executable, "-m", "elasticsearch_tpu.server",
         "--port", str(http_ports[2]), "--name", "east-0",
         "--cluster-name", "east",
         "--data", os.path.join(tmp, "east"),
         "-E", f"transport.port={tp_ports[2]}"],
        cwd=REPO, env=env,
        stdout=open(os.path.join(tmp, "east.log"), "w"),
        stderr=subprocess.STDOUT))
    try:
        for p in http_ports:
            _wait_up(p)
        local = f"http://127.0.0.1:{http_ports[0]}"
        east = f"http://127.0.0.1:{http_ports[2]}"
        # wait for the 2-node cluster to form
        deadline = time.monotonic() + 90
        while time.monotonic() < deadline:
            try:
                h = _req("GET", f"{local}/_cluster/health")
                if h.get("number_of_nodes") == 2:
                    break
            except Exception:
                pass
            time.sleep(0.5)

        _req("PUT", f"{east}/logs/_doc/1", {"msg": "east doc"})
        _req("POST", f"{east}/logs/_refresh")
        _req("PUT", f"{local}/logs/_doc/1", {"msg": "local doc"})
        _req("POST", f"{local}/logs/_refresh")

        _req("PUT", f"{local}/_cluster/settings", {"persistent": {
            "cluster.remote.east.seeds": [f"127.0.0.1:{tp_ports[2]}"]}})
        info = _req("GET", f"{local}/_remote/info")
        assert "east" in info and info["east"]["mode"] == "sniff"

        r = _req("POST", f"{local}/logs,east:logs/_search",
                 {"query": {"match": {"msg": "doc"}}})
        assert r["hits"]["total"]["value"] == 2
        assert {h["_index"] for h in r["hits"]["hits"]} \
            == {"logs", "east:logs"}
    finally:
        for p in procs:
            if p.poll() is None:
                p.send_signal(signal.SIGTERM)
        for p in procs:
            try:
                p.wait(timeout=10)
            except subprocess.TimeoutExpired:
                p.kill()
