"""Engine tests: CRUD, versioning, refresh/NRT, translog recovery, merge."""

import numpy as np
import pytest

from elasticsearch_tpu.common.errors import (
    DocumentMissingError, MapperParsingError, VersionConflictError,
)
from elasticsearch_tpu.index.engine import Engine
from elasticsearch_tpu.index.mapping import MapperService
from elasticsearch_tpu.index.translog import Translog, TranslogCorruptedError

MAPPING = {
    "properties": {
        "title": {"type": "text", "analyzer": "standard"},
        "tag": {"type": "keyword"},
        "views": {"type": "long"},
        "price": {"type": "float"},
        "published": {"type": "date"},
        "active": {"type": "boolean"},
        "embedding": {"type": "dense_vector", "dims": 4, "similarity": "cosine"},
    }
}


@pytest.fixture
def engine(tmp_path):
    e = Engine(str(tmp_path / "shard0"), MapperService(MAPPING))
    yield e
    e.close()


def test_index_and_get(engine):
    r = engine.index("1", {"title": "hello world", "views": 10})
    assert r.result == "created" and r.version == 1 and r.seq_no == 0
    doc = engine.get("1")
    assert doc["_source"]["title"] == "hello world"
    assert doc["_version"] == 1
    # realtime: visible before refresh
    assert engine.get("1", realtime=True) is not None


def test_update_and_versioning(engine):
    engine.index("1", {"title": "v1"})
    r2 = engine.index("1", {"title": "v2"})
    assert r2.result == "updated" and r2.version == 2
    assert engine.get("1")["_source"]["title"] == "v2"
    assert engine.doc_count() == 1


def test_op_type_create_conflict(engine):
    engine.index("1", {"title": "x"})
    with pytest.raises(VersionConflictError):
        engine.index("1", {"title": "y"}, op_type="create")


def test_if_seq_no_conflict(engine):
    r = engine.index("1", {"title": "x"})
    engine.index("1", {"title": "y"})  # bumps seq_no
    with pytest.raises(VersionConflictError):
        engine.index("1", {"title": "z"}, if_seq_no=r.seq_no, if_primary_term=r.primary_term)


def test_external_versioning(engine):
    engine.index("1", {"title": "x"}, version=5, version_type="external")
    with pytest.raises(VersionConflictError):
        engine.index("1", {"title": "y"}, version=4, version_type="external")
    r = engine.index("1", {"title": "z"}, version=9, version_type="external")
    assert r.version == 9


def test_delete(engine):
    engine.index("1", {"title": "x"})
    r = engine.delete("1")
    assert r.result == "deleted"
    assert engine.get("1") is None
    assert engine.doc_count() == 0
    with pytest.raises(DocumentMissingError):
        engine.delete("1")


def test_refresh_visibility(engine):
    engine.index("1", {"title": "the quick brown fox"})
    reader = engine.acquire_searcher()
    # was refreshed at engine init; new doc is in the builder, not the reader
    assert reader.num_docs == 0
    reader = engine.refresh()
    assert reader.num_docs == 1
    p = reader.views[0].segment.get_postings("title", "quick")
    assert p is not None and p.doc_freq == 1


def test_deletes_visible_in_reader(engine):
    engine.index("1", {"tag": "a"})
    engine.index("2", {"tag": "b"})
    engine.refresh()
    engine.delete("1")
    reader = engine.refresh()
    assert reader.num_docs == 1
    rows = reader.live_global_rows()
    assert all(reader.get_id(r) == "2" for r in rows)


def test_translog_recovery(tmp_path):
    path = str(tmp_path / "shard")
    e = Engine(path, MapperService(MAPPING))
    e.index("1", {"title": "persisted"})
    e.index("2", {"title": "also persisted"})
    e.delete("1")
    e.close()
    # reopen WITHOUT flush: everything must come back from the translog
    e2 = Engine(path, MapperService(MAPPING))
    assert e2.doc_count() == 1
    assert e2.get("2")["_source"]["title"] == "also persisted"
    assert e2.get("1") is None
    assert e2.local_checkpoint == 2
    e2.close()


def test_flush_and_recovery(tmp_path):
    path = str(tmp_path / "shard")
    e = Engine(path, MapperService(MAPPING))
    for i in range(5):
        e.index(str(i), {"title": f"doc {i}", "views": i})
    e.flush()
    e.index("9", {"title": "after flush"})
    e.close()
    e2 = Engine(path, MapperService(MAPPING))
    assert e2.doc_count() == 6
    assert e2.get("9") is not None
    assert e2.get("3")["_source"]["views"] == 3
    e2.close()


def test_merge_compacts(engine):
    for i in range(10):
        engine.index(str(i), {"tag": f"t{i}"})
    engine.refresh()
    for i in range(5):
        engine.delete(str(i))
    engine.index("3", {"tag": "resurrected"})
    engine.refresh()
    assert len(engine.segments) == 2
    engine.merge()
    assert len(engine.segments) == 1
    reader = engine.acquire_searcher()
    assert reader.num_docs == 6  # 5 survivors + resurrected "3"
    assert engine.get("3")["_source"]["tag"] == "resurrected"
    assert engine.get("4") is None


def test_replica_out_of_order(engine):
    engine.index("1", {"title": "new"}, seq_no=5, primary_term=1, version=2, origin="replica")
    r = engine.index("1", {"title": "old"}, seq_no=3, primary_term=1, version=1, origin="replica")
    assert r.result == "noop"
    assert engine.get("1")["_source"]["title"] == "new"


def test_vector_field(engine):
    engine.index("1", {"embedding": [1.0, 0.0, 0.0, 0.0], "title": "v"})
    reader = engine.refresh()
    seg = reader.views[0].segment
    mat, present = seg.vectors["embedding"]
    assert mat.shape == (1, 4) and present[0]
    np.testing.assert_allclose(mat[0], [1, 0, 0, 0])
    with pytest.raises(MapperParsingError):
        engine.index("2", {"embedding": [1.0, 2.0]})  # wrong dims


def test_translog_corruption_detected(tmp_path):
    t = Translog(str(tmp_path / "tl"))
    t.add({"op": "index", "id": "1", "seq_no": 0, "source": {"a": 1}})
    t.close()
    # flip a byte in the payload
    path = str(tmp_path / "tl" / "translog-1.tlog")
    data = bytearray(open(path, "rb").read())
    data[3] ^= 0xFF
    open(path, "wb").write(bytes(data))
    t2 = Translog(str(tmp_path / "tl"))
    with pytest.raises(TranslogCorruptedError):
        t2.read_ops(0)
    t2.close()


def test_mapping_dynamic_and_multifield(tmp_path):
    ms = MapperService({"properties": {}})
    e = Engine(str(tmp_path / "s"), ms)
    e.index("1", {"title": "Some Text Here", "count": 7, "score": 1.5, "flag": True})
    assert ms.get("title").type_name == "text"
    assert ms.get("title.keyword").type_name == "keyword"
    assert ms.get("count").type_name == "long"
    assert ms.get("score").type_name == "float"
    assert ms.get("flag").type_name == "boolean"
    reader = e.refresh()
    # keyword multi-field indexed the raw string
    p = reader.views[0].segment.get_postings("title.keyword", "Some Text Here")
    assert p is not None
    e.close()


def test_mapping_render_roundtrip():
    ms = MapperService(MAPPING)
    rendered = ms.to_dict()
    assert rendered["properties"]["embedding"]["type"] == "dense_vector"
    assert rendered["properties"]["embedding"]["dims"] == 4
    ms2 = MapperService(rendered)
    assert ms2.get("embedding").dims == 4
