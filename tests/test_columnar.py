"""Columnar segment block store (`elasticsearch_tpu/columnar/`).

Pins the PR 13 contract:
* byte parity — store-backed compositions are identical to the three
  retired private extractors (vector rows + row_map, agg value/ordinal
  columns, BM25 CSR) across append / delete / merge-style segment
  rewrite;
* O(delta) refresh — append-only refreshes extract ONLY delta segments,
  for all three consumers, counter-pinned (zero full-corpus
  compositions after first build);
* merge-does-not-pin — no device generation retains a private
  corpus-sized host array after seal or merge; blocks are zero-copy
  onto the engine segments where tombstones allow;
* eviction — dropping a segment releases its blocks (weak-keyed);
* dp-aware HBM budgeting (`parallel/policy.eligible`) — replication
  eligibility accounts dp× device bytes;
* stats/profile wiring — `_nodes/stats indices.columnar` and the
  `columnar` annotations in `profile.knn` / aggs profile.
"""

import gc as _gc
import json

import numpy as np
import pytest

from elasticsearch_tpu import columnar
from elasticsearch_tpu.index.mapping import DenseVectorFieldMapper
from elasticsearch_tpu.index.segment import (
    DocValuesColumn, Segment, SegmentView, ShardReader)
from elasticsearch_tpu.vectors.store import (
    VectorStoreShard, device_corpus_nbytes, extract_field_rows)

SEED = 7
DIMS = 12


def _seg(seg_id, base, mat, doc_values=None, deleted=None):
    n = mat.shape[0]
    return Segment(
        seg_id=seg_id, base=base, num_docs=n, postings={},
        field_lengths={}, total_terms={},
        doc_values=doc_values or {},
        vectors={"v": (mat, np.ones(n, dtype=bool))},
        ids=[f"d{base + i}" for i in range(n)], sources=[None] * n,
        seq_nos=np.arange(base, base + n, dtype=np.int64))


def _mapper():
    return DenseVectorFieldMapper(
        "v", {"type": "dense_vector", "dims": DIMS,
              "similarity": "cosine"})


def _oracle_vector_rows(reader, field):
    """The RETIRED extract_field_rows loop, verbatim — the parity
    oracle for the store-backed composition."""
    mats, rows = [], []
    for view in reader.views:
        seg = view.segment
        if field not in seg.vectors:
            continue
        mat, present = seg.vectors[field]
        keep = present & view.live
        locs = np.nonzero(keep)[0]
        if len(locs):
            mats.append(np.asarray(mat[locs], dtype=np.float32))
            rows.append(locs.astype(np.int64) + seg.base)
    if not mats:
        return (np.zeros((0, 0), dtype=np.float32),
                np.zeros(0, dtype=np.int64))
    return np.concatenate(mats, axis=0), np.concatenate(rows)


def _oracle_values_column(view, field, want_objs):
    """The RETIRED ops/aggs._extract_segment_column loop, verbatim."""
    seg = view.segment
    n_live = int(view.live.sum())
    col = seg.doc_values.get(field)
    vals = np.full(n_live, np.nan, dtype=np.float64)
    present = np.zeros(n_live, dtype=bool)
    objs = np.empty(n_live, dtype=object) if want_objs else None
    multi = False
    if col is not None and n_live:
        live_idx = np.nonzero(view.live)[0]
        raw = None
        if want_objs or col.numeric is None:
            raw = np.empty(n_live, dtype=object)
            for i, loc in enumerate(live_idx):
                v = col.values[int(loc)]
                raw[i] = v
                if isinstance(v, list):
                    multi = True
            if want_objs:
                objs = raw
        else:
            multi = any(isinstance(col.values[int(loc)], list)
                        for loc in live_idx)
        if col.numeric is not None:
            vals[:] = col.numeric[live_idx]
            present[:] = col.present[live_idx]
            vals[~present] = np.nan
        else:
            for i in range(n_live):
                v = raw[i]
                if isinstance(v, list):
                    v = v[0] if v else None
                if v is None:
                    continue
                if isinstance(v, bool):
                    vals[i] = 1.0 if v else 0.0
                    present[i] = True
                elif isinstance(v, (int, float)):
                    vals[i] = float(v)
                    present[i] = True
    return vals, present, objs, multi


# ---------------------------------------------------------------------------
# byte parity vs the retired extractors
# ---------------------------------------------------------------------------


class TestVectorParity:
    def _check(self, reader):
        full, rows = extract_field_rows(reader, "v")
        o_full, o_rows = _oracle_vector_rows(reader, "v")
        assert full.tobytes() == o_full.tobytes()
        assert np.array_equal(rows, o_rows)

    def test_append_delete_rewrite_lifecycle(self):
        rng = np.random.default_rng(SEED)
        mats = [rng.standard_normal((n, DIMS)).astype(np.float32)
                for n in (17, 9, 5)]
        s0, s1 = _seg(0, 0, mats[0]), _seg(1, 17, mats[1])
        self._check(ShardReader([SegmentView(s0)]))
        # append
        self._check(ShardReader([SegmentView(s0), SegmentView(s1)]))
        # delete (tombstones in an existing segment)
        self._check(ShardReader([SegmentView(s0, {2, 11}),
                                 SegmentView(s1)]))
        # more appends on top of the tombstoned view
        s2 = _seg(2, 26, mats[2])
        self._check(ShardReader([SegmentView(s0, {2, 11}),
                                 SegmentView(s1), SegmentView(s2)]))
        # engine merge/rewrite: one combined segment, new id, re-based
        merged = _seg(7, 0, np.concatenate(
            [np.delete(mats[0], [2, 11], axis=0), mats[1], mats[2]]))
        self._check(ShardReader([SegmentView(merged)]))

    def test_zero_copy_when_clean(self):
        rng = np.random.default_rng(SEED)
        mat = rng.standard_normal((8, DIMS)).astype(np.float32)
        s = _seg(11, 0, mat)
        view = columnar.STORE.vector_view(ShardReader([SegmentView(s)]),
                                          "v")
        assert len(view.blocks) == 1
        blk = view.blocks[0]
        assert blk.zero_copy
        assert np.shares_memory(blk.matrix, s.vectors["v"][0])
        # the store's added-RAM accounting excludes the shared matrix
        assert blk.nbytes == blk.rows.nbytes

    def test_empty_field_shape_matches_retired_extractor(self):
        s = Segment(seg_id=21, base=0, num_docs=3, postings={},
                    field_lengths={}, total_terms={}, doc_values={},
                    vectors={}, ids=["a", "b", "c"], sources=[None] * 3,
                    seq_nos=np.arange(3, dtype=np.int64))
        full, rows = extract_field_rows(
            ShardReader([SegmentView(s)]), "v")
        assert full.shape == (0, 0) and full.dtype == np.float32
        assert rows.shape == (0,) and rows.dtype == np.int64


class TestAggColumnParity:
    def _dv_seg(self, seg_id, base, values):
        n = len(values)
        mat = np.zeros((n, DIMS), dtype=np.float32)
        return _seg(seg_id, base, mat,
                    doc_values={"f": DocValuesColumn(list(values))})

    @pytest.mark.parametrize("want_objs", [False, True])
    def test_block_matches_retired_loop(self, want_objs):
        segs = [
            self._dv_seg(0, 0, [1, None, 3.5, [7, 8], 2]),
            self._dv_seg(1, 5, ["x", True, None, [True], 4]),
            self._dv_seg(2, 10, [10, 11, 12]),
        ]
        views = [SegmentView(segs[0], {1}), SegmentView(segs[1]),
                 SegmentView(segs[2])]
        for view in views:
            blk, _ = columnar.STORE.values_block(view, "f", want_objs)
            vals, present, objs, multi = _oracle_values_column(
                view, "f", want_objs)
            assert blk.vals.tobytes() == vals.tobytes()
            assert np.array_equal(blk.present, present)
            assert blk.multi_valued == multi
            if want_objs:
                assert list(blk.objs) == list(objs)
            else:
                assert blk.objs is None

    def test_agg_store_column_across_append_and_delete(self):
        from elasticsearch_tpu.ops.aggs import AggFieldStore
        store = AggFieldStore(warmup=False)
        segs = [self._dv_seg(0, 0, [5, 2, None, 9]),
                self._dv_seg(1, 4, [1, 1, 3])]
        r1 = ShardReader([SegmentView(s) for s in segs])
        col1 = store.column(r1, "f", want_ords=True)
        # oracle composition over the same views
        parts = [_oracle_values_column(v, "f", True) for v in r1.views]
        o_vals = np.concatenate([p[0] for p in parts])
        assert col1.vals[:len(o_vals)].tobytes() == o_vals.tobytes()
        assert col1.ords is not None
        # append a segment, delete a row: delta rebuild stays identical
        segs.append(self._dv_seg(2, 7, [4, None, 2]))
        r2 = ShardReader([SegmentView(segs[0], {1}), SegmentView(segs[1]),
                          SegmentView(segs[2])])
        col2 = store.column(r2, "f", want_ords=True)
        parts = [_oracle_values_column(v, "f", True) for v in r2.views]
        o_vals = np.concatenate([p[0] for p in parts])
        o_present = np.concatenate([p[1] for p in parts])
        assert col2.vals[:len(o_vals)].tobytes() == o_vals.tobytes()
        assert np.array_equal(col2.present[:len(o_present)], o_present)
        assert store.columnar_refresh["f"]["mode"] == "delta"


class TestBm25CsrParity:
    def _node(self, tmp):
        from elasticsearch_tpu.node import Node
        node = Node(tmp)
        node.create_index_with_templates(
            "t", mappings={"properties": {"body": {"type": "text"}}})
        words = ["alpha", "beta", "gamma", "delta", "epsilon"]
        ops = []
        for i in range(60):
            ops.append({"index": {"_index": "t", "_id": str(i)}})
            ops.append({"body": " ".join(
                words[j % 5] for j in range(i % 7 + 1))})
        node.bulk(ops)
        node.indices.get("t").refresh()
        return node

    def test_cold_vs_warm_store_identical_csr(self, tmp_path):
        from elasticsearch_tpu.ops.bm25 import LexicalField
        node = self._node(str(tmp_path))
        try:
            reader = node.indices.get("t").shards[0] \
                .engine.acquire_searcher()
            warm = LexicalField("body")
            warm.sync(reader)          # extracts blocks into the store
            cold = LexicalField("body")
            cold.sync(reader)          # pure cache hits
            assert cold.columnar_refresh["mode"] == "cached"
            for attr in ("tile_slots", "tile_impacts", "row_map"):
                assert getattr(cold, attr).tobytes() == \
                    getattr(warm, attr).tobytes()
            assert cold.term_tiles == warm.term_tiles
            assert cold.nnz == warm.nnz
            # delete + append: re-extraction parity against a store
            # rebuilt from scratch on the same reader
            node.delete_doc("t", "3")
            ops = [{"index": {"_index": "t", "_id": "new1"}},
                   {"body": "alpha zeta zeta"}]
            node.bulk(ops)
            node.indices.get("t").refresh()
            reader2 = node.indices.get("t").shards[0] \
                .engine.acquire_searcher()
            warm.sync(reader2)
            fresh = LexicalField("body")
            fresh.sync(reader2)
            for attr in ("tile_slots", "tile_impacts", "row_map"):
                assert getattr(fresh, attr).tobytes() == \
                    getattr(warm, attr).tobytes()
            assert fresh.term_tiles == warm.term_tiles
        finally:
            node.close()


# ---------------------------------------------------------------------------
# O(delta) refresh: counter-pinned across all three consumers
# ---------------------------------------------------------------------------


class TestDeltaRefresh:
    def test_append_only_refresh_extracts_only_delta_segments(self):
        """After first build, append-only refreshes must classify as
        'delta' for every consumer and never add a 'full' composition —
        the acceptance counter for the O(delta) claim."""
        from elasticsearch_tpu.ops.aggs import AggFieldStore
        from elasticsearch_tpu.ops.bm25 import LexicalField
        rng = np.random.default_rng(SEED)
        mapper = _mapper()
        vstore = VectorStoreShard(segments_enabled=True,
                                  host_mirror_max_bytes=0,
                                  segments_background_merge=False)
        astore = AggFieldStore(warmup=False)
        segs = [_seg(0, 0, rng.standard_normal((32, DIMS))
                     .astype(np.float32),
                     doc_values={"f": DocValuesColumn(list(range(32)))})]
        vstore.sync(ShardReader([SegmentView(s) for s in segs]),
                    {"v": mapper})
        astore.column(ShardReader([SegmentView(s) for s in segs]), "f")
        base_stats = columnar.STORE.stats()
        full0 = base_stats["compositions"]["full"]
        extracts0 = base_stats["extracts"]
        n_appends = 3
        for i in range(n_appends):
            base = sum(s.num_docs for s in segs)
            segs.append(_seg(i + 1, base,
                             rng.standard_normal((8, DIMS))
                             .astype(np.float32),
                             doc_values={"f": DocValuesColumn(
                                 list(range(base, base + 8)))}))
            reader = ShardReader([SegmentView(s) for s in segs])
            vstore.sync(reader, {"v": mapper})
            assert vstore.columnar_refresh["v"]["mode"] == "delta"
            assert vstore.columnar_refresh["v"]["extracted"] == 1
            astore.column(reader, "f")
            assert astore.columnar_refresh["f"]["mode"] == "delta"
            assert astore.columnar_refresh["f"]["extracted"] == 1
        st = columnar.STORE.stats()
        # ZERO full-corpus compositions during append-only ingest
        assert st["compositions"]["full"] == full0
        # extraction volume is the delta segments alone (vector + values
        # per new segment)
        assert st["extracts"] - extracts0 == 2 * n_appends

    def test_absent_field_extraction_is_cached_not_recounted(self):
        """A segment without the field caches an absent marker: repeat
        syncs are cache hits, so the extracts ledger can't inflate in
        fully-cached steady state (and the composition reports
        cached, not full)."""
        rng = np.random.default_rng(SEED)
        seg = _seg(55, 0, rng.standard_normal((4, DIMS))
                   .astype(np.float32))
        reader = ShardReader([SegmentView(seg)])
        before = columnar.STORE.stats()["extracts"]
        v1 = columnar.STORE.vector_view(reader, "no_such_field")
        assert v1.n_rows == 0 and v1.refresh["mode"] == "full"
        v2 = columnar.STORE.vector_view(reader, "no_such_field")
        assert v2.refresh["mode"] == "cached"
        assert columnar.STORE.stats()["extracts"] == before + 1

    def test_bm25_append_only_is_delta(self, tmp_path):
        from elasticsearch_tpu.node import Node
        from elasticsearch_tpu.ops.bm25 import LexicalField
        node = Node(str(tmp_path))
        try:
            node.create_index_with_templates(
                "t2", mappings={"properties": {
                    "body": {"type": "text"}}})
            ops = []
            for i in range(20):
                ops.append({"index": {"_index": "t2", "_id": str(i)}})
                ops.append({"body": f"alpha beta tok{i % 4}"})
            node.bulk(ops)
            node.indices.get("t2").refresh()
            shard = node.indices.get("t2").shards[0]
            lf = LexicalField("body")
            lf.sync(shard.engine.acquire_searcher())
            full0 = columnar.STORE.stats()["compositions"]["full"]
            ops = [{"index": {"_index": "t2", "_id": "a1"}},
                   {"body": "alpha gamma"}]
            node.bulk(ops)
            node.indices.get("t2").refresh()
            lf.sync(shard.engine.acquire_searcher())
            assert lf.columnar_refresh["mode"] == "delta"
            assert lf.columnar_refresh["extracted"] == 1
            assert columnar.STORE.stats()["compositions"]["full"] == full0
        finally:
            node.close()


# ---------------------------------------------------------------------------
# merge does not pin
# ---------------------------------------------------------------------------


class TestMergeDoesNotPin:
    def test_no_generation_pins_a_private_host_array(self):
        """Seed + appends + merges: every live generation's host rows
        resolve through shared blocks (private bytes == 0), the base
        blocks are zero-copy onto the engine segments, and the merged
        serving output stays byte-identical to a monolithic store."""
        rng = np.random.default_rng(SEED)
        mapper = _mapper()
        gen_store = VectorStoreShard(segments_enabled=True,
                                     host_mirror_max_bytes=0,
                                     segments_background_merge=False,
                                     segments_tier_size=2,
                                     segments_max_l0=2)
        mono = VectorStoreShard(segments_enabled=False,
                                host_mirror_max_bytes=0)
        segs = [_seg(0, 0, rng.standard_normal((64, DIMS))
                     .astype(np.float32))]
        for i in range(4):
            base = sum(s.num_docs for s in segs)
            segs.append(_seg(i + 1, base,
                             rng.standard_normal((16, DIMS))
                             .astype(np.float32)))
            gen_store.sync(ShardReader([SegmentView(s) for s in segs]),
                           {"v": mapper})
        gc = gen_store._gens["v"]
        assert gc.run_merges() > 0
        snap = gc.snapshot()
        corpus_bytes = sum(s.num_docs for s in segs) * DIMS * 4
        for g in snap.generations:
            assert g.host_pinned_nbytes() == 0, \
                f"generation {g.gen_id} pins a private host array"
        # a merged generation's source still materializes correct rows
        merged = snap.generations[0]
        gathered = merged.source.gather()
        oracle = np.concatenate(
            [s.vectors["v"][0] for s in segs])[:merged.n_rows]
        assert gathered.tobytes() == oracle[:len(gathered)].tobytes()
        assert gathered.nbytes >= corpus_bytes // 2  # sanity: corpus-sized
        # serving byte parity vs the monolithic oracle
        mono.sync(ShardReader([SegmentView(s) for s in segs]),
                  {"v": mapper})
        for _ in range(3):
            q = rng.standard_normal(DIMS).astype(np.float32)
            a = gen_store.search("v", q, 10)
            b = mono.search("v", q, 10)
            assert np.array_equal(a[0], b[0])
            assert np.array_equal(a[1], b[1])

    def test_sealed_generation_source_reads_through_store(self):
        """An L0 seal's source points at the delta block (shared), not a
        private copy — and gathers the exact sealed rows."""
        rng = np.random.default_rng(SEED)
        mapper = _mapper()
        store = VectorStoreShard(segments_enabled=True,
                                 host_mirror_max_bytes=0,
                                 segments_background_merge=False)
        segs = [_seg(0, 0, rng.standard_normal((32, DIMS))
                     .astype(np.float32))]
        store.sync(ShardReader([SegmentView(s) for s in segs]),
                   {"v": mapper})
        delta = rng.standard_normal((8, DIMS)).astype(np.float32)
        segs.append(_seg(1, 32, delta))
        store.sync(ShardReader([SegmentView(s) for s in segs]),
                   {"v": mapper})
        snap = store._gens["v"].snapshot()
        assert len(snap.generations) == 2
        sealed = snap.generations[-1]
        assert sealed.host_pinned_nbytes() == 0
        assert sealed.source.gather().tobytes() == delta.tobytes()
        # zero-copy all the way down: the sealed source's matrix IS the
        # engine segment's array
        assert any(np.shares_memory(p.matrix, segs[1].vectors["v"][0])
                   for p in sealed.source.parts)


# ---------------------------------------------------------------------------
# eviction
# ---------------------------------------------------------------------------


class TestEviction:
    def test_dropped_segment_releases_blocks(self):
        rng = np.random.default_rng(SEED)
        seg = _seg(99, 0, rng.standard_normal((16, DIMS))
                   .astype(np.float32),
                   doc_values={"f": DocValuesColumn(list(range(16)))})
        reader = ShardReader([SegmentView(seg)])
        columnar.STORE.vector_view(reader, "v")
        columnar.STORE.values_block(reader.views[0], "f", False)
        before = columnar.STORE.stats()
        del reader, seg
        _gc.collect()
        after = columnar.STORE.stats()
        assert after["evictions"] >= before["evictions"] + 2
        assert after["blocks"] <= before["blocks"] - 2


# ---------------------------------------------------------------------------
# dp-aware HBM budgeting (PR 11 leftover c)
# ---------------------------------------------------------------------------


class TestHbmBudget:
    def test_eligibility_accounts_dp_times_device_bytes(self):
        from elasticsearch_tpu.parallel import policy
        import jax
        if len(jax.devices()) < 4:
            pytest.skip("needs a multi-device host")
        policy.reset(full=True)
        try:
            n_rows, dims = 100_000, 128
            bytes_one = device_corpus_nbytes(n_rows, dims, "bf16")
            policy.configure(enabled=True, min_rows=1, dp=2,
                             hbm_budget_bytes=bytes_one * 2)
            assert policy.serving_mesh() is not None
            # dp=2 × bytes_one fits the 2× budget exactly
            assert policy.eligible(n_rows, device_bytes=bytes_one)
            # a corpus whose replicated footprint exceeds it stays
            # single-device, and the rejection is counted
            assert not policy.eligible(n_rows,
                                       device_bytes=bytes_one + 1024)
            st = policy.stats()["hbm"]
            assert st["budget_bytes"] == bytes_one * 2
            assert st["rejections"] == 1
            assert st["last_rejected_bytes"] == (bytes_one + 1024) * 2
            assert st["accepted_bytes_high_water"] == bytes_one * 2
            # no budget configured → bytes are not a gate (legacy shape)
            policy.configure(hbm_budget_bytes=None)
            assert policy.eligible(n_rows, device_bytes=bytes_one * 100)
        finally:
            policy.reset(full=True)

    def test_device_corpus_nbytes_shapes(self):
        assert device_corpus_nbytes(1000, 64, "bf16") == \
            1000 * 64 * 2 + 4000
        assert device_corpus_nbytes(1000, 64, "int8") == \
            1000 * 64 + 4000 + 4000
        assert device_corpus_nbytes(0, 64, "f32") == 0


# ---------------------------------------------------------------------------
# stats + profile wiring
# ---------------------------------------------------------------------------


class TestStatsAndProfile:
    def test_node_stats_columnar_section_shape(self, tmp_path):
        from elasticsearch_tpu.node import Node
        node = Node(str(tmp_path))
        try:
            node.create_index_with_templates(
                "k", mappings={"properties": {
                    "v": {"type": "dense_vector", "dims": DIMS}}})
            rng = np.random.default_rng(SEED)
            ops = []
            for i in range(40):
                ops.append({"index": {"_index": "k", "_id": str(i)}})
                ops.append({"v": rng.standard_normal(DIMS).tolist()})
            node.bulk(ops)
            node.indices.get("k").refresh()
            st = node.local_node_stats()["indices"]["columnar"]
            for key in ("blocks", "bytes", "hits", "extracts",
                        "extract_nanos", "evictions", "compositions",
                        "fields", "zero_copy_blocks"):
                assert key in st
            assert st["extracts"] >= 1
            assert set(st["compositions"]) == {"cached", "delta", "full"}
            assert any(k.startswith("v:vector") for k in st["fields"])
        finally:
            node.close()

    def test_profile_knn_carries_columnar_annotation(self, tmp_path):
        from elasticsearch_tpu.node import Node
        node = Node(str(tmp_path))
        try:
            node.create_index_with_templates(
                "k2", mappings={"properties": {
                    "v": {"type": "dense_vector", "dims": DIMS}}})
            rng = np.random.default_rng(SEED)
            ops = []
            for i in range(30):
                ops.append({"index": {"_index": "k2", "_id": str(i)}})
                ops.append({"v": rng.standard_normal(DIMS).tolist()})
            node.bulk(ops)
            node.indices.get("k2").refresh()
            body = {"knn": {"field": "v",
                            "query_vector":
                                rng.standard_normal(DIMS).tolist(),
                            "k": 5, "num_candidates": 10},
                    "size": 5, "profile": True}
            resp = node.search("k2", body)
            prof = resp["profile"]["shards"][0]["knn"]
            assert "columnar" in prof
            assert prof["columnar"]["mode"] in ("full", "delta", "cached")
            assert prof["columnar"]["blocks"] >= 1
        finally:
            node.close()

    def test_aggs_profile_carries_columnar_annotation(self, tmp_path):
        from elasticsearch_tpu.node import Node
        node = Node(str(tmp_path))
        # the annotation is a device-path artifact (column builds); the
        # measured cost router would route this tiny corpus host
        node.settings["search.aggs.cost_router"] = "false"
        try:
            node.create_index_with_templates(
                "logs", mappings={"properties": {
                    "cat": {"type": "keyword"},
                    "val": {"type": "long"}}})
            ops = []
            for i in range(120):
                ops.append({"index": {"_index": "logs", "_id": str(i)}})
                ops.append({"cat": ["a", "b"][i % 2], "val": i})
            node.bulk(ops)
            node.indices.get("logs").refresh()
            body = {"size": 0, "profile": True,
                    "aggs": {"by": {"terms": {"field": "cat"}}}}
            resp = node.search("logs", json.loads(json.dumps(body)))
            shard = resp["profile"]["shards"][0]
            assert "columnar" in shard
            assert any(info["mode"] in ("full", "delta", "cached")
                       for info in shard["columnar"].values())
        finally:
            node.close()
