"""Multi-process cluster launcher: real data-node processes over TCP.

The in-process TCP tests (test_tcp_transport.py) prove the socket tier;
these prove the PROCESS tier — each data node is a separate interpreter
with its own engines and device corpus, the parent joins as coordinator
over the same wire protocol, and node death is a real SIGKILL rather
than a simulated transport partition.

Subprocess boot cost is dominated by interpreter + jax import, so the
whole module shares ONE launched cluster.
"""

import asyncio

import pytest

from elasticsearch_tpu.cluster.launcher import (
    DEFAULT_HOST, NodeProcess, default_host, find_free_ports, format_peers,
    join_cluster, launch_nodes, parse_peers,
)
from elasticsearch_tpu.cluster.state import ShardRoutingEntry


def test_peer_spec_roundtrip():
    peers = {"n0": ("127.0.0.1", 9300), "n1": ("127.0.0.1", 9301)}
    assert parse_peers(format_peers(peers)) == peers
    assert parse_peers("") == {}


def test_find_free_ports_distinct():
    ports = find_free_ports(4)
    assert len(set(ports)) == 4
    assert all(p > 0 for p in ports)


def test_bind_host_env_resolves_at_call_time(monkeypatch):
    monkeypatch.delenv("ES_TPU_BIND_HOST", raising=False)
    assert default_host() == DEFAULT_HOST
    monkeypatch.setenv("ES_TPU_BIND_HOST", "127.0.0.2")
    assert default_host() == "127.0.0.2"


def test_node_advertises_configured_bind_host(tmp_path, monkeypatch):
    """ES_TPU_BIND_HOST steers both the bound socket and the address the
    node publishes into the cluster state — the contract cross-machine
    topologies depend on (peers dial what the state advertises)."""
    monkeypatch.setenv("ES_TPU_BIND_HOST", "127.0.0.2")
    loop = asyncio.new_event_loop()
    asyncio.set_event_loop(loop)
    node = transport = None
    try:
        node, transport = join_cluster(
            "solo", str(tmp_path / "solo"), peers={}, masters=["solo"],
            loop=loop)
        deadline = loop.time() + 30.0
        while loop.time() < deadline:
            loop.run_until_complete(asyncio.sleep(0.02))
            if node.cluster_state.master_node_id == "solo":
                break
        me = node.cluster_state.nodes["solo"]
        assert me.address == f"127.0.0.2:{transport.port}"
    finally:
        if node is not None:
            try:
                node.stop()
            except Exception:
                pass
        if transport is not None:
            loop.run_until_complete(transport.close())
        loop.close()


class LaunchedCluster:
    """One in-process coordinator + N child data-node processes."""

    def __init__(self, tmp_path, loop, n_data=2):
        self.loop = loop
        data_ids = [f"d{i}" for i in range(n_data)]
        all_ids = ["coord"] + data_ids
        ports = find_free_ports(len(all_ids))
        self.peers = {nid: (DEFAULT_HOST, port)
                      for nid, port in zip(all_ids, ports)}
        self.procs = launch_nodes(
            data_ids, str(tmp_path), self.peers, masters=all_ids)
        self.node, self.transport = join_cluster(
            "coord", str(tmp_path / "coord"), self.peers,
            masters=all_ids, loop=loop)

    def run_until(self, cond, max_s=60.0):
        deadline = self.loop.time() + max_s
        while self.loop.time() < deadline:
            self.loop.run_until_complete(asyncio.sleep(0.02))
            if cond():
                return True
        return cond()

    def call(self, fn, *args, **kw):
        box = {}
        fn(*args, **kw, on_done=lambda r: box.update(r=r))
        assert self.run_until(lambda: "r" in box), \
            f"no response from {fn.__name__}"
        return box["r"]

    def close(self):
        for p in self.procs:
            p.terminate()
        try:
            self.node.stop()
        except Exception:
            pass
        self.loop.run_until_complete(self.transport.close())


@pytest.fixture(scope="module")
def launched(tmp_path_factory):
    loop = asyncio.new_event_loop()
    asyncio.set_event_loop(loop)
    cluster = LaunchedCluster(tmp_path_factory.mktemp("launcher"), loop)
    try:
        yield cluster
    finally:
        cluster.close()
        loop.close()


def test_multiprocess_cluster_serves_and_survives_sigkill(launched):
    c = launched
    # formation: master elected, all three processes in the node set
    assert c.run_until(
        lambda: c.node.cluster_state.master_node_id is not None
        and len(c.node.cluster_state.nodes) == 3), \
        "multi-process cluster did not form"

    c.node.client_create_index(
        "docs", settings={"index.number_of_shards": 2,
                          "index.number_of_replicas": 1},
        mappings={"properties": {"title": {"type": "text"},
                                 "n": {"type": "long"}}})

    def all_started():
        shards = c.node.cluster_state.shards_of("docs")
        return bool(shards) and all(
            s.state == ShardRoutingEntry.STARTED for s in shards)
    assert c.run_until(all_started), "shards did not start across processes"

    for i in range(12):
        r = c.call(c.node.client_write, "docs",
                   {"type": "index", "id": str(i),
                    "source": {"title": f"doc number {i}", "n": i}})
        assert r.get("result") in ("created", "updated"), r

    # transport-level broadcast refresh is the only way to reach engines
    # living in other processes
    refreshed = c.call(c.node.client_refresh, "docs")
    assert refreshed["_shards"]["failed"] == 0, refreshed

    resp = c.call(c.node.client_search, "docs",
                  {"query": {"match_all": {}}, "size": 20})
    assert resp["hits"]["total"]["value"] == 12

    # the docs live in child processes: bytes really crossed the kernel
    assert c.transport.stats["tx_bytes"] > 0

    # SIGKILL a data child that is not master; the cluster must keep
    # answering (each shard has a surviving copy on the other child or
    # the coordinator's replicas)
    master = c.node.cluster_state.master_node_id
    victim = next(p for p in c.procs if p.node_id != master)
    victim.kill()
    assert not victim.alive

    resp = c.call(c.node.client_search, "docs",
                  {"query": {"match_all": {}}, "size": 20})
    assert "hits" in resp  # returned — did not hang on the dead socket
