"""REST-level tests for the extended surface: ingest, scroll, async-search,
tasks, templates, reindex family, rank-eval, field caps, validate, explain,
suggesters, snapshots."""

import json
import time

import pytest

from elasticsearch_tpu.node import Node
from elasticsearch_tpu.rest.actions import register_all
from elasticsearch_tpu.rest.controller import RestController


class Client:
    def __init__(self, node):
        self.rc = RestController()
        register_all(self.rc, node)

    def req(self, method, path, body=None, **query):
        raw = b""
        if body is not None:
            if isinstance(body, (list, tuple)):
                raw = b"\n".join(json.dumps(l).encode() for l in body) + b"\n"
            else:
                raw = json.dumps(body).encode()
        return self.rc.dispatch(method, path, {k: str(v) for k, v in query.items()},
                                raw, "application/json")


@pytest.fixture
def node(tmp_path):
    n = Node(str(tmp_path / "data"))
    yield n
    n.close()


@pytest.fixture
def client(node):
    return Client(node)


def seed(client, n=25, index="logs"):
    for i in range(n):
        client.req("PUT", f"/{index}/_doc/{i}",
                   {"msg": f"event number {i}", "level": "error" if i % 5 == 0 else "info",
                    "n": i})
    client.req("POST", f"/{index}/_refresh")


# ---------------------------------------------------------------- ingest

def test_ingest_pipeline(client):
    status, _ = client.req("PUT", "/_ingest/pipeline/clean", {
        "description": "test",
        "processors": [
            {"set": {"field": "env", "value": "prod"}},
            {"rename": {"field": "raw", "target_field": "message"}},
            {"lowercase": {"field": "message"}},
            {"convert": {"field": "count", "type": "integer"}},
            {"split": {"field": "tags_csv", "separator": ",", "target_field": "tags"}},
            {"remove": {"field": "tags_csv"}},
        ]})
    assert status == 200
    status, body = client.req("PUT", "/idx/_doc/1",
                              {"raw": "HELLO World", "count": "42",
                               "tags_csv": "a,b,c"}, pipeline="clean", refresh="true")
    assert status == 201
    _, doc = client.req("GET", "/idx/_doc/1")
    assert doc["_source"] == {"env": "prod", "message": "hello world",
                              "count": 42, "tags": ["a", "b", "c"]}


def test_ingest_conditionals_drop_and_simulate(client):
    client.req("PUT", "/_ingest/pipeline/filter", {
        "processors": [
            {"drop": {"if": "ctx.level == 'debug'"}},
            {"set": {"field": "kept", "value": True}},
        ]})
    status, body = client.req("POST", "/_ingest/pipeline/filter/_simulate", {
        "docs": [{"_source": {"level": "debug"}},
                 {"_source": {"level": "error"}}]})
    assert body["docs"][0].get("dropped") is True
    assert body["docs"][1]["doc"]["_source"]["kept"] is True
    # dropped doc is not indexed
    r = client.req("PUT", "/d/_doc/1", {"level": "debug"}, pipeline="filter")
    assert r[1]["result"] == "noop"
    _, doc = client.req("GET", "/d/_doc/1")
    assert not doc["found"]


def test_ingest_default_pipeline_and_failure(client):
    client.req("PUT", "/_ingest/pipeline/strict", {
        "processors": [{"fail": {"message": "boom {{reason}}",
                                 "if": "ctx.bad == True"}}]})
    client.req("PUT", "/defp", {"settings": {"index.default_pipeline": "strict"}})
    status, _ = client.req("PUT", "/defp/_doc/1", {"ok": 1})
    assert status == 201
    status, body = client.req("PUT", "/defp/_doc/2", {"bad": True, "reason": "x"})
    assert status == 400
    assert "boom" in body["error"]["reason"]


def test_ingest_dissect_and_script(client):
    client.req("PUT", "/_ingest/pipeline/parse", {
        "processors": [
            {"dissect": {"field": "line", "pattern": "%{client} - %{verb} %{path}"}},
            {"script": {"source": "ctx.score = params.base + 1",
                        "params": {"base": 10}}},
        ]})
    _, body = client.req("POST", "/_ingest/pipeline/parse/_simulate", {
        "docs": [{"_source": {"line": "1.2.3.4 - GET /index.html"}}]})
    src = body["docs"][0]["doc"]["_source"]
    assert src["client"] == "1.2.3.4" and src["verb"] == "GET"
    assert src["score"] == 11


# ---------------------------------------------------------------- scroll

def test_scroll(client):
    seed(client, 25)
    status, page1 = client.req("POST", "/logs/_search",
                               {"size": 10, "sort": [{"n": "asc"}]}, scroll="1m")
    assert status == 200
    sid = page1["_scroll_id"]
    assert [h["_source"]["n"] for h in page1["hits"]["hits"]] == list(range(10))
    _, page2 = client.req("POST", "/_search/scroll", {"scroll_id": sid, "scroll": "1m"})
    assert [h["_source"]["n"] for h in page2["hits"]["hits"]] == list(range(10, 20))
    _, page3 = client.req("POST", "/_search/scroll", {"scroll_id": sid})
    assert [h["_source"]["n"] for h in page3["hits"]["hits"]] == list(range(20, 25))
    _, page4 = client.req("POST", "/_search/scroll", {"scroll_id": sid})
    assert page4["hits"]["hits"] == []
    status, body = client.req("DELETE", "/_search/scroll", {"scroll_id": sid})
    assert body["num_freed"] == 1
    status, _ = client.req("POST", "/_search/scroll", {"scroll_id": sid})
    assert status == 404


# ------------------------------------------------------------ async search

def test_async_search(client):
    seed(client, 10)
    status, body = client.req("POST", "/logs/_async_search",
                              {"query": {"match_all": {}}, "size": 3})
    assert status == 200
    sid = body["id"]
    deadline = time.time() + 5
    while body.get("is_running") and time.time() < deadline:
        time.sleep(0.05)
        _, body = client.req("GET", f"/_async_search/{sid}")
    assert body["is_running"] is False
    assert body["response"]["hits"]["total"]["value"] == 10
    status, _ = client.req("DELETE", f"/_async_search/{sid}")
    assert status == 200
    status, _ = client.req("GET", f"/_async_search/{sid}")
    assert status == 404


# ----------------------------------------------------------------- tasks

def test_tasks_api(client, node):
    t = node.tasks.register("indices:data/read/search", "test task")
    status, body = client.req("GET", "/_tasks")
    tasks = body["nodes"][node.node_id]["tasks"]
    assert t.task_id in tasks
    status, body = client.req("POST", f"/_tasks/{t.task_id}/_cancel")
    assert node.tasks.get(t.task_id).cancelled
    node.tasks.unregister(t)
    status, _ = client.req("GET", f"/_tasks/{t.task_id}")
    assert status == 404


# -------------------------------------------------------------- templates

def test_legacy_template_applied_on_autocreate(client):
    client.req("PUT", "/_template/logs_t", {
        "index_patterns": ["logs-*"],
        "settings": {"index.number_of_shards": 2},
        "mappings": {"properties": {"ts": {"type": "date"}}}})
    client.req("PUT", "/logs-2024/_doc/1", {"ts": "2024-01-01", "x": 1})
    _, body = client.req("GET", "/logs-2024")
    assert body["logs-2024"]["settings"]["index"]["number_of_shards"] == 2
    assert body["logs-2024"]["mappings"]["properties"]["ts"]["type"] == "date"


def test_composable_template_priority(client):
    client.req("PUT", "/_index_template/base", {
        "index_patterns": ["app-*"], "priority": 1,
        "template": {"settings": {"index.number_of_replicas": 0},
                     "mappings": {"properties": {"a": {"type": "keyword"}}}}})
    client.req("PUT", "/_index_template/override", {
        "index_patterns": ["app-prod-*"], "priority": 10,
        "template": {"mappings": {"properties": {"b": {"type": "long"}}}}})
    client.req("PUT", "/app-prod-1/_doc/1", {"a": "x", "b": 2})
    _, body = client.req("GET", "/app-prod-1")
    props = body["app-prod-1"]["mappings"]["properties"]
    assert props["a"]["type"] == "keyword" and props["b"]["type"] == "long"
    status, body = client.req("GET", "/_index_template/base")
    assert body["index_templates"][0]["name"] == "base"


# ---------------------------------------------------------- reindex family

def test_reindex_with_query_and_script(client):
    seed(client, 10, index="src")
    status, body = client.req("POST", "/_reindex", {
        "source": {"index": "src", "query": {"range": {"n": {"gte": 5}}}},
        "dest": {"index": "dst"},
        "script": {"source": "ctx._source.n = ctx._source.n * 10"}})
    assert status == 200 and body["created"] == 5
    _, body = client.req("GET", "/dst/_count")
    assert body["count"] == 5
    _, doc = client.req("GET", "/dst/_doc/7")
    assert doc["_source"]["n"] == 70


def test_update_and_delete_by_query(client):
    seed(client, 10, index="ud")
    status, body = client.req("POST", "/ud/_update_by_query", {
        "query": {"term": {"level": "error"}},
        "script": {"source": "ctx._source.flagged = true"}})
    assert body["updated"] == 2  # i=0,5
    client.req("POST", "/ud/_refresh")
    _, cnt = client.req("POST", "/ud/_count", {"query": {"term": {"flagged": True}}})
    assert cnt["count"] == 2
    status, body = client.req("POST", "/ud/_delete_by_query",
                              {"query": {"term": {"level": "error"}}})
    assert body["deleted"] == 2
    _, cnt = client.req("GET", "/ud/_count")
    assert cnt["count"] == 8


# ---------------------------------------------------- field caps / validate

def test_field_caps_validate_explain(client):
    seed(client, 5)
    _, body = client.req("GET", "/logs/_field_caps", fields="*")
    assert body["fields"]["n"]["long"]["aggregatable"] is True
    assert body["fields"]["msg"]["text"]["searchable"] is True

    _, body = client.req("POST", "/logs/_validate/query",
                         {"query": {"match": {"msg": "event"}}})
    assert body["valid"] is True
    _, body = client.req("POST", "/logs/_validate/query",
                         {"query": {"bogus": {}}})
    assert body["valid"] is False

    _, body = client.req("POST", "/logs/_explain/3",
                         {"query": {"match": {"msg": "event"}}})
    assert body["matched"] is True and body["explanation"]["value"] > 0
    _, body = client.req("POST", "/logs/_explain/3",
                         {"query": {"term": {"level": "error"}}})
    assert body["matched"] is False


# ------------------------------------------------------------- rank eval

def test_rank_eval(client):
    seed(client, 10)
    body = {
        "requests": [{
            "id": "q1",
            "request": {"query": {"term": {"level": "error"}}},
            "ratings": [
                {"_index": "logs", "_id": "0", "rating": 1},
                {"_index": "logs", "_id": "5", "rating": 1},
                {"_index": "logs", "_id": "1", "rating": 0},
            ]}],
        "metric": {"recall": {"k": 10}}}
    status, out = client.req("POST", "/logs/_rank_eval", body)
    assert status == 200
    assert out["metric_score"] == 1.0  # both relevant docs found
    body["metric"] = {"mean_reciprocal_rank": {"k": 10}}
    _, out = client.req("POST", "/logs/_rank_eval", body)
    assert out["metric_score"] == 1.0


# ------------------------------------------------------------- suggesters

def test_suggesters(client):
    for i, word in enumerate(["elastic", "elastic", "search", "searching", "engine"]):
        client.req("PUT", f"/s/_doc/{i}", {"body": word, "tag": word})
    client.req("POST", "/s/_refresh")
    _, body = client.req("POST", "/s/_search", {
        "size": 0,
        "suggest": {
            "fix": {"text": "elastik serch", "term": {"field": "body"}},
            "phrase_fix": {"text": "elastik serch", "phrase": {"field": "body"}},
            "auto": {"prefix": "sea", "completion": {"field": "tag"}},
        }})
    sug = body["suggest"]
    fix = sug["fix"]
    assert fix[0]["options"][0]["text"] == "elastic"
    assert fix[1]["options"][0]["text"] == "search"
    assert sug["phrase_fix"][0]["options"][0]["text"] == "elastic search"
    opts = [o["text"] for o in sug["auto"][0]["options"]]
    assert "search" in opts and "searching" in opts


# -------------------------------------------------------------- snapshots

def test_snapshot_and_restore(client, tmp_path):
    seed(client, 12, index="snap_src")
    repo_path = str(tmp_path / "repo")
    status, _ = client.req("PUT", "/_snapshot/backup",
                           {"type": "fs", "settings": {"location": repo_path}})
    assert status == 200
    status, body = client.req("PUT", "/_snapshot/backup/snap1", {"indices": "snap_src"})
    assert body["snapshot"]["state"] == "SUCCESS"

    # second snapshot of unchanged data dedups blobs (content-addressed)
    import os
    blobs_before = len(os.listdir(os.path.join(repo_path, "blobs")))
    client.req("PUT", "/_snapshot/backup/snap2", {"indices": "snap_src"})
    blobs_after = len(os.listdir(os.path.join(repo_path, "blobs")))
    assert blobs_after == blobs_before

    _, listing = client.req("GET", "/_snapshot/backup/_all")
    assert [s["snapshot"] for s in listing["snapshots"]] == ["snap1", "snap2"]

    status, body = client.req("POST", "/_snapshot/backup/snap1/_restore",
                              {"indices": "snap_src",
                               "rename_pattern": "snap_src",
                               "rename_replacement": "restored"})
    assert "restored" in body["snapshot"]["indices"]
    _, cnt = client.req("GET", "/restored/_count")
    assert cnt["count"] == 12
    _, doc = client.req("GET", "/restored/_doc/7")
    assert doc["found"] and doc["_source"]["n"] == 7

    # restoring over an existing open index is rejected
    status, body = client.req("POST", "/_snapshot/backup/snap1/_restore",
                              {"indices": "snap_src"})
    assert status == 400

    status, _ = client.req("DELETE", "/_snapshot/backup/snap2")
    _, listing = client.req("GET", "/_snapshot/backup/_all")
    assert [s["snapshot"] for s in listing["snapshots"]] == ["snap1"]

    # endpoint-less cloud repos, and SDK-dependent types, are gated clearly
    status, body = client.req("PUT", "/_snapshot/cloud",
                              {"type": "s3", "settings": {"bucket": "b"}})
    assert status == 400 and "endpoint" in body["error"]["reason"]
    status, body = client.req("PUT", "/_snapshot/cloud",
                              {"type": "gcs", "settings": {"bucket": "b"}})
    assert status == 400 and "endpoint" in body["error"]["reason"]
    status, body = client.req("PUT", "/_snapshot/cloud",
                              {"type": "hdfs", "settings": {}})
    assert status == 400 and "not available" in body["error"]["reason"]


def test_scroll_past_10k(client):
    """Scroll must page past the 10k result window (regression: truncation)."""
    ops = []
    for i in range(10_500):
        ops.append({"index": {"_index": "big", "_id": str(i)}})
        ops.append({"n": i})
    client.req("POST", "/_bulk", ops)
    client.req("POST", "/big/_refresh")
    _, page = client.req("POST", "/big/_search", {"size": 5000, "sort": [{"n": "asc"}]},
                         scroll="1m")
    sid = page["_scroll_id"]
    assert page["hits"]["total"]["value"] == 10_500
    seen = len(page["hits"]["hits"])
    while True:
        _, page = client.req("POST", "/_search/scroll", {"scroll_id": sid})
        assert page["hits"]["total"]["value"] == 10_500  # stable across pages
        if not page["hits"]["hits"]:
            break
        seen += len(page["hits"]["hits"])
    assert seen == 10_500


def test_ingest_cycle_detection(client):
    client.req("PUT", "/_ingest/pipeline/a", {
        "processors": [{"pipeline": {"name": "b"}}]})
    client.req("PUT", "/_ingest/pipeline/b", {
        "processors": [{"pipeline": {"name": "a"}}]})
    status, body = client.req("PUT", "/c/_doc/1", {"x": 1}, pipeline="a")
    assert status == 400
    assert "Cycle detected" in body["error"]["reason"]


def test_dissect_dotted_keys(client):
    client.req("PUT", "/_ingest/pipeline/dd", {
        "processors": [{"dissect": {"field": "line",
                                    "pattern": "%{client.ip} %{verb}"}}]})
    _, body = client.req("POST", "/_ingest/pipeline/dd/_simulate",
                         {"docs": [{"_source": {"line": "1.2.3.4 GET"}}]})
    src = body["docs"][0]["doc"]["_source"]
    assert src["client"]["ip"] == "1.2.3.4" and src["verb"] == "GET"


def test_reindex_pipeline_does_not_corrupt_source(client):
    client.req("PUT", "/_ingest/pipeline/tagger", {
        "processors": [{"append": {"field": "tags", "value": "copied"}},
                       {"set": {"field": "meta.copied", "value": True}}]})
    client.req("PUT", "/orig/_doc/1", {"tags": ["a"], "meta": {"x": 1}}, refresh="true")
    client.req("POST", "/_reindex", {"source": {"index": "orig"},
                                     "dest": {"index": "copy", "pipeline": "tagger"}})
    _, src_doc = client.req("GET", "/orig/_doc/1")
    assert src_doc["_source"] == {"tags": ["a"], "meta": {"x": 1}}, \
        "source index corrupted by reindex pipeline"
    _, dst_doc = client.req("GET", "/copy/_doc/1")
    assert dst_doc["_source"]["tags"] == ["a", "copied"]
    assert dst_doc["_source"]["meta"] == {"x": 1, "copied": True}
