"""End-to-end request telemetry (elasticsearch_tpu/telemetry/).

The contracts ISSUE 14 ships:

* histogram math — fixed log2 buckets must reproduce numpy percentiles
  within one bucket (the `_nodes/stats telemetry` fidelity claim);
* single-node tracing — `?trace=true` / a `profile` body forces a trace
  whose spans cover REST parse, query, fetch, merge; the completed trace
  lands in the per-node ring (`GET _nodes/traces`);
* the async batcher — queue-wait/dispatch/sync spans survive the
  pipelined batcher, coalesced FOLLOWERS link to the leader's batch span
  instead of double-counting device time, and task cancellation sheds
  queued entries at EDF admission exactly like expired deadlines;
* cross-node tracing — the trace context rides the PR-12 deadline
  envelope, remote segments parent under the coordinator's per-leg
  spans, a dead node's leg is an ERROR span (never a leak), and the
  device-path attribution (queue wait / dispatch / device sync /
  hydrate) sums consistently inside the trace — with zero added
  recompiles (checked here) and zero new host syncs (the tpulint
  TPU002/TPU009 gate in test_tpulint.py covers the instrumented
  modules);
* X-Opaque-ID — one header threads through tasks, traces, and slow-log
  breaches;
* REST/stats response shapes — `_tasks`, `_nodes/traces`,
  `_nodes/stats` telemetry + slowlog sections.
"""

import json
import threading
import time

import numpy as np
import pytest

from elasticsearch_tpu import telemetry
from elasticsearch_tpu.common.errors import TaskCancelledError
from elasticsearch_tpu.telemetry import TRACER, metrics
from elasticsearch_tpu.telemetry.metrics import (
    Histogram, bucket_index, percentile_from_counts,
)

DIMS = 4


@pytest.fixture(autouse=True)
def _fresh_tracer():
    TRACER.clear()
    prior = TRACER.sample_rate
    yield
    TRACER.configure(sample_rate=prior)
    TRACER.clear()


@pytest.fixture()
def node(tmp_path):
    from elasticsearch_tpu.node import Node
    n = Node(str(tmp_path / "n"),
             settings={"telemetry.tracing.sample_rate": 0.0})
    yield n
    n.close()


@pytest.fixture()
def rest(node):
    from elasticsearch_tpu.rest.actions import register_all
    from elasticsearch_tpu.rest.controller import RestController
    rc = RestController()
    register_all(rc, node)
    return rc


def _dispatch(rc, method, path, query=None, body=None, headers=None):
    raw = json.dumps(body).encode() if body is not None else b""
    return rc.dispatch(method, path, query or {}, raw,
                       "application/json", headers=headers)


def _seed(rc, index="idx", docs=8, vectors=False):
    props = {"a": {"type": "text"}, "n": {"type": "long"}}
    if vectors:
        props["v"] = {"type": "dense_vector", "dims": DIMS}
    st, _ = _dispatch(rc, "PUT", f"/{index}",
                      body={"mappings": {"properties": props}})
    assert st == 200
    rng = np.random.default_rng(5)
    for i in range(docs):
        doc = {"a": f"hello doc{i}", "n": i}
        if vectors:
            doc["v"] = rng.standard_normal(DIMS).tolist()
        st, _ = _dispatch(rc, "PUT", f"/{index}/_doc/{i}",
                          {"refresh": "true"}, doc)
        assert st in (200, 201)


# ---------------------------------------------------------------------------
# histogram math
# ---------------------------------------------------------------------------

def test_histogram_percentiles_within_one_log2_bucket_of_numpy():
    rng = np.random.default_rng(7)
    samples = np.exp(rng.normal(13.0, 2.0, size=5_000)).astype(np.int64)
    h = Histogram("t")
    for s in samples:
        h.record(int(s))
    for q in (0.50, 0.90, 0.99):
        ours = h.percentile(q)
        ref = float(np.percentile(samples, q * 100))
        assert abs(bucket_index(int(ours)) - bucket_index(int(ref))) <= 1, \
            f"q={q}: histogram {ours} vs numpy {ref}"


def test_histogram_snapshot_and_empty_percentiles():
    h = Histogram("t")
    assert h.percentile(0.99) == 0.0
    h.record(1000)
    snap = h.snapshot(raw=True)
    assert snap["count"] == 1 and snap["sum_nanos"] == 1000
    assert snap["max_nanos"] == 1000
    assert len(snap["counts"]) == metrics.N_BUCKETS
    assert percentile_from_counts(snap["counts"], 0.5) <= 1024


def test_registry_snapshot_shapes():
    reg = metrics.MetricsRegistry()
    reg.counter("c").inc(3)
    reg.gauge("g").set(2.5)
    reg.histogram("h").record(10)
    snap = reg.snapshot()
    assert snap["counters"]["c"] == 3
    assert snap["gauges"]["g"] == 2.5
    assert snap["histograms"]["h"]["count"] == 1


# ---------------------------------------------------------------------------
# single-node tracing through REST
# ---------------------------------------------------------------------------

def test_forced_trace_spans_and_ring(rest, node):
    _seed(rest, docs=4)
    st, resp = _dispatch(rest, "POST", "/idx/_search", {"trace": "true"},
                         {"query": {"match": {"a": "hello"}}},
                         headers={"x-opaque-id": "op-7"})
    assert st == 200 and resp["hits"]["total"]["value"] == 4
    traces = TRACER.traces(node_id=node.node_id)
    assert len(traces) == 1
    tr = traces[0]
    assert tr["action"] == "indices:data/read/search"
    assert tr["opaque_id"] == "op-7"
    assert tr["took_ns"] > 0
    names = [s["name"] for s in tr["spans"]]
    for expected in ("rest.parse", "query[idx]", "fetch[idx]", "merge"):
        assert expected in names, f"{expected} missing from {names}"
    # every span is closed (no leaks) and parents resolve inside the trace
    ids = {s["span_id"] for s in tr["spans"]}
    for s in tr["spans"]:
        assert s["dur_ns"] is not None, f"leaked span {s['name']}"
        assert s["parent_id"] is None or s["parent_id"] in ids


def test_profile_body_forces_trace_and_profile_trace_section(rest, node):
    _seed(rest, docs=4)
    st, resp = _dispatch(rest, "POST", "/idx/_search", {},
                         {"query": {"match_all": {}}, "profile": True})
    assert st == 200
    prof_trace = resp["profile"]["trace"]
    assert prof_trace["trace_id"]
    ring = TRACER.traces(node_id=node.node_id)
    assert ring and ring[0]["trace_id"] == prof_trace["trace_id"]


def test_unsampled_request_leaves_no_trace(rest, node):
    _seed(rest, docs=2)
    st, _ = _dispatch(rest, "POST", "/idx/_search", {},
                      {"query": {"match_all": {}}})
    assert st == 200
    assert TRACER.traces(node_id=node.node_id) == []


def test_sampling_is_deterministic_counter_based():
    TRACER.configure(sample_rate=0.5)
    decisions = [TRACER.should_sample() for _ in range(8)]
    assert decisions == [False, True] * 4


def test_search_took_histogram_records_without_tracing(rest):
    _seed(rest, docs=2)
    before = metrics.REGISTRY.histogram("search.took").count
    st, _ = _dispatch(rest, "POST", "/idx/_search", {},
                      {"query": {"match_all": {}}})
    assert st == 200
    assert metrics.REGISTRY.histogram("search.took").count == before + 1


# ---------------------------------------------------------------------------
# slow log + X-Opaque-ID
# ---------------------------------------------------------------------------

def test_slow_log_carries_opaque_trace_and_phases(rest, node):
    _seed(rest, docs=4)
    st, _ = _dispatch(rest, "PUT", "/idx/_settings",
                      body={"index.search.slowlog.threshold.query.warn":
                            "0ms"})
    assert st == 200
    st, _ = _dispatch(rest, "POST", "/idx/_search", {"trace": "true"},
                      {"query": {"match": {"a": "hello"}}},
                      headers={"x-opaque-id": "slow-1"})
    assert st == 200
    entry = node.search_slow_log.entries[-1]
    assert entry["index"] == "idx" and entry["level"] == "warn"
    assert entry["opaque_id"] == "slow-1"
    assert entry["trace_id"]
    assert entry["phases"]["query_nanos"] > 0
    assert isinstance(entry["top_spans"], list) and entry["top_spans"]
    # the attached trace id resolves in the ring
    ring_ids = {t["trace_id"] for t in TRACER.traces(node_id=node.node_id)}
    assert entry["trace_id"] in ring_ids


def test_nodes_stats_has_telemetry_and_slowlog_sections(rest, node):
    _seed(rest, docs=2)
    _dispatch(rest, "POST", "/idx/_search", {},
              {"query": {"match_all": {}}})
    st, resp = _dispatch(rest, "GET", "/_nodes/stats")
    assert st == 200
    section = resp["nodes"][node.node_id]["telemetry"]
    hist = section["histograms"]["search.took"]
    for key in ("count", "p50_nanos", "p90_nanos", "p99_nanos",
                "p999_nanos"):
        assert key in hist
    assert hist["count"] >= 1
    assert "tracing" in section and "sample_rate" in section["tracing"]
    slowlog = resp["nodes"][node.node_id]["indices"]["slowlog"]
    assert set(slowlog) == {"search", "indexing"}
    assert "count" in slowlog["search"]


def test_nodes_traces_endpoint_shape(rest, node):
    _seed(rest, docs=2)
    _dispatch(rest, "POST", "/idx/_search", {"trace": "true"},
              {"query": {"match_all": {}}})
    st, resp = _dispatch(rest, "GET", "/_nodes/traces", {"size": "10"})
    assert st == 200
    section = resp["nodes"][node.node_id]
    assert section["traces"], "ring empty after a forced trace"
    tr = section["traces"][0]
    assert {"trace_id", "node", "action", "spans"} <= set(tr)


def test_hybrid_slow_log_breach_carries_phases_without_profile(rest, node):
    _seed(rest, docs=6, vectors=True)
    st, _ = _dispatch(rest, "PUT", "/idx/_settings",
                      body={"index.search.slowlog.threshold.query.warn":
                            "0ms"})
    assert st == 200
    rng = np.random.default_rng(11)
    st, resp = _dispatch(
        rest, "POST", "/idx/_search", {},
        {"rank": {"rrf": {}},
         "query": {"match": {"a": "hello"}},
         "knn": {"field": "v",
                 "query_vector": rng.standard_normal(DIMS).tolist(),
                 "k": 3, "num_candidates": 3},
         "size": 3})
    assert st == 200
    # the private phases key never reaches the client...
    assert "_took_phases" not in resp
    # ...but the breach entry carries the device-path breakdown even
    # though the request never asked for profile
    entry = node.search_slow_log.entries[-1]
    assert entry["index"] == "idx"
    for key in ("plan_nanos", "device_dispatch_nanos",
                "device_sync_nanos", "hydrate_nanos"):
        assert key in entry["phases"], entry["phases"]


# ---------------------------------------------------------------------------
# tasks API
# ---------------------------------------------------------------------------

def test_tasks_api_lists_inflight_with_opaque_trace_and_current_span(
        rest, node):
    with telemetry.rest_request(node, "indices:data/read/search",
                                opaque_id="task-op", force_trace=True):
        st, resp = _dispatch(rest, "GET", "/_tasks")
        assert st == 200
        tasks = resp["nodes"][node.node_id]["tasks"]
        mine = [t for t in tasks.values()
                if t.get("headers", {}).get("X-Opaque-Id") == "task-op"]
        assert mine, f"in-flight task not listed: {tasks}"
        task = mine[0]
        assert task["action"] == "indices:data/read/search"
        assert task["running_time_in_nanos"] >= 0
        assert task["trace_id"]
        assert task["current_span"] == "indices:data/read/search"
    # unregistered after the request finishes
    st, resp = _dispatch(rest, "GET", "/_tasks")
    tasks = resp["nodes"][node.node_id]["tasks"]
    assert not [t for t in tasks.values()
                if t.get("headers", {}).get("X-Opaque-Id") == "task-op"]


def test_rest_cancel_all_sets_cancelled_flag(rest, node):
    task = node.tasks.register("indices:data/read/search", trace=None)
    try:
        st, resp = _dispatch(rest, "POST", "/_tasks/_cancel",
                             {"actions": "indices:data/read/*"})
        assert st == 200
        assert task.cancelled is True
        listed = resp["nodes"][node.node_id]["tasks"][task.task_id]
        assert listed["cancelled"] is True
    finally:
        node.tasks.unregister(task)


# ---------------------------------------------------------------------------
# the async batcher: spans, follower links, cancellation
# ---------------------------------------------------------------------------

def _drain_barrier_batcher(started, release):
    """A batcher whose executor blocks until `release` is set — queued
    entries pile up behind the in-flight batch."""
    from elasticsearch_tpu.serving.batcher import CombiningBatcher

    def execute(reqs):
        started.set()
        assert release.wait(10)
        return list(reqs)

    return CombiningBatcher(execute, max_batch=8, topup=False)


def test_cancellation_sheds_queued_entries_at_admission():
    started, release = threading.Event(), threading.Event()
    batcher = _drain_barrier_batcher(started, release)

    class Token:
        cancelled = False

    token = Token()
    results = {}

    def blocker():
        results["lead"] = batcher.submit("lead")

    lead = threading.Thread(target=blocker)
    lead.start()
    assert started.wait(10)

    def queued():
        with telemetry.use(task=token):
            try:
                results["q"] = batcher.submit("q")
            except TaskCancelledError as e:
                results["q_err"] = e

    qt = threading.Thread(target=queued)
    qt.start()
    # wait until the entry is actually queued, then cancel it
    deadline = time.monotonic() + 10
    while batcher.pending() == 0 and time.monotonic() < deadline:
        time.sleep(0.005)
    assert batcher.pending() == 1
    token.cancelled = True
    release.set()
    lead.join(10)
    qt.join(10)
    assert isinstance(results.get("q_err"), TaskCancelledError)
    assert batcher.sched["cancelled_sheds"] == 1
    assert results["lead"] == "lead"


def test_coalesced_follower_links_to_leader_batch_span():
    started, release = threading.Event(), threading.Event()
    batcher = _drain_barrier_batcher(started, release)
    leader_tr = TRACER.start("search", node_id="n", forced=True)
    follower_tr = TRACER.start("search", node_id="n", forced=True)
    out = {}

    def first():
        with telemetry.use(trace=leader_tr):
            out["a"] = batcher.submit("a")

    t1 = threading.Thread(target=first)
    t1.start()
    assert started.wait(10)

    def second():
        with telemetry.use(trace=follower_tr):
            out["b"] = batcher.submit("b")

    t2 = threading.Thread(target=second)
    t2.start()
    deadline = time.monotonic() + 10
    while batcher.pending() == 0 and time.monotonic() < deadline:
        time.sleep(0.005)
    release.set()
    t1.join(10)
    t2.join(10)
    assert out == {"a": "a", "b": "b"}
    TRACER.finish(leader_tr)
    TRACER.finish(follower_tr)
    # exactly one of the two traces carries the second batch's execute
    # span; the other links to it (never double-counts device time)
    all_spans = {sp.span_id: (tr, sp)
                 for tr in (leader_tr, follower_tr)
                 for sp in tr.spans}
    linked = [link for tr in (leader_tr, follower_tr)
              for link in tr.links if link["reason"] == "coalesced_follower"]
    if linked:   # both coalesced into one batch
        link = linked[0]
        assert link["span_id"] in all_spans
        owner, span = all_spans[link["span_id"]]
        assert span.attrs.get("coalesced", 0) >= 2
        assert owner.trace_id == link["trace_id"]
    else:        # scheduling served them as two singleton batches
        for tr in (leader_tr, follower_tr):
            assert any(sp.name == "batch.execute" for sp in tr.spans)
    # queue waits are always per-request, never shared
    assert any(sp.name == "queue.wait" for sp in follower_tr.spans)


# ---------------------------------------------------------------------------
# cross-node tracing on the 3-node simulator (fault harness active)
# ---------------------------------------------------------------------------

def _cluster(tmp_path, **kw):
    import sys
    sys.path.insert(0, str(__import__("pathlib").Path(__file__).parent))
    from test_fanout import FaultyCluster, _build
    c = FaultyCluster(tmp_path, n_nodes=3)
    _build(c, docs=12, shards=3, vectors=True)
    return c


def _traced_search(c, body):
    coord = c.nodes["n0"]
    tr = TRACER.start("indices:data/read/search", node_id="n0",
                      forced=True, opaque_id="xn-1")
    box = {}
    coord.client_search("docs", body,
                        on_done=lambda r: box.update(r=r),
                        telemetry_ctx=(tr, tr.root.span_id, None))
    assert c.run_until(lambda: "r" in box)
    TRACER.finish(tr)
    return tr, box["r"]


def test_cross_node_trace_parents_device_attribution_no_recompiles(
        tmp_path):
    from elasticsearch_tpu.ops import dispatch
    c = _cluster(tmp_path)
    try:
        rng = np.random.default_rng(3)
        body = {"knn": {"field": "v",
                        "query_vector": rng.standard_normal(DIMS).tolist(),
                        "k": 3, "num_candidates": 6},
                "size": 3}
        # warm pass: compiles happen here, not in the traced request
        _traced_search(c, dict(body))
        TRACER.clear()
        compiles_before = dispatch.DISPATCH.compile_count()
        tr, resp = _traced_search(c, dict(body))
        assert resp["_shards"]["failed"] == 0
        # acceptance: ZERO added recompiles from tracing the request
        assert dispatch.DISPATCH.compile_count() == compiles_before
        spans = tr.span_dicts()
        by_id = {s["span_id"]: s for s in spans}
        names = [s["name"] for s in spans]
        # coordinator spans
        assert "phase.query" in names and "phase.fetch" in names
        # per-leg spans for all three shards, remote segments under them
        legs = [s for s in spans if s["name"].startswith("query[")]
        assert len(legs) == 3
        remote_roots = [s for s in spans
                        if s["name"].startswith("shard.query[")]
        assert len(remote_roots) == 3
        leg_ids = {s["span_id"] for s in legs}
        for rr in remote_roots:
            assert rr["parent_id"] in leg_ids, \
                "remote segment must parent under its coordinator leg"
        # device-path attribution spans from the remote batcher
        assert "queue.wait" in names
        assert "batch.execute" in names or "batch.dispatch" in names
        assert "hydrate" in names
        # every span closed; parents resolve; attribution is consistent:
        # each child's duration fits inside the request window
        root_dur = tr.took_ns
        for s in spans:
            assert s["dur_ns"] is not None, f"leaked span {s['name']}"
            assert s["parent_id"] is None or s["parent_id"] in by_id
            assert s["dur_ns"] <= root_dur * 2 + 50_000_000
        # per-leg attribution sums to (within slack) the leg's own span
        for rr in remote_roots:
            children = [s for s in spans if s["parent_id"] == rr["span_id"]]
            assert children, "remote segment carries no attribution"
            assert sum(s["dur_ns"] for s in children) <= \
                rr["dur_ns"] + 50_000_000
    finally:
        c.stop()


def test_cross_node_dead_node_leg_is_error_span_not_a_leak(tmp_path):
    c = _cluster(tmp_path)
    try:
        # warm once so the kill window only covers the traced request
        _traced_search(c, {"query": {"match_all": {}}, "size": 3})
        victim = [nid for nid in c.nodes if nid != "n0"][0]
        c.faults.kill_node(victim)
        tr, resp = _traced_search(
            c, {"query": {"match_all": {}}, "size": 3,
                "timeout": "2s"})
        assert resp["_shards"]["failed"] >= 1
        spans = tr.span_dicts()
        bad = [s for s in spans if s["name"] == f"query[{victim}]"]
        assert bad, "dead node's leg span missing"
        assert bad[0]["dur_ns"] is not None, "dead node's leg span leaked"
        assert bad[0]["status"] != "ok"
        # the phase still completed and every span closed
        assert all(s["dur_ns"] is not None for s in spans)
    finally:
        c.stop()


def test_remote_segments_land_in_their_own_nodes_ring(tmp_path):
    c = _cluster(tmp_path)
    try:
        tr, _resp = _traced_search(
            c, {"query": {"match_all": {}}, "size": 3})
        data_nodes = [nid for nid in c.nodes if nid != "n0"]
        remote = [t for nid in data_nodes
                  for t in TRACER.traces(node_id=nid)]
        assert remote, "data nodes recorded no segments"
        assert all(t["trace_id"] == tr.trace_id for t in remote
                   if t["opaque_id"] == "xn-1")
    finally:
        c.stop()
