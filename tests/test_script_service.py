"""Scripting subsystem: stored scripts, search templates, mustache engine.

Reference behavior: `script/ScriptService.java` (stored scripts),
`modules/lang-mustache` (search templates), stored-script use inside
script_score specs (`Script.java` id resolution).
"""

import json

import pytest

from elasticsearch_tpu.node import Node
from elasticsearch_tpu.rest.actions import register_all
from elasticsearch_tpu.rest.controller import RestController
from elasticsearch_tpu.script import mustache


class Client:
    def __init__(self, node):
        self.rc = RestController()
        register_all(self.rc, node)

    def req(self, method, path, body=None, **query):
        raw = b""
        if body is not None:
            if isinstance(body, (list, tuple)):
                raw = b"\n".join(json.dumps(l).encode() for l in body) + b"\n"
            else:
                raw = json.dumps(body).encode()
        return self.rc.dispatch(method, path, {k: str(v) for k, v in query.items()},
                                raw, "application/json")


@pytest.fixture
def node(tmp_path):
    n = Node(str(tmp_path / "data"))
    yield n
    n.close()


@pytest.fixture
def client(node):
    return Client(node)


def _seed(client):
    for i, (name, price) in enumerate(
            [("red shirt", 10), ("blue shirt", 25), ("green hat", 5)]):
        client.req("PUT", f"/products/_doc/{i}",
                   {"name": name, "price": price})
    client.req("POST", "/products/_refresh")


# ---------------------------------------------------------------- mustache

def test_mustache_variables_and_sections():
    assert mustache.render("hello {{name}}", {"name": "world"}) == "hello world"
    assert mustache.render("{{#xs}}[{{.}}]{{/xs}}", {"xs": [1, 2]}) == "[1][2]"
    assert mustache.render("{{^xs}}empty{{/xs}}", {"xs": []}) == "empty"
    assert mustache.render("{{a.b}}", {"a": {"b": 3}}) == "3"
    assert mustache.render("{{! comment }}x", {}) == "x"


def test_mustache_to_json_and_join():
    out = mustache.render('{"ids": {{#toJson}}ids{{/toJson}}}', {"ids": [1, 2]})
    assert json.loads(out) == {"ids": [1, 2]}
    assert mustache.render("{{#join}}tags{{/join}}",
                           {"tags": ["a", "b"]}) == "a,b"


def test_render_search_template_conditional():
    src = ('{"query": {"bool": {"must": {"match": {"name": "{{q}}"}}'
           '{{#min_price}}, "filter": {"range": {"price": '
           '{"gte": {{min_price}}}}}{{/min_price}} }}}')
    with_filter = mustache.render_search_template(src, {"q": "shirt",
                                                        "min_price": 20})
    assert "filter" in with_filter["query"]["bool"]
    without = mustache.render_search_template(src, {"q": "shirt"})
    assert "filter" not in without["query"]["bool"]


# ---------------------------------------------------------- stored scripts

def test_stored_script_crud(client):
    st, body = client.req("PUT", "/_scripts/my-calc",
                          {"script": {"lang": "painless",
                                      "source": "doc['price'].value * 2"}})
    assert st == 200 and body["acknowledged"]
    st, body = client.req("GET", "/_scripts/my-calc")
    assert body["found"] and body["script"]["source"] == "doc['price'].value * 2"
    st, _ = client.req("DELETE", "/_scripts/my-calc")
    assert st == 200
    st, _ = client.req("GET", "/_scripts/my-calc")
    assert st == 404


def test_stored_script_compile_error(client):
    st, body = client.req("PUT", "/_scripts/bad",
                          {"script": {"lang": "painless", "source": "1 +*/ 2"}})
    assert st == 400


def test_script_score_with_stored_id(client):
    _seed(client)
    client.req("PUT", "/_scripts/price-boost",
               {"script": {"lang": "painless",
                           "source": "doc['price'].value * params.f"}})
    st, body = client.req("POST", "/products/_search", {
        "query": {"script_score": {"query": {"match_all": {}},
                                   "script": {"id": "price-boost",
                                              "params": {"f": 2}}}}})
    assert st == 200
    hits = body["hits"]["hits"]
    assert hits[0]["_score"] == 50.0  # price 25 * 2


# --------------------------------------------------------- search template

def test_search_template_inline(client):
    _seed(client)
    st, body = client.req("POST", "/products/_search/template", {
        "source": {"query": {"match": {"name": "{{q}}"}}},
        "params": {"q": "shirt"}})
    assert st == 200
    assert body["hits"]["total"]["value"] == 2


def test_search_template_stored(client):
    _seed(client)
    client.req("PUT", "/_scripts/find-by-name",
               {"script": {"lang": "mustache",
                           "source": '{"query": {"match": {"name": "{{q}}"}}}'}})
    st, body = client.req("POST", "/products/_search/template",
                          {"id": "find-by-name", "params": {"q": "hat"}})
    assert st == 200
    assert body["hits"]["total"]["value"] == 1


def test_render_template(client):
    client.req("PUT", "/_scripts/tpl",
               {"script": {"lang": "mustache",
                           "source": '{"size": {{n}}}'}})
    st, body = client.req("POST", "/_render/template/tpl", {"params": {"n": 5}})
    assert body["template_output"] == {"size": 5}


def test_msearch_template(client):
    _seed(client)
    st, body = client.req("POST", "/_msearch/template", [
        {"index": "products"},
        {"source": {"query": {"match": {"name": "{{q}}"}}},
         "params": {"q": "shirt"}},
        {"index": "products"},
        {"source": {"query": {"match_all": {}}}, "params": {}},
    ])
    assert st == 200
    assert body["responses"][0]["hits"]["total"]["value"] == 2
    assert body["responses"][1]["hits"]["total"]["value"] == 3


def test_update_with_stored_script(client, node):
    _seed(client)
    client.req("PUT", "/_scripts/bump",
               {"script": {"lang": "painless",
                           "source": "ctx._source.price += params.n"}})
    st, body = client.req("POST", "/products/_update/0",
                          {"script": {"id": "bump", "params": {"n": 7}}})
    assert st == 200
    _, doc = client.req("GET", "/products/_doc/0")
    assert doc["_source"]["price"] == 17


def test_stored_mustache_rejected_in_score_context(client):
    _seed(client)
    client.req("PUT", "/_scripts/tpl2",
               {"script": {"lang": "mustache", "source": '{"a": 1}'}})
    st, body = client.req("POST", "/products/_search", {
        "query": {"script_score": {"query": {"match_all": {}},
                                   "script": {"id": "tpl2"}}}})
    assert st == 400


def test_stored_scripts_persist_across_restart(tmp_path):
    from elasticsearch_tpu.script.service import GLOBAL_SCRIPTS
    n1 = Node(str(tmp_path / "data"))
    c1 = Client(n1)
    c1.req("PUT", "/_scripts/persisted",
           {"script": {"lang": "painless", "source": "1 + 1"}})
    n1.close()
    GLOBAL_SCRIPTS.clear()   # simulate process restart
    n2 = Node(str(tmp_path / "data"))
    c2 = Client(n2)
    st, body = c2.req("GET", "/_scripts/persisted")
    assert st == 200 and body["script"]["source"] == "1 + 1"
    n2.close()
