"""Black-box REST API conformance tests (the analog of the reference's
306 YAML suites under rest-api-spec): drive the full controller the way an
HTTP client would, asserting response shapes."""

import json

import pytest

from elasticsearch_tpu.node import Node
from elasticsearch_tpu.rest.actions import register_all
from elasticsearch_tpu.rest.controller import RestController


class Client:
    """Test client: dispatches through the controller like the HTTP layer."""

    def __init__(self, node):
        self.rc = RestController()
        register_all(self.rc, node)

    def req(self, method, path, body=None, **query):
        raw = b""
        if body is not None:
            if isinstance(body, (list, tuple)):  # ndjson
                raw = b"\n".join(json.dumps(l).encode() for l in body) + b"\n"
            else:
                raw = json.dumps(body).encode()
        q = {k: str(v) for k, v in query.items()}
        return self.rc.dispatch(method, path, q, raw, "application/json")


@pytest.fixture
def client(tmp_path):
    node = Node(str(tmp_path / "data"))
    yield Client(node)
    node.close()


def test_root(client):
    status, body = client.req("GET", "/")
    assert status == 200
    assert body["tagline"] == "You Know, for (TPU) Search"


def test_document_crud(client):
    status, body = client.req("PUT", "/books/_doc/1",
                              {"title": "Dune", "pages": 412})
    assert status == 201 and body["result"] == "created" and body["_seq_no"] == 0

    status, body = client.req("GET", "/books/_doc/1")
    assert status == 200 and body["found"] and body["_source"]["title"] == "Dune"

    status, body = client.req("PUT", "/books/_doc/1", {"title": "Dune", "pages": 500})
    assert status == 200 and body["result"] == "updated" and body["_version"] == 2

    status, body = client.req("GET", "/books/_source/1")
    assert status == 200 and body == {"title": "Dune", "pages": 500}

    status, body = client.req("DELETE", "/books/_doc/1")
    assert status == 200 and body["result"] == "deleted"

    status, body = client.req("GET", "/books/_doc/1")
    assert status == 404 and not body["found"]

    status, body = client.req("DELETE", "/books/_doc/1")
    assert status == 404 and body["result"] == "not_found"


def test_create_conflict_and_optimistic_concurrency(client):
    client.req("PUT", "/idx/_doc/1", {"a": 1})
    status, body = client.req("PUT", "/idx/_create/1", {"a": 2})
    assert status == 409
    assert body["error"]["type"] == "version_conflict_engine_exception"

    status, ok = client.req("GET", "/idx/_doc/1")
    status, body = client.req("PUT", "/idx/_doc/1", {"a": 3},
                              if_seq_no=ok["_seq_no"], if_primary_term=ok["_primary_term"])
    assert status == 200
    status, body = client.req("PUT", "/idx/_doc/1", {"a": 4},
                              if_seq_no=ok["_seq_no"], if_primary_term=ok["_primary_term"])
    assert status == 409


def test_auto_id_and_update(client):
    status, body = client.req("POST", "/idx/_doc", {"x": 1})
    assert status == 201 and body["_id"]
    doc_id = body["_id"]
    status, body = client.req("POST", f"/idx/_update/{doc_id}",
                              {"doc": {"y": 2}})
    assert status == 200
    _, body = client.req("GET", f"/idx/_doc/{doc_id}")
    assert body["_source"] == {"x": 1, "y": 2}

    status, body = client.req("POST", f"/idx/_update/{doc_id}",
                              {"script": {"source": "ctx._source.x += params.n",
                                          "params": {"n": 10}}})
    assert status == 200
    _, body = client.req("GET", f"/idx/_doc/{doc_id}")
    assert body["_source"]["x"] == 11

    status, body = client.req("POST", "/idx/_update/missing",
                              {"doc": {"a": 1}, "doc_as_upsert": True})
    assert status == 200
    _, body = client.req("GET", "/idx/_doc/missing")
    assert body["found"]


def test_bulk(client):
    ops = [
        {"index": {"_index": "bulk1", "_id": "1"}}, {"n": 1},
        {"index": {"_index": "bulk1", "_id": "2"}}, {"n": 2},
        {"create": {"_index": "bulk1", "_id": "1"}}, {"n": 99},   # conflict
        {"delete": {"_index": "bulk1", "_id": "2"}},
        {"update": {"_index": "bulk1", "_id": "1"}}, {"doc": {"m": 5}},
    ]
    status, body = client.req("POST", "/_bulk", ops, refresh="true")
    assert status == 200
    assert body["errors"] is True
    results = [next(iter(i.values())) for i in body["items"]]
    assert results[0]["status"] == 201
    assert results[2]["status"] == 409
    assert results[3]["status"] == 200
    assert results[4]["status"] == 200
    status, body = client.req("GET", "/bulk1/_count")
    assert body["count"] == 1


def test_index_admin(client):
    status, body = client.req("PUT", "/catalog", {
        "settings": {"number_of_shards": 2},
        "mappings": {"properties": {"name": {"type": "text"},
                                    "sku": {"type": "keyword"}}},
        "aliases": {"products": {}}})
    assert status == 200 and body["acknowledged"]

    status, body = client.req("PUT", "/catalog", {})
    assert status == 400  # already exists

    status, body = client.req("GET", "/catalog")
    assert body["catalog"]["mappings"]["properties"]["sku"]["type"] == "keyword"
    assert body["catalog"]["settings"]["index"]["number_of_shards"] == 2

    status, _ = client.req("HEAD", "/catalog")
    assert status == 200
    status, _ = client.req("HEAD", "/nope")
    assert status == 404

    # write via alias
    status, _ = client.req("PUT", "/products/_doc/1", {"name": "widget", "sku": "W1"})
    assert status == 201
    status, body = client.req("GET", "/catalog/_search",
                              {"query": {"term": {"sku": "W1"}}}, refresh=True)
    # needs refresh first
    client.req("POST", "/catalog/_refresh")
    status, body = client.req("GET", "/products/_search",
                              {"query": {"term": {"sku": "W1"}}})
    assert body["hits"]["total"]["value"] == 1

    status, body = client.req("PUT", "/catalog/_mapping",
                              {"properties": {"price": {"type": "float"}}})
    assert body["acknowledged"]
    _, body = client.req("GET", "/catalog/_mapping")
    assert body["catalog"]["mappings"]["properties"]["price"]["type"] == "float"

    status, body = client.req("DELETE", "/catalog")
    assert body["acknowledged"]
    status, _ = client.req("GET", "/catalog")
    assert status == 404


def test_search_end_to_end(client):
    docs = [
        {"title": "quick brown fox", "tag": "a", "n": 1},
        {"title": "lazy dog", "tag": "b", "n": 2},
        {"title": "quick dog", "tag": "b", "n": 3},
    ]
    for i, d in enumerate(docs):
        client.req("PUT", f"/s/_doc/{i}", d)
    client.req("POST", "/s/_refresh")

    status, body = client.req("POST", "/s/_search", {
        "query": {"match": {"title": "quick"}},
        "aggs": {"tags": {"terms": {"field": "tag.keyword"}}}})
    assert status == 200
    assert body["hits"]["total"] == {"value": 2, "relation": "eq"}
    assert {h["_id"] for h in body["hits"]["hits"]} == {"0", "2"}
    assert body["hits"]["hits"][0]["_score"] > 0
    buckets = {b["key"]: b["doc_count"] for b in body["aggregations"]["tags"]["buckets"]}
    assert buckets == {"a": 1, "b": 1}

    # URI search q=field:value
    status, body = client.req("GET", "/s/_search", q="title:dog", size=10)
    assert body["hits"]["total"]["value"] == 2

    # sort + from/size
    status, body = client.req("POST", "/s/_search",
                              {"sort": [{"n": "desc"}], "size": 2})
    assert [h["_id"] for h in body["hits"]["hits"]] == ["2", "1"]
    assert body["hits"]["hits"][0]["sort"] == [3.0]


def test_msearch_and_mget(client):
    for i in range(3):
        client.req("PUT", f"/m/_doc/{i}", {"n": i}, refresh="true")
    status, body = client.req("POST", "/_msearch", [
        {"index": "m"}, {"query": {"range": {"n": {"gte": 1}}}},
        {"index": "missing-idx"}, {"query": {"match_all": {}}},
    ])
    assert body["responses"][0]["hits"]["total"]["value"] == 2
    assert body["responses"][1]["status"] == 404

    status, body = client.req("POST", "/_mget", {
        "docs": [{"_index": "m", "_id": "0"}, {"_index": "m", "_id": "77"}]})
    assert body["docs"][0]["found"] is True
    assert body["docs"][1]["found"] is False


def test_multi_shard_routing(client):
    client.req("PUT", "/sharded", {"settings": {"number_of_shards": 4}})
    for i in range(40):
        client.req("PUT", f"/sharded/_doc/{i}", {"n": i})
    client.req("POST", "/sharded/_refresh")
    _, body = client.req("GET", "/sharded/_count")
    assert body["count"] == 40
    _, body = client.req("POST", "/sharded/_search",
                         {"query": {"range": {"n": {"lt": 10}}}, "size": 20,
                          "sort": [{"n": "asc"}]})
    assert body["hits"]["total"]["value"] == 10
    assert [h["_source"]["n"] for h in body["hits"]["hits"]] == list(range(10))
    # GET routes to the right shard
    _, body = client.req("GET", "/sharded/_doc/17")
    assert body["found"] and body["_source"]["n"] == 17
    # _cat/shards shows 4 primaries
    _, text = client.req("GET", "/_cat/shards")
    # 4 STARTED primaries + 4 UNASSIGNED replica rows (default replicas=1
    # can never assign on a single node, like the reference)
    lines = [l for l in text.strip().split("\n") if l.startswith("sharded")]
    assert sum(1 for l in lines if " p " in l and "STARTED" in l) == 4
    assert sum(1 for l in lines if " r " in l and "UNASSIGNED" in l) == 4


def test_knn_over_rest(client):
    client.req("PUT", "/vec", {
        "settings": {"number_of_shards": 2},
        "mappings": {"properties": {
            "v": {"type": "dense_vector", "dims": 4, "similarity": "cosine"},
            "cat": {"type": "keyword"}}}})
    import random
    random.seed(3)
    for i in range(30):
        client.req("PUT", f"/vec/_doc/{i}",
                   {"v": [random.gauss(0, 1) for _ in range(4)], "cat": f"c{i % 3}"})
    client.req("POST", "/vec/_refresh")
    _, target = client.req("GET", "/vec/_doc/7")
    qv = target["_source"]["v"]
    _, body = client.req("POST", "/vec/_search",
                         {"knn": {"field": "v", "query_vector": qv, "k": 5}})
    assert body["hits"]["hits"][0]["_id"] == "7"
    assert body["hits"]["hits"][0]["_score"] == pytest.approx(1.0, abs=5e-3)
    # filtered knn
    _, body = client.req("POST", "/vec/_search",
                         {"knn": {"field": "v", "query_vector": qv, "k": 5,
                                  "filter": {"term": {"cat": "c1"}}}})
    ids = [int(h["_id"]) for h in body["hits"]["hits"]]
    assert all(i % 3 == 1 for i in ids)
    assert 7 in ids


def test_analyze(client):
    _, body = client.req("POST", "/_analyze",
                         {"text": "The Quick-Brown FOXES", "analyzer": "english"})
    tokens = [t["token"] for t in body["tokens"]]
    assert "quick" in tokens and "fox" in tokens  # stemmed, stopword removed


def test_cluster_and_cat(client):
    client.req("PUT", "/one/_doc/1", {"a": 1})
    _, body = client.req("GET", "/_cluster/health")
    # default replicas=1 on one node: unassigned replicas -> yellow
    assert body["status"] == "yellow" and body["number_of_nodes"] == 1
    assert body["unassigned_shards"] == body["active_shards"]
    _, body = client.req("GET", "/_cluster/state")
    assert "one" in body["metadata"]["indices"]
    _, body = client.req("GET", "/_nodes")
    assert body["_nodes"]["total"] == 1
    _, body = client.req("GET", "/_cat/indices", format="json")
    assert body[0]["index"] == "one"
    _, text = client.req("GET", "/_cat/health", v="")
    assert "cluster" in text  # header line with v


def test_error_shapes(client):
    status, body = client.req("GET", "/missing/_search", {"query": {"match_all": {}}})
    assert status == 404
    assert body["error"]["type"] == "index_not_found_exception"
    assert body["status"] == 404

    status, body = client.req("POST", "/e/_doc/1", {"a": 1})
    status, body = client.req("POST", "/e/_search", {"query": {"bogus": {}}})
    assert status == 400 and body["error"]["type"] == "parsing_exception"

    status, body = client.req("PUT", "/INVALID-UPPER", {})
    assert status == 400

    status, body = client.req("POST", "/", None)
    assert status == 405  # method not allowed on root


def test_flush_persists_and_stats(client, tmp_path):
    client.req("PUT", "/p/_doc/1", {"a": 1})
    _, body = client.req("POST", "/p/_flush")
    assert body["_shards"]["failed"] == 0
    _, body = client.req("GET", "/p/_stats")
    assert body["_all"]["primaries"]["docs"]["count"] == 1
    _, body = client.req("POST", "/p/_forcemerge")
    assert body["_shards"]["failed"] == 0


def test_nested_and_reverse_nested_aggs(client):
    """nested doc_count counts NESTED docs; reverse_nested joins back to
    parents (ReverseNestedAggregator.java:48): per-author comment buckets
    report how many PARENT issues they commented on."""
    client.req("PUT", "/issues", {"mappings": {"properties": {
        "title": {"type": "keyword"},
        "comments": {"type": "nested", "properties": {
            "author": {"type": "keyword"},
            "likes": {"type": "long"}}}}}})
    docs = [
        {"title": "a", "comments": [
            {"author": "kim", "likes": 10}, {"author": "lee", "likes": 1}]},
        {"title": "b", "comments": [
            {"author": "kim", "likes": 3}]},
        {"title": "c", "comments": [
            {"author": "lee", "likes": 7}, {"author": "kim", "likes": 2},
            {"author": "kim", "likes": 4}]},
    ]
    for i, d in enumerate(docs):
        client.req("PUT", f"/issues/_doc/{i}", d)
    client.req("POST", "/issues/_refresh")
    st, body = client.req("POST", "/issues/_search", {"size": 0, "aggs": {
        "to_comments": {"nested": {"path": "comments"}, "aggs": {
            "authors": {"terms": {"field": "comments.author"}, "aggs": {
                "issues": {"reverse_nested": {}}}}}}}})
    assert st == 200
    nested = body["aggregations"]["to_comments"]
    assert nested["doc_count"] == 6  # six nested comments total
    buckets = {b["key"]: b for b in nested["authors"]["buckets"]}
    # terms under nested count NESTED docs: kim commented 4 times, lee 2 —
    # consistent with the enclosing nested doc_count (4 + 2 == 6)
    assert buckets["kim"]["doc_count"] == 4
    assert buckets["lee"]["doc_count"] == 2
    # reverse_nested joins back to parents: kim across 3 issues, lee 2
    assert buckets["kim"]["issues"]["doc_count"] == 3
    assert buckets["lee"]["issues"]["doc_count"] == 2

    # reverse_nested outside a nested context is a 400
    st, body = client.req("POST", "/issues/_search", {"size": 0, "aggs": {
        "bad": {"reverse_nested": {}}}})
    assert st == 400
    # a path equal to the current scope must step OUT, not sideways: 400
    st, body = client.req("POST", "/issues/_search", {"size": 0, "aggs": {
        "c": {"nested": {"path": "comments"}, "aggs": {
            "bad": {"reverse_nested": {"path": "comments"}}}}}})
    assert st == 400


def test_nested_agg_multi_level_path(client):
    """Multi-level nested paths count leaf nested docs list-aware at every
    level (comments.replies through a list of comments)."""
    client.req("PUT", "/threads", {"mappings": {"properties": {
        "comments": {"type": "nested", "properties": {
            "replies": {"type": "nested", "properties": {
                "who": {"type": "keyword"}}}}}}}})
    client.req("PUT", "/threads/_doc/1", {"comments": [
        {"replies": [{"who": "x"}, {"who": "y"}]},
        {"replies": [{"who": "x"}]}]})
    client.req("PUT", "/threads/_doc/2", {"comments": [
        {"replies": [{"who": "z"}]}]})
    client.req("POST", "/threads/_refresh")
    st, body = client.req("POST", "/threads/_search", {"size": 0, "aggs": {
        "r": {"nested": {"path": "comments.replies"}}}})
    assert st == 200
    assert body["aggregations"]["r"]["doc_count"] == 4


def test_scripted_metric_agg_rest(client):
    client.req("PUT", "/sales", {"mappings": {"properties": {
        "type": {"type": "keyword"}, "amount": {"type": "double"}}}})
    for i, (t, a) in enumerate([("sale", 80.0), ("cost", 10.0),
                                ("sale", 130.0), ("cost", 30.0)]):
        client.req("PUT", f"/sales/_doc/{i}", {"type": t, "amount": a})
    client.req("POST", "/sales/_refresh")
    st, body = client.req("POST", "/sales/_search", {"size": 0, "aggs": {
        "profit": {"scripted_metric": {
            "init_script": "state.transactions = []",
            "map_script":
                "state.transactions.add(doc['type'].value == 'sale' ? "
                "doc['amount'].value : -1 * doc['amount'].value)",
            "combine_script":
                "double profit = 0; for (t in state.transactions) "
                "{ profit += t } return profit",
            "reduce_script":
                "double profit = 0; for (a in states) "
                "{ profit += a } return profit"}}}})
    assert st == 200
    assert body["aggregations"]["profit"]["value"] == 170.0


def test_scripted_metric_sees_real_scores(client):
    """map_script reads each doc's real _score (reference binds the score
    in ScriptedMetricAggregator's map context)."""
    client.req("PUT", "/scored", {"mappings": {"properties": {
        "t": {"type": "text"}}}})
    for i in range(3):
        client.req("PUT", f"/scored/_doc/{i}", {"t": "alpha beta"})
    client.req("POST", "/scored/_refresh")
    st, body = client.req("POST", "/scored/_search", {
        "size": 0,
        "query": {"match": {"t": "alpha"}},
        "aggs": {"s": {"scripted_metric": {
            "init_script": "state.s = 0.0",
            "map_script": "state.s += _score",
            "combine_script": "return state.s",
            "reduce_script":
                "double s = 0; for (a in states) { s += a } return s"}}}})
    assert st == 200
    assert body["aggregations"]["s"]["value"] > 0.0
