"""Spec-driven YAML conformance runner.

Executes the reference's REST behavior suites — the do/match DSL under
`rest-api-spec/src/main/resources/rest-api-spec/test/` — against this
framework's REST controller, resolving each `do:` call through the
machine-readable API specs in `rest-api-spec/api/*.json` exactly the way
`ESClientYamlSuiteTestCase` (§4.5) does.

The reference material is read from /root/reference at RUN time (it is the
API contract, not code) — nothing is copied into this repo.

Supported DSL: setup/teardown docs, do (with catch/warnings ignored-but-
tolerated), match ($stash refs, /regex/ values, subset match on objects),
length, is_true/is_false, gt/gte/lt/lte, contains, set; `skip` blocks for
versions/features. Unsupported features mark the test SKIPPED, never
PASSED.
"""

from __future__ import annotations

import json
import os
import re
from typing import Any, Dict, List, Optional, Tuple

REF_SPEC = "/root/reference/rest-api-spec/src/main/resources/rest-api-spec"

# DSL features (test/README.asciidoc "features") this runner implements;
# a skip block naming anything else skips the test
SUPPORTED_FEATURES = {"contains", "allowed_warnings", "headers",
                      "arbitrary_key"}

# the reference snapshot's version (buildSrc/version.properties): skip
# blocks carry "A - B" ranges meaning "skip when A <= version <= B"
EMULATED_VERSION = (8, 0, 0)


def _parse_version(s: str):
    s = s.strip()
    if not s:
        return None
    parts = []
    for p in s.split("."):
        try:
            parts.append(int(p))
        except ValueError:
            parts.append(99)
    while len(parts) < 3:
        parts.append(0)
    return tuple(parts[:3])


def _version_range_matches(expr: str, version) -> bool:
    for rng in expr.split(","):
        rng = rng.strip()
        if not rng:
            continue
        if "-" in rng and (" " in rng or rng.startswith("-") or rng.endswith("-")):
            lo_s, _, hi_s = rng.partition("-")
            lo = _parse_version(lo_s) or (0, 0, 0)
            hi = _parse_version(hi_s) or (999, 999, 999)
        else:
            lo = hi = _parse_version(rng) or (0, 0, 0)
        if lo <= version <= hi:
            return True
    return False

_MISSING = object()


def specs_available() -> bool:
    return os.path.isdir(os.path.join(REF_SPEC, "api"))


_SPECS: Optional[Dict[str, dict]] = None


def load_specs() -> Dict[str, dict]:
    global _SPECS
    if _SPECS is None:
        out = {}
        api_dir = os.path.join(REF_SPEC, "api")
        for name in os.listdir(api_dir):
            if not name.endswith(".json") or name.startswith("_"):
                continue
            with open(os.path.join(api_dir, name)) as f:
                spec = json.load(f)
            for api_name, body in spec.items():
                out[api_name] = body
        _SPECS = out
    return _SPECS


class StepFailure(AssertionError):
    pass


class StepSkip(Exception):
    pass


def _fmt_param(v: Any) -> str:
    if isinstance(v, bool):
        return "true" if v else "false"
    if isinstance(v, (list, tuple)):
        return ",".join(str(x) for x in v)
    return str(v)


def resolve_call(api_name: str, args: Dict[str, Any]) -> Tuple[str, str, Dict[str, str], Any]:
    """(method, path, query, body) for a `do:` call, via the JSON spec."""
    specs = load_specs()
    spec = specs.get(api_name)
    if spec is None:
        raise StepSkip(f"no API spec for [{api_name}]")
    body = args.get("body")
    arg_keys = {k for k in args if k not in ("body",)}
    # choose the path with the most parts all satisfied by the args
    best = None
    for p in spec["url"]["paths"]:
        parts = set(p.get("parts", {}))
        if parts <= arg_keys:
            if best is None or len(parts) > len(best[0]):
                best = (parts, p)
    if best is None:
        raise StepSkip(f"[{api_name}] no path matches args {sorted(arg_keys)}")
    parts, pathspec = best
    path = pathspec["path"]
    for part in parts:
        # URL-encode path parts like the real low-level client does —
        # date-math names (<logstash-{now/M}>) carry slashes
        from urllib.parse import quote
        path = path.replace("{%s}" % part,
                            quote(_fmt_param(args[part]), safe=",*"))
    methods = pathspec.get("methods", ["GET"])
    if body is not None and "POST" in methods and "PUT" not in methods:
        method = "POST"
    elif body is not None and "PUT" in methods and api_name not in (
            "index",):
        method = "PUT" if "POST" not in methods else (
            "PUT" if args.get("id") is not None or "{id}" in pathspec["path"]
            else "POST")
    else:
        method = methods[0]
    query = {k: _fmt_param(v) for k, v in args.items()
             if k not in parts and k != "body"}
    return method, path, query, body


def _split_path(path: str) -> List[str]:
    # dots split keys; `\.` escapes a literal dot inside a key
    parts = re.split(r"(?<!\\)\.", path)
    return [p.replace("\\.", ".") for p in parts]


def get_path(resp: Any, path: str, stash: Dict[str, Any]) -> Any:
    if path in ("$body", ""):
        return resp
    node = resp
    for raw in _split_path(path):
        key = stash.get(raw[1:], raw) if raw.startswith("$") else raw
        if key == "_arbitrary_key_" and isinstance(node, dict) and node:
            # the `arbitrary_key` feature: resolves to the FIRST KEY NAME
            # (reference ObjectPath semantics; used to stash a node id)
            node = next(iter(node))
            continue
        if isinstance(node, list):
            try:
                node = node[int(key)]
            except (ValueError, IndexError):
                return _MISSING
        elif isinstance(node, dict):
            if key in node:
                node = node[key]
            elif str(key) in node:
                node = node[str(key)]
            else:
                return _MISSING
        else:
            return _MISSING
    return node


def _stash_sub(value: Any, stash: Dict[str, Any]) -> Any:
    if isinstance(value, str):
        if value.startswith("$"):
            name = value[1:]
            if name in stash:
                return stash[name]
        # ${name} interpolation inside strings
        def repl(m):
            return str(stash.get(m.group(1), m.group(0)))
        return re.sub(r"\$\{(\w+)\}", repl, value)
    if isinstance(value, dict):
        return {k: _stash_sub(v, stash) for k, v in value.items()}
    if isinstance(value, list):
        return [_stash_sub(v, stash) for v in value]
    return value


def _values_match(actual: Any, expected: Any, stash: Dict[str, Any]) -> bool:
    expected = _stash_sub(expected, stash)
    if isinstance(expected, str) and len(expected) > 2 and \
            expected.startswith("/") and expected.rstrip().endswith("/"):
        pattern = expected.strip()[1:-1]
        # MatchAssertion.java compiles body regexes with Pattern.COMMENTS
        # unconditionally (whitespace/# ignored outside classes)
        return actual is not _MISSING and \
            re.search(pattern, str(actual), re.VERBOSE) is not None
    if isinstance(expected, dict):
        if not isinstance(actual, dict):
            return False
        # subset semantics (MatchAssertion on objects)
        return all(_values_match(actual.get(k, _MISSING), v, stash)
                   for k, v in expected.items())
    if isinstance(expected, (int, float)) and not isinstance(expected, bool) \
            and isinstance(actual, (int, float)) and not isinstance(actual, bool):
        return float(actual) == float(expected)
    return actual == expected


# catch keyword -> expected HTTP status predicate
_CATCHES = {
    "missing": lambda s: s == 404,
    "conflict": lambda s: s == 409,
    "forbidden": lambda s: s == 403,
    "unauthorized": lambda s: s == 401,
    "bad_request": lambda s: s == 400,
    "request_timeout": lambda s: s == 408,
    "unavailable": lambda s: s == 503,
    "request": lambda s: 400 <= s < 600,
    "param": lambda s: 400 <= s < 600,
}


class YamlTestRunner:
    """Runs one suite file's tests against a fresh client per test."""

    def __init__(self, client_factory):
        self.client_factory = client_factory

    def run_suite(self, path: str) -> List[dict]:
        import yaml as _yaml

        # keep YAML timestamps as raw strings: the reference runner sends
        # them over the wire verbatim; PyYAML's datetime objects aren't
        # JSON-serializable and would alter date-format semantics
        class _StrTimestampLoader(_yaml.SafeLoader):
            pass

        _StrTimestampLoader.add_constructor(
            "tag:yaml.org,2002:timestamp",
            lambda loader, node: loader.construct_scalar(node))

        with open(path) as f:
            docs = [d for d in _yaml.load_all(f, Loader=_StrTimestampLoader)
                    if d]
        setup = []
        teardown = []
        tests = []
        for doc in docs:
            if "setup" in doc and len(doc) == 1:
                setup = doc["setup"] or []
            elif "teardown" in doc and len(doc) == 1:
                teardown = doc["teardown"] or []
            else:
                for name, steps in doc.items():
                    tests.append((name, steps or []))
        results = []
        for name, steps in tests:
            results.append(self._run_one(path, name, setup, steps, teardown))
        return results

    def _run_one(self, suite, name, setup, steps, teardown) -> dict:
        client = self.client_factory()
        stash: Dict[str, Any] = {}
        result = {"suite": suite, "test": name, "status": "PASS", "reason": ""}
        try:
            try:
                for step in setup:
                    self._step(client, step, stash)
                for step in steps:
                    self._step(client, step, stash)
            finally:
                for step in teardown:
                    try:
                        self._step(client, step, stash)
                    except Exception:
                        pass
        except StepSkip as e:
            result.update(status="SKIP", reason=str(e))
        except StepFailure as e:
            result.update(status="FAIL", reason=str(e))
        except Exception as e:  # runner/transport error = failure, not crash
            result.update(status="FAIL",
                          reason=f"{type(e).__name__}: {e}")
        finally:
            closer = getattr(client, "close", None)
            if closer:
                closer()
        return result

    # ------------------------------------------------------------- steps
    def _step(self, client, step: dict, stash: Dict[str, Any]) -> None:
        ((kind, spec),) = step.items()
        if kind == "do":
            self._do(client, spec, stash)
        elif kind == "skip":
            self._skip(spec)
        elif kind == "match":
            ((path, expected),) = spec.items()
            actual = get_path(stash["__last__"], path, stash)
            if not _values_match(actual, expected, stash):
                raise StepFailure(
                    f"match {path}: expected {expected!r}, got "
                    f"{_short(actual)}")
        elif kind == "length":
            ((path, expected),) = spec.items()
            actual = get_path(stash["__last__"], path, stash)
            if actual is _MISSING or not hasattr(actual, "__len__") \
                    or len(actual) != int(_stash_sub(expected, stash)):
                raise StepFailure(
                    f"length {path}: expected {expected}, got "
                    f"{_short(actual)}")
        elif kind in ("is_true", "is_false"):
            actual = get_path(stash["__last__"], spec, stash)
            truthy = actual is not _MISSING and actual not in (
                False, None, "", "false", 0)
            if truthy != (kind == "is_true"):
                raise StepFailure(f"{kind} {spec}: got {_short(actual)}")
        elif kind in ("gt", "gte", "lt", "lte"):
            ((path, expected),) = spec.items()
            actual = get_path(stash["__last__"], path, stash)
            expected = float(_stash_sub(expected, stash))
            ops = {"gt": lambda a: a > expected,
                   "gte": lambda a: a >= expected,
                   "lt": lambda a: a < expected,
                   "lte": lambda a: a <= expected}
            if actual is _MISSING or not ops[kind](float(actual)):
                raise StepFailure(
                    f"{kind} {path}: expected {kind} {expected}, got "
                    f"{_short(actual)}")
        elif kind == "contains":
            ((path, expected),) = spec.items()
            actual = get_path(stash["__last__"], path, stash)
            expected = _stash_sub(expected, stash)
            ok = False
            if isinstance(actual, list):
                ok = any(_values_match(item, expected, stash)
                         for item in actual)
            if not ok:
                raise StepFailure(
                    f"contains {path}: {expected!r} not in {_short(actual)}")
        elif kind == "set":
            ((path, var),) = spec.items()
            value = get_path(stash["__last__"], path, stash)
            if value is _MISSING:
                raise StepFailure(f"set: no value at {path}")
            stash[var] = value
        elif kind == "transform_and_set":
            raise StepSkip("transform_and_set not supported")
        else:
            raise StepSkip(f"unsupported step [{kind}]")

    def _skip(self, spec: dict) -> None:
        version = str(spec.get("version", "")).strip()
        if version == "all":
            raise StepSkip(spec.get("reason", "skipped for all versions"))
        if version and _version_range_matches(version, EMULATED_VERSION):
            raise StepSkip(spec.get("reason", f"skipped for [{version}]"))
        features = spec.get("features") or []
        if isinstance(features, str):
            features = [features]
        unsupported = [f for f in features if f not in SUPPORTED_FEATURES]
        if unsupported:
            raise StepSkip(f"requires features {unsupported}")

    def _do(self, client, spec: dict, stash: Dict[str, Any]) -> None:
        spec = dict(spec)
        catch = spec.pop("catch", None)
        spec.pop("warnings", None)
        spec.pop("allowed_warnings", None)
        # custom request headers (the `headers` feature): alternative
        # Content-Type/Accept wire formats, auth headers, ...
        headers = {str(k).lower(): _stash_sub(v, stash)
                   for k, v in (spec.pop("headers", None) or {}).items()}
        if "node_selector" in spec:
            raise StepSkip("node_selector not supported")
        ((api_name, raw_args),) = spec.items()
        args = _stash_sub(raw_args or {}, stash)
        ignore = args.pop("ignore", None) if isinstance(args, dict) else None
        ignored = ([int(s) for s in ignore] if isinstance(ignore, list)
                   else [int(ignore)] if ignore is not None else [])
        method, path, query, body = resolve_call(api_name, args)
        status, resp = client.req(method, path, body=body,
                                  headers=headers or None, **query)
        if status in ignored:
            stash["__last__"] = resp
            return
        if method == "HEAD":
            # HEAD APIs (exists/ping) have no body: the runner exposes the
            # existence boolean, and a 404 is the valid `false` answer —
            # other 4xx/5xx still fail the step (ClientYamlTestClient)
            resp = status < 300
        stash["__last__"] = resp
        if method == "HEAD" and status == 404 and catch is None:
            return
        if catch is not None:
            if catch.startswith("/") and catch.endswith("/"):
                if status < 400 or not re.search(
                        catch[1:-1], json.dumps(resp)):
                    raise StepFailure(
                        f"{api_name}: expected error {catch}, got "
                        f"[{status}] {_short(resp)}")
            else:
                pred = _CATCHES.get(catch)
                if pred is None:
                    raise StepSkip(f"unsupported catch [{catch}]")
                if not pred(status):
                    raise StepFailure(
                        f"{api_name}: expected catch {catch}, got "
                        f"[{status}] {_short(resp)}")
        elif status >= 400:
            raise StepFailure(
                f"{api_name} {method} {path}: [{status}] {_short(resp)}")


def _short(v: Any, n: int = 200) -> str:
    s = repr(v) if v is not _MISSING else "<missing>"
    return s if len(s) <= n else s[:n] + "..."
