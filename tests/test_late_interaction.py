"""Late-interaction (`rank_vectors`) retrieval: fused gather+MaxSim
rescore over columnar token blocks (ops/pallas_maxsim.py +
vectors/late_interaction.py).

Contract tiers, following tests/test_pallas_parity.py:

* kernel vs reference twin: identical candidate ORDERING on separated
  scores, scores allclose to a few ULPs of bf16 — the interpret-mode
  grid loop can steer XLA CPU to a different accumulation order for
  the same per-pair dot, an artifact, not a semantics difference
  (f32 tolerance is tighter than the quantized rungs').
* end-to-end: device top-k recall@10 >= 0.95 vs the exact host walker
  (`late_interaction` query) on a clustered corpus at int8 AND int4,
  under the default oversample window.
"""

import tempfile

import numpy as np
import pytest

from elasticsearch_tpu.index.engine import Engine
from elasticsearch_tpu.index.mapping import MapperService
from elasticsearch_tpu.ops import dispatch
from elasticsearch_tpu.ops.pallas_maxsim import (maxsim_reference,
                                                 maxsim_rescore)
from elasticsearch_tpu.quant import tokens as quant_tokens
from elasticsearch_tpu.search.queries import SearchContext, parse_query
from elasticsearch_tpu.vectors.late_interaction import (
    MAX_QUERY_TOKENS, LateInteractionField, LateInteractionShard)


def _clustered(rng, n_docs, dims, max_tokens, n_topics=12, noise=0.25):
    """Docs whose tokens scatter around a shared topic vector: the
    pooled-centroid coarse phase is informative (as it is for real
    ColBERT-style embeddings), so recall measures the full pipeline."""
    topics = rng.standard_normal((n_topics, dims)).astype(np.float32)
    docs = []
    for i in range(n_docs):
        t = topics[i % n_topics]
        nt = int(rng.integers(2, max_tokens + 1))
        docs.append((t + noise * rng.standard_normal((nt, dims)))
                    .astype(np.float32))
    return topics, docs


# --------------------------------------------------------------- kernel


class TestKernelParity:
    def _board(self, rng, encoding, n=24, cap=8, dims=32, nq=8, wc=16,
               tq=8):
        docs = [rng.standard_normal((int(rng.integers(1, cap + 1)),
                                     dims)).astype(np.float32)
                for _ in range(n)]
        w = quant_tokens.packed_width(encoding, dims)
        n_pad = 32
        dtype = np.uint8 if encoding == "int4" else None
        toks = None
        scales = np.zeros((n_pad, cap), dtype=np.float32)
        for i, d in enumerate(docs):
            prepped = quant_tokens.prep_tokens(d, "cosine")
            data, sc = quant_tokens.encode_tokens(prepped, encoding, dims)
            if toks is None:
                toks = np.zeros((n_pad, cap, w), dtype=data.dtype)
            toks[i, :len(d)] = data
            scales[i, :len(d)] = sc
        ids = rng.integers(0, n, size=(nq, wc)).astype(np.int32)
        q = np.zeros((nq, tq, quant_tokens.pad_dim(dims)),
                     dtype=np.float32)
        for qi in range(nq):
            nt = int(rng.integers(1, tq + 1))
            q[qi, :nt, :dims] = quant_tokens.prep_tokens(
                rng.standard_normal((nt, dims)).astype(np.float32),
                "cosine")
        return ids, q, toks, scales

    def test_f32_matches_reference_tightly(self):
        rng = np.random.default_rng(3)
        ids, q, toks, scales = self._board(rng, "f32")
        got = np.asarray(maxsim_rescore(ids, q, toks, scales))
        ref = np.asarray(maxsim_reference(ids, q, toks, scales))
        # bf16 operands: a few ULPs of drift from contraction order is
        # the ceiling; anything larger is a real math difference
        np.testing.assert_allclose(got, ref, rtol=1e-5, atol=1e-6)

    @pytest.mark.parametrize("encoding", ["bf16", "int8", "int4"])
    def test_quantized_ordering_and_scores(self, encoding):
        rng = np.random.default_rng(4)
        ids, q, toks, scales = self._board(rng, encoding)
        got = np.asarray(maxsim_rescore(ids, q, toks, scales))
        ref = np.asarray(maxsim_reference(ids, q, toks, scales))
        np.testing.assert_allclose(got, ref, rtol=2e-3, atol=2e-3)
        # candidate ordering per query must agree where scores are
        # separated beyond the contraction's ULP drift
        for qi in range(got.shape[0]):
            go, ro = np.argsort(-got[qi]), np.argsort(-ref[qi])
            gv, rv = got[qi][go], ref[qi][ro]
            sep = np.abs(np.diff(rv)) > 1e-2
            stable = np.concatenate([[True], sep]) \
                & np.concatenate([sep, [True]])
            assert np.array_equal(go[stable[:len(go)]],
                                  ro[stable[:len(ro)]])

    def test_zero_scale_padding_scores_neg_inf(self):
        rng = np.random.default_rng(5)
        ids, q, toks, scales = self._board(rng, "f32")
        ids[:, -1] = 31                      # all-zero padding row
        got = np.asarray(maxsim_rescore(ids, q, toks, scales))
        assert np.all(got[:, -1] <= -1e38)


# ---------------------------------------------------------------- field


def _engine(rng, n_docs=200, dims=16, encoding="int8", oversample=4,
            max_tokens=6, n_topics=12, noise=0.25):
    ms = MapperService({"properties": {
        "colv": {"type": "rank_vectors", "dims": dims,
                 "encoding": encoding, "oversample": oversample}}})
    eng = Engine(tempfile.mkdtemp(), ms)
    _topics, docs = _clustered(rng, n_docs, dims, max_tokens,
                               n_topics=n_topics, noise=noise)
    for i, d in enumerate(docs):
        eng.index(str(i), {"colv": d.tolist()})
    eng.refresh()
    return ms, eng, docs


def _oracle_topk(reader, ms, qtok, k):
    ctx = SearchContext(reader, ms)
    ds = parse_query({"late_interaction": {
        "field": "colv", "query_tokens": qtok.tolist()}}).execute(ctx)
    order = np.lexsort((ds.rows, -ds.scores))[:k]
    return ds.rows[order], ds.scores[order]


class TestRecall:
    @pytest.mark.parametrize("encoding", ["int8", "int4"])
    def test_recall_at_10_vs_exact_host_oracle(self, encoding):
        """ColBERT-shaped geometry: 64-dim tokens, ~8 docs per topic so
        a top-10 crosses cluster boundaries (separations above the int4
        step; within-cluster near-ties below it are legitimately
        unordered at 4 bits and are what oversample covers)."""
        rng = np.random.default_rng(7)
        ms, eng, docs = _engine(rng, dims=64, encoding=encoding,
                                oversample=8, n_topics=24, noise=0.8)
        reader = eng.acquire_searcher()
        shard = LateInteractionShard()
        mapper = ms.get("colv")
        hits = total = 0
        for t in range(12):
            base = docs[t * 7 % len(docs)][:4]
            qtok = base + 0.1 * rng.standard_normal(
                base.shape).astype(np.float32)
            (rows, _), = shard.search_batch(reader, mapper,
                                            [(qtok, 1.0)], 10)
            oracle_rows, _ = _oracle_topk(reader, ms, qtok, 10)
            hits += len(set(rows.tolist()) & set(oracle_rows.tolist()))
            total += 10
        recall = hits / total
        assert recall >= 0.95, f"{encoding} recall@10 {recall:.3f}"

    def test_full_window_matches_oracle_ordering(self):
        """oversample wide enough to cover the corpus: the coarse prune
        is a no-op, so device ordering equals the oracle's modulo int8
        quantization on near-ties."""
        rng = np.random.default_rng(9)
        ms, eng, docs = _engine(rng, n_docs=100, oversample=32)
        reader = eng.acquire_searcher()
        shard = LateInteractionShard()
        mapper = ms.get("colv")
        qtok = docs[5][:3]
        (rows, scores), = shard.search_batch(reader, mapper,
                                             [(qtok, 1.0)], 10)
        oracle_rows, oracle_scores = _oracle_topk(reader, ms, qtok, 10)
        assert len(set(rows.tolist()) & set(oracle_rows.tolist())) >= 9
        np.testing.assert_allclose(
            scores[:5], oracle_scores[:5], rtol=5e-2)

    def test_boost_scales_scores(self):
        rng = np.random.default_rng(10)
        ms, eng, docs = _engine(rng, n_docs=60)
        reader = eng.acquire_searcher()
        shard = LateInteractionShard()
        mapper = ms.get("colv")
        qtok = docs[3][:2]
        (r1, s1), = shard.search_batch(reader, mapper, [(qtok, 1.0)], 5)
        (r2, s2), = shard.search_batch(reader, mapper, [(qtok, 2.5)], 5)
        assert np.array_equal(r1, r2)
        np.testing.assert_allclose(s2, s1 * np.float32(2.5), rtol=1e-6)


class TestLifecycle:
    def test_append_delete_rebuild(self):
        rng = np.random.default_rng(11)
        ms = MapperService({"properties": {
            "colv": {"type": "rank_vectors", "dims": 8,
                     "oversample": 32}}})
        eng = Engine(tempfile.mkdtemp(), ms)
        for i in range(40):
            eng.index(str(i), {
                "colv": rng.standard_normal((3, 8)).tolist()})
        eng.refresh()
        shard = LateInteractionShard()
        mapper = ms.get("colv")
        reader = eng.acquire_searcher()
        qtok = rng.standard_normal((2, 8)).astype(np.float32)
        shard.search_batch(reader, mapper, [(qtok, 1.0)], 5)
        assert shard.stats["rebuilds"] == 1
        shard.search_batch(reader, mapper, [(qtok, 1.0)], 5)
        assert shard.stats["rebuilds"] == 1       # same reader

        for i in range(40, 60):
            eng.index(str(i), {
                "colv": rng.standard_normal((4, 8)).tolist()})
        eng.refresh()
        reader2 = eng.acquire_searcher()
        (rows, _), = shard.search_batch(reader2, mapper, [(qtok, 1.0)], 60)
        assert shard.stats["rebuilds"] == 2
        oracle_rows, _ = _oracle_topk(reader2, ms, qtok, 60)
        assert set(rows.tolist()) == set(oracle_rows.tolist())

        eng.delete("3")
        eng.refresh()
        reader3 = eng.acquire_searcher()
        (rows, _), = shard.search_batch(reader3, mapper, [(qtok, 1.0)], 60)
        assert shard.stats["rebuilds"] == 3
        assert not any(reader3.get_id(int(r)) == "3" for r in rows)

    def test_docs_without_field_are_absent(self):
        rng = np.random.default_rng(12)
        ms = MapperService({"properties": {
            "colv": {"type": "rank_vectors", "dims": 8,
                     "oversample": 32}}})
        eng = Engine(tempfile.mkdtemp(), ms)
        eng.index("a", {"colv": rng.standard_normal((2, 8)).tolist()})
        eng.index("b", {})
        eng.index("c", {"colv": rng.standard_normal((3, 8)).tolist()})
        eng.refresh()
        reader = eng.acquire_searcher()
        shard = LateInteractionShard()
        lf = shard.field(reader, ms.get("colv"))
        assert lf.n_docs == 2
        (rows, _), = shard.search_batch(
            reader, ms.get("colv"),
            [(rng.standard_normal((2, 8)).astype(np.float32), 1.0)], 10)
        assert {reader.get_id(int(r)) for r in rows} == {"a", "c"}

    def test_padding_rows_reserved_and_never_surface(self):
        rng = np.random.default_rng(13)
        ms, eng, docs = _engine(rng, n_docs=33, oversample=32)
        reader = eng.acquire_searcher()
        shard = LateInteractionShard()
        lf = shard.field(reader, ms.get("colv"))
        assert lf.n_pad > lf.n_docs              # >= 1 all-zero row
        assert np.all(lf.tile_scales[lf.n_docs:] == 0.0)
        (rows, scores), = shard.search_batch(
            reader, ms.get("colv"), [(docs[0][:2], 1.0)], 33)
        assert len(rows) <= 33 and np.all(np.isfinite(scores))
        assert rows.max() < 33


class TestDispatchGrid:
    def test_strict_zero_recompile_second_pass(self):
        rng = np.random.default_rng(14)
        ms, eng, docs = _engine(rng, n_docs=120)
        reader = eng.acquire_searcher()
        shard = LateInteractionShard()
        mapper = ms.get("colv")
        queries = [(docs[i][:3], 1.0) for i in range(3)]
        shard.search_batch(reader, mapper, queries, 10)      # warm
        before = dispatch.DISPATCH.compile_count()
        strict_before = dispatch.DISPATCH.strict
        dispatch.DISPATCH.strict = True
        try:
            got = shard.search_batch(reader, mapper, queries, 10)
        finally:
            dispatch.DISPATCH.strict = strict_before
        assert got is not None
        assert dispatch.DISPATCH.compile_count() == before

    def test_warmup_entries_precompile_grid(self):
        rng = np.random.default_rng(15)
        ms, eng, docs = _engine(rng, n_docs=90)
        reader = eng.acquire_searcher()
        shard = LateInteractionShard()
        mapper = ms.get("colv")
        entries = shard.warmup_entries(reader, mapper)
        assert entries
        dispatch.DISPATCH.warmup(entries, background=False)
        before = dispatch.DISPATCH.compile_count()
        shard.search_batch(reader, mapper, [(docs[0][:3], 1.0)], 10)
        assert dispatch.DISPATCH.compile_count() == before


class TestNodePath:
    def test_three_leg_hybrid_and_fallback_count(self):
        from elasticsearch_tpu.node import Node
        rng = np.random.default_rng(16)
        n = Node(tempfile.mkdtemp())
        n.create_index_with_templates("li", mappings={"properties": {
            "body": {"type": "text"},
            "feats": {"type": "rank_features"},
            "colv": {"type": "rank_vectors", "dims": 16}}})
        _topics, docs = _clustered(rng, 80, 16, 5)
        ops = []
        for i, d in enumerate(docs):
            ops.append({"index": {"_index": "li", "_id": str(i)}})
            ops.append({"body": " ".join(rng.choice(list("abcd"), 4)),
                        "feats": {f"t{j}": 1.0
                                  for j in rng.integers(0, 20, 3)},
                        "colv": d.tolist()})
        n.bulk(ops)
        n.indices.get("li").refresh()
        try:
            body = {"rank": {"rrf": {}}, "sub_searches": [
                {"query": {"match": {"body": "a b"}}},
                {"query": {"sparse_vector": {
                    "field": "feats",
                    "query_vector": {"t1": 2.0, "t2": 1.0}}}},
                {"query": {"late_interaction": {
                    "field": "colv", "query_tokens": docs[0].tolist(),
                    "k": 10}}}], "size": 10}
            resp = n.search("li", body)
            assert len(resp["hits"]["hits"]) == 10
            ex = n._hybrid[n.indices.get("li").name]
            assert ex.late.stats["searches"] >= 1

            # over-grid query-token count -> counted walker fallback
            wide = rng.standard_normal(
                (MAX_QUERY_TOKENS + 4, 16)).tolist()
            n.search("li", {"rank": {"rrf": {}}, "sub_searches": [
                {"query": {"match": {"body": "a"}}},
                {"query": {"late_interaction": {
                    "field": "colv", "query_tokens": wide}}}],
                "size": 5})
            assert ex.stats["maxsim_grid_fallbacks"] >= 1
            hyb = n.local_node_stats()["indices"]["hybrid"]
            assert hyb["late_interaction"]["searches"] >= 1
            assert hyb["late_interaction"]["grid_fallbacks"] >= 1
            assert "colv" in hyb["late_interaction"]["fields"]
        finally:
            n.close()


class TestMapping:
    def test_rank_vectors_validation(self):
        from elasticsearch_tpu.common.errors import (
            IllegalArgumentError, MapperParsingError)
        with pytest.raises((IllegalArgumentError, MapperParsingError)):
            MapperService({"properties": {
                "c": {"type": "rank_vectors"}}})          # dims required
        with pytest.raises((IllegalArgumentError, MapperParsingError)):
            MapperService({"properties": {
                "c": {"type": "rank_vectors", "dims": 7,
                      "encoding": "int4"}}})              # odd dims
        ms = MapperService({"properties": {
            "c": {"type": "rank_vectors", "dims": 8}}})
        m = ms.get("c")
        assert (m.encoding, m.similarity, m.oversample) \
            == ("int8", "cosine", 4)

    def test_dims_mismatch_rejected_at_index_time(self):
        ms = MapperService({"properties": {
            "c": {"type": "rank_vectors", "dims": 8}}})
        eng = Engine(tempfile.mkdtemp(), ms)
        with pytest.raises(Exception):
            eng.index("x", {"c": [[1.0] * 5]})
