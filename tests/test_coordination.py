"""Deterministic simulation of the coordination layer.

The analog of `AbstractCoordinatorTestCase` + `LinearizabilityChecker`
(SURVEY.md §4.3): whole clusters on a virtual clock with seeded random
message interleavings, partitions, and node kills; safety invariants
asserted over every run:
  S1  at most one leader per term
  S2  committed (term, version) pairs form a single totally-ordered lineage:
      a committed version is never re-committed with different content
  S3  committed metadata is never lost by later committed states
"""

import pytest

from elasticsearch_tpu.cluster.coordination import (
    CANDIDATE, FOLLOWER, LEADER, Coordinator, PersistedState, bootstrap_state,
)
from elasticsearch_tpu.cluster.state import DiscoveryNode
from elasticsearch_tpu.testing.deterministic import (
    DeterministicTaskQueue, DisruptableTransport,
)


class SimCluster:
    def __init__(self, node_ids, seed=0):
        self.queue = DeterministicTaskQueue(seed=seed)
        self.transport = DisruptableTransport(self.queue)
        self.node_ids = list(node_ids)
        initial = bootstrap_state(self.node_ids)
        self.nodes = {}
        self.committed_log = {}   # (term, version) -> state dict (S2)
        self.leaders_by_term = {} # term -> set of node ids ever leader (S1)
        for nid in node_ids:
            persisted = PersistedState(0, initial)
            node = DiscoveryNode(nid)
            coord = Coordinator(
                node, persisted, self.transport, self.queue,
                seed_peers=[p for p in node_ids if p != nid],
                on_committed=lambda s, n=nid: self._check_commit(n, s))
            self.nodes[nid] = coord
        for coord in self.nodes.values():
            coord.start()

    def _check_commit(self, node_id, state):
        key = (state.term, state.version)
        snap = state.to_dict()
        if key in self.committed_log:
            assert self.committed_log[key]["metadata"] == snap["metadata"], \
                f"S2 violated: different content committed at {key}"
        else:
            self.committed_log[key] = snap

    def observe_leaders(self):
        for nid, coord in self.nodes.items():
            if coord.mode == LEADER:
                term = coord.state.current_term
                self.leaders_by_term.setdefault(term, set()).add(nid)

    def run(self, ms, observe_every=50):
        end = self.queue.now_ms + ms
        while self.queue.now_ms < end:
            self.queue.run_for(observe_every)
            self.observe_leaders()
            self.assert_single_leader_per_term()

    def assert_single_leader_per_term(self):
        for term, leaders in self.leaders_by_term.items():
            assert len(leaders) <= 1, f"S1 violated: term {term} leaders {leaders}"

    def leader(self):
        live = [c for c in self.nodes.values() if c.mode == LEADER and not c.stopped]
        return live[0] if live else None

    def converged(self, exclude=()):
        states = [(c.committed_state.term, c.committed_state.version)
                  for nid, c in self.nodes.items()
                  if nid not in exclude and not c.stopped]
        return len(set(states)) == 1


@pytest.mark.parametrize("seed", [0, 1, 7])
def test_three_node_election_and_convergence(seed):
    sim = SimCluster(["n0", "n1", "n2"], seed=seed)
    sim.run(30_000)
    leader = sim.leader()
    assert leader is not None, "no leader elected"
    # all nodes follow the same leader and share the committed state
    assert sim.converged()
    for nid, c in sim.nodes.items():
        assert c.known_leader == leader.node.node_id
        assert set(c.committed_state.nodes) == {"n0", "n1", "n2"}


def test_publish_metadata_update():
    sim = SimCluster(["n0", "n1", "n2"], seed=3)
    sim.run(30_000)
    leader = sim.leader()
    ok = leader.publish_state_update(
        lambda s: s.with_(metadata={**s.metadata, "idx": {"settings": {"shards": 2}}}))
    assert ok
    sim.run(5_000)
    for c in sim.nodes.values():
        assert c.committed_state.metadata.get("idx") == {"settings": {"shards": 2}}


def test_leader_partition_failover_preserves_committed():
    sim = SimCluster(["n0", "n1", "n2"], seed=11)
    sim.run(30_000)
    old_leader = sim.leader()
    assert old_leader is not None
    old_leader.publish_state_update(
        lambda s: s.with_(metadata={**s.metadata, "durable": {"v": 1}}))
    sim.run(5_000)
    assert sim.converged()

    # cut the leader off from both followers
    others = {nid for nid in sim.nodes if nid != old_leader.node.node_id}
    sim.transport.partition({old_leader.node.node_id}, others)
    sim.run(60_000)
    new_leader = None
    for nid in others:
        if sim.nodes[nid].mode == LEADER:
            new_leader = sim.nodes[nid]
    assert new_leader is not None, "majority side failed to elect"
    assert new_leader.state.current_term > old_leader.state.current_term or \
        old_leader.mode != LEADER
    # S3: the committed metadata survives failover
    assert new_leader.committed_state.metadata.get("durable") == {"v": 1}

    # heal: old leader rejoins as follower and catches up
    sim.transport.heal_all()
    sim.run(60_000)
    assert sim.nodes[old_leader.node.node_id].mode in (FOLLOWER, LEADER)
    assert sim.converged()


def test_minority_cannot_elect():
    sim = SimCluster(["n0", "n1", "n2", "n3", "n4"], seed=5)
    sim.run(40_000)
    assert sim.leader() is not None
    # isolate two nodes: they must never form a quorum
    sim.transport.partition({"n0", "n1"}, {"n2", "n3", "n4"})
    # figure out which side the leader is on; minority side loses leadership
    sim.run(60_000)
    minority = {"n0", "n1"}
    for nid in minority:
        c = sim.nodes[nid]
        if c.mode == LEADER:
            # a minority leader can remain in LEADER mode only if it can't
            # learn otherwise, but must not commit anything new
            pass
    majority_leader = [sim.nodes[n] for n in ("n2", "n3", "n4")
                       if sim.nodes[n].mode == LEADER]
    assert majority_leader, "majority side must have a leader"
    # publishes on the majority side succeed
    ok = majority_leader[0].publish_state_update(
        lambda s: s.with_(metadata={**s.metadata, "after_split": True}))
    assert ok
    sim.run(10_000)
    assert majority_leader[0].committed_state.metadata.get("after_split") is True
    # minority never committed it
    for nid in minority:
        assert sim.nodes[nid].committed_state.metadata.get("after_split") is None


def test_node_removed_on_silence_and_rejoin():
    sim = SimCluster(["n0", "n1", "n2"], seed=9)
    sim.run(30_000)
    leader = sim.leader()
    victim = next(nid for nid in sim.nodes if nid != leader.node.node_id)
    sim.transport.blackhole(victim)
    sim.run(60_000)
    leader2 = sim.leader()
    assert leader2 is not None
    assert victim not in leader2.committed_state.nodes, \
        "silent node should be removed from the cluster"
    # heal: the node re-joins via the next election/term or join flow
    sim.transport.heal_node(victim)
    sim.run(120_000)
    leader3 = sim.leader()
    assert leader3 is not None
    assert victim in leader3.committed_state.nodes, "healed node should rejoin"


class RegisterClient:
    """Linearizability-history recorder over the cluster-state register
    (the AbstractCoordinatorTestCase:1065 client analog): writes go
    through the leader's publication and respond with the PREVIOUS value
    once COMMITTED; reads are no-op state tasks responding with the
    current value. Definite failures (submitted to a non-leader) are
    removed from the history; a write whose leader stepped down before
    commit stays open — it may still apply — and completes as TIMED_OUT
    at check time."""

    def __init__(self, key="reg"):
        from elasticsearch_tpu.testing.linearizability import History
        self.key = key
        self.history = History()
        self.next_val = 1

    def _get(self, state):
        return state.metadata.get("__register__", {}).get(self.key, 0)

    def write(self, coord):
        val = self.next_val
        self.next_val += 1
        eid = self.history.invoke((self.key, ("w", val)))
        box = {}

        def updater(s):
            box["prev"] = self._get(s)
            regs = {**s.metadata.get("__register__", {}), self.key: val}
            return s.with_(metadata={**s.metadata, "__register__": regs})

        def on_commit(ok):
            if ok and "prev" in box:
                self.history.respond(eid, box["prev"])

        submitted = coord.publish_state_update(updater, on_commit)
        if not submitted and "prev" not in box:
            # rejected before the updater ran (not leader): provably
            # never reached the system
            self.history.remove(eid)

    def read(self, coord):
        eid = self.history.invoke((self.key, ("r", None)))
        box = {}

        def updater(s):
            box["v"] = self._get(s)
            return s

        def on_commit(ok):
            if ok and "v" in box:
                self.history.respond(eid, box["v"])

        submitted = coord.publish_state_update(updater, on_commit)
        if not submitted and "v" not in box:
            self.history.remove(eid)

    def assert_linearizable(self):
        from elasticsearch_tpu.testing.linearizability import (
            KeyedSpec, TIMED_OUT, is_linearizable, visualize,
        )

        class Spec(KeyedSpec):
            def initial_state(self):
                return 0

            def next_state(self, state, inp, out):
                kind, val = inp
                if kind == "w":
                    if out is TIMED_OUT or out == state:
                        return val
                    return None
                if out is TIMED_OUT or out == state:
                    return state
                return None

            def get_key(self, inp):
                return inp[0]

            def get_value(self, inp):
                return inp[1]

        h = self.history.clone()
        h.complete(lambda inp: TIMED_OUT)
        # h is already complete, so the checker's internal completion pass
        # is a no-op; the same object feeds the failure diagram
        assert is_linearizable(Spec(), h), \
            f"history not linearizable:\n{visualize(h)}"


@pytest.mark.parametrize("seed", list(range(6)))
def test_random_disruption_storm_safety(seed):
    """Random partitions/heals with a register client running throughout;
    asserts S1/S2 continuously AND, at the end, that the client-visible
    operation history is linearizable (Wing & Gong, the reference's
    LinearizabilityChecker.java:63 harness behavior)."""
    sim = SimCluster(["n0", "n1", "n2", "n3", "n4"], seed=seed)
    rng = sim.queue.rng
    client = RegisterClient()

    def client_ops():
        # a couple of operations against RANDOM nodes (stale leaders
        # included — that's the point)
        for _ in range(rng.randint(1, 3)):
            coord = sim.nodes[rng.choice(list(sim.nodes))]
            if coord.stopped:
                continue
            if rng.random() < 0.5:
                client.write(coord)
            else:
                client.read(coord)

    for _ in range(8):
        sim.run(7_500)
        client_ops()
        sim.run(7_500)
        if rng.random() < 0.6:
            ids = list(sim.nodes)
            rng.shuffle(ids)
            cut = rng.randint(1, 2)
            sim.transport.heal_all()
            sim.transport.partition(set(ids[:cut]), set(ids[cut:]))
        else:
            sim.transport.heal_all()
        client_ops()
    sim.transport.heal_all()
    sim.run(120_000)
    assert sim.leader() is not None
    assert sim.converged()
    ops = sum(1 for e in client.history.events if e[0] == "invocation")
    assert ops > 0, "storm ran without recording any client operations"
    client.assert_linearizable()


def test_stale_leader_never_false_acks(make_cluster=None):
    """A deposed leader's uncommitted update must fail its waiter — never
    ack on a NEWER term's unrelated commit (commit-gated acks)."""
    from elasticsearch_tpu.cluster.coordination import (
        CoordinationState, PersistedState, bootstrap_state,
    )
    from elasticsearch_tpu.cluster.state import ClusterState

    initial = bootstrap_state(["a", "b", "c"])
    st = CoordinationState("a", PersistedState(0, initial))
    # win term 1
    st.handle_start_join("a", 1)
    for voter in ("a", "b"):
        st.handle_join({"source": voter, "target": "a", "term": 1,
                        "last_accepted_term": 0, "last_accepted_version": 0})
    assert st.election_won
    # a Coordinator-level check: waiters keyed (term=1, v) must not match
    # a commit at term 2 under the exact-term rule
    from elasticsearch_tpu.cluster import coordination as coord
    fired = []
    class FakeSched:
        now_ms = 0
        def schedule_in(self, *a, **k):
            pass
    class FakeTransport:
        def register(self, *a):
            pass
        def send(self, *a, **k):
            pass
    c = coord.Coordinator(
        coord.DiscoveryNode("a"), PersistedState(0, initial),
        FakeTransport(), FakeSched(), seed_peers=["b", "c"])
    c._commit_waiters.append((1, 5, lambda ok: fired.append(("old", ok))))
    c._commit_waiters.append((2, 3, lambda ok: fired.append(("new", ok))))
    committed = ClusterState(term=2, version=3, master_node_id="b",
                             last_committed_config=initial.last_committed_config,
                             last_accepted_config=initial.last_accepted_config)
    c._apply_committed(committed)
    assert ("old", False) in fired, f"stale-term waiter not failed: {fired}"
    assert ("new", True) in fired, f"same-term waiter not acked: {fired}"
