"""In-process multi-node cluster tests (InternalTestCluster analog, §4.2):
full ClusterNodes over the deterministic transport — replication, recovery,
primary failover, distributed search."""

import pytest

from elasticsearch_tpu.cluster.cluster_node import ClusterNode
from elasticsearch_tpu.cluster.coordination import bootstrap_state
from elasticsearch_tpu.cluster.state import ShardRoutingEntry
from elasticsearch_tpu.testing.deterministic import (
    DeterministicTaskQueue, DisruptableTransport,
)


class TestCluster:
    def __init__(self, tmp_path, n_nodes=3, seed=0):
        self.queue = DeterministicTaskQueue(seed=seed)
        self.transport = DisruptableTransport(self.queue)
        ids = [f"n{i}" for i in range(n_nodes)]
        initial = bootstrap_state(ids)
        self.nodes = {}
        for nid in ids:
            self.nodes[nid] = ClusterNode(
                nid, str(tmp_path / nid), self.transport, self.queue,
                seed_peers=[p for p in ids if p != nid], initial_state=initial)
        for n in self.nodes.values():
            n.start()

    def add_node(self, nid, tmp_path, attributes=None):
        """Join a fresh node to the running cluster (node-join event)."""
        peers = [p for p in self.nodes if p != nid]
        node = ClusterNode(nid, str(tmp_path / nid), self.transport,
                           self.queue, seed_peers=peers,
                           initial_state=self.nodes[peers[0]].cluster_state,
                           attributes=attributes)
        self.nodes[nid] = node
        node.start()
        return node

    def run_until(self, cond, max_ms=120_000, step=200):
        waited = 0
        while waited < max_ms:
            self.queue.run_for(step)
            waited += step
            if cond():
                return True
        return cond()

    def master(self):
        for n in self.nodes.values():
            if n.is_master and not n.coordinator.stopped:
                return n
        return None

    def any_node(self, exclude=()):
        for nid, n in self.nodes.items():
            if nid not in exclude and not n.coordinator.stopped:
                return n
        raise AssertionError("no live node")

    def all_started(self, index):
        n = self.any_node()
        shards = n.cluster_state.shards_of(index)
        return bool(shards) and all(
            s.state == ShardRoutingEntry.STARTED for s in shards)

    def call(self, fn, *args, **kw):
        """Invoke a callback-style client method; run the sim until it responds."""
        box = {}
        fn(*args, **kw, on_done=lambda r: box.update(r=r))
        ok = self.run_until(lambda: "r" in box)
        assert ok, f"no response from {fn.__name__}"
        return box["r"]


@pytest.fixture
def cluster(tmp_path):
    c = TestCluster(tmp_path, n_nodes=3, seed=17)
    # ensureStableCluster analog: master elected AND every node joined —
    # otherwise index creation races the joins and allocation is lopsided
    def stable():
        m = c.master()
        return m is not None and len(m.cluster_state.nodes) == 3
    assert c.run_until(stable), "cluster did not stabilize"
    yield c
    for n in c.nodes.values():
        if not n.coordinator.stopped:
            n.stop()


def test_replicated_index_and_search(cluster):
    c = cluster
    c.any_node().client_create_index(
        "docs", settings={"index.number_of_shards": 2, "index.number_of_replicas": 1},
        mappings={"properties": {"title": {"type": "text"}, "n": {"type": "long"}}})
    assert c.run_until(lambda: c.all_started("docs")), "shards did not start"

    # write through a non-master node; replicate synchronously
    writer = c.any_node()
    for i in range(20):
        r = c.call(writer.client_write, "docs",
                   {"type": "index", "id": str(i),
                    "source": {"title": f"doc number {i}", "n": i}})
        assert r["result"] == "created", r

    # every copy holds its shard's docs: primary count == replica count
    counts = {}
    for nid, node in c.nodes.items():
        for key, shard in node.local_shards.items():
            counts.setdefault((key, shard.routing.primary), 0)
            counts[(key, shard.routing.primary)] += shard.engine.doc_count()
    for (key, _), cnt in counts.items():
        primary_cnt = counts.get((key, True))
        assert cnt == primary_cnt, f"replica of {key} diverged: {counts}"

    for node in c.nodes.values():
        node.refresh_all()

    # distributed search through any node
    resp = c.call(c.any_node().client_search, "docs",
                  {"query": {"match": {"title": "doc"}}, "size": 25,
                   "sort": [{"n": "asc"}]})
    assert resp["hits"]["total"]["value"] == 20
    assert [h["_source"]["n"] for h in resp["hits"]["hits"]] == list(range(20))
    assert resp["_shards"]["failed"] == 0

    # realtime get routed to the primary
    got = c.call(c.any_node().client_get, "docs", "13")
    assert got["found"] and got["_source"]["n"] == 13


def test_primary_failover_preserves_data(cluster):
    c = cluster
    c.any_node().client_create_index(
        "ha", settings={"index.number_of_shards": 1, "index.number_of_replicas": 1},
        mappings={"properties": {"v": {"type": "long"}}})
    assert c.run_until(lambda: c.all_started("ha"))

    for i in range(10):
        c.call(c.any_node().client_write, "ha",
               {"type": "index", "id": str(i), "source": {"v": i}})

    state = c.any_node().cluster_state
    primary = state.primary_of("ha", 0)
    victim = primary.node_id
    # kill the node holding the primary
    c.transport.blackhole(victim)
    c.nodes[victim].stop()

    def promoted():
        # every LIVE node must see the post-failover primary, else a client
        # on a stale node would still route to the dead one
        for nid, n in c.nodes.items():
            if nid == victim or n.coordinator.stopped:
                continue
            p = n.cluster_state.primary_of("ha", 0)
            if p is None or not p.node_id or p.node_id == victim:
                return False
        return True

    assert c.run_until(promoted, max_ms=240_000), "no failover promotion"

    survivor = c.any_node(exclude={victim})
    # all 10 docs survive on the promoted replica
    for i in range(10):
        got = c.call(survivor.client_get, "ha", str(i))
        assert got["found"], f"doc {i} lost in failover"
    # and writes continue on the new primary
    r = c.call(survivor.client_write, "ha",
               {"type": "index", "id": "99", "source": {"v": 99}})
    assert r["result"] == "created"

    # replica gets re-allocated on the remaining third node and recovers
    def green_again():
        shards = survivor.cluster_state.shards_of("ha")
        started = [s for s in shards if s.state == ShardRoutingEntry.STARTED
                   and s.node_id != victim]
        return len(started) >= 2

    assert c.run_until(green_again, max_ms=240_000), "replica not re-established"
    # the recovered replica holds all 11 docs
    for nid, n in c.nodes.items():
        if nid == victim or n.coordinator.stopped:
            continue
        for key, shard in n.local_shards.items():
            if key == ("ha", 0) and not shard.routing.primary:
                assert shard.engine.doc_count() == 11, \
                    f"recovered replica has {shard.engine.doc_count()} docs"


def test_kill_copy_holder_keeps_data_searchable(cluster):
    """ROADMAP regression (found via the live 3-node repro): SIGKILL a
    copy-holding node → health goes green again, but searches on the
    survivors returned 0 docs. Root cause was NOT allocation (promotion
    from the in-sync set worked): the re-established replica applied its
    peer-recovery ops but never REFRESHED, so its searcher served an
    empty view forever — green-but-empty. The fix refreshes the engine
    before the replica reports started; this test pins search-VISIBLE
    data on every copy, not just engine doc counts."""
    c = cluster
    c.any_node().client_create_index(
        "vis", settings={"index.number_of_shards": 1,
                         "index.number_of_replicas": 1},
        mappings={"properties": {"v": {"type": "long"}}})
    assert c.run_until(lambda: c.all_started("vis"))
    for i in range(20):
        c.call(c.any_node().client_write, "vis",
               {"type": "index", "id": str(i), "source": {"v": i}})
    for node in c.nodes.values():
        node.refresh_all()

    # kill the PRIMARY holder (a copy holder whose loss exercises both
    # promotion and replica re-establishment on the data-free node)
    state = c.any_node().cluster_state
    victim = state.primary_of("vis", 0).node_id
    c.transport.blackhole(victim)
    c.nodes[victim].stop()

    def green_again():
        n = c.any_node(exclude={victim})
        shards = [s for s in n.cluster_state.shards_of("vis")
                  if s.node_id and s.node_id != victim]
        return len(shards) >= 2 and all(
            s.state == ShardRoutingEntry.STARTED for s in shards)

    assert c.run_until(green_again, max_ms=240_000), \
        "cluster never re-established both copies"

    # EVERY copy must serve the full doc set through its SEARCHER — the
    # engine holding the ops is not enough (the green-but-empty bug)
    for nid, n in c.nodes.items():
        if nid == victim or n.coordinator.stopped:
            continue
        for key, shard in n.local_shards.items():
            if key != ("vis", 0):
                continue
            reader = shard.engine.acquire_searcher()
            assert reader.num_docs == 20, (
                f"copy on {nid} (primary={shard.routing.primary}) "
                f"searcher sees {reader.num_docs}/20 docs — "
                f"green-but-empty regression")

    # and distributed searches through EITHER survivor return everything
    for nid, n in c.nodes.items():
        if nid == victim or n.coordinator.stopped:
            continue
        resp = c.call(n.client_search, "vis",
                      {"query": {"match_all": {}}, "size": 0})
        assert resp["hits"]["total"]["value"] == 20, \
            f"search via {nid} lost docs: {resp['hits']['total']}"


def test_write_through_any_node_routes_to_primary(cluster):
    c = cluster
    c.any_node().client_create_index(
        "routed", settings={"index.number_of_shards": 3, "index.number_of_replicas": 0})
    assert c.run_until(lambda: c.all_started("routed"))
    for i in range(30):
        writer = list(c.nodes.values())[i % 3]
        r = c.call(writer.client_write, "routed",
                   {"type": "index", "id": f"k{i}", "source": {"i": i}})
        assert r["result"] == "created"
    total = sum(s.engine.doc_count()
                for n in c.nodes.values() for s in n.local_shards.values())
    assert total == 30
    # shard counts are balanced-ish across the 3 nodes (each has exactly 1 shard)
    per_node = {nid: len(n.local_shards) for nid, n in c.nodes.items()}
    assert all(v == 1 for v in per_node.values()), per_node


def test_delete_index_cleans_up(cluster):
    c = cluster
    c.any_node().client_create_index("temp", settings={"index.number_of_shards": 1})
    assert c.run_until(lambda: c.all_started("temp"))
    c.any_node().client_delete_index("temp")
    assert c.run_until(lambda: all(
        ("temp", 0) not in n.local_shards for n in c.nodes.values()))
    assert "temp" not in c.any_node().cluster_state.metadata


def test_total_copy_loss_goes_red_not_empty(tmp_path):
    """Losing every copy of a shard must leave it red/unassigned — never
    fabricate a fresh empty primary (silent data loss). Needs 5 nodes so the
    master quorum survives losing both copy holders."""
    c = TestCluster(tmp_path, n_nodes=5, seed=23)
    assert c.run_until(lambda: c.master() is not None)
    c.any_node().client_create_index(
        "red", settings={"index.number_of_shards": 1, "index.number_of_replicas": 1})
    assert c.run_until(lambda: c.all_started("red"))
    for i in range(5):
        c.call(c.any_node().client_write, "red",
               {"type": "index", "id": str(i), "source": {"v": i}})
    state = c.any_node().cluster_state
    holders = {r.node_id for r in state.shards_of("red") if r.node_id}
    assert len(holders) == 2
    for nid in holders:
        c.transport.blackhole(nid)
        c.nodes[nid].stop()
    survivor = c.any_node(exclude=holders)

    def holders_removed():
        return all(h not in c.any_node(exclude=holders).cluster_state.nodes
                   for h in holders)

    assert c.run_until(holders_removed, max_ms=240_000), "dead nodes not removed"
    c.queue.run_for(60_000)
    shards = survivor.cluster_state.shards_of("red")
    primaries = [r for r in shards if r.primary]
    assert primaries, "primary entry disappeared"
    for p in primaries:
        assert p.state == ShardRoutingEntry.UNASSIGNED, \
            f"red shard was silently re-allocated: {p.to_dict()}"
    resp = c.call(survivor.client_search, "red", {"query": {"match_all": {}}})
    assert resp["_shards"]["failed"] >= 1
    assert resp["hits"]["total"]["value"] == 0
    for n in c.nodes.values():
        if not n.coordinator.stopped:
            n.stop()


def test_cross_shard_metric_aggs_correct(cluster):
    """Round-1 regression: metric aggs across shards with divergent data
    must equal single-shard ground truth (the old merge kept shard 0's
    value). Docs are routed so the two shards hold disjoint value ranges."""
    c = cluster
    c.any_node().client_create_index(
        "skew", settings={"index.number_of_shards": 2,
                          "index.number_of_replicas": 0},
        mappings={"properties": {"cat": {"type": "keyword"},
                                 "name": {"type": "keyword"},
                                 "v": {"type": "double"}}})
    assert c.run_until(lambda: c.all_started("skew"))

    writer = c.any_node()
    vals = [float(i) for i in range(60)]
    for i, v in enumerate(vals):
        r = c.call(writer.client_write, "skew",
                   {"type": "index", "id": str(i),
                    "source": {"cat": ["a", "b"][i % 2],
                               "name": f"n{i % 11}", "v": v}})
        assert r["result"] == "created", r
    for node in c.nodes.values():
        node.refresh_all()

    # sanity: data actually spans both shards
    per_shard = {}
    for node in c.nodes.values():
        for (idx, sid), shard in node.local_shards.items():
            if idx == "skew" and shard.routing.primary:
                per_shard[sid] = shard.engine.doc_count()
    assert len(per_shard) == 2 and all(n > 0 for n in per_shard.values()), per_shard

    resp = c.call(c.any_node().client_search, "skew", {
        "size": 0,
        "aggs": {
            "mean": {"avg": {"field": "v"}},
            "card": {"cardinality": {"field": "name"}},
            "pct": {"percentiles": {"field": "v", "percents": [50]}},
            "cats": {"terms": {"field": "cat"},
                     "aggs": {"m": {"avg": {"field": "v"}}}},
        }})
    aggs = resp["aggregations"]
    assert abs(aggs["mean"]["value"] - sum(vals) / len(vals)) < 1e-9
    assert aggs["card"]["value"] == 11
    assert abs(aggs["pct"]["values"]["50.0"] - 29.5) < 1.5
    buckets = {b["key"]: b for b in aggs["cats"]["buckets"]}
    evens = [v for i, v in enumerate(vals) if i % 2 == 0]
    odds = [v for i, v in enumerate(vals) if i % 2 == 1]
    assert buckets["a"]["doc_count"] == 30
    assert abs(buckets["a"]["m"]["value"] - sum(evens) / 30) < 1e-9
    assert abs(buckets["b"]["m"]["value"] - sum(odds) / 30) < 1e-9


def test_peer_recovery_phase1_after_translog_trim(tmp_path):
    """A new replica whose gap the trimmed translog cannot cover must
    bootstrap via phase-1 file copy (RecoverySourceHandler.java:262), not
    silently lose the flushed history."""
    c = TestCluster(tmp_path, n_nodes=3, seed=29)
    assert c.run_until(lambda: c.master() is not None
                       and len(c.master().cluster_state.nodes) == 3)
    c.any_node().client_create_index(
        "keepr", settings={"index.number_of_shards": 1,
                           "index.number_of_replicas": 1},
        mappings={"properties": {"n": {"type": "long"}}})
    assert c.run_until(lambda: c.all_started("keepr"))

    w = c.any_node()
    for i in range(25):
        r = c.call(w.client_write, "keepr",
                   {"type": "index", "id": str(i), "source": {"n": i}})
        assert r["result"] == "created"

    primary_node = replica_node = None
    for nid, node in c.nodes.items():
        sh = node.local_shards.get(("keepr", 0))
        if sh is not None:
            if sh.routing.primary:
                primary_node = nid
            else:
                replica_node = nid
    spare = next(n for n in c.nodes if n not in (primary_node, replica_node))

    # flush the primary: commit + translog trim — ops-only recovery of a
    # fresh copy is now impossible
    pshard = c.nodes[primary_node].local_shards[("keepr", 0)]
    pshard.engine.flush()
    assert not pshard.engine.can_replay_from(0)

    # kill the replica's node; the master reroutes the copy to the spare
    c.transport.blackhole(replica_node)
    c.nodes[replica_node].stop()

    def replica_started_on_spare():
        state = c.nodes[primary_node].cluster_state
        return any(r.node_id == spare and not r.primary
                   and r.state == ShardRoutingEntry.STARTED
                   for r in state.shards_of("keepr"))

    assert c.run_until(replica_started_on_spare, max_ms=240_000), \
        "replica never recovered on the spare node"

    new_shard = c.nodes[spare].local_shards[("keepr", 0)]
    assert new_shard.engine.doc_count() == 25, \
        f"phase-1 recovery lost docs: {new_shard.engine.doc_count()}"

    # the recovered copy keeps receiving live writes
    r = c.call(c.nodes[primary_node].client_write, "keepr",
               {"type": "index", "id": "99", "source": {"n": 99}})
    assert r["result"] == "created"
    assert c.run_until(
        lambda: new_shard.engine.doc_count() == 26, max_ms=30_000)
    for n in c.nodes.values():
        if not n.coordinator.stopped:
            n.stop()


def test_flush_respects_retention_lease(tmp_path):
    """The translog keeps history a peer-recovery retention lease pins."""
    from elasticsearch_tpu.index.engine import Engine
    from elasticsearch_tpu.index.mapping import MapperService

    e = Engine(str(tmp_path / "lease_shard"),
               MapperService({"properties": {"n": {"type": "long"}}}))
    for i in range(10):
        e.index(str(i), {"n": i})
    retained = {"seq": 0}
    e.retained_seq_no_provider = lambda: retained["seq"]
    e.flush()
    # lease pins seq 0: nothing may be trimmed
    assert e.can_replay_from(0)
    assert len(e.translog.read_ops(0)) == 10
    # lease released: next flush trims
    retained["seq"] = e.local_checkpoint + 1
    e.flush()
    assert not e.can_replay_from(0)
    e.close()


def test_two_phase_search_payload_shape(cluster):
    """Query phase ships (row, score, sort) only — no _source — and the
    fetch phase round-trips just the global window (FetchSearchPhase)."""
    c = cluster
    c.any_node().client_create_index(
        "tp", settings={"index.number_of_shards": 2,
                        "index.number_of_replicas": 0},
        mappings={"properties": {"n": {"type": "long"},
                                 "blob": {"type": "keyword"}}})
    assert c.run_until(lambda: c.all_started("tp"))
    w = c.any_node()
    for i in range(40):
        c.call(w.client_write, "tp",
               {"type": "index", "id": str(i),
                "source": {"n": i, "blob": "x" * 500}})
    for n in c.nodes.values():
        n.refresh_all()

    coordinator = c.any_node()
    captured = []
    orig_send = c.transport.send

    def capture_send(sender, target, action, request, **kw):
        captured.append((action, request, kw))
        return orig_send(sender, target, action, request, **kw)

    c.transport.send = capture_send
    try:
        resp = c.call(coordinator.client_search, "tp",
                      {"query": {"match_all": {}}, "size": 5,
                       "sort": [{"n": "asc"}]})
    finally:
        c.transport.send = orig_send
    assert resp["hits"]["total"]["value"] == 40
    assert [h["_source"]["n"] for h in resp["hits"]["hits"]] == [0, 1, 2, 3, 4]

    query_reqs = [r for a, r, k in captured
                  if a == "indices:data/read/query"]
    fetch_reqs = [r for a, r, k in captured
                  if a == "indices:data/read/fetch"]
    assert query_reqs, "query phase never went over the wire"
    # fetch requests cover at most the global window (5 docs total)
    if fetch_reqs:  # remote shards only; local shard fetches in-process
        assert sum(len(r["rows"]) for r in fetch_reqs) <= 5
    # ARS recorded latencies for the queried nodes
    assert getattr(coordinator, "_ars_ewma", {}), "no ARS observations"


def test_ars_prefers_faster_node(cluster):
    c = cluster
    node = c.any_node()
    node._ars_observe("slow", 100.0)
    node._ars_observe("fast", 5.0)
    node._ars_observe("slow", 120.0)
    from elasticsearch_tpu.cluster.state import ShardRoutingEntry as SRE
    copies = [SRE("i", 0, True, "slow", SRE.STARTED, "a1"),
              SRE("i", 0, False, "fast", SRE.STARTED, "a2")]
    assert node._select_copy(copies, 0).node_id == "fast"
    # unknown nodes get probed before measured ones
    copies.append(SRE("i", 0, False, "unknown", SRE.STARTED, "a3"))
    assert node._select_copy(copies, 0).node_id == "unknown"


def test_rebalance_on_node_join_moves_shards_and_keeps_data(tmp_path):
    """A node joining an established cluster attracts shards via the
    weighted balancer (BalancedShardsAllocator.balance): relocations run
    real recoveries, hand off, and drop the source copies — with zero data
    loss and searches green throughout."""
    c = TestCluster(tmp_path, n_nodes=2, seed=43)
    assert c.run_until(lambda: c.master() is not None
                       and len(c.master().cluster_state.nodes) == 2)
    c.any_node().client_create_index(
        "reb", settings={"index.number_of_shards": 6,
                         "index.number_of_replicas": 0},
        mappings={"properties": {"n": {"type": "long"}}})
    assert c.run_until(lambda: c.all_started("reb"))

    w = c.any_node()
    for i in range(30):
        r = c.call(w.client_write, "reb",
                   {"type": "index", "id": str(i), "source": {"n": i}})
        assert r["result"] == "created"

    spare = c.add_node("n9", tmp_path)

    def rebalanced():
        state = c.any_node().cluster_state
        shards = state.shards_of("reb")
        if any(s.state != ShardRoutingEntry.STARTED for s in shards):
            return False
        on_spare = sum(1 for s in shards if s.node_id == "n9")
        return on_spare >= 1 and len(shards) == 6

    assert c.run_until(rebalanced, max_ms=240_000), \
        f"no shards moved to the new node: " \
        f"{[s.to_dict() for s in c.any_node().cluster_state.shards_of('reb')]}"

    # per-node shard counts converged (6 over 3 nodes -> 2 each)
    counts = {}
    for s in c.any_node().cluster_state.shards_of("reb"):
        counts[s.node_id] = counts.get(s.node_id, 0) + 1
    assert max(counts.values()) - min(counts.values()) <= 1, counts

    for n in c.nodes.values():
        n.refresh_all()
    resp = c.call(c.any_node().client_search, "reb",
                  {"query": {"match_all": {}}, "size": 50})
    assert resp["hits"]["total"]["value"] == 30
    assert resp["_shards"]["failed"] == 0

    for n in c.nodes.values():
        if not n.coordinator.stopped:
            n.stop()


def test_filter_exclude_drains_node(tmp_path):
    """cluster.routing.allocation.exclude._name drains a node's shards
    (FilterAllocationDecider can_remain + the move pass)."""
    c = TestCluster(tmp_path, n_nodes=3, seed=47)
    assert c.run_until(lambda: c.master() is not None
                       and len(c.master().cluster_state.nodes) == 3)
    c.any_node().client_create_index(
        "drain", settings={"index.number_of_shards": 3,
                           "index.number_of_replicas": 0},
        mappings={"properties": {"n": {"type": "long"}}})
    assert c.run_until(lambda: c.all_started("drain"))

    w = c.any_node()
    for i in range(12):
        c.call(w.client_write, "drain",
               {"type": "index", "id": str(i), "source": {"n": i}})

    victim = next(nid for nid, n in c.nodes.items()
                  if any(s.index == "drain"
                         for s in n.cluster_state.shards_on_node(nid)))
    r = c.call(c.any_node().client_update_settings,
               {"cluster.routing.allocation.exclude._name": victim})
    assert r.get("acknowledged"), r

    def drained():
        state = c.any_node().cluster_state
        shards = state.shards_of("drain")
        return all(s.state == ShardRoutingEntry.STARTED for s in shards) \
            and not any(s.node_id == victim for s in shards) \
            and len(shards) == 3

    assert c.run_until(drained, max_ms=240_000), \
        [s.to_dict() for s in c.any_node().cluster_state.shards_of("drain")]

    for n in c.nodes.values():
        n.refresh_all()
    resp = c.call(c.any_node().client_search, "drain",
                  {"query": {"match_all": {}}, "size": 20})
    assert resp["hits"]["total"]["value"] == 12

    for n in c.nodes.values():
        if not n.coordinator.stopped:
            n.stop()


def test_can_match_prefilter_skips_shards(cluster):
    """Range searches skip shards whose field stats cannot match
    (CanMatchPreFilterSearchPhase.java:57): docs are laid out so each
    shard holds a disjoint n-range, then a narrow range query with
    pre_filter_shard_size=1 must skip the other shards."""
    from elasticsearch_tpu.cluster.routing import shard_id_for

    c = cluster
    c.any_node().client_create_index(
        "pref", settings={"index.number_of_shards": 3,
                          "index.number_of_replicas": 0},
        mappings={"properties": {"n": {"type": "long"}}})
    assert c.run_until(lambda: c.all_started("pref"))

    # give each shard a disjoint value range: n = shard*1000 + i
    w = c.any_node()
    per_shard = {0: 0, 1: 0, 2: 0}
    for i in range(60):
        sid = shard_id_for(str(i), 3)
        n = sid * 1000 + per_shard[sid]
        per_shard[sid] += 1
        r = c.call(w.client_write, "pref",
                   {"type": "index", "id": str(i), "source": {"n": n}})
        assert r["result"] == "created"
    for node in c.nodes.values():
        node.refresh_all()

    # range hits only shard 1's [1000,2000) band
    resp = c.call(c.any_node().client_search, "pref",
                  {"query": {"range": {"n": {"gte": 1000, "lt": 2000}}},
                   "size": 30, "pre_filter_shard_size": 1})
    assert resp["_shards"]["skipped"] == 2, resp["_shards"]
    assert resp["_shards"]["failed"] == 0
    assert resp["hits"]["total"]["value"] == per_shard[1]
    assert all(1000 <= h["_source"]["n"] < 2000
               for h in resp["hits"]["hits"])

    # without the param, range queries prefilter by DEFAULT (the
    # reference's default-on-range behavior): same hits, other shards
    # still skipped
    resp2 = c.call(c.any_node().client_search, "pref",
                   {"query": {"range": {"n": {"gte": 1000, "lt": 2000}}},
                    "size": 30})
    assert resp2["_shards"]["skipped"] == 2, resp2["_shards"]
    assert resp2["hits"]["total"]["value"] == per_shard[1]

    # an EXPLICIT pre_filter_shard_size above the fan-out width disables
    # the auto-range round: no skipping, same hits
    resp3 = c.call(c.any_node().client_search, "pref",
                   {"query": {"range": {"n": {"gte": 1000, "lt": 2000}}},
                    "size": 30, "pre_filter_shard_size": 128})
    assert resp3["_shards"]["skipped"] == 0
    assert resp3["hits"]["total"]["value"] == per_shard[1]

    # pruning yield lands in the coordinator's fan-out phase counters
    pc = c.any_node().fanout_stats.phases.get("can_match", {})
    assert pc.get("skipped_shards", 0) >= 4, pc

    # non-range queries below the threshold keep the single-round path
    resp4 = c.call(c.any_node().client_search, "pref",
                   {"query": {"match_all": {}}, "size": 0})
    assert resp4["_shards"]["skipped"] == 0


def test_request_cache_serves_agg_search(cluster):
    """size=0 agg searches are served from the shard request cache on
    repeat, and a refresh after new writes invalidates (reader gen key)."""
    c = cluster
    c.any_node().client_create_index(
        "rc", settings={"index.number_of_shards": 2,
                        "index.number_of_replicas": 0},
        mappings={"properties": {"n": {"type": "long"}}})
    assert c.run_until(lambda: c.all_started("rc"))
    w = c.any_node()
    for i in range(10):
        c.call(w.client_write, "rc",
               {"type": "index", "id": str(i), "source": {"n": i}})
    for node in c.nodes.values():
        node.refresh_all()

    body = {"size": 0, "aggs": {"s": {"sum": {"field": "n"}}}}
    r1 = c.call(c.any_node().client_search, "rc", dict(body))
    assert r1["aggregations"]["s"]["value"] == sum(range(10))
    hits_before = sum(n.caches.request.hits for n in c.nodes.values())
    r2 = c.call(c.any_node().client_search, "rc", dict(body))
    assert r2["aggregations"]["s"]["value"] == sum(range(10))
    hits_after = sum(n.caches.request.hits for n in c.nodes.values())
    assert hits_after > hits_before, "second agg search did not hit the cache"

    # new data + refresh -> fresh result, not the stale cached one
    c.call(w.client_write, "rc", {"type": "index", "id": "x",
                                  "source": {"n": 100}})
    for node in c.nodes.values():
        node.refresh_all()
    r3 = c.call(c.any_node().client_search, "rc", dict(body))
    assert r3["aggregations"]["s"]["value"] == sum(range(10)) + 100


def test_master_task_batching_coalesces_publications(cluster):
    """N concurrent state-update tasks drain as O(1) publications
    (MasterService.submitStateUpdateTask batching): submit 10 registry
    updates back-to-back; the committed state version advances by far
    fewer than 10, and every task still acks after commit."""
    master = cluster.master()
    v0 = master.cluster_state.version
    acks = []
    for i in range(10):
        master.coordinator.submit_state_update(
            f"put-registry [k{i}]",
            (lambda i: lambda base: base.with_(metadata={
                **base.metadata,
                "__batch_test__": {**(base.metadata.get("__batch_test__")
                                      or {}), f"k{i}": i}}))(i),
            lambda ok: acks.append(ok))
    # a queue snapshot taken before the drain runs shows pending tasks
    assert cluster.run_until(lambda: len(acks) == 10)
    assert all(acks)
    v1 = master.cluster_state.version
    assert v1 - v0 <= 3, f"{v1 - v0} publications for 10 tasks"
    merged = master.cluster_state.metadata["__batch_test__"]
    assert merged == {f"k{i}": i for i in range(10)}


def test_persistent_task_runs_on_exactly_one_node_and_fails_over(cluster):
    """PersistentTasksClusterService semantics: a registered background
    task ticks on EXACTLY one node; when that node dies, the master
    reassigns it and the new owner picks up the ticking — never two
    owners at once (VERDICT r2 item 5)."""
    ticks = {nid: 0 for nid in cluster.nodes}
    for nid, n in cluster.nodes.items():
        n.persistent_task_executors["bg"] = (
            lambda nid=nid: ticks.__setitem__(nid, ticks[nid] + 1))

    r = cluster.call(cluster.master().client_register_persistent_task,
                     "bg", interval_ms=50)
    assert r.get("acknowledged")
    assert cluster.run_until(lambda: sum(ticks.values()) >= 5)
    owners = [nid for nid, c in ticks.items() if c > 0]
    assert len(owners) == 1, f"task ticked on {owners}"
    owner = owners[0]

    # assignment is visible in the cluster state
    from elasticsearch_tpu.cluster.cluster_node import PERSISTENT_TASKS_KEY
    t = cluster.any_node().cluster_state.metadata[PERSISTENT_TASKS_KEY]["bg"]
    assert t["assigned_node"] == owner

    # kill the owner: the task must move to a survivor and keep ticking
    cluster.transport.blackhole(owner)
    cluster.nodes[owner].stop()
    survivors = [nid for nid in cluster.nodes if nid != owner]
    for nid in survivors:
        ticks[nid] = 0
    assert cluster.run_until(
        lambda: any(ticks[nid] > 0 for nid in survivors),
        max_ms=240_000), "no failover tick"
    new_owners = [nid for nid in survivors if ticks[nid] > 0]
    assert len(new_owners) == 1, f"failover ticked on {new_owners}"
    t2 = cluster.nodes[new_owners[0]].cluster_state.metadata[
        PERSISTENT_TASKS_KEY]["bg"]
    assert t2["assigned_node"] == new_owners[0]


def test_scripted_metric_across_shards(cluster):
    """scripted_metric through the REAL distributed path: each shard runs
    init/map/combine and ships only its combined state over the wire;
    reduce_script folds the states at the coordinator — the distributed
    result equals the arithmetic ground truth."""
    c = cluster
    c.any_node().client_create_index(
        "sm", settings={"index.number_of_shards": 2,
                        "index.number_of_replicas": 0},
        mappings={"properties": {"v": {"type": "double"}}})
    assert c.run_until(lambda: c.all_started("sm"))
    writer = c.any_node()
    for i in range(40):
        r = c.call(writer.client_write, "sm",
                   {"type": "index", "id": str(i),
                    "source": {"v": float(i)}})
        assert r["result"] == "created", r
    for node in c.nodes.values():
        node.refresh_all()
    resp = c.call(c.any_node().client_search, "sm", {
        "size": 0,
        "aggs": {"total": {"scripted_metric": {
            "init_script": "state.s = 0.0",
            "map_script": "state.s += doc['v'].value",
            "combine_script": "return state.s",
            "reduce_script":
                "double t = 0; for (a in states) { t += a } return t"}}}})
    assert resp["aggregations"]["total"]["value"] == float(sum(range(40)))
    # two shards -> two combined states folded in the reduce
    assert resp["_shards"]["successful"] == 2


def test_text_only_shards_never_materialize_vector_store(cluster):
    """Remote-shard stubs stay LIGHT: a shard whose mapping has no
    vector fields must never build a VectorStoreShard (device corpus,
    batcher, routers) — writes and searches run host-only. A vector
    mapping materializes the store lazily on first access."""
    c = cluster
    c.any_node().client_create_index(
        "plain", settings={"index.number_of_shards": 2,
                           "index.number_of_replicas": 1},
        mappings={"properties": {"title": {"type": "text"},
                                 "n": {"type": "long"}}})
    assert c.run_until(lambda: c.all_started("plain"))
    writer = c.any_node()
    for i in range(6):
        r = c.call(writer.client_write, "plain",
                   {"type": "index", "id": str(i),
                    "source": {"title": f"doc {i}", "n": i}})
        assert r["result"] == "created", r
    c.call(writer.client_refresh, "plain")
    resp = c.call(writer.client_search, "plain",
                  {"query": {"match_all": {}}, "size": 10})
    assert resp["hits"]["total"]["value"] == 6
    # the full write+replicate+search lifecycle ran; no copy ever paid
    # for a device vector store
    n_copies = 0
    for node in c.nodes.values():
        for (idx, _sid), shard in node.local_shards.items():
            if idx != "plain":
                continue
            n_copies += 1
            assert shard._vector_store is None, \
                f"text-only shard materialized a vector store on {node.node_id}"
            assert shard.active_vector_store() is None
    assert n_copies == 4  # 2 shards x (primary + replica)

    # a vector-mapped index DOES materialize — but only on access
    c.any_node().client_create_index(
        "vec", settings={"index.number_of_shards": 1,
                         "index.number_of_replicas": 0},
        mappings={"properties": {"v": {"type": "dense_vector", "dims": 4}}})
    assert c.run_until(lambda: c.all_started("vec"))
    holder = next(node for node in c.nodes.values()
                  if ("vec", 0) in node.local_shards)
    vshard = holder.local_shards[("vec", 0)]
    assert vshard.active_vector_store() is not None
    assert vshard._vector_store is not None
