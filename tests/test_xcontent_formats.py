"""x-content format coverage: SMILE + YAML codecs, auto-sniffing, REST
content negotiation (reference: libs/x-content json/smile/yaml/cbor
packages + XContentFactory.xContentType)."""

import json

import pytest

from elasticsearch_tpu.common import xcontent
from elasticsearch_tpu.common.errors import ParsingError
from elasticsearch_tpu.common.xcontent import XContentType


SAMPLES = [
    None,
    True,
    False,
    0,
    7,
    -16,
    15,
    123456,
    -987654321,
    1 << 40,
    -(1 << 40),
    3.14159,
    -2.5e10,
    "",
    "a",
    "hello world",
    "x" * 32,
    "y" * 33,
    "z" * 64,
    "w" * 200,
    "ünïcode",
    "é" * 40,
    "日本語のテキスト",
    [],
    [1, 2, 3],
    ["a", None, True, 2.5],
    {},
    {"k": "v"},
    {"nested": {"a": [1, {"b": "c"}]}, "n": 42},
    {"": "empty key", "long" * 30: "long key"},
    {"ünïcode-kéy": 1},
]


@pytest.mark.parametrize("content_type", [XContentType.SMILE,
                                          XContentType.YAML,
                                          XContentType.CBOR])
def test_roundtrip_all_samples(content_type):
    for sample in SAMPLES:
        encoded = xcontent.dumps(sample, content_type)
        decoded = xcontent.loads(encoded, content_type)
        if isinstance(sample, float):
            assert decoded == pytest.approx(sample), (content_type, sample)
        else:
            assert decoded == sample, (content_type, sample)


def test_smile_header_and_tokens():
    data = xcontent.dumps({"a": 1}, XContentType.SMILE)
    assert data.startswith(b":)\n")            # magic
    assert data[3] == 0x00                      # no shared names/values
    assert data[4] == 0xFA and data[-1] == 0xFB  # object frame
    # small int 1 → 0xC0 + zigzag(1)=2
    assert data[4:].count(bytes([0xC2])) == 1

    assert xcontent.dumps(True, XContentType.SMILE)[4] == 0x23
    assert xcontent.dumps(None, XContentType.SMILE)[4] == 0x21
    assert xcontent.dumps("", XContentType.SMILE)[4] == 0x20


def test_smile_rejects_garbage():
    with pytest.raises(ParsingError):
        xcontent.loads(b"\xff\xff\xff", XContentType.SMILE)
    with pytest.raises(ParsingError):
        xcontent.loads(b"not smile", XContentType.SMILE)


def test_smile_malformed_inputs_raise_parsing_error():
    bad_docs = [
        b":)\n\x00\x41\xff",       # invalid UTF-8 in tiny string
        b":)\n\x00\x29\x01",       # truncated double
        b":)\n\x00\x42ab",          # length-3 string token, 2 bytes present
        b":)\n\x00\x21XYZ",         # trailing garbage after value
        b":)\n\x00\xfa",            # unterminated object
        b":)\n\x00\xf8\x21",        # unterminated array
        b":)\n\x00\xe0abc",         # unterminated long string
        b":)\n\x01\xfa\xfb",        # shared-names flag set
    ]
    for doc in bad_docs:
        with pytest.raises(ParsingError):
            xcontent.loads(doc, XContentType.SMILE)


def test_smile_huge_negative_int_roundtrip():
    for n in (-(1 << 63) - 1, (1 << 70), -(1 << 70)):
        enc = xcontent.dumps(n, XContentType.SMILE)
        assert xcontent.loads(enc, XContentType.SMILE) == n


def test_yaml_parses_yml_style_document():
    doc = b"""---
settings:
  number_of_shards: 2
mappings:
  properties:
    title: {type: text}
list:
  - a
  - b
"""
    out = xcontent.loads(doc, XContentType.YAML)
    assert out["settings"]["number_of_shards"] == 2
    assert out["mappings"]["properties"]["title"]["type"] == "text"
    assert out["list"] == ["a", "b"]


def test_loads_auto_sniffs_all_formats():
    obj = {"k": [1, 2], "s": "v"}
    assert xcontent.loads_auto(xcontent.dumps(obj, XContentType.JSON)) == obj
    assert xcontent.loads_auto(xcontent.dumps(obj, XContentType.SMILE)) == obj
    assert xcontent.loads_auto(b"---\nk: 1\n") == {"k": 1}


def test_rest_accepts_smile_and_yaml_bodies(tmp_path):
    from elasticsearch_tpu.node import Node
    from elasticsearch_tpu.rest.actions import register_all
    from elasticsearch_tpu.rest.controller import RestController
    node = Node(str(tmp_path / "d"))
    try:
        rc = RestController()
        register_all(rc, node)
        body = xcontent.dumps({"doc_field": "from smile"}, XContentType.SMILE)
        status, resp = rc.dispatch("PUT", "/i/_doc/1", {"refresh": "true"},
                                   body, "application/smile")
        assert status == 201
        body = xcontent.dumps({"query": {"term": {"doc_field.keyword":
                                                  "from smile"}}},
                              XContentType.YAML)
        status, resp = rc.dispatch("POST", "/i/_search", {}, body,
                                   "application/yaml")
        assert status == 200 and resp["hits"]["total"]["value"] == 1
    finally:
        node.close()


def test_http_response_negotiation(tmp_path):
    """End-to-end: Accept: application/smile gets a SMILE response body."""
    import socket

    from tests.conftest import http_server_subprocess

    port = 19341
    with http_server_subprocess(port, str(tmp_path / "srv")):
        s = socket.create_connection(("127.0.0.1", port), timeout=5)
        req = (f"GET / HTTP/1.1\r\nHost: localhost\r\n"
               f"Accept: application/smile\r\nConnection: close\r\n\r\n")
        s.sendall(req.encode())
        raw = b""
        while True:
            chunk = s.recv(65536)
            if not chunk:
                break
            raw += chunk
        s.close()
        head, _, payload = raw.partition(b"\r\n\r\n")
        assert b"content-type: application/smile" in head
        out = xcontent.loads(payload, XContentType.SMILE)
        assert out["tagline"] == "You Know, for (TPU) Search"
