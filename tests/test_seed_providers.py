"""Seed-hosts providers (discovery-ec2 / discovery-gce / file):
dynamic transport-address discovery against API-shaped fixtures, with
per-provider failure isolation (a cloud outage never blocks boot)."""

import json
import threading
import urllib.parse
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from elasticsearch_tpu.cluster.seed_providers import resolve_seed_hosts


class _Ec2Handler(BaseHTTPRequestHandler):
    instances = []  # (private_ip, public_ip, state, tags)
    last_query = {}

    def log_message(self, *args):
        pass

    def do_GET(self):
        q = dict(urllib.parse.parse_qsl(
            urllib.parse.urlsplit(self.path).query))
        type(self).last_query = q
        # honor instance-state + tag filters like DescribeInstances
        wanted = []
        for ip, pub, state, tags in self.instances:
            ok = state == "running"
            i = 2
            while f"Filter.{i}.Name" in q:
                name = q[f"Filter.{i}.Name"]
                vals = [v for k, v in q.items()
                        if k.startswith(f"Filter.{i}.Value.")]
                if name.startswith("tag:"):
                    ok = ok and tags.get(name[4:]) in vals
                i += 1
            if ok:
                wanted.append((ip, pub))
        body = ("<DescribeInstancesResponse>" + "".join(
            f"<item><privateIpAddress>{ip}</privateIpAddress>"
            f"<ipAddress>{pub}</ipAddress>"
            f"<instanceState><name>running</name></instanceState></item>"
            for ip, pub in wanted) + "</DescribeInstancesResponse>").encode()
        self.send_response(200)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)


class _GceHandler(BaseHTTPRequestHandler):
    items = []

    def log_message(self, *args):
        pass

    def do_GET(self):
        body = json.dumps({"items": self.items}).encode()
        self.send_response(200)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)


class _Svc:
    def __init__(self, handler):
        self.server = ThreadingHTTPServer(("127.0.0.1", 0), handler)
        self.endpoint = f"http://127.0.0.1:{self.server.server_address[1]}"
        self.t = threading.Thread(target=self.server.serve_forever,
                                  daemon=True)

    def __enter__(self):
        self.t.start()
        return self

    def __exit__(self, *exc):
        self.server.shutdown()
        self.server.server_close()


def test_ec2_provider_with_tag_filter():
    _Ec2Handler.instances = [
        ("10.0.0.1", "54.0.0.1", "running", {"es": "yes"}),
        ("10.0.0.2", "54.0.0.2", "running", {"es": "no"}),
        ("10.0.0.3", "54.0.0.3", "stopped", {"es": "yes"}),
    ]
    with _Svc(_Ec2Handler) as svc:
        hosts = resolve_seed_hosts({
            "discovery.seed_providers": "ec2",
            "discovery.ec2.endpoint": svc.endpoint,
            "discovery.ec2.tag.es": "yes"})
        assert hosts == ["10.0.0.1:9300"]
        # public-ip host_type + custom default port
        hosts = resolve_seed_hosts({
            "discovery.seed_providers": "ec2",
            "discovery.ec2.endpoint": svc.endpoint,
            "discovery.ec2.host_type": "public_ip",
            "discovery.ec2.tag.es": "yes",
            "transport.default_port": 9377})
        assert hosts == ["54.0.0.1:9377"]


def test_gce_provider_running_only():
    _GceHandler.items = [
        {"status": "RUNNING",
         "networkInterfaces": [{"networkIP": "10.1.0.1"}]},
        {"status": "TERMINATED",
         "networkInterfaces": [{"networkIP": "10.1.0.2"}]},
        {"status": "RUNNING", "networkInterfaces": []},
    ]
    with _Svc(_GceHandler) as svc:
        hosts = resolve_seed_hosts({
            "discovery.seed_providers": "gce",
            "discovery.gce.endpoint": svc.endpoint,
            "discovery.gce.project": "p", "discovery.gce.zone": "z"})
        assert hosts == ["10.1.0.1:9300"]


def test_file_provider_and_failure_isolation(tmp_path):
    cfg = tmp_path / "config"
    cfg.mkdir()
    (cfg / "unicast_hosts.txt").write_text(
        "# comment\n10.2.0.1\n10.2.0.2:9301\n\n")
    # ec2 endpoint refused (no server) must not poison the file provider
    hosts = resolve_seed_hosts({
        "discovery.seed_providers": "ec2,file",
        "discovery.ec2.endpoint": "http://127.0.0.1:9"},
        data_path=str(tmp_path))
    assert hosts == ["10.2.0.1:9300", "10.2.0.2:9301"]


def test_dedup_and_unknown_provider():
    hosts = resolve_seed_hosts({
        "discovery.seed_providers": "bogus"})
    assert hosts == []


def test_ipv6_hosts_bracket_correctly():
    from elasticsearch_tpu.cluster.seed_providers import _with_port
    assert _with_port("fd00::1", {}) == "[fd00::1]:9300"
    assert _with_port("[fd00::1]", {}) == "[fd00::1]:9300"
    assert _with_port("[fd00::1]:9301", {}) == "[fd00::1]:9301"
    assert _with_port("10.0.0.1:9301", {}) == "10.0.0.1:9301"
    assert _with_port("10.0.0.1", {"transport.default_port": 9400}) \
        == "10.0.0.1:9400"
