"""IVF partitioned ANN engine (`elasticsearch_tpu/ann/` + `ops/knn_ivf.py`).

Fast fixed-seed smoke tests (small synthetic corpus, nlist=16) keep tier-1
within budget; the full 100k-doc recall-gate sweep is `@pytest.mark.slow`.
"""

import numpy as np
import pytest

from elasticsearch_tpu.ann import IVFRouter, build_ivf_index
from elasticsearch_tpu.ann import kmeans as kmeans_lib
from elasticsearch_tpu.ops import knn as knn_ops
from elasticsearch_tpu.ops import similarity as sim

SEED = 1234


def _clustered(n, d, n_centers=16, seed=SEED, spread=1.0):
    rng = np.random.default_rng(seed)
    centers = rng.standard_normal((n_centers, d)).astype(np.float32) * 3.0
    assign = rng.integers(0, n_centers, size=n)
    vecs = centers[assign] + spread * rng.standard_normal(
        (n, d)).astype(np.float32)
    return vecs.astype(np.float32), centers


def _exact_topk(vecs, queries, k, metric):
    import jax.numpy as jnp

    corpus = knn_ops.build_corpus(vecs, metric=metric, dtype="f32")
    _, ids = knn_ops.knn_search(jnp.asarray(queries), corpus, k,
                                metric=metric, precision="f32")
    return np.asarray(ids)


def _recall(rows, ids_ref):
    k = ids_ref.shape[1]
    hits = sum(len(set(rows[i]) & set(ids_ref[i]))
               for i in range(len(ids_ref)))
    return hits / (len(ids_ref) * k)


# ---------------------------------------------------------------- kmeans

def test_kmeans_trains_deterministic_centroids():
    vecs, _ = _clustered(4000, 24)
    c1 = kmeans_lib.train_kmeans(vecs, 16, seed=7)
    c2 = kmeans_lib.train_kmeans(vecs, 16, seed=7)
    assert c1.shape == (16, 24)
    assert np.isfinite(c1).all()
    np.testing.assert_array_equal(c1, c2)
    # centroids actually spread over the data: every centroid has members,
    # and assignment distortion beats a degenerate single-center layout
    assign = np.asarray(kmeans_lib.assign_blocks(vecs, c1))
    assert len(np.unique(assign)) >= 12
    d_km = np.linalg.norm(vecs - c1[assign], axis=1).mean()
    d_one = np.linalg.norm(vecs - vecs.mean(0), axis=1).mean()
    assert d_km < 0.7 * d_one


def test_kmeans_rejects_bad_args():
    vecs, _ = _clustered(64, 8)
    with pytest.raises(ValueError):
        kmeans_lib.train_kmeans(vecs, 128)  # more centroids than rows
    with pytest.raises(ValueError):
        kmeans_lib.train_kmeans(vecs, 0)


# ----------------------------------------------------------- index build

def test_build_respects_capacity_and_keeps_every_row():
    vecs, _ = _clustered(5000, 16)
    index = build_ivf_index(vecs, metric=sim.COSINE, nlist=16, seed=SEED)
    assert index.total == 5000
    assert (index.counts <= index.cap).all()
    assert index.cap % 8 == 0  # tile-padded
    assert index.spilled == 0
    # every input row id appears exactly once across the buckets
    rows = index.part_rows[index.part_rows >= 0]
    assert sorted(rows.tolist()) == list(range(5000))


def test_smoke_recall_nlist16():
    """Fixed-seed smoke: small corpus, nlist=16 — the tier-1 stand-in for
    the slow 100k sweep."""
    vecs, centers = _clustered(4096, 32)
    rng = np.random.default_rng(SEED + 1)
    queries = vecs[rng.integers(0, len(vecs), 64)] \
        + 0.1 * rng.standard_normal((64, 32)).astype(np.float32)
    index = build_ivf_index(vecs, metric=sim.COSINE, nlist=16, seed=SEED)
    router = IVFRouter(index, nprobe="auto", recall_target=0.95)
    nprobe = router.effective_nprobe(10)
    _, rows, phases = router.search(queries, 10)
    recall = _recall(rows, _exact_topk(vecs, queries, 10, sim.COSINE))
    assert recall >= 0.9, f"recall {recall} at nprobe {nprobe}"
    assert phases["engine"] == "tpu_ivf"
    assert phases["scored_rows"] < 4096  # actually pruned


@pytest.mark.parametrize("metric", [sim.L2_NORM, sim.DOT_PRODUCT])
def test_other_metrics(metric):
    vecs, _ = _clustered(3000, 16)
    rng = np.random.default_rng(SEED + 2)
    queries = vecs[rng.integers(0, len(vecs), 32)] \
        + 0.05 * rng.standard_normal((32, 16)).astype(np.float32)
    index = build_ivf_index(vecs, metric=metric, nlist=16, seed=SEED)
    router = IVFRouter(index, nprobe=8)
    _, rows, _ = router.search(queries, 10)
    recall = _recall(rows, _exact_topk(vecs, queries, 10, metric))
    assert recall >= 0.9, f"{metric} recall {recall}"


def test_int8_partitions_match_fp32_partitions():
    vecs, _ = _clustered(3000, 16)
    rng = np.random.default_rng(SEED + 3)
    queries = vecs[rng.integers(0, len(vecs), 32)]
    i_f = build_ivf_index(vecs, metric=sim.COSINE, nlist=16, seed=SEED,
                          dtype="f32")
    i_q = build_ivf_index(vecs, metric=sim.COSINE, nlist=16, seed=SEED,
                          dtype="int8")
    r_f = IVFRouter(i_f, nprobe=8)
    r_q = IVFRouter(i_q, nprobe=8)
    s_f, rows_f, _ = r_f.search(queries, 10)
    s_q, rows_q, _ = r_q.search(queries, 10)
    # int8 quantization may swap near-ties but the candidate sets overlap
    overlap = sum(len(set(rows_f[i]) & set(rows_q[i]))
                  for i in range(32)) / 320
    assert overlap >= 0.9
    # scores agree within int8 tolerance where rows agree
    for i in range(32):
        common = set(rows_f[i]) & set(rows_q[i])
        for r in common:
            sf = s_f[i][list(rows_f[i]).index(r)]
            sq = s_q[i][list(rows_q[i]).index(r)]
            assert abs(sf - sq) < 0.05


def test_incremental_add_and_retrain_threshold():
    vecs, centers = _clustered(2000, 16)
    index = build_ivf_index(vecs, metric=sim.COSINE, nlist=16, seed=SEED,
                            retrain_threshold=0.2)
    assert not index.needs_retrain
    # adds land in buckets and become searchable
    rng = np.random.default_rng(SEED + 4)
    extra = (centers[3] + 0.05 * rng.standard_normal(
        (50, 16))).astype(np.float32)
    index.add(extra, np.arange(2000, 2050, dtype=np.int32))
    assert index.total == 2050
    router = IVFRouter(index, nprobe=4)
    _, rows, _ = router.search(extra[:8], 5)
    assert (rows.flatten() >= 2000).any(), "added rows never surfaced"
    # a drifted flood displaces adds past the threshold → retrain flag
    flood = (centers[5] + 0.02 * rng.standard_normal(
        (index.cap * 5, 16))).astype(np.float32)
    index.add(flood, np.arange(3000, 3000 + len(flood), dtype=np.int32))
    assert index.displaced > 0
    assert index.needs_retrain
    assert IVFRouter(index, nprobe=4).should_fallback(
        10, False, "bf16") == "needs_retrain"


def test_auto_nprobe_meets_target_on_sample():
    vecs, _ = _clustered(6000, 24, spread=2.0)  # blurrier clusters
    index = build_ivf_index(vecs, metric=sim.COSINE, nlist=32, seed=SEED)
    router = IVFRouter(index, nprobe="auto", recall_target=0.95,
                       tune_sample=64)
    nprobe = router.effective_nprobe(10)
    assert 1 <= nprobe <= 32
    # the tuned setting really meets the gate on the held-out sample
    rng = np.random.default_rng(router.tune_seed)
    # recall on corpus rows as queries (self-recall) must clear the gate
    pick = rng.integers(0, len(vecs), 64)
    _, rows, _ = router.search(vecs[pick], 10, nprobe=nprobe)
    recall = _recall(rows, _exact_topk(vecs, vecs[pick], 10, sim.COSINE))
    assert recall >= 0.93, f"tuned nprobe {nprobe} gives recall {recall}"


def test_fallback_reasons():
    vecs, _ = _clustered(2000, 16)
    index = build_ivf_index(vecs, metric=sim.COSINE, nlist=16, seed=SEED)
    router = IVFRouter(index, nprobe=4)
    assert router.should_fallback(10, True, "bf16") == "filtered"
    assert router.should_fallback(10, False, "f32") == "f32_precision"
    assert router.should_fallback(index.cap + 1, False, "bf16") \
        == "k_exceeds_partition"
    assert router.should_fallback(10, False, "bf16") is None


# ------------------------------------------------------- store dispatch

def _make_store_with_field(vecs, engine="tpu_ivf", nlist=16):
    """VectorStoreShard over a synthetic sealed segment."""
    from elasticsearch_tpu.index.mapping import MapperService
    from elasticsearch_tpu.index.segment import Segment, SegmentView, ShardReader
    from elasticsearch_tpu.vectors.store import VectorStoreShard

    n, d = vecs.shape
    seg = Segment(seg_id=0, base=0, num_docs=n, postings={},
                  field_lengths={}, total_terms={}, doc_values={},
                  vectors={"v": (vecs, np.ones(n, dtype=bool))},
                  ids=[str(i) for i in range(n)], sources=[None] * n,
                  seq_nos=np.arange(n, dtype=np.int64))
    reader = ShardReader([SegmentView(seg)])
    ms = MapperService({"properties": {
        "v": {"type": "dense_vector", "dims": d}}})
    store = VectorStoreShard(knn_engine=engine, knn_nlist=nlist,
                             knn_nprobe=4)
    store.sync(reader, ms.vector_fields())
    return store


def test_store_routes_through_ivf_and_falls_back_on_filter():
    vecs, _ = _clustered(2000, 16)
    store = _make_store_with_field(vecs)
    fc = store.field("v")
    assert fc.router is not None
    rows, scores = store.search("v", vecs[7], 5)
    assert 7 in rows
    assert store.knn_stats["ivf_searches"] == 1
    assert store.last_knn_phases["engine"] == "tpu_ivf"
    assert store.last_knn_phases["score_nanos"] > 0
    # filtered search takes the exhaustive escape hatch
    rows_f, _ = store.search("v", vecs[7], 5,
                             filter_rows=np.arange(100, dtype=np.int64))
    assert store.knn_stats["fallback_searches"] == 1
    assert store.last_knn_phases["fallback_reason"] == "filtered"
    assert (rows_f < 100).all()


def test_store_append_only_refresh_reuses_layout():
    """A refresh that only appends segments never retrains k-means on
    the refresh thread: the delta seals into an L0 generation (searched
    exhaustively, fused with the IVF base), and the MERGE scheduler
    re-enters the delta into the trained layout (clone + add, tuned
    nprobe kept) off the refresh path."""
    from elasticsearch_tpu.index.mapping import MapperService
    from elasticsearch_tpu.index.segment import Segment, SegmentView, ShardReader
    from elasticsearch_tpu.vectors.store import VectorStoreShard

    vecs, centers = _clustered(2000, 16)
    n = len(vecs)

    def seg_of(mat, base, seg_id):
        m = len(mat)
        return Segment(seg_id=seg_id, base=base, num_docs=m, postings={},
                       field_lengths={}, total_terms={}, doc_values={},
                       vectors={"v": (mat, np.ones(m, dtype=bool))},
                       ids=[str(base + i) for i in range(m)],
                       sources=[None] * m,
                       seq_nos=np.arange(base, base + m, dtype=np.int64))

    ms = MapperService({"properties": {
        "v": {"type": "dense_vector", "dims": 16}}})
    store = VectorStoreShard(knn_engine="tpu_ivf", knn_nlist=16,
                             knn_nprobe=4, segments_background_merge=False)
    seg0 = seg_of(vecs, 0, 0)
    store.sync(ShardReader([SegmentView(seg0)]), ms.vector_fields())
    router0 = store.field("v").router
    assert router0 is not None

    # append-only refresh: same first segment + a new sealed one
    rng = np.random.default_rng(SEED + 5)
    extra = (centers[2] + 0.1 * rng.standard_normal(
        (64, 16))).astype(np.float32)
    reader2 = ShardReader([SegmentView(seg0),
                           SegmentView(seg_of(extra, n, 1))])
    store.sync(reader2, ms.vector_fields())
    gc = store._gens["v"]
    base = gc.snapshot().generations[0]
    assert base.router is router0, "append-only sync retrained k-means"
    assert base.router.index.total == n, \
        "refresh thread touched the IVF layout"
    rows, _ = store.search("v", extra[0], 5)
    assert (rows >= n).any(), "appended rows not searchable pre-merge"

    # the merge graduates the delta into the trained layout: no retrain
    # (centroids shared via clone), tuned nprobe carried over
    assert gc.force_merge()
    merged = gc.snapshot().generations[0]
    assert merged.router is not None
    assert merged.router.index.total == n + 64
    assert merged.router.index.centroids is router0.index.centroids, \
        "append-shaped merge retrained k-means"
    rows, _ = store.search("v", extra[0], 5)
    assert (rows >= n).any(), "appended rows not searchable via IVF"

    # a delete drops the base router (tombstones would leak through the
    # partition layout); the background compaction rebuilds it
    reader3 = ShardReader([SegmentView(seg0, deleted_locals={0}),
                           SegmentView(seg_of(extra, n, 1))])
    store.sync(reader3, ms.vector_fields())
    assert gc.snapshot().generations[0].router is None
    rows, _ = store.search("v", vecs[3], 5)  # still correct, masked
    assert 0 not in rows
    assert gc.run_merges() >= 1
    assert gc.snapshot().generations[0].router is not None
    assert gc.snapshot().generations[0].router is not router0


def test_store_default_engine_stays_exhaustive():
    vecs, _ = _clustered(1500, 16)
    store = _make_store_with_field(vecs, engine="tpu")
    assert store.field("v").router is None
    rows, _ = store.search("v", vecs[3], 5)
    assert 3 in rows
    assert store.knn_stats["ivf_searches"] == 0


def test_field_level_index_options_override():
    """index_options.type: ivf opts a field in even when the index-level
    engine is the default exhaustive one."""
    from elasticsearch_tpu.index.mapping import MapperService
    from elasticsearch_tpu.vectors.store import VectorStoreShard

    ms = MapperService({"properties": {
        "v": {"type": "dense_vector", "dims": 16,
              "index_options": {"type": "ivf", "nlist": 16}}}})
    store = VectorStoreShard(knn_engine="tpu")
    vecs, _ = _clustered(2000, 16)
    from elasticsearch_tpu.index.segment import Segment, SegmentView, ShardReader
    n = len(vecs)
    seg = Segment(seg_id=0, base=0, num_docs=n, postings={},
                  field_lengths={}, total_terms={}, doc_values={},
                  vectors={"v": (vecs, np.ones(n, dtype=bool))},
                  ids=[str(i) for i in range(n)], sources=[None] * n,
                  seq_nos=np.arange(n, dtype=np.int64))
    reader = ShardReader([SegmentView(seg)])
    store.sync(reader, ms.vector_fields())
    fc = store.field("v")
    assert fc.router is not None
    assert fc.router.index.nlist == 16


def test_index_settings_validation():
    import tempfile

    from elasticsearch_tpu.common.errors import IllegalArgumentError
    from elasticsearch_tpu.index.mapping import MapperParsingError, MapperService
    from elasticsearch_tpu.indices.service import IndicesService

    indices = IndicesService(tempfile.mkdtemp())
    with pytest.raises(IllegalArgumentError):
        indices.create_index("bad", settings={"index.knn.engine": "hnsw"})
    with pytest.raises(IllegalArgumentError):
        indices.create_index("bad2", settings={
            "index.knn.engine": "tpu_ivf", "index.knn.nlist": 0})
    with pytest.raises(IllegalArgumentError):
        indices.create_index("bad3", settings={
            "index.knn.engine": "tpu_ivf", "index.knn.nprobe": "lots"})
    with pytest.raises(MapperParsingError):
        MapperService({"properties": {"v": {
            "type": "dense_vector", "dims": 4,
            "index_options": {"type": "hnsw"}}}})
    with pytest.raises(MapperParsingError):
        # "auto" is an nprobe concept; nlist must be a real integer
        MapperService({"properties": {"v": {
            "type": "dense_vector", "dims": 4,
            "index_options": {"type": "ivf", "nlist": "auto"}}}})
    indices.close()


def test_small_corpus_stays_exhaustive_under_ivf_engine():
    """Below IVF_MIN_ROWS the engine quietly serves exhaustive."""
    vecs, _ = _clustered(100, 8)
    store = _make_store_with_field(vecs)
    assert store.field("v").router is None
    rows, _ = store.search("v", vecs[0], 5)
    assert 0 in rows


# ------------------------------------------------------------ slow sweep

@pytest.mark.slow
def test_recall_gate_100k_corpus():
    """Acceptance: >=100k-doc corpus, tuned nprobe reaches recall@10 >=
    0.95 vs exhaustive ground truth while scoring <= 25% of the corpus."""
    vecs, _ = _clustered(100_000, 64, n_centers=256, seed=SEED)
    rng = np.random.default_rng(SEED + 9)
    queries = vecs[rng.integers(0, len(vecs), 128)] \
        + 0.1 * rng.standard_normal((128, 64)).astype(np.float32)

    index = build_ivf_index(vecs, metric=sim.COSINE, nlist=256, seed=SEED)
    router = IVFRouter(index, nprobe="auto", recall_target=0.95)
    nprobe = router.effective_nprobe(10)

    frac = index.scored_fraction(nprobe)
    assert frac <= 0.25, f"tuned nprobe {nprobe} scores {frac:.1%}"

    _, rows, phases = router.search(queries, 10)
    recall = _recall(rows, _exact_topk(vecs, queries, 10, sim.COSINE))
    assert recall >= 0.95, \
        f"recall {recall:.4f} at nprobe {nprobe} (scored {frac:.1%})"
    assert phases["scored_rows"] <= 0.25 * len(vecs)


@pytest.mark.slow
def test_recall_gate_100k_int8():
    """int8 partitions: the tuner gates ROUTING recall (vs the engine's
    own full probe — extra probes can't undo quantization), and the
    end-to-end recall vs exact f32 stays within the int8 envelope."""
    vecs, _ = _clustered(100_000, 64, n_centers=256, seed=SEED)
    rng = np.random.default_rng(SEED + 10)
    queries = vecs[rng.integers(0, len(vecs), 64)] \
        + 0.1 * rng.standard_normal((64, 64)).astype(np.float32)
    index = build_ivf_index(vecs, metric=sim.COSINE, nlist=256, seed=SEED,
                            dtype="int8")
    router = IVFRouter(index, nprobe="auto", recall_target=0.95)
    nprobe = router.effective_nprobe(10)
    assert index.scored_fraction(nprobe) <= 0.25, \
        f"tuned nprobe {nprobe} defeats pruning"
    _, rows, _ = router.search(queries, 10)
    # routing recall: tuned probing finds what full probing would
    _, rows_full, _ = router.search(queries, 10, nprobe=index.nlist)
    routing_recall = _recall(rows, rows_full)
    assert routing_recall >= 0.95, \
        f"routing recall {routing_recall:.4f} at nprobe {nprobe}"
    # end-to-end vs exact f32: quantization envelope on top of the gate
    recall = _recall(rows, _exact_topk(vecs, queries, 10, sim.COSINE))
    assert recall >= 0.90, f"int8 recall {recall:.4f} at nprobe {nprobe}"
