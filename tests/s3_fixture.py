"""In-process S3-compatible fixture (analog of the reference's dockerized
test/fixtures/s3-fixture): path-style GET/PUT/DELETE/HEAD on
/{bucket}/{key} plus list-objects-v2 ?prefix= returning minimal XML."""

from __future__ import annotations

import threading
import urllib.parse
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, Tuple


class _Handler(BaseHTTPRequestHandler):
    store: Dict[Tuple[str, str], bytes] = {}

    def log_message(self, *args):  # quiet
        pass

    def _parse(self):
        parsed = urllib.parse.urlsplit(self.path)
        parts = parsed.path.lstrip("/").split("/", 1)
        bucket = parts[0]
        key = urllib.parse.unquote(parts[1]) if len(parts) > 1 else ""
        query = {k: v[-1] for k, v in
                 urllib.parse.parse_qs(parsed.query).items()}
        return bucket, key, query

    def do_PUT(self):
        bucket, key, _ = self._parse()
        length = int(self.headers.get("Content-Length", 0))
        data = self.rfile.read(length)
        self.store[(bucket, key)] = data
        self.send_response(200)
        self.end_headers()

    def do_GET(self):
        bucket, key, query = self._parse()
        if not key:  # list-objects
            prefix = query.get("prefix", "")
            keys = sorted(k for (b, k) in self.store
                          if b == bucket and k.startswith(prefix))
            body = ("<?xml version=\"1.0\"?><ListBucketResult>"
                    + "".join(f"<Contents><Key>{k}</Key></Contents>"
                              for k in keys)
                    + "</ListBucketResult>").encode()
            self.send_response(200)
            self.send_header("Content-Type", "application/xml")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)
            return
        data = self.store.get((bucket, key))
        if data is None:
            self.send_response(404)
            self.end_headers()
            return
        self.send_response(200)
        self.send_header("Content-Length", str(len(data)))
        self.end_headers()
        self.wfile.write(data)

    def do_HEAD(self):
        bucket, key, _ = self._parse()
        self.send_response(200 if (bucket, key) in self.store else 404)
        self.end_headers()

    def do_DELETE(self):
        bucket, key, _ = self._parse()
        self.store.pop((bucket, key), None)
        self.send_response(204)
        self.end_headers()


class S3Fixture:
    def __init__(self):
        self.server = ThreadingHTTPServer(("127.0.0.1", 0), _Handler)
        self.port = self.server.server_address[1]
        self.thread = threading.Thread(target=self.server.serve_forever,
                                       daemon=True)

    @property
    def endpoint(self) -> str:
        return f"http://127.0.0.1:{self.port}"

    def __enter__(self):
        self.thread.start()
        return self

    def __exit__(self, *exc):
        self.server.shutdown()
        self.server.server_close()
