"""Serving layer: host VNNI kNN kernel, combining batcher, cost routing.

Covers the round-4 serving redesign: the native int8 packed-corpus kernel
(native/es_native.cc es_knn_i8p_topk), the HostFieldCorpus mirror with bf16
rescore, the CombiningBatcher coalescing concurrent requests into one
dispatch, and the host/device routing inside VectorStoreShard.
"""

import threading

import numpy as np
import pytest

from elasticsearch_tpu import native
from elasticsearch_tpu.ops import similarity as sim
from elasticsearch_tpu.serving.batcher import CombiningBatcher, CostModel
from elasticsearch_tpu.vectors.host_corpus import HostFieldCorpus


def _exact_topk(raw, k):
    order = np.lexsort((np.arange(raw.shape[-1]), -raw))
    return order[:k]


class TestHostCorpus:
    def test_cosine_matches_exact_ranking(self):
        rng = np.random.default_rng(0)
        vecs = rng.standard_normal((5000, 96)).astype(np.float32)
        hc = HostFieldCorpus(vecs, sim.COSINE)
        q = rng.standard_normal((4, 96)).astype(np.float32)
        scores, rows = hc.search(q, 10)
        qn = q / np.linalg.norm(q, axis=-1, keepdims=True)
        vn = vecs / np.linalg.norm(vecs, axis=-1, keepdims=True)
        exact = qn @ vn.T
        for i in range(4):
            ref = set(_exact_topk(exact[i], 10).tolist())
            got = set(rows[i].tolist())
            # int8 + bf16 rescore: allow at most 1 swap at the boundary
            assert len(ref & got) >= 9
            # scores are raw cosine, descending
            assert np.all(np.diff(scores[i]) <= 1e-6)
            assert scores[i][0] == pytest.approx(exact[i].max(), abs=2e-2)

    def test_l2_raw_convention(self):
        rng = np.random.default_rng(1)
        vecs = rng.standard_normal((1000, 32)).astype(np.float32)
        hc = HostFieldCorpus(vecs, sim.L2_NORM)
        q = rng.standard_normal((2, 32)).astype(np.float32)
        scores, rows = hc.search(q, 5)
        for i in range(2):
            d2 = ((vecs[rows[i]] - q[i]) ** 2).sum(axis=-1)
            # raw = -||q - c||^2
            np.testing.assert_allclose(scores[i], -d2, rtol=2e-2, atol=2e-2)
            ref = np.argsort(d2)
            assert np.all(np.diff(scores[i]) <= 1e-6)

    def test_shared_and_per_query_masks(self):
        rng = np.random.default_rng(2)
        vecs = rng.standard_normal((800, 48)).astype(np.float32)
        hc = HostFieldCorpus(vecs, sim.COSINE)
        q = rng.standard_normal((3, 48)).astype(np.float32)
        shared = rng.random(800) < 0.3
        _, rows = hc.search(q, 20, mask=shared)
        assert np.all(shared[rows[rows >= 0]])
        perq = rng.random((3, 800)) < 0.3
        _, rows = hc.search(q, 20, mask=perq)
        for i in range(3):
            r = rows[i][rows[i] >= 0]
            assert np.all(perq[i][r])

    def test_fewer_than_k_eligible(self):
        rng = np.random.default_rng(3)
        vecs = rng.standard_normal((50, 16)).astype(np.float32)
        hc = HostFieldCorpus(vecs, sim.COSINE)
        q = rng.standard_normal((1, 16)).astype(np.float32)
        mask = np.zeros(50, dtype=bool)
        mask[:7] = True
        scores, rows = hc.search(q, 20, mask=mask)
        got = rows[0][rows[0] >= 0]
        assert set(got.tolist()) == set(range(7))
        assert np.all(np.isneginf(scores[0][7:]))


@pytest.mark.skipif(not native.AVAILABLE, reason="native kernels unavailable")
class TestNativeKernelExact:
    def test_matches_int8_emulation(self):
        """Kernel scores must equal the exact int8 quantized dot product."""
        rng = np.random.default_rng(4)
        n, d, b, k = 3001, 65, 18, 9
        vecs = rng.standard_normal((n, d)).astype(np.float32)
        hc = HostFieldCorpus(vecs, sim.DOT_PRODUCT)
        q = rng.standard_normal((b, d)).astype(np.float32)
        scores, rows = hc.search(q, k, rescore=False)
        # emulate: symmetric int8 rows, i8 queries
        rs = np.abs(vecs).max(axis=1) / 127.0
        ri = np.clip(np.rint(vecs / rs[:, None]), -127, 127)
        qs = np.abs(q).max(axis=1) / 127.0
        qi = np.clip(np.rint(q / qs[:, None]), -127, 127)
        ref = (qi @ ri.T) * qs[:, None] * rs[None, :]
        for i in range(b):
            top = _exact_topk(ref[i].astype(np.float32), k)
            assert set(rows[i].tolist()) == set(top.tolist())
            np.testing.assert_allclose(
                np.sort(scores[i]), np.sort(ref[i][top]).astype(np.float32),
                rtol=1e-5, atol=1e-5)


class TestCombiningBatcher:
    def test_single_thread_executes_immediately(self):
        calls = []

        def execute(reqs):
            calls.append(len(reqs))
            return [r * 2 for r in reqs]

        b = CombiningBatcher(execute)
        assert b.submit(21) == 42
        assert calls == [1]

    def test_concurrent_requests_coalesce(self):
        batch_sizes = []
        gate = threading.Event()

        def execute(reqs):
            gate.wait(5)
            batch_sizes.append(len(reqs))
            return [r + 100 for r in reqs]

        b = CombiningBatcher(execute)
        results = {}

        def worker(i):
            results[i] = b.submit(i)

        threads = [threading.Thread(target=worker, args=(i,))
                   for i in range(12)]
        for t in threads:
            t.start()
        # let every request enqueue, then open the gate: the first runner
        # serves its batch; everything queued behind coalesces
        import time
        time.sleep(0.2)
        gate.set()
        for t in threads:
            t.join(10)
        assert results == {i: i + 100 for i in range(12)}
        assert sum(batch_sizes) == 12
        assert len(batch_sizes) <= 3  # coalescing actually happened

    def test_coalesced_batch_events_are_labeled(self):
        """Dispatch-trace attribution: a profiled runner executing a
        coalesced batch labels those events `coalesced_batch: N` instead
        of silently claiming follower dispatches as its own; a solo
        dispatch stays unlabeled."""
        from concurrent.futures import Future

        from elasticsearch_tpu.ops import dispatch

        dispatch.DISPATCH.register("test.batcher_trace", lambda x: x + 1.0)

        def execute(reqs):
            import jax.numpy as jnp
            return [float(np.asarray(dispatch.call(
                "test.batcher_trace", jnp.float32(r)))) for r in reqs]

        b = CombiningBatcher(execute)
        dispatch.DISPATCH.record_events(True)
        try:
            # a queued follower makes the submitting thread a runner
            # executing a 2-request batch deterministically
            follower = Future()
            b._enqueue(1.0, follower)
            assert b.submit(2.0) == 3.0
            assert follower.result(timeout=5) == 2.0
            events = dispatch.DISPATCH.drain_events()
            batch_events = [e for e in events
                            if e["kernel"] == "test.batcher_trace"]
            assert len(batch_events) == 2
            assert all(e.get("coalesced_batch") == 2
                       for e in batch_events)
            # solo dispatch: no coalescing marker
            dispatch.DISPATCH.record_events(True)
            assert b.submit(5.0) == 6.0
            (solo,) = [e for e in dispatch.DISPATCH.drain_events()
                       if e["kernel"] == "test.batcher_trace"]
            assert "coalesced_batch" not in solo

            # poisoned batch: the serial per-request retries run on the
            # same runner thread — their dispatches must be labeled too
            def poisoned_execute(reqs):
                if len(reqs) > 1:
                    raise RuntimeError("poisoned batch")
                return execute(reqs)

            b2 = CombiningBatcher(poisoned_execute)
            dispatch.DISPATCH.record_events(True)
            follower2 = Future()
            b2._enqueue(1.0, follower2)
            assert b2.submit(2.0) == 3.0
            assert follower2.result(timeout=5) == 2.0
            retry_events = [e for e in dispatch.DISPATCH.drain_events()
                            if e["kernel"] == "test.batcher_trace"]
            assert len(retry_events) == 2
            assert all(e.get("coalesced_batch") == 2
                       for e in retry_events)
        finally:
            dispatch.DISPATCH.record_events(False)

    def test_error_propagates_to_all_waiters(self):
        def execute(reqs):
            raise RuntimeError("boom")

        b = CombiningBatcher(execute)
        with pytest.raises(RuntimeError, match="boom"):
            b.submit(1)

    def test_poisoned_request_does_not_fail_coalesced_peers(self):
        """A batch failure retries each request alone: only the offender
        errors, healthy requests that coalesced with it still succeed."""
        import threading

        calls = []

        def execute(reqs):
            calls.append(list(reqs))
            if any(r == "bad" for r in reqs):
                raise ValueError("poisoned")
            return [f"ok:{r}" for r in reqs]

        b = CombiningBatcher(execute)
        release = threading.Event()
        slow_started = threading.Event()

        def slow_execute(reqs):
            slow_started.set()
            release.wait(5)
            return execute(reqs)

        b._execute = slow_execute
        results: dict = {}

        def run(r):
            try:
                results[r] = b.submit(r)
            except Exception as e:  # noqa: BLE001
                results[r] = e

        # occupy the runner so the next two coalesce into one batch
        t0 = threading.Thread(target=run, args=("warm",))
        t0.start()
        slow_started.wait(5)
        b._execute = execute
        t1 = threading.Thread(target=run, args=("good",))
        t2 = threading.Thread(target=run, args=("bad",))
        t1.start(); t2.start()
        import time
        time.sleep(0.05)  # let both enqueue behind the held lock
        release.set()
        for t in (t0, t1, t2):
            t.join(5)
        assert results["warm"] == "ok:warm"
        assert results["good"] == "ok:good"
        assert isinstance(results["bad"], ValueError)


def _build_store(n=400, dims=32, seed=5):
    from elasticsearch_tpu.index.mapping import DenseVectorFieldMapper
    from elasticsearch_tpu.vectors.store import VectorStoreShard

    class FakeSeg:
        def __init__(self, mat):
            self.seg_id = "s0"
            self.num_docs = len(mat)
            self.base = 0
            self.vectors = {"v": (mat, np.ones(len(mat), dtype=bool))}

    class FakeView:
        def __init__(self, seg):
            self.segment = seg
            self.live = np.ones(seg.num_docs, dtype=bool)

    class FakeReader:
        def __init__(self, mat):
            self.views = [FakeView(FakeSeg(mat))]

    rng = np.random.default_rng(seed)
    mat = rng.standard_normal((n, dims)).astype(np.float32)
    mapper = DenseVectorFieldMapper("v", {"dims": dims,
                                          "similarity": "cosine"})
    store = VectorStoreShard()
    store.sync(FakeReader(mat), {"v": mapper})
    return store, mat, rng


class TestStoreRouting:
    def _store(self, n=400, dims=32, seed=5):
        return _build_store(n=n, dims=dims, seed=seed)

    def test_host_and_device_paths_agree(self, monkeypatch):
        store, mat, rng = self._store()
        q = rng.standard_normal(32).astype(np.float32)

        monkeypatch.setattr(CostModel, "prefer_host",
                            classmethod(lambda cls, *a: True))
        rows_h, scores_h = store.search("v", q, 10)
        store._batchers.clear()
        monkeypatch.setattr(CostModel, "prefer_host",
                            classmethod(lambda cls, *a: False))
        rows_d, scores_d = store.search("v", q, 10)
        # same corpus, same query: both paths must retrieve ~the same set
        assert len(set(rows_h.tolist()) & set(rows_d.tolist())) >= 9
        np.testing.assert_allclose(scores_h[:5], scores_d[:5], atol=2e-2)

    def test_filtered_search_respects_filter_on_both_paths(self, monkeypatch):
        store, mat, rng = self._store()
        q = rng.standard_normal(32).astype(np.float32)
        filter_rows = np.arange(0, 400, 3, dtype=np.int64)
        for prefer in (True, False):
            store._batchers.clear()
            monkeypatch.setattr(CostModel, "prefer_host",
                                classmethod(lambda cls, *a, _p=prefer: _p))
            rows, _ = store.search("v", q, 15, filter_rows=filter_rows)
            assert len(rows) == 15
            assert np.all(np.isin(rows, filter_rows))

    def test_concurrent_store_searches(self):
        store, mat, rng = self._store(n=2000)
        queries = rng.standard_normal((16, 32)).astype(np.float32)
        results = {}

        def worker(i):
            results[i] = store.search("v", queries[i], 5)

        threads = [threading.Thread(target=worker, args=(i,))
                   for i in range(16)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(30)
        assert len(results) == 16
        vn = mat / np.linalg.norm(mat, axis=-1, keepdims=True)
        for i in range(16):
            rows, scores = results[i]
            qn = queries[i] / np.linalg.norm(queries[i])
            exact = vn @ qn
            ref = set(_exact_topk(exact, 5).tolist())
            assert len(ref & set(rows.tolist())) >= 4


class TestContinuousScheduler:
    """The PR-8 continuous-batching scheduler: deadline-aware EDF
    admission with schedule-time shedding, in-flight bucket top-up
    (byte-identical to an up-front batch, zero new compiles), and
    dispatch/finalize pipelining."""

    def test_drain_is_edf_and_sheds_expired_oldest_first(self):
        """Queued requests schedule earliest-deadline-first; an entry
        whose deadline passed is shed the moment the scheduler touches
        it (429-typed), and the later-deadline entry is NOT starved —
        it serves in the next turn."""
        import time as _time

        from concurrent.futures import Future

        from elasticsearch_tpu.common.threadpool import (
            EsRejectedExecutionError)
        from elasticsearch_tpu.serving.batcher import BoundedBatcher

        executed = []

        def execute(reqs):
            executed.append(list(reqs))
            return list(reqs)

        b = BoundedBatcher(execute, max_batch=1, deadline_ms=10_000.0)
        now = _time.monotonic()
        f_far, f_near, f_dead = Future(), Future(), Future()
        e_far = b._enqueue("far", f_far)
        e_near = b._enqueue("near", f_near)
        e_dead = b._enqueue("dead", f_dead)
        # forge the schedule: "dead" expired long ago, "near" is due
        # before "far" despite arriving later
        e_dead.deadline = now - 1.0
        e_near.deadline = now + 1.0
        e_far.deadline = now + 100.0
        b._run_once()
        with pytest.raises(EsRejectedExecutionError):
            f_dead.result(timeout=1)
        assert f_near.result(timeout=1) == "near"
        assert executed == [["near"]]
        assert b.stats["shed_deadline"] == 1
        assert b.sched["deadline_sheds"] == 1
        b._run_once()   # the large/old request is not starved
        assert f_far.result(timeout=1) == "far"
        assert executed == [["near"], ["far"]]

    def test_topup_batch_byte_identical_and_zero_recompiles(self,
                                                            monkeypatch):
        """Late arrivals joining a forming batch at the bucket boundary
        return byte-identical results to the same requests batched up
        front — and the topped-up dispatch compiles NOTHING new (the
        compiled shape is the bucket), checked under strict mode."""
        import threading
        import time as _time

        from concurrent.futures import Future

        from elasticsearch_tpu.ops import dispatch
        from elasticsearch_tpu.serving.batcher import CombiningBatcher

        store, mat, rng = _build_store(n=512)
        monkeypatch.setattr(CostModel, "prefer_host",
                            classmethod(lambda cls, *a: False))
        queries = rng.standard_normal((8, 32)).astype(np.float32)
        baseline = store.search_many("v", [(q, None) for q in queries], 10)

        fc = store._fields["v"]

        def dispatch_fn(reqs):
            return store._dispatch_many(fc, 10, "bf16", reqs)

        b = CombiningBatcher(None, dispatch_fn=dispatch_fn,
                             finalize_fn=store.finalize_many,
                             topup=True, target_batch_latency_ms=500.0)
        futs = [Future() for _ in range(8)]
        for q, f in zip(queries[:5], futs[:5]):
            b._enqueue((q, None), f)

        def late():
            _time.sleep(0.02)
            for q, f in zip(queries[5:], futs[5:]):
                b._enqueue((q, None), f)

        t = threading.Thread(target=late)
        t.start()
        compiles_before = dispatch.DISPATCH.compile_count()
        old_strict = dispatch.DISPATCH.strict
        dispatch.DISPATCH.strict = True
        try:
            b._run_once()
        finally:
            dispatch.DISPATCH.strict = old_strict
        t.join(5)
        # the 5 early + 3 late requests rode ONE bucket-8 dispatch
        assert b.sched["batches"] == 1
        assert b.sched["topups"] == 3
        # zero new compiles: the bucket-8 program was already compiled
        # by the up-front baseline batch
        assert dispatch.DISPATCH.compile_count() == compiles_before
        for f, (rows_ref, scores_ref) in zip(futs, baseline):
            rows, scores = f.result(timeout=5)
            np.testing.assert_array_equal(rows, rows_ref)
            np.testing.assert_array_equal(scores, scores_ref)

    def test_idle_single_query_never_waits_for_topup(self):
        """bucket_queries(1) == 1: a lone request has zero bucket
        headroom, so the top-up window must not add idle latency."""
        import time as _time

        from elasticsearch_tpu.serving.batcher import CombiningBatcher

        b = CombiningBatcher(lambda reqs: list(reqs),
                             topup=True, target_batch_latency_ms=500.0)
        t0 = _time.monotonic()
        assert b.submit("solo") == "solo"
        assert (_time.monotonic() - t0) < 0.25  # far under the 500ms window
        assert b.sched["topups"] == 0

    def test_pipelined_finalize_overlaps_next_dispatch(self):
        """While batch N finalizes (outside the scheduler lock), batch
        N+1 must be able to dispatch — the overlap the tail fix is made
        of. Results stay correct and the overlap is counted."""
        import threading
        import time as _time

        from elasticsearch_tpu.serving.batcher import CombiningBatcher

        started_finalize = threading.Event()
        release_finalize = threading.Event()

        def dispatch_fn(reqs):
            return list(reqs)

        def finalize_fn(handle):
            started_finalize.set()
            release_finalize.wait(5)
            return [r * 10 for r in handle]

        b = CombiningBatcher(None, dispatch_fn=dispatch_fn,
                             finalize_fn=finalize_fn, topup=False)
        results = {}

        def worker(i):
            results[i] = b.submit(i)

        t1 = threading.Thread(target=worker, args=(1,))
        t1.start()
        assert started_finalize.wait(5)
        # batch 1 is mid-finalize and holds NO lock: batch 2 dispatches
        t2 = threading.Thread(target=worker, args=(2,))
        t2.start()
        deadline = _time.monotonic() + 5
        while (b.sched["overlap_hits"] < 1
               and _time.monotonic() < deadline):
            _time.sleep(0.005)
        assert b.sched["overlap_hits"] >= 1
        release_finalize.set()
        t1.join(5)
        t2.join(5)
        assert results == {1: 10, 2: 20}
        assert b.sched["pipelined_batches"] == 2

    def test_pipelined_poisoned_batch_retries_serially(self):
        """A finalize failure on a coalesced batch retries each request
        alone through the synchronous path — 429/error semantics are
        identical to the pre-pipeline batcher."""
        from concurrent.futures import Future

        from elasticsearch_tpu.serving.batcher import CombiningBatcher

        def dispatch_fn(reqs):
            return list(reqs)

        def finalize_fn(handle):
            if any(r == "bad" for r in handle):
                raise ValueError("poisoned")
            return [f"ok:{r}" for r in handle]

        b = CombiningBatcher(None, dispatch_fn=dispatch_fn,
                             finalize_fn=finalize_fn, topup=False)
        follower = Future()
        b._enqueue("bad", follower)
        assert b.submit("good") == "ok:good"
        with pytest.raises(ValueError, match="poisoned"):
            follower.result(timeout=5)

    def test_queue_wait_and_scheduler_counters_accumulate(self):
        from elasticsearch_tpu.serving.batcher import CombiningBatcher

        b = CombiningBatcher(lambda reqs: list(reqs))
        for i in range(4):
            assert b.submit(i) == i
        assert b.sched["batches"] == 4
        assert b.sched["requests"] == 4
        assert b.sched["queue_wait_nanos"] >= 0
        assert b.sched["dispatch_nanos"] > 0

    def test_store_scheduler_stats_survive_batcher_retirement(self):
        """Refresh drops stale (field, k) batchers; their scheduler
        counters must fold into the retired total, not vanish."""
        store, mat, rng = _build_store(n=128)
        q = rng.standard_normal(32).astype(np.float32)
        store.search("v", q, 5)
        before = store.scheduler_stats()
        assert before.get("batches", 0) >= 1
        with store._batchers_lock:
            for key in list(store._batchers):
                store._retire_sched(store._batchers.pop(key))
        after = store.scheduler_stats()
        assert after.get("batches", 0) == before.get("batches", 0)


class TestRrfFastPath:
    """RRF fuses query-phase ranked lists and fetches only `size` docs
    (node.py _search_rrf fast path); results must match the definition
    score(d) = sum_lists 1/(rank_constant + rank)."""

    def _node(self, tmp_path):
        import jax

        try:
            jax.config.update("jax_platforms", "cpu")
        except RuntimeError:
            pass
        from elasticsearch_tpu.node import Node

        rng = np.random.default_rng(9)
        node = Node(str(tmp_path))
        node.create_index_with_templates("h", mappings={"properties": {
            "body": {"type": "text"},
            "v": {"type": "dense_vector", "dims": 8}}})
        ops = []
        for i in range(300):
            ops.append({"index": {"_index": "h", "_id": str(i)}})
            ops.append({"body": " ".join(rng.choice(list("abcde"), 4)),
                        "v": rng.standard_normal(8).tolist()})
        node.bulk(ops)
        node.indices.get("h").refresh()
        return node, rng

    def test_matches_manual_fusion(self, tmp_path):
        node, rng = self._node(tmp_path)
        qv = rng.standard_normal(8).tolist()
        body = {"rank": {"rrf": {"rank_constant": 60,
                                 "rank_window_size": 50}},
                "query": {"match": {"body": "a b"}},
                "knn": {"field": "v", "query_vector": qv, "k": 50},
                "size": 10}
        resp = node.search("h", body)
        fused = {}
        for q in (body["query"], {"knn": body["knn"]}):
            sub = node.search("h", {"query": q, "size": 50})
            for rp, hit in enumerate(sub["hits"]["hits"]):
                fused[hit["_id"]] = fused.get(hit["_id"], 0.0) \
                    + 1.0 / (60 + rp + 1)
        expect = sorted(fused.values(), reverse=True)[:10]
        got = [h["_score"] for h in resp["hits"]["hits"]]
        np.testing.assert_allclose(got, expect, rtol=1e-9)
        assert resp["hits"]["total"]["value"] == len(fused)
        assert "_source" in resp["hits"]["hits"][0]
        node.close()

    def test_source_false_and_window_clamp(self, tmp_path):
        node, rng = self._node(tmp_path)
        body = {"rank": {"rrf": {"rank_window_size": 20}},
                "query": {"match": {"body": "a"}},
                "knn": {"field": "v",
                        "query_vector": rng.standard_normal(8).tolist(),
                        "k": 20},
                "size": 5, "_source": False}
        resp = node.search("h", body)
        assert len(resp["hits"]["hits"]) == 5
        assert "_source" not in resp["hits"]["hits"][0]
        node.close()
