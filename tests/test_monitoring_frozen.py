"""Monitoring collection/shipping, frozen indices, deprecation API.
Reference: x-pack/plugin/monitoring, x-pack/plugin/frozen-indices,
x-pack deprecation checks."""

import json

import pytest

from elasticsearch_tpu.node import Node
from elasticsearch_tpu.rest.actions import register_all
from elasticsearch_tpu.rest.controller import RestController


@pytest.fixture
def node(tmp_path):
    n = Node(str(tmp_path / "data"))
    yield n
    n.close()


@pytest.fixture
def client(node):
    class Client:
        def __init__(self):
            self.rc = RestController()
            register_all(self.rc, node)

        def req(self, method, path, body=None, **query):
            raw = b""
            if body is not None:
                if isinstance(body, (list, tuple)):
                    raw = b"\n".join(json.dumps(l).encode()
                                     for l in body) + b"\n"
                else:
                    raw = json.dumps(body).encode()
            q = {k: str(v) for k, v in query.items()}
            return self.rc.dispatch(method, path, q, raw, "application/json")
    return Client()


def test_monitoring_collect(node):
    node.index_doc("logs", "1", {"m": "x"}, refresh="true")
    out = node.monitoring.collect()
    assert out["enabled"] and out["collected"] == 3  # cluster+node+index
    resp = node.search(out["index"], {
        "query": {"term": {"type.keyword": "index_stats"}}, "size": 10})
    hits = resp["hits"]["hits"]
    assert len(hits) == 1
    assert hits[0]["_source"]["index_stats"]["index"] == "logs"
    assert hits[0]["_source"]["index_stats"]["docs"]["count"] == 1
    # node_stats doc carries counters
    resp = node.search(out["index"], {
        "query": {"term": {"type.keyword": "node_stats"}}})
    assert resp["hits"]["hits"][0]["_source"]["node_stats"]["node_id"] \
        == node.node_id


def test_monitoring_collect_disabled(tmp_path):
    n = Node(str(tmp_path / "d"),
             settings={"xpack.monitoring.collection.enabled": False})
    try:
        assert n.monitoring.collect() == {"collected": 0, "enabled": False}
    finally:
        n.close()


def test_monitoring_bulk_rest(client, node):
    status, out = client.req(
        "POST", "/_monitoring/bulk",
        [{"index": {"_type": "kibana_stats"}},
         {"kibana": {"uuid": "k1", "status": "green"}}],
        system_id="kibana")
    assert status == 200 and out["indexed"] == 1 and not out["errors"]
    status, out = client.req("POST", "/_monitoring/bulk",
                             [{"index": {}}, {"x": 1}])
    assert status == 400  # system_id required


def test_freeze_unfreeze_search_semantics(client, node):
    node.index_doc("hot", "1", {"v": 1}, refresh="true")
    node.index_doc("cold", "1", {"v": 2}, refresh="true")

    status, _ = client.req("POST", "/cold/_freeze")
    assert status == 200
    assert node.indices.get("cold").settings.get("index.frozen") is True

    # frozen index sits out of normal searches...
    status, resp = client.req("POST", "/hot,cold/_search",
                              {"query": {"match_all": {}}})
    assert resp["hits"]["total"]["value"] == 1
    # ...but participates with ignore_throttled=false
    status, resp = client.req("POST", "/hot,cold/_search",
                              {"query": {"match_all": {}}},
                              ignore_throttled="false")
    assert resp["hits"]["total"]["value"] == 2

    # explicit search of the frozen index alone is also skipped by default
    status, resp = client.req("POST", "/cold/_search",
                              {"query": {"match_all": {}}})
    assert resp["hits"]["total"]["value"] == 0

    status, _ = client.req("POST", "/cold/_unfreeze")
    assert status == 200
    status, resp = client.req("POST", "/hot,cold/_search",
                              {"query": {"match_all": {}}})
    assert resp["hits"]["total"]["value"] == 2


def test_frozen_state_survives_restart(tmp_path):
    data = str(tmp_path / "d")
    n = Node(data)
    n.index_doc("cold", "1", {"v": 1}, refresh="true")
    svc = n.indices.get("cold")
    n.indices.update_settings(svc, {"index.frozen": True})
    n.close()

    n2 = Node(data)
    try:
        svc2 = n2.indices.open_index("cold") \
            if not n2.indices.exists("cold") else n2.indices.get("cold")
        assert svc2.settings.get("index.frozen") in (True, "true")
        resp = n2.search("cold", {"query": {"match_all": {}}})
        assert resp["hits"]["total"]["value"] == 0  # still frozen
    finally:
        n2.close()


def test_scroll_respects_frozen(client, node):
    node.index_doc("hot", "1", {"v": 1}, refresh="true")
    node.index_doc("cold", "1", {"v": 2}, refresh="true")
    client.req("POST", "/cold/_freeze")
    status, resp = client.req("POST", "/hot,cold/_search",
                              {"query": {"match_all": {}}}, scroll="1m")
    assert resp["hits"]["total"]["value"] == 1
    status, resp = client.req("POST", "/hot,cold/_search",
                              {"query": {"match_all": {}}}, scroll="1m",
                              ignore_throttled="false")
    assert resp["hits"]["total"]["value"] == 2


def test_string_false_settings_not_truthy(tmp_path):
    from elasticsearch_tpu.common.settings import setting_bool
    assert setting_bool("false") is False
    assert setting_bool("true") is True
    assert setting_bool(None, True) is True
    n = Node(str(tmp_path / "d"),
             settings={"xpack.monitoring.collection.enabled": "false"})
    try:
        assert n.monitoring.collect()["enabled"] is False
        # an index whose frozen setting is the string "false" is searchable
        n.index_doc("i", "1", {"v": 1}, refresh="true")
        n.indices.update_settings(n.indices.get("i"),
                                  {"index.frozen": "false"})
        assert n.search("i", {})["hits"]["total"]["value"] == 1
    finally:
        n.close()


def test_monitoring_bulk_bad_meta_does_not_shift_pairing(node):
    out = node.monitoring.bulk("beats", [
        None,                                   # bad meta
        {"index": {"_type": "x"}},              # its doc (dropped with it)
        {"index": {"_type": "beats_stats"}},    # valid pair
        {"beat": {"name": "b1"}},
    ])
    assert out["indexed"] == 1 and out["ignored"]
    # the indexed doc carries the right type from ITS meta line
    import elasticsearch_tpu.xpack.monitoring as mon
    r = node.search(mon._today_index(), {
        "query": {"term": {"type.keyword": "beats_stats"}}})
    assert r["hits"]["total"]["value"] == 1
    assert r["hits"]["hits"][0]["_source"]["beat"]["name"] == "b1"


def test_deprecations_reports_frozen(client, node):
    node.index_doc("old", "1", {"v": 1}, refresh="true")
    client.req("POST", "/old/_freeze")
    status, body = client.req("GET", "/_migration/deprecations")
    assert status == 200
    assert any("frozen" in d["message"] for d in body["deprecations"])
